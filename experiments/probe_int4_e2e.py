"""int4 vs int8 MXU operands in the REAL device-ingest bench configs.

probe_int4.py showed int4 ≈ +18% on the isolated Gramian einsum; this
runs the actual bench configs (full driver pipeline) with
``_operand_dtypes`` patched to int4 on the exact path, to see what
survives end to end.

Outcome (v5e, 2026-07-31): nothing — large-cohort 8.73 s (int8) vs
8.75 s (int4), whole-genome 4.36 vs 4.34; the isolated probe's +18% is
an artifact of its cheaper ``(u32 & 1).astype`` cast. int8 stays.
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np

import bench
import spark_examples_tpu.ops.gramian as gramian


def run(config, dtype_name):
    orig = gramian._operand_dtypes

    def patched(exact_int, mesh=None):
        op, acc = orig(exact_int, mesh)
        return (jnp.int4, acc) if op == np.int8 else (op, acc)

    gramian._operand_dtypes = patched if dtype_name == "int4" else orig
    try:
        payload = bench._run_config(config, jax.devices()[0])
    finally:
        gramian._operand_dtypes = orig
    print(
        f"{config} [{dtype_name}]: {payload['value']} s  "
        f"({payload['details']['sites_per_sec_per_chip']} sites/s/chip, "
        f"compile {payload['details']['compile_seconds_excluded']}s)",
        file=sys.__stdout__, flush=True,
    )


import contextlib, io
for config in ("large-cohort", "whole-genome"):
    for dt in ("int8", "int4"):
        with contextlib.redirect_stdout(io.StringIO()):
            run(config, dt)

import jax, jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def try_scratch(mb):
    rows = int(mb * 1024 * 1024 // 4) // 1024
    def kernel(x_ref, o_ref, scratch):
        scratch[0:8, :] = x_ref[:]
        o_ref[:] = scratch[0:8, :]
    try:
        f = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 1024), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.VMEM((rows, 1024), jnp.float32)],
        )
        jax.block_until_ready(f(jnp.ones((8, 1024), jnp.float32)))
        return True
    except Exception as e:
        print(f"  {mb}MB error tail: ...{str(e)[-400:]}")
        return False

import sys
for mb in [1, 4, 8, 12, 16, 24, 32, 40, 48, 64, 96, 120]:
    ok = try_scratch(mb)
    print(f"VMEM scratch {mb}MB: {'OK' if ok else 'FAIL'}")
    sys.stdout.flush()
    if not ok:
        break

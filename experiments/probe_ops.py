"""Microbench: per-op cost of u32 VPU ops in a Mosaic kernel."""
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

R = 64
SH = (1024, 2560)


def make(op_name):
    def kernel(x_ref, o_ref):
        def body(i, x):
            if op_name == "xor":
                return x ^ (x + jnp.uint32(i))
            if op_name == "mul":
                return x * jnp.uint32(0x85EBCA6B) + jnp.uint32(i)
            if op_name == "mul_i32":
                xi = x.astype(jnp.int32)
                # -2048144789 == int32(0x85EBCA6B): same low-32 product bits.
                return (xi * np.int32(-2048144789) + i).astype(jnp.uint32)
            if op_name == "shiftxor":
                return (x ^ (x >> jnp.uint32(16))) + jnp.uint32(i)
            if op_name == "fmix32":
                y = (x ^ (x >> jnp.uint32(16))) * jnp.uint32(0x85EBCA6B)
                y = (y ^ (y >> jnp.uint32(13))) * jnp.uint32(0xC2B2AE35)
                return (y ^ (y >> jnp.uint32(16))) + jnp.uint32(i)
            if op_name == "cmp":
                return x + (x < jnp.uint32(0x7FFFFFFF + i)).astype(jnp.uint32)
            raise ValueError(op_name)
        o_ref[:] = jax.lax.fori_loop(0, R, body, x_ref[:])
    return kernel


def run(op_name):
    f = pl.pallas_call(
        make(op_name),
        out_shape=jax.ShapeDtypeStruct(SH, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=100 << 20),
    )
    fj = jax.jit(f)
    x = jnp.asarray(np.random.randint(0, 2**32, SH, dtype=np.uint64).astype(np.uint32))
    out = fj(x)
    _ = np.asarray(out[0, 0])
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        out = fj(out)
    _ = np.asarray(out[0, 0])
    dt = (time.perf_counter() - t0) / reps
    per_elem_op = dt / (R * SH[0] * SH[1])
    print(f"{op_name:10s}: {dt*1e3:7.2f} ms  {per_elem_op*1e12:7.2f} ps/elem/iter "
          f"({1/per_elem_op/1e9:6.1f} Gelem-iter/s)")


for op in ["xor", "shiftxor", "cmp", "mul", "mul_i32", "fmix32"]:
    run(op)

"""Software-pipelined generate→dot scan vs the production serialized scan.

The production ingest scan (``ops/devicegen.py:_fused_update``) generates
block k on the VPU, materializes it through an ``optimization_barrier``, and
feeds the MXU dot — strictly serialized within each scan step. DESIGN.md §7
measured the dot at ~180 Tmac/s isolated while end-to-end ingest runs at
~55% of that, so up to ~1.4–1.8× would be available IF the VPU generation of
block k could overlap the MXU dot of block k−1.

This probe restructures the scan to carry X: step k generates X_k and dots
X_{k−1} (no data dependence between the two inside one step), with the first
block generated ahead of the scan and the last block's dot issued after it.
Bit-identical to the serial program by construction (parity-checked below,
including the row/kept counters).

Run on the real producer chain at the whole-genome bench config
(N=2504, B=16384, K=32, spacing 73, seed 42) with QUEUED timing: CHAIN
dispatch groups back to back, ONE terminal fetch of a scalar that depends on
the full chain (per-call timing adds ~35 ms tunnel RTT per call).

Result (v5e, 2026-07-31, medians over 4 rounds of 40-dispatch chains; the
serial program reproduces the whole-genome bench rate in this harness):

    serial     median 41.5 ms/dispatch  (12.6M sites/s)
    pipelined  median 48.2 ms/dispatch  (10.9M sites/s)   +16% SLOWER

NEGATIVE: XLA:TPU executes HLOs in sequence — removing the data dependence
between generation and dot inside the loop body does not make the scheduler
co-issue them; compute overlap on TPU happens inside ONE fusion, and a dot
cannot host the generation chain as a sibling output (that is exactly the
per-tile-recompute fusion the barrier exists to prevent). The carried
(B, N) int8 X adds a 41 MB loop-carry round-trip through HBM per step with
no offsetting win. The production scan stays serialized.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from spark_examples_tpu.ops.devicegen import (
    _fused_update,
    generate_has_variation,
    site_thresholds_on_device,
    _c64,
)
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

N = 2504
B = 16384
K = 32
SPACING = 73
# 40-dispatch chains: shorter chains carry ~150 ms of fixed queue overhead
# (~15 ms/dispatch at CHAIN=10) and under-report sustained throughput; at 40
# the serial program reproduces the whole-genome bench rate (~13.5M sites/s).
CHAIN = 40
ROUNDS = 4

source = SyntheticGenomicsSource(num_samples=N, seed=42, variant_spacing=SPACING)
VS = "bench-1kg"
update_args = dict(
    vs_keys=(int(source.genotype_stream_key(VS)),),
    pops_bytes=np.asarray(source.populations, dtype=np.int32).tobytes(),
    site_key=int(source.site_key),
    spacing=SPACING,
    ref_block_fraction=source.ref_block_fraction,
    min_af_micro=None,
    block_size=B,
    blocks_per_dispatch=K,
    operand_name="int8",
    accum_name="int32",
    n_pops=source.n_pops,
    set_sizes=None,
)

serial = _fused_update(**update_args)


def build_pipelined():
    """The same program with X software-pipelined through the scan carry."""
    n_pops = update_args["n_pops"]
    ref_frac = update_args["ref_block_fraction"]

    with jax.enable_x64(True):
        # Constants INSIDE x64 or the uint64 keys canonicalize to uint32
        # (exactly how _fused_update builds them).
        vs_keys_arr = jnp.asarray(
            np.array(
                [k & (2**64 - 1) for k in update_args["vs_keys"]],
                dtype=np.uint64,
            )
        )
        pops_arr = jnp.asarray(
            np.frombuffer(update_args["pops_bytes"], dtype=np.int32)
        )
        site_key_arr = _c64(update_args["site_key"])

        @jax.jit
        def update(G, rows_count, kept_count, grid_offset, n_valid):
            block_idx = jnp.arange(K * B, dtype=jnp.int64).reshape(K, B)

            def gen_block(idx):
                index = grid_offset + idx
                positions = index * SPACING
                valid = idx < n_valid
                T = site_thresholds_on_device(
                    site_key_arr, positions, valid, n_pops, ref_frac, None
                )
                kept_inc = jnp.sum(jnp.any(T > 0, axis=1)).astype(jnp.int64)
                hv = generate_has_variation(
                    positions, T, vs_keys_arr, pops_arr, None
                )
                rows_inc = jnp.sum(
                    jnp.any(hv.reshape(hv.shape[0], 1, -1), axis=2), axis=0
                ).astype(jnp.int64)
                X = lax.optimization_barrier(hv.astype(jnp.int8))
                return X, rows_inc, kept_inc

            X0, r0, k0 = gen_block(block_idx[0])

            def body(carry, idx):
                G, rows_count, kept_count, Xp = carry
                Xn, r_inc, k_inc = gen_block(idx)
                G = G + jnp.einsum(
                    "bn,bm->nm", Xp, Xp, preferred_element_type=jnp.int32
                )
                return (G, rows_count + r_inc, kept_count + k_inc, Xn), None

            (G, rows_count, kept_count, Xl), _ = lax.scan(
                body, (G, rows_count + r0, kept_count + k0, X0), block_idx[1:]
            )
            G = G + jnp.einsum(
                "bn,bm->nm", Xl, Xl, preferred_element_type=jnp.int32
            )
            return G, rows_count, kept_count

    return update


pipelined = build_pipelined()


def fresh_state():
    with jax.enable_x64(True):
        return (
            jnp.zeros((N, N), jnp.int32),
            jnp.zeros((1,), jnp.int64),
            jnp.zeros((), jnp.int64),
        )


def run_chain(fn, n_calls, offset0=0):
    G, rows, kept = fresh_state()
    with jax.enable_x64(True):
        for i in range(n_calls):
            G, rows, kept = fn(
                G,
                rows,
                kept,
                jnp.asarray(np.int64(offset0 + i * K * B)),
                jnp.asarray(np.int64(K * B)),
            )
            if i == 0:
                # Production pokes after the first dispatch to flip the
                # tunneled backend eager (ops/devicegen.py:poke); without it
                # the deferred queue replays at the terminal fetch and the
                # probe under-reports sustained throughput ~2×.
                _ = np.asarray(kept)
    return G, rows, kept


# Parity first: bit-identical Gramian and counters over 2 dispatch groups.
Gs, rs, ks = run_chain(serial, 2)
Gp, rp, kp = run_chain(pipelined, 2)
assert np.array_equal(np.asarray(Gs), np.asarray(Gp)), "Gramian mismatch"
assert np.array_equal(np.asarray(rs), np.asarray(rp)), "row-count mismatch"
assert int(ks) == int(kp), "kept-count mismatch"
print(f"parity OK (G sum {int(np.asarray(Gs, dtype=np.int64).sum())})", flush=True)

times = {"serial": [], "pipelined": []}
for rnd in range(ROUNDS):
    for name, fn in (("serial", serial), ("pipelined", pipelined)):
        t0 = time.perf_counter()
        G, rows, kept = run_chain(fn, CHAIN, offset0=rnd * 10_000_000)
        # Terminal fetch depends on the full chain (tunnel ACKs early).
        _ = int(np.asarray(G[0, 0])) + int(kept)
        times[name].append((time.perf_counter() - t0) / CHAIN)

for name, ts in times.items():
    ts = sorted(ts)
    med = ts[len(ts) // 2]
    print(
        f"{name:10s} median {med*1e3:7.1f} ms/dispatch  "
        f"min {ts[0]*1e3:7.1f}  max {ts[-1]*1e3:7.1f}",
        flush=True,
    )

"""Fused Mosaic generate→Gramian kernel vs the production XLA path.

The measurement record behind DESIGN.md §7.1. The kernel generates the
(sites × samples) {0,1} genotype tile directly in VMEM — the per-(site,
sample) plane is pure u32 because the u64 fold commutes with xor
(``fold(h2 ^ s·P4) = fold(h2) ^ fold(s·P4)``), so only O(sites) u64
metadata stays in XLA — and accumulates ``XᵀX`` on the MXU into a
VMEM-resident (NPAD, NPAD) Gramian across the whole site grid.

Verified bit-identical to ``ops/devicegen.py``'s XLA program, and slower:
the i1→i8 relayout into Mosaic's 4-way packed int8 vectors costs more
than the int8 matmul it feeds, and the cast-free bf16 route pays ~3× on
the Mosaic MXU path. Variants (``VARIANT`` env var; ``TB`` = sites per
grid step, default 1024):

- ``full``     int8 X, int32 G — parity + timing (default)
- ``fullbf16`` bf16 X, f32 G (exact: per-dispatch partials < 2^24)
- ``gen``      generation + i1→i8 cast + X assembly, no matmul
- ``gen32``    generation only, no i8 cast (isolates the cast cost)
- ``genbf16``  generation + i1→bf16 cast (shows the bf16 cast is free)
- ``dot``      trivial generation + int8 matmul (isolates the MXU side)

(A ``min(d1, d2)`` single-compare variant does not compile: Mosaic has no
vector ``arith.minui`` lowering.)
"""
import os
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spark_examples_tpu.ops.devicegen import (
    _c64,
    _P2,
    _P3,
    _P4,
    _S_GENOTYPE,
    fmix32,
    mix64,
    site_thresholds_on_device,
    generate_has_variation,
)

N = 2504
P = 4
SPACING = 73
REF_FRAC = 0.25
SITE_KEY = 0x1234_5678_9ABC_DEF0
VS_KEY = 0x0FED_CBA9_8765_4321
TB = int(os.environ.get("TB", 1024))  # sites per pallas grid step
TN = 128  # columns per tile
VARIANT = os.environ.get("VARIANT", "full")

# ---- static column layout: per-pop segments padded to TN multiples ----
pops_np = (np.arange(N, dtype=np.int64) * P) // N
tiles = []  # (pop, n_valid_in_tile) per TN-column tile
col_map = []  # padded col -> real col (or -1)
start = 0
for p in range(P):
    stop = int(np.searchsorted(pops_np, p + 1))
    for r0 in range(start, stop, TN):
        nv = min(TN, stop - r0)
        tiles.append((p, nv))
        col_map.extend(range(r0, r0 + nv))
        col_map.extend([-1] * (TN - nv))
    start = stop
col_map = np.array(col_map)
NPAD = len(col_map)
M_TILES = NPAD // TN

valid_mask = col_map >= 0
real_cols = np.where(valid_mask)[0]  # padded indices of real columns

# fsamp: fold(col * P4) per padded column
cols_u64 = col_map.astype(np.uint64) * np.uint64(_P4 & (2**64 - 1))
fsamp_np = ((cols_u64 >> np.uint64(32)) ^ cols_u64).astype(np.uint32)
fsamp_np[~valid_mask] = 0
mask_np = valid_mask.astype(np.int32)


def tile_hv(fs, tq_ref, fsamp_ref, mask_ref, m):
    """(TB, TN) i1 has-variation for column tile ``m`` — the in-kernel u32
    half of ``devicegen._allele_pair`` plus the threshold compare (the u64
    xor+fold is pre-folded into ``fs``/``fsamp``); padding columns masked."""
    pop, nv = tiles[m]
    x32 = fs ^ fsamp_ref[0:1, m * TN:(m + 1) * TN]
    d1 = fmix32(x32)
    d2 = (d1 * jnp.uint32(0x9E3779B9)) ^ jnp.uint32(0x85EBCA6B)
    tf = tq_ref[:, pop:pop + 1]
    hv = (d1 < tf) | (d2 < tf)
    if nv < TN:
        hv = hv & (mask_ref[0:1, m * TN:(m + 1) * TN] != 0)
    return hv


def make_kernel(variant):
    x_dtype = jnp.bfloat16 if variant == "fullbf16" else jnp.int8

    def kernel(fsite_ref, tq_ref, fsamp_ref, mask_ref, g_ref, rowany_ref):
        k = pl.program_id(0)

        @pl.when(k == 0)
        def _():
            g_ref[:] = jnp.zeros_like(g_ref)

        fs = fsite_ref[:, 0:1]  # (TB, 1) u32
        if variant == "gen32":  # no cast at all
            acc = None
            for m in range(M_TILES):
                hv = tile_hv(fs, tq_ref, fsamp_ref, mask_ref, m).astype(jnp.int32)
                acc = hv if acc is None else jnp.maximum(acc, hv)
            rowany_ref[:] = jnp.max(acc, axis=1, keepdims=True)
            return
        if variant == "genbf16":  # bf16 cast, no matmul
            acc = None
            for m in range(M_TILES):
                hvb = tile_hv(fs, tq_ref, fsamp_ref, mask_ref, m).astype(jnp.bfloat16)
                a = jnp.max(hvb.astype(jnp.float32), axis=1, keepdims=True)
                acc = a if acc is None else jnp.maximum(acc, a)
            rowany_ref[:] = acc.astype(jnp.int32)
            return

        x_parts = []
        anyv = None
        for m in range(M_TILES):
            if variant == "dot":  # trivial generation, isolate the MXU side
                hvx = (fs ^ fsamp_ref[0:1, m * TN:(m + 1) * TN]
                       < tq_ref[:, 0:1]).astype(x_dtype)
            else:
                hvx = tile_hv(fs, tq_ref, fsamp_ref, mask_ref, m).astype(x_dtype)
                a = jnp.max(hvx.astype(jnp.int32), axis=1, keepdims=True)
                anyv = a if anyv is None else jnp.maximum(anyv, a)
            x_parts.append(hvx)
        X = jnp.concatenate(x_parts, axis=1)  # (TB, NPAD)
        rowany_ref[:] = (
            anyv if anyv is not None
            else jnp.max(X[:, 0:1].astype(jnp.int32), axis=1, keepdims=True)
        )
        if variant != "gen":
            acc_dt = jnp.float32 if variant == "fullbf16" else jnp.int32
            g_ref[:] += jax.lax.dot_general(
                X, X, (((0,), (0,)), ((), ())),
                preferred_element_type=acc_dt,
            )

    return kernel


def pallas_gram(fsite, tq, n_site_blocks, variant=VARIANT):
    g_dtype = jnp.float32 if variant == "fullbf16" else jnp.int32
    return pl.pallas_call(
        make_kernel(variant),
        grid=(n_site_blocks,),
        in_specs=[
            pl.BlockSpec((TB, 1), lambda k: (k, 0 * k), memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, P), lambda k: (k, 0 * k), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, NPAD), lambda k: (0 * k, 0 * k), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, NPAD), lambda k: (0 * k, 0 * k), memory_space=pltpu.VMEM),
        ],
        out_shape=(
            jax.ShapeDtypeStruct((NPAD, NPAD), g_dtype),
            jax.ShapeDtypeStruct((n_site_blocks * TB, 1), jnp.int32),
        ),
        out_specs=(
            pl.BlockSpec((NPAD, NPAD), lambda k: (0 * k, 0 * k), memory_space=pltpu.VMEM),
            pl.BlockSpec((TB, 1), lambda k: (k, 0 * k), memory_space=pltpu.VMEM),
        ),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(fsite, tq, jnp.asarray(fsamp_np)[None, :], jnp.asarray(mask_np)[None, :])


def metadata(grid_offset, n_sites):
    """(fsite (n,1) u32, tq (n,P) u32, T) for grid indices [offset, offset+n)."""
    idx = grid_offset + jnp.arange(n_sites, dtype=jnp.int64)
    positions = idx * SPACING
    valid = jnp.ones((n_sites,), bool)
    T = site_thresholds_on_device(
        _c64(SITE_KEY), positions, valid, P, REF_FRAC, None
    )
    pos_term = positions.astype(jnp.uint64) * _c64(_P2)
    h2 = mix64(mix64(_c64(VS_KEY) ^ pos_term) ^ _c64(_S_GENOTYPE * _P3))
    fsite = ((h2 >> jnp.uint64(32)) ^ h2).astype(jnp.uint32)
    return fsite[:, None], T.astype(jnp.uint32), T


def main():
    with jax.enable_x64(True):
        # ---- parity check on a small batch (full variants only) ----
        if VARIANT in ("full", "fullbf16"):
            nb = 2
            ns = nb * TB
            fsite, tq, T = metadata(jnp.int64(0), ns)
            Gp, rowany = pallas_gram(fsite, tq, nb)
            Gp = np.asarray(Gp).astype(np.int64)[np.ix_(real_cols, real_cols)]

            positions = jnp.arange(ns, dtype=jnp.int64) * SPACING
            hv = generate_has_variation(
                positions, T, jnp.asarray([np.uint64(VS_KEY)]),
                jnp.asarray(pops_np.astype(np.int32)), None,
            )
            X = hv.astype(jnp.int8)
            Gref = np.asarray(
                jnp.einsum("bn,bm->nm", X, X, preferred_element_type=jnp.int32)
            )
            rowany_ref = np.asarray(jnp.any(hv, axis=1)).astype(np.int32)
            ok_g = np.array_equal(Gp, Gref)
            print("parity G:", ok_g, "rowany:",
                  np.array_equal(np.asarray(rowany)[:, 0], rowany_ref))
            if not ok_g:
                bad = np.argwhere(Gp != Gref)
                print("mismatches:", len(bad), bad[:5])
                return

        # ---- timing: one production-sized dispatch of sites ----
        NSITES = 524288
        NB = NSITES // TB

        @jax.jit
        def pallas_dispatch(offset):
            fsite, tq, _ = metadata(offset, NSITES)
            G, ra = pallas_gram(fsite, tq, NB)
            return G.astype(jnp.int32), jnp.sum(ra)

        # In-script XLA replica of the production scanned-einsum program
        # (ops/devicegen.py:_fused_update) — same harness overhead as the
        # Mosaic variants. NOTE: the production program itself measures
        # ~51 ms/dispatch at this group size (DESIGN.md §7 roofline); the
        # replica pays ~70 ms (extra per-block reductions + this harness's
        # x64 tracing context), so compare Mosaic variants against BOTH.
        B, K = 16384, 32

        @jax.jit
        def xla_dispatch(offset):
            def body(carry, kk):
                G = carry
                idx = offset + kk * B + jnp.arange(B, dtype=jnp.int64)
                positions = idx * SPACING
                valid = jnp.ones((B,), bool)
                T = site_thresholds_on_device(
                    _c64(SITE_KEY), positions, valid, P, REF_FRAC, None)
                hv = generate_has_variation(
                    positions, T, jnp.asarray([np.uint64(VS_KEY)]),
                    jnp.asarray(pops_np.astype(np.int32)), None)
                X = hv.astype(jnp.int8)
                G = G + jnp.einsum("bn,bm->nm", X, X,
                                   preferred_element_type=jnp.int32)
                return G, jnp.sum(jnp.any(hv, axis=1))
            G0 = jnp.zeros((N, N), jnp.int32)
            G, ras = jax.lax.scan(body, G0, jnp.arange(K, dtype=jnp.int64))
            return G, jnp.sum(ras)

        for name, fn in [(f"pallas[{VARIANT}]", pallas_dispatch),
                         ("xla replica", xla_dispatch)]:
            t0 = time.perf_counter()
            jax.block_until_ready(fn(jnp.int64(0)))
            compile_s = time.perf_counter() - t0
            reps = 5
            t0 = time.perf_counter()
            for r in range(reps):
                out = fn(jnp.int64(r * NSITES))
            jax.block_until_ready(out)
            _ = np.asarray(out[1])
            dt = (time.perf_counter() - t0) / reps
            print(f"{name}: compile {compile_s:.1f}s, {dt*1e3:.1f} ms/dispatch, "
                  f"{NSITES/dt/1e6:.2f} M sites/s")


if __name__ == "__main__":
    main()

"""Triangular (symmetry-exploiting) Gramian blocking: measured and REJECTED.

G = XᵀX only needs its upper-triangle column-tile pairs (T(T+1)/2 of T²
tiles) plus one mirror. Honest interleaved measurement (NP env var sets the
padded cohort width; medians over round-robin rounds; per-scan-step-varying
X so XLA cannot hoist the dot out of the scan — a first version measured an
illusory 4× because the loop-invariant einsum WAS hoisted, timing one dot +
K adds):

    N=2560  T=4: -11%   N=12800 T=4: -22%   N=25088 T=2: -4%, T=4: +28%

Midrange gains don't cover the production configs (2,504-sample headline:
noise-level; 25,000-sample large-cohort: regression from G slice-update HBM
traffic), so the accumulators keep the single full einsum."""
import time
import numpy as np
import jax
import jax.numpy as jnp

B = 16384
K = 8
import os
NP = int(os.environ.get('NP', 2560))


def make(T):
    pad = NP // T

    @jax.jit
    def tri(Xu, G0):
        def body(G, kk):
            # kk-dependent X so XLA cannot hoist the dots out of the scan
            X = ((Xu >> kk.astype(jnp.uint32)) & 1).astype(jnp.int8)
            if T == 1:
                return G + jnp.einsum("bn,bm->nm", X, X,
                                      preferred_element_type=jnp.int32), None
            for i in range(T):
                Xi = jax.lax.slice_in_dim(X, i * pad, (i + 1) * pad, axis=1)
                for j in range(i, T):
                    Xj = jax.lax.slice_in_dim(X, j * pad, (j + 1) * pad, axis=1)
                    blk = jax.lax.dot_general(
                        Xi, Xj, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
                    G = jax.lax.dynamic_update_slice(
                        G,
                        jax.lax.dynamic_slice(G, (i * pad, j * pad), (pad, pad)) + blk,
                        (i * pad, j * pad))
            return G, None
        G, _ = jax.lax.scan(body, G0, jnp.arange(K) % 8)
        if T > 1:
            G = jnp.triu(G) + jnp.triu(G, 1).T
        return G
    return tri


variants = {T: make(T) for T in [1, 2, 4] if NP % T == 0}
x = jnp.asarray(np.random.randint(0, 2**31, (B, NP), dtype=np.int64)
                .astype(np.uint32))
G0 = jnp.zeros((NP, NP), jnp.int32)
for T, fn in variants.items():
    out = fn(x, G0)
    _ = np.asarray(out[0, 0])  # compile + settle

CHAIN = 10
times = {T: [] for T in variants}
for rnd in range(6):
    for T, fn in variants.items():
        t0 = time.perf_counter()
        out = G0
        for _ in range(CHAIN):
            out = fn(x, out)
        _ = np.asarray(out[0, 0])
        times[T].append((time.perf_counter() - t0) / CHAIN)

for T, ts in times.items():
    ts = sorted(ts)
    med = ts[len(ts) // 2]
    print(f"T={T}: median {med*1e3:7.1f} ms/call  min {ts[0]*1e3:7.1f}  "
          f"max {ts[-1]*1e3:7.1f}", flush=True)

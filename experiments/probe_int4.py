"""Does an int4 einsum beat int8 for the Gramian on v5e? X is {0,1}."""
import time
import numpy as np
import jax
import jax.numpy as jnp

N = 2504
B = 16384
K = 32


def bench(dtype_name):
    dt = getattr(jnp, dtype_name)

    @jax.jit
    def run(Xu32, G0):
        def body(G, _):
            X = (Xu32 & 1).astype(dt)
            G = G + jnp.einsum("bn,bm->nm", X, X,
                               preferred_element_type=jnp.int32)
            return G, None
        G, _ = jax.lax.scan(body, G0, jnp.arange(K))
        return G

    x = jnp.asarray(
        np.random.randint(0, 2**31, (B, N), dtype=np.int64).astype(np.uint32))
    G0 = jnp.zeros((N, N), jnp.int32)
    out = run(x, G0)
    _ = np.asarray(out[0, 0])
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        out = run(x, out)
    _ = np.asarray(out[0, 0])
    dt_s = (time.perf_counter() - t0) / reps
    macs = B * K * N * N
    print(f"{dtype_name}: {dt_s*1e3:7.1f} ms  {macs/dt_s/1e12:6.1f} Tmac/s")


for d in ["int8", "int4", "bfloat16"]:
    try:
        bench(d)
    except Exception as e:
        print(f"{d}: FAILED {str(e)[:200]}")

"""Shared test helpers."""

import numpy as np


def parse_pc_lines(lines):
    """Emitted TSV lines (``name<TAB>dataset<TAB>pc...``) → (N, num_pc)."""
    return np.array([[float(x) for x in l.split("\t")[2:]] for l in lines])


def assert_pcs_match(a_lines, b_lines, atol=5e-3):
    """Two runs' emitted PC lines agree: same callset order, components
    equal up to the eigenvector sign ambiguity."""
    assert [l.split("\t")[0] for l in a_lines] == [
        l.split("\t")[0] for l in b_lines
    ]
    A, B = parse_pc_lines(a_lines), parse_pc_lines(b_lines)
    signs = np.sign((A * B).sum(axis=0))
    signs[signs == 0] = 1
    np.testing.assert_allclose(A, B * signs, atol=atol)

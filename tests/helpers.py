"""Shared test helpers."""

import os
import subprocess
import sys

import numpy as np


def run_cli(
    args, env_extra=None, cwd=None, timeout=240, check=False
):
    """One CLI invocation as a REAL subprocess (CPU-pinned, no persistent
    compile cache) — the harness the chaos matrix SIGKILLs mid-run. A dict
    of extra environment variables (e.g. ``SPARK_EXAMPLES_TPU_FAULTS``)
    rides on top of the inherited environment."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARK_EXAMPLES_TPU_NO_CACHE"] = "1"
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, "-m", "spark_examples_tpu", *[str(a) for a in args]],
        env=env,
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if check and proc.returncode != 0:
        raise AssertionError(
            f"CLI {args[0]} exited {proc.returncode}:\n{proc.stderr[-4000:]}"
        )
    return proc


def parse_pc_lines(lines):
    """Emitted TSV lines (``name<TAB>dataset<TAB>pc...``) → (N, num_pc)."""
    return np.array([[float(x) for x in l.split("\t")[2:]] for l in lines])


def assert_pcs_match(a_lines, b_lines, atol=5e-3):
    """Two runs' emitted PC lines agree: same callset order, components
    equal up to the eigenvector sign ambiguity."""
    assert [l.split("\t")[0] for l in a_lines] == [
        l.split("\t")[0] for l in b_lines
    ]
    A, B = parse_pc_lines(a_lines), parse_pc_lines(b_lines)
    signs = np.sign((A * B).sum(axis=0))
    signs[signs == 0] = 1
    np.testing.assert_allclose(A, B * signs, atol=atol)

"""The host → contig-partition split (``sharding/contig.py``): the pure
integer math every process of a pod-scale run must independently agree on.

Each test pins one clause of the documented split rule — contiguous
ordered runs, exact-integer fair-share boundaries, the tie rule, the
zero-weight degenerate walk — plus the driver-facing ``host_partition``
slice and the merge identity the whole scheme rests on (``G += XᵀX``
commutes over any partition of the row set).
"""

import numpy as np
import pytest

from spark_examples_tpu.sharding.contig import (
    Contig,
    host_partition,
    partition_contigs_by_host,
)


def _contigs(*ranges):
    return [Contig(str(i + 1), 0, r) for i, r in enumerate(ranges)]


def test_concatenation_is_original_order():
    """Partitions are contiguous runs whose concatenation is the input —
    the order every accounting surface assumes."""
    contigs = _contigs(10, 30, 20, 40, 5)
    parts = partition_contigs_by_host(contigs, 3)
    flat = [c for part in parts for c in part]
    assert flat == contigs


def test_equal_weights_split_evenly():
    contigs = _contigs(100, 100, 100, 100)
    parts = partition_contigs_by_host(contigs, 2)
    assert parts == [contigs[:2], contigs[2:]]


def test_tie_closes_earlier_host():
    """A contig landing cumulative weight EXACTLY on the fair-share
    boundary belongs to the EARLIER host."""
    contigs = _contigs(50, 50)
    parts = partition_contigs_by_host(contigs, 2)
    assert parts == [[contigs[0]], [contigs[1]]]


def test_exact_integer_boundaries_no_float_drift():
    """Weights chosen so a float fair-share comparison would misplace the
    boundary; the exact-integer rule (cum*H >= (h+1)*total) cannot."""
    # total = 3, H = 3: boundaries at 1 and 2. Float total/H = 0.9999...
    # style drift must not move contig 2.
    contigs = _contigs(1, 1, 1)
    parts = partition_contigs_by_host(contigs, 3)
    assert parts == [[contigs[0]], [contigs[1]], [contigs[2]]]


def test_more_hosts_than_contigs_leaves_empty_partitions():
    contigs = _contigs(100, 100)
    parts = partition_contigs_by_host(contigs, 5)
    assert [len(p) for p in parts].count(0) == 3
    assert [c for part in parts for c in part] == contigs


def test_single_contig_goes_to_first_host():
    contigs = _contigs(1000)
    parts = partition_contigs_by_host(contigs, 4)
    assert parts[0] == contigs
    assert all(not p for p in parts[1:])


def test_giant_contig_spans_several_fair_shares():
    """One contig holding >2/3 of the weight covers hosts 0 and 1's fair
    shares; host 1 receives an empty partition (contigs never split)."""
    contigs = _contigs(700, 100, 100, 100)
    parts = partition_contigs_by_host(contigs, 3)
    assert parts[0] == [contigs[0]]
    # 700/1000 passes both the 1/3 and 2/3 boundaries: host 1 is empty.
    assert parts[1] == []
    assert parts[2] == contigs[1:]


def test_empty_contig_list():
    parts = partition_contigs_by_host([], 3)
    assert parts == [[], [], []]


def test_all_zero_weights_degenerates_to_one_per_host():
    contigs = _contigs(0, 0, 0, 0, 0)
    parts = partition_contigs_by_host(contigs, 3)
    assert parts == [[contigs[0]], [contigs[1]], contigs[2:]]


def test_zero_weight_contig_rides_open_partition():
    contigs = _contigs(100, 0, 100)
    parts = partition_contigs_by_host(contigs, 2)
    # The zero-weight contig lands wherever the walk stands; contig 1
    # closes host 0 exactly on the boundary (tie rule), so it rides host 1.
    assert parts == [[contigs[0]], contigs[1:]]


def test_custom_weight_function():
    contigs = _contigs(1, 1, 1, 1)
    weights = {c.reference_name: w for c, w in zip(contigs, (90, 10, 10, 10))}
    parts = partition_contigs_by_host(
        contigs, 2, weight=lambda c: weights[c.reference_name]
    )
    # 90 of 120 > the 60 fair share: host 0 closes after the first contig.
    assert parts == [[contigs[0]], contigs[1:]]


def test_determinism_across_calls():
    contigs = _contigs(17, 93, 41, 8, 260, 55)
    for hosts in (1, 2, 3, 4, 7):
        first = partition_contigs_by_host(contigs, hosts)
        assert first == partition_contigs_by_host(contigs, hosts)
        assert [c for p in first for c in p] == contigs


def test_negative_weight_raises():
    with pytest.raises(ValueError, match="negative declared weight"):
        partition_contigs_by_host(
            _contigs(10), 2, weight=lambda c: -1
        )


def test_invalid_num_hosts_raises():
    with pytest.raises(ValueError, match="num_hosts"):
        partition_contigs_by_host(_contigs(10), 0)


def test_host_partition_slices_and_validates():
    contigs = _contigs(100, 100, 100, 100)
    assert host_partition(contigs, 0, 2) == contigs[:2]
    assert host_partition(contigs, 1, 2) == contigs[2:]
    with pytest.raises(ValueError, match="process_index"):
        host_partition(contigs, 2, 2)
    with pytest.raises(ValueError, match="process_index"):
        host_partition(contigs, -1, 2)


def test_declared_sites_weights():
    """The two weight providers: the base-range prior and the synthetic
    source's exact site-grid span."""
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    source = SyntheticGenomicsSource(num_samples=4, seed=7, variant_spacing=100)
    contig = Contig("17", 41196311, 41277499)
    k0, k1 = source.site_grid_range(contig)
    assert source.declared_sites(contig) == k1 - k0
    # The ABC default (bases ∝ sites prior) via a minimal concrete source.
    from spark_examples_tpu.sources.base import GenomicsSource

    class _Stub(GenomicsSource):
        def client(self):  # pragma: no cover - unused
            raise NotImplementedError

        def search_callsets(self, ids):  # pragma: no cover - unused
            return []

        def get_contigs(self, vs, sex_filter=None):  # pragma: no cover
            return []

    assert _Stub().declared_sites(contig) == contig.range
    assert _Stub().declared_sites(Contig("x", 10, 4)) == 0


def test_partitioned_gramian_merge_is_exact():
    """The merge identity host-sharded ingest rests on: per-partition
    XᵀX partials summed in int64 equal the whole-cohort Gramian exactly,
    for ANY host count."""
    rng = np.random.default_rng(7)
    contigs = _contigs(3, 5, 2, 7, 4)
    rows_by_contig = {
        c.reference_name: rng.integers(0, 2, size=(c.range, 6), dtype=np.int64)
        for c in contigs
    }
    whole = np.zeros((6, 6), dtype=np.int64)
    for c in contigs:
        X = rows_by_contig[c.reference_name]
        whole += X.T @ X
    for hosts in (1, 2, 3, 5, 8):
        partials = []
        for part in partition_contigs_by_host(contigs, hosts):
            partial = np.zeros((6, 6), dtype=np.int64)
            for c in part:
                X = rows_by_contig[c.reference_name]
                partial += X.T @ X
            partials.append(partial)
        merged = np.stack(partials).sum(axis=0)
        assert np.array_equal(merged, whole)

"""Resident PCA service (serve/): protocol round-trip and version
rejection, the admission 400/413/429 matrix mirroring the plan
accept/reject matrix, small-job batching ahead of a queued long job,
cancellation, graceful-drain 503, /metrics well-known names, and the
warm-cache e2e (identical resubmit reports a compile-cache hit and lower
latency)."""

import json
import os
import threading
import time
import urllib.request

import pytest

from spark_examples_tpu.serve.client import ServeClient, ServeError
from spark_examples_tpu.serve.daemon import MEM_LIMIT_CODES, PcaService
from spark_examples_tpu.serve.executor import ExecutionOutcome
from spark_examples_tpu.serve.http import start_server
from spark_examples_tpu.serve.protocol import (
    PROTOCOL_ID,
    PROTOCOL_VERSION,
    ProtocolError,
    error_doc,
    parse_request,
    request_doc,
)
from spark_examples_tpu.serve.queue import (
    LARGE_CLASS,
    SMALL_CLASS,
    BoundedJobQueue,
    Job,
    QueueClosed,
    QueueFull,
    classify_conf,
)

TINY_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]
#: 300k candidate sites on the synthetic grid — past SMALL_JOB_MAX_SITES.
LARGE_FLAGS = ["--num-samples", "8", "--references", "1:0:30000000"]


# ---------------------------------------------------------------- protocol


def test_protocol_round_trip():
    doc = request_doc(
        TINY_FLAGS, kind="similarity", deadline_seconds=5.0, tag="t1"
    )
    req = parse_request(json.loads(json.dumps(doc)))
    assert req.kind == "similarity"
    assert list(req.flags) == TINY_FLAGS
    assert req.deadline_seconds == 5.0
    assert req.tag == "t1"


def test_protocol_version_rejected():
    doc = request_doc(TINY_FLAGS)
    doc["protocol"]["version"] = PROTOCOL_VERSION + 1
    with pytest.raises(ProtocolError) as e:
        parse_request(doc)
    assert e.value.code == "unsupported-protocol-version"


@pytest.mark.parametrize(
    "mutate, code",
    [
        (lambda d: d.pop("protocol"), "protocol-missing"),
        (lambda d: d["protocol"].update(id="other/proto"), "protocol-id"),
        (lambda d: d.update(kind="mystery"), "unknown-kind"),
        (lambda d: d.update(flags="--num-samples 8"), "bad-flags"),
        (lambda d: d.update(deadline_seconds=-1), "bad-deadline"),
        (lambda d: d.update(surprise=True), "unknown-field"),
    ],
)
def test_protocol_schema_violations(mutate, code):
    doc = request_doc(TINY_FLAGS)
    mutate(doc)
    with pytest.raises(ProtocolError) as e:
        parse_request(doc)
    assert e.value.code == code


def test_error_doc_carries_protocol_and_plan():
    doc = error_doc("plan-rejected", "nope", plan={"issues": []})
    assert doc["protocol"]["id"] == PROTOCOL_ID
    assert doc["error"]["code"] == "plan-rejected"
    assert doc["plan"] == {"issues": []}


# ------------------------------------------------------------------- queue


def _job(job_id, job_class):
    return Job(
        id=job_id,
        request=parse_request(request_doc(TINY_FLAGS)),
        conf=None,
        job_class=job_class,
        submitted_unix=time.time(),
    )


def test_queue_small_class_pops_first():
    q = BoundedJobQueue(small_capacity=4, large_capacity=4)
    q.put(_job("L1", LARGE_CLASS))
    q.put(_job("S1", SMALL_CLASS))
    q.put(_job("L2", LARGE_CLASS))
    q.put(_job("S2", SMALL_CLASS))
    order = [q.pop(timeout=1).id for _ in range(4)]
    assert order == ["S1", "S2", "L1", "L2"]


def test_queue_bounded_and_closed():
    q = BoundedJobQueue(small_capacity=1, large_capacity=1)
    q.put(_job("S1", SMALL_CLASS))
    with pytest.raises(QueueFull):
        q.put(_job("S2", SMALL_CLASS))
    q.put(_job("L1", LARGE_CLASS))
    assert q.depth() == {SMALL_CLASS: 1, LARGE_CLASS: 1}
    q.close()
    with pytest.raises(QueueClosed):
        q.put(_job("S3", SMALL_CLASS))
    # Pending jobs still pop after close; then drained.
    assert q.pop(timeout=1).id == "S1"
    assert not q.drained
    assert q.pop(timeout=1).id == "L1"
    assert q.pop(timeout=0.05) is None
    assert q.drained


def test_queue_remove_only_while_queued():
    q = BoundedJobQueue()
    q.put(_job("S1", SMALL_CLASS))
    assert q.remove("S1").id == "S1"
    assert q.remove("S1") is None


def test_classify_conf():
    from spark_examples_tpu.config import PcaConf

    small = PcaConf()
    small.references = "17:41196311:41277499"  # BRCA1: ~812 sites
    assert classify_conf(small) == SMALL_CLASS
    big = PcaConf()
    big.references = "1:0:30000000"
    assert classify_conf(big) == LARGE_CLASS
    whole = PcaConf()
    whole.all_references = True
    assert classify_conf(whole) == LARGE_CLASS
    filed = PcaConf()
    filed.source = "file"
    assert classify_conf(filed) == LARGE_CLASS


# --------------------------------------------------------------- admission


class GateExecutor:
    """Stub executor: records execution order, blocks until released —
    the scheduling/cancel/backpressure tests' controllable worker."""

    def __init__(self):
        self.order = []
        self.release = threading.Event()
        self.started = threading.Event()

    def __call__(self, job, run_dir):
        self.order.append(job.id)
        self.started.set()
        assert self.release.wait(timeout=30), "gate never released"
        return ExecutionOutcome(
            result={"stub": True}, manifest_path=None, compile_cache="cold"
        )


@pytest.fixture
def gated_service(tmp_path):
    """A started service with a gated stub executor (no real pipeline)."""
    gate = GateExecutor()
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        small_capacity=1,
        large_capacity=2,
        executor=gate,
    ).start()
    yield service, gate
    gate.release.set()
    service.stop(timeout=30)


def test_admission_rejects_protocol_and_flag_errors(gated_service):
    service, _gate = gated_service
    status, body = service.submit({"protocol": "nope"})
    assert status == 400 and body["error"]["code"] == "protocol-missing"
    bad_version = request_doc(TINY_FLAGS)
    bad_version["protocol"]["version"] = 99
    status, body = service.submit(bad_version)
    assert status == 400
    assert body["error"]["code"] == "unsupported-protocol-version"
    status, body = service.submit(request_doc(["--no-such-flag"]))
    assert status == 400 and body["error"]["code"] == "flag-grammar"
    status, body = service.submit(
        request_doc(TINY_FLAGS + ["--metrics-json", "/tmp/x.json"])
    )
    assert status == 400 and body["error"]["code"] == "reserved-flag"
    # Falsy-but-set reserved values must reject too: 0 is the canonical
    # process id.
    status, body = service.submit(
        request_doc(TINY_FLAGS + ["--process-id", "0"])
    )
    assert status == 400 and body["error"]["code"] == "reserved-flag"
    # Every daemon-host write path is reserved — a client-chosen output
    # location would be an arbitrary-path write on the service host.
    for flag in ("--output-path", "--profile-dir", "--save-variants"):
        status, body = service.submit(
            request_doc(TINY_FLAGS + [flag, "/tmp/evil"])
        )
        assert status == 400 and body["error"]["code"] == "reserved-flag", (
            flag
        )


def test_admission_mirrors_plan_rejections(gated_service):
    """Plan-invalid configurations are 400s whose body carries the SAME
    issue codes `graftcheck plan` exits 2 with."""
    service, _gate = gated_service
    for flags, expected_code in [
        (["--num-samples", "8", "--num-pc", "99"], "num-pc-exceeds-cohort"),
        (["--block-size", "0"], "block-size"),
        (
            ["--mesh-shape", "16,1", "--num-reduce-partitions", "16"],
            "mesh-exceeds-devices",  # 8 virtual devices in conftest
        ),
        (["--references", "bogus"], "references-grammar"),
    ]:
        status, body = service.submit(request_doc(flags))
        assert status == 400, flags
        assert body["error"]["code"] == "plan-rejected"
        codes = [i["code"] for i in body["plan"]["issues"]]
        assert expected_code in codes, (flags, codes)
    # The plan facts ride the rejection body (geometry block present).
    assert "geometry" in body["plan"]


def test_admission_memory_rejections_are_413(tmp_path):
    gate = GateExecutor()
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        host_mem_budget=1 << 20,  # 1 MiB: nothing fits
        executor=gate,
    ).start()
    try:
        sharded = [
            "--num-samples", "64", "--references", "1:0:400000",
            "--mesh-shape", "1,4", "--similarity-strategy", "sharded",
            "--block-size", "64",
        ]
        status, body = service.submit(request_doc(sharded))
        assert status == 413
        codes = [i["code"] for i in body["plan"]["issues"]]
        assert "host-mem-over-budget" in codes
        assert set(codes) & MEM_LIMIT_CODES
        # File-ingest configs used to be "unprovable"; the total resolver
        # now gives them a real (huge, ceiling-rows) bound, so under a
        # 1 MiB budget they reject as plainly over-budget — still 413.
        status, body = service.submit(
            request_doc(
                ["--source", "file", "--input-files", "cohort.vcf"]
                + TINY_FLAGS
            )
        )
        assert status == 413
        codes = [i["code"] for i in body["plan"]["issues"]]
        assert "host-mem-over-budget" in codes
        assert "host-mem-unprovable" not in codes
    finally:
        gate.release.set()
        service.stop(timeout=30)


def test_admission_backpressure_429(gated_service):
    service, gate = gated_service
    status, first = service.submit(request_doc(TINY_FLAGS))
    assert status == 202
    assert gate.started.wait(timeout=10)  # worker claimed the first job
    status, _ = service.submit(request_doc(TINY_FLAGS))
    assert status == 202  # fills the small lane (capacity 1)
    status, body = service.submit(request_doc(TINY_FLAGS))
    assert status == 429
    assert body["error"]["code"] == "queue-full"
    assert body["error"]["retry_after_seconds"] > 0


def test_small_jobs_batch_ahead_of_queued_large_job(gated_service):
    service, gate = gated_service
    # L1 occupies the worker; L2 queues; smalls submitted AFTER L2 must
    # still run before it.
    _, l1 = service.submit(request_doc(LARGE_FLAGS))
    assert gate.started.wait(timeout=10)
    _, l2 = service.submit(request_doc(LARGE_FLAGS))
    _, s1 = service.submit(request_doc(TINY_FLAGS))
    assert l2["job"]["class"] == LARGE_CLASS
    assert s1["job"]["class"] == SMALL_CLASS
    gate.release.set()
    deadline = time.monotonic() + 30
    while len(gate.order) < 3 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert gate.order == [
        l1["job"]["id"],
        s1["job"]["id"],
        l2["job"]["id"],
    ]


def test_cancellation_matrix(gated_service):
    service, gate = gated_service
    _, running = service.submit(request_doc(TINY_FLAGS))
    assert gate.started.wait(timeout=10)
    _, queued = service.submit(request_doc(TINY_FLAGS))
    # Queued: cancellable.
    status, body = service.cancel(queued["job"]["id"])
    assert status == 200 and body["job"]["status"] == "cancelled"
    # Running: conflict.
    status, body = service.cancel(running["job"]["id"])
    assert status == 409 and body["error"]["code"] == "job-running"
    # Unknown: 404.
    status, body = service.cancel("job-999999")
    assert status == 404 and body["error"]["code"] == "unknown-job"
    # Terminal: conflict.
    gate.release.set()
    deadline = time.monotonic() + 30
    while service.job_status(running["job"]["id"])[1]["job"][
        "status"
    ] not in ("done", "failed") and time.monotonic() < deadline:
        time.sleep(0.02)
    status, body = service.cancel(running["job"]["id"])
    assert status == 409 and body["error"]["code"] == "job-finished"
    # The cancelled job stayed cancelled (the worker never ran it).
    assert service.job_status(queued["job"]["id"])[1]["job"][
        "status"
    ] == "cancelled"
    assert queued["job"]["id"] not in gate.order


def test_deadline_exceeded_fails_without_running(gated_service):
    service, gate = gated_service
    # This test targets the DEQUEUE-time expiry path; a 0.2 s deadline
    # is below the cold-compile cost estimate, so the admission-time
    # feasibility gate (tested in test_cost_observatory.py) must be off
    # for the job to reach the queue at all.
    service.deadline_feasibility = False
    _, blocker = service.submit(request_doc(TINY_FLAGS))
    assert gate.started.wait(timeout=10)
    _, doomed = service.submit(
        json.loads(
            json.dumps(request_doc(TINY_FLAGS, deadline_seconds=0.2))
        )
    )
    time.sleep(0.5)  # deadline passes while queued behind the blocker
    gate.release.set()
    deadline = time.monotonic() + 30
    while service.job_status(doomed["job"]["id"])[1]["job"]["status"] not in (
        "done",
        "failed",
    ) and time.monotonic() < deadline:
        time.sleep(0.02)
    _, body = service.job_status(doomed["job"]["id"])
    assert body["job"]["status"] == "failed"
    assert "deadline-exceeded" in body["job"]["error"]
    assert doomed["job"]["id"] not in gate.order


def test_terminal_retention_bounds_the_job_table(tmp_path):
    """The control plane stays O(retention): old terminal records evict
    (404 afterwards), recent ones remain queryable."""

    class InstantExecutor:
        def __call__(self, job, run_dir):
            return ExecutionOutcome(
                result={"ok": True}, manifest_path=None, compile_cache="cold"
            )

    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        executor=InstantExecutor(),
        terminal_retention=2,
    ).start()
    try:
        ids = []
        for _ in range(5):
            status, doc = service.submit(request_doc(TINY_FLAGS))
            assert status == 202
            ids.append(doc["job"]["id"])
            deadline = time.monotonic() + 10
            while (
                service.job_status(ids[-1])[0] == 200
                and service.job_status(ids[-1])[1]["job"]["status"]
                != "done"
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
        # Only the newest `terminal_retention` jobs remain queryable.
        assert service.job_status(ids[-1])[0] == 200
        assert service.job_status(ids[-2])[0] == 200
        for old in ids[:-2]:
            assert service.job_status(old)[0] == 404
        # The lifetime gauge still counts every terminal job.
        assert service.healthz()["jobs"]["terminal"] == 5
        assert service.healthz()["jobs"]["tracked"] == 2
    finally:
        service.stop(timeout=30)


def test_graceful_drain_503_and_worker_exit(gated_service):
    service, gate = gated_service
    _, inflight = service.submit(request_doc(TINY_FLAGS))
    assert gate.started.wait(timeout=10)
    service.begin_drain()
    assert service.healthz()["status"] == "draining"
    status, body = service.submit(request_doc(TINY_FLAGS))
    assert status == 503 and body["error"]["code"] == "draining"
    gate.release.set()
    assert service.wait_drained(timeout=30)
    # The in-flight job finished rather than being dropped.
    _, body = service.job_status(inflight["job"]["id"])
    assert body["job"]["status"] == "done"
    assert not service.healthz()["queue"]["worker_alive"]


# ------------------------------------------------------- HTTP layer + e2e


@pytest.fixture
def http_service(tmp_path):
    """Real executor behind a real HTTP server on an ephemeral port."""
    service = PcaService(run_dir=str(tmp_path / "serve")).start()
    server = start_server(service)
    yield service, ServeClient(server.url)
    server.shutdown()
    service.stop(timeout=60)


def test_http_routes_and_health(http_service):
    service, client = http_service
    health = client.healthz()
    assert health["status"] == "ok"
    assert health["mesh"]["devices"] >= 1
    assert health["queue"]["worker_alive"]
    with pytest.raises(ServeError) as e:
        client.status("job-404404")
    assert e.value.status == 404
    # Unknown route and non-JSON body are structured errors, not tracebacks.
    with pytest.raises(ServeError) as e:
        client._json("GET", "/v1/nothing")
    assert e.value.status == 404
    req = urllib.request.Request(
        client.url + "/v1/jobs",
        data=b"not json",
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        urllib.request.urlopen(req, timeout=10)
        raised = None
    except urllib.error.HTTPError as err:
        raised = err.code
        body = json.loads(err.read().decode())
    assert raised == 400 and body["error"]["code"] == "bad-json"


def test_keep_alive_connection_survives_ignored_bodies(http_service):
    """Routes that ignore request bodies must still drain them: on a
    persistent connection, unread bytes would parse as the next request
    line."""
    import http.client
    from urllib.parse import urlparse

    _service, client = http_service
    parsed = urlparse(client.url)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port, timeout=10)
    try:
        conn.request(
            "POST",
            "/v1/jobs/job-nope/cancel",
            body=b'{"ignored": "body"}',
            headers={"Content-Type": "application/json"},
        )
        first = conn.getresponse()
        first.read()
        assert first.status == 404
        # The SAME connection must serve the next request cleanly.
        conn.request("GET", "/healthz")
        second = conn.getresponse()
        assert second.status == 200
        assert b'"status"' in second.read()
    finally:
        conn.close()


def test_http_plan_rejection_body(http_service):
    _service, client = http_service
    with pytest.raises(ServeError) as e:
        client.submit(["--num-samples", "8", "--num-pc", "99"])
    assert e.value.status == 400
    assert e.value.code == "plan-rejected"
    assert "num-pc-exceeds-cohort" in [
        i["code"] for i in e.value.body["plan"]["issues"]
    ]


@pytest.mark.slow
def test_warm_cache_e2e_and_per_job_manifest(http_service):
    """The compile-once promise, end to end over HTTP: job 1 is cold, the
    identical resubmit is warm (hit counter moves, latency drops), and
    every job writes a valid schema-v2 manifest at its per-job path."""
    from spark_examples_tpu.obs.manifest import (
        manifest_metric_value,
        read_manifest,
        validate_manifest,
    )
    from spark_examples_tpu.obs.metrics import COMPILE_CACHE_GEOMETRY_HITS
    from spark_examples_tpu.utils.cache import reset_compile_cache_stats

    service, client = http_service
    reset_compile_cache_stats()
    flags = TINY_FLAGS + ["--seed", "1234"]  # geometry unique to this test

    job1 = client.wait(client.submit(flags)["job"]["id"], timeout=300)["job"]
    assert job1["status"] == "done"
    assert job1["compile_cache"] == "cold"
    assert len(job1["result"]["pc_lines"]) == 8

    # Per-job manifest: exists under the service run dir, schema-valid,
    # and records the warm-geometry counters (v2-additive compile_cache).
    path = job1["manifest_path"]
    assert path.startswith(os.path.join(service.run_dir, "jobs"))
    doc = read_manifest(path)
    assert validate_manifest(doc) == []
    assert doc["compile_cache"]["geometry_misses"] >= 1
    assert manifest_metric_value(doc, COMPILE_CACHE_GEOMETRY_HITS) is not None

    job2 = client.wait(client.submit(flags)["job"]["id"], timeout=300)["job"]
    assert job2["status"] == "done"
    assert job2["compile_cache"] == "warm"
    assert job2["result"]["pc_lines"] == job1["result"]["pc_lines"]
    # Warm latency: no XLA compile in the path — decisively faster.
    assert job2["seconds"] < job1["seconds"]
    # The hit is visible in the scrape, not inferred.
    scrape = client.metrics()
    hits = [
        line
        for line in scrape.splitlines()
        if line.startswith(COMPILE_CACHE_GEOMETRY_HITS + " ")
    ]
    assert hits and float(hits[0].split()[1]) >= 1


@pytest.mark.slow
def test_similarity_kind_over_http(http_service):
    _service, client = http_service
    doc = client.wait(
        client.submit(TINY_FLAGS, kind="similarity")["job"]["id"],
        timeout=300,
    )
    job = doc["job"]
    assert job["status"] == "done"
    summary = job["result"]["similarity"]
    assert summary["shape"] == [8, 8]
    assert summary["nonzero_rows"] == 8
    assert summary["trace"] > 0


def test_metrics_scrape_well_known_names(http_service):
    _service, client = http_service
    scrape = client.metrics()
    from spark_examples_tpu.obs.metrics import (
        COMPILE_CACHE_GEOMETRY_HITS,
        COMPILE_CACHE_GEOMETRY_MISSES,
        SERVE_JOBS_DONE,
        SERVE_JOBS_INFLIGHT,
        SERVE_QUEUE_DEPTH,
    )

    for name in (
        SERVE_QUEUE_DEPTH,
        SERVE_JOBS_INFLIGHT,
        SERVE_JOBS_DONE,
        COMPILE_CACHE_GEOMETRY_HITS,
        COMPILE_CACHE_GEOMETRY_MISSES,
        "serve_jobs_submitted_total",
        "serve_jobs_rejected_total",
        "serve_jobs_completed_total",
        "serve_job_seconds",
    ):
        assert f"# TYPE {name} " in scrape, name


def test_service_heartbeat_line_shows_serve_segments(tmp_path):
    from spark_examples_tpu.obs.heartbeat import Heartbeat

    gate = GateExecutor()
    service = PcaService(
        run_dir=str(tmp_path / "serve"), executor=gate
    ).start()
    try:
        service.submit(request_doc(TINY_FLAGS))
        assert gate.started.wait(timeout=10)
        line = Heartbeat(60.0, service.registry).line()
        assert "serve queue" in line
        assert "in-flight 1" in line
        assert "compile cache" in line
    finally:
        gate.release.set()
        service.stop(timeout=30)


# ------------------------------------------------------------ submit verb


def test_submit_cli_verb_no_wait(http_service, capsys):
    from spark_examples_tpu.serve.client import submit_main

    _service, client = http_service
    rc = submit_main(["--url", client.url, "--no-wait", "--"] + TINY_FLAGS)
    assert rc == 0
    job_id = capsys.readouterr().out.strip()
    assert job_id.startswith("job-")
    # The thread-routed job capture must NOT swallow this main-thread
    # print even while the job is mid-flight; finish it anyway so the
    # fixture teardown has nothing left to drain.
    client.wait(job_id, timeout=300)
    capsys.readouterr()
    # Rejections print the body and exit 2.
    rc = submit_main(
        ["--url", client.url, "--", "--num-samples", "8", "--num-pc", "99"]
    )
    assert rc == 2
    body = json.loads(capsys.readouterr().out)
    assert body["http_status"] == 400
    assert body["error"]["code"] == "plan-rejected"


# ------------------------------------------------------ library entry point


def test_run_pipeline_is_cli_equivalent(tmp_path):
    """The executor's library entry point returns exactly what the CLI
    prints — the refactor moved `pca_driver` internals, not behavior."""
    from spark_examples_tpu.config import PcaConf
    from spark_examples_tpu.pipeline.pca_driver import run, run_pipeline

    argv = TINY_FLAGS + ["--metrics-json", str(tmp_path / "m.json")]
    lines = run(argv)
    result = run_pipeline(PcaConf.parse(argv))
    assert result.lines == lines
    assert result.manifest is not None
    assert result.manifest_path == str(tmp_path / "m.json")
    sim = run_pipeline(PcaConf.parse(TINY_FLAGS), similarity_only=True)
    assert sim.lines == []
    assert sim.similarity_summary["shape"] == [8, 8]


def test_compile_fingerprint_ignores_placement_flags():
    from spark_examples_tpu.config import PcaConf
    from spark_examples_tpu.utils.cache import compile_fingerprint

    a = PcaConf.parse(TINY_FLAGS)
    b = PcaConf.parse(TINY_FLAGS + ["--metrics-json", "/tmp/elsewhere.json"])
    c = PcaConf.parse(["--num-samples", "16", "--references", "1:0:50000"])
    assert compile_fingerprint(a) == compile_fingerprint(b)
    assert compile_fingerprint(a) != compile_fingerprint(c)
    # The job kind is geometry: similarity-only runs compile a strict
    # subset of the PCA kernels, so they must not share a fingerprint.
    assert compile_fingerprint(a, kind="similarity") != compile_fingerprint(
        a, kind="pca"
    )


def test_geometry_ledger_warms_only_on_success(tmp_path):
    from spark_examples_tpu.config import PcaConf
    from spark_examples_tpu.pipeline.pca_driver import run_pipeline
    from spark_examples_tpu.utils.cache import (
        compile_fingerprint,
        geometry_seen,
        reset_compile_cache_stats,
    )

    reset_compile_cache_stats()
    try:
        # A run that dies before its kernels compile must not warm the
        # fingerprint — a retry would falsely report "warm".
        bad = PcaConf.parse(
            [
                "--source",
                "file",
                "--input-files",
                str(tmp_path / "missing.vcf"),
                "--references",
                "1:0:50000",
            ]
        )
        with pytest.raises(Exception):
            run_pipeline(bad)
        assert not geometry_seen(compile_fingerprint(bad))
        # A completed run warms exactly its own kind.
        good = PcaConf.parse(TINY_FLAGS)
        run_pipeline(good, similarity_only=True)
        assert geometry_seen(compile_fingerprint(good, kind="similarity"))
        assert not geometry_seen(compile_fingerprint(good, kind="pca"))
    finally:
        reset_compile_cache_stats()

"""Differential fold fuzz: the journal fold is order-insensitive.

``replay_journal``'s contract says the fold is order-insensitive across
events of one job (appenders are concurrent threads AND concurrent
replica processes, serialized only per record) — and ``graftcheck
proto``'s canonical journal ordering additionally relies on records of
DIFFERENT jobs commuting. This test checks the theorem the docstring
states: for seeded random protocol histories, folding every permutation
of the records yields the same semantic state — same pending set, same
fence epochs, same effective/fenced terminal verdicts.

Two deliberate scope notes:

- Histories only contain record multisets the protocol can produce:
  at most one ``accepted`` per job and strictly increasing lease epochs
  per job. Same-epoch lease re-issue by two replicas is exactly what
  GP004 proves impossible — outside that set the fold's ``owner`` pick
  is legitimately order-dependent.
- Presentation order (the ``terminals`` list, pending-job list order)
  follows input order by design; the comparison normalizes it. What
  must NOT vary is the semantic content.

Deterministic by construction: seeded ``random.Random``, no third-party
property-testing dependency.
"""

import itertools
import random
from dataclasses import asdict

from spark_examples_tpu.serve.journal import (
    accepted_record,
    began_record,
    compacted_records,
    fold_records,
    lease_record,
    protocol_summary,
    terminal_record,
)

_REPLICAS = ("rep-a", "rep-b", "rep-c")
_STATUSES = ("done", "failed", "cancelled")


def _random_history(rng):
    """One protocol-producible history: per job an accepted record, a
    strictly-increasing lease chain, maybe a began, and 0-2 terminals
    (epoch-less, fenced-low, or at-the-fence)."""
    records = []
    for i in range(rng.randint(1, 3)):
        job = f"job-{i:04d}"
        records.append(
            accepted_record(
                job,
                {"n": i},
                "pca",
                100.0 + i,
                None,
                replica=rng.choice(_REPLICAS),
            )
        )
        epoch = 0
        for _ in range(rng.randint(0, 2)):
            epoch += rng.randint(1, 2)
            records.append(
                lease_record(
                    job,
                    epoch,
                    replica=rng.choice(_REPLICAS),
                    stolen=rng.random() < 0.3,
                )
            )
        if epoch and rng.random() < 0.7:
            records.append(
                began_record(
                    job,
                    replica=rng.choice(_REPLICAS),
                    epoch=rng.randint(1, epoch),
                )
            )
        for _ in range(rng.randint(0, 2)):
            records.append(
                terminal_record(
                    job,
                    rng.choice(_STATUSES),
                    replica=rng.choice(_REPLICAS),
                    epoch=rng.randint(1, epoch) if epoch else None,
                )
            )
    return records


def _fold_key(records):
    """The fold's semantic content, presentation order normalized."""
    pending, max_seq = fold_records(records)
    return (
        sorted((asdict(job) for job in pending), key=lambda j: j["job_id"]),
        max_seq,
    )


def _summary_key(records):
    summary = protocol_summary(records)
    jobs = {}
    for job_id, info in summary["jobs"].items():
        info = dict(info)
        info["terminals"] = sorted(
            (
                (t["status"], -1 if t["epoch"] is None else t["epoch"],
                 t["effective"])
                for t in info["terminals"]
            )
        )
        jobs[job_id] = info
    return {"jobs": jobs, "totals": summary["totals"]}


def _permutations(records, rng, cap=150):
    """Every permutation when the factorial is small; otherwise ``cap``
    seeded shuffles (still deterministic — the rng is seeded)."""
    if len(records) <= 6:
        return list(itertools.permutations(records))
    perms = []
    for _ in range(cap):
        shuffled = list(records)
        rng.shuffle(shuffled)
        perms.append(tuple(shuffled))
    return perms


def test_fold_is_permutation_invariant():
    checked = 0
    for seed in range(40):
        rng = random.Random(seed)
        records = _random_history(rng)
        base_fold = _fold_key(records)
        base_summary = _summary_key(records)
        for perm in _permutations(records, rng):
            assert _fold_key(perm) == base_fold, (seed, perm)
            assert _summary_key(perm) == base_summary, (seed, perm)
            checked += 1
    # The loop must have actually exercised interleavings, not
    # degenerate one-record histories.
    assert checked > 1000


def test_compaction_rewrite_preserves_fold_semantics():
    # fold -> compacted_records -> re-fold keeps every pending job with
    # its began flag and fence epoch (the invariant that makes the
    # checker's compact transition and the daemon's rewrite one thing).
    for seed in range(40):
        rng = random.Random(seed ^ 0xC0FFEE)
        records = _random_history(rng)
        pending, _seq = fold_records(records)
        refolded, _seq2 = fold_records(compacted_records(pending))
        before = {
            j.job_id: (j.device_began, j.lease_epoch) for j in pending
        }
        after = {
            j.job_id: (j.device_began, j.lease_epoch) for j in refolded
        }
        assert before == after, seed


def test_no_property_testing_dependency():
    # The differential fuzz must stay importable on the bare image: a
    # hypothesis import would make this file collection-error there.
    import sys

    assert "hypothesis" not in sys.modules

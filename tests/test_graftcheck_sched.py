"""graftcheck sched: the device-free collective-schedule prover, the
hierarchical two-level ring, and their satellites.

Covers: the closed-form traffic properties (monotonicity, exact pack
ratio, hier-DCN-below-flat for every multi-host topology), topology
grammar, schedule extraction/simulation over the shipped matrix, every GS
rule via a broken or mis-selected subject, the hierarchical kernel's
runtime parity against the flat ring (byte-identical on 8 virtual
devices), the two-radix ranges refinement, the plan validator's
``--topology``/``--sched-budget-seconds`` accept/reject matrix, the
manifest ``schedule`` block, the zero-live-arrays contract, and the
retired checkpoint-compute streaming path.
"""

import json

import numpy as np
import pytest

import jax

from spark_examples_tpu.parallel.mesh import (
    DEFAULT_DCN_BYTES_PER_S,
    DEFAULT_ICI_BYTES_PER_S,
    Topology,
    flat_traffic_split,
    hierarchical_mesh,
    hierarchical_traffic_bytes,
    make_mesh,
    parse_topology,
    resolve_hier_hosts,
    resolve_reduce_schedule,
    ring_traffic_bytes,
)
from spark_examples_tpu.check.sched import (
    DEFAULT_TOPOLOGIES,
    audit_schedule,
    extract_schedule,
    run_audit,
    schedule_kernel_spec,
)


# --------------------------------------------------------------------------
# Closed-form traffic properties (the formula layer the GS rules enforce).
# --------------------------------------------------------------------------


class TestTrafficFormulas:
    def test_hier_total_equals_flat_total(self):
        # The hierarchical schedule moves the SAME bytes as the flat ring
        # — it proves their placement, it does not shrink them.
        for hosts, per_host in DEFAULT_TOPOLOGIES:
            s = hosts * per_host
            for packed in (True, False):
                level = hierarchical_traffic_bytes(64, hosts, per_host, 16, packed)
                assert level.total == ring_traffic_bytes(64, s, 16, packed)

    def test_hier_dcn_strictly_below_flat_for_multihost(self):
        # The acceptance property: on EVERY hosts>1 topology of the
        # matrix, hier puts strictly fewer bytes on the slow link than
        # the flat ring's provable bound.
        for hosts, per_host in DEFAULT_TOPOLOGIES:
            if hosts == 1:
                continue
            topo = Topology(hosts, per_host)
            for packed in (True, False):
                hier = hierarchical_traffic_bytes(
                    64, hosts, per_host, 16, packed
                )
                flat = flat_traffic_split(64, topo, 16, packed)
                assert hier.dcn_bytes < flat.dcn_bytes, (hosts, per_host)
                assert flat.ici_bytes == 0  # nothing provably intra-host

    def test_single_host_rides_ici_only(self):
        topo = Topology(1, 4)
        flat = flat_traffic_split(64, topo, 16, True)
        hier = hierarchical_traffic_bytes(64, 1, 4, 16, True)
        assert flat.dcn_bytes == 0 and hier.dcn_bytes == 0
        assert flat.ici_bytes == hier.ici_bytes > 0

    def test_monotone_in_sites_and_devices(self):
        base = hierarchical_traffic_bytes(64, 4, 8, 16, True)
        assert (
            hierarchical_traffic_bytes(128, 4, 8, 16, True).total
            > base.total
        )
        assert (
            hierarchical_traffic_bytes(64, 8, 8, 16, True).total
            > base.total
        )
        assert (
            hierarchical_traffic_bytes(64, 4, 16, 16, True).total
            > base.total
        )
        assert ring_traffic_bytes(128, 8, 16, True) > ring_traffic_bytes(
            64, 8, 16, True
        )
        assert ring_traffic_bytes(64, 16, 16, True) > ring_traffic_bytes(
            64, 8, 16, True
        )

    def test_exact_pack_ratio(self):
        # n_local a multiple of 8 -> the packed wire moves EXACTLY 1/8.
        assert ring_traffic_bytes(64, 8, 16, False) == 8 * ring_traffic_bytes(
            64, 8, 16, True
        )
        packed = hierarchical_traffic_bytes(64, 4, 8, 16, True)
        unpacked = hierarchical_traffic_bytes(64, 4, 8, 16, False)
        assert unpacked.ici_bytes == 8 * packed.ici_bytes
        assert unpacked.dcn_bytes == 8 * packed.dcn_bytes

    def test_topology_grammar(self):
        topo = parse_topology("32,8")
        assert (topo.hosts, topo.devices_per_host, topo.devices) == (32, 8, 256)
        assert topo.ici_bytes_per_s == DEFAULT_ICI_BYTES_PER_S
        assert topo.dcn_bytes_per_s == DEFAULT_DCN_BYTES_PER_S
        for bad in ("32", "a,b", "1,2,3", ""):
            with pytest.raises(ValueError):
                parse_topology(bad)
        with pytest.raises(ValueError):
            Topology(0, 4)
        with pytest.raises(ValueError):
            Topology(2, 2, ici_bytes_per_s=0)

    def test_resolve_reduce_schedule(self):
        assert resolve_reduce_schedule("auto", 1) == "flat"
        assert resolve_reduce_schedule("auto", 4) == "hier"
        assert resolve_reduce_schedule("flat", 4) == "flat"
        assert resolve_reduce_schedule("hier", 1) == "hier"
        with pytest.raises(ValueError):
            resolve_reduce_schedule("ring", 2)

    def test_resolve_hier_hosts(self, monkeypatch):
        assert resolve_hier_hosts(8, 2) == 2
        with pytest.raises(ValueError):
            resolve_hier_hosts(8, 3)  # must divide
        monkeypatch.setenv("SPARK_EXAMPLES_TPU_HIER_HOSTS", "4")
        assert resolve_hier_hosts(8) == 4


# --------------------------------------------------------------------------
# Schedule extraction + simulation over the shipped matrix.
# --------------------------------------------------------------------------


class TestSchedMatrix:
    def test_default_matrix_proves_clean(self):
        report = run_audit()
        assert report.ok, "\n".join(f.format() for f in report.findings)
        # Every multi-host topology carries its hier-vs-flat comparison
        # for BOTH ring kernels (the host-fed Gramian ring and the fused
        # generation ring), hier strictly below on the slow link.
        multihost = [t for t in DEFAULT_TOPOLOGIES if t[0] > 1]
        assert len(report.comparisons) == 2 * len(multihost)
        kernels = {comp.get("kernel") for comp in report.comparisons}
        assert kernels == {"gramian", "devicegen"}
        for comp in report.comparisons:
            assert comp["hier_strictly_below"], comp
            assert comp["dcn_reduction"] > 1.0

    def test_flat_simulation_matches_formula_exactly(self):
        # GS002's clean side, asserted directly: the simulated flat
        # schedule reproduces ring_traffic_bytes byte for byte.
        for hosts, per_host in DEFAULT_TOPOLOGIES:
            topo = Topology(hosts, per_host)
            audit = audit_schedule(topo, "flat", selected=False)
            assert audit.ok, [f.format() for f in audit.findings]
            total = audit.facts["ici_bytes"] + audit.facts["dcn_bytes"]
            assert total == ring_traffic_bytes(
                audit.facts["rows_per_call"],
                topo.devices,
                schedule_kernel_spec(topo, "flat", 64, 8).n_local,
                True,
            )

    def test_hier_per_level_bytes_and_steps(self):
        topo = Topology(4, 8)
        audit = audit_schedule(topo, "hier")
        assert audit.ok
        level = hierarchical_traffic_bytes(
            audit.facts["rows_per_call"], 4, 8,
            schedule_kernel_spec(topo, "hier", 64, 8).n_local, True,
        )
        assert audit.facts["ici_bytes"] == level.ici_bytes
        assert audit.facts["dcn_bytes"] == level.dcn_bytes
        # Per-device step counts: H*(D-1) inner + (H-1) outer = S-1.
        assert audit.facts["ici_steps"] == 4 * 7
        assert audit.facts["dcn_steps"] == 3

    def test_critical_path_scales_linearly_with_rows(self):
        topo = Topology(4, 8)
        spec = schedule_kernel_spec(topo, "hier", 64, 8)
        from spark_examples_tpu.check.ir import trace_kernel

        sched = extract_schedule(trace_kernel(spec), spec, topo, "hier")
        one = sched.critical_path_seconds()
        assert sched.critical_path_seconds(sched.rows_per_call * 10) == (
            pytest.approx(one * 10)
        )
        # Overlap proven on both levels -> critical path is the slower
        # level, not the sum.
        seconds = sched.link_seconds()
        assert sched.critical_path_seconds() == max(seconds.values())

    def test_zero_live_arrays_after_audit(self):
        before = len(jax.live_arrays())
        run_audit(topologies=((2, 2), (1, 2)))
        assert len(jax.live_arrays()) == before


# --------------------------------------------------------------------------
# The GS rules, one broken/mis-selected subject each.
# --------------------------------------------------------------------------


def _serialized_hier_trace(hosts, per_host, num_samples, block_size):
    """A two-level ring whose dots CONSUME the just-permuted tile (the
    serialized anti-pattern): same geometry as the real kernel, so it can
    stand in as ``traced`` for GS003/GI001 fixtures."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import AbstractMesh, PartitionSpec as P

    from spark_examples_tpu.utils.compat import shard_map
    from spark_examples_tpu.parallel.mesh import (
        DATA_AXIS,
        HOST_AXIS,
        SAMPLES_AXIS,
        RING_PACK_MULTIPLE,
        padded_cohort,
    )

    samples = hosts * per_host
    padded = padded_cohort(num_samples, samples, pack=True)
    n_local = padded // samples
    mesh = AbstractMesh(
        ((DATA_AXIS, 1), (HOST_AXIS, hosts), (SAMPLES_AXIS, per_host))
    )

    from spark_examples_tpu.ops.gramian import _unpack_bits

    def per_slice(G_local, X_local):
        G, X = G_local[0], X_local[0]
        D = per_host
        H = hosts
        x_mine = _unpack_bits(X, n_local).astype(jnp.float32).T
        perm_d = [((p + 1) % D, p) for p in range(D)]
        perm_h = [((p + 1) % H, p) for p in range(H)]

        def inner(j, carry):
            G, cur = carry
            cur = lax.ppermute(cur, SAMPLES_AXIS, perm_d)  # then consumed!
            t = jnp.matmul(
                x_mine, _unpack_bits(cur, n_local).astype(jnp.float32),
                preferred_element_type=G.dtype,
            )
            return G + jnp.pad(
                t, ((0, 0), (0, padded - n_local))
            ), cur

        def outer(k, carry):
            G, cur = carry
            cur = lax.ppermute(cur, HOST_AXIS, perm_h)  # then consumed!
            G, cur = lax.fori_loop(0, D - 1, inner, (G, cur))
            return G, cur

        G, _ = lax.fori_loop(0, H - 1, outer, (G, X))
        return G[None]

    @jax.jit
    def update(G, X):
        return shard_map(
            per_slice,
            mesh=mesh,
            in_specs=(
                P(DATA_AXIS, (HOST_AXIS, SAMPLES_AXIS), None),
                P(DATA_AXIS, None, (HOST_AXIS, SAMPLES_AXIS)),
            ),
            out_specs=P(DATA_AXIS, (HOST_AXIS, SAMPLES_AXIS), None),
        )(G, X)

    with jax.enable_x64(True):
        G = jax.ShapeDtypeStruct((1, padded, padded), jnp.float32)
        X = jax.ShapeDtypeStruct(
            (1, block_size, padded // RING_PACK_MULTIPLE), jnp.uint8
        )
        return jax.make_jaxpr(update)(G, X)


class TestSchedRules:
    def test_gs001_flat_selected_on_multihost(self):
        audit = audit_schedule(Topology(2, 4), "flat", selected=True)
        assert [f.rule_id for f in audit.findings] == ["GS001"]
        assert "inter-host" in audit.findings[0].detail

    def test_gs001_not_on_single_host_or_unselected(self):
        assert audit_schedule(Topology(1, 4), "flat", selected=True).ok
        assert audit_schedule(Topology(2, 4), "flat", selected=False).ok

    def test_gs001_silent_when_one_device_per_host(self):
        # hosts x 1: the flat ring IS the host ring — hier buys nothing,
        # the bounds are equal, and flat stays a legitimate selection.
        audit = audit_schedule(Topology(4, 1), "flat", selected=True)
        assert audit.ok, [f.format() for f in audit.findings]

    def test_gs003_serialized_schedule(self):
        traced = _serialized_hier_trace(2, 2, 64, 8)
        audit = audit_schedule(
            Topology(2, 2), "hier", selected=False, traced=traced
        )
        rules = {f.rule_id for f in audit.findings}
        assert "GS003" in rules  # every link step is an overlap hole
        assert "GI001" in rules  # and the IR layer agrees
        # With holes, the levels serialize: critical path is the sum.
        spec = schedule_kernel_spec(Topology(2, 2), "hier", 64, 8)
        sched = extract_schedule(traced, spec, Topology(2, 2), "hier")
        seconds = sched.link_seconds()
        assert sched.critical_path_seconds() == pytest.approx(
            seconds["ici"] + seconds["dcn"]
        )

    def test_gs004_liveness_budget(self):
        audit = audit_schedule(
            Topology(2, 2), "hier", hbm_budget_bytes=1024
        )
        assert [f.rule_id for f in audit.findings] == ["GS004"]

    def test_gs005_budget(self):
        topo = Topology(32, 8)
        tight = audit_schedule(
            topo, "hier", rows=40_000_000, budget_seconds=1e-6
        )
        assert [f.rule_id for f in tight.findings] == ["GS005"]
        roomy = audit_schedule(
            topo, "hier", rows=40_000_000, budget_seconds=3600.0
        )
        assert roomy.ok, [f.format() for f in roomy.findings]

    def test_gs002_schedule_formula_mismatch(self):
        # A DOUBLE-WIDTH hierarchical trace (unpacked wire) against the
        # packed spec: the simulated bytes can no longer match the packed
        # formulas.
        from spark_examples_tpu.check.ir import hier_kernel_spec, trace_kernel

        unpacked = trace_kernel(hier_kernel_spec(1, 2, 2, 64, 8, False))
        audit = audit_schedule(
            Topology(2, 2), "hier", selected=False, traced=unpacked
        )
        assert "GS002" in {f.rule_id for f in audit.findings}


# --------------------------------------------------------------------------
# The hierarchical kernel at runtime: parity + schedule block.
# --------------------------------------------------------------------------


class TestHierRuntime:
    @pytest.fixture()
    def mesh(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        return make_mesh({"data": 1, "samples": 4})

    def test_hier_parity_flat_and_oracle(self, mesh):
        from spark_examples_tpu.ops.gramian import (
            ShardedGramianAccumulator,
            gramian_reference,
        )

        rng = np.random.default_rng(11)
        rows = (rng.random((90, 52)) < 0.35).astype(np.uint8)
        oracle = gramian_reference(rows)
        results = {}
        for sched, hosts in (("flat", None), ("hier", 2), ("hier", 4)):
            acc = ShardedGramianAccumulator(
                52, mesh, block_size=16,
                reduce_schedule=sched, hier_hosts=hosts,
            )
            acc.add_rows(rows)
            results[(sched, hosts)] = acc.finalize()
        for key, G in results.items():
            assert np.array_equal(G, oracle), key
        # Byte-identical across schedules, not merely oracle-equal.
        flat = results[("flat", None)]
        assert flat.tobytes() == results[("hier", 2)].tobytes()
        assert flat.tobytes() == results[("hier", 4)].tobytes()

    def test_hier_parity_unpacked_and_counts_fallback(self, mesh):
        from spark_examples_tpu.ops.gramian import (
            ShardedGramianAccumulator,
            gramian_reference,
        )

        rng = np.random.default_rng(3)
        rows = rng.integers(0, 3, (40, 48)).astype(np.uint8)  # count-valued
        expect = rows.astype(np.int64).T @ rows
        for pack_bits in ("on", "off"):
            acc = ShardedGramianAccumulator(
                48, mesh, block_size=8, pack_bits=pack_bits,
                reduce_schedule="hier", hier_hosts=2,
            )
            acc.add_rows(rows)
            assert np.array_equal(acc.finalize(), expect), pack_bits

    def test_hier_requires_dividing_host_factor(self, mesh):
        from spark_examples_tpu.ops.gramian import ShardedGramianAccumulator

        with pytest.raises(ValueError, match="divide"):
            ShardedGramianAccumulator(
                48, mesh, reduce_schedule="hier", hier_hosts=3
            )
        # auto with a non-dividing factor degrades to flat, loudly typed.
        acc = ShardedGramianAccumulator(
            48, mesh, reduce_schedule="auto", hier_hosts=3
        )
        assert acc.reduce_schedule == "flat"

    def test_ring_bytes_survive_checkpoint_round_trip(self, mesh):
        # A resumed run's schedule block must keep predicted == measured:
        # ring accounting rides the snapshot (absent in old artifacts -> 0).
        from spark_examples_tpu.ops.gramian import ShardedGramianAccumulator

        acc = ShardedGramianAccumulator(
            48, mesh, block_size=8, reduce_schedule="hier", hier_hosts=2
        )
        rows = (np.arange(16 * 48).reshape(16, 48) % 3 == 0).astype(np.uint8)
        acc.add_rows(rows)
        state = acc.snapshot_state()
        assert state["ring_bytes_total"] == acc.ring_bytes_total > 0
        fresh = ShardedGramianAccumulator(
            48, mesh, block_size=8, reduce_schedule="hier", hier_hosts=2
        )
        fresh.restore_state({"meta": state, "G": state["G"]})
        assert fresh.ring_bytes_total == acc.ring_bytes_total
        block = fresh.schedule_block()
        assert block["predicted_ring_bytes"] == block["measured_ring_bytes"]
        # Old artifacts without the field resume with 0 (no crash).
        legacy = {k: v for k, v in state.items() if k != "ring_bytes_total"}
        fresh2 = ShardedGramianAccumulator(
            48, mesh, block_size=8, reduce_schedule="hier", hier_hosts=2
        )
        fresh2.restore_state({"meta": legacy, "G": state["G"]})
        assert fresh2.ring_bytes_total == 0

    def test_schedule_block_shape(self, mesh):
        from spark_examples_tpu.obs.manifest import (
            build_manifest,
            validate_manifest,
        )
        from spark_examples_tpu.ops.gramian import ShardedGramianAccumulator
        from spark_examples_tpu.parallel.mesh import (
            hierarchical_traffic_bytes,
        )

        acc = ShardedGramianAccumulator(
            48, mesh, block_size=8, reduce_schedule="hier", hier_hosts=2
        )
        rows = (np.arange(16 * 48).reshape(16, 48) % 3 == 0).astype(np.uint8)
        acc.add_rows(rows)
        acc.finalize()
        block = acc.schedule_block()
        assert block["kind"] == "hier"
        assert (block["hosts"], block["devices_per_host"]) == (2, 2)
        assert block["predicted_ring_bytes"] == block["measured_ring_bytes"]
        # Per-flush projection x flush count (capacity rows per flush).
        level = hierarchical_traffic_bytes(
            acc.block_size, 2, 2, acc.n_local, acc.pack
        )
        flushes = acc._flushes
        assert flushes == 2
        assert block["predicted_ici_bytes"] == level.ici_bytes * flushes
        assert block["predicted_dcn_bytes"] == level.dcn_bytes * flushes
        doc = build_manifest(schedule=block)
        assert validate_manifest(doc) == []
        bad = dict(block, kind="ring")
        assert validate_manifest(build_manifest(schedule=bad))

    def test_device_ingest_runs_explicit_hier(self, mesh, monkeypatch):
        # The generation ring speaks the two-level schedule: an explicit
        # hier request (host factor from the rehearsal override) runs the
        # hierarchical kernel and lands byte-identical to the flat run.
        from spark_examples_tpu.config import PcaConf
        from spark_examples_tpu.parallel.mesh import HIER_HOSTS_ENV
        from spark_examples_tpu.pipeline.pca_driver import VariantsPcaDriver

        argv = ["--num-samples", "16", "--references", "1:0:50000",
                "--mesh-shape", "1,4", "--similarity-strategy", "sharded",
                "--ingest", "device"]
        monkeypatch.setenv(HIER_HOSTS_ENV, "2")
        conf = PcaConf.parse(argv + ["--reduce-schedule", "hier"])
        driver = VariantsPcaDriver(conf)
        hier_res = np.asarray(
            driver.get_similarity_device_gen(
                conf.get_contigs(driver.source, conf.variant_set_id)
            )
        )
        block = driver._sched_block
        driver.stop()
        assert block["kind"] == "hier"
        assert (block["hosts"], block["devices_per_host"]) == (2, 2)
        assert block["predicted_dcn_bytes"] > 0
        monkeypatch.delenv(HIER_HOSTS_ENV)
        conf2 = PcaConf.parse(argv + ["--reduce-schedule", "flat"])
        driver2 = VariantsPcaDriver(conf2)
        flat_res = np.asarray(
            driver2.get_similarity_device_gen(
                conf2.get_contigs(driver2.source, conf2.variant_set_id)
            )
        )
        flat_block = driver2._sched_block
        driver2.stop()
        assert flat_block["kind"] == "flat"
        assert hier_res.tobytes() == flat_res.tobytes()

    def test_hierarchical_mesh_factorization(self, mesh):
        m3 = hierarchical_mesh(mesh, 2)
        assert m3.shape == {"data": 1, "hosts": 2, "samples": 2}
        # Host-major: the inner axis groups consecutive samples-axis slots.
        assert list(np.asarray(m3.devices).flat) == list(
            np.asarray(mesh.devices).flat
        )
        with pytest.raises(ValueError, match="divide"):
            hierarchical_mesh(mesh, 3)


# --------------------------------------------------------------------------
# The two-radix ranges refinement for the hierarchical kernel.
# --------------------------------------------------------------------------


class TestHierRanges:
    def test_two_radix_refinement_engages(self):
        from spark_examples_tpu.check.ranges import (
            audit_range_kernel,
            hier_range_spec,
        )

        for hosts, per_host in ((2, 2), (2, 4), (4, 2)):
            audit = audit_range_kernel(
                hier_range_spec(hosts, per_host, 64, 8, True, False)
            )
            assert audit.ok, [f.format() for f in audit.findings]
            # Refined to ONE dot partial per pass (8 = block rows), not
            # the conservative trips-multiplied bound.
            assert audit.facts["entry_increment"] == 8.0
            assert audit.facts["entry_increment_conservative"] > 8.0

    def test_flat_matrix_unchanged_by_multiplier_generalization(self):
        from spark_examples_tpu.check.ranges import run_audit as ranges_audit

        report = ranges_audit()
        assert report.ok, "\n".join(f.format() for f in report.findings)


# --------------------------------------------------------------------------
# CLI surfaces: sched subcommand + the unified --topology spelling.
# --------------------------------------------------------------------------


class TestCli:
    def test_sched_clean_and_json(self, capsys):
        from spark_examples_tpu.check import cli

        assert cli.main(["sched", "--topology", "2,2", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["tool"] == "graftcheck-sched"
        assert doc["ok"] is True
        kinds = {s["facts"]["schedule"] for s in doc["subjects"]}
        assert kinds == {"hier", "flat"}
        assert doc["comparisons"][0]["hier_strictly_below"] is True

    def test_sched_flat_forced_flags_gs001(self, capsys):
        from spark_examples_tpu.check import cli

        assert cli.main(
            ["sched", "--topology", "2,2", "--reduce-schedule", "flat"]
        ) == 1
        assert "GS001" in capsys.readouterr().out

    def test_sched_budget_flag(self, capsys):
        from spark_examples_tpu.check import cli

        assert cli.main(
            ["sched", "--topology", "2,2",
             "--sched-budget-seconds", "1e-15"]
        ) == 1
        assert "GS005" in capsys.readouterr().out

    def test_topology_grammar_error_exit_2(self, capsys):
        from spark_examples_tpu.check import cli

        assert cli.main(["sched", "--topology", "nope"]) == 2
        assert cli.main(["ir", "--topology", "1"]) == 2
        assert cli.main(["ranges", "--topology", "2,2,2"]) == 2

    def test_sched_rejects_mesh_flag(self, capsys):
        # --mesh belongs to ir/ranges; silently ignoring it on sched
        # would fake a constrained matrix.
        from spark_examples_tpu.check import cli

        assert cli.main(["sched", "--mesh", "2,2"]) == 2
        assert "--topology" in capsys.readouterr().err

    def test_sched_rejects_nonpositive_budget(self, capsys):
        # Same positivity contract as graftcheck plan: a usage error
        # (exit 2), not a GS005 finding on every topology.
        from spark_examples_tpu.check import cli

        assert cli.main(["sched", "--sched-budget-seconds", "-1"]) == 2
        assert "positive" in capsys.readouterr().err

    def test_ir_topology_appends_hier_kernels(self, capsys):
        from spark_examples_tpu.check import cli

        assert cli.main(
            ["ir", "--mesh", "1,2", "--topology", "2,2", "--json"]
        ) == 0
        doc = json.loads(capsys.readouterr().out)
        names = [k["kernel"] for k in doc["kernels"]]
        assert any(n.startswith("hier[") for n in names)

    def test_ranges_topology_appends_hier_kernels(self, capsys):
        from spark_examples_tpu.check import cli

        assert cli.main(
            ["ranges", "--mesh", "1,2", "--topology", "2,2", "--json"]
        ) == 0
        doc = json.loads(capsys.readouterr().out)
        names = [k["kernel"] for k in doc["kernels"]]
        assert any("hier[" in n for n in names)


# --------------------------------------------------------------------------
# graftcheck plan: --topology / --sched-budget-seconds matrix.
# --------------------------------------------------------------------------


class TestPlanTopology:
    def _plan(self, argv):
        from spark_examples_tpu.check.plan import (
            parse_plan_args,
            validate_plan,
        )

        conf, devices, _json, budget, analysis, topology, sched_budget = (
            parse_plan_args(argv)
        )
        return validate_plan(
            conf, devices, host_mem_budget=budget, analysis=analysis,
            topology=topology, sched_budget_seconds=sched_budget,
        )

    BASE = ["--num-samples", "64", "--references", "1:0:400000"]

    def test_accepts_pod_topology(self):
        report = self._plan(self.BASE + ["--topology", "32,8"])
        assert report.ok, [i.message for i in report.issues]
        assert report.geometry["sched_schedule"] == "hier"
        assert report.geometry["sched_dcn_bytes"] > 0
        assert report.geometry["sched_critical_path_seconds"] > 0
        assert report.geometry["sched_rows"] == 4001

    def test_rejects_flat_on_pod(self):
        report = self._plan(
            self.BASE + ["--topology", "2,4", "--reduce-schedule", "flat"]
        )
        assert not report.ok
        assert any(i.code == "sched-GS001" for i in report.issues)

    def test_rejects_unprovable_budget(self):
        report = self._plan(
            ["--num-samples", "64", "--all-references",
             "--topology", "2,4", "--sched-budget-seconds", "10"]
        )
        assert any(
            i.code == "sched-budget-unprovable" for i in report.issues
        )

    def test_rejects_budget_past_critical_path(self):
        report = self._plan(
            self.BASE + ["--topology", "32,8",
                         "--sched-budget-seconds", "1e-12"]
        )
        assert any(i.code == "sched-GS005" for i in report.issues)

    def test_accepts_provable_budget(self):
        report = self._plan(
            self.BASE + ["--topology", "32,8",
                         "--sched-budget-seconds", "60"]
        )
        assert report.ok, [i.message for i in report.issues]

    def test_budget_without_topology_rejected(self):
        report = self._plan(self.BASE + ["--sched-budget-seconds", "60"])
        assert any(
            i.code == "sched-budget-seconds" for i in report.issues
        )

    def test_budget_on_host_backend_rejected_not_ignored(self):
        # A declared budget the config cannot prove must reject, never
        # silently pass: the host backend dispatches no ring schedule.
        report = self._plan(
            self.BASE + ["--pca-backend", "host", "--topology", "2,4",
                         "--sched-budget-seconds", "0.001"]
        )
        assert any(
            i.code == "sched-budget-unprovable" for i in report.issues
        )

    def test_topology_on_host_backend_warns(self):
        report = self._plan(
            self.BASE + ["--pca-backend", "host", "--topology", "2,4"]
        )
        assert report.ok
        assert any(
            i.code == "sched-not-applicable" and i.severity == "warning"
            for i in report.issues
        )

    def test_budget_on_ld_analysis_rejected(self):
        report = self._plan(
            ["--analysis", "ld", *self.BASE, "--topology", "2,4",
             "--sched-budget-seconds", "1"]
        )
        assert any(
            i.code == "sched-budget-unprovable" for i in report.issues
        )

    def test_explicit_dense_strategy_not_falsely_proven(self):
        # An EXPLICIT dense pin dispatches no ring even on the pod: the
        # topology must not produce a false schedule proof — budget
        # rejects, topology alone warns.
        report = self._plan(
            self.BASE + ["--similarity-strategy", "dense",
                         "--topology", "32,8"]
        )
        assert report.ok
        assert "sched_schedule" not in report.geometry
        assert any(i.code == "sched-not-applicable" for i in report.issues)
        report = self._plan(
            self.BASE + ["--similarity-strategy", "dense",
                         "--topology", "32,8",
                         "--sched-budget-seconds", "60"]
        )
        assert any(
            i.code == "sched-budget-unprovable" for i in report.issues
        )

    def test_data_only_mesh_rejected_against_topology(self):
        # An explicit samples=1 mesh pins a run with no ring at all; the
        # schedule proof must not admit it.
        report = self._plan(
            self.BASE + ["--topology", "2,2", "--mesh-shape", "4,1",
                         "--plan-devices", "4"]
        )
        assert any(
            i.code == "topology-mesh-mismatch" for i in report.issues
        )

    def test_hier_on_device_ingest_accepted(self):
        # The generation ring speaks the two-level schedule now
        # (ops/devicegen.py:_ring_update + _hier_ring_tiles): an explicit
        # hier request on device ingest validates instead of rejecting,
        # and the topology proof traces the DEVICEGEN kernel.
        report = self._plan(
            self.BASE + ["--ingest", "device", "--reduce-schedule", "hier"]
        )
        assert report.ok, [i.message for i in report.issues]
        report = self._plan(
            self.BASE + ["--ingest", "device", "--reduce-schedule", "hier",
                         "--topology", "2,4"]
        )
        assert report.ok, [i.message for i in report.issues]
        assert report.geometry["sched_schedule"] == "hier"
        assert report.geometry["sched_kernel"] == "devicegen"
        assert report.geometry["sched_dcn_bytes"] > 0

    def test_hier_host_factor_must_divide_samples_axis(self):
        # The factorization invariant IS the static validation that
        # replaced the blanket device-ingest rejection: a declared
        # topology whose host count does not divide the declared samples
        # axis cannot build the host-major mesh.
        report = self._plan(
            self.BASE + ["--reduce-schedule", "hier",
                         "--mesh-shape", "1,9", "--plan-devices", "9",
                         "--similarity-strategy", "sharded",
                         "--topology", "2,4"]
        )
        assert any(
            i.code == "hier-hosts-samples-axis" for i in report.issues
        )

    def test_hier_env_override_validated_offline(self, monkeypatch):
        from spark_examples_tpu.parallel.mesh import HIER_HOSTS_ENV

        monkeypatch.setenv(HIER_HOSTS_ENV, "3")
        report = self._plan(
            self.BASE + ["--reduce-schedule", "hier",
                         "--mesh-shape", "1,8", "--plan-devices", "8",
                         "--similarity-strategy", "sharded"]
        )
        assert any(
            i.code == "hier-hosts-samples-axis" for i in report.issues
        )
        monkeypatch.setenv(HIER_HOSTS_ENV, "4")
        report = self._plan(
            self.BASE + ["--reduce-schedule", "hier",
                         "--mesh-shape", "1,8", "--plan-devices", "8",
                         "--similarity-strategy", "sharded"]
        )
        assert report.ok, [i.message for i in report.issues]

    def test_plan_devices_topology_mismatch(self):
        report = self._plan(
            self.BASE + ["--topology", "32,8", "--plan-devices", "8"]
        )
        assert any(
            i.code == "topology-devices-mismatch" for i in report.issues
        )
        # Agreement passes.
        report = self._plan(
            self.BASE + ["--topology", "2,4", "--plan-devices", "8"]
        )
        assert report.ok, [i.message for i in report.issues]

    def test_mesh_topology_mismatch(self):
        report = self._plan(
            self.BASE + ["--topology", "2,4", "--mesh-shape", "1,2",
                         "--plan-devices", "8",
                         "--similarity-strategy", "sharded"]
        )
        assert any(
            i.code == "topology-mesh-mismatch" for i in report.issues
        )

    def test_mesh_matching_topology_accepted(self):
        report = self._plan(
            self.BASE + ["--topology", "2,2", "--mesh-shape", "1,4",
                         "--plan-devices", "4",
                         "--similarity-strategy", "sharded"]
        )
        assert report.ok, [i.message for i in report.issues]

    def test_topology_grammar_rejection(self):
        from spark_examples_tpu.check.plan import parse_plan_args

        with pytest.raises(ValueError):
            parse_plan_args(self.BASE + ["--topology", "pod"])

    def test_reduce_schedule_spelling_validated(self):
        from spark_examples_tpu.check.plan import validate_plan
        from spark_examples_tpu.config import PcaConf

        conf = PcaConf(num_samples=8)
        conf.reduce_schedule = "ring"
        report = validate_plan(conf)
        assert any(i.code == "reduce-schedule" for i in report.issues)

    def test_plan_cli_exit_codes(self):
        from spark_examples_tpu.check import cli

        assert cli.main(["plan", *self.BASE, "--topology", "2,4"]) == 0
        assert cli.main(
            ["plan", *self.BASE, "--topology", "2,4",
             "--reduce-schedule", "flat"]
        ) == 2
        assert cli.main(["plan", *self.BASE, "--topology", "bad"]) == 2


# --------------------------------------------------------------------------
# Satellite: the retired checkpoint-compute O(part) list.
# --------------------------------------------------------------------------


class TestCheckpointComputeStreams:
    def test_compute_streams_and_round_trips(self, tmp_path):
        from typing import Iterator

        from spark_examples_tpu.models.variant import (
            VariantKey,
            VariantsBuilder,
        )
        from spark_examples_tpu.pipeline import checkpoint as cp

        records = []
        for i in range(40):
            wire = {
                "referenceName": "1",
                "variantSetId": "s",
                "id": f"v{i}",
                "start": 100 + i,
                "end": 101 + i,
                "referenceBases": "A",
                "alternateBases": ["C"],
                "calls": [
                    {
                        "callSetId": "s-0",
                        "callSetName": "S0",
                        "genotype": [0, 1],
                    }
                ],
            }
            built = VariantsBuilder.build(wire)
            assert built is not None
            records.append((VariantKey("1", 100 + i), built[1]))
        path = tmp_path / "ckpt"
        cp.save_variants(str(path), [records[:25], records[25:]])
        loaded = cp.load_variants(str(path))
        first = loaded.partitions()[0]
        stream = loaded.compute(first)
        # A generator, not an O(part) list — the retired hostmem site.
        assert isinstance(stream, Iterator)
        got = list(stream)
        assert [k for k, _ in got] == [k for k, _ in records[:25]]
        assert [v.to_json() for _, v in got] == [
            v.to_json() for _, v in records[:25]
        ]

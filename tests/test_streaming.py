"""Bounded-memory streaming VCF ingest (``sources/files.py:_StreamedVcf``).

The reference's paging architecture streamed arbitrarily large datasets one
page per executor (``rdd/VariantsRDD.scala:198-225``); the streamed packed
path restates that for the TPU ingest: one pass over the file in fixed-size
decompressed chunks, peak host memory O(chunk), results identical to the
in-memory parser.
"""

import gzip
import os
import tempfile
import tracemalloc

import numpy as np
import pytest
from helpers import assert_pcs_match

# hypothesis is declared only under the `test` extra; every handwritten test
# here must still collect and run on the bare seed image, so only the fuzz
# test (defined conditionally below) depends on it.
try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False
if HAVE_HYPOTHESIS:
    from test_files_fuzz import _vcf_documents

from spark_examples_tpu.pipeline import pca_driver
from spark_examples_tpu.sharding.contig import Contig
from spark_examples_tpu.sources.files import (
    FileGenomicsSource,
    StreamCounters,
    _iter_vcf_chunks,
)


def _make_vcf(
    tmp_path,
    name="big.vcf",
    n_samples=7,
    rows_per_contig=120,
    contigs=("1", "17", "GL000229.1"),
    spacing=37,
    compress=False,
    shuffle_contig=None,
):
    """A deterministic multi-contig VCF with AF-carrying and AF-less rows,
    multi-allele genotypes, and missing calls — coordinate-sorted unless
    ``shuffle_contig`` swaps two rows of that contig."""
    rng = np.random.default_rng(123)
    header = ["##fileformat=VCFv4.2"]
    cols = "\t".join(f"S{i:03d}" for i in range(n_samples))
    header.append(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t" + cols
    )
    lines = []
    for contig in contigs:
        contig_lines = []
        for k in range(rows_per_contig):
            pos = 101 + k * spacing
            af = rng.random()
            info = f"AF={af:.4f}" if k % 5 else "NS=3"
            gts = []
            for _ in range(n_samples):
                draw = rng.random()
                if draw < 0.1:
                    gts.append("./.")
                elif draw < 0.5:
                    gts.append("0|0")
                elif draw < 0.8:
                    gts.append("0|1")
                else:
                    gts.append("1|2")
            contig_lines.append(
                f"{contig}\t{pos}\trs{contig}_{k}\tAC\tG,T\t50\tPASS\t"
                f"{info}\tGT\t" + "\t".join(gts)
            )
        if shuffle_contig == contig and len(contig_lines) > 3:
            contig_lines[1], contig_lines[3] = contig_lines[3], contig_lines[1]
        lines.extend(contig_lines)
    text = "\n".join(header + lines) + "\n"
    path = tmp_path / (name + (".gz" if compress else ""))
    if compress:
        with gzip.open(path, "wt") as f:
            f.write(text)
    else:
        path.write_text(text)
    return str(path)


def _blocks_concat(blocks):
    blocks = list(blocks)
    if not blocks:
        return (
            np.empty(0, np.int64),
            np.zeros((0, 0), np.uint8),
            np.empty(0, np.float64),
        )
    return (
        np.concatenate([b["positions"] for b in blocks]),
        np.concatenate([b["has_variation"] for b in blocks]),
        np.concatenate([b["af"] for b in blocks]),
    )


def test_chunk_iterator_reassembles_exactly(tmp_path):
    path = _make_vcf(tmp_path, rows_per_contig=40)
    raw = open(path, "rb").read()
    chunks = list(_iter_vcf_chunks(path, 1))  # clamps to the 64-byte floor
    assert len(chunks) > 1
    assert b"".join(chunks) == raw
    for chunk in chunks[:-1]:
        assert chunk.endswith(b"\n")


@pytest.mark.parametrize("compress", [False, True])
def test_streamed_blocks_match_in_memory(tmp_path, compress):
    """The streamed pass and the in-memory packed view produce identical
    rows for every window — gz and plain, AF filter on and off."""
    path = _make_vcf(tmp_path, compress=compress)
    plain = FileGenomicsSource([path], stream_chunk_bytes=0)
    streamed = FileGenomicsSource([path], stream_chunk_bytes=1)  # force
    set_id = plain.set_ids[0]
    assert not plain.wants_streaming(set_id)
    assert streamed.wants_streaming(set_id)

    windows = [
        Contig("17", 0, 10_000),
        Contig("17", 2_000, 3_000),
        Contig("1", 101, 102),
        Contig("GL000229.1", 0, 1 << 40),
        Contig("absent", 0, 1000),
    ]
    for min_af in (None, 0.3):
        for window in windows:
            want = _blocks_concat(
                plain.genotype_blocks(
                    set_id, window, block_size=16, min_allele_frequency=min_af
                )
            )
            got = _blocks_concat(
                streamed.genotype_blocks(
                    set_id, window, block_size=16, min_allele_frequency=min_af
                )
            )
            for w, g in zip(want, got):
                np.testing.assert_array_equal(w, g)


def test_streamed_python_fallback_matches_native(tmp_path):
    """Without the native library the streamed chunks parse through the
    shared wire-parser semantics — identical blocks."""
    from spark_examples_tpu.utils import native as native_mod

    if native_mod.vcf_library() is None:
        pytest.skip("no native build")
    path = _make_vcf(tmp_path)
    window = Contig("17", 0, 1 << 40)

    native_src = FileGenomicsSource([path], stream_chunk_bytes=1)
    want = _blocks_concat(
        native_src.genotype_blocks(native_src.set_ids[0], window)
    )
    original = native_mod.vcf_library
    try:
        native_mod.vcf_library = lambda: None
        py_src = FileGenomicsSource([path], stream_chunk_bytes=1)
        got = _blocks_concat(
            py_src.genotype_blocks(py_src.set_ids[0], window)
        )
    finally:
        native_mod.vcf_library = original
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)


def test_stream_counters_match_random_access_accounting(tmp_path):
    """One-pass per-shard page/variant accounting == the random-access
    path's ``page_requests`` + kept-row counts."""
    path = _make_vcf(tmp_path)
    plain = FileGenomicsSource([path], stream_chunk_bytes=0)
    streamed = FileGenomicsSource([path], stream_chunk_bytes=1)
    set_id = plain.set_ids[0]
    bpp = 1500
    window = Contig("17", 0, 4600)
    shards = window.get_shards(bpp)

    counters = StreamCounters(len(shards), page_size=2)
    blocks = list(
        streamed.stream_genotype_blocks(
            set_id, shards, block_size=16, counters=counters
        )
    )
    want_requests = 0
    for shard in shards:
        rows = len(plain.packed(set_id).window(shard)[0])
        want_requests += max(1, -(-rows // 2))
    assert counters.requests() == want_requests
    want_variants = sum(
        len(b["positions"])
        for shard in shards
        for b in plain.genotype_blocks(set_id, shard, block_size=16)
    )
    assert counters.variants == want_variants == sum(
        len(b["positions"]) for b in blocks
    )


def test_lazy_contig_discovery_streams_no_table(tmp_path):
    """--all-references discovery on a streamed VCF: bounds from the
    site-only pass, identical to the packed view's, with neither the wire
    table nor the packed arrays ever materialized."""
    path = _make_vcf(tmp_path)
    streamed = FileGenomicsSource([path], stream_chunk_bytes=1)
    set_id = streamed.set_ids[0]
    got = streamed.get_contigs(set_id)
    assert streamed._tables == {} and streamed._packed == {}

    plain = FileGenomicsSource([path], stream_chunk_bytes=0)
    want = plain.get_contigs(set_id)
    assert [(c.reference_name, c.start, c.end) for c in got] == [
        (c.reference_name, c.start, c.end) for c in want
    ]


def test_header_only_callsets(tmp_path):
    path = _make_vcf(tmp_path, n_samples=4)
    source = FileGenomicsSource([path], stream_chunk_bytes=1)
    callsets = source.search_callsets(source.set_ids)
    assert [c["name"] for c in callsets] == ["S000", "S001", "S002", "S003"]
    assert source._tables == {}  # no wire parse happened


def test_gz_auto_threshold_accounts_for_compression(tmp_path, monkeypatch):
    """The auto-streaming threshold is defined in DECOMPRESSED bytes: a
    compressed .gz whose on-disk size is below the raw threshold but whose
    expansion clearly is not must stream (the standard compressed 1000
    Genomes distribution), while the same on-disk size uncompressed need
    not."""
    gz = _make_vcf(tmp_path, name="a.vcf", compress=True)
    plain = _make_vcf(tmp_path, name="b.vcf", compress=False)
    source = FileGenomicsSource([gz, plain])  # auto mode
    fake = 20 << 20  # 20 MB on disk: > 128 MB decompressed only if .gz
    monkeypatch.setattr(
        "spark_examples_tpu.sources.files.os.path.getsize", lambda p: fake
    )
    assert source.wants_streaming(source.set_ids[0])  # .gz → ~200 MB text
    assert not source.wants_streaming(source.set_ids[1])


def test_headerless_vcf_keeps_working(tmp_path):
    """A VCF with no #CHROM row (sites-only) still runs: header-only
    callset discovery yields the empty cohort exactly like the wire parser,
    instead of rejecting a file the data parse accepts."""
    vcf = "17\t101\t.\tA\tG\t50\tPASS\tAF=0.5\n17\t205\t.\tT\tC\t50\tPASS\tAF=0.3\n"
    path = tmp_path / "headerless.vcf"
    path.write_text(vcf)
    for chunk_bytes in (0, 1):  # in-memory and streamed
        source = FileGenomicsSource([str(path)], stream_chunk_bytes=chunk_bytes)
        assert source.search_callsets(source.set_ids) == []
        contigs = source.get_contigs(source.set_ids[0])
        # POS 205 (1-based) → start 204, end = 204 + len("T") = 205.
        assert [(c.reference_name, c.end) for c in contigs] == [("17", 205)]


def test_native_site_scan_rejects_short_lines_like_python(tmp_path):
    """vcf_scan_sites must reject <8-field data lines exactly like the
    Python fallback — contig discovery must not be environment-dependent."""
    from spark_examples_tpu.utils import native as native_mod

    if native_mod.vcf_library() is None:
        pytest.skip("no native build")
    short = b"17\t101\t.\tA\tG\n"
    with pytest.raises(ValueError, match="data line #1"):
        native_mod.scan_vcf_sites_chunk(short)


def test_unsorted_vcf_fails_loudly_in_streaming_mode(tmp_path):
    path = _make_vcf(tmp_path, shuffle_contig="17")
    streamed = FileGenomicsSource([path], stream_chunk_bytes=1)
    set_id = streamed.set_ids[0]
    with pytest.raises(ValueError, match="coordinate-sorted"):
        list(
            streamed.genotype_blocks(set_id, Contig("17", 0, 1 << 40))
        )
    # The in-memory path has no ordering requirement.
    plain = FileGenomicsSource([path], stream_chunk_bytes=0)
    assert list(plain.genotype_blocks(set_id, Contig("17", 0, 1 << 40)))


def test_noncontiguous_contig_fails_loudly(tmp_path):
    text = (
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS1\n"
        "1\t101\t.\tA\tG\t1\t.\tAF=0.5\tGT\t0|1\n"
        "2\t101\t.\tA\tG\t1\t.\tAF=0.5\tGT\t0|1\n"
        "1\t201\t.\tA\tG\t1\t.\tAF=0.5\tGT\t0|1\n"
    )
    path = tmp_path / "split.vcf"
    path.write_text(text)
    source = FileGenomicsSource([str(path)], stream_chunk_bytes=1)
    with pytest.raises(ValueError, match="not contiguous"):
        list(
            source.genotype_blocks(
                source.set_ids[0], Contig("1", 0, 1 << 40)
            )
        )


def _coordinate_sort(document: str) -> str:
    """A streaming-legal equivalent of a fuzzed VCF document: contigs made
    contiguous (first-seen order), positions sorted stably within each —
    exactly the layout `bcftools sort` would emit."""
    eol = "\r\n" if "\r\n" in document else "\n"
    lines = [l for l in document.split(eol) if l]
    head = [l for l in lines if l.startswith("#")]
    groups: dict = {}
    for line in lines:
        if line.startswith("#"):
            continue
        groups.setdefault(line.split("\t")[0], []).append(line)
    for group in groups.values():
        group.sort(key=lambda l: int(l.split("\t")[1]))
    data = [line for name in groups for line in groups[name]]
    return eol.join(head + data) + eol


if HAVE_HYPOTHESIS:

    @settings(max_examples=30, deadline=None)
    @given(
        document=_vcf_documents(),
        chunk=st.integers(min_value=64, max_value=512),
        min_af=st.sampled_from([None, 0.05]),
    )
    def test_fuzz_streamed_matches_in_memory(document, chunk, min_af):
        """Property: for ANY (sorted) fuzzed VCF document and ANY chunk size
        — including chunks smaller than one line — the streamed pass produces
        the same blocks and the same contig bounds as the in-memory parser.
        This is the chunk-boundary/carry torture test."""
        doc = _coordinate_sort(document)
        fd, path = tempfile.mkstemp(suffix=".vcf")
        try:
            with os.fdopen(fd, "w", newline="") as f:
                f.write(doc)
            plain = FileGenomicsSource([path], stream_chunk_bytes=0)
            streamed = FileGenomicsSource([path], stream_chunk_bytes=chunk)
            set_id = plain.set_ids[0]
            plain_contigs = plain.get_contigs(set_id)
            streamed_contigs = streamed.get_contigs(set_id)
            assert [
                (c.reference_name, c.start, c.end) for c in streamed_contigs
            ] == [(c.reference_name, c.start, c.end) for c in plain_contigs]
            for c in plain_contigs:
                window = Contig(c.reference_name, 0, 1 << 40)
                want = _blocks_concat(
                    plain.genotype_blocks(
                        set_id, window, block_size=4, min_allele_frequency=min_af
                    )
                )
                got = _blocks_concat(
                    streamed.genotype_blocks(
                        set_id, window, block_size=4, min_allele_frequency=min_af
                    )
                )
                for w, g in zip(want, got):
                    np.testing.assert_array_equal(w, g)
        finally:
            os.unlink(path)

else:

    @pytest.mark.skip(reason="hypothesis not installed (test extra)")
    def test_fuzz_streamed_matches_in_memory():
        pass


def test_cli_streamed_run_matches_in_memory(tmp_path, capsys):
    """variants-pca end to end: the streamed run (auto-selected packed via
    --stream-chunk-bytes) prints byte-identical output — PCs AND I/O stats —
    to the in-memory packed run and the wire run."""
    path = _make_vcf(tmp_path, n_samples=5, rows_per_contig=80)
    base = [
        "--source", "file", "--input-files", path,
        "--references", "17:0:2500",
        "--min-allele-frequency", "0.1",
        "--block-size", "32",
    ]

    def run(extra):
        lines = pca_driver.run(base + extra)
        return lines, capsys.readouterr().out

    streamed_lines, streamed_out = run(["--stream-chunk-bytes", "1"])
    packed_lines, packed_out = run(
        ["--ingest", "packed", "--stream-chunk-bytes", "0"]
    )
    wire_lines, _ = run(["--ingest", "wire", "--stream-chunk-bytes", "0"])
    assert streamed_lines == packed_lines == wire_lines
    assert streamed_out == packed_out


def test_cli_streamed_sharded_strategy_matches_wire(tmp_path, capsys):
    """Streamed file ingest composed with the SHARDED similarity strategy:
    the streamed blocks feed the row-tile-sharded Gramian + sharded
    centering/eigensolve and the principal components match the wire run."""
    path = _make_vcf(tmp_path, n_samples=6, rows_per_contig=90)
    base = [
        "--source", "file", "--input-files", path,
        "--references", "17:0:3000",
        "--block-size", "32",
    ]
    wire = pca_driver.run(base + ["--ingest", "wire", "--stream-chunk-bytes", "0"])
    capsys.readouterr()
    streamed_sharded = pca_driver.run(
        base
        + [
            "--stream-chunk-bytes", "1",
            "--similarity-strategy", "sharded",
            "--mesh-shape", "1,8",
        ]
    )
    capsys.readouterr()
    assert_pcs_match(wire, streamed_sharded)


def test_streamed_ingest_memory_is_bounded_by_chunk(tmp_path):
    """The capability claim, measured: peak traced host allocations during a
    full streamed ingest stay a small multiple of the chunk size — far under
    the file size — while the in-memory parse necessarily holds O(file).
    (tracemalloc sees every chunk buffer and numpy array; the enforced-cap
    equivalent of an rlimit without its JAX address-space fragility.)"""
    path = _make_vcf(
        tmp_path, n_samples=40, rows_per_contig=6000, contigs=("1", "2")
    )
    file_bytes = int(np.int64(__import__("os").path.getsize(path)))
    assert file_bytes > 2_000_000  # the claim is vacuous on a tiny file
    chunk = 1 << 16
    source = FileGenomicsSource([path], stream_chunk_bytes=chunk)
    set_id = source.set_ids[0]
    shards = [Contig("1", 0, 1 << 40), Contig("2", 0, 1 << 40)]

    tracemalloc.start()
    tracemalloc.reset_peak()
    rows = 0
    for block in source.stream_genotype_blocks(set_id, shards, block_size=64):
        rows += len(block["positions"])
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert rows > 0
    # Generous bound: a handful of chunk-sized buffers plus parsed arrays
    # for one chunk. The whole-file path would need >= file_bytes.
    assert peak < 16 * chunk + (1 << 20), (
        f"streamed ingest peak {peak} bytes vs chunk {chunk} "
        f"(file is {file_bytes} bytes)"
    )
    assert peak < file_bytes // 2


def test_cli_streamed_run_memory_is_bounded(tmp_path):
    """The VERDICT-r4 'Done' criterion, literally: a VCF ingested THROUGH
    ``variants-pca --source file`` with streaming on keeps peak traced host
    memory far below the file size (tracemalloc sees every chunk buffer and
    parse array; device buffers are O(N²), not O(file)). The wire path on
    the same file allocates a multiple of the file size in Python records —
    asserted as the contrast so the bound stays meaningful."""
    path = _make_vcf(
        tmp_path, n_samples=30, rows_per_contig=4000, contigs=("1", "2")
    )
    file_bytes = os.path.getsize(path)
    assert file_bytes > 1_000_000
    chunk = 1 << 16
    argv = [
        "--source", "file", "--input-files", path,
        "--all-references",
        "--block-size", "64",
    ]

    streamed_argv = argv + ["--stream-chunk-bytes", str(chunk)]
    # Warm pass: jit tracing allocates ~20 MB of one-time Python objects
    # that tracemalloc would otherwise attribute to the measured run; the
    # second identical run reuses the compiled programs, so its peak is the
    # parse memory this test is about.
    pca_driver.run(streamed_argv)
    tracemalloc.start()
    tracemalloc.reset_peak()
    streamed_lines = pca_driver.run(streamed_argv)
    _, streamed_peak = tracemalloc.get_traced_memory()
    tracemalloc.reset_peak()
    wire_lines = pca_driver.run(
        argv + ["--stream-chunk-bytes", "0", "--ingest", "wire"]
    )
    _, wire_peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()

    assert streamed_lines == wire_lines
    assert streamed_peak < file_bytes // 2, (
        f"streamed CLI peak {streamed_peak} vs file {file_bytes}"
    )
    assert wire_peak > file_bytes  # the bound distinguishes the two paths

"""Distributed tracing + flight recorder + prover conformance (obs/trace,
obs/recorder, the serve-side propagation, and the `trace export` verb):
recorder ring/flush/torn-tail semantics, kill-point flush hooks, the
Chrome-trace merge (span pairing, truncated-span closure, steal flow
arrows) and its validator, trace-id propagation client → HTTP → journal →
steal, and the conformance gauges/manifest block."""

import json
import os
import threading
import time

import pytest

from spark_examples_tpu.obs.metrics import (
    CONFORMANCE_PROVERS,
    MetricsRegistry,
    PROVER_CONFORMANCE_MEASURED,
    PROVER_CONFORMANCE_PROVEN,
    conformance_block,
    record_prover_conformance,
)
from spark_examples_tpu.obs.recorder import (
    FlightRecorder,
    read_segments,
    trace_dir,
)
from spark_examples_tpu.obs.trace import (
    TRACE_HEADER,
    export_main,
    merge_run_trace,
    mint_trace_id,
    normalize_trace_id,
    validate_chrome_trace,
)
from spark_examples_tpu.utils import faults

TINY_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]


# ------------------------------------------------------------ trace ids


def test_trace_id_mint_and_normalize():
    tid = mint_trace_id()
    assert normalize_trace_id(tid) == tid
    assert normalize_trace_id(tid.upper()) == tid
    assert normalize_trace_id("  " + tid + "  ") == tid
    # Malformed ids are rejected, never raised on — the caller mints.
    for bad in (None, 42, "", "short", "g" * 32, "a b c d e f a b"):
        assert normalize_trace_id(bad) is None
    assert mint_trace_id() != mint_trace_id()


# ------------------------------------------------------------- recorder


def test_recorder_round_trip(tmp_path):
    rec = FlightRecorder(str(tmp_path), "a", clock=lambda: 10.0)
    rec.record("accepted", job="job-1", trace="ab" * 16, job_class="small")
    rec.begin("job", job="job-1", tid="small-0")
    rec.end("job", job="job-1", tid="small-0", status="done")
    assert rec.flush() == 3
    events = read_segments(str(tmp_path))
    assert [e["name"] for e in events] == ["accepted", "job", "job"]
    assert [e["ph"] for e in events] == ["i", "B", "E"]
    assert events[0]["args"] == {"job_class": "small"}
    assert events[0]["trace"] == "ab" * 16
    assert events[1]["tid"] == "small-0"
    assert events[0]["replica"] == "a"
    rec.close()


def test_recorder_ring_bound_drops_oldest(tmp_path):
    rec = FlightRecorder(str(tmp_path), "a", capacity=3)
    for i in range(7):
        rec.record(f"e{i}")
    assert rec.flush() == 4  # 3 survivors + the ring-overflow marker
    events = read_segments(str(tmp_path))
    assert events[0]["name"] == "ring-overflow"
    assert events[0]["args"]["dropped"] == 4
    assert [e["name"] for e in events[1:]] == ["e4", "e5", "e6"]
    rec.close()


def test_recorder_torn_tail_skipped(tmp_path):
    rec = FlightRecorder(str(tmp_path), "a")
    rec.record("whole")
    rec.flush()
    rec.close()
    with open(rec.path, "a", encoding="utf-8") as f:
        f.write('{"ts": 1.0, "name": "torn", "ph": "i", "repl')
    events = read_segments(str(tmp_path))
    assert [e["name"] for e in events] == ["whole"]


def test_recorder_closed_ignores_and_bad_phase_raises(tmp_path):
    rec = FlightRecorder(str(tmp_path), "a")
    with pytest.raises(ValueError):
        rec.record("x", ph="Q")
    rec.close()
    rec.record("late")
    assert rec.flush() == 0
    assert read_segments(str(tmp_path)) == []


def test_recorder_two_incarnations_do_not_collide(tmp_path):
    """Same replica name, distinct segment files per pid-suffixed path
    (here: two recorder instances — their events both survive)."""
    a1 = FlightRecorder(str(tmp_path), "a")
    a1.record("first-life")
    a1.flush()
    a1.close()
    a2 = FlightRecorder(str(tmp_path), "a")
    assert a2.path == a1.path  # same pid in tests — appends, still whole
    a2.record("second-life")
    a2.flush()
    a2.close()
    names = [e["name"] for e in read_segments(str(tmp_path))]
    assert names == ["first-life", "second-life"]


def test_fault_kill_point_flushes_recorder(tmp_path):
    """The crash-durability contract: a registered flush hook runs BEFORE
    an injected fault fires, so the ring reaches disk ahead of the kill
    the chaos harness is about to assert recovery from."""
    rec = FlightRecorder(str(tmp_path), "a")
    faults.add_flush_hook(rec.flush)
    try:
        faults.configure("raise@serve.worker.mid-job")
        rec.record("about-to-die", job="job-1")
        with pytest.raises(faults.InjectedFault):
            faults.kill_point("serve.worker.mid-job")
        # NOT via rec.flush() here: the hook must already have drained it.
        events = read_segments(str(tmp_path))
        assert [e["name"] for e in events] == ["about-to-die"]
    finally:
        faults.remove_flush_hook(rec.flush)
        faults.configure(None)
        rec.close()


def test_fault_flush_hook_errors_are_swallowed():
    def bad_hook():
        raise RuntimeError("telemetry bug")

    faults.add_flush_hook(bad_hook)
    try:
        faults.configure("raise@serve.worker.claim")
        with pytest.raises(faults.InjectedFault):
            faults.kill_point("serve.worker.claim")
    finally:
        faults.remove_flush_hook(bad_hook)
        faults.configure(None)


# ------------------------------------------------------- merge + validate


def _write_segment(tmp_path, replica, events):
    directory = trace_dir(str(tmp_path))
    os.makedirs(directory, exist_ok=True)
    with open(
        os.path.join(directory, f"{replica}.1.jsonl"), "w", encoding="utf-8"
    ) as f:
        for event in events:
            base = {"replica": replica, "pid": 1, "tid": "control"}
            base.update(event)
            f.write(json.dumps(base) + "\n")


def _write_journal(tmp_path, records):
    from spark_examples_tpu.serve.journal import journal_path

    with open(journal_path(str(tmp_path)), "w", encoding="utf-8") as f:
        for record in records:
            f.write(json.dumps(record) + "\n")


def test_merge_two_replica_steal_trace(tmp_path):
    """The acceptance shape, in miniature: owner `a` accepts + begins a
    job and dies (its `job` span never ends); stealer `b` steals and
    settles it. The merged trace holds the complete story: a truncated
    span on a, the steal flow arrow a→b, b's terminal — and validates
    with zero orphan spans and zero orphan arrows."""
    trace = mint_trace_id()
    job = "job-a-000001"
    _write_segment(
        tmp_path,
        "a",
        [
            {"ts": 1.0, "name": "accepted", "ph": "i", "trace": trace,
             "job": job},
            {"ts": 1.1, "name": "job", "ph": "B", "trace": trace,
             "job": job, "tid": "all-0", "args": {"epoch": 1}},
            {"ts": 1.2, "name": "device-began", "ph": "i", "trace": trace,
             "job": job, "tid": "all-0", "args": {"epoch": 1}},
            # ... kill -9: no E ever lands on a.
        ],
    )
    _write_segment(
        tmp_path,
        "b",
        [
            {"ts": 3.0, "name": "steal", "ph": "i", "trace": trace,
             "job": job, "args": {"from": "a", "epoch": 2}},
            {"ts": 3.1, "name": "adopt", "ph": "i", "trace": trace,
             "job": job, "args": {"stolen": True, "device_began": True}},
            {"ts": 3.2, "name": "terminal", "ph": "i", "trace": trace,
             "job": job, "args": {"status": "failed"}},
        ],
    )
    _write_journal(
        tmp_path,
        [
            {"event": "accepted", "id": job, "request": {}, "job_class":
             "large", "submitted_unix": 1.0, "trace": trace,
             "replica": "a"},
            {"event": "lease", "id": job, "epoch": 1, "replica": "a"},
            {"event": "began", "id": job, "replica": "a", "epoch": 1},
            {"event": "lease", "id": job, "epoch": 2, "replica": "b",
             "stolen": True},
            {"event": "terminal", "id": job, "status": "failed",
             "replica": "b", "epoch": 2},
        ],
    )
    doc = merge_run_trace(str(tmp_path))
    assert validate_chrome_trace(doc) == []
    events = doc["traceEvents"]
    pids = {
        e["args"]["name"]: e["pid"]
        for e in events
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert set(pids) == {"replica a", "replica b"}
    # The owner's killed span closed as truncated, on its own process.
    spans = [e for e in events if e["ph"] == "X"]
    assert len(spans) == 1
    assert spans[0]["name"] == "job"
    assert spans[0]["pid"] == pids["replica a"]
    assert spans[0]["args"]["truncated"] is True
    assert spans[0]["args"]["epoch"] == 1
    # The steal edge: one whole flow arrow from a's lane to b's.
    s = [e for e in events if e["ph"] == "s"]
    f = [e for e in events if e["ph"] == "f"]
    assert len(s) == 1 and len(f) == 1
    assert s[0]["id"] == f[0]["id"]
    assert s[0]["pid"] == pids["replica a"]
    assert f[0]["pid"] == pids["replica b"]
    # Journal summary: fenced final state + both epochs.
    facts = doc["otherData"]["jobs"][job]
    assert facts["status"] == "failed"
    assert facts["stolen"] is True
    assert facts["lease_epoch"] == 2
    assert facts["trace"] == trace
    assert doc["otherData"]["steal_arrows"] == 1
    assert doc["otherData"]["truncated_spans"] == 1


def test_merge_fences_zombie_terminal(tmp_path):
    """The journal summary applies the same epoch fencing as the replay
    fold: a deposed owner's late terminal does not become the merged
    trace's final state."""
    job = "job-a-000001"
    _write_segment(
        tmp_path, "a", [{"ts": 1.0, "name": "accepted", "ph": "i",
                         "job": job}]
    )
    _write_journal(
        tmp_path,
        [
            {"event": "accepted", "id": job, "request": {},
             "job_class": "small", "submitted_unix": 1.0, "replica": "a"},
            {"event": "lease", "id": job, "epoch": 2, "replica": "b"},
            # Zombie a's fenced terminal (epoch 1) vs b's valid one.
            {"event": "terminal", "id": job, "status": "done",
             "replica": "a", "epoch": 1},
            {"event": "terminal", "id": job, "status": "failed",
             "replica": "b", "epoch": 2},
        ],
    )
    doc = merge_run_trace(str(tmp_path))
    assert doc["otherData"]["jobs"][job]["status"] == "failed"


def test_merge_pairs_requeued_job_spans(tmp_path):
    """A requeued job (crash before device work) runs twice on one
    replica: two B/E pairs become two complete X spans."""
    job = "job-000001"
    _write_segment(
        tmp_path,
        "solo",
        [
            {"ts": 1.0, "name": "job", "ph": "B", "job": job},
            {"ts": 1.5, "name": "job", "ph": "E", "job": job,
             "args": {"status": "worker-crashed"}},
            {"ts": 2.0, "name": "job", "ph": "B", "job": job},
            {"ts": 3.0, "name": "job", "ph": "E", "job": job,
             "args": {"status": "done"}},
        ],
    )
    doc = merge_run_trace(str(tmp_path))
    assert validate_chrome_trace(doc) == []
    spans = sorted(
        (e for e in doc["traceEvents"] if e["ph"] == "X"),
        key=lambda e: e["ts"],
    )
    assert len(spans) == 2
    assert spans[0]["args"]["status"] == "worker-crashed"
    assert spans[1]["args"]["status"] == "done"
    assert spans[0]["dur"] == 500_000 and spans[1]["dur"] == 1_000_000


def test_merge_unmatched_end_becomes_instant(tmp_path):
    _write_segment(
        tmp_path,
        "solo",
        [{"ts": 1.0, "name": "job", "ph": "E", "job": "job-1"}],
    )
    doc = merge_run_trace(str(tmp_path))
    assert validate_chrome_trace(doc) == []
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(instants) == 1
    assert instants[0]["args"]["unmatched_end"] is True


def test_merge_empty_run_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        merge_run_trace(str(tmp_path))


def test_validator_catches_malformed_traces():
    assert validate_chrome_trace([]) != []
    assert validate_chrome_trace({"traceEvents": "nope"}) != []
    ok = {
        "traceEvents": [
            {"ph": "B", "name": "s", "pid": 1, "tid": 1, "ts": 0},
            {"ph": "E", "name": "s", "pid": 1, "tid": 1, "ts": 5},
        ]
    }
    assert validate_chrome_trace(ok) == []
    orphan_b = {
        "traceEvents": [{"ph": "B", "name": "s", "pid": 1, "tid": 1, "ts": 0}]
    }
    assert any("orphan span" in e for e in validate_chrome_trace(orphan_b))
    orphan_e = {
        "traceEvents": [{"ph": "E", "name": "s", "pid": 1, "tid": 1, "ts": 0}]
    }
    assert any("orphan end" in e for e in validate_chrome_trace(orphan_e))
    crossed = {
        "traceEvents": [
            {"ph": "B", "name": "outer", "pid": 1, "tid": 1, "ts": 0},
            {"ph": "B", "name": "inner", "pid": 1, "tid": 1, "ts": 1},
            {"ph": "E", "name": "outer", "pid": 1, "tid": 1, "ts": 2},
            {"ph": "E", "name": "inner", "pid": 1, "tid": 1, "ts": 3},
        ]
    }
    assert any(
        "mismatched nesting" in e for e in validate_chrome_trace(crossed)
    )
    orphan_flow = {
        "traceEvents": [
            {"ph": "s", "name": "arrow", "id": 7, "pid": 1, "tid": 1, "ts": 0}
        ]
    }
    assert any(
        "orphan flow arrow" in e for e in validate_chrome_trace(orphan_flow)
    )
    bad_dur = {
        "traceEvents": [
            {"ph": "X", "name": "s", "pid": 1, "tid": 1, "ts": 0, "dur": -1}
        ]
    }
    assert any("bad dur" in e for e in validate_chrome_trace(bad_dur))
    bad_ph = {"traceEvents": [{"ph": "?", "name": "s", "ts": 0}]}
    assert any("unknown phase" in e for e in validate_chrome_trace(bad_ph))


# ------------------------------------------------------------ CLI verb


def test_trace_export_cli(tmp_path, capsys):
    job = "job-000001"
    _write_segment(
        tmp_path,
        "solo",
        [
            {"ts": 1.0, "name": "job", "ph": "B", "job": job},
            {"ts": 2.0, "name": "job", "ph": "E", "job": job,
             "args": {"status": "done"}},
        ],
    )
    out = tmp_path / "merged.json"
    rc = export_main(
        ["export", "--run-dir", str(tmp_path), "--out", str(out)]
    )
    assert rc == 0
    doc = json.loads(out.read_text())
    assert validate_chrome_trace(doc) == []
    assert any(e["ph"] == "X" for e in doc["traceEvents"])
    # Default output path lands under <run-dir>/trace/.
    assert export_main(["export", "--run-dir", str(tmp_path)]) == 0
    assert os.path.exists(
        os.path.join(trace_dir(str(tmp_path)), "merged.trace.json")
    )


def test_trace_export_cli_exit_codes(tmp_path):
    assert export_main([]) == 2  # no subcommand
    assert export_main(["frobnicate"]) == 2  # unknown subcommand
    missing = str(tmp_path / "nope")
    assert export_main(["export", "--run-dir", missing]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert export_main(["export", "--run-dir", str(empty)]) == 1


def test_trace_cli_verb_registered():
    from spark_examples_tpu.cli import COMMANDS, main

    assert "trace" in COMMANDS
    assert main(["trace"]) == 2


# ---------------------------------------------------------- conformance


def test_record_and_read_conformance_block():
    registry = MetricsRegistry()
    assert conformance_block(registry) is None
    record_prover_conformance(registry, "hostmem", 100, 200)
    record_prover_conformance(registry, "sched", 64, 64)
    record_prover_conformance(registry, "ranges", 8, None)
    block = conformance_block(registry)
    assert block == {
        "hostmem": {"measured": 100, "proven": 200, "ok": True},
        "sched": {"measured": 64, "proven": 64, "ok": True},
        "ranges": {"measured": 8, "proven": None, "ok": None},
    }
    # A regression reads as ok=False, never as a silent clamp.
    record_prover_conformance(registry, "hostmem", 300, 200)
    assert conformance_block(registry)["hostmem"]["ok"] is False
    with pytest.raises(Exception):
        record_prover_conformance(registry, "mystery", 1, 2)


def test_conformance_gauges_export_on_prometheus_text():
    registry = MetricsRegistry()
    record_prover_conformance(registry, "hostmem", 100, 200)
    text = registry.prometheus_text()
    assert (
        f'{PROVER_CONFORMANCE_MEASURED}{{prover="hostmem"}} 100' in text
    )
    assert f'{PROVER_CONFORMANCE_PROVEN}{{prover="hostmem"}} 200' in text


def test_manifest_validator_conformance_block():
    from spark_examples_tpu.obs.manifest import (
        build_manifest,
        validate_manifest,
    )

    doc = build_manifest(
        conformance={
            "hostmem": {"measured": 1, "proven": 2, "ok": True},
            "ranges": None,
        }
    )
    assert validate_manifest(doc) == []
    assert validate_manifest(build_manifest(conformance=None)) == []
    bad = build_manifest(conformance={"mystery": {"measured": 1}})
    assert any("unknown prover" in e for e in validate_manifest(bad))
    bad = build_manifest(conformance={"hostmem": {"proven": 2}})
    assert any(
        "hostmem.measured" in e for e in validate_manifest(bad)
    )
    bad = build_manifest(
        conformance={"hostmem": {"measured": -1, "proven": None, "ok": None}}
    )
    assert any("hostmem.measured" in e for e in validate_manifest(bad))
    bad = build_manifest(
        conformance={"hostmem": {"measured": 1, "proven": 2, "ok": "yes"}}
    )
    assert any("hostmem.ok" in e for e in validate_manifest(bad))


def test_run_pipeline_registers_hostmem_conformance(tmp_path):
    """Driver e2e: a bounded synthetic run's manifest carries the hostmem
    conformance pair with measured <= proven (the CI tripwire's shape)."""
    from spark_examples_tpu.config import PcaConf
    from spark_examples_tpu.pipeline.pca_driver import run_pipeline

    manifest_path = str(tmp_path / "manifest.json")
    conf = PcaConf.parse(TINY_FLAGS + ["--metrics-json", manifest_path])
    result = run_pipeline(conf)
    doc = result.manifest
    block = doc.get("conformance")
    assert block is not None
    hostmem = block["hostmem"]
    assert hostmem is not None
    assert hostmem["proven"] is not None
    assert 0 < hostmem["measured"] <= hostmem["proven"]
    assert hostmem["ok"] is True
    from spark_examples_tpu.obs.manifest import validate_manifest

    assert validate_manifest(doc) == []


@pytest.mark.slow
def test_run_pipeline_check_ranges_conformance(tmp_path):
    """--check-ranges adds the ranges pair next to hostmem's."""
    from spark_examples_tpu.config import PcaConf
    from spark_examples_tpu.pipeline.pca_driver import run_pipeline

    conf = PcaConf.parse(
        TINY_FLAGS
        + [
            "--ingest", "packed", "--check-ranges",
            "--metrics-json", str(tmp_path / "m.json"),
        ]
    )
    block = run_pipeline(conf).manifest["conformance"]
    assert block["ranges"] is not None
    assert block["ranges"]["ok"] is True
    assert block["hostmem"]["ok"] is True


# -------------------------------------------------- serve-side propagation


class _InstantExecutor:
    def __init__(self, conformance=None):
        self.conformance = conformance

    def __call__(self, job, run_dir):
        from spark_examples_tpu.serve.executor import ExecutionOutcome

        return ExecutionOutcome(
            result={"ok": True},
            manifest_path=None,
            compile_cache="cold",
            conformance=self.conformance,
        )


def test_serve_trace_propagation_and_recorder(tmp_path):
    """One in-process service: a client-sent trace id is echoed on the
    job envelope, journaled on the accepted record, stamped on every
    recorder event, and the drained run dir exports a valid Chrome trace
    holding the job's complete span."""
    from spark_examples_tpu.serve.daemon import PcaService
    from spark_examples_tpu.serve.journal import (
        iter_journal_records,
        journal_path,
    )
    from spark_examples_tpu.serve.protocol import request_doc

    run_dir = str(tmp_path / "serve")
    service = PcaService(run_dir=run_dir, executor=_InstantExecutor()).start()
    try:
        trace = mint_trace_id()
        status, doc = service.submit(request_doc(TINY_FLAGS), trace_id=trace)
        assert status == 202
        assert doc["job"]["trace"] == trace
        job_id = doc["job"]["id"]
        deadline = time.monotonic() + 10
        while service.job_status(job_id)[1]["job"]["status"] != "done":
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # A malformed client header gets a minted replacement, not a 4xx.
        status, doc2 = service.submit(
            request_doc(TINY_FLAGS), trace_id="NOT HEX!"
        )
        assert status == 202
        assert normalize_trace_id(doc2["job"]["trace"]) is not None
        assert doc2["job"]["trace"] != trace
    finally:
        assert service.stop(timeout=30)
    accepted = [
        r
        for r in iter_journal_records(journal_path(run_dir))
        if r.get("event") == "accepted" and r.get("id") == job_id
    ]
    # Compaction may have dropped the settled record; the recorder is
    # the durable timeline either way.
    for record in accepted:
        assert record["trace"] == trace
    events = read_segments(run_dir)
    job_events = [e for e in events if e.get("job") == job_id]
    assert {"accepted", "job", "terminal"} <= {e["name"] for e in job_events}
    assert all(e.get("trace") == trace for e in job_events)
    doc = merge_run_trace(run_dir)
    assert validate_chrome_trace(doc) == []
    spans = [
        e
        for e in doc["traceEvents"]
        if e["ph"] == "X" and e["args"].get("job") == job_id
    ]
    assert len(spans) == 1
    assert spans[0]["args"]["status"] == "done"
    assert spans[0]["args"]["trace"] == trace
    assert not spans[0]["args"].get("truncated")


def test_serve_mirrors_job_conformance_into_metrics(tmp_path):
    from spark_examples_tpu.serve.daemon import PcaService
    from spark_examples_tpu.serve.protocol import request_doc

    block = {
        "hostmem": {"measured": 123, "proven": 456, "ok": True},
        "sched": None,
        "ranges": None,
    }
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        executor=_InstantExecutor(conformance=block),
    ).start()
    try:
        status, doc = service.submit(request_doc(TINY_FLAGS))
        assert status == 202
        job_id = doc["job"]["id"]
        deadline = time.monotonic() + 10
        while service.job_status(job_id)[1]["job"]["status"] != "done":
            assert time.monotonic() < deadline
            time.sleep(0.01)
        text = service.metrics_text()
        assert f'{PROVER_CONFORMANCE_MEASURED}{{prover="hostmem"}} 123' in text
        assert f'{PROVER_CONFORMANCE_PROVEN}{{prover="hostmem"}} 456' in text
    finally:
        assert service.stop(timeout=30)


def test_client_sends_trace_header(tmp_path):
    """HTTP e2e: ServeClient mints the X-Trace-Id header; the server
    echoes it through the job envelope."""
    from spark_examples_tpu.serve.daemon import PcaService
    from spark_examples_tpu.serve.client import ServeClient
    from spark_examples_tpu.serve.http import start_server

    service = PcaService(
        run_dir=str(tmp_path / "serve"), executor=_InstantExecutor()
    ).start()
    server = start_server(service, port=0)
    try:
        client = ServeClient(server.url)
        doc = client.submit(TINY_FLAGS, trace_id="f" * 32)
        assert doc["job"]["trace"] == "f" * 32
        minted = client.submit(TINY_FLAGS)
        assert normalize_trace_id(minted["job"]["trace"]) is not None
    finally:
        server.shutdown()
        service.stop(timeout=30)


def test_replayed_job_keeps_journaled_trace(tmp_path):
    """Restart e2e: a job accepted by one service life is replayed by the
    next with the SAME trace id — one job, one span tree across lives."""
    from spark_examples_tpu.serve.daemon import PcaService
    from spark_examples_tpu.serve.protocol import request_doc

    run_dir = str(tmp_path / "serve")

    class _Gate:
        def __init__(self):
            self.release = threading.Event()

        def __call__(self, job, run_dir):
            from spark_examples_tpu.serve.executor import ExecutionOutcome

            assert self.release.wait(timeout=30)
            return ExecutionOutcome(
                result={"ok": True}, manifest_path=None, compile_cache="cold"
            )

    gate = _Gate()
    first = PcaService(run_dir=run_dir, executor=gate).start()
    status, doc = first.submit(request_doc(TINY_FLAGS), trace_id="ab" * 16)
    assert status == 202
    job_id = doc["job"]["id"]
    # Abandon the first life without draining (the restart story); the
    # worker is parked on the gate so the job never began device work.
    second = PcaService(run_dir=run_dir, executor=_InstantExecutor()).start()
    try:
        status, doc = second.job_status(job_id)
        assert status == 200
        assert doc["job"]["trace"] == "ab" * 16
    finally:
        gate.release.set()
        second.stop(timeout=30)


# ------------------------------------------------- review-hardening fixes


def test_conformance_rerecord_clears_stale_proven():
    """Last-write-wins mirroring: a later unprovable pair must not keep
    the earlier job's proven bound (which would fabricate verdicts from
    two different jobs)."""
    registry = MetricsRegistry()
    record_prover_conformance(registry, "hostmem", 100, 200)
    record_prover_conformance(registry, "hostmem", 700, None)
    block = conformance_block(registry)
    assert block["hostmem"] == {"measured": 700, "proven": None, "ok": None}


def test_conformance_verdict_compares_raw_floats():
    """The ok verdict is computed on the raw floats — rounding for the
    manifest's int contract must never turn a violated bound into a
    pass."""
    registry = MetricsRegistry()
    record_prover_conformance(registry, "ranges", 1000.4, 1000.2)
    block = conformance_block(registry)
    # The displayed ints round in the verdict's direction, so the int
    # pair re-derives the same verdict (the serve mirror re-records the
    # ints — a violated bound must stay violated on /metrics too).
    assert block["ranges"]["ok"] is False
    assert block["ranges"]["measured"] == 1001
    assert block["ranges"]["proven"] == 1000
    assert block["ranges"]["measured"] > block["ranges"]["proven"]
    record_prover_conformance(registry, "ranges", 0.4, 0.5)
    block = conformance_block(registry)
    assert block["ranges"]["ok"] is True
    assert block["ranges"]["measured"] <= block["ranges"]["proven"]
    # Mirror round trip: re-recording the displayed ints preserves the
    # verdict in both directions.
    for measured, proven, verdict in ((1000.4, 1000.2, False), (3.0, 7.0, True)):
        record_prover_conformance(registry, "sched", measured, proven)
        pair = conformance_block(registry)["sched"]
        mirror = MetricsRegistry()
        record_prover_conformance(
            mirror, "sched", pair["measured"], pair["proven"]
        )
        assert conformance_block(mirror)["sched"]["ok"] is verdict


def test_recorder_failed_flush_retains_events(tmp_path):
    """A flush that cannot reach the disk must restore the drained ring
    (and drop accounting) for the next attempt, never discard it."""
    # A FILE named `trace` makes the segment directory uncreatable.
    blocker = tmp_path / "trace"
    blocker.write_text("in the way")
    rec = FlightRecorder(str(tmp_path), "a", capacity=2)
    rec.record("one")
    rec.record("two")
    rec.record("three")  # overflows: "one" dropped
    assert rec.flush() == 0
    assert rec.dropped == 1  # the drop count survived the failure
    blocker.unlink()
    assert rec.flush() == 3  # overflow marker + the two retained events
    events = read_segments(str(tmp_path))
    assert [e["name"] for e in events] == ["ring-overflow", "two", "three"]
    assert events[0]["args"]["dropped"] == 1
    rec.close()


def test_steal_arrow_anchors_at_or_before_the_steal(tmp_path):
    """A deposed-but-alive zombie owner keeps recording after the steal;
    the arrow must anchor on its last event AT OR BEFORE the steal, not
    be dropped because the owner's globally-last event postdates it."""
    job = "job-a-000001"
    _write_segment(
        tmp_path,
        "a",
        [
            {"ts": 1.0, "name": "job", "ph": "B", "job": job},
            # The zombie wakes AFTER b's steal and abandons.
            {"ts": 5.0, "name": "job", "ph": "E", "job": job,
             "args": {"status": "failed", "abandoned": "lease-lost"}},
            {"ts": 5.1, "name": "abandoned", "ph": "i", "job": job},
        ],
    )
    _write_segment(
        tmp_path,
        "b",
        [
            {"ts": 3.0, "name": "steal", "ph": "i", "job": job,
             "args": {"from": "a", "epoch": 2}},
            {"ts": 3.5, "name": "terminal", "ph": "i", "job": job,
             "args": {"status": "failed"}},
        ],
    )
    doc = merge_run_trace(str(tmp_path))
    assert validate_chrome_trace(doc) == []
    s = [e for e in doc["traceEvents"] if e["ph"] == "s"]
    f = [e for e in doc["traceEvents"] if e["ph"] == "f"]
    assert len(s) == 1 and len(f) == 1
    # Anchored at the owner's B (ts 1.0) — at-or-before the steal's 3.0.
    assert s[0]["ts"] <= f[0]["ts"]


def test_read_segments_skips_foreign_jsonl(tmp_path):
    """A foreign JSONL dropped into trace/ (valid JSON, not our event
    schema) is skipped like a torn tail — the export must not crash."""
    _write_segment(
        tmp_path,
        "solo",
        [{"ts": 1.0, "name": "job", "ph": "B", "job": "job-1"},
         {"ts": 2.0, "name": "job", "ph": "E", "job": "job-1"}],
    )
    with open(
        os.path.join(trace_dir(str(tmp_path)), "foreign.jsonl"),
        "w",
        encoding="utf-8",
    ) as f:
        f.write('{"ts": 1.5, "name": "alien", "ph": "i"}\n')  # no replica
        f.write('{"totally": "unrelated"}\n')
    events = read_segments(str(tmp_path))
    assert [e["name"] for e in events] == ["job", "job"]
    doc = merge_run_trace(str(tmp_path))
    assert validate_chrome_trace(doc) == []

"""The seven example analyses against naive host recomputations."""

import os

import numpy as np
import pytest

from spark_examples_tpu.analyses import reads_examples, variants_examples
from spark_examples_tpu.config import GenomicsConf
from spark_examples_tpu.constants import Examples
from spark_examples_tpu.sharding.contig import Contig
from spark_examples_tpu.sources.base import ShardBoundary
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource


@pytest.fixture(scope="module")
def source():
    return SyntheticGenomicsSource(
        num_samples=12, seed=11, variant_spacing=100, read_depth=4
    )


@pytest.fixture()
def conf(tmp_path):
    c = GenomicsConf()
    c.num_samples = 12
    c.seed = 11
    c.output_path = str(tmp_path)
    return c


def test_klotho_counts(conf, source):
    contig = Contig("chr13", 33_628_000, 33_630_000)
    out = variants_examples.run_klotho(conf, source, contig)
    n_total = int(out[0].split()[2])
    n_var = int(out[1].split()[2])
    n_ref = int(out[2].split()[2])
    assert n_total == n_var + n_ref
    assert n_total > 0
    # "Reference: <contig> @ <start>" lines for non-N records.
    ref_lines = [l for l in out if l.startswith("Reference: ")]
    assert len(ref_lines) == n_var  # non-N == has alternates in synthetic data


def test_brca1_counts(conf, source):
    contig = Contig("chr17", 41_196_311, 41_216_311)
    out = variants_examples.run_brca1(conf, source, contig)
    n_total = int(out[0].split()[2])
    assert n_total == int(out[1].split()[2]) + int(out[2].split()[2])


def test_example1_pileup_alignment(conf, source):
    snp = 6_889_648
    out = reads_examples.run_example1(conf, source, snp=snp)
    assert out[0].endswith("v") and out[-1].endswith("^")
    assert len(out) > 2
    # Marker column aligns: every read line has its SNP base directly under
    # the "v" (position of "(" is i+1 chars after the leading spaces).
    marker = len(out[0]) - 1
    for line in out[1:-1]:
        paren = line.index("(")
        assert paren - 1 == marker  # head ends at the SNP base


def test_example2_mean_coverage(conf, source):
    region = (1_000, 21_000)
    coverage = reads_examples.run_example2(conf, source, region=region)
    # Naive recomputation.
    client = source.client()
    reads = list(
        client.search_reads(
            {
                "readGroupSetIds": [Examples.GOOGLE_EXAMPLE_READSET],
                "referenceName": "21",
                "start": region[0],
                "end": region[0] + (region[1] - region[0]) // 1,
            }
        )
    )
    # run_example2 divides by the full chromosome length, as the reference
    # does (SearchReadsExample.scala:130-131).
    expected_total = sum(len(r["alignedSequence"]) for r in reads)
    # Partitioner drops remainder bases; allow the boundary reads to differ.
    assert coverage > 0
    assert abs(coverage * Examples.HUMAN_CHROMOSOMES["21"] - expected_total) <= (
        source.read_length * source.read_depth * 2
    )


def _naive_depth(source, readset, sequence, start, end):
    client = source.client()
    depth = {}
    reads = client.search_reads(
        {
            "readGroupSetIds": [readset],
            "referenceName": sequence,
            "start": start,
            "end": end,
        }
    )
    for r in reads:
        pos = r["alignment"]["position"]["position"]
        for i in range(len(r["alignedSequence"])):
            depth[pos + i] = depth.get(pos + i, 0) + 1
    return depth


def test_example3_depth_matches_naive(conf, source):
    region = (1_000, 9_000)
    part_path = reads_examples.run_example3(conf, source, region=region)
    # The result now STREAMS through the bounded per-site writer; the
    # saved part file is the whole result surface.
    assert part_path == f"{conf.output_path}/coverage_21/part-00000"
    saved = open(part_path).read().splitlines()
    got = {}
    for line in saved:
        pos, depth = line.strip("()").split(",")
        got[int(pos)] = int(depth)
    # The partitioner's span layout may drop trailing remainder bases
    # (reference behavior); naive over the emitted coordinate range.
    max_pos = max(got)
    naive = _naive_depth(source, Examples.GOOGLE_EXAMPLE_READSET, "21", 1_000, 9_000)
    naive = {p: d for p, d in naive.items() if p <= max_pos}
    assert got == naive
    # Byte-identical to the reference's saveAsTextFile shape: Scala tuple
    # rendering, ascending positions, headerless, no streaming artifacts.
    assert saved == [f"({p},{naive[p]})" for p in sorted(naive)]
    assert not [
        f
        for f in os.listdir(f"{conf.output_path}/coverage_21")
        if f.endswith(".tmp")
    ]


def test_example4_finds_somatic_differences(conf):
    source = SyntheticGenomicsSource(
        num_samples=4, seed=13, read_depth=6, somatic_rate=0.01
    )
    region = (100_000_000, 100_008_000)
    lines = reads_examples.run_example4(
        conf,
        source,
        region=region,
        normal_readset=Examples.GOOGLE_DREAM_SET3_NORMAL,
        tumor_readset=Examples.GOOGLE_DREAM_SET3_TUMOR,
    )
    assert lines, "synthetic somatic sites must produce differences"
    positions = np.array([int(l.strip("()").split(",")[0]) for l in lines])
    # Every reported position is a synthetic somatic site.
    somatic = source._is_somatic_site("1", positions)
    assert somatic.all()
    # Format: (pos,(normalBases,tumorBases)), ascending positions.
    assert (np.diff(positions) > 0).all()
    for line in lines:
        inner = line.split(",(", 1)[1].rstrip(")")
        normal_bases, tumor_bases = inner.split(",")
        assert normal_bases != tumor_bases
    saved = open(f"{conf.output_path}/diff_1/part-00000").read().splitlines()
    assert saved == lines


def test_cli_dispatch(capsys, tmp_path):
    from spark_examples_tpu.cli import main

    assert main([]) == 0
    assert "variants-pca" in capsys.readouterr().out
    assert main(["bogus"]) == 2
    rc = main(
        [
            "variants-pca",
            "--references", "17:0:10000",
            "--num-samples", "8",
            "--variant-set-id", "vs-x",
            "--bases-per-partition", "5000",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "Matrix size: 8." in out


def test_example3_depth_long_reads(conf):
    """Reads longer than the old 256-bp cap are fully counted (no silent
    truncation): depth from a 400-bp-read source matches the naive oracle."""
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    long_source = SyntheticGenomicsSource(
        num_samples=4, seed=3, read_length=400, read_depth=2
    )
    region = (1_000, 6_000)
    part_path = reads_examples.run_example3(conf, long_source, region=region)
    got = {}
    for line in open(part_path).read().splitlines():
        pos, depth = line.strip("()").split(",")
        got[int(pos)] = int(depth)
    max_pos = max(got)
    naive = _naive_depth(
        long_source, Examples.GOOGLE_EXAMPLE_READSET, "21", *region
    )
    naive = {p: d for p, d in naive.items() if p <= max_pos}
    assert got == naive
    # A 400-bp tiling really produces depths past position+256.
    assert any(p - 1_000 > 256 for p in got)


def test_reads_overlaps_boundary():
    """OVERLAPS returns reads that start before the range but extend into
    it; STRICT returns only reads starting inside (exactly-one-shard)."""
    from spark_examples_tpu.sources.base import ShardBoundary
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    source = SyntheticGenomicsSource(num_samples=4, seed=3)
    client = source.client()
    request = {
        "readGroupSetIds": ["rgs"],
        "referenceName": "21",
        "start": 5_000,
        "end": 5_200,
    }
    strict = list(client.search_reads(request, ShardBoundary.STRICT))
    overlaps = list(client.search_reads(request, ShardBoundary.OVERLAPS))
    strict_ids = {r["id"] for r in strict}
    overlap_ids = {r["id"] for r in overlaps}
    assert strict_ids < overlap_ids  # strictly more reads under OVERLAPS
    for r in overlaps:
        pos = r["alignment"]["position"]["position"]
        L = len(r["alignedSequence"])
        assert pos + L > 5_000 and pos < 5_200  # genuinely overlapping
    extra = overlap_ids - strict_ids
    for r in overlaps:
        if r["id"] in extra:
            assert r["alignment"]["position"]["position"] < 5_000


def test_profile_dir_stage_timings(tmp_path, capsys):
    """--profile-dir writes a device trace and prints stage timings."""
    from spark_examples_tpu.pipeline import pca_driver

    prof = str(tmp_path / "prof")
    pca_driver.run(
        [
            "--references", "17:0:10000",
            "--variant-set-id", "vs",
            "--num-samples", "8",
            "--block-size", "32",
            "--profile-dir", prof,
        ]
    )
    out = capsys.readouterr().out
    assert "Stage timings:" in out
    assert "ingest+similarity:" in out and "center+pca:" in out
    assert os.path.isdir(prof) and os.listdir(prof)

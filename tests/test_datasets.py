"""VariantsDataset / ReadsDataset streaming and stats accounting."""

from spark_examples_tpu.pipeline.datasets import ReadsDataset, VariantsDataset
from spark_examples_tpu.pipeline.stats import VariantsDatasetStats
from spark_examples_tpu.sharding.contig import Contig
from spark_examples_tpu.sharding.partitioners import (
    FixedSplits,
    ReadsPartitioner,
    VariantsPartitioner,
)


def test_variants_dataset_streams_all_shards(small_source):
    partitioner = VariantsPartitioner([Contig("17", 0, 10_000)], 2_500)
    stats = VariantsDatasetStats()
    dataset = VariantsDataset(small_source, "vs-a", partitioner, stats=stats)
    records = list(dataset)
    assert len(records) > 0
    assert stats.partitions == 4
    assert stats.reference_bases == 10_000
    assert stats.variants >= len(records)
    assert stats.requests >= 4

    # Same records regardless of sharding (STRICT boundaries).
    one_shard = VariantsDataset(
        small_source, "vs-a", VariantsPartitioner([Contig("17", 0, 10_000)], 10_000)
    )
    assert [k for k, _ in one_shard] == [k for k, _ in records]


def test_variants_dataset_parallel_matches_serial(small_source):
    partitioner = VariantsPartitioner([Contig("17", 0, 20_000)], 2_000)
    serial = VariantsDataset(small_source, "vs-a", partitioner, num_workers=1)
    parallel = VariantsDataset(small_source, "vs-a", partitioner, num_workers=8)
    assert list(serial) == list(parallel)


def test_stats_report_format():
    stats = VariantsDatasetStats()
    report = str(stats)
    # Line-for-line shape of rdd/VariantsRDD.scala:160-171.
    assert report.startswith("Variants API stats:\n-----")
    for line in (
        "# of partitions:",
        "# of bases requested:",
        "# of variants read:",
        "# of API requests:",
        "# of unsuccessful responses:",
        "# of IO exceptions:",
    ):
        assert line in report


def test_reads_dataset_streams(small_source):
    partitioner = ReadsPartitioner({"11": (0, 4_000)}, FixedSplits(2))
    dataset = ReadsDataset(small_source, ["rgs-1"], partitioner)
    records = list(dataset)
    assert records
    keys = [k for k, _ in records]
    assert all(0 <= k.position < 4_000 for k in keys)
    # Partition invariance across split counts.
    one = ReadsDataset(
        small_source, ["rgs-1"], ReadsPartitioner({"11": (0, 4_000)}, FixedSplits(1))
    )
    assert sorted(k.position for k, _ in one) == sorted(k.position for k in keys)

"""Test harness configuration.

Multi-device code is exercised on a virtual 8-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) — the moral
equivalent of the reference's ``local[4]`` Spark master (``README.md:38``,
SURVEY.md §4). These env vars must be set before JAX is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def small_source():
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    return SyntheticGenomicsSource(num_samples=40, seed=7, variant_spacing=100)

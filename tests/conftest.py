"""Test harness configuration.

Multi-device code is exercised on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``) — the moral equivalent of the
reference's ``local[4]`` Spark master (``README.md:38``, SURVEY.md §4).

This image pre-registers the real-TPU ``axon`` PJRT backend from a
``sitecustomize`` hook that imports jax at interpreter start, so env vars are
too late; instead we select the CPU platform via ``jax.config`` (the CPU
client is still uncreated at conftest time, so the device-count flag takes
effect).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# CLI invocations inside tests must not flip on the user-level persistent
# compile cache (writes outside tmp_path).
os.environ["SPARK_EXAMPLES_TPU_NO_CACHE"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def small_source():
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    return SyntheticGenomicsSource(num_samples=40, seed=7, variant_spacing=100)

"""Contig shard math, split policies, partitioners."""

import pytest

from spark_examples_tpu.sharding.contig import (
    Contig,
    SexChromosomeFilter,
    filter_sex_chromosomes,
    parse_contigs,
)
from spark_examples_tpu.sharding.partitioners import (
    FixedSplits,
    ReadsPartitioner,
    TargetSizeSplits,
    VariantsPartitioner,
)


def test_get_shards_covers_range_exactly():
    shards = Contig("17", 100, 1050).get_shards(250)
    assert [(s.start, s.end) for s in shards] == [
        (100, 350),
        (350, 600),
        (600, 850),
        (850, 1050),
    ]
    assert all(s.reference_name == "17" for s in shards)


def test_get_shards_single_window():
    assert Contig("1", 0, 10).get_shards(100) == [Contig("1", 0, 10)]


def test_parse_contigs_grammar():
    # GenomicsConf.scala:40-43 grammar: reference:start:end,...
    contigs = parse_contigs("17:41196311:41277499,13:33628137:33628138")
    assert contigs == [
        Contig("17", 41196311, 41277499),
        Contig("13", 33628137, 33628138),
    ]


def test_parse_contigs_rejects_bad_spec():
    with pytest.raises(ValueError):
        parse_contigs("17:123")


def test_sex_chromosome_filter():
    contigs = [Contig("1", 0, 10), Contig("X", 0, 10), Contig("Y", 0, 10)]
    kept = filter_sex_chromosomes(contigs, SexChromosomeFilter.EXCLUDE_XY)
    assert [c.reference_name for c in kept] == ["1"]
    assert (
        filter_sex_chromosomes(contigs, SexChromosomeFilter.INCLUDE_XY) == contigs
    )


def test_variants_partitioner_enumerates_windows():
    partitioner = VariantsPartitioner([Contig("17", 0, 2500)], 1000)
    parts = partitioner.get_partitions("vs-1")
    assert [p.index for p in parts] == [0, 1, 2]
    assert parts[1].get_variants_request() == {
        "variantSetIds": ["vs-1"],
        "referenceName": "17",
        "start": 1000,
        "end": 2000,
    }
    assert parts[2].range == 500


def test_fixed_splits_caps_at_sequence_length():
    # rdd/ReadsPartitioner.scala:76-78
    assert FixedSplits(4).splits(1000) == 4
    assert FixedSplits(4).splits(2) == 2


def test_target_size_splits_formula():
    # rdd/ReadsPartitioner.scala:84-90: 1 + ((len/readLen)*depth*size)/(partSize+1)
    splitter = TargetSizeSplits(100, 5, 1024, 16 * 1024 * 1024)
    assert splitter.splits(48129895) == 1 + (
        (48129895 // 100) * 5 * 1024
    ) // (16 * 1024 * 1024 + 1)


def test_reads_partitioner_layout():
    partitioner = ReadsPartitioner(
        {"11": (1000, 2000), "1": (0, 300)}, FixedSplits(2)
    )
    # Sequence-name order ("1" < "11"), global indices contiguous.
    parts = partitioner.get_partitions(["rgs-a"])
    assert partitioner.count == 4
    assert [(p.sequence, p.start, p.end) for p in parts] == [
        ("1", 0, 150),
        ("1", 150, 300),
        ("11", 1000, 1500),
        ("11", 1500, 2000),
    ]
    assert [p.index for p in parts] == [0, 1, 2, 3]
    assert parts[0].get_reads_request()["readGroupSetIds"] == ["rgs-a"]


def test_reads_partitioner_get_partition_inverts_layout():
    partitioner = ReadsPartitioner(
        {"11": (1000, 2000), "1": (0, 300)}, FixedSplits(2)
    )
    for part in partitioner.get_partitions(["rgs"]):
        for pos in (part.start, part.start + 1, part.end - 1):
            assert partitioner.get_partition(part.sequence, pos) == part.index

"""Two-replica chaos matrix: real daemons, real SIGKILL, byte-compared
against a single-replica oracle.

The acceptance proof of PR 13: with two live replica daemons sharing one
run dir, SIGKILL one of them at EVERY registered serve kill-point
(``serve.worker.claim``, ``serve.worker.mid-job``,
``serve.lease.pre-renew``, ``serve.steal.pre-claim``) and assert that
every accepted job reaches exactly ONE terminal state on the survivor:

- a job whose device work never began re-runs on the survivor with its
  result **byte-identical** to the single-replica oracle run of the same
  request, and the journal shows exactly one ``began``;
- a job journaled ``began`` before the kill fails with the structured
  ``replica-failover:`` error — the devices are never driven twice
  (requeue-once across replica lives);
- a stealer killed at ``serve.steal.pre-claim`` leaves no half-taken
  lease: a later replica claims and settles the job.

Marked slow (each scenario boots 2-3 real daemons); ci.sh stage 5c runs
this matrix alongside its inline two-replica kill -9 smoke.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from spark_examples_tpu.serve.client import ServeClient, ServeError
from spark_examples_tpu.serve.journal import journal_path, replay_journal
from spark_examples_tpu.serve.protocol import TERMINAL_STATUSES

pytestmark = pytest.mark.slow

#: The canonical chaos job: deterministic synthetic cohort, small enough
#: to finish in seconds on one CPU device, big enough to outlive the
#: kill windows.
CHAOS_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]

#: Sub-second failover timings: a lease this stale means its owner died.
LEASE_FLAGS = [
    "--lease-seconds", "1.0",
    "--lease-grace-seconds", "0.2",
    "--steal-interval-seconds", "0.2",
]


def _spawn_replica(run_dir, rid, fault_plan=None, replica=True):
    """One real daemon subprocess; returns (proc, url) once listening."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARK_EXAMPLES_TPU_NO_CACHE"] = "1"
    env.pop("SPARK_EXAMPLES_TPU_FAULTS", None)
    if fault_plan is not None:
        env["SPARK_EXAMPLES_TPU_FAULTS"] = fault_plan
    endpoint = os.path.join(run_dir, f"endpoint.{rid}")
    argv = [
        sys.executable, "-m", "spark_examples_tpu", "serve",
        "--port", "0",
        "--run-dir", run_dir,
        "--executor-slices", "0",
        "--no-persistent-cache",
        "--endpoint-file", endpoint,
    ]
    if replica:
        argv += ["--replica-id", rid] + LEASE_FLAGS
    err = open(os.path.join(run_dir, f"daemon.{rid}.err"), "w")
    proc = subprocess.Popen(
        argv, stdout=subprocess.DEVNULL, stderr=err, env=env
    )
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if os.path.exists(endpoint):
            with open(endpoint, encoding="utf-8") as f:
                return proc, f.read().strip()
        if proc.poll() is not None:
            raise AssertionError(
                f"replica {rid} exited {proc.returncode} before listening; "
                f"stderr: {open(err.name).read()[-2000:]}"
            )
        time.sleep(0.05)
    proc.kill()
    raise AssertionError(f"replica {rid} never published its endpoint")


def _stop(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def _wait_terminal(client, job_id, timeout=300):
    """Poll the survivor for the job's terminal state; 404s are re-polled
    — the job only appears in the survivor's table once stolen."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            doc = client.status(job_id)
        except ServeError as e:
            if e.status != 404:
                raise
        else:
            if doc["job"]["status"] in TERMINAL_STATUSES:
                return doc["job"]
        time.sleep(0.2)
    raise AssertionError(f"job {job_id} never settled on the survivor")


def _journal_facts(run_dir, job_id):
    """(began_count, valid_terminal_count, settled) for one job id."""
    lease_epoch = 0
    began = 0
    terminals = []
    with open(journal_path(run_dir), encoding="utf-8") as f:
        for line in f:
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if record.get("id") != job_id:
                continue
            if record["event"] == "began":
                began += 1
            elif record["event"] == "lease":
                lease_epoch = max(lease_epoch, record.get("epoch", 0))
            elif record["event"] == "terminal":
                terminals.append(record)
    valid = [
        t for t in terminals
        if t.get("epoch") is None or t["epoch"] >= lease_epoch
    ]
    pending, _seq = replay_journal(journal_path(run_dir))
    settled = job_id not in {p.job_id for p in pending}
    return began, len(valid), settled


@pytest.fixture(scope="module")
def oracle_lines(tmp_path_factory):
    """The single-replica oracle: the same request served by a solo
    daemon — the byte-compare reference for every stolen re-run."""
    run_dir = str(tmp_path_factory.mktemp("oracle"))
    proc, url = _spawn_replica(run_dir, "oracle", replica=False)
    try:
        client = ServeClient(url, timeout=60)
        doc = client.submit(CHAOS_FLAGS)
        job = client.wait(doc["job"]["id"], timeout=300)["job"]
        assert job["status"] == "done", job
        return job["result"]["pc_lines"]
    finally:
        _stop(proc)


def _run_kill_scenario(tmp_path, fault_plan):
    """Two replicas; ``a`` carries the fault plan and SIGKILLs itself;
    the client submits to ``a`` then fails over to the survivor ``b``.
    Returns (terminal job doc, run_dir, a's exit code)."""
    run_dir = str(tmp_path / "rd")
    os.makedirs(run_dir, exist_ok=True)
    a_proc, a_url = _spawn_replica(run_dir, "a", fault_plan=fault_plan)
    b_proc, b_url = _spawn_replica(run_dir, "b")
    try:
        client = ServeClient(a_url, timeout=60)
        doc = client.submit(CHAOS_FLAGS)
        job_id = doc["job"]["id"]
        assert job_id.startswith("job-a-")
        a_rc = a_proc.wait(timeout=120)
        survivor = ServeClient(b_url, timeout=60, max_retries=5)
        job = _wait_terminal(survivor, job_id)
        return job, run_dir, a_rc
    finally:
        _stop(b_proc)
        if a_proc.poll() is None:
            a_proc.kill()


def test_kill_at_worker_claim_survivor_reruns_byte_identical(
    tmp_path, oracle_lines
):
    """SIGKILL before any device work: the survivor re-runs the job and
    its eigenvectors are byte-identical to the single-replica oracle."""
    job, run_dir, a_rc = _run_kill_scenario(
        tmp_path, "kill@serve.worker.claim"
    )
    assert a_rc == -signal.SIGKILL
    assert job["status"] == "done", job
    assert job["result"]["pc_lines"] == oracle_lines
    began, valid_terminals, settled = _journal_facts(run_dir, job["id"])
    assert settled and valid_terminals == 1
    assert began == 1  # only the survivor's run touched the devices


def test_kill_at_worker_mid_job_survivor_fails_structured(
    tmp_path, oracle_lines
):
    """SIGKILL after ``began`` was journaled: requeue-once holds across
    replica lives — the survivor settles the job with the structured
    failover error and never drives the devices a second time."""
    job, run_dir, a_rc = _run_kill_scenario(
        tmp_path, "kill@serve.worker.mid-job"
    )
    assert a_rc == -signal.SIGKILL
    assert job["status"] == "failed", job
    assert job["error"].startswith("replica-failover:")
    began, valid_terminals, settled = _journal_facts(run_dir, job["id"])
    assert settled and valid_terminals == 1
    assert began == 1  # the dead replica's begin; never a second one


def test_kill_at_lease_pre_renew_exactly_one_outcome(
    tmp_path, oracle_lines
):
    """SIGKILL at the renewal tick (the canonical host loss): whether
    the job had begun when the host died decides the outcome — re-run
    byte-identical, or structured failure — but either way exactly one
    terminal state and no double device run."""
    job, run_dir, a_rc = _run_kill_scenario(
        tmp_path, "kill@serve.lease.pre-renew"
    )
    assert a_rc == -signal.SIGKILL
    began, valid_terminals, settled = _journal_facts(run_dir, job["id"])
    assert settled and valid_terminals == 1
    if job["status"] == "done":
        assert job["result"]["pc_lines"] == oracle_lines
        assert began == 1
    else:
        assert job["status"] == "failed", job
        assert job["error"].startswith("replica-failover:")
        assert began == 1


def test_kill_at_steal_pre_claim_job_stays_claimable(
    tmp_path, oracle_lines
):
    """The stealer itself dies mid-steal, before the epoch claim: no
    half-taken lease may remain — a third replica claims the job and
    completes it byte-identically."""
    run_dir = str(tmp_path / "rd")
    os.makedirs(run_dir, exist_ok=True)
    # a dies the moment its worker claims the job (unbegun, stealable).
    a_proc, a_url = _spawn_replica(
        run_dir, "a", fault_plan="kill@serve.worker.claim"
    )
    # b dies at the steal's pre-claim kill-point.
    b_proc, b_url = _spawn_replica(
        run_dir, "b", fault_plan="kill@serve.steal.pre-claim"
    )
    c_proc = None
    try:
        client = ServeClient(a_url, timeout=60)
        job_id = client.submit(CHAOS_FLAGS)["job"]["id"]
        assert a_proc.wait(timeout=120) == -signal.SIGKILL
        assert b_proc.wait(timeout=120) == -signal.SIGKILL
        # Nothing half-taken: a fresh replica adopts and completes.
        c_proc, c_url = _spawn_replica(run_dir, "c")
        job = _wait_terminal(
            ServeClient(c_url, timeout=60, max_retries=5), job_id
        )
        assert job["status"] == "done", job
        assert job["result"]["pc_lines"] == oracle_lines
        began, valid_terminals, settled = _journal_facts(run_dir, job_id)
        assert settled and valid_terminals == 1 and began == 1
    finally:
        for proc in (c_proc, b_proc, a_proc):
            if proc is not None and proc.poll() is None:
                _stop(proc)

"""Multi-replica serving (PR 13): lease-fenced work-stealing over the
shared journal, surviving host loss.

The contract under test:

- **run-dir guard** — a second unreplicated daemon on one ``--run-dir``
  is refused (exit 2 via ``RunDirBusy``); replicas with distinct
  ``--replica-id`` values coexist by design, duplicates are refused;
- **leases** — ``os.link``-atomic claim files: exactly one replica wins
  each (job, epoch); stealing requires expiry PLUS the grace window;
  renewals extend expiry; a deposed or lapsed owner abandons;
- **epoch fencing** — a zombie replica's late terminal record at a
  stale epoch is ignored by ``replay_journal``; the stolen run's
  terminal wins; exactly one valid outcome per job id;
- **requeue-once across replica lives** — a stolen job journaled
  ``began`` fails with the structured ``replica-failover:`` error, never
  a silent device re-run; an unbegun job re-runs on the survivor;
- **deadlines across a steal** — the original ``deadline_seconds``
  budget rides the steal and is re-validated at re-dispatch;
- **lease-aware compaction** — only the compaction-lock holder rewrites
  the shared journal; fencing epochs survive the rewrite; a torn
  boundary record is dropped; appenders re-open across a compaction;
- **client failover** — a comma-separated endpoint list fails over on a
  refused connect, for GETs and (refused-only) POSTs.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
import time

import pytest

from spark_examples_tpu.serve.client import ServeClient
from spark_examples_tpu.serve.daemon import PcaService
from spark_examples_tpu.serve.executor import ExecutionOutcome
from spark_examples_tpu.serve.http import serve_main, start_server
from spark_examples_tpu.serve.journal import (
    JOURNAL_LOCK_SUFFIX,
    JobJournal,
    LeaseStore,
    RunDirBusy,
    acquire_run_dir_lock,
    compact_journal,
    compact_journal_shared,
    journal_path,
    replay_journal,
)
from spark_examples_tpu.serve.protocol import request_doc
from spark_examples_tpu.utils import faults

TINY_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]


@pytest.fixture(autouse=True)
def _reset_fault_plan():
    """Every test starts and ends with no active fault plan (the crash
    tests configure one; a leak would poison unrelated tests)."""
    faults.configure(None)
    yield
    faults.configure(None)


def _wait_status(service, job_id, statuses, timeout=20.0):
    """Poll one service's table until the job reaches a wanted status
    (404s while the job still belongs to another replica are re-polled)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        _http, doc = service.job_status(job_id)
        if doc.get("job", {}).get("status") in statuses:
            return doc["job"]
        time.sleep(0.02)
    raise AssertionError(
        f"job {job_id} never reached {statuses}: {service.job_status(job_id)}"
    )


class StubExecutor:
    """Records executed job ids; optionally blocks (deterministic zombie
    windows) and publishes a per-job manifest naming which replica ran
    the job — the manifest-uniqueness probe."""

    def __init__(self, name, block=False, write_manifest=True):
        self.name = name
        self.block = block
        self.write_manifest = write_manifest
        self.calls = []
        self.started = threading.Event()
        self.release = threading.Event()
        self._lock = threading.Lock()  # lock order: test-local leaf

    def __call__(self, job, run_dir):
        with self._lock:
            self.calls.append(job.id)
        self.started.set()
        if self.block:
            assert self.release.wait(timeout=30), "gate never released"
        manifest_path = None
        if self.write_manifest:
            job_dir = os.path.join(run_dir, "jobs", job.id)
            os.makedirs(job_dir, exist_ok=True)
            manifest_path = os.path.join(job_dir, "manifest.json")
            tmp = f"{manifest_path}.{self.name}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"by": self.name, "id": job.id}, f)
            os.replace(tmp, manifest_path)
        return ExecutionOutcome(
            result={"by": self.name, "id": job.id},
            manifest_path=manifest_path,
            compile_cache="cold",
        )


def _replica(run_dir, name, executor, **kw):
    """A fast-failover in-process replica. Lease timings are sub-second
    for test speed but not TOO tight: a loaded CI box can stall a
    renewal thread for a few hundred ms, and a replica that loses its
    OWN lease to scheduler noise turns a steal test flaky."""
    kw.setdefault("lease_seconds", 0.75)
    kw.setdefault("lease_grace_seconds", 0.25)
    kw.setdefault("steal_interval_seconds", 0.25)
    return PcaService(
        run_dir=str(run_dir),
        executor=executor,
        small_slices=0,
        replica_id=name,
        **kw,
    )


def _dead_replica_state(
    run_dir,
    job_id="job-a-000001",
    began=False,
    lease=True,
    lease_expires_in=0.01,
    deadline_unix=None,
):
    """The on-disk state a SIGKILLed replica ``a`` leaves behind: an
    accepted (optionally leased / begun) job in the shared journal plus
    its lease file — exactly what a survivor's steal path consumes."""
    run_dir = str(run_dir)
    # A real daemon heartbeats at startup, before its first admission —
    # so a dead owner always leaves a STALE heartbeat file behind (the
    # steal scan's liveness discriminator relies on it).
    LeaseStore(
        run_dir, "a", lease_seconds=1.0, clock=lambda: time.time() - 60.0
    ).heartbeat()
    journal = JobJournal(journal_path(run_dir), replica="a")
    journal.accepted(
        job_id, request_doc(TINY_FLAGS), "small", time.time(), deadline_unix
    )
    if lease:
        store = LeaseStore(
            run_dir, "a", lease_seconds=lease_expires_in, grace_seconds=0.0
        )
        assert store.claim(job_id) == 1
        journal.lease(job_id, 1)
    if began:
        journal.began(job_id, epoch=1 if lease else None)
    journal.close()
    return job_id


# ---------------------------------------------------------- run-dir guard


def test_run_dir_guard_solo_is_exclusive(tmp_path):
    lock = acquire_run_dir_lock(str(tmp_path))
    with pytest.raises(RunDirBusy, match="distinct --replica-id"):
        acquire_run_dir_lock(str(tmp_path))
    with pytest.raises(RunDirBusy, match="without --replica-id"):
        acquire_run_dir_lock(str(tmp_path), "a")
    lock.release()
    # Released: a replica can now claim it.
    acquire_run_dir_lock(str(tmp_path), "a").release()


def test_run_dir_guard_replicas_coexist_duplicates_refused(tmp_path):
    lock_a = acquire_run_dir_lock(str(tmp_path), "a")
    lock_b = acquire_run_dir_lock(str(tmp_path), "b")  # coexists by design
    with pytest.raises(RunDirBusy, match="already running"):
        acquire_run_dir_lock(str(tmp_path), "a")  # duplicate identity
    with pytest.raises(RunDirBusy, match="distinct --replica-id"):
        acquire_run_dir_lock(str(tmp_path))  # solo vs live replicas
    lock_a.release()
    lock_b.release()


def test_serve_main_second_solo_daemon_exits_2(tmp_path, capsys):
    """The satellite contract: a second daemon on the same --run-dir
    WITHOUT --replica-id exits 2 with a clear message (previously it
    would silently corrupt the journal)."""
    lock = acquire_run_dir_lock(str(tmp_path))
    try:
        rc = serve_main(["--run-dir", str(tmp_path), "--port", "0"])
    finally:
        lock.release()
    assert rc == 2
    err = capsys.readouterr().err
    assert "--replica-id" in err


@pytest.mark.parametrize(
    "flags",
    [
        ["--lease-seconds", "0"],
        ["--lease-grace-seconds", "-1"],
        ["--steal-interval-seconds", "0"],
    ],
)
def test_serve_main_rejects_bad_lease_flags(flags):
    with pytest.raises(SystemExit) as e:
        serve_main(["--port", "0", *flags])
    assert e.value.code == 2


def test_service_validates_replica_parameters(tmp_path):
    with pytest.raises(ValueError, match="replica_id"):
        PcaService(run_dir=str(tmp_path), replica_id="a/b")
    with pytest.raises(ValueError, match="lease_seconds"):
        PcaService(run_dir=str(tmp_path), replica_id="a", lease_seconds=0)
    with pytest.raises(ValueError, match="steal_interval_seconds"):
        PcaService(
            run_dir=str(tmp_path), replica_id="a", steal_interval_seconds=0
        )


# ------------------------------------------------------------ lease store


def _clocked(tmp_path, replica, now, lease=1.0, grace=0.5):
    return LeaseStore(
        str(tmp_path),
        replica,
        lease_seconds=lease,
        grace_seconds=grace,
        clock=lambda: now[0],
    )


def test_lease_claim_is_exclusive(tmp_path):
    now = [100.0]
    a = _clocked(tmp_path, "a", now)
    b = _clocked(tmp_path, "b", now)
    assert a.claim("j1") == 1
    assert b.claim("j1") is None
    assert b.claim("j1", steal=True) is None  # live, not stealable
    assert a.still_owner("j1")
    assert not b.still_owner("j1")


def test_lease_steal_requires_expiry_plus_grace(tmp_path):
    now = [100.0]
    a = _clocked(tmp_path, "a", now)
    b = _clocked(tmp_path, "b", now)
    assert a.claim("j1") == 1  # expires at 101.0, grace to 101.5
    now[0] = 101.2  # expired, but inside the clock-skew grace window
    assert b.claim("j1", steal=True) is None
    now[0] = 101.6  # past expiry + grace: the owner is dead
    assert b.claim("j1", steal=True) == 2
    assert b.still_owner("j1")
    # The deposed owner's next renewal detects the loss and abandons.
    assert a.renew("j1") is False
    assert not a.still_owner("j1")


def test_two_stealers_exactly_one_wins(tmp_path):
    now = [100.0]
    a = _clocked(tmp_path, "a", now)
    b = _clocked(tmp_path, "b", now)
    c = _clocked(tmp_path, "c", now)
    assert a.claim("j1") == 1
    now[0] = 102.0
    # Both stealers race epoch 2; the os.link claim admits exactly one —
    # the loser's raw claim-file attempt fails atomically.
    assert b.claim("j1", steal=True) == 2
    assert c._try_claim_file("j1", 2) is False
    # And via the protocol: b's epoch-2 lease is live, so c gets None.
    assert c.claim("j1", steal=True) is None


def test_lease_renewal_extends_expiry(tmp_path):
    now = [100.0]
    a = _clocked(tmp_path, "a", now)
    assert a.claim("j1") == 1
    now[0] = 100.9
    assert a.renew("j1") is True  # new expiry: 101.9
    now[0] = 101.5
    assert a.still_owner("j1")
    now[0] = 102.0
    assert not a.still_owner("j1")  # lapsed: the owner must abandon


def test_own_expired_lease_reclaims_at_higher_epoch(tmp_path):
    """A restart (same replica id) past its own TTL must NOT renew the
    stale epoch — a stealer may be mid-claim at epoch+1; re-claiming
    through the same link primitive lets the race decide exactly once."""
    now = [100.0]
    a = _clocked(tmp_path, "a", now)
    assert a.claim("j1") == 1
    now[0] = 105.0
    assert a.claim("j1") == 2
    # Fast restart (unexpired): adopts the existing epoch instead.
    b_now = [100.0]
    b = _clocked(tmp_path, "b", b_now)
    assert b.claim("j2") == 1
    b2 = _clocked(tmp_path, "b", b_now)
    assert b2.claim("j2") == 1


def test_release_unlinks_lease_files(tmp_path):
    now = [100.0]
    a = _clocked(tmp_path, "a", now)
    assert a.claim("j1") == 1
    assert a.current("j1") is not None
    a.release("j1")
    assert a.current("j1") is None
    assert a.owned_jobs() == {}


def test_heartbeats_and_peer_liveness(tmp_path):
    now = [100.0]
    a = _clocked(tmp_path, "a", now, lease=1.0)
    b = _clocked(tmp_path, "b", now, lease=1.0)
    a.heartbeat()
    b.heartbeat()
    peers = a.peers()
    assert [p["id"] for p in peers] == ["b"]
    assert peers[0]["alive"]
    assert a.alive_count() == 2
    now[0] = 110.0  # b is 10s stale against a 3s horizon (3x TTL)
    a.heartbeat()
    assert not a.peers()[0]["alive"]
    assert a.alive_count() == 1


# -------------------------------------------------------- fenced journal


def test_fold_ignores_stale_epoch_terminal(tmp_path):
    path = journal_path(str(tmp_path))
    a = JobJournal(path, replica="a")
    b = JobJournal(path, replica="b")
    a.accepted("job-a-000001", request_doc(TINY_FLAGS), "small", 1.0, None)
    a.lease("job-a-000001", 1)
    b.lease("job-a-000001", 2, stolen=True)
    # The zombie's late terminal at the deposed epoch: ignored.
    a.terminal("job-a-000001", "done", epoch=1)
    pending, _seq = replay_journal(path)
    assert [p.job_id for p in pending] == ["job-a-000001"]
    assert pending[0].lease_epoch == 2
    assert pending[0].lease_replica == "b"
    # The stolen run's terminal at the fencing epoch settles the job.
    b.terminal("job-a-000001", "failed", epoch=2)
    pending, _seq = replay_journal(path)
    assert pending == []
    a.close()
    b.close()


def test_fold_fencing_is_order_insensitive(tmp_path):
    """The stale terminal may land BEFORE the steal's lease record in
    the file (concurrent appenders): the verdict must not depend on
    line order."""
    path = journal_path(str(tmp_path))
    a = JobJournal(path, replica="a")
    a.accepted("job-a-000001", request_doc(TINY_FLAGS), "small", 1.0, None)
    a.lease("job-a-000001", 1)
    a.terminal("job-a-000001", "done", epoch=1)  # would settle...
    b = JobJournal(path, replica="b")
    b.lease("job-a-000001", 2, stolen=True)  # ...but the fence arrives
    pending, _seq = replay_journal(path)
    assert [p.job_id for p in pending] == ["job-a-000001"]
    a.close()
    b.close()


def test_epochless_terminal_always_settles(tmp_path):
    """Solo-mode records carry no epoch and fold exactly as before —
    even next to lease records (a solo journal later adopted by
    replicas must not resurrect settled jobs)."""
    path = journal_path(str(tmp_path))
    j = JobJournal(path)
    j.accepted("job-000001", request_doc(TINY_FLAGS), "small", 1.0, None)
    j.terminal("job-000001", "done")
    pending, seq = replay_journal(path)
    assert pending == [] and seq == 1
    j.close()


def test_max_seq_parses_replica_stamped_ids(tmp_path):
    path = journal_path(str(tmp_path))
    j = JobJournal(path, replica="a")
    j.accepted("job-a-000007", request_doc(TINY_FLAGS), "small", 1.0, None)
    _pending, seq = replay_journal(path)
    assert seq == 7
    j.close()


# ------------------------------------------------------------ compaction


def test_compact_shared_skips_when_lock_held(tmp_path):
    """Only the compaction-lock holder compacts; contenders skip — the
    satellite's concurrent-writer fix (two rewriters would lose records)."""
    path = journal_path(str(tmp_path))
    j = JobJournal(path, replica="a")
    j.accepted("job-a-000001", request_doc(TINY_FLAGS), "small", 1.0, None)
    j.close()
    before = open(path, encoding="utf-8").read()
    fd = os.open(path + JOURNAL_LOCK_SUFFIX, os.O_CREAT | os.O_RDWR)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        assert compact_journal_shared(path) is False
        assert open(path, encoding="utf-8").read() == before
    finally:
        os.close(fd)
    assert compact_journal_shared(path) is True


def test_compact_shared_preserves_fencing_and_sweeps_leases(tmp_path):
    run_dir = str(tmp_path)
    path = journal_path(run_dir)
    now = [100.0]
    store_a = _clocked(tmp_path, "a", now)
    j = JobJournal(path, replica="a")
    # Pending job leased at epoch 2 (one steal in its history).
    j.accepted("job-a-000001", request_doc(TINY_FLAGS), "small", 1.0, None)
    j.lease("job-a-000001", 1)
    j.lease("job-a-000001", 2, stolen=True)
    store_a.claim("job-a-000001")
    # Settled job whose lease files linger.
    j.accepted("job-a-000002", request_doc(TINY_FLAGS), "small", 1.0, None)
    j.lease("job-a-000002", 1)
    store_a.claim("job-a-000002")
    j.terminal("job-a-000002", "done", epoch=1)
    j.close()
    assert compact_journal_shared(path, lease_dir=store_a.lease_dir) is True
    pending, _seq = replay_journal(path)
    assert [p.job_id for p in pending] == ["job-a-000001"]
    # Fencing survives the rewrite: a zombie terminal at epoch 1 is
    # still stale after compaction.
    assert pending[0].lease_epoch == 2
    z = JobJournal(path, replica="zombie")
    z.terminal("job-a-000001", "done", epoch=1)
    z.close()
    pending, _seq = replay_journal(path)
    assert [p.job_id for p in pending] == ["job-a-000001"]
    # Settled job's lease files are swept; pending job's remain.
    assert store_a.current("job-a-000002") is None
    assert store_a.current("job-a-000001") is not None


def test_compact_shared_drops_torn_boundary_record(tmp_path):
    """Regression: a torn record at the compaction boundary (a replica
    SIGKILLed mid-append) must neither corrupt the rewrite nor change
    the pending verdict."""
    path = journal_path(str(tmp_path))
    j = JobJournal(path, replica="a")
    j.accepted("job-a-000001", request_doc(TINY_FLAGS), "small", 1.0, None)
    j.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"event": "terminal", "id": "job-a-000001", "sta')
    before, _seq = replay_journal(path)
    assert [p.job_id for p in before] == ["job-a-000001"]
    assert compact_journal_shared(path) is True
    text = open(path, encoding="utf-8").read()
    assert '"sta' not in text
    after, _seq = replay_journal(path)
    assert [p.job_id for p in after] == ["job-a-000001"]


def test_appender_reopens_across_compaction(tmp_path):
    """A concurrent writer whose journal was compacted under it must not
    keep appending into the dead inode (records would vanish)."""
    path = journal_path(str(tmp_path))
    j = JobJournal(path, replica="a")
    j.accepted("job-a-000001", request_doc(TINY_FLAGS), "small", 1.0, None)
    compact_journal(path, [])  # another process swaps the file
    j.accepted("job-a-000002", request_doc(TINY_FLAGS), "small", 1.0, None)
    j.close()
    pending, _seq = replay_journal(path)
    assert [p.job_id for p in pending] == ["job-a-000002"]


# ------------------------------------------------- replica service: steal


def test_survivor_steals_unbegun_job_and_completes(tmp_path):
    """Host loss before device work: the survivor re-runs the job (its
    one requeue consumed) and publishes the only manifest."""
    jid = _dead_replica_state(tmp_path, began=False)
    time.sleep(0.1)  # the dead replica's 0.01s lease expires
    stub = StubExecutor("b")
    b = _replica(tmp_path, "b", stub).start()
    try:
        job = _wait_status(b, jid, {"done"})
        assert job["result"] == {"by": "b", "id": jid}
        assert stub.calls == [jid]
        health = b.healthz()
        assert health["replica"]["jobs_stolen"] == 1
        manifest = os.path.join(str(tmp_path), "jobs", jid, "manifest.json")
        with open(manifest, encoding="utf-8") as f:
            assert json.load(f)["by"] == "b"
        pending, _seq = replay_journal(journal_path(str(tmp_path)))
        assert pending == []  # exactly one terminal state, settled
    finally:
        b.stop(timeout=20)


def test_survivor_fails_begun_job_structured(tmp_path):
    """Requeue-once holds ACROSS replica lives: the journaled
    device_began flag pins the stolen job to a structured failure —
    the devices are never driven twice, no manifest is published."""
    jid = _dead_replica_state(tmp_path, began=True)
    time.sleep(0.1)
    stub = StubExecutor("b")
    b = _replica(tmp_path, "b", stub).start()
    try:
        job = _wait_status(b, jid, {"failed"})
        assert job["error"].startswith("replica-failover:")
        assert "replica a died" in job["error"]
        assert stub.calls == []  # the executor never ran
        assert not os.path.exists(
            os.path.join(str(tmp_path), "jobs", jid, "manifest.json")
        )
        pending, _seq = replay_journal(journal_path(str(tmp_path)))
        assert pending == []
    finally:
        b.stop(timeout=20)


def test_running_steal_scan_reclaims_after_owner_death(tmp_path):
    """The survivor is ALREADY serving when the peer dies: its periodic
    steal scan (not startup replay) reclaims the job."""
    stub = StubExecutor("b")
    b = _replica(tmp_path, "b", stub).start()
    try:
        # The peer accepts a job and dies: its lease outlives b's replay
        # (0.25s) so only the running scan can have stolen it.
        jid = _dead_replica_state(tmp_path, lease_expires_in=0.25)
        job = _wait_status(b, jid, {"done"})
        assert job["result"]["by"] == "b"
        assert b.healthz()["replica"]["jobs_stolen"] == 1
    finally:
        b.stop(timeout=20)


def test_orphan_accepted_without_lease_is_reclaimed(tmp_path):
    """A replica can die in the one-record window between journaling
    ``accepted`` and claiming the lease: the job has no lease file, so
    the steal scan attributes it via the accepted record's replica stamp
    and the (absent) heartbeat, and reclaims it."""
    stub = StubExecutor("b")
    b = _replica(tmp_path, "b", stub).start()
    try:
        jid = _dead_replica_state(tmp_path, lease=False)
        job = _wait_status(b, jid, {"done"})
        assert job["result"]["by"] == "b"
    finally:
        b.stop(timeout=20)


def test_replica_restart_adopts_own_jobs(tmp_path):
    """Same replica id, fast restart (lease unexpired): the jobs adopt
    at their existing epoch and complete — no steal, no epoch bump."""
    jid = _dead_replica_state(tmp_path, lease_expires_in=30.0)
    stub = StubExecutor("a2")
    a2 = _replica(tmp_path, "a", stub).start()
    try:
        job = _wait_status(a2, jid, {"done"})
        assert job["result"]["by"] == "a2"
        assert a2.healthz()["replica"]["jobs_stolen"] == 0
    finally:
        a2.stop(timeout=20)


# ------------------------------------------------ deadlines across steals


def test_deadline_budget_survives_steal_within_window(tmp_path):
    jid = _dead_replica_state(tmp_path, deadline_unix=time.time() + 30.0)
    time.sleep(0.1)
    stub = StubExecutor("b")
    b = _replica(tmp_path, "b", stub).start()
    try:
        job = _wait_status(b, jid, {"done"})
        assert job["result"]["by"] == "b"
    finally:
        b.stop(timeout=20)


def test_deadline_expired_across_steal_fails_structured(tmp_path):
    """A job whose original deadline passed while its owner was dead
    must fail with the EXISTING structured code at re-dispatch — never
    run late."""
    jid = _dead_replica_state(tmp_path, deadline_unix=time.time() + 0.05)
    time.sleep(0.15)  # deadline AND lease both expire
    stub = StubExecutor("b")
    b = _replica(tmp_path, "b", stub).start()
    try:
        job = _wait_status(b, jid, {"failed"})
        assert job["error"].startswith("deadline-exceeded")
        assert stub.calls == []
    finally:
        b.stop(timeout=20)


# --------------------------------------------------- zombie epoch fencing


def test_zombie_abandons_unpublished_and_stolen_outcome_wins(tmp_path):
    """The full fencing story in-process: replica a's maintenance stalls
    mid-job (GC-pause stand-in), b steals the begun job and settles it
    structurally; a's run finishes AFTER being deposed and must abandon
    — no terminal record, no result, no manifest from the zombie — and
    even a forced stale-epoch terminal write is ignored by the fold."""
    gate = StubExecutor("a", block=True, write_manifest=False)
    a = _replica(tmp_path, "a", gate).start()
    b = None
    try:
        status, doc = a.submit(request_doc(TINY_FLAGS))
        assert status == 202, doc
        jid = doc["job"]["id"]
        assert gate.started.wait(timeout=10)
        a._lease_stop.set()  # freeze renewals + heartbeat: the zombie
        stub = StubExecutor("b")
        b = _replica(tmp_path, "b", stub).start()
        stolen = _wait_status(b, jid, {"failed"})
        assert stolen["error"].startswith("replica-failover:")
        assert stub.calls == []  # began: never re-run
        # The zombie wakes and finishes its run: pre-publish fence fires.
        gate.release.set()
        abandoned = _wait_status(a, jid, {"failed"})
        assert abandoned["error"].startswith("lease-lost:")
        assert abandoned.get("result") is None
        assert abandoned.get("manifest_path") is None
        # Exactly one valid terminal: b's, at the fencing epoch. Even a
        # forced zombie terminal at the stale epoch cannot resurrect or
        # double-complete the job.
        path = journal_path(str(tmp_path))
        pending, _seq = replay_journal(path)
        assert pending == []
        z = JobJournal(path, replica="a")
        z.terminal(jid, "done", epoch=1)
        z.close()
        pending, _seq = replay_journal(path)
        assert pending == []
        terminals = [
            json.loads(line)
            for line in open(path, encoding="utf-8")
            if '"terminal"' in line
        ]
        valid = [t for t in terminals if t.get("epoch", 0) >= 2]
        assert len(valid) == 1 and valid[0]["replica"] == "b"
        assert not os.path.exists(
            os.path.join(str(tmp_path), "jobs", jid, "manifest.json")
        )
    finally:
        gate.release.set()
        if b is not None:
            b.stop(timeout=20)
        a.stop(timeout=20)


def test_claim_respects_min_epoch(tmp_path):
    """A claim made from a fold that saw epoch N must never re-issue an
    epoch at or below N — even when the previous holder's lease files
    are already gone (settled + released)."""
    now = [100.0]
    a = _clocked(tmp_path, "a", now)
    assert a.claim("j1", steal=True, min_epoch=5) == 6


def test_revalidate_claim_abandons_settled_job(tmp_path):
    """The stale-fold race: a stealer decides from a snapshot, but the
    job settles (terminal + lease release) before its claim lands. The
    post-claim re-fold must abandon the claim — no lease record, no
    adoption, no second device run."""
    stub = StubExecutor("c")
    # Start on an EMPTY run dir with the steal scan effectively off, so
    # only this test's manual claims drive the lease state.
    c = _replica(
        tmp_path,
        "c",
        stub,
        lease_seconds=30.0,
        steal_interval_seconds=3600.0,
    ).start()
    run_dir = str(tmp_path)
    jid = "job-a-000001"
    j = JobJournal(journal_path(run_dir), replica="a")
    j.accepted(jid, request_doc(TINY_FLAGS), "small", time.time(), None)
    j.lease(jid, 1)
    j.close()
    try:
        epoch = c._lease_store.claim(jid, steal=True, min_epoch=1)
        assert epoch == 2
        # The previous holder's terminal lands before our re-validation
        # (in the real race it landed before our claim even succeeded).
        z = JobJournal(journal_path(run_dir), replica="a")
        z.terminal(jid, "done", epoch=1)
        z.close()
        assert c._revalidate_claim(jid, epoch) is None
        assert c._lease_store.current(jid) is None  # claim abandoned
        # And the positive side: a still-pending job survives re-fold.
        jid2 = "job-a-000002"
        j2 = JobJournal(journal_path(run_dir), replica="a")
        j2.accepted(jid2, request_doc(TINY_FLAGS), "small", time.time(), None)
        j2.close()
        epoch2 = c._lease_store.claim(jid2)
        fresh = c._revalidate_claim(jid2, epoch2)
        assert fresh is not None and fresh.job_id == jid2
    finally:
        c.stop(timeout=20)


def test_clean_stop_withdraws_heartbeat_not_degraded(tmp_path):
    """An intentionally drained replica must leave the pool as a
    departed member, not a corpse: the survivor's healthz stays 'ok'
    instead of reporting 'degraded' forever."""
    a = _replica(tmp_path, "a", StubExecutor("a"), lease_seconds=0.3).start()
    b = _replica(tmp_path, "b", StubExecutor("b"), lease_seconds=0.3).start()
    assert {p["id"] for p in a._lease_store.peers()} == {"b"}
    assert b.stop(timeout=20)
    time.sleep(1.0)  # past 3x the 0.3s TTL: a corpse would read stale
    health = a.healthz()
    try:
        assert health["status"] == "ok"
        assert health["replica"]["degraded"] is False
        assert health["replica"]["peers"] == []
    finally:
        a.stop(timeout=20)


def test_client_wait_spans_the_failover_404_window(tmp_path):
    """`submit --wait` against an endpoint list must survive the window
    where the dead owner's job is not yet in the survivor's table: with
    more than one endpoint, 404 is non-terminal (bounded by the wait
    deadline), so the wait resolves once the steal lands."""
    jid = _dead_replica_state(tmp_path, lease_expires_in=0.4)
    stub = StubExecutor("b")
    b = _replica(tmp_path, "b", stub).start()
    server = start_server(b)
    try:
        client = ServeClient(
            f"http://127.0.0.1:1,{server.url}", max_retries=2
        )
        doc = client.wait(jid, timeout=20)
        assert doc["job"]["status"] == "done"
        assert doc["job"]["result"]["by"] == "b"
    finally:
        server.shutdown()
        b.stop(timeout=20)


# ------------------------------------------------- kill-point integration


def test_new_kill_points_registered():
    assert "serve.lease.pre-renew" in faults.KILL_POINTS
    assert "serve.steal.pre-claim" in faults.KILL_POINTS


def test_crash_at_lease_pre_renew_triggers_failover(tmp_path):
    """crash@serve.lease.pre-renew kills the owning replica's lease
    maintenance thread (the in-process host-loss stand-in): its lease
    lapses and the peer steals the begun job into a structured failure."""
    faults.configure("crash@serve.lease.pre-renew")
    gate = StubExecutor("a", block=True, write_manifest=False)
    a = _replica(tmp_path, "a", gate).start()
    b = None
    try:
        status, doc = a.submit(request_doc(TINY_FLAGS))
        assert status == 202, doc
        jid = doc["job"]["id"]
        assert gate.started.wait(timeout=10)
        # a's next maintenance tick (it owns a lease now) crashes.
        stub = StubExecutor("b")
        b = _replica(tmp_path, "b", stub).start()
        stolen = _wait_status(b, jid, {"failed"})
        assert stolen["error"].startswith("replica-failover:")
    finally:
        gate.release.set()
        if b is not None:
            b.stop(timeout=20)
        a.stop(timeout=20)


def test_crash_at_steal_pre_claim_leaves_job_claimable(tmp_path):
    """A stealer dying at the pre-claim kill-point must leave no
    half-taken lease: the job stays claimable and a later replica
    completes it."""
    stub_b = StubExecutor("b")
    b = _replica(tmp_path, "b", stub_b).start()
    c = None
    try:
        faults.configure("crash@serve.steal.pre-claim")
        jid = _dead_replica_state(tmp_path)
        # b's steal scan hits the kill-point and its maintenance thread
        # dies mid-steal — before the epoch claim, so nothing is taken.
        time.sleep(1.0)
        assert stub_b.calls == []
        stub_c = StubExecutor("c")
        c = _replica(tmp_path, "c", stub_c).start()
        job = _wait_status(c, jid, {"done"})
        assert job["result"]["by"] == "c"
        # b is degraded (dead maintenance thread) but still serves.
        assert b.healthz()["queue"]["worker_alive"]
    finally:
        if c is not None:
            c.stop(timeout=20)
        b.stop(timeout=20)


# ------------------------------------------------------- client failover


def test_client_endpoint_list_parsing():
    client = ServeClient("http://a:1, http://b:2/")
    assert client.urls == ["http://a:1", "http://b:2"]
    assert client.url == "http://a:1"
    with pytest.raises(ValueError, match="no endpoint"):
        ServeClient(" , ")


def test_client_fails_over_on_connection_refused(tmp_path):
    """A dead first endpoint (refused connect) fails over for both GETs
    and the single-shot POST — a refused connect provably never reached
    a server, so the submit cannot duplicate."""
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        executor=StubExecutor("solo"),
        small_slices=0,
    ).start()
    server = start_server(service)
    try:
        client = ServeClient(
            f"http://127.0.0.1:1,{server.url}", max_retries=2
        )
        doc = client.submit(TINY_FLAGS)
        assert client.url == server.url  # rotated off the dead endpoint
        done = client.wait(doc["job"]["id"], timeout=20)
        assert done["job"]["status"] == "done"
        assert client.healthz()["status"] in ("ok", "degraded")
    finally:
        server.shutdown()
        service.stop(timeout=20)


# ------------------------------------------------------------ telemetry


def test_replica_healthz_and_metrics(tmp_path):
    stub = StubExecutor("a")
    a = _replica(tmp_path, "a", stub).start()
    try:
        status, doc = a.submit(request_doc(TINY_FLAGS))
        assert status == 202, doc
        _wait_status(a, doc["job"]["id"], {"done"})
        health = a.healthz()
        block = health["replica"]
        assert block["id"] == "a"
        assert block["alive"] == 1
        assert block["degraded"] is False
        assert block["peers"] == []
        text = a.metrics_text()
        assert "serve_replicas_alive 1" in text
        assert "serve_jobs_stolen_total 0" in text
        assert "serve_lease_renewals_total" in text
    finally:
        a.stop(timeout=20)


def test_solo_healthz_has_no_replica_block(tmp_path):
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        executor=StubExecutor("solo"),
        small_slices=0,
    ).start()
    try:
        assert service.healthz()["replica"] is None
    finally:
        service.stop(timeout=20)

"""The population-genetics analyses (``analyses/``: GRM, LD prune, assoc
scan) against NumPy oracles, plus their plan/serve/manifest integration.

The oracle discipline mirrors the Gramian tests: every device path must
match a host recomputation EXACTLY (the analyses' statistics are integer
moments closed in float64, so parity is equality, not tolerance)."""

import json
import os
import time

import numpy as np
import pytest

from spark_examples_tpu.analyses.assoc import (
    AssocResult,
    case_vector,
    chi2_from_counts,
    load_phenotypes,
    run_assoc_pipeline,
)
from spark_examples_tpu.analyses.base import (
    ANALYSIS_KINDS,
    analysis_conf_violations,
    check_analysis_conf,
)
from spark_examples_tpu.analyses.grm import (
    GrmMoments,
    format_grm_rows,
    grm_finalize,
    grm_reference,
    run_grm_pipeline,
)
from spark_examples_tpu.analyses.ld import ld_prune_reference, run_ld_pipeline
from spark_examples_tpu.config import AssocConf, GrmConf, LdConf
from spark_examples_tpu.ops.ld import (
    build_case_counts,
    build_ld_window_stats,
    case_counts_reference,
    greedy_prune,
    ld_window_stats_reference,
    r2_from_counts,
)
from spark_examples_tpu.pipeline.sitewriter import SiteOutputWriter
from spark_examples_tpu.utils.af import (
    carrier_counts,
    variance_counts,
)

REFS = "1:0:30000"


def _rand_rows(rng, m, n):
    """A random has-variation block with no all-zero rows (the sources
    drop them before the analyses ever see one)."""
    rows = (rng.random((m, n)) < 0.4).astype(np.uint8)
    rows[rows.sum(axis=1) == 0, 0] = 1
    return rows


def _grm_conf(*extra):
    return GrmConf.parse(
        ["--num-samples", "8", "--references", REFS, *extra]
    )


def _stream_rows(conf):
    """Every has-variation block of the conf's synthetic stream, in
    contig order — the analyses' exact input, recomputed independently."""
    from spark_examples_tpu.pipeline.pca_driver import make_source

    src = make_source(conf)
    return [
        block["has_variation"]
        for contig in conf.get_contigs(src, conf.variant_set_id)
        for block in src.genotype_blocks(
            conf.variant_set_id[0],
            contig,
            block_size=conf.block_size,
            min_allele_frequency=conf.min_allele_frequency,
        )
    ]


def _cohort_names(conf):
    from spark_examples_tpu.pipeline.pca_driver import make_source

    return [
        cs["name"]
        for cs in make_source(conf).search_callsets(conf.variant_set_id)
    ]


# --------------------------------------------------------------- utils/af


class TestAfHelpers:
    def test_carrier_counts_ragged_tail(self):
        rng = np.random.default_rng(0)
        for m in (1, 3, 17):  # ragged block sizes need no special casing
            rows = _rand_rows(rng, m, 6)
            k = carrier_counts(rows)
            assert k.dtype == np.int64
            assert k.tolist() == rows.sum(axis=1).tolist()

    def test_carrier_counts_rejects_non_block(self):
        with pytest.raises(ValueError, match=r"\(B, N\) block"):
            carrier_counts(np.zeros(4, dtype=np.uint8))

    def test_variance_counts_out_of_contract_rejects(self):
        # Count-valued join rows leaking into a {0,1} path fail loudly:
        # the implied frequency k/n would leave the AF [0, 1] contract.
        with pytest.raises(ValueError, match="outside"):
            variance_counts(np.array([7]), 6)
        with pytest.raises(ValueError, match="outside"):
            variance_counts(np.array([-1]), 6)
        with pytest.raises(ValueError, match="num_samples"):
            variance_counts(np.array([1]), 0)

    def test_monomorphic_zero_variance_guard(self):
        # k == 0 and k == n are exactly zero variance — the denominator
        # every consumer divides by is exactly 0 (guarded), never NaN:
        # GRM raises on C == 0, LD's r² treats zero-variance pairs as 0.
        counts = np.array([0, 4, 8])
        var = variance_counts(counts, 8)
        assert var.tolist() == [0, 16, 0]
        r2 = r2_from_counts(
            np.zeros((3, 3), dtype=np.int64), counts, 8
        )
        assert np.isfinite(r2).all()

    def test_variance_counts_is_exact_int(self):
        assert variance_counts(np.array([3]), 7).dtype == np.int64


# ---------------------------------------------------------- sitewriter


class TestSiteOutputWriter:
    def test_atomic_publish(self, tmp_path):
        path = str(tmp_path / "out.tsv")
        writer = SiteOutputWriter(path, header=("a", "b"))
        writer.write_rows([(1, 2), (3, 4)])
        assert not os.path.exists(path)  # nothing visible until close
        writer.close()
        assert open(path).read() == "a\tb\n1\t2\n3\t4\n"
        assert writer.rows_written == 2
        writer.close()  # idempotent

    def test_abort_leaves_nothing(self, tmp_path):
        path = str(tmp_path / "out.tsv")
        writer = SiteOutputWriter(path, header=("a",))
        writer.write_rows([(1,)])
        writer.abort()
        assert not os.path.exists(path)
        assert not list(tmp_path.iterdir())

    def test_context_manager_error_aborts(self, tmp_path):
        path = str(tmp_path / "out.tsv")
        with pytest.raises(RuntimeError):
            with SiteOutputWriter(path, header=("a",)) as writer:
                writer.write_rows([(1,)])
                raise RuntimeError("boom")
        assert not os.path.exists(path)

    def test_closed_writer_rejects_rows(self, tmp_path):
        writer = SiteOutputWriter(str(tmp_path / "x.tsv"), header=("a",))
        writer.close()
        with pytest.raises(ValueError, match="closed"):
            writer.write_rows([(1,)])

    def test_headerless_part_file_bytes(self, tmp_path):
        """``header=None`` writes no header line at all — the reference's
        saveAsTextFile part files (reads examples) are headerless and must
        stay byte-identical when routed through the streaming writer."""
        path = str(tmp_path / "part-00000")
        with SiteOutputWriter(path) as writer:
            writer.write_rows([("(1000,3)",), ("(1001,2)",)])
        assert open(path).read() == "(1000,3)\n(1001,2)\n"


# ------------------------------------------------------- shared admission


class TestAnalysisConf:
    @pytest.mark.parametrize(
        "extra, code",
        [
            (
                ["--num-samples", "8,8", "--variant-set-id", "a,b"],
                "analysis-variant-sets",
            ),
            (["--save-variants", "x"], "analysis-save-variants"),
            (["--input-path", "x"], "analysis-input-path"),
            (
                ["--gramian-checkpoint-dir", "x"],
                "analysis-checkpoint",
            ),
            (["--ingest", "wire"], "analysis-ingest"),
            (["--stream-chunk-bytes", "1024"], "analysis-streaming"),
        ],
    )
    def test_violations_catalogue(self, extra, code):
        conf = _grm_conf(*extra)
        codes = [c for c, _ in analysis_conf_violations(conf, "grm")]
        assert code in codes
        with pytest.raises(ValueError):
            check_analysis_conf(conf, "grm")

    def test_clean_conf_passes_every_kind(self):
        conf = _grm_conf()
        for kind in ANALYSIS_KINDS:
            assert analysis_conf_violations(conf, kind) == []
        with pytest.raises(ValueError, match="unknown analysis kind"):
            check_analysis_conf(conf, "nope")


# ------------------------------------------------------------------- GRM


class TestGrm:
    def test_moments_blocked_equals_single_pass(self):
        rng = np.random.default_rng(1)
        X = _rand_rows(rng, 50, 8)
        blocked = GrmMoments(8)
        for start in range(0, 50, 17):  # ragged tail: 17 + 17 + 16
            blocked.add_block(X[start : start + 17])
        whole = GrmMoments(8)
        whole.add_block(X)
        assert np.array_equal(blocked.U, whole.U)
        assert (blocked.S2, blocked.C, blocked.sites) == (
            whole.S2,
            whole.C,
            whole.sites,
        )
        assert np.array_equal(
            grm_finalize(X.T.astype(np.int64) @ X, blocked),
            grm_reference(X, 8),
        )

    def test_finalize_matches_direct_vanraden(self):
        # The expanded integer formula == the textbook centered form.
        rng = np.random.default_rng(2)
        X = _rand_rows(rng, 40, 6).astype(np.float64)
        p = X.mean(axis=1, keepdims=True)
        direct = (X - p).T @ (X - p) / (p.squeeze() * (1 - p.squeeze())).sum()
        oracle = grm_reference(X.astype(np.int64), 6)
        np.testing.assert_allclose(oracle, direct, rtol=1e-12)

    def test_finalize_all_monomorphic_raises(self):
        moments = GrmMoments(4)
        moments.add_block(np.ones((3, 4), dtype=np.uint8))
        with pytest.raises(ValueError, match="monomorphic"):
            grm_finalize(np.full((4, 4), 3, dtype=np.int64), moments)

    def test_pipeline_matches_oracle_exactly(self, tmp_path):
        out = str(tmp_path / "kin.tsv")
        manifest = str(tmp_path / "m.json")
        conf = _grm_conf(
            "--grm-out", out, "--metrics-json", manifest
        )
        result = run_grm_pipeline(conf)
        X = np.concatenate(_stream_rows(conf))
        oracle = grm_reference(X, 8)
        assert np.array_equal(result.matrix, oracle)  # byte-identical
        names = _cohort_names(conf)
        assert result.sample_names == names
        expected = ["\t".join(["name", *names])] + [
            "\t".join(str(f) for f in row)
            for row in format_grm_rows(names, oracle)
        ]
        assert open(out).read().splitlines() == expected
        assert result.manifest_path == manifest
        assert result.manifest["analysis"] == {
            "kind": "grm",
            "sites_kept": None,
            "sites_tested": len(X),
        }
        from spark_examples_tpu.obs.manifest import validate_manifest

        assert validate_manifest(result.manifest) == []

    def test_host_backend_parity(self):
        tpu = run_grm_pipeline(_grm_conf())
        host = run_grm_pipeline(_grm_conf("--pca-backend", "host"))
        assert np.array_equal(tpu.matrix, host.matrix)

    @pytest.mark.parametrize("pack", ["on", "off"])
    def test_sharded_ring_parity(self, pack):
        # 16 columns over a 4-wide samples axis: the packed ring pads the
        # cohort to 32 (pack-width invariant); the GRM trims back to 16
        # and must equal the dense oracle EXACTLY in both wire formats.
        conf = GrmConf.parse(
            [
                "--num-samples", "16",
                "--references", REFS,
                "--mesh-shape", "1,4",
                "--similarity-strategy", "sharded",
                "--block-size", "32",
                "--ring-pack-bits", pack,
            ]
        )
        result = run_grm_pipeline(conf)
        X = np.concatenate(_stream_rows(conf))
        assert np.array_equal(result.matrix, grm_reference(X, 16))


# -------------------------------------------------------------------- LD


class TestLdKernels:
    def test_window_stats_matches_reference(self):
        rng = np.random.default_rng(3)
        rows = _rand_rows(rng, 24, 8)
        C_ref, k_ref = ld_window_stats_reference(rows)
        C, k = build_ld_window_stats(None)(rows)
        assert np.array_equal(np.asarray(C), C_ref)
        assert np.array_equal(np.asarray(k), k_ref)

    def test_window_stats_sharded_matches_reference(self):
        import jax

        from spark_examples_tpu.parallel.mesh import make_mesh

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices for a samples axis")
        mesh = make_mesh({"data": 1, "samples": 4})
        rng = np.random.default_rng(4)
        rows = _rand_rows(rng, 16, 8)
        C_ref, k_ref = ld_window_stats_reference(rows)
        C, k = build_ld_window_stats(mesh)(rows)
        assert np.array_equal(np.asarray(C), C_ref)
        assert np.array_equal(np.asarray(k), k_ref)

    def test_r2_self_correlation_and_guard(self):
        rows = np.array(
            [
                [1, 0, 1, 0],  # polymorphic
                [1, 0, 1, 0],  # identical -> r2 1 with row 0
                [0, 1, 0, 1],  # complement -> also r2 1
                [1, 1, 1, 1],  # monomorphic (k == n) -> guard: r2 0
            ],
            dtype=np.uint8,
        )
        C, k = ld_window_stats_reference(rows)
        r2 = r2_from_counts(C, k, 4)
        assert np.isfinite(r2).all()
        assert r2[0, 0] == 1.0 and r2[0, 1] == 1.0 and r2[0, 2] == 1.0
        assert (r2[3] == 0).all() and (r2[:, 3] == 0).all()

    def test_greedy_prune_order_threshold_and_mask(self):
        rows = np.array(
            [
                [1, 0, 1, 0],
                [1, 0, 1, 0],  # duplicate of 0 -> pruned at any threshold
                [1, 1, 0, 0],  # r2 vs row 0 is (4*1-2*2)^2/... = 0 -> kept
            ],
            dtype=np.uint8,
        )
        C, k = ld_window_stats_reference(rows)
        kept = greedy_prune(C, k, 4, 0.2)
        assert kept.tolist() == [True, False, True]
        # Prune is STRICTLY above: r2 == threshold survives.
        assert greedy_prune(C, k, 4, 1.0).tolist() == [True, True, True]
        # Padding rows are never kept and never pruned against.
        valid = np.array([True, False, True])
        kept = greedy_prune(C, k, 4, 0.2, valid=valid)
        assert kept.tolist() == [True, False, True]


class TestLdPipeline:
    def _conf(self, tmp_path, *extra):
        return LdConf.parse(
            [
                "--num-samples", "8",
                "--references", "1:0:20000,2:0:20000",
                "--ld-window-sites", "32",
                "--ld-out", str(tmp_path / "kept.tsv"),
                "--metrics-json", str(tmp_path / "m.json"),
                *extra,
            ]
        )

    def test_matches_windowed_oracle(self, tmp_path):
        conf = self._conf(tmp_path)
        result = run_ld_pipeline(conf)
        from spark_examples_tpu.pipeline.pca_driver import make_source

        src = make_source(conf)
        expected = ["contig\tpos\tkept"]
        kept_total = 0
        for contig in conf.get_contigs(src, conf.variant_set_id):
            blocks = [
                (block["positions"], block["has_variation"])
                for block in src.genotype_blocks(
                    conf.variant_set_id[0],
                    contig,
                    block_size=conf.block_size,
                    min_allele_frequency=conf.min_allele_frequency,
                )
            ]
            positions = np.concatenate([p for p, _ in blocks])
            hv = np.concatenate([h for _, h in blocks])
            W = conf.ld_window_sites
            windows = [
                (positions[i : i + W], hv[i : i + W])
                for i in range(0, len(positions), W)
            ]
            for pos, kept in ld_prune_reference(
                windows, conf.num_samples, conf.ld_r2_threshold
            ):
                expected.append(
                    f"{contig.reference_name}\t{pos}\t{int(kept)}"
                )
                kept_total += int(kept)
        assert open(conf.ld_out).read().splitlines() == expected
        assert result.sites_kept == kept_total
        assert result.sites_tested == len(expected) - 1
        assert result.manifest["analysis"] == {
            "kind": "ld",
            "sites_kept": kept_total,
            "sites_tested": result.sites_tested,
        }

    def test_threshold_extremes(self, tmp_path):
        # Threshold 1.0 keeps everything but exact duplicates (r2 must be
        # STRICTLY greater); threshold 0.0 prunes any correlated pair.
        wide = run_ld_pipeline(self._conf(tmp_path, "--ld-r2-threshold", "1"))
        tight_dir = tmp_path / "tight"
        tight_dir.mkdir()
        tight = run_ld_pipeline(
            self._conf(tight_dir, "--ld-r2-threshold", "0")
        )
        assert tight.sites_kept < wide.sites_kept
        assert wide.sites_tested == tight.sites_tested

    def test_live_progress_gauges(self, tmp_path):
        from spark_examples_tpu.obs.manifest import manifest_metric_value
        from spark_examples_tpu.obs.metrics import (
            ANALYSIS_SITES_KEPT,
            ANALYSIS_SITES_TESTED,
        )

        conf = self._conf(tmp_path)
        result = run_ld_pipeline(conf)
        assert (
            manifest_metric_value(result.manifest, ANALYSIS_SITES_TESTED)
            == result.sites_tested
        )
        assert (
            manifest_metric_value(result.manifest, ANALYSIS_SITES_KEPT)
            == result.sites_kept
        )

    def test_parse_rejects_bad_flags(self):
        with pytest.raises(ValueError, match="ld-r2-threshold"):
            LdConf.parse(
                ["--num-samples", "8", "--references", REFS,
                 "--ld-r2-threshold", "1.5"]
            )
        with pytest.raises(ValueError, match="ld-window-sites"):
            LdConf.parse(
                ["--num-samples", "8", "--references", REFS,
                 "--ld-window-sites", "1"]
            )

    def test_indivisible_samples_axis_rejected(self, tmp_path):
        import jax

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices for a samples axis")
        conf = LdConf.parse(
            [
                "--num-samples", "9",
                "--references", REFS,
                "--mesh-shape", "1,4",
            ]
        )
        with pytest.raises(ValueError, match="does not divide"):
            run_ld_pipeline(conf)


# ------------------------------------------------------------------ assoc


class TestPhenotypes:
    def _write(self, tmp_path, text):
        path = tmp_path / "p.tsv"
        path.write_text(text)
        return str(path)

    def test_parse_good_file(self, tmp_path):
        path = self._write(
            tmp_path, "# comment\nA\t1\n\nB\t0\nC\t1\n"
        )
        assert load_phenotypes(path) == {"A": 1, "B": 0, "C": 1}

    @pytest.mark.parametrize(
        "text, match",
        [
            ("A\t2\n", "status"),
            ("A\t1\nA\t0\n", "duplicate"),
            ("A 1\n", "name<TAB>status"),
            ("", "no phenotype rows"),
            ("A\t1\nB\t1\n", "control"),
            ("A\t0\nB\t0\n", "case"),
        ],
    )
    def test_parse_rejects(self, tmp_path, text, match):
        with pytest.raises(ValueError, match=match):
            load_phenotypes(self._write(tmp_path, text))

    def test_case_vector_strict_both_ways(self):
        statuses = {"A": 1, "B": 0}
        assert case_vector(statuses, ["B", "A"]).tolist() == [0, 1]
        with pytest.raises(ValueError, match="missing"):
            case_vector(statuses, ["A", "B", "C"])
        with pytest.raises(ValueError, match="not in the"):
            case_vector({"A": 1, "B": 0, "Z": 1}, ["A", "B"])


class TestChi2:
    def test_matches_textbook_2x2(self):
        rng = np.random.default_rng(5)
        n_cases, n_controls = 6, 10
        n = n_cases + n_controls
        t = rng.integers(1, n, size=50)
        a = np.minimum(rng.integers(0, n_cases + 1, size=50), t)
        # guard c <= n_controls
        a = np.maximum(a, t - n_controls)
        got = chi2_from_counts(a, t, n_cases, n_controls)
        for i in range(50):
            table = np.array(
                [
                    [a[i], n_cases - a[i]],
                    [t[i] - a[i], n_controls - (t[i] - a[i])],
                ],
                dtype=np.float64,
            )
            total = table.sum()
            expected_counts = (
                table.sum(axis=1, keepdims=True)
                * table.sum(axis=0, keepdims=True)
                / total
            )
            if (expected_counts == 0).any():
                assert got[i] == 0.0
                continue
            chi2 = ((table - expected_counts) ** 2 / expected_counts).sum()
            np.testing.assert_allclose(got[i], chi2, rtol=1e-12)

    def test_zero_variance_guard(self):
        # t == 0 and t == n carry no genotype variance -> statistic 0.
        out = chi2_from_counts(
            np.array([0, 3]), np.array([0, 8]), 3, 5
        )
        assert out.tolist() == [0.0, 0.0]

    def test_case_counts_kernel_matches_reference(self):
        rng = np.random.default_rng(6)
        rows = _rand_rows(rng, 20, 8)
        case = (rng.random(8) < 0.5).astype(np.uint8)
        a_ref, t_ref = case_counts_reference(rows, case)
        a, t = build_case_counts()(rows, case)
        assert np.array_equal(np.asarray(a), a_ref)
        assert np.array_equal(np.asarray(t), t_ref)


class TestAssocPipeline:
    def _planted(self, tmp_path):
        """Phenotypes = one polymorphic site's carrier vector: that site's
        chi-square is the theoretical max (n) and must rank first."""
        conf = AssocConf.parse(
            ["--num-samples", "8", "--references", REFS,
             "--phenotypes", "pending"]
        )
        from spark_examples_tpu.pipeline.pca_driver import make_source

        src = make_source(conf)
        names = _cohort_names(conf)
        for contig in conf.get_contigs(src, conf.variant_set_id):
            for block in src.genotype_blocks(
                conf.variant_set_id[0],
                contig,
                block_size=conf.block_size,
                min_allele_frequency=conf.min_allele_frequency,
            ):
                carriers = block["has_variation"].sum(axis=1)
                hits = np.nonzero((carriers >= 2) & (carriers <= 6))[0]
                if len(hits):
                    i = int(hits[0])
                    path = tmp_path / "pheno.tsv"
                    path.write_text(
                        "".join(
                            f"{name}\t{int(s)}\n"
                            for name, s in zip(
                                names, block["has_variation"][i]
                            )
                        )
                    )
                    return str(path), (
                        contig.reference_name,
                        int(block["positions"][i]),
                    )
        raise AssertionError("no polymorphic site in the fixture stream")

    def _conf(self, tmp_path, phenotypes, *extra):
        return AssocConf.parse(
            [
                "--num-samples", "8",
                "--references", REFS,
                "--phenotypes", phenotypes,
                "--assoc-out", str(tmp_path / "scan.tsv"),
                "--metrics-json", str(tmp_path / "m.json"),
                *extra,
            ]
        )

    def test_planted_signal_top_ranked(self, tmp_path):
        phenotypes, signal = self._planted(tmp_path)
        result = run_assoc_pipeline(self._conf(tmp_path, phenotypes))
        assert isinstance(result, AssocResult)
        chi2, contig, pos, a, t = result.top[0]
        assert (contig, pos) == signal
        assert chi2 == float(result.n_cases + result.n_controls)
        # Spilled rows: one per tested site, chi2 column matches the top.
        lines = open(str(tmp_path / "scan.tsv")).read().splitlines()
        assert len(lines) == result.sites_tested + 1
        by_site = {
            (l.split("\t")[0], int(l.split("\t")[1])): float(
                l.split("\t")[4]
            )
            for l in lines[1:]
        }
        assert by_site[signal] == chi2
        assert max(by_site.values()) == chi2
        assert result.manifest["analysis"]["kind"] == "assoc"
        assert (
            result.manifest["analysis"]["sites_tested"]
            == result.sites_tested
        )

    def test_device_matches_host_oracle_exactly(self, tmp_path):
        phenotypes, _ = self._planted(tmp_path)
        device = run_assoc_pipeline(self._conf(tmp_path, phenotypes))
        host_dir = tmp_path / "host"
        host_dir.mkdir()
        host = run_assoc_pipeline(
            self._conf(host_dir, phenotypes, "--pca-backend", "host")
        )
        assert device.top == host.top  # float64-exact parity
        assert (
            open(str(tmp_path / "scan.tsv")).read()
            == open(str(host_dir / "scan.tsv")).read()
        )

    def test_requires_phenotypes(self):
        conf = AssocConf.parse(
            ["--num-samples", "8", "--references", REFS]
        )
        with pytest.raises(ValueError, match="phenotypes"):
            run_assoc_pipeline(conf)

    def test_bad_assoc_top_rejected_at_parse(self):
        with pytest.raises(ValueError, match="assoc-top"):
            AssocConf.parse(
                ["--num-samples", "8", "--references", REFS,
                 "--phenotypes", "x", "--assoc-top", "0"]
            )


# ----------------------------------------------------------- plan entries


class TestPlanEntries:
    def _run_plan(self, argv):
        from spark_examples_tpu.check.plan import (
            parse_plan_args,
            validate_plan,
        )

        conf, devices, _json, budget, analysis, topology, sched_budget = (
            parse_plan_args(argv)
        )
        return validate_plan(
            conf, devices, host_mem_budget=budget, analysis=analysis,
            topology=topology, sched_budget_seconds=sched_budget,
        )

    def test_accepts_each_analysis(self, tmp_path):
        pheno = tmp_path / "p.tsv"
        pheno.write_text("A\t1\nB\t0\n")
        base = ["--num-samples", "2", "--references", REFS,
                "--variant-set-id", "tiny", "--num-samples", "2"]
        # grm / ld accept a minimal conf; assoc needs a parseable TSV and
        # a matching cohort, so its coverage runs against the synthetic
        # names below.
        for analysis in ("grm", "ld"):
            report = self._run_plan(
                ["--analysis", analysis, "--num-samples", "8",
                 "--references", REFS]
            )
            assert report.ok, [i.message for i in report.issues]
            assert report.geometry["analysis"] == analysis

    def test_assoc_accepts_matching_cohort(self, tmp_path):
        conf = AssocConf.parse(
            ["--num-samples", "4", "--references", REFS,
             "--phenotypes", "pending"]
        )
        names = _cohort_names(conf)
        pheno = tmp_path / "p.tsv"
        pheno.write_text(
            "".join(f"{n}\t{i % 2}\n" for i, n in enumerate(names))
        )
        report = self._run_plan(
            ["--analysis", "assoc", "--num-samples", "4",
             "--references", REFS, "--phenotypes", str(pheno)]
        )
        assert report.ok, [i.message for i in report.issues]
        assert report.geometry["assoc_cases"] == 2

    @pytest.mark.parametrize(
        "argv, code",
        [
            (
                ["--analysis", "grm", "--num-samples", "8,8",
                 "--variant-set-id", "a,b", "--references", REFS],
                "analysis-variant-sets",
            ),
            (
                ["--analysis", "ld", "--num-samples", "9",
                 "--references", REFS, "--mesh-shape", "1,2",
                 "--plan-devices", "2"],
                "ld-cohort-not-divisible",
            ),
            (
                ["--analysis", "assoc", "--num-samples", "8",
                 "--references", REFS],
                "assoc-phenotypes",
            ),
            (
                ["--analysis", "assoc", "--num-samples", "8",
                 "--references", REFS, "--phenotypes",
                 "/nonexistent/p.tsv"],
                "assoc-phenotypes",
            ),
            (
                ["--analysis", "grm", "--num-samples", "8",
                 "--references", REFS, "--grm-out",
                 "/nonexistent/dir/kin.tsv"],
                "grm-out",
            ),
        ],
    )
    def test_reject_matrix(self, argv, code):
        report = self._run_plan(argv)
        assert not report.ok
        assert code in [i.code for i in report.issues]

    def test_assoc_cohort_mismatch_rejected(self, tmp_path):
        pheno = tmp_path / "p.tsv"
        pheno.write_text("NOBODY\t1\nNOONE\t0\n")
        report = self._run_plan(
            ["--analysis", "assoc", "--num-samples", "8",
             "--references", REFS, "--phenotypes", str(pheno)]
        )
        assert "assoc-cohort-mismatch" in [i.code for i in report.issues]

    def test_num_pc_only_gates_pca(self):
        # --num-pc > cohort is an eigensolve contract; the analyses never
        # eigensolve, so only the pca surface rejects it.
        pca = self._run_plan(
            ["--num-samples", "2", "--references", REFS, "--num-pc", "5"]
        )
        assert "num-pc-exceeds-cohort" in [i.code for i in pca.issues]
        grm = self._run_plan(
            ["--analysis", "grm", "--num-samples", "2",
             "--references", REFS, "--num-pc", "5"]
        )
        assert grm.ok, [i.message for i in grm.issues]

    def test_ld_skips_gramian_hbm_rule(self):
        # A cohort far past the dense-HBM bound is still a valid LD plan:
        # LD never allocates the N x N accumulator.
        argv = ["--num-samples", "60000", "--references", REFS,
                "--similarity-strategy", "dense"]
        pca = self._run_plan(argv)
        assert "dense-exceeds-hbm" in [i.code for i in pca.issues]
        ld = self._run_plan(["--analysis", "ld", *argv])
        assert ld.ok, [i.message for i in ld.issues]
        assert "ld_window_stats_bytes" in ld.geometry

    def test_unknown_analysis_raises(self):
        from spark_examples_tpu.check.plan import parse_plan_args

        with pytest.raises(ValueError, match="--analysis"):
            parse_plan_args(["--analysis", "nope", "--num-samples", "8"])
        with pytest.raises(ValueError, match="--analysis"):
            parse_plan_args(["--analysis"])

    def test_plan_cli_exit_codes(self, capsys):
        from spark_examples_tpu.check.cli import main

        rc = main(
            ["plan", "--analysis", "grm", "--num-samples", "8",
             "--references", REFS]
        )
        assert rc == 0
        # Parse-time contract violations (LdConf._from_namespace) surface
        # as flag-contract plan rejections, exit 2.
        rc = main(
            ["plan", "--analysis", "ld", "--num-samples", "8",
             "--references", REFS, "--ld-r2-threshold", "1.5"]
        )
        assert rc == 2
        out = capsys.readouterr().out
        assert "plan REJECTED" in out


# ---------------------------------------------------------------- manifest


class TestManifestAnalysisBlock:
    def _doc(self, analysis):
        from spark_examples_tpu.obs.manifest import build_manifest

        return build_manifest(config={}, analysis=analysis)

    def test_null_block_valid(self):
        from spark_examples_tpu.obs.manifest import validate_manifest

        assert validate_manifest(self._doc(None)) == []

    def test_valid_block(self):
        from spark_examples_tpu.obs.manifest import validate_manifest

        doc = self._doc(
            {"kind": "ld", "sites_kept": 3, "sites_tested": 10}
        )
        assert validate_manifest(doc) == []

    @pytest.mark.parametrize(
        "block, match",
        [
            ({"sites_kept": 1, "sites_tested": 1}, "analysis.kind"),
            ({"kind": "", "sites_kept": 1, "sites_tested": 1},
             "analysis.kind"),
            ({"kind": "ld", "sites_tested": 1}, "sites_kept missing"),
            ({"kind": "ld", "sites_kept": -1, "sites_tested": 1},
             "sites_kept"),
            ({"kind": "ld", "sites_kept": True, "sites_tested": 1},
             "sites_kept"),
            ("not-a-dict", "analysis"),
        ],
    )
    def test_invalid_blocks(self, block, match):
        from spark_examples_tpu.obs.manifest import validate_manifest

        errors = validate_manifest(self._doc(block))
        assert any(match in e for e in errors), errors


# ------------------------------------------------------------------ serve


class TestServeGrm:
    def test_reserved_kinds_rejected(self):
        from spark_examples_tpu.serve.protocol import (
            ProtocolError,
            parse_request,
            request_doc,
        )

        for kind in ("ld", "assoc"):
            with pytest.raises(ProtocolError) as err:
                parse_request(request_doc(["--num-samples", "8"], kind=kind))
            assert err.value.code == "reserved-kind"
        with pytest.raises(ProtocolError) as err:
            parse_request(request_doc([], kind="nope"))
        assert err.value.code == "unknown-kind"

    def test_submit_cli_kind_choices_track_protocol(self):
        # The submit verb's --kind choices come from the protocol's own
        # tables: served kinds submit; reserved kinds pass argparse so the
        # server's structured reserved-kind 400 reaches the user.
        from spark_examples_tpu.serve import client, protocol

        assert client.SUBMIT_KIND_CHOICES == (
            tuple(protocol.JOB_KINDS) + tuple(protocol.RESERVED_KINDS)
        )
        assert "grm" in client.SUBMIT_KIND_CHOICES

    def test_grm_fingerprint_is_kind_keyed(self):
        from spark_examples_tpu.utils.cache import compile_fingerprint

        conf = _grm_conf()
        assert compile_fingerprint(conf, kind="grm") != compile_fingerprint(
            conf, kind="pca"
        )

    def test_classify_conf_handles_grm(self):
        from spark_examples_tpu.serve.queue import classify_conf

        assert classify_conf(_grm_conf()) == "small"

    def _wait_terminal(self, svc, job_id, timeout=120.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            _s, doc = svc.job_status(job_id)
            if doc["job"]["status"] in ("done", "failed", "cancelled"):
                return doc["job"]
            time.sleep(0.05)
        raise AssertionError(f"job {job_id} never finished")

    def test_grm_job_end_to_end(self, tmp_path):
        from spark_examples_tpu.obs.manifest import (
            read_manifest,
            validate_manifest,
        )
        from spark_examples_tpu.serve.daemon import PcaService
        from spark_examples_tpu.serve.protocol import request_doc

        svc = PcaService(run_dir=str(tmp_path / "serve")).start()
        try:
            flags = ["--num-samples", "8", "--references", REFS]
            # Reserved per-site output path: 400 before any device work.
            status, doc = svc.submit(
                request_doc(
                    flags + ["--grm-out", str(tmp_path / "kin.tsv")],
                    kind="grm",
                )
            )
            assert status == 400
            assert doc["error"]["code"] == "reserved-flag"
            # A doomed grm conf rejects through the analysis plan entry.
            status, doc = svc.submit(
                request_doc(
                    ["--num-samples", "8,8", "--variant-set-id", "a,b",
                     "--references", REFS],
                    kind="grm",
                )
            )
            assert status == 400
            codes = [i["code"] for i in doc["plan"]["issues"]]
            assert "analysis-variant-sets" in codes
            # The real job: done, kinship summary, valid per-job manifest
            # with the analysis block.
            status, doc = svc.submit(request_doc(flags, kind="grm"))
            assert status == 202, doc
            job = self._wait_terminal(svc, doc["job"]["id"])
            assert job["status"] == "done", job
            summary = job["result"]["grm"]
            assert summary["shape"] == [8, 8]
            assert summary["sites"] > 0
            manifest = read_manifest(job["manifest_path"])
            assert validate_manifest(manifest) == []
            assert manifest["analysis"]["kind"] == "grm"
            # Identical resubmit: the kind-keyed geometry is warm.
            status, doc = svc.submit(request_doc(flags, kind="grm"))
            assert status == 202
            job2 = self._wait_terminal(svc, doc["job"]["id"])
            assert job2["status"] == "done"
            assert job2["compile_cache"] == "warm"
        finally:
            assert svc.stop(timeout=60.0)


# -------------------------------------------------------------- heartbeat


def test_heartbeat_analysis_segment():
    from spark_examples_tpu.obs import MetricsRegistry
    from spark_examples_tpu.obs.heartbeat import Heartbeat
    from spark_examples_tpu.obs.metrics import (
        ANALYSIS_SITES_KEPT,
        ANALYSIS_SITES_TESTED,
        well_known_gauge,
    )

    registry = MetricsRegistry()
    beat = Heartbeat(60.0, registry, emit=lambda line: None)
    assert "analysis kept" not in beat.line()
    well_known_gauge(registry, ANALYSIS_SITES_TESTED).set(1000)
    well_known_gauge(registry, ANALYSIS_SITES_KEPT).set(250)
    assert "analysis kept 250/1,000 sites" in beat.line()

"""End-to-end flagship pipeline: TPU backend vs. the literal host replication
of the reference algorithm, multi-dataset join/merge, checkpoint resume,
emit formats."""

import os

import numpy as np
import pytest
from helpers import assert_pcs_match

from spark_examples_tpu.config import PcaConf
from spark_examples_tpu.pipeline import pca_driver
from spark_examples_tpu.pipeline.checkpoint import load_variants, save_variants
from spark_examples_tpu.pipeline.pca_driver import VariantsPcaDriver, extract_call_info
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource


def _conf(**kw):
    base = dict(
        references="17:0:20000",
        variant_set_id=["vs-a"],
        num_samples=30,
        seed=7,
        bases_per_partition=5000,
        block_size=64,
    )
    base.update(kw)
    conf = PcaConf()
    for k, v in base.items():
        setattr(conf, k, v)
    return conf


def _source(conf):
    return SyntheticGenomicsSource(num_samples=conf.num_samples, seed=conf.seed)


def test_extract_call_info_semantics(small_source):
    conf = _conf(num_samples=40)
    driver = VariantsPcaDriver(conf, small_source)
    data = driver.get_data()
    variant = next(data[0].variants())
    calls = extract_call_info(variant, driver.indexes)
    assert len(calls) == 40
    for call, model_call in zip(calls, variant.calls):
        assert call.has_variation == any(g > 0 for g in model_call.genotype)
        assert call.callset_id == driver.indexes[model_call.callset_id]


def test_similarity_tpu_matches_host_reference():
    conf = _conf()
    driver = VariantsPcaDriver(conf, _source(conf))
    calls = list(driver.iter_calls(driver.get_data()))
    assert calls
    tpu = driver.get_similarity_matrix(calls)

    conf_host = _conf(pca_backend="host")
    driver_host = VariantsPcaDriver(conf_host, _source(conf_host))
    host = driver_host.get_similarity_matrix(iter(calls))
    np.testing.assert_array_equal(tpu, host)
    # Diagonal counts = per-sample variant counts.
    assert (np.diag(host) > 0).any()


def test_pca_tpu_matches_host_reference():
    conf = _conf(references="17:0:40000")
    driver = VariantsPcaDriver(conf, _source(conf))
    calls = list(driver.iter_calls(driver.get_data()))
    S = driver.get_similarity_matrix(calls)
    ours = driver.compute_pca(S)

    conf_host = _conf(references="17:0:40000", pca_backend="host")
    driver_host = VariantsPcaDriver(conf_host, _source(conf_host))
    theirs = driver_host.compute_pca(S)

    A = np.array([pcs for _, pcs in ours])
    B = np.array([pcs for _, pcs in theirs])
    # Align arbitrary eigenvector signs, then compare.
    signs = np.sign((A * B).sum(axis=0))
    signs[signs == 0] = 1
    np.testing.assert_allclose(A, B * signs, atol=5e-3)
    assert [cid for cid, _ in ours] == [cid for cid, _ in theirs]


def test_pca_separates_populations():
    conf = _conf(references="17:0:100000", num_samples=24)
    source = SyntheticGenomicsSource(num_samples=24, seed=3, n_pops=2)
    driver = VariantsPcaDriver(conf, source)
    calls = list(driver.iter_calls(driver.get_data()))
    S = driver.get_similarity_matrix(calls)
    result = driver.compute_pca(S)
    pc1 = np.array([pcs[0] for _, pcs in result])
    pops = np.asarray(source._pops)
    # PC1 separates the two synthetic populations almost perfectly.
    means = [pc1[pops == p].mean() for p in (0, 1)]
    spread = max(pc1[pops == p].std() for p in (0, 1))
    assert abs(means[0] - means[1]) > 3 * spread


def test_min_allele_frequency_filters():
    conf = _conf(min_allele_frequency=0.2)
    driver = VariantsPcaDriver(conf, _source(conf))
    filtered = list(driver.iter_calls(driver.get_data()))
    conf2 = _conf()
    driver2 = VariantsPcaDriver(conf2, _source(conf2))
    unfiltered = list(driver2.iter_calls(driver2.get_data()))
    assert 0 < len(filtered) < len(unfiltered)


def test_two_dataset_join_doubles_matrix():
    conf = _conf(variant_set_id=["vs-a", "vs-b"])
    driver = VariantsPcaDriver(conf, _source(conf))
    assert len(driver.indexes) == 60  # 30 + 30 columns
    calls = list(driver.iter_calls(driver.get_data()))
    assert calls
    # Joined rows may contain indices from both datasets.
    flat = {i for row in calls for i in row}
    assert min(flat) < 30 <= max(flat)
    S = driver.get_similarity_matrix(calls)
    assert S.shape == (60, 60)
    # Cross-dataset co-occurrence exists (shared sites).
    assert S[:30, 30:].sum() > 0


def test_three_dataset_merge_intersects():
    conf = _conf(variant_set_id=["vs-a", "vs-b", "vs-c"], references="17:0:10000")
    driver = VariantsPcaDriver(conf, _source(conf))
    calls = list(driver.iter_calls(driver.get_data()))
    assert calls
    assert len(driver.indexes) == 90
    flat = {i for row in calls for i in row}
    assert max(flat) >= 60  # third dataset contributes


def test_merge_equals_join_on_shared_sites():
    """For synthetic data every site exists in every dataset exactly once, so
    2-dataset join and 3-dataset merge (restricted to two sets) agree."""
    conf2 = _conf(variant_set_id=["vs-a", "vs-b"], references="17:0:8000")
    d2 = VariantsPcaDriver(conf2, _source(conf2))
    joined = sorted(tuple(sorted(r)) for r in d2.iter_calls(d2.get_data()))

    # Force the merge path with the same two datasets by monkey-patching the
    # dataset count check is not possible; instead verify merge on 3 sets
    # restricted to the first two datasets' columns matches the join rows.
    conf3 = _conf(variant_set_id=["vs-a", "vs-b", "vs-c"], references="17:0:8000")
    d3 = VariantsPcaDriver(conf3, _source(conf3))
    merged = [
        tuple(sorted(i for i in row if i < 60))
        for row in d3.iter_calls(d3.get_data())
    ]
    merged = sorted(t for t in merged if t)
    assert merged == [t for t in joined if t]


def test_checkpoint_round_trip(tmp_path):
    conf = _conf()
    driver = VariantsPcaDriver(conf, _source(conf))
    data = driver.get_data()
    shards = [records for _, records in data[0].iter_shards()]
    path = str(tmp_path / "variants-ckpt")
    n = save_variants(path, shards)
    assert n == sum(len(s) for s in shards)

    loaded = load_variants(path)
    original = [kv for shard in shards for kv in shard]
    assert list(loaded) == original

    # Driver resume path: --input-path replaces the API read
    # (VariantsPca.scala:112-113) and disables stats (:332-335).
    conf2 = _conf(input_path=path)
    driver2 = VariantsPcaDriver(conf2, _source(conf2))
    assert driver2.io_stats is None
    calls_resumed = list(driver2.iter_calls(driver2.get_data()))
    calls_fresh = list(driver.iter_calls(data))
    assert calls_resumed == calls_fresh


def test_cli_save_variants_round_trip(tmp_path, capsys):
    """--save-variants end to end: ingest → save while streaming → resume
    via --input-path produces identical principal components, with no
    Python in between (the writer the reference's objectFile resume never
    had, VariantsPca.scala:112-113)."""
    ckpt = str(tmp_path / "saved-variants")
    base = [
        "--references", "17:0:30000",
        "--variant-set-id", "vs",
        "--num-samples", "12",
        "--seed", "5",
        "--block-size", "32",
        "--min-allele-frequency", "0.05",
    ]
    saved_lines = pca_driver.run(base + ["--save-variants", ckpt])
    out = capsys.readouterr().out
    assert "Saved " in out and ckpt in out
    # The checkpoint holds UNFILTERED records (filters re-apply on resume):
    # more records than AF-kept rows.
    total = sum(1 for _ in load_variants(ckpt))
    assert total > 0

    resumed_lines = pca_driver.run(base + ["--input-path", ckpt])
    capsys.readouterr()
    assert resumed_lines == saved_lines

    # A different threshold still works against the saved (unfiltered) data.
    loose = pca_driver.run(
        [a for a in base if a not in ("--min-allele-frequency", "0.05")]
        + ["--input-path", ckpt]
    )
    capsys.readouterr()
    fresh_loose = pca_driver.run(
        [a for a in base if a not in ("--min-allele-frequency", "0.05")]
    )
    capsys.readouterr()
    assert loose == fresh_loose


def test_save_variants_refuses_streaming_scale_file(tmp_path):
    """A VCF the auto logic would STREAM must not silently revert to the
    O(file) wire parse because --save-variants was added."""
    vcf = (
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS1\n"
        "17\t101\t.\tA\tG\t1\t.\tAF=0.5\tGT\t0|1\n"
    )
    path = tmp_path / "tiny.vcf"
    path.write_text(vcf)
    with pytest.raises(ValueError, match="streaming-scale"):
        pca_driver.run(
            [
                "--source", "file", "--input-files", str(path),
                "--stream-chunk-bytes", "1",  # force streaming eligibility
                "--save-variants", str(tmp_path / "ckpt"),
                "--references", "17:0:1000",
            ]
        )


def test_save_variants_flag_guards():
    for argv, message in [
        (["--save-variants", "/tmp/x", "--ingest", "device"], "wire"),
        (["--save-variants", "/tmp/x", "--input-path", "/tmp/y"], "re-save"),
        (
            [
                "--save-variants", "/tmp/x",
                "--variant-set-id", "vs-a,vs-b",
            ],
            "single variant set",
        ),
    ]:
        with pytest.raises(ValueError, match=message):
            pca_driver.run(argv)


def test_emit_result_formats(tmp_path, capsys):
    conf = _conf(output_path=str(tmp_path / "out"))
    driver = VariantsPcaDriver(conf, _source(conf))
    result = [
        (driver_id, [0.125, -0.5])
        for driver_id in list(driver.indexes)[:3]
    ]
    lines = driver.emit_result(result)
    # Console: name<TAB>dataset<TAB>pc1<TAB>pc2, sorted by name.
    names = [l.split("\t")[0] for l in lines]
    assert names == sorted(names)
    assert all(l.split("\t")[1] == "vs" for l in lines)
    # Saved: name, pcs..., dataset (the reference's saved column order).
    saved = open(str(tmp_path / "out-pca.tsv" / "part-00000")).read().splitlines()
    assert len(saved) == 3
    assert saved[0].split("\t")[-1] == "vs"


def test_full_run_entrypoint(tmp_path, capsys):
    lines = pca_driver.run(
        [
            "--references", "17:0:20000",
            "--variant-set-id", "vs-a",
            "--num-samples", "12",
            "--seed", "5",
            "--bases-per-partition", "5000",
            "--block-size", "32",
            "--output-path", str(tmp_path / "run"),
        ]
    )
    assert len(lines) == 12
    captured = capsys.readouterr().out
    assert "Matrix size: 12." in captured
    assert "Non zero rows in matrix:" in captured
    assert "Variants API stats:" in captured
    assert os.path.exists(str(tmp_path / "run-pca.tsv" / "part-00000"))


def test_packed_run_matches_wire_run(tmp_path):
    """The packed fast path (run()) and the wire-record path produce the
    same similarity matrix, hence the same result lines."""
    argv = [
        "--references", "17:0:20000",
        "--variant-set-id", "vs-a",
        "--num-samples", "12",
        "--seed", "5",
        "--bases-per-partition", "5000",
    ]
    fast = pca_driver.run(argv)
    conf = PcaConf.parse(argv)
    driver = VariantsPcaDriver(conf)
    calls = driver.iter_calls(driver.get_data())
    S = driver.get_similarity_matrix(calls)
    slow = driver.emit_result(driver.compute_pca(S))
    assert fast == slow


def test_device_ingest_similarity_matches_wire_similarity():
    """The fused device generation path produces the identical Gramian to the
    wire-record path, single dataset."""
    import jax

    conf = _conf(ingest="device")
    driver = VariantsPcaDriver(conf, _source(conf))
    contigs = conf.get_contigs(driver.source, conf.variant_set_id)
    S_dev = np.asarray(jax.device_get(driver.get_similarity_device_gen(contigs)))

    conf2 = _conf()
    driver2 = VariantsPcaDriver(conf2, _source(conf2))
    calls = list(driver2.iter_calls(driver2.get_data()))
    S_wire = np.asarray(jax.device_get(driver2.get_similarity_matrix(calls)))
    np.testing.assert_array_equal(S_dev, S_wire)


@pytest.mark.parametrize("n_sets", [2, 3])
def test_device_ingest_matches_wire_multiset(n_sets):
    """2-set join and 3-set merge-intersect collapse to column concatenation
    on the device path — must equal the wire join/merge Gramian exactly."""
    import jax

    sets = ["vs-a", "vs-b", "vs-c"][:n_sets]
    conf = _conf(variant_set_id=sets, references="17:0:12000", ingest="device")
    driver = VariantsPcaDriver(conf, _source(conf))
    contigs = conf.get_contigs(driver.source, conf.variant_set_id)
    S_dev = np.asarray(jax.device_get(driver.get_similarity_device_gen(contigs)))

    conf2 = _conf(variant_set_id=sets, references="17:0:12000")
    driver2 = VariantsPcaDriver(conf2, _source(conf2))
    calls = list(driver2.iter_calls(driver2.get_data()))
    S_wire = np.asarray(jax.device_get(driver2.get_similarity_matrix(calls)))
    np.testing.assert_array_equal(S_dev, S_wire)


def test_multiset_wire_join_runs_windows_concurrently():
    """The ≥2-set wire join streams windows through the shard thread pool:
    with --num-workers N and a blocking source, multiple windows' record
    builds must be in flight at once (round-2 ask: the join previously
    computed every dataset's window serially per index)."""
    import threading
    import time

    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    class SlowSource(SyntheticGenomicsSource):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.lock = threading.Lock()
            self.active = 0
            self.max_active = 0

        def client(self):
            outer = self

            class SlowClient(type(super().client())):
                def search_variants(self, request, *a, **kw):
                    with outer.lock:
                        outer.active += 1
                        outer.max_active = max(outer.max_active, outer.active)
                    time.sleep(0.05)
                    try:
                        yield from super().search_variants(request, *a, **kw)
                    finally:
                        with outer.lock:
                            outer.active -= 1

            return SlowClient(outer)

    source = SlowSource(num_samples=8, seed=5, variant_spacing=100)
    conf = _conf(
        variant_set_id=["vs-a", "vs-b"],
        references="17:0:40000",
        num_samples=8,
        bases_per_partition=5000,  # 8 windows
        num_workers=4,
    )
    driver = VariantsPcaDriver(conf, source)
    rows = list(driver.iter_calls(driver.get_data()))
    assert rows  # the join produced records
    assert source.max_active >= 2  # windows overlapped, not serial


def test_asymmetric_joint_cohort_device_matches_wire():
    """The reference's ACTUAL joint-cohort scenario — a large cohort joined
    with a small deep-call cohort (1KG × Platinum,
    ``VariantsPca.scala:155-168``; ``SearchVariantsExample.scala:28``): a
    2-set join with DIFFERENT column counts per set, identical between the
    fused device ingest and the wire-record join path."""
    argv = [
        "--references", "17:0:20000",
        "--variant-set-id", "vs-a,vs-b",
        "--num-samples", "30,7",
        "--seed", "5",
        "--bases-per-partition", "5000",
    ]
    device_lines = pca_driver.run(argv + ["--ingest", "device"])
    wire_lines = pca_driver.run(argv + ["--ingest", "wire"])
    assert device_lines == wire_lines
    assert len(device_lines) == 37  # 30 + 7 columns


def test_asymmetric_cohort_callsets_and_populations():
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    source = SyntheticGenomicsSource(
        num_samples=30, seed=5, cohort_sizes={"vs-b": 7}
    )
    callsets = source.search_callsets(["vs-a", "vs-b"])
    assert len(callsets) == 37
    assert source.num_samples_for("vs-a") == 30
    assert source.num_samples_for("vs-b") == 7
    # A cohort smaller than n_pops still spans the populations it can:
    # population assignment is s*n_pops//N within EACH cohort.
    pops_b = source.populations_for("vs-b")
    assert len(pops_b) == 7 and pops_b.max() < source.n_pops


def test_device_run_entrypoint_matches_wire(tmp_path, capsys):
    argv = [
        "--references", "17:0:20000",
        "--variant-set-id", "vs-a",
        "--num-samples", "12",
        "--seed", "5",
        "--bases-per-partition", "5000",
    ]
    device_lines = pca_driver.run(argv + ["--ingest", "device"])
    wire_lines = pca_driver.run(argv + ["--ingest", "wire"])
    assert device_lines == wire_lines
    captured = capsys.readouterr().out
    assert "Variants API stats:" in captured


def test_same_set_join_accumulates_multiplicity():
    """Joining a variant set with itself: duplicate callset columns must
    contribute k² per entry (reference pair-loop semantics), on both the host
    oracle and the TPU path."""
    conf = _conf(variant_set_id=["vs-a", "vs-a"], references="17:0:8000")
    driver = VariantsPcaDriver(conf, _source(conf))
    assert len(driver.indexes) == 30  # duplicate ids collapse columns
    calls = list(driver.iter_calls(driver.get_data()))
    assert any(len(row) != len(set(row)) for row in calls)
    S_tpu = np.asarray(driver.get_similarity_matrix(iter(calls)))

    conf_host = _conf(variant_set_id=["vs-a", "vs-a"], references="17:0:8000",
                      pca_backend="host")
    driver_host = VariantsPcaDriver(conf_host, _source(conf_host))
    S_host = driver_host.get_similarity_matrix(iter(calls))
    np.testing.assert_array_equal(S_tpu, S_host)
    # k duplicates ⇒ diagonal gets k² > k somewhere.
    row = next(r for r in calls if len(r) != len(set(r)))
    assert S_host.max() >= 4 or len(calls) < 5


def test_ingest_flag_guards():
    with pytest.raises(ValueError, match="ingest device"):
        pca_driver.run(["--ingest", "device", "--source", "rest",
                        "--references", "17:0:1000"])
    with pytest.raises(ValueError, match="ingest packed"):
        pca_driver.run(["--ingest", "packed", "--pca-backend", "host",
                        "--references", "17:0:1000"])
    with pytest.raises(ValueError, match="single variant set"):
        pca_driver.run(["--ingest", "packed", "--variant-set-id", "a,b",
                        "--references", "17:0:1000", "--num-samples", "8"])


def test_sharded_strategy_end_to_end_matches_dense(tmp_path):
    """--similarity-strategy sharded (row-tile Gramian + sharded centering +
    sharded subspace PCA) equals the dense strategy end to end, at a padded
    non-divisible cohort size (21 samples on a samples-axis-8 mesh)."""
    argv = [
        "--references", "17:0:30000",
        "--variant-set-id", "vs-a",
        "--num-samples", "21",
        "--seed", "5",
        "--bases-per-partition", "10000",
        "--block-size", "32",
        "--ingest", "packed",
    ]
    dense = pca_driver.run(argv + ["--similarity-strategy", "dense"])
    sharded = pca_driver.run(
        argv + ["--similarity-strategy", "sharded", "--mesh-shape", "1,8"]
    )
    assert_pcs_match(dense, sharded)


def test_sharded_strategy_guard_without_mesh():
    with pytest.raises(ValueError, match="samples axis"):
        conf = _conf(similarity_strategy="sharded", mesh_shape="8,1")
        driver = VariantsPcaDriver(conf, _source(conf))
        driver.get_similarity_matrix(iter([[0, 1]]))


def test_sharded_device_ingest_run_matches_dense_run():
    """Single-set sharded strategy now stays on the device ingest path
    (ring accumulator) end to end; result equals the dense device run."""
    argv = [
        "--references", "17:0:30000",
        "--variant-set-id", "vs-a",
        "--num-samples", "21",
        "--seed", "5",
        "--bases-per-partition", "10000",
        "--block-size", "32",
    ]
    dense = pca_driver.run(argv + ["--similarity-strategy", "dense"])
    sharded = pca_driver.run(
        argv + ["--similarity-strategy", "sharded", "--mesh-shape", "1,8"]
    )
    assert_pcs_match(dense, sharded)


def test_merged_sharded_run_stays_on_device_and_matches_wire(capsys):
    """The VERDICT-r4 cliff, closed: a merged (asymmetric 2-set) config
    under the SHARDED strategy — the joint-cohort-past-the-dense-HBM-rule
    scenario (``VariantsPca.scala:155-168``) — now runs the multi-set ring
    device path instead of silently falling back to wire ingest, and its
    principal components match the wire oracle."""
    argv = [
        "--references", "17:0:30000",
        "--variant-set-id", "vs-a,vs-b",
        "--num-samples", "13,6",
        "--seed", "5",
        "--block-size", "32",
    ]
    wire = pca_driver.run(argv + ["--ingest", "wire"])
    capsys.readouterr()
    sharded = pca_driver.run(
        argv + ["--similarity-strategy", "sharded", "--mesh-shape", "1,8"]
    )
    out = capsys.readouterr().out
    # Loud-fallback guard: the run must NOT have taken the wire path.
    assert "using wire ingest" not in out
    assert_pcs_match(wire, sharded)


def test_io_stats_parity_across_ingest_paths(capsys):
    """partitions / requests / variants agree between the device, packed and
    wire ingest paths for the same single-set configuration."""
    argv = [
        "--references", "17:0:20000",
        "--variant-set-id", "vs-a",
        "--num-samples", "12",
        "--seed", "5",
        "--bases-per-partition", "5000",
        "--block-size", "32",
    ]

    def stats_of(ingest):
        pca_driver.run(argv + ["--ingest", ingest])
        out = capsys.readouterr().out
        fields = {}
        for line in out.splitlines():
            if line.startswith("# of"):
                key, value = line.split(": ")
                fields[key] = int(value)
        return fields

    device = stats_of("device")
    packed = stats_of("packed")
    wire = stats_of("wire")
    for key in ("# of partitions", "# of bases requested", "# of API requests"):
        assert device[key] == packed[key] == wire[key], (key, device, packed, wire)
    # Variants: device/packed count kept rows after the nonzero drop; wire
    # counts every record built (ref blocks included) — a documented
    # divergence, but device and packed must agree exactly.
    assert device["# of variants read"] == packed["# of variants read"]
    assert wire["# of variants read"] >= device["# of variants read"]

"""REST backend logic via an injected transport (no sockets).

Covers the ``Client`` + ``Paginator`` behaviors the reference relied on
(``Client.scala:42-54``, ``rdd/VariantsRDD.scala:201-224``): pagination
through ``nextPageToken``, STRICT boundary filtering, retry/failure
accounting, auth headers, and driver-side callset/contig discovery.
"""

import urllib.error

import pytest

from spark_examples_tpu.sharding.contig import SexChromosomeFilter
from spark_examples_tpu.sources.base import OfflineAuth, ShardBoundary
from spark_examples_tpu.sources.rest import RestClient, RestGenomicsSource


class FakeTransport:
    """Scripted transport: queue of responses/exceptions per call."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = []

    def __call__(self, url, payload, headers):
        self.calls.append((url, dict(payload), dict(headers)))
        action = self.script.pop(0)
        if isinstance(action, Exception):
            raise action
        return action


def _variant(start):
    return {"id": f"v{start}", "start": start}


def test_pagination_follows_next_page_token():
    transport = FakeTransport(
        [
            {"variants": [_variant(1), _variant(2)], "nextPageToken": "t1"},
            {"variants": [_variant(3)], "nextPageToken": "t2"},
            {"variants": [_variant(4)]},
        ]
    )
    client = RestClient(None, base_url="http://x/api", transport=transport)
    got = list(
        client.search_variants({"start": 0, "end": 100}, ShardBoundary.STRICT)
    )
    assert [v["id"] for v in got] == ["v1", "v2", "v3", "v4"]
    assert client.counters.initialized_requests == 3
    # Page tokens thread through subsequent payloads.
    assert "pageToken" not in transport.calls[0][1]
    assert transport.calls[1][1]["pageToken"] == "t1"
    assert transport.calls[2][1]["pageToken"] == "t2"


def test_strict_boundary_filters_out_of_range_records():
    transport = FakeTransport(
        [{"variants": [_variant(5), _variant(10), _variant(20)]}]
    )
    client = RestClient(None, base_url="http://x", transport=transport)
    got = list(
        client.search_variants({"start": 10, "end": 20}, ShardBoundary.STRICT)
    )
    assert [v["start"] for v in got] == [10]


def test_retries_count_failures_then_succeed():
    transport = FakeTransport(
        [
            urllib.error.HTTPError("u", 500, "boom", {}, None),
            urllib.error.URLError("down"),
            {"variants": [_variant(1)]},
        ]
    )
    client = RestClient(
        None, base_url="http://x", transport=transport, sleep=lambda s: None
    )
    got = list(client.search_variants({"start": 0, "end": 10}))
    assert len(got) == 1
    assert client.counters.initialized_requests == 3
    assert client.counters.unsuccessful_responses == 1
    assert client.counters.io_exceptions == 1


def test_retries_exhausted_raises():
    transport = FakeTransport(
        [urllib.error.URLError("down")] * 3
    )
    client = RestClient(
        None,
        base_url="http://x",
        transport=transport,
        max_retries=3,
        sleep=lambda s: None,
    )
    with pytest.raises(RuntimeError, match="failed after retries"):
        list(client.search_variants({"start": 0, "end": 10}))
    assert client.counters.io_exceptions == 3


def test_4xx_is_not_retried():
    """A caller error (bad request/id/auth scope) raises immediately — no
    retry can fix it, and hammering the server would be hostile."""
    transport = FakeTransport(
        [urllib.error.HTTPError("u", 404, "nope", {}, None)]
    )
    slept = []
    client = RestClient(
        None, base_url="http://x", transport=transport, sleep=slept.append
    )
    with pytest.raises(RuntimeError, match="HTTP 404"):
        list(client.search_variants({"start": 0, "end": 10}))
    assert client.counters.initialized_requests == 1
    assert client.counters.unsuccessful_responses == 1
    assert slept == []


def test_429_is_retried():
    """Rate-limiting is transient: retried like a 5xx."""
    transport = FakeTransport(
        [
            urllib.error.HTTPError("u", 429, "slow down", {}, None),
            {"variants": [_variant(1)]},
        ]
    )
    client = RestClient(
        None, base_url="http://x", transport=transport, sleep=lambda s: None
    )
    got = list(client.search_variants({"start": 0, "end": 10}))
    assert len(got) == 1
    assert client.counters.initialized_requests == 2


def test_backoff_is_exponential_with_full_jitter():
    """Delays are uniform in [0, min(cap, base·2^attempt)]: bounded by the
    growing ceiling, and no sleep after the final attempt."""
    import random

    transport = FakeTransport([urllib.error.URLError("down")] * 4)
    slept = []
    client = RestClient(
        None,
        base_url="http://x",
        transport=transport,
        max_retries=4,
        backoff_base=1.0,
        backoff_cap=3.0,
        sleep=slept.append,
        rng=random.Random(0),
    )
    with pytest.raises(RuntimeError, match="failed after retries"):
        client._post("variants/search", {})
    assert len(slept) == 3  # one fewer than attempts
    # Exactly the seeded jitter draws over the exponential ceilings
    # (cap kicks in at attempt 3: min(3.0, 1.0·2²) = 3.0) — a regression
    # to constant or zero backoff cannot reproduce this sequence.
    mirror = random.Random(0)
    assert slept == [mirror.uniform(0.0, c) for c in [1.0, 2.0, 3.0]]


def test_auth_header_attached():
    transport = FakeTransport([{"variants": []}])
    client = RestClient(
        OfflineAuth(client_secrets_file="cs.json", access_token="tok123"),
        base_url="http://x",
        transport=transport,
    )
    list(client.search_variants({"start": 0, "end": 1}))
    assert transport.calls[0][2]["Authorization"] == "Bearer tok123"


def test_callsets_and_contigs_discovery():
    transport = FakeTransport(
        [
            {
                "callSets": [{"id": "cs0", "name": "S0"}],
                "nextPageToken": "n",
            },
            {"callSets": [{"id": "cs1", "name": "S1"}]},
            {
                "referenceBounds": [
                    {"referenceName": "chr1", "upperBound": 1000},
                    {"referenceName": "X", "upperBound": 500},
                ]
            },
        ]
    )
    source = RestGenomicsSource(base_url="http://x", transport=transport)
    callsets = source.search_callsets(["vs1"])
    assert [c["id"] for c in callsets] == ["cs0", "cs1"]
    contigs = source.get_contigs("vs1", SexChromosomeFilter.EXCLUDE_XY)
    assert [c.reference_name for c in contigs] == ["chr1"]
    assert contigs[0].end == 1000


def test_reads_boundary_filtering():
    def read(pos):
        return {"alignment": {"position": {"position": pos}}}

    transport = FakeTransport([{"alignments": [read(5), read(15)]}])
    client = RestClient(None, base_url="http://x", transport=transport)
    got = list(
        client.search_reads(
            {"start": 10, "end": 20}, ShardBoundary.STRICT
        )
    )
    assert len(got) == 1


def test_driver_end_to_end_against_rest_backend():
    """The full PCoA driver over --source rest: a transport serving the
    synthetic cohort's wire JSON must reproduce the synthetic-source run."""
    import json as _json

    import numpy as np

    from spark_examples_tpu.config import PcaConf
    from spark_examples_tpu.pipeline.pca_driver import VariantsPcaDriver
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    synthetic = SyntheticGenomicsSource(num_samples=10, seed=4)

    def transport(url, payload, headers):
        if url.endswith("/callsets/search"):
            return {
                "callSets": synthetic.search_callsets(payload["variantSetIds"])
            }
        if url.endswith("/variants/search"):
            client = synthetic.client()
            items = list(
                client.search_variants(payload, ShardBoundary.STRICT)
            )
            return {"variants": _json.loads(_json.dumps(items))}
        raise AssertionError(f"unexpected url {url}")

    rest = RestGenomicsSource(base_url="http://fake", transport=transport)
    conf = PcaConf()
    conf.references = "17:41196311:41216311"
    conf.variant_set_id = ["vs"]
    conf.num_samples = 10
    conf.source = "rest"
    conf.block_size = 32
    driver = VariantsPcaDriver(conf, rest)
    S_rest = driver.get_similarity_matrix(driver.iter_calls(driver.get_data()))

    conf2 = PcaConf()
    conf2.references = "17:41196311:41216311"
    conf2.variant_set_id = ["vs"]
    conf2.num_samples = 10
    conf2.block_size = 32
    driver2 = VariantsPcaDriver(conf2, synthetic)
    S_syn = driver2.get_similarity_matrix(
        driver2.iter_calls(driver2.get_data())
    )
    np.testing.assert_array_equal(np.asarray(S_rest), np.asarray(S_syn))

"""The graftcheck static-analysis subsystem: linter golden fixtures (rule
IDs + line numbers), the clean-tree gate, escape hatches, the device-free
plan validator's accept/reject matrix, and the sanitizer corpus/harness.

The fixtures are inline sources (not importable files): the linter works on
text, and inline keeps each violation's expected LINE NUMBER adjacent to
the code that produces it.
"""

import json
import os
import subprocess
import textwrap
import warnings

import numpy as np
import pytest

from spark_examples_tpu.check.linter import json_report, lint_paths, lint_source
from spark_examples_tpu.check.plan import validate_plan
from spark_examples_tpu.check.rules import RULES
from spark_examples_tpu.config import PcaConf

_PACKAGE_DIR = os.path.dirname(
    os.path.abspath(__import__("spark_examples_tpu").__file__)
)


def _ids(findings):
    return [(f.rule_id, f.line) for f in findings]


# --------------------------------------------------------------------------
# Golden fixtures: one violation per rule, asserting id AND line number.
# --------------------------------------------------------------------------


def test_gc001_item_sync_in_hot_path():
    src = textwrap.dedent(
        """
        def f(x):
            return x.mean().item()
        """
    )
    assert _ids(lint_source(src, "ops/fixture.py")) == [("GC001", 3)]


def test_gc001_float_of_jnp_value():
    src = textwrap.dedent(
        """
        import jax.numpy as jnp
        def f(x):
            y = jnp.sum(x)
            return float(y)
        """
    )
    assert _ids(lint_source(src, "pipeline/fixture.py")) == [("GC001", 5)]


def test_gc001_scoped_to_hot_paths_only():
    src = textwrap.dedent(
        """
        import jax.numpy as jnp
        def f(x):
            y = jnp.sum(x)
            return float(y)
        """
    )
    # The same code outside ops/ and pipeline/ is legitimate (tests,
    # oracles, benchmark reporting).
    assert lint_source(src, "utils/fixture.py") == []


def test_gc002_branch_on_traced_param():
    src = textwrap.dedent(
        """
        import jax
        @jax.jit
        def f(x, n):
            if x > 0:
                return x
            while n:
                n = n - 1
            return n
        """
    )
    assert _ids(lint_source(src, "anywhere.py")) == [
        ("GC002", 5),
        ("GC002", 7),
    ]


def test_gc002_static_and_identity_tests_pass():
    src = textwrap.dedent(
        """
        import functools, jax
        @functools.partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            if n > 0:
                return x
            if x is None:
                return x
            return x
        """
    )
    assert lint_source(src, "anywhere.py") == []


def test_gc003_jit_inside_loop():
    src = textwrap.dedent(
        """
        import jax
        def f(xs):
            out = []
            for x in xs:
                g = jax.jit(lambda v: v + 1)
                out.append(g(x))
            return out
        """
    )
    assert _ids(lint_source(src, "anywhere.py")) == [("GC003", 6)]


def test_gc004_jnp_at_import_time():
    src = textwrap.dedent(
        """
        import jax.numpy as jnp
        TABLE = jnp.arange(16)
        """
    )
    assert _ids(lint_source(src, "anywhere.py")) == [("GC004", 3)]
    # Inside a function: fine.
    fn = "import jax.numpy as jnp\ndef f():\n    return jnp.arange(16)\n"
    assert lint_source(fn, "anywhere.py") == []
    # A module-level lambda BODY runs at call time, not import time.
    lam = "import jax.numpy as jnp\nf = lambda x: jnp.sum(x)\n"
    assert lint_source(lam, "anywhere.py") == []


def test_gc005_update_without_donation_and_with():
    bad = textwrap.dedent(
        """
        import jax
        @jax.jit
        def gram_update(G, X):
            return G + X
        """
    )
    assert _ids(lint_source(bad, "ops/fixture.py")) == [("GC005", 4)]
    good = textwrap.dedent(
        """
        import functools, jax
        @functools.partial(jax.jit, donate_argnums=(0,))
        def gram_update(G, X):
            return G + X
        """
    )
    assert lint_source(good, "ops/fixture.py") == []
    # Outside ops/: not this rule's business.
    assert lint_source(bad, "pipeline/fixture.py") == []


def test_gc006_lock_without_ordering_comment():
    bad = textwrap.dedent(
        """
        import threading
        class A:
            def __init__(self):
                self._lock = threading.Lock()
        """
    )
    assert _ids(lint_source(bad, "sources/fixture.py")) == [("GC006", 5)]
    good = textwrap.dedent(
        """
        import threading
        class A:
            def __init__(self):
                # lock order: leaf lock, never held across another acquire
                self._lock = threading.Lock()
        """
    )
    assert lint_source(good, "sources/fixture.py") == []


def test_gc007_block_until_ready_in_loop():
    src = textwrap.dedent(
        """
        import jax
        def feed(blocks, G):
            for b in blocks:
                G = G + b
                jax.block_until_ready(G)
            return G
        """
    )
    assert _ids(lint_source(src, "ops/fixture.py")) == [("GC007", 6)]


def test_gc008_print_under_jit():
    src = textwrap.dedent(
        """
        from jax import jit
        @jit
        def f(x):
            print("tracing", x)
            return x
        """
    )
    assert _ids(lint_source(src, "anywhere.py")) == [("GC008", 5)]


def test_gc009_ad_hoc_stats_mutation():
    bad = textwrap.dedent(
        """
        def account(self, io_stats, n):
            io_stats.requests += n
            self.counters.initialized_requests += 1
            self.stream_counters.variants += n
        """
    )
    assert _ids(lint_source(bad, "pipeline/fixture.py")) == [
        ("GC009", 3),
        ("GC009", 4),
        ("GC009", 5),
    ]
    # Methods on the owner (`self.x += n` inside the stats class) and
    # non-stats objects stay clean, as does out-of-scope code.
    good = textwrap.dedent(
        """
        class StreamCounters:
            def add_variants(self, n):
                self.variants += n

        def feed(acc, io_stats, n):
            acc.rows_seen += n
            io_stats.add_requests(n)
        """
    )
    assert lint_source(good, "sources/fixture.py") == []
    assert lint_source(bad, "utils/fixture.py") == []


def test_gc009_disable_escape_hatch():
    src = (
        "def f(io_stats):\n"
        "    io_stats.requests += 1  # graftcheck: disable=GC009 -- oracle\n"
    )
    assert lint_source(src, "pipeline/fixture.py") == []


def test_gc010_host_numpy_under_jit():
    bad = textwrap.dedent(
        """
        import jax
        import numpy as np
        @jax.jit
        def kernel(G, X):
            mask = np.asarray(X)
            return G + np.sum(mask)
        """
    )
    assert _ids(lint_source(bad, "ops/fixture.py")) == [
        ("GC010", 6),
        ("GC010", 7),
    ]


def test_gc010_shard_map_decoration_and_scope():
    bad = textwrap.dedent(
        """
        import functools
        import numpy as np
        from spark_examples_tpu.utils.compat import shard_map
        @functools.partial(shard_map, mesh=None, in_specs=(), out_specs=())
        def per_device(x):
            return np.packbits(x)
        """
    )
    assert _ids(lint_source(bad, "ops/fixture.py")) == [("GC010", 7)]
    # The same code outside ops/ (tests, host staging) is legitimate.
    assert lint_source(bad, "sources/fixture.py") == []
    # Undecorated host code in ops/ is the normal staging path.
    host = textwrap.dedent(
        """
        import numpy as np
        def stage(rows):
            return np.packbits(rows, axis=-1)
        """
    )
    assert lint_source(host, "ops/fixture.py") == []


def test_gc010_dtype_constructors_and_escape_hatch():
    # np dtype constructors are trace-time metadata, not host compute.
    ok = textwrap.dedent(
        """
        import jax
        import numpy as np
        @jax.jit
        def kernel(G, X):
            return G + X.astype(np.dtype("float32"))
        """
    )
    assert lint_source(ok, "ops/fixture.py") == []
    src = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def kernel(G):\n"
        "    return G + np.sum(G)  # graftcheck: disable=GC010 -- trace-time constant, measured\n"
    )
    assert lint_source(src, "ops/fixture.py") == []


# --------------------------------------------------------------------------
# Escape hatches.
# --------------------------------------------------------------------------


def test_disable_comment_silences_named_rule_only():
    src = (
        "def f(x):\n"
        "    return x.mean().item()  # graftcheck: disable=GC001 -- oracle\n"
    )
    assert lint_source(src, "ops/fixture.py") == []
    wrong_id = (
        "def f(x):\n"
        "    return x.mean().item()  # graftcheck: disable=GC007\n"
    )
    assert _ids(lint_source(wrong_id, "ops/fixture.py")) == [("GC001", 2)]


def test_disable_file_and_disable_all():
    src = (
        "# graftcheck: disable-file=GC001\n"
        "def f(x):\n"
        "    return x.mean().item()\n"
    )
    assert lint_source(src, "ops/fixture.py") == []
    src_all = (
        "def f(x):\n"
        "    return x.mean().item()  # graftcheck: disable=all\n"
    )
    assert lint_source(src_all, "ops/fixture.py") == []


# --------------------------------------------------------------------------
# The merged tree lints clean, and the report is machine-readable.
# --------------------------------------------------------------------------


def test_package_tree_is_lint_clean():
    findings, checked = lint_paths([_PACKAGE_DIR])
    assert checked > 40  # the whole package was walked, not a subtree
    assert findings == [], "\n".join(f.format() for f in findings)


def test_json_report_schema():
    src = "def f(x):\n    return x.mean().item()\n"
    findings = lint_source(src, "ops/fixture.py")
    report = json.loads(json_report(findings, checked=1))
    assert report["tool"] == "graftcheck"
    assert report["checked_files"] == 1
    assert report["finding_count"] == 1
    [entry] = report["findings"]
    assert entry["rule"] == "GC001"
    assert entry["path"] == "ops/fixture.py"
    assert entry["line"] == 2
    assert entry["name"] == RULES["GC001"].name


def test_cli_exit_codes(tmp_path):
    from spark_examples_tpu.check.cli import main

    assert main(["lint", _PACKAGE_DIR]) == 0
    bad = tmp_path / "ops"
    bad.mkdir()
    (bad / "fixture.py").write_text("def f(x):\n    return x.item()\n")
    assert main(["lint", str(tmp_path)]) == 1
    assert main(["lint", str(tmp_path / "missing")]) == 2
    assert main(["nonsense"]) == 2


def test_single_file_lint_keeps_scoped_rules():
    """Linting ONE file must apply the same scoped rules as the tree walk
    (per-changed-file invocations — hooks, editors — must not silently
    drop GC001/GC005/GC006/GC007)."""
    findings, checked = lint_paths(
        [os.path.join(_PACKAGE_DIR, "ops", "gramian.py")]
    )
    assert checked == 1
    assert findings == []  # clean WITH its disables honored…
    # …and the scoped rule genuinely ran: the same file with the GC005
    # disables stripped must flag again under its package relpath.
    from spark_examples_tpu.check.linter import _package_relpath

    relpath = _package_relpath(os.path.join(_PACKAGE_DIR, "ops", "gramian.py"))
    assert relpath == "ops/gramian.py"
    with open(os.path.join(_PACKAGE_DIR, "ops", "gramian.py")) as f:
        stripped = f.read().replace("# graftcheck: disable=GC005", "#")
    assert any(
        f.rule_id == "GC005" for f in lint_source(stripped, relpath)
    )


# --------------------------------------------------------------------------
# Plan validator: accepts runnable configs, rejects impossible ones —
# without touching a device (asserted via live array count).
# --------------------------------------------------------------------------


def _plan(argv, devices=None):
    conf = PcaConf.parse(argv)
    return validate_plan(conf, plan_devices=devices)


def _error_codes(report):
    return {i.code for i in report.issues if i.severity == "error"}


def test_plan_accepts_default_config():
    report = _plan([])
    assert report.ok, report.format()
    assert any("dense update" in c for c in report.shape_checks)


def test_plan_accepts_sharded_mesh_with_enough_devices():
    report = _plan(
        ["--mesh-shape", "4,2", "--similarity-strategy", "sharded"],
        devices=8,
    )
    assert report.ok, report.format()
    assert any("abstract 4x2 mesh" in c for c in report.shape_checks)


def test_plan_rejects_mesh_exceeding_declared_devices():
    report = _plan(["--mesh-shape", "4,2"], devices=4)
    assert not report.ok
    assert "mesh-exceeds-devices" in _error_codes(report)


def test_plan_rejects_sharded_without_samples_axis():
    report = _plan(
        ["--similarity-strategy", "sharded", "--mesh-shape", "4,1"],
        devices=4,
    )
    assert not report.ok
    assert "sharded-needs-samples-axis" in _error_codes(report)


def test_plan_rejects_data_axis_past_reduce_partitions():
    report = _plan(
        ["--mesh-shape", "8,1", "--num-reduce-partitions", "4"], devices=8
    )
    assert not report.ok
    assert "data-axis-exceeds-reduce-partitions" in _error_codes(report)


def test_plan_rejects_num_pc_past_cohort():
    report = _plan(["--num-pc", "500", "--num-samples", "100"])
    assert not report.ok
    assert "num-pc-exceeds-cohort" in _error_codes(report)


def test_plan_rejects_flag_contract_via_cli():
    from spark_examples_tpu.check.cli import main

    assert main(["plan", "--blocks-per-dispatch", "0"]) == 2
    # argparse-level flag errors must ALSO come back as an int plan
    # rejection, never a SystemExit out of main().
    assert main(["plan", "--ingest", "bogus"]) == 2
    assert main(["plan", "--no-such-flag"]) == 2


def test_plan_warns_on_cohort_padding():
    report = _plan(
        [
            "--similarity-strategy", "sharded", "--mesh-shape", "2,3",
            "--num-samples", "100",
        ],
        devices=6,
    )
    assert report.ok
    assert any(i.code == "cohort-padding" for i in report.issues)


def test_plan_touches_no_device_arrays():
    import jax

    before = len(jax.live_arrays())
    report = _plan(
        ["--mesh-shape", "2,2", "--similarity-strategy", "sharded"],
        devices=4,
    )
    assert report.ok
    assert len(jax.live_arrays()) == before  # eval_shape only — no buffers


def test_plan_rejects_negative_heartbeat():
    # The parse path rejects it as a flag contract…
    from spark_examples_tpu.check.cli import main

    assert main(["plan", "--heartbeat-seconds", "-5"]) == 2
    # …and programmatic PcaConf construction (which bypasses
    # _from_namespace) is caught by validate_plan itself.
    conf = PcaConf()
    conf.heartbeat_seconds = -1.0
    report = validate_plan(conf)
    assert not report.ok
    assert "heartbeat-seconds" in _error_codes(report)


def test_plan_rejects_unwritable_metrics_json(tmp_path):
    report = _plan(
        ["--metrics-json", str(tmp_path / "no_such_dir" / "m.json")]
    )
    assert not report.ok
    assert "metrics-json-parent" in _error_codes(report)
    # A directory path can't receive the manifest either.
    report = _plan(["--metrics-json", str(tmp_path)])
    assert not report.ok
    assert "metrics-json-parent" in _error_codes(report)
    # A writable parent passes.
    report = _plan(["--metrics-json", str(tmp_path / "m.json")])
    assert report.ok, report.format()
    from spark_examples_tpu.check.cli import main

    assert (
        main(["plan", "--metrics-json", str(tmp_path / "x" / "m.json")]) == 2
    )


def test_plan_surfaces_ir_facts_for_sharded_configs():
    """The sharded plan report carries the jaxpr-derived ring traffic and
    static liveness facts, and the jaxpr traffic equals the formula-derived
    fact the report already had — cross-validated every plan run."""
    report = _plan(
        ["--mesh-shape", "1,2", "--similarity-strategy", "sharded"],
        devices=2,
    )
    assert report.ok, report.format()
    geometry = report.geometry
    assert (
        geometry["ring_bytes_per_flush_jaxpr"]
        == geometry["ring_bytes_per_flush"]
    )
    assert geometry["ring_peak_live_bytes_per_device"] > 0
    assert geometry["ring_permute_steps"] == 1  # samples axis 2 -> D-1 = 1
    assert any("ring IR audit" in c for c in report.shape_checks)


# --------------------------------------------------------------------------
# Sanitizer corpus + harness.
# --------------------------------------------------------------------------


def test_corpus_is_deterministic_and_covers_edges():
    from spark_examples_tpu.check.corpus import corpus_documents

    a = corpus_documents()
    b = corpus_documents()
    assert a == b
    assert len(a) >= 30
    joined = b"\n".join(a)
    assert b"" in a  # empty buffer
    assert b"\r\n" in joined  # CRLF documents
    assert any(doc and not doc.startswith(b"#") for doc in a)  # headerless


def test_corpus_parses_match_python_oracle():
    """Every non-malformed corpus document parses identically through the
    native and Python paths (the sanitize replay checks memory/race safety;
    this pins semantic parity over the same corpus)."""
    from spark_examples_tpu.check.corpus import corpus_documents
    from spark_examples_tpu.utils import native as native_mod

    if native_mod.vcf_library() is None:
        pytest.skip(f"no native build: {native_mod.native_unavailable_reason()}")
    import tempfile

    from spark_examples_tpu.sources.files import _python_vcf_arrays

    # One comparison semantics for every parity tier: the grouping and the
    # NaN-aware array equality live in the fuzz module.
    from test_files_fuzz import _assert_same_arrays, _group_by_contig

    checked = 0
    for doc in corpus_documents():
        try:
            native = native_mod.parse_vcf_arrays(doc)
        except ValueError:
            continue  # malformed by design; parity on errors is tested elsewhere
        fd, path = tempfile.mkstemp(suffix=".vcf")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(doc)
            try:
                python = _python_vcf_arrays(path, "corpus")
            except ValueError:
                continue
        finally:
            os.unlink(path)
        grouped_native = _group_by_contig(*native)
        grouped_python = _group_by_contig(*python)
        assert set(grouped_native) == set(grouped_python)
        for contig in grouped_native:
            _assert_same_arrays(grouped_native[contig], grouped_python[contig])
        checked += 1
    assert checked >= 10  # the corpus is mostly well-formed by design


def _compiler_available():
    from spark_examples_tpu.utils.native import _compiler

    return _compiler() is not None


@pytest.mark.skipif(not _compiler_available(), reason="no C++ compiler")
def test_asan_harness_replays_mini_corpus_clean():
    """Tier-1 smoke: the ASan build replays a corpus subset clean (the full
    3-mode replay is the slow test below / `ci.sh --sanitize`)."""
    from spark_examples_tpu.check.corpus import corpus_documents
    from spark_examples_tpu.check.sanitize import replay_corpus

    proc = replay_corpus("asan", corpus=corpus_documents()[:8])
    assert proc.returncode == 0, proc.stderr[-2000:]


@pytest.mark.slow
@pytest.mark.skipif(not _compiler_available(), reason="no C++ compiler")
@pytest.mark.parametrize("mode", ["asan", "ubsan", "tsan"])
def test_sanitizer_full_corpus_replay(mode):
    from spark_examples_tpu.check.sanitize import replay_corpus

    proc = replay_corpus(mode)
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_typecheck_gate_skips_or_passes():
    """On images without mypy the gate must SKIP (exit 0); with mypy it
    must pass against the committed baseline — either way the lint stage
    stays green on the merged tree."""
    from spark_examples_tpu.check.typecheck import run_typecheck

    assert run_typecheck(strict=False) == 0


# --------------------------------------------------------------------------
# The gz auto-streaming sortedness fallback (ADVICE.md sharp edge).
# --------------------------------------------------------------------------

_VCF_HEADER = (
    "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\tS1\n"
)


def _write(tmp_path, name, text):
    path = tmp_path / name
    path.write_text(text)
    return str(path)


def test_auto_stream_falls_back_on_unsorted(tmp_path, monkeypatch):
    import spark_examples_tpu.sources.files as files_mod
    from spark_examples_tpu.sharding.contig import Contig
    from spark_examples_tpu.sources.files import (
        FileGenomicsSource,
        StreamCounters,
    )

    monkeypatch.setattr(files_mod, "STREAM_THRESHOLD_BYTES", 1)
    path = _write(
        tmp_path,
        "unsorted.vcf",
        _VCF_HEADER
        + "1\t30\t.\tA\tG\t.\t.\tAF=0.5\tGT\t0|1\t1|1\n"
        + "1\t5\t.\tA\tG\t.\t.\tAF=0.5\tGT\t1|0\t0|0\n",
    )
    src = FileGenomicsSource([path])
    set_id = src.set_ids[0]
    assert src.wants_streaming(set_id)  # the size heuristic chose streaming
    shards = [Contig("1", 0, 100)]
    counters = StreamCounters(len(shards))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        blocks = list(
            src.stream_genotype_blocks(
                set_id, shards, block_size=16, counters=counters
            )
        )
    assert any("unsorted" in str(w.message) for w in caught)
    # The in-memory fallback served the SAME data (position-sorted).
    [block] = blocks
    assert block["positions"].tolist() == [4, 29]
    assert counters.shard_rows == {0: 2}
    assert counters.variants == 2
    # The set is now pinned to the in-memory path.
    assert not src.wants_streaming(set_id)
    assert [(c.reference_name, c.end) for c in src.get_contigs(set_id)] == [
        ("1", 30)
    ]


def test_explicit_streaming_keeps_hard_error(tmp_path):
    from spark_examples_tpu.sharding.contig import Contig
    from spark_examples_tpu.sources.files import (
        FileGenomicsSource,
        UnsortedVcfError,
    )

    path = _write(
        tmp_path,
        "unsorted.vcf",
        _VCF_HEADER
        + "1\t30\t.\tA\tG\t.\t.\t.\tGT\t0|1\t1|1\n"
        + "1\t5\t.\tA\tG\t.\t.\t.\tGT\t1|0\t0|0\n",
    )
    src = FileGenomicsSource([path], stream_chunk_bytes=64)
    with pytest.raises(UnsortedVcfError):
        list(
            src.stream_genotype_blocks(
                src.set_ids[0], [Contig("1", 0, 100)]
            )
        )


def test_auto_stream_sorted_file_still_streams(tmp_path, monkeypatch):
    import spark_examples_tpu.sources.files as files_mod
    from spark_examples_tpu.sharding.contig import Contig
    from spark_examples_tpu.sources.files import FileGenomicsSource

    monkeypatch.setattr(files_mod, "STREAM_THRESHOLD_BYTES", 1)
    path = _write(
        tmp_path,
        "sorted.vcf",
        _VCF_HEADER
        + "".join(
            f"1\t{p}\t.\tA\tG\t.\t.\tAF=0.5\tGT\t0|1\t1|1\n"
            for p in (5, 10, 20, 30)
        ),
    )
    src = FileGenomicsSource([path])
    set_id = src.set_ids[0]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        blocks = list(
            src.stream_genotype_blocks(
                set_id, [Contig("1", 0, 100)], block_size=2
            )
        )
    assert not [w for w in caught if "unsorted" in str(w.message)]
    assert sum(len(b["positions"]) for b in blocks) == 4
    assert src.wants_streaming(set_id)  # still the streaming path

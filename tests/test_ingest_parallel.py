"""Chunk-parallel native ingest engine + double-buffered device feeding.

The parity contract is absolute: ``--ingest-workers N`` (any N) must produce
byte-identical packed arrays — and therefore identical PCA output — to the
serial oracle path (``--ingest-workers 0``) on every fixture, including gz
streaming and header-edge-case files. The machinery under test:

- line-aligned span chunking + order-preserving pool merge
  (``sources/files.py``), over the GIL-releasing C-ABI span parser
  (``native/vcfparse.cpp:vcf_parse_span`` via ``utils/native.py``);
- the bounded prefetch queue between parse and device feed
  (``pipeline/datasets.py:PrefetchIterator``) — backpressure must hold;
- the double-buffered Gramian feed (``ops/gramian.py`` ``pipeline_depth``).
"""

import ctypes
import gzip
import os
import threading
import time

import numpy as np
import pytest

from spark_examples_tpu.pipeline import pca_driver
from spark_examples_tpu.pipeline.datasets import PrefetchIterator
from spark_examples_tpu.sources.files import (
    FileGenomicsSource,
    _line_aligned_spans,
    _ordered_pool_map,
    _PackedVcf,
    _read_vcf_header_samples,
    _StreamedVcf,
    default_ingest_workers,
)


def _assert_arrays_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    if a.dtype != object and np.issubdtype(a.dtype, np.floating):
        np.testing.assert_array_equal(np.isnan(a), np.isnan(b))
        np.testing.assert_array_equal(a[~np.isnan(a)], b[~np.isnan(b)])
    else:
        np.testing.assert_array_equal(a, b)


def _edge_case_vcf(tmp_path, name="cohort.vcf", n_samples=7, rows=300,
                   compress=False, seed=5):
    """Deterministic multi-contig fixture exercising the header edge cases:
    a single-'#' comment BEFORE #CHROM, another mid-file, CRLF-free sorted
    rows, AF-less rows, missing calls, and a contig switch."""
    rng = np.random.default_rng(seed)
    lines = [
        "##fileformat=VCFv4.2",
        "# single-hash comment before the column row",
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
        + "\t".join(f"S{i:02d}" for i in range(n_samples)),
    ]
    for contig in ("1", "17"):
        for k in range(rows):
            pos = 50 + 17 * k
            info = f"AF={rng.random():.4f}" if k % 3 else "NS=2"
            gts = "\t".join(
                rng.choice(["0|0", "0|1", "1|1", ".|.", "0/2"])
                for _ in range(n_samples)
            )
            lines.append(f"{contig}\t{pos}\t.\tAC\tG\t.\t.\t{info}\tGT\t{gts}")
        lines.append("# mid-file comment line")
    doc = "\n".join(lines) + "\n"
    path = tmp_path / (name + (".gz" if compress else ""))
    if compress:
        with gzip.open(path, "wt") as f:
            f.write(doc)
    else:
        path.write_text(doc)
    return str(path)


# ------------------------------------------------------------ chunking units


def test_line_aligned_spans_reassemble_exactly():
    text = b"alpha\nbeta\nmuch longer line gamma\nd\n\ntail without newline"
    for n in (1, 2, 3, 5, 64):
        spans = _line_aligned_spans(text, n)
        assert b"".join(text[a:b] for a, b in spans) == text
        assert all(b > a for a, b in spans)
        # Every boundary except the last sits just past a newline.
        assert all(text[b - 1 : b] == b"\n" for _, b in spans[:-1])
    assert _line_aligned_spans(b"", 4) == []


def test_ordered_pool_map_preserves_order_and_errors():
    assert list(_ordered_pool_map(lambda x: x * x, range(50), 4)) == [
        x * x for x in range(50)
    ]

    def boom(x):
        if x == 7:
            raise ValueError("chunk 7 exploded")
        return x

    out = []
    with pytest.raises(ValueError, match="chunk 7 exploded"):
        for item in _ordered_pool_map(boom, range(20), 3):
            out.append(item)
    assert out == list(range(7))  # everything before the failure, in order


def test_ordered_pool_map_bounds_source_advance():
    """Backpressure: a paused consumer stops the source iterator from being
    drained arbitrarily far ahead (the streaming-reader memory bound)."""
    pulled = []

    def source():
        for i in range(100):
            pulled.append(i)
            yield i

    workers = 3
    gen = _ordered_pool_map(lambda x: x, source(), workers)
    consumed = []
    for item in gen:
        consumed.append(item)
        time.sleep(0.002)
        # window = workers + 2 pending futures, plus one yielded and one
        # being pulled from the source.
        assert len(pulled) - len(consumed) <= workers + 2 + 2
        if len(consumed) >= 30:
            break
    gen.close()
    assert consumed == list(range(30))
    assert len(pulled) < 100


# ----------------------------------------------------------- native GIL path


def test_native_library_is_gil_releasing_cdll():
    """The chunk-parallel engine's scaling rests on ctypes releasing the GIL
    around foreign calls — true for CDLL, false for PyDLL. Guard the binding
    class so a refactor cannot silently serialize the pool."""
    from spark_examples_tpu.utils import native as native_mod

    lib = native_mod.vcf_library()
    if lib is None:
        pytest.skip(f"no native build: {native_mod.native_unavailable_reason()}")
    assert isinstance(lib, ctypes.CDLL)
    assert not isinstance(lib, ctypes.PyDLL)


def test_parse_vcf_span_matches_whole_buffer(tmp_path):
    from spark_examples_tpu.utils import native as native_mod

    if native_mod.vcf_library() is None:
        pytest.skip("no native build")
    path = _edge_case_vcf(tmp_path, rows=40)
    text = open(path, "rb").read()
    whole = native_mod.parse_vcf_arrays(text)
    _, n_samples = native_mod.scan_vcf_counts(text)
    for n_spans in (1, 2, 5):
        spans = _line_aligned_spans(text, n_spans)
        parts = [
            native_mod.parse_vcf_span(text, a, b, n_samples) for a, b in spans
        ]
        merged = [np.concatenate([p[i] for p in parts]) for i in range(5)]
        for a, b in zip(whole, merged):
            _assert_arrays_equal(a, b)


# ------------------------------------------------------------- parity: packed


@pytest.mark.parametrize("compress", [False, True])
@pytest.mark.parametrize("workers", [1, 2, 4])
def test_packed_parallel_parity(tmp_path, compress, workers):
    """The tentpole invariant: byte-identical per-contig packed arrays for
    every worker count vs the serial oracle — gz and plain, with comment
    lines before #CHROM and mid-file."""
    path = _edge_case_vcf(tmp_path, compress=compress)
    serial = _PackedVcf(path, "cohort", ingest_workers=0)
    parallel = _PackedVcf(path, "cohort", ingest_workers=workers)
    assert serial.num_samples == parallel.num_samples == 7
    assert list(serial.by_contig) == list(parallel.by_contig)
    for name in serial.by_contig:
        for a, b in zip(serial.by_contig[name], parallel.by_contig[name]):
            _assert_arrays_equal(a, b)
    assert serial.contig_bounds == parallel.contig_bounds


@pytest.mark.parametrize("compress", [False, True])
def test_streamed_parallel_parity(tmp_path, compress):
    """Streaming integration: parallel chunk decode yields the chunks in
    file order with identical arrays, across chunk sizes that slice lines
    mid-record."""
    path = _edge_case_vcf(tmp_path, compress=compress)

    def collect(workers, chunk_bytes):
        view = _StreamedVcf(
            path, "cohort", chunk_bytes=chunk_bytes, ingest_workers=workers
        )
        parts = list(view.iter_chunk_arrays())
        assert parts, "fixture should produce data"
        return [np.concatenate([p[i] for p in parts]) for i in range(5)]

    want = collect(0, 1024)
    for workers in (2, 4):
        for chunk_bytes in (777, 4096):
            got = collect(workers, chunk_bytes)
            for a, b in zip(want, got):
                _assert_arrays_equal(a, b)


def test_malformed_line_raises_same_file_level_ordinal(tmp_path):
    """Both paths fail loudly AND report the same FILE-level data-line
    number — the parallel merge translates the span-relative ordinal."""
    from spark_examples_tpu.utils import native as native_mod

    rows = [
        f"1\t{10 + 7 * k}\t.\tA\tG\t.\t.\tAF=0.5\tGT\t0|1" for k in range(90)
    ]
    rows[61] = "1\tnot_a_pos\t.\tA"  # data line #62
    path = tmp_path / "bad.vcf"
    path.write_text(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\n"
        + "\n".join(rows)
        + "\n"
    )
    messages = []
    for workers in (0, 3):
        with pytest.raises(ValueError) as err:
            _PackedVcf(str(path), "bad", ingest_workers=workers)
        messages.append(str(err.value))
    assert messages[0] == messages[1]
    if native_mod.vcf_library() is not None:
        assert "#62" in messages[0]


def test_driver_end_to_end_parity_across_workers(tmp_path):
    """``--ingest-workers N`` (N>=2) produces identical PCA output to the
    serial oracle on the same fixture, for the in-memory packed path AND the
    streamed path."""
    path = _edge_case_vcf(tmp_path, rows=120)
    base = [
        "--source", "file", "--input-files", path,
        "--references", "1:0:6000,17:0:6000",
        "--ingest", "packed",
        "--min-allele-frequency", "0.2",
    ]
    want = pca_driver.run(base + ["--ingest-workers", "0"])
    assert pca_driver.run(base + ["--ingest-workers", "4"]) == want
    streamed = base + ["--stream-chunk-bytes", "2048"]
    assert pca_driver.run(streamed + ["--ingest-workers", "0"]) == want
    assert pca_driver.run(streamed + ["--ingest-workers", "4"]) == want


# ---------------------------------------------------- prefetch / double-buffer


def test_prefetch_iterator_is_bounded_and_ordered():
    produced = []

    def source():
        for i in range(60):
            produced.append(i)
            yield i

    prefetch = PrefetchIterator(source(), depth=3)
    seen = []
    for item in prefetch:
        time.sleep(0.001)
        seen.append(item)
        # The queue holds ≤ depth items; the producer may hold one more.
        assert len(produced) - len(seen) <= 3 + 1
    assert seen == list(range(60))
    assert prefetch.items == 60


def test_prefetch_iterator_propagates_producer_error():
    def source():
        yield "ok"
        raise RuntimeError("parse died")

    prefetch = PrefetchIterator(source(), depth=2)
    assert next(prefetch) == "ok"
    with pytest.raises(RuntimeError, match="parse died"):
        next(prefetch)


def test_prefetch_close_releases_producer_thread():
    release = threading.Event()

    def source():
        for i in range(1000):
            if i > 2:
                release.wait(5.0)
            yield i

    prefetch = PrefetchIterator(source(), depth=2)
    assert next(prefetch) == 0
    release.set()
    prefetch.close()
    assert not prefetch._thread.is_alive()


def test_gramian_pipeline_depth_matches_synced_feed():
    from spark_examples_tpu.ops.gramian import GramianAccumulator

    rng = np.random.default_rng(11)
    X = (rng.random((500, 23)) < 0.4).astype(np.uint8)
    want = (X.T.astype(np.int64) @ X.astype(np.int64)).astype(np.float64)
    for depth in (None, 1, 2, 4):
        acc = GramianAccumulator(23, block_size=64, pipeline_depth=depth)
        for off in range(0, 500, 61):
            acc.add_rows(X[off : off + 61])
        np.testing.assert_array_equal(acc.finalize(), want)


def test_gramian_pipeline_depth_counts_kernel_parity():
    """Count-valued rows (same-set joins) take the unpacked counts kernel,
    whose full-block flush ships a view of the reused staging buffer — the
    one branch where pipelined (non-syncing) flushes must copy before the
    next add_rows overwrites it. Exact block-multiple feed sizes force the
    no-copy full-block path."""
    from spark_examples_tpu.ops.gramian import GramianAccumulator

    rng = np.random.default_rng(7)
    X = rng.integers(0, 3, (384, 17)).astype(np.uint8)  # values in {0,1,2}
    want = (X.T.astype(np.int64) @ X.astype(np.int64)).astype(np.float64)
    for depth in (None, 2):
        acc = GramianAccumulator(
            17, block_size=32, exact_int=True, pipeline_depth=depth
        )
        for off in range(0, 384, 32):  # exactly one full block per call
            acc.add_rows(X[off : off + 32])
        np.testing.assert_array_equal(acc.finalize(), want)


# ------------------------------------------------------- satellite regressions


def test_header_comment_before_chrom_keeps_cohort(tmp_path):
    """ADVICE fix: a single-'#' comment line before #CHROM must not end the
    header scan with a silent 0-sample cohort."""
    path = tmp_path / "commented.vcf"
    path.write_text(
        "##fileformat=VCFv4.2\n"
        "# a perfectly legal comment\n"
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\tS1\n"
        "1\t10\t.\tA\tG\t.\t.\tAF=0.5\tGT\t0|1\t1|1\n"
    )
    assert _read_vcf_header_samples(str(path)) == ["S0", "S1"]
    # And the streaming view built on it sees the full cohort.
    view = _StreamedVcf(str(path), "commented")
    assert view.num_samples == 2
    # Headerless files still yield the empty cohort (not an error).
    bare = tmp_path / "headerless.vcf"
    bare.write_text("1\t10\t.\tA\tG\t.\t.\tAF=0.5\n")
    assert _read_vcf_header_samples(str(bare)) == []


def test_blocks_per_dispatch_rejects_non_positive():
    from spark_examples_tpu.config import PcaConf

    for bad in ("0", "-3"):
        with pytest.raises(ValueError, match="blocks-per-dispatch"):
            PcaConf.parse(["--blocks-per-dispatch", bad])
    assert PcaConf.parse(["--blocks-per-dispatch", "5"]).blocks_per_dispatch == 5
    assert PcaConf.parse([]).blocks_per_dispatch is None


def test_ingest_workers_flag_validation():
    from spark_examples_tpu.config import PcaConf

    with pytest.raises(ValueError, match="ingest-workers"):
        PcaConf.parse(["--ingest-workers", "-1"])
    assert PcaConf.parse(["--ingest-workers", "0"]).ingest_workers == 0
    assert PcaConf.parse([]).ingest_workers is None
    assert 1 <= default_ingest_workers() <= 8
    with pytest.raises(ValueError, match=">= 0"):
        FileGenomicsSource(["x.vcf"], ingest_workers=-2)

"""``graftcheck hostmem``: golden fixtures per GH rule (id + line), the
clean-tree gate over the shipped host-staging layers, escape-hatch
honoring, the ``host_peak_bytes`` formula, the ``graftcheck plan
--host-mem-budget`` accept/reject matrix, the chunked-checkpoint
round-trip regression, and the measured-peak <= static-bound e2e parity
run that proves the formula against reality.

Fixtures are inline sources (the auditor works on text), keeping each
violation's expected LINE NUMBER adjacent to the code that produces it —
the same layout as ``tests/test_graftcheck.py``.
"""

import gzip
import json
import os
import subprocess
import sys
import textwrap

import pytest

from spark_examples_tpu.check.hostmem import (
    audit_paths,
    audit_source,
    conf_host_peak_bytes,
    default_hostmem_paths,
    parse_hostmem_hatches,
)
from spark_examples_tpu.check.plan import validate_plan
from spark_examples_tpu.check.rules import HOSTMEM_RULES
from spark_examples_tpu.config import PcaConf
from spark_examples_tpu.parallel.mesh import (
    HOST_RUNTIME_BASELINE_BYTES,
    host_peak_bytes,
)

_PACKAGE_DIR = os.path.dirname(
    os.path.abspath(__import__("spark_examples_tpu").__file__)
)
_REPO_ROOT = os.path.dirname(_PACKAGE_DIR)


def _ids(findings):
    return [(f.rule_id, f.line) for f in findings]


def _audit(src, relpath="sources/fixture.py"):
    return audit_source(textwrap.dedent(src), relpath)


# --------------------------------------------------------------------------
# Golden fixtures: one violation per rule, asserting id AND line number.
# --------------------------------------------------------------------------


def test_gh001_whole_file_read():
    findings, declared = _audit(
        """
        def load(path):
            with open(path, "rb") as f:
                return f.read()
        """
    )
    assert _ids(findings) == [("GH001", 4)]
    assert declared == []


def test_gh001_readlines_and_clean_windowed_read():
    findings, _ = _audit(
        """
        import gzip
        def load(path):
            f = gzip.open(path, "rb")
            lines = f.readlines()
            return lines
        def windowed(path):
            with open(path, "rb") as f:
                while True:
                    piece = f.read(1 << 20)
                    if not piece:
                        return
                    yield piece
        """
    )
    # The sized read in `windowed` is the bounded idiom — no finding.
    assert _ids(findings) == [("GH001", 5)]


def test_gh002_append_of_stream_items_in_read_loop():
    findings, _ = _audit(
        """
        def parse(path):
            rows = []
            with open(path, "rt") as f:
                for line in f:
                    rows.append(line.split())
            return rows
        """
    )
    assert _ids(findings) == [("GH002", 6)]


def test_gh002_byte_buffer_augassign_and_enumerate_wrapper():
    findings, _ = _audit(
        """
        import gzip
        def slurp(path):
            buf = b""
            with gzip.open(path, "rb") as f:
                while True:
                    piece = f.read(4096)
                    if not piece:
                        break
                    buf += piece
            return buf
        def count(path):
            out = []
            with open(path) as f:
                for i, line in enumerate(f):
                    out.append((i, line))
            return out
        """
    )
    assert _ids(findings) == [("GH002", 10), ("GH002", 16)]


def test_gh002_scalar_extractors_launder_taint():
    findings, _ = _audit(
        """
        def total(path):
            sizes = []
            n = 0
            with open(path, "rb") as f:
                while True:
                    piece = f.read(4096)
                    if not piece:
                        break
                    n += len(piece)
                    sizes.append(len(piece))
            return n, sizes
        """
    )
    # Accounting (len of the chunk) is O(1) per item — not accumulation.
    assert findings == []


def test_gh003_stream_materialization():
    findings, _ = _audit(
        """
        def eager(source, shards):
            blocks = list(source.stream_genotype_blocks("s", shards))
            return blocks
        def lazy(source, shards):
            for block in source.stream_genotype_blocks("s", shards):
                yield block["has_variation"]
        """
    )
    assert _ids(findings) == [("GH003", 3)]


def test_gh003_file_handle_materialization():
    findings, _ = _audit(
        """
        def slurp(path):
            with open(path) as f:
                return list(f)
        """
    )
    assert _ids(findings) == [("GH003", 4)]


def test_gh004_whole_buffer_decompress():
    findings, _ = _audit(
        """
        import gzip
        def load(data):
            return gzip.decompress(data)
        """
    )
    assert _ids(findings) == [("GH004", 4)]


def test_gh005_numpy_staging_of_file_buffer():
    findings, _ = _audit(
        """
        import numpy as np
        def stage(path):
            with open(path, "rb") as f:
                raw = f.read()
            return np.frombuffer(raw, dtype=np.uint8)
        def accumulate(path, chunks):
            parts = []
            with open(path) as f:
                for line in f:
                    parts.append(line)
            return np.stack(parts)
        """
    )
    # The whole-file read fires GH001 at its site and GH005 where the
    # buffer stages into numpy; the stream-accumulated list fires GH002
    # at the append and GH005 at the stack.
    assert _ids(findings) == [
        ("GH001", 5),
        ("GH005", 6),
        ("GH002", 11),
        ("GH005", 12),
    ]


def test_bounded_parser_shapes_stay_clean():
    findings, declared = _audit(
        """
        import numpy as np
        def per_chunk(path, chunk_bytes):
            carry = b""
            with open(path, "rb") as f:
                while True:
                    data = f.read(chunk_bytes)
                    if not data:
                        break
                    data = carry + data
                    cut = data.rfind(b"\\n")
                    if cut < 0:
                        carry = data
                        continue
                    carry = data[cut + 1:]
                    yield np.frombuffer(data[:cut + 1], dtype=np.uint8)
        """
    )
    # One window in, one window out: sized reads, a partial-line carry,
    # and per-chunk numpy staging are the bounded idiom — no findings.
    assert findings == []
    assert declared == []


def test_scope_limited_to_host_staging_layers():
    src = """
    def load(path):
        with open(path, "rb") as f:
            return f.read()
    """
    findings, _ = _audit(src, relpath="utils/fixture.py")
    assert findings == []
    findings, _ = _audit(src, relpath="ops/fixture.py")
    assert _ids(findings) == [("GH001", 4)]


# --------------------------------------------------------------------------
# Escape hatches are FORBIDDEN (GH006): the hatch line itself is a finding,
# justified or not. A justified hatch still routes its underlying GH00x
# finding into the declared inventory so the report says what it hides —
# but the audit fails either way.
# --------------------------------------------------------------------------


def test_justified_hatch_is_a_gh006_finding_with_inventory_context():
    findings, declared = _audit(
        """
        def load(path):
            with open(path, "rb") as f:
                return f.read()  # graftcheck: hostmem(unbounded) -- whole-file parse by contract
        """
    )
    # GH006 fires ON the hatch line; the suppressed GH001 is still
    # surfaced in the declared inventory for context.
    assert _ids(findings) == [("GH006", 4)]
    assert [(d.rule_id, d.line) for d in declared] == [("GH001", 4)]
    assert declared[0].justification == "whole-file parse by contract"


def test_unjustified_hatch_fires_both_rules():
    findings, declared = _audit(
        """
        def load(path):
            with open(path, "rb") as f:
                return f.read()  # graftcheck: hostmem(unbounded)
        """
    )
    assert _ids(findings) == [("GH001", 4), ("GH006", 4)]
    assert declared == []


def test_comment_only_hatch_flagged_and_declares_next_line():
    source = textwrap.dedent(
        """
        def load(path):
            with open(path, "rb") as f:
                # graftcheck: hostmem(unbounded) -- long justification on its own line
                return f.read()
        """
    )
    assert parse_hostmem_hatches(source) == {
        5: "long justification on its own line"
    }
    findings, declared = audit_source(source, "sources/fixture.py")
    assert [(f.rule_id, f.line) for f in findings] == [("GH006", 4)]
    assert [(d.rule_id, d.line) for d in declared] == [("GH001", 5)]


def test_hatch_does_not_leak_to_other_lines():
    findings, _ = _audit(
        """
        def load(path):
            with open(path, "rb") as f:
                a = f.read()  # graftcheck: hostmem(unbounded) -- declared here only
            with open(path, "rb") as g:
                return a + g.read()
        """
    )
    assert _ids(findings) == [("GH006", 4), ("GH001", 6)]


def test_gh006_scope_matches_hostmem_globs():
    # Outside the host-staging layers the hatch comment is inert text.
    findings, declared = _audit(
        """
        def load(path):
            with open(path, "rb") as f:
                return f.read()  # graftcheck: hostmem(unbounded) -- not our layer
        """,
        relpath="utils/fixture.py",
    )
    assert findings == []
    assert declared == []


# --------------------------------------------------------------------------
# The clean-tree gate: the shipped host-staging layers audit clean with a
# ZERO declared-unbounded inventory — every source streams through
# sources/stream.py, and GH006 makes any future hatch a finding.
# --------------------------------------------------------------------------


def test_shipped_tree_audits_clean_with_empty_inventory():
    report = audit_paths(default_hostmem_paths())
    assert report.ok, "\n".join(f.format() for f in report.findings)
    assert report.checked_files > 10
    # TOTAL: zero declared sites. A regression re-adding a hatch fails
    # twice — GH006 on the hatch line AND a non-empty inventory here.
    assert report.declared == []
    assert report.findings == []


def test_hostmem_cli_exit_codes(tmp_path):
    from spark_examples_tpu.check import cli

    assert cli.main(["hostmem"]) == 0
    # A nested package mirror so the scope globs (sources/*) resolve the
    # fixture exactly as they resolve the shipped tree.
    pkg = tmp_path / "pkg"
    dirty = pkg / "sources"
    dirty.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (dirty / "__init__.py").write_text("")
    (dirty / "bad.py").write_text(
        "def f(path):\n    g = open(path)\n    return g.read()\n"
    )
    assert cli.main(["hostmem", str(pkg)]) == 1
    assert cli.main(["hostmem", str(tmp_path / "missing")]) == 2


def test_hostmem_json_report_schema(capsys):
    from spark_examples_tpu.check import cli

    assert cli.main(["hostmem", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["tool"] == "graftcheck-hostmem"
    assert doc["ok"] is True
    assert doc["finding_count"] == 0
    # TOTAL: the declared-unbounded inventory is asserted EMPTY — this is
    # the machine-checked "zero declared sites" acceptance gate (ci.sh
    # re-asserts the same field against the shipped tree).
    assert doc["declared_unbounded"] == []


# --------------------------------------------------------------------------
# The closed-form budget formula and its configuration resolver.
# --------------------------------------------------------------------------


def test_host_peak_bytes_closed_form():
    # Term-by-term arithmetic, pinned: baseline + parse window
    # ((workers+2) * 2 * chunk) + prefetch (depth * B*N) + staging
    # (data * B*N) + flush copies ((1+depth) * staging).
    n, b = 64, 32
    got = host_peak_bytes(
        num_samples=n,
        block_size=b,
        data_axis=2,
        ingest_workers=4,
        chunk_bytes=1 << 20,
        prefetch_depth=2,
        pipeline_depth=2,
        baseline_bytes=0,
    )
    staging = 2 * b * n
    expected = (4 + 2) * 2 * (1 << 20) + 2 * b * n + staging + 3 * staging
    assert got == expected


def test_host_peak_bytes_monotone_and_baselined():
    base = host_peak_bytes(num_samples=64, block_size=32)
    assert base >= HOST_RUNTIME_BASELINE_BYTES
    assert host_peak_bytes(num_samples=128, block_size=32) > base
    assert host_peak_bytes(num_samples=64, block_size=64) > base
    assert (
        host_peak_bytes(num_samples=64, block_size=32, chunk_bytes=1 << 20)
        > base
    )
    host = host_peak_bytes(num_samples=64, block_size=32, host_accumulator=True)
    assert host == base + 2 * 64 * 64 * 8


def test_conf_resolver_is_total():
    # Every configuration shape that used to return None — in-memory/auto
    # file parse, wire ingest, JSONL/SAM, multi-set joins, checkpoint
    # resume, REST — now resolves to a finite positive bound.
    synthetic = PcaConf(num_samples=64, block_size=32)
    assert conf_host_peak_bytes(synthetic, device_count=1) > 0

    streamed = PcaConf(
        source="file",
        input_files=["cohort.vcf"],
        variant_set_id=["cohort"],
        stream_chunk_bytes=1 << 20,
        num_samples=64,
        block_size=32,
    )
    bound = conf_host_peak_bytes(streamed, device_count=1)
    assert bound > 0
    # The chunk term is in the bound: a bigger window raises it.
    streamed.stream_chunk_bytes = 8 << 20
    assert conf_host_peak_bytes(streamed, device_count=1) > bound

    for conf in (
        PcaConf(source="file", input_files=["c.vcf"], variant_set_id=["c"]),
        PcaConf(
            source="file",
            input_files=["c.vcf"],
            variant_set_id=["c"],
            stream_chunk_bytes=0,
        ),
        PcaConf(input_path="/tmp/ckpt"),
        PcaConf(
            source="file",
            input_files=["c.vcf"],
            variant_set_id=["c"],
            stream_chunk_bytes=1 << 20,
            ingest="wire",
        ),
        PcaConf(
            source="file",
            input_files=["c.jsonl"],
            variant_set_id=["c"],
            stream_chunk_bytes=1 << 20,
        ),
        PcaConf(
            source="file",
            input_files=["c.sam"],
            variant_set_id=["c"],
            stream_chunk_bytes=1 << 20,
        ),
        PcaConf(
            source="file",
            input_files=["a.vcf", "b.vcf"],
            variant_set_id=["a", "b"],
            stream_chunk_bytes=1 << 20,
        ),
        PcaConf(source="rest"),
    ):
        b = conf_host_peak_bytes(conf, device_count=1)
        assert isinstance(b, int) and b > 0
        # Monotone in the cohort width: growing N never shrinks the bound.
        import dataclasses

        wider = dataclasses.replace(conf, num_samples=conf.num_samples * 2)
        assert conf_host_peak_bytes(wider, device_count=1) >= b


def test_conf_resolver_wire_bound_tracks_bytes_on_disk(tmp_path):
    # A REAL (statable) wire input is bounded by its size on disk, not
    # the declared geometry ceiling: a small file proves a small bound.
    small = tmp_path / "c.jsonl"
    small.write_text('{"referenceName": "1"}\n' * 50)
    conf = PcaConf(
        source="file",
        input_files=[str(small)],
        variant_set_id=[small.name[:-6]],
        ingest="wire",
        num_samples=8,
        block_size=8,
    )
    bound = conf_host_peak_bytes(conf, device_count=1)
    assert bound > 0
    # Far under the geometry-ceiling bound of an unstatable path.
    ceiling_conf = PcaConf(
        source="file",
        input_files=["/nonexistent/c.jsonl"],
        variant_set_id=["c"],
        ingest="wire",
        num_samples=8,
        block_size=8,
    )
    assert bound < conf_host_peak_bytes(ceiling_conf, device_count=1)
    # And provable under a modest budget: the smoke ci.sh runs.
    assert bound < 8 << 30


# --------------------------------------------------------------------------
# graftcheck plan --host-mem-budget accept/reject matrix.
# --------------------------------------------------------------------------


def _plan(args, budget=None, devices=1):
    conf = PcaConf.parse(args)
    return validate_plan(conf, plan_devices=devices, host_mem_budget=budget)


def test_plan_reports_host_peak_fact_without_budget():
    report = _plan(["--num-samples", "64", "--references", "1:0:50000"])
    assert report.ok
    assert report.geometry["host_peak_bytes"] > HOST_RUNTIME_BASELINE_BYTES


def test_plan_accepts_within_budget():
    report = _plan(
        ["--num-samples", "64", "--references", "1:0:50000"],
        budget=8 << 30,
    )
    assert report.ok


def test_plan_rejects_over_budget():
    report = _plan(
        ["--num-samples", "64", "--references", "1:0:50000"],
        budget=1 << 20,
    )
    assert not report.ok
    assert any(i.code == "host-mem-over-budget" for i in report.issues)


def test_plan_every_path_gets_a_bound_fact():
    # The "host-mem-unprovable" rejection class is GONE: a file config
    # with no explicit streaming still proves a finite bound (from the
    # geometry ceiling when the path cannot be statted), recorded as a
    # geometry fact with no warning attached.
    report = _plan(
        [
            "--source", "file", "--input-files", "cohort.vcf",
            "--references", "1:0:50000",
        ]
    )
    assert report.ok
    assert not any(
        i.code in ("host-mem-unprovable", "host-mem-unbounded-path")
        for i in report.issues
    )
    assert report.geometry["host_peak_bytes"] > 0
    # Under a budget the only possible outcome is over-budget — the
    # unstatable path's geometry-ceiling bound exceeds 8 GiB honestly.
    report = _plan(
        [
            "--source", "file", "--input-files", "cohort.vcf",
            "--references", "1:0:50000",
        ],
        budget=8 << 30,
    )
    assert not report.ok
    assert any(i.code == "host-mem-over-budget" for i in report.issues)
    assert not any(i.code == "host-mem-unprovable" for i in report.issues)


def test_plan_streamed_file_config_is_provable():
    report = _plan(
        [
            "--source", "file", "--input-files", "cohort.vcf",
            "--num-samples", "64",
            "--references", "1:0:50000", "--stream-chunk-bytes", "1048576",
        ],
        budget=64 << 30,
    )
    assert report.ok
    assert report.geometry["host_peak_bytes"] > 0


def test_plan_proves_wire_jsonl_under_budget(tmp_path):
    # Previously the exit-2 "unprovable" class: a JSONL wire input under
    # --host-mem-budget. With the total resolver a REAL file proves a
    # tight bound from its bytes on disk and passes a modest budget.
    path = tmp_path / "cohort.jsonl"
    path.write_text('{"referenceName": "1"}\n' * 100)
    report = _plan(
        [
            "--source", "file", "--input-files", str(path),
            "--references", "1:0:50000", "--ingest", "wire",
        ],
        budget=8 << 30,
    )
    assert report.ok, [i.code for i in report.issues]
    assert report.geometry["host_peak_bytes"] <= 8 << 30


def test_plan_rejects_nonpositive_budget():
    report = _plan(
        ["--num-samples", "64", "--references", "1:0:50000"], budget=0
    )
    assert not report.ok
    assert any(i.code == "host-mem-budget" for i in report.issues)


def test_plan_budget_flag_via_cli():
    from spark_examples_tpu.check import cli

    args = ["plan", "--num-samples", "64", "--references", "1:0:50000"]
    assert cli.main(args + ["--host-mem-budget", str(8 << 30)]) == 0
    assert cli.main(args + ["--host-mem-budget", "1048576"]) == 2


# --------------------------------------------------------------------------
# Chunked checkpoint round trip: byte-identical artifacts, streaming read.
# --------------------------------------------------------------------------


def _checkpoint_records(n=300):
    from spark_examples_tpu.models.variant import VariantKey, VariantsBuilder

    records = []
    for i in range(n):
        wire = {
            "referenceName": "1",
            "variantSetId": "s",
            "id": f"v{i}",
            "start": 100 + i,
            "end": 101 + i,
            "referenceBases": "A",
            "alternateBases": ["T"],
            "info": {"AF": [f"0.{i % 9 + 1}"]},
            "calls": [
                {"callSetId": "s-0", "callSetName": "S0", "genotype": [0, 1]}
            ],
        }
        built = VariantsBuilder.build(wire)
        assert built is not None
        records.append((VariantKey("1", 100 + i), built[1]))
    return records


def test_checkpoint_chunked_round_trip_byte_identical(tmp_path):
    from spark_examples_tpu.pipeline import checkpoint as cp

    records = _checkpoint_records()
    path = tmp_path / "ckpt"
    total = cp.save_variants(str(path), [records[:150], records[150:]])
    assert total == len(records)

    # Decompressed artifact bytes == the per-record reference encoding
    # (the coalescing write buffer must not change a single byte).
    part_paths = sorted(p for p in os.listdir(path) if p.startswith("part-"))
    assert part_paths == ["part-00000.jsonl.gz", "part-00001.jsonl.gz"]
    for part, shard in zip(part_paths, [records[:150], records[150:]]):
        expected = "".join(
            json.dumps(
                {
                    "key": {"contig": k.contig, "position": k.position},
                    "variant": v.to_json(),
                }
            )
            + "\n"
            for k, v in shard
        )
        with gzip.open(path / part, "rt") as f:
            assert f.read() == expected

    # Streaming reader (fixed-size window + carry) round-trips exactly,
    # through both the part-list API and whole-checkpoint iteration.
    loaded = cp.load_variants(str(path))
    streamed = list(loaded)
    assert [k for k, _ in streamed] == [k for k, _ in records]
    assert [v.to_json() for _, v in streamed] == [
        v.to_json() for _, v in records
    ]
    first_part = loaded.partitions()[0]
    assert [k for k, _ in loaded.compute(first_part)] == [
        k for k, _ in records[:150]
    ]


def test_checkpoint_reader_window_smaller_than_line(tmp_path):
    from spark_examples_tpu.pipeline.checkpoint import _iter_jsonl_lines

    path = tmp_path / "tiny.jsonl.gz"
    rows = [{"i": i, "pad": "x" * 500} for i in range(20)]
    with gzip.open(path, "wt") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    # A window far below one encoded line exercises the carry path.
    assert list(_iter_jsonl_lines(str(path), chunk_bytes=64)) == rows


# --------------------------------------------------------------------------
# Manifest schema v2: the host_memory block.
# --------------------------------------------------------------------------


def test_manifest_v2_host_memory_block_and_validation():
    from spark_examples_tpu.obs.manifest import (
        MANIFEST_VERSION,
        build_manifest,
        validate_manifest,
    )

    assert MANIFEST_VERSION == 2
    doc = build_manifest()
    assert validate_manifest(doc) == []
    assert doc["host_memory"]["peak_rss_bytes"] > 0
    # ALWAYS a real bound: outside a driver run the block carries the
    # runtime-baseline bound, never null — and the validator REQUIRES a
    # positive int (a "no bound" manifest is a schema error now).
    from spark_examples_tpu.parallel.mesh import HOST_RUNTIME_BASELINE_BYTES

    assert doc["host_memory"]["static_bound_bytes"] >= (
        HOST_RUNTIME_BASELINE_BYTES
    )

    bad = build_manifest()
    del bad["host_memory"]
    assert any("host_memory" in e for e in validate_manifest(bad))
    bad = build_manifest()
    bad["host_memory"] = {"peak_rss_bytes": -1, "static_bound_bytes": True}
    errors = validate_manifest(bad)
    assert any("peak_rss_bytes" in e for e in errors)
    assert any("static_bound_bytes" in e for e in errors)
    bad = build_manifest()
    bad["host_memory"]["static_bound_bytes"] = None
    assert any("static_bound_bytes" in e for e in validate_manifest(bad))


def test_driver_registers_host_memory_pair():
    from spark_examples_tpu.obs.manifest import build_run_manifest
    from spark_examples_tpu.obs.metrics import (
        HOST_PEAK_RSS_BYTES,
        HOST_STATIC_BOUND_BYTES,
    )
    from spark_examples_tpu.pipeline.pca_driver import VariantsPcaDriver

    conf = PcaConf(num_samples=8, block_size=8)
    driver = VariantsPcaDriver(conf)
    peak = driver.registry.value(HOST_PEAK_RSS_BYTES)
    bound = driver.registry.value(HOST_STATIC_BOUND_BYTES)
    assert peak and peak > 0
    assert bound and bound >= HOST_RUNTIME_BASELINE_BYTES
    doc = build_run_manifest(conf=conf, registry=driver.registry)
    assert doc["host_memory"]["peak_rss_bytes"] > 0
    assert doc["host_memory"]["static_bound_bytes"] == int(bound)


# --------------------------------------------------------------------------
# The e2e parity proof: measured peak RSS <= host_peak_bytes(config) on a
# real streamed run, recorded in the run manifest — the formula is proven
# against reality, the way GI005 proves ring_traffic_bytes.
# --------------------------------------------------------------------------


def _write_sorted_vcf(path, n_sites=400, n_samples=8):
    names = "\t".join(f"S{i}" for i in range(n_samples))
    with open(path, "w") as f:
        f.write("##fileformat=VCFv4.2\n")
        f.write(
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
            + names
            + "\n"
        )
        for i in range(n_sites):
            gts = "\t".join(
                "0|1" if (i + j) % 3 == 0 else "0|0" for j in range(n_samples)
            )
            f.write(
                f"1\t{1000 + i * 10}\tv{i}\tA\tT\t.\tPASS\t"
                f"AF=0.{i % 9 + 1}\tGT\t{gts}\n"
            )


def test_e2e_streamed_peak_rss_within_static_bound(tmp_path):
    """Subprocess (fresh RSS high-water mark) streamed-file PCA run: the
    manifest must record measured peak <= the static bound, and the bound
    must be the same number ``conf_host_peak_bytes`` computes."""
    vcf = tmp_path / "cohort.vcf"
    _write_sorted_vcf(str(vcf))
    manifest_path = tmp_path / "manifest.json"
    env = dict(os.environ)
    env.update(
        {
            "JAX_PLATFORMS": "cpu",
            # Images pre-registering an accelerator PJRT plugin override
            # JAX_PLATFORMS at interpreter start; the package's own
            # jax.config override (parallel/mesh.py) still wins — without
            # it this subprocess grabs the real backend, whose runtime
            # maps gigabytes of host RSS into the measurement.
            "SPARK_EXAMPLES_TPU_PLATFORM": "cpu",
            "SPARK_EXAMPLES_TPU_NO_CACHE": "1",
        }
    )
    chunk = 4096
    proc = subprocess.run(
        [
            sys.executable, "-m", "spark_examples_tpu", "variants-pca",
            "--source", "file", "--input-files", str(vcf),
            "--all-references", "--stream-chunk-bytes", str(chunk),
            "--ingest-workers", "2", "--block-size", "64",
            "--mesh-shape", "1,1",  # pin the data axis: the parity
            # assertion below must not depend on the host's device count
            "--metrics-json", str(manifest_path),
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd=_REPO_ROOT,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    doc = json.loads(manifest_path.read_text())
    from spark_examples_tpu.obs.manifest import validate_manifest

    assert validate_manifest(doc) == []
    hm = doc["host_memory"]
    assert hm["peak_rss_bytes"] and hm["peak_rss_bytes"] > 0
    assert hm["static_bound_bytes"] and hm["static_bound_bytes"] > 0
    assert hm["peak_rss_bytes"] <= hm["static_bound_bytes"], (
        "measured peak RSS exceeds the static host-memory bound: "
        f"{hm['peak_rss_bytes']} > {hm['static_bound_bytes']}"
    )
    conf = PcaConf(
        source="file",
        input_files=[str(vcf)],
        variant_set_id=["cohort"],
        stream_chunk_bytes=chunk,
        ingest_workers=2,
        block_size=64,
        mesh_shape="1,1",
    )
    # The driver resolves the bound against the DISCOVERED cohort (8
    # samples from the header), not the flag default.
    expected = conf_host_peak_bytes(conf, device_count=1, num_samples=8)
    assert hm["static_bound_bytes"] == expected

"""A REAL 2-process ``jax.distributed`` run (the reference's cluster-spanning
capability, ``/root/reference/README.md:64-104``; ``GenomicsConf.scala:50-57``).

These tests spawn actual coordinator-connected subprocesses — no mocking, no
single-process simulation — and assert the multi-controller code paths
(``parallel/mesh.py:host_value``/``local_shard``, the replicated finalize in
``ops/devicegen.py``) execute and agree with the host oracle in EVERY
process.
"""

import json
import subprocess
import sys

from spark_examples_tpu.parallel.multihost import verify_multihost


def test_two_process_distributed_run():
    """Phase 1: (a) data-parallel device ingest over the global 2×4-device
    mesh with the cross-slice finalize reduce, (b) ring ingest over the
    samples-only mesh whose ppermute hops cross the process boundary, and
    (c) the hierarchical two-level schedule on that same ring (host factor
    2) — all Gramians == host oracle in both processes. Phase 2: the fleet
    rehearsal — host-sharded ingest over four contigs (each process reads
    ~1/2 of the solo bases), PC rows byte-identical to the solo oracle,
    per-host conformance bounds hold, and the per-process flight-recorder
    segments merge into one valid Chrome trace."""
    report = verify_multihost(num_processes=2, local_devices=4)
    assert report["gramian_ok"], json.dumps(report, indent=2)
    assert report["ring_gramian_ok"], json.dumps(report, indent=2)
    assert report["hier_gramian_ok"], json.dumps(report, indent=2)
    # The global results must actually span both processes — otherwise this
    # test would silently degrade into a single-controller run.
    assert report["result_spans_processes"], json.dumps(report, indent=2)
    for child in report["children"]:
        assert child["global_devices"] == 8, child
        assert child["local_devices"] == 4, child
        assert child["hier_schedule_kind"] == "hier", child
    assert report["cli_ok"], json.dumps(report, indent=2)
    assert report["cli_outputs_identical"], json.dumps(report, indent=2)
    assert report["cli_pc_lines"] == 24, json.dumps(report, indent=2)
    assert report["fleet_host_sharded"], json.dumps(report, indent=2)
    assert report["fleet_io_ok"], json.dumps(report, indent=2)
    # Two processes over four equal windows: the split is exactly half —
    # per-process ingest strictly below the solo total.
    bases = report["fleet_io_reference_bases"]
    assert sum(bases["per_process"]) == bases["solo"]
    assert all(0 < b < bases["solo"] for b in bases["per_process"])
    assert report["fleet_conformance_ok"], json.dumps(report, indent=2)
    assert report["fleet_trace_ok"], json.dumps(report, indent=2)


def test_three_process_distributed_run_non_power_of_two():
    """Three coordinator-connected processes, 2 devices each — a 6-device
    global fleet. Non-power-of-two process counts exercise the shapes the
    2×4 run cannot: the data-axis round-robin hands UNEVEN dispatch counts
    to the slices (7 grid groups over 6 slices), the ring exchange runs
    6 ppermute hops with 4 of every 6 crossing a process boundary, and the
    hier schedule factors the samples axis 3×2. The fleet rehearsal's
    4-contig split over 3 hosts is uneven by construction ([2,1,1]) — the
    1/H+overshoot bound and the exact partition-sum still hold."""
    report = verify_multihost(num_processes=3, local_devices=2)
    assert report["gramian_ok"], json.dumps(report, indent=2)
    assert report["ring_gramian_ok"], json.dumps(report, indent=2)
    assert report["hier_gramian_ok"], json.dumps(report, indent=2)
    assert report["result_spans_processes"], json.dumps(report, indent=2)
    for child in report["children"]:
        assert child["global_devices"] == 6, child
        assert child["local_devices"] == 2, child
    assert report["cli_ok"], json.dumps(report, indent=2)
    assert report["cli_outputs_identical"], json.dumps(report, indent=2)
    assert report["cli_pc_lines"] == 24, json.dumps(report, indent=2)
    assert report["fleet_host_sharded"], json.dumps(report, indent=2)
    assert report["fleet_io_ok"], json.dumps(report, indent=2)
    assert report["fleet_conformance_ok"], json.dumps(report, indent=2)
    assert report["fleet_trace_ok"], json.dumps(report, indent=2)


def test_child_cli_exits_nonzero_on_bad_coordinator():
    """A child whose coordinator is unreachable must fail loudly within its
    initialization timeout — not hang, not fall back to single-process."""
    from spark_examples_tpu.parallel.multihost import _child_env

    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "from spark_examples_tpu.parallel.mesh import distributed_init\n"
            # Port 1 is never listening; a non-coordinator process (id 1)
            # must give up after the timeout rather than retry forever.
            "distributed_init('127.0.0.1:1', 2, 1, initialization_timeout=5)",
        ],
        env=_child_env(1),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode != 0


def test_partial_cluster_flags_rejected():
    """Partially-specified cluster flags must raise, not silently fall back
    to a single-process run over 1/N of the fleet."""
    import pytest

    from spark_examples_tpu.parallel.mesh import distributed_init

    with pytest.raises(ValueError, match="num-processes"):
        distributed_init("127.0.0.1:1", None, 0)

"""murmur3 x64 128 vectors (public MurmurHash3/Guava test vectors) and the
variant-key protocol of ``VariantsPca.scala:71-86``."""

from spark_examples_tpu.utils.murmur3 import murmur3_x64_128, murmur3_x64_128_hex
from spark_examples_tpu.models.variant import Variant


def test_empty_input_is_zero():
    # MurmurHash3_x64_128("", seed=0) == 0 (canonical vector).
    assert murmur3_x64_128(b"") == b"\x00" * 16


def test_hello_vector():
    # MurmurHash3_x64_128("hello", 0) = h1=0xcbd8a7b341bd9b02, h2=0x5b1e906a48ae1d19;
    # Guava HashCode.toString() emits h1 LE then h2 LE as lowercase hex.
    assert murmur3_x64_128_hex(b"hello") == "029bbd41b3a7d8cb191dae486a901e5b"


def test_tail_lengths_are_stable():
    # Exercise every tail length 0..16; self-consistency (regression pin).
    digests = {murmur3_x64_128_hex(b"a" * n) for n in range(17)}
    assert len(digests) == 17


def test_seed_changes_digest():
    assert murmur3_x64_128(b"abc", 0) != murmur3_x64_128(b"abc", 1)


def _mk_variant(**kw):
    base = dict(
        contig="17",
        id="v1",
        names=None,
        start=41196311,
        end=41196312,
        reference_bases="A",
        alternate_bases=("G",),
        info={},
        created=0,
        variant_set_id="vs",
        calls=None,
    )
    base.update(kw)
    return Variant(**base)


def test_variant_key_depends_on_all_fields():
    v = _mk_variant()
    assert v.variant_key() != _mk_variant(contig="18").variant_key()
    assert v.variant_key() != _mk_variant(start=41196312).variant_key()
    assert v.variant_key() != _mk_variant(end=41196313).variant_key()
    assert v.variant_key() != _mk_variant(reference_bases="C").variant_key()
    assert v.variant_key() != _mk_variant(alternate_bases=("T",)).variant_key()


def test_variant_key_joins_multiallelic_alternates():
    # alternateBases are concatenated with no separator (VariantsPca.scala:72-73).
    joined = _mk_variant(alternate_bases=("G", "T")).variant_key()
    single = _mk_variant(alternate_bases=("GT",)).variant_key()
    assert joined == single


def test_variant_key_none_alternates_is_empty_string():
    assert (
        _mk_variant(alternate_bases=None).variant_key()
        == _mk_variant(alternate_bases=()).variant_key()
    )


def test_variant_key_is_32_hex_chars():
    key = _mk_variant().variant_key()
    assert len(key) == 32
    assert all(c in "0123456789abcdef" for c in key)

"""Fused batch execution (PR 19): the stacked-jobs kernel's byte parity
with serial accumulation (group sizes 1/2/cap, ragged lanes, interleaved
feeds), ``preflight_fused``'s refusal matrix, the cost-ordered queue
(deterministic SJF pops, deadline slack, the age-cap starvation guard,
the linger anchor), steal-targeting-by-cost, and the daemon end-to-end:
one device program per group, byte-identical results, fused-vs-serial
dispatch counters, and the over-HBM fused group's structured 413."""

import time

import numpy as np
import pytest

from spark_examples_tpu.ops.batched import (
    FusedIneligible,
    StackedJobsAccumulator,
    max_fused_jobs,
)
from spark_examples_tpu.serve.daemon import (
    MEM_LIMIT_CODES,
    PcaService,
    _parse_job_flags,
)
from spark_examples_tpu.serve.protocol import parse_request, request_doc
from spark_examples_tpu.serve.queue import (
    SMALL_CLASS,
    BoundedJobQueue,
    Job,
)

TINY_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]


# ------------------------------------------------- stacked kernel parity


def _serial_gramian(rows_per_lane, num_samples, block_size):
    """Each lane through its own serial dense accumulator — the byte
    reference the stacked program must reproduce exactly."""
    import jax

    from spark_examples_tpu.ops.gramian import GramianAccumulator

    slices = []
    for rows in rows_per_lane:
        acc = GramianAccumulator(
            num_samples=num_samples, mesh=None, block_size=block_size
        )
        if len(rows):
            acc.add_rows(rows)
        slices.append(np.asarray(jax.device_get(acc.finalize_device())))
    return slices


def _lane_rows(num_lanes, num_samples, seed=11):
    """Ragged {0,1} row streams: lengths straddle block boundaries so
    every lane exercises a zero-padded partial tail, and lane lengths
    differ so the stacked drain pads finished lanes with zero operands."""
    rng = np.random.default_rng(seed)
    lengths = [3 + 4 * lane + lane % 2 for lane in range(num_lanes)]
    return [
        rng.integers(0, 2, size=(n, num_samples)).astype(np.uint8)
        for n in lengths
    ]


def _cap_device_bytes(num_samples, cap):
    """A synthetic device budget whose ``max_fused_jobs`` is exactly
    ``cap`` — the parity matrix's "max" row is tied to the real cap
    formula instead of a hand-picked constant."""
    from spark_examples_tpu.ops.gramian import _DENSE_BUFFERS, DENSE_HBM_FRACTION

    per_job = _DENSE_BUFFERS * num_samples**2 * 4
    return int(cap * per_job / DENSE_HBM_FRACTION) + 1


@pytest.mark.parametrize("group", ["one", "two", "max"])
def test_stacked_parity_matrix(group):
    """Group sizes 1, 2, and the HBM cap: every lane's slice of the
    stacked ``(K, N, N)`` accumulator is byte-identical to its serial
    run, with a small block size forcing ragged multi-step drains."""
    import jax

    num_samples, block_size = 16, 4
    if group == "max":
        device_bytes = _cap_device_bytes(num_samples, 5)
        k = max_fused_jobs(num_samples, device_bytes=device_bytes)
        assert k == 5
    else:
        k = {"one": 1, "two": 2}[group]
    rows_per_lane = _lane_rows(k, num_samples)
    stacked = StackedJobsAccumulator(
        num_jobs=k, num_samples=num_samples, block_size=block_size
    )
    # Interleave feeds in uneven chunks: lanes hit block boundaries at
    # different steps, so the lockstep drain queues pending operands.
    cursors = [0] * k
    chunk = 3
    while any(cursors[i] < len(rows_per_lane[i]) for i in range(k)):
        for lane in range(k):
            rows = rows_per_lane[lane]
            if cursors[lane] < len(rows):
                stacked.add_rows(
                    lane, rows[cursors[lane] : cursors[lane] + chunk]
                )
                cursors[lane] += chunk
    for lane in range(k):
        stacked.finish_lane(lane)
    stacked.finalize()
    serial = _serial_gramian(rows_per_lane, num_samples, block_size)
    for lane in range(k):
        fused = np.asarray(jax.device_get(stacked.job_slice(lane)))
        assert fused.dtype == serial[lane].dtype
        assert fused.tobytes() == serial[lane].tobytes(), (
            f"lane {lane} of {k} diverged from its serial run"
        )
    # Lockstep accounting: the stacked program stepped once per LONGEST
    # lane's block count, not once per lane-block.
    longest_blocks = max(
        -(-len(rows) // block_size) for rows in rows_per_lane
    )
    assert stacked.steps == longest_blocks


def test_stacked_ragged_last_group_with_empty_lane():
    """The ragged extreme: one lane contributes nothing at all (its
    slice is the zero matrix, same as a serial run over zero rows) while
    the others drain multi-block streams over its zero-operand pads."""
    import jax

    num_samples, block_size = 16, 4
    rows_per_lane = [
        np.zeros((0, num_samples), dtype=np.uint8),
        _lane_rows(1, num_samples, seed=3)[0][:5],
        _lane_rows(1, num_samples, seed=5)[0][:3] .repeat(4, axis=0)[:11],
    ]
    stacked = StackedJobsAccumulator(
        num_jobs=3, num_samples=num_samples, block_size=block_size
    )
    stacked.finish_lane(0)  # empty lane finishes before any feed
    stacked.add_rows(1, rows_per_lane[1])
    stacked.add_rows(2, rows_per_lane[2])
    stacked.finish_lane(1)
    stacked.finish_lane(2)
    stacked.finalize()
    serial = _serial_gramian(rows_per_lane, num_samples, block_size)
    for lane in range(3):
        fused = np.asarray(jax.device_get(stacked.job_slice(lane)))
        assert fused.tobytes() == serial[lane].tobytes()
    assert not np.asarray(jax.device_get(stacked.job_slice(0))).any()


def test_stacked_refuses_count_valued_rows():
    """The stacked contract covers {0,1} has-variation rows only; a
    count-valued block (same-set join) must refuse, not approximate."""
    stacked = StackedJobsAccumulator(num_jobs=2, num_samples=16, block_size=4)
    counts = np.full((4, 16), 2, dtype=np.uint8)
    with pytest.raises(FusedIneligible, match="count-valued"):
        stacked.add_rows(0, counts)


# ------------------------------------------------------ preflight refusals


def _conf(flags, kind="pca"):
    return _parse_job_flags(["--pca-backend", "tpu", *flags], kind=kind)


def test_preflight_refuses_mixed_kind_group():
    from spark_examples_tpu.pipeline.fused import preflight_fused

    confs = [_conf(TINY_FLAGS), _conf(TINY_FLAGS)]
    with pytest.raises(FusedIneligible, match="mixed-kind"):
        preflight_fused(confs, ["pca", "similarity"])
    with pytest.raises(FusedIneligible, match="no stacked device program"):
        preflight_fused(confs, ["grm", "grm"])


def test_preflight_refuses_mismatched_geometry():
    from spark_examples_tpu.pipeline.fused import preflight_fused

    narrow = _conf(TINY_FLAGS)
    wide = _conf(["--num-samples", "16", "--references", "1:0:50000"])
    with pytest.raises(FusedIneligible, match="cohort width"):
        preflight_fused([narrow, wide], ["pca", "pca"])


def test_preflight_accepts_then_caps_group_size():
    """An eligible pair passes (returns K); the same pair against a toy
    device budget whose cap is 1 refuses with the cap named."""
    from spark_examples_tpu.pipeline.fused import preflight_fused

    confs = [_conf(TINY_FLAGS), _conf(TINY_FLAGS)]
    assert preflight_fused(confs, ["pca", "pca"]) == 2
    tiny_budget = _cap_device_bytes(8, 1)
    with pytest.raises(FusedIneligible, match="max_fused_jobs=1"):
        preflight_fused(confs, ["pca", "pca"], device_bytes=tiny_budget)


# ------------------------------------------------------ cost-ordered queue


def _qjob(job_id, estimate=None, deadline_unix=None, queued_ago=None):
    job = Job(
        id=job_id,
        request=parse_request(request_doc(TINY_FLAGS)),
        conf=None,
        job_class=SMALL_CLASS,
        submitted_unix=time.time(),
        deadline_unix=deadline_unix,
        cost_estimate_seconds=estimate,
    )
    if queued_ago is not None:
        # Backdate the first-admission stamp (put() only stamps None):
        # age-dependent behavior tests stay sleep-free and deterministic.
        job.enqueued_monotonic = time.monotonic() - queued_ago
    return job


def test_cost_ordered_pop_is_deterministic():
    """SJF within the lane: cheapest estimate first, missing estimates
    last, equal keys in admission order — twice, identically."""
    for _ in range(2):
        q = BoundedJobQueue(ordering="cost")
        q.put(_qjob("slow", estimate=40.0))
        q.put(_qjob("none-1"))  # no prediction stamped -> sorts last
        q.put(_qjob("fast", estimate=0.2))
        q.put(_qjob("mid-1", estimate=5.0))
        q.put(_qjob("mid-2", estimate=5.0))  # tie -> admission order
        q.put(_qjob("none-2"))
        order = [q.pop(timeout=1).id for _ in range(6)]
        assert order == ["fast", "mid-1", "mid-2", "slow", "none-1", "none-2"]


def test_fifo_ordering_preserves_admission_order():
    q = BoundedJobQueue(ordering="fifo")
    q.put(_qjob("first", estimate=40.0))
    q.put(_qjob("second", estimate=0.1))
    assert [q.pop(timeout=1).id for _ in range(2)] == ["first", "second"]


def test_age_cap_starvation_guard():
    """A job queued past the age cap outranks every estimate-ordered
    peer — FIFO among the aged — so SJF cannot park an expensive job
    behind an endless stream of cheap arrivals."""
    q = BoundedJobQueue(ordering="cost", age_cap_seconds=5.0)
    q.put(_qjob("aged-expensive", estimate=100.0, queued_ago=6.0))
    q.put(_qjob("aged-older", estimate=50.0, queued_ago=8.0))
    q.put(_qjob("fresh-cheap", estimate=0.1))
    order = [q.pop(timeout=1).id for _ in range(3)]
    # Both aged jobs first, in their own admission order (enqueue_seq:
    # aged-expensive was admitted first), then the cost-ordered rest.
    assert order == ["aged-expensive", "aged-older", "fresh-cheap"]


def test_deadline_slack_orders_ahead_of_estimates():
    """Deadline-carrying jobs sort by slack (deadline - now - estimate)
    ahead of the estimate tier: the job closest to breaking its promise
    runs first."""
    now = time.time()
    q = BoundedJobQueue(ordering="cost")
    q.put(_qjob("cheap", estimate=0.1))
    q.put(_qjob("roomy-deadline", estimate=1.0, deadline_unix=now + 500))
    q.put(_qjob("tight-deadline", estimate=1.0, deadline_unix=now + 50))
    order = [q.pop(timeout=1).id for _ in range(3)]
    assert order == ["tight-deadline", "roomy-deadline", "cheap"]


def test_pop_batch_linger_anchor_already_spent():
    """Satellite regression: the linger clock anchors at the FIRST
    member's enqueue time. A head job that already waited out the window
    in the queue dispatches with zero added wait, regardless of the
    linger the pop call declares."""
    q = BoundedJobQueue()
    stale = _qjob("stale", queued_ago=10.0)
    stale.batch_key = "shared"
    q.put(stale)
    t0 = time.monotonic()
    batch = q.pop_batch(timeout=1, linger_seconds=5.0, max_batch=4)
    waited = time.monotonic() - t0
    assert [job.id for job in batch] == ["stale"]
    assert waited < 1.0, f"pop re-spent the linger budget: {waited:.2f}s"
    # Control arm: a FRESH head job does linger (bounded by the window).
    fresh = _qjob("fresh")
    fresh.batch_key = "shared"
    q.put(fresh)
    t0 = time.monotonic()
    batch = q.pop_batch(timeout=1, linger_seconds=0.2, max_batch=4)
    waited = time.monotonic() - t0
    assert [job.id for job in batch] == ["fresh"]
    assert waited >= 0.15, f"fresh head did not linger: {waited:.3f}s"


# -------------------------------------------------------- steal by cost


def test_steal_claims_highest_cost_first(tmp_path, monkeypatch):
    """A survivor replica's steal scan claims a dead owner's orphans in
    descending journaled-estimate order (cost unknown last, file order
    among ties): the first, least-contended claims recover the most
    stranded seconds."""
    from spark_examples_tpu.serve.journal import (
        JobJournal,
        LeaseStore,
        journal_path,
    )

    run_dir = str(tmp_path / "rd")
    claimed = []
    monkeypatch.setattr(
        PcaService, "_steal_one", lambda self, record: claimed.append(
            record.job_id
        )
    )
    survivor = PcaService(
        run_dir=run_dir,
        replica_id="b",
        small_slices=0,
        lease_seconds=1.0,
        lease_grace_seconds=0.1,
        steal_interval_seconds=3600.0,  # scan only when the test calls it
        persistent_cache=False,
    ).start()
    try:
        # Replica "a" dies AFTER the survivor is up (planting the state
        # first would let the survivor's startup replay adopt it): a
        # stale heartbeat plus three accepted jobs whose leases expire
        # immediately, with distinct journaled estimates.
        LeaseStore(
            run_dir, "a", lease_seconds=1.0, clock=lambda: time.time() - 60.0
        ).heartbeat()
        journal = JobJournal(journal_path(run_dir), replica="a")
        stale_store = LeaseStore(
            run_dir, "a", lease_seconds=0.01, grace_seconds=0.0
        )
        for job_id, cost in (
            ("job-a-000001", {"predicted_seconds": 2.0}),
            ("job-a-000002", None),  # pre-cost journal record
            ("job-a-000003", {"predicted_seconds": 90.0}),
        ):
            journal.accepted(
                job_id,
                request_doc(TINY_FLAGS),
                SMALL_CLASS,
                time.time(),
                None,
                cost=cost,
            )
            epoch = stale_store.claim(job_id)
            journal.lease(job_id, epoch)
        journal.close()
        # Leases (ttl 10 ms) must be expired PAST the survivor's grace
        # window (0.1 s) before the scan may treat them as orphaned.
        time.sleep(0.25)
        survivor._steal_expired()
    finally:
        survivor.stop(timeout=30)
    assert claimed == ["job-a-000003", "job-a-000001", "job-a-000002"]


# ----------------------------------------------------------- daemon e2e


def test_service_fused_group_byte_identical_and_counted(tmp_path):
    """Two identical small jobs inside the linger window ride ONE
    stacked device program (fused_size 2 on both envelopes); a singleton
    resubmit runs serially; all three emit byte-identical result rows;
    the dispatch counters partition fused vs serial."""
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        small_slices=0,
        batch_max_jobs=2,
        batch_linger_seconds=2.0,
    ).start()
    try:
        ids = []
        for _ in range(2):
            status, body = service.submit(request_doc(TINY_FLAGS))
            assert status == 202, body
            ids.append(body["job"]["id"])
        fused = [_wait_done(service, jid) for jid in ids]
        status, body = service.submit(request_doc(TINY_FLAGS))
        assert status == 202, body
        serial = _wait_done(service, body["job"]["id"])
        dispatch = service.fleet_stats()["dispatch"]
    finally:
        service.stop(timeout=60)
    for job in fused:
        assert job["fused_size"] == 2, job
    assert serial["fused_size"] == 1
    reference = serial["result"]["pc_lines"]
    for job in fused:
        assert job["result"]["pc_lines"] == reference
    assert dispatch["fused_groups"] == 1
    assert dispatch["fused_jobs"] == 2
    assert dispatch["serial_jobs"] == 1


def _wait_done(service, job_id, timeout=300.0):
    deadline = time.time() + timeout
    while True:
        _, doc = service.job_status(job_id)
        job = doc["job"]
        if job["status"] in ("done", "failed", "cancelled"):
            assert job["status"] == "done", job
            return job
        assert time.time() < deadline, f"timed out waiting on {job_id}"
        time.sleep(0.02)


def test_fused_over_hbm_group_is_413(tmp_path):
    """``--fused-jobs`` rides admission as a plan directive: a group
    whose K× stacked charge exceeds the HBM budget is a structured 413
    naming the cohort's fused ceiling, and the code is a declared
    memory-limit code (the 400-vs-413 contract)."""
    assert "fused-group-exceeds-hbm" in MEM_LIMIT_CODES
    service = PcaService(run_dir=str(tmp_path / "serve"), small_slices=0)
    try:
        status, body = service.submit(
            request_doc(
                [
                    "--num-samples",
                    "20000",
                    "--references",
                    "1:0:50000",
                    "--pca-backend",
                    "tpu",
                    "--fused-jobs",
                    "12",
                ]
            )
        )
    finally:
        service.stop(timeout=30)
    assert status == 413
    assert body["error"]["code"] == "plan-rejected"
    codes = [i["code"] for i in body["plan"]["issues"]]
    assert "fused-group-exceeds-hbm" in codes
    geometry = body["plan"]["geometry"]
    assert geometry["fused_jobs"] == 12
    assert 1 <= geometry["max_fused_jobs"] < 12

"""Public composable API (the variants_pca.py:19-152 decomposition)."""

import numpy as np

from spark_examples_tpu import api
from spark_examples_tpu.config import PcaConf
from spark_examples_tpu.pipeline.pca_driver import VariantsPcaDriver
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource


def _request(start, end):
    return {
        "variantSetIds": ["vs"],
        "referenceName": "17",
        "start": start,
        "end": end,
    }


def test_api_doctest_example():
    import doctest

    results = doctest.testmod(api)
    assert results.failed == 0


def test_api_stages_match_driver():
    """prepare → similarity → center → pca equals the driver pipeline."""
    source = SyntheticGenomicsSource(num_samples=15, seed=9)
    callsets = source.search_callsets(["vs"])
    id_to_index = {c["id"]: i for i, c in enumerate(callsets)}

    variants = list(source.client().search_variants(_request(0, 30000)))
    calls = list(
        api.prepare_call_data(iter(variants), id_to_index, use_names=False)
    )
    assert calls
    S = api.calculate_similarity_matrix(iter(calls), 15, block_size=32)
    B = api.center_matrix(S)
    components = api.perform_pca(B, num_pc=2)
    assert components.shape == (15, 2)

    conf = PcaConf()
    conf.references = "17:0:30000"
    conf.variant_set_id = ["vs"]
    conf.num_samples = 15
    conf.seed = 9
    conf.block_size = 32
    driver = VariantsPcaDriver(conf, source)
    S_driver = driver.get_similarity_matrix(driver.iter_calls(driver.get_data()))
    np.testing.assert_array_equal(np.asarray(S), np.asarray(S_driver))
    result = driver.compute_pca(S_driver)
    driver_components = np.array([pcs for _, pcs in result])
    signs = np.sign((components * driver_components).sum(axis=0))
    signs[signs == 0] = 1
    np.testing.assert_allclose(components, driver_components * signs, atol=5e-3)


def test_center_matrix_exact_past_f32_range():
    """center_matrix keeps integer counts past 2^24 exact (the driver's f64
    centering policy), instead of truncating them with an up-front f32 cast."""
    import jax
    import jax.numpy as jnp

    from spark_examples_tpu.ops.centering import gower_center

    rng = np.random.default_rng(17)
    base = rng.integers(0, 50, size=(6, 6))
    # Symmetric, all entries odd and > 2^24: none are f32-representable, so
    # a premature f32 cast visibly perturbs the centered result.
    S = ((base + base.T) * 2 + (1 << 25) + 1).astype(np.int64)

    centered = np.asarray(api.center_matrix(S))
    assert centered.dtype == np.float32

    # Bit-match the driver's dense centering path
    # (pipeline/pca_driver.py:compute_pca): f64 arithmetic under x64, f32 out.
    with jax.enable_x64(True):
        driver_centered = gower_center(jnp.asarray(S))
    driver_centered = np.asarray(driver_centered.astype(jnp.float32))
    np.testing.assert_array_equal(centered, driver_centered)

    # And match the literal f64 host oracle (rounded to f32 at the end).
    Sf = S.astype(np.float64)
    oracle = (
        Sf
        - Sf.mean(axis=1, keepdims=True)
        - Sf.mean(axis=0, keepdims=True)
        + Sf.mean()
    ).astype(np.float32)
    np.testing.assert_array_equal(centered, oracle)

    # The pre-fix behavior (force-cast to f32 before centering) is measurably
    # different on this input — the test would catch a regression.
    truncated = np.asarray(gower_center(jnp.asarray(S, dtype=jnp.float32)))
    assert not np.array_equal(centered, truncated)


def test_package_version_matches_pyproject():
    """__version__ and pyproject agree (it drifted once)."""
    import os
    import re

    import spark_examples_tpu

    root = os.path.dirname(os.path.dirname(spark_examples_tpu.__file__))
    with open(os.path.join(root, "pyproject.toml"), "rb") as f:
        text = f.read().decode("utf-8")
    try:  # tomllib is 3.11+; the seed image runs 3.10
        import tomllib

        declared = tomllib.loads(text)["project"]["version"]
    except ModuleNotFoundError:
        match = re.search(r'^version\s*=\s*"([^"]+)"', text, re.MULTILINE)
        assert match, "pyproject.toml has no version line"
        declared = match.group(1)
    assert spark_examples_tpu.__version__ == declared


def test_api_pca_entrypoint():
    lines = api.pca(
        [
            "--references", "17:0:20000",
            "--variant-set-id", "vs",
            "--num-samples", "10",
            "--seed", "3",
            "--block-size", "32",
        ]
    )
    assert len(lines) == 10

"""Public composable API (the variants_pca.py:19-152 decomposition)."""

import numpy as np

from spark_examples_tpu import api
from spark_examples_tpu.config import PcaConf
from spark_examples_tpu.pipeline.pca_driver import VariantsPcaDriver
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource


def _request(start, end):
    return {
        "variantSetIds": ["vs"],
        "referenceName": "17",
        "start": start,
        "end": end,
    }


def test_api_doctest_example():
    import doctest

    results = doctest.testmod(api)
    assert results.failed == 0


def test_api_stages_match_driver():
    """prepare → similarity → center → pca equals the driver pipeline."""
    source = SyntheticGenomicsSource(num_samples=15, seed=9)
    callsets = source.search_callsets(["vs"])
    id_to_index = {c["id"]: i for i, c in enumerate(callsets)}

    variants = list(source.client().search_variants(_request(0, 30000)))
    calls = list(
        api.prepare_call_data(iter(variants), id_to_index, use_names=False)
    )
    assert calls
    S = api.calculate_similarity_matrix(iter(calls), 15, block_size=32)
    B = api.center_matrix(S)
    components = api.perform_pca(B, num_pc=2)
    assert components.shape == (15, 2)

    conf = PcaConf()
    conf.references = "17:0:30000"
    conf.variant_set_id = ["vs"]
    conf.num_samples = 15
    conf.seed = 9
    conf.block_size = 32
    driver = VariantsPcaDriver(conf, source)
    S_driver = driver.get_similarity_matrix(driver.iter_calls(driver.get_data()))
    np.testing.assert_array_equal(np.asarray(S), np.asarray(S_driver))
    result = driver.compute_pca(S_driver)
    driver_components = np.array([pcs for _, pcs in result])
    signs = np.sign((components * driver_components).sum(axis=0))
    signs[signs == 0] = 1
    np.testing.assert_allclose(components, driver_components * signs, atol=5e-3)


def test_api_pca_entrypoint():
    lines = api.pca(
        [
            "--references", "17:0:20000",
            "--variant-set-id", "vs",
            "--num-samples", "10",
            "--seed", "3",
            "--block-size", "32",
        ]
    )
    assert len(lines) == 10

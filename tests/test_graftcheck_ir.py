"""The graftcheck IR auditor (``check/ir.py``) and lock-order analysis
(``check/lockgraph.py``): golden jaxpr audits of the shipped kernels across
mesh shapes (aligned + ragged cohorts), deliberately-broken kernel fixtures
that each GI rule must flag, the lock-graph's clean-tree gate, broken lock
fixtures per GL rule, DOT artifact emission, and CLI exit codes.

Broken ring kernels are built inline with the same shard_map/AbstractMesh
machinery as the real ``ops/gramian.py:build_sharded_update``, each with
exactly one contract defect, so the audit's discrimination (not just its
acceptance) is pinned.
"""

import os
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from spark_examples_tpu.check.ir import (
    DonationSite,
    KernelSpec,
    audit_kernel,
    counts_kernel_spec,
    default_specs,
    dense_kernel_spec,
    devicegen_ring_spec,
    gc005_justified_functions,
    peak_live_bytes,
    ring_kernel_spec,
    run_audit,
)
from spark_examples_tpu.check.lockgraph import (
    build_lock_graph,
    default_lock_paths,
)
from spark_examples_tpu.ops.gramian import _unpack_bits
from spark_examples_tpu.parallel.mesh import (
    DATA_AXIS,
    SAMPLES_AXIS,
    padded_cohort,
    ring_traffic_bytes,
)
from spark_examples_tpu.utils.compat import shard_map

_PACKAGE_DIR = os.path.dirname(
    os.path.abspath(__import__("spark_examples_tpu").__file__)
)


def _rule_ids(audit):
    return sorted({f.rule_id for f in audit.findings})


# --------------------------------------------------------------------------
# Golden audits: the shipped kernels must prove every contract.
# --------------------------------------------------------------------------


@pytest.mark.parametrize("data,samples", [(1, 2), (1, 4), (2, 2)])
@pytest.mark.parametrize("num_samples", [64, 100])  # aligned + ragged
@pytest.mark.parametrize("pack", [True, False])
def test_ring_kernel_audits_clean(data, samples, num_samples, pack):
    audit = audit_kernel(
        ring_kernel_spec(data, samples, num_samples, 8, pack)
    )
    assert audit.ok, "\n".join(f.format() for f in audit.findings)
    assert audit.facts["permute_executions"] == samples - 1
    assert audit.facts["ring_overlap_independent"]
    assert not audit.facts["accumulator_donated"]
    assert audit.facts["gc005_disable_present"]
    assert audit.facts["f64_free"]
    # The jaxpr-derived traffic equals the ONE audited formula exactly.
    padded = padded_cohort(num_samples, samples, pack=pack)
    assert audit.facts["ring_bytes_jaxpr"] == ring_traffic_bytes(
        data * 8, samples, padded // samples, pack
    )
    assert audit.facts["peak_live_bytes"] > 0
    assert audit.facts["liveness_scope"] == "per-device"


@pytest.mark.parametrize("data", [1, 2])
def test_dense_kernels_audit_clean(data):
    for spec in (
        dense_kernel_spec(data, 64, 8),
        counts_kernel_spec(data, 64, 8),
    ):
        audit = audit_kernel(spec)
        assert audit.ok, "\n".join(f.format() for f in audit.findings)
        assert not audit.facts["accumulator_donated"]
        assert audit.facts["gc005_disable_present"]


@pytest.mark.parametrize("data,samples", [(1, 2), (1, 4), (2, 2)])
def test_devicegen_ring_audits_clean(data, samples):
    K, B = 2, 8
    audit = audit_kernel(devicegen_ring_spec(data, samples, 64, B, K))
    assert audit.ok, "\n".join(f.format() for f in audit.findings)
    # K ring passes per dispatch: K x (S-1) permutes, and the traced bytes
    # equal the accumulator's own per-dispatch accounting
    # (DeviceGenRingGramianAccumulator.ring_bytes_total's formula).
    assert audit.facts["permute_executions"] == K * (samples - 1)
    padded = padded_cohort(64, samples, pack=True)
    assert audit.facts["ring_bytes_jaxpr"] == ring_traffic_bytes(
        data * K * B, samples, padded // samples, True
    )


def test_default_matrix_clean_and_device_free():
    before = len(jax.live_arrays())
    report = run_audit(default_specs(num_samples=32, ragged_samples=52,
                                     block_size=8, meshes=((1, 2), (2, 2))))
    assert report.ok, report.format()
    assert len(report.audits) >= 8
    # Pure tracing: no device buffer outlives the audit.
    assert len(jax.live_arrays()) == before


def test_report_json_schema():
    import json

    report = run_audit([ring_kernel_spec(1, 2, 32, 4, True)])
    doc = json.loads(report.to_json())
    assert doc["tool"] == "graftcheck-ir"
    assert doc["ok"] is True
    assert doc["kernel_count"] == 1
    [kernel] = doc["kernels"]
    assert kernel["facts"]["ring_bytes_jaxpr"] == kernel["facts"][
        "ring_bytes_formula"
    ]


def test_gc005_cross_check_reads_the_real_disables():
    names = gc005_justified_functions(
        os.path.join(_PACKAGE_DIR, "ops", "gramian.py")
    )
    assert {"_dense_update", "_dense_update_counts", "update"} <= names
    names_dg = gc005_justified_functions(
        os.path.join(_PACKAGE_DIR, "ops", "devicegen.py")
    )
    assert "_ring_update" in names_dg


def test_peak_live_bytes_is_deterministic_and_bounded_below():
    def f(a, b):
        c = a @ b
        return c + 1.0

    closed = jax.make_jaxpr(f)(
        jax.ShapeDtypeStruct((64, 64), jnp.float32),
        jax.ShapeDtypeStruct((64, 64), jnp.float32),
    )
    peak = peak_live_bytes(closed.jaxpr)
    # At least the two inputs plus one output buffer must coexist.
    assert peak >= 3 * 64 * 64 * 4
    assert peak == peak_live_bytes(closed.jaxpr)


# --------------------------------------------------------------------------
# Broken-kernel fixtures: one defect each, the right GI rule must fire.
# --------------------------------------------------------------------------


def _fixture_update(kernel_body, packed_width):
    """A jitted shard_map update over an abstract 1x4 mesh whose per-slice
    body is ``kernel_body(G_local, X_local)`` — the same harness the real
    builder uses, with the defect injected in the body."""
    from jax.sharding import AbstractMesh, PartitionSpec as P

    mesh = AbstractMesh(((DATA_AXIS, 1), (SAMPLES_AXIS, 4)))
    g_spec = P(DATA_AXIS, SAMPLES_AXIS, None)
    x_spec = P(DATA_AXIS, None, SAMPLES_AXIS)

    @jax.jit
    def update(G, X):
        def per_slice(G_local, X_local):
            return kernel_body(G_local[0], X_local[0])[None]

        return shard_map(
            per_slice, mesh=mesh, in_specs=(g_spec, x_spec), out_specs=g_spec
        )(G, X)

    G = jax.ShapeDtypeStruct((1, 64, 64), jnp.float32)
    X = jax.ShapeDtypeStruct((1, 8, packed_width), jnp.uint8)
    return update, (G, X)


def _fixture_spec(name, kernel_body, packed_width=8, tmp_module=None,
                  **overrides):
    spec_kwargs = dict(
        name=name,
        build=lambda: _fixture_update(kernel_body, packed_width),
        samples_axis=4,
        total_devices=4,
        packed=True,
        ring=True,
        ring_passes=1,
        rows_per_call=8,
        n_local=16,
        acc_invar=0,
        donation=tmp_module,
    )
    spec_kwargs.update(overrides)
    return KernelSpec(**spec_kwargs)


def _justified_module(tmp_path):
    """A fixture module whose `update` carries the GC005 justification, so
    broken-kernel specs isolate their own defect from GI002."""
    mod = tmp_path / "fixture_kernels.py"
    mod.write_text(
        "def update(G, X):  # graftcheck: disable=GC005 -- fixture\n"
        "    return G\n"
    )
    return DonationSite(str(mod), "update", "fixture_kernels.py")


def _dot_into(G, tile, k, i, D, n_local, operand=jnp.float32):
    j = (i + k) % D
    x_mine = _unpack_bits_t(tile)
    col = (j * n_local).astype(jnp.int32)
    zero = jnp.int32(0)
    t = jnp.matmul(
        x_mine.T, x_mine, preferred_element_type=G.dtype
    )
    return lax.dynamic_update_slice(
        G,
        lax.dynamic_slice(G, (zero, col), (n_local, n_local)) + t,
        (zero, col),
    )


def _unpack_bits_t(tile):
    return _unpack_bits(tile, tile.shape[-1] * 8).astype(jnp.float32)


def test_serialized_ring_flags_gi001(tmp_path):
    """The old pattern — permute first, dot on the permuted tile — has the
    dot waiting on the transfer every step."""

    def body_serialized(G_local, X_cols):
        D = 4
        i = lax.axis_index(SAMPLES_AXIS)
        n_local = X_cols.shape[1] * 8
        perm = [((p + 1) % D, p) for p in range(D)]

        def body(k, carry):
            G, cur = carry
            nxt = lax.ppermute(cur, SAMPLES_AXIS, perm)
            return _dot_into(G, nxt, k + 1, i, D, n_local), nxt

        G_local = _dot_into(G_local, X_cols, 0, i, D, n_local)
        G_local, _ = lax.fori_loop(0, D - 1, body, (G_local, X_cols))
        return G_local

    audit = audit_kernel(
        _fixture_spec(
            "fixture-serialized", body_serialized,
            tmp_module=_justified_module(tmp_path),
        )
    )
    assert "GI001" in _rule_ids(audit)
    assert not audit.facts["ring_overlap_independent"]


def test_extra_permute_flags_gi006(tmp_path):
    """A correct double-buffered loop run for D steps instead of D-1 pays
    one wasted tile circulation per block."""

    def body_extra(G_local, X_cols):
        D = 4
        i = lax.axis_index(SAMPLES_AXIS)
        n_local = X_cols.shape[1] * 8
        perm = [((p + 1) % D, p) for p in range(D)]

        def body(k, carry):
            G, cur = carry
            nxt = lax.ppermute(cur, SAMPLES_AXIS, perm)
            return _dot_into(G, cur, k, i, D, n_local), nxt

        G_local, _ = lax.fori_loop(0, D, body, (G_local, X_cols))
        return G_local

    audit = audit_kernel(
        _fixture_spec(
            "fixture-extra-permute", body_extra,
            tmp_module=_justified_module(tmp_path),
        )
    )
    assert "GI006" in _rule_ids(audit)
    assert audit.facts["permute_executions"] == 4


def test_unpacked_wire_flags_gi003(tmp_path):
    """Unpacking BEFORE the ring circulates f32 tiles — 32x the ICI bytes
    the packed wire format promises."""

    def body_unpacked_wire(G_local, X_cols):
        D = 4
        i = lax.axis_index(SAMPLES_AXIS)
        n_local = X_cols.shape[1] * 8
        perm = [((p + 1) % D, p) for p in range(D)]
        wire = _unpack_bits_t(X_cols)  # f32 (B, n_local) on the wire

        def dot_wide(G, tile, k):
            j = (i + k) % D
            col = (j * n_local).astype(jnp.int32)
            zero = jnp.int32(0)
            t = jnp.matmul(tile.T, tile, preferred_element_type=G.dtype)
            return lax.dynamic_update_slice(
                G,
                lax.dynamic_slice(G, (zero, col), (n_local, n_local)) + t,
                (zero, col),
            )

        def body(k, carry):
            G, cur = carry
            nxt = lax.ppermute(cur, SAMPLES_AXIS, perm)
            return dot_wide(G, cur, k), nxt

        G_local, last = lax.fori_loop(0, D - 1, body, (G_local, wire))
        return dot_wide(G_local, last, D - 1)

    audit = audit_kernel(
        _fixture_spec(
            "fixture-unpacked-wire", body_unpacked_wire,
            tmp_module=_justified_module(tmp_path),
        )
    )
    assert "GI003" in _rule_ids(audit)


def test_chatty_ring_flags_gi005(tmp_path):
    """Circulating a double-width tile moves 2x the formula's bytes while
    keeping dtype, count, and overlap intact — only GI005 may fire."""

    def body_chatty(G_local, X_cols):
        D = 4
        i = lax.axis_index(SAMPLES_AXIS)
        n_local = X_cols.shape[1] * 8
        perm = [((p + 1) % D, p) for p in range(D)]
        fat = jnp.concatenate([X_cols, X_cols], axis=1)

        def body(k, carry):
            G, cur = carry
            nxt = lax.ppermute(cur, SAMPLES_AXIS, perm)
            tile = cur[:, : cur.shape[1] // 2]
            return _dot_into(G, tile, k, i, D, n_local), nxt

        G_local, last = lax.fori_loop(0, D - 1, body, (G_local, fat))
        return _dot_into(
            G_local, last[:, : last.shape[1] // 2], D - 1, i, D, n_local
        )

    audit = audit_kernel(
        _fixture_spec(
            "fixture-chatty", body_chatty,
            tmp_module=_justified_module(tmp_path),
        )
    )
    ids = _rule_ids(audit)
    assert "GI005" in ids
    assert "GI001" not in ids and "GI006" not in ids
    assert (
        audit.facts["ring_bytes_jaxpr"]
        == 2 * audit.facts["ring_bytes_formula"]
    )


def test_f64_promotion_flags_gi004(tmp_path):
    """A float64 intermediate inside the kernel body (the silent x64/weak
    promotion class)."""

    def body_f64(G_local, X_cols):
        x = _unpack_bits_t(X_cols)
        scale = jnp.sum(x.astype(jnp.float64)) * np.float64(1.0)
        return G_local + scale.astype(G_local.dtype)

    audit = audit_kernel(
        _fixture_spec(
            "fixture-f64", body_f64, ring=False, packed=False,
            tmp_module=_justified_module(tmp_path),
        )
    )
    assert "GI004" in _rule_ids(audit)
    assert not audit.facts["f64_free"]


def test_undonated_unjustified_flags_gi002(tmp_path):
    mod = tmp_path / "plain_kernels.py"
    mod.write_text("def plain_update(G, X):\n    return G\n")

    def build():
        fn = jax.jit(lambda G, X: G + X.astype(G.dtype).sum())
        return fn, (
            jax.ShapeDtypeStruct((8, 8), jnp.float32),
            jax.ShapeDtypeStruct((4, 8), jnp.uint8),
        )

    audit = audit_kernel(
        KernelSpec(
            name="fixture-undonated",
            build=build,
            acc_invar=0,
            donation=DonationSite(str(mod), "plain_update", "plain_kernels.py"),
        )
    )
    assert _rule_ids(audit) == ["GI002"]
    assert "NOT donated" in audit.findings[0].detail


def test_stale_disable_flags_gi002_drift(tmp_path):
    mod = tmp_path / "stale_kernels.py"
    mod.write_text(
        "def donated_update(G, X):"
        "  # graftcheck: disable=GC005 -- stale justification\n"
        "    return G\n"
    )

    def build():
        fn = jax.jit(
            lambda G, X: G + X.astype(G.dtype).sum(), donate_argnums=(0,)
        )
        return fn, (
            jax.ShapeDtypeStruct((8, 8), jnp.float32),
            jax.ShapeDtypeStruct((4, 8), jnp.float32),
        )

    audit = audit_kernel(
        KernelSpec(
            name="fixture-stale-disable",
            build=build,
            acc_invar=0,
            donation=DonationSite(
                str(mod), "donated_update", "stale_kernels.py"
            ),
        )
    )
    assert _rule_ids(audit) == ["GI002"]
    assert "drifted" in audit.findings[0].detail


def test_trace_failure_flags_gi000():
    def build():
        raise ValueError("fixture cannot build")

    audit = audit_kernel(KernelSpec(name="fixture-boom", build=build))
    assert _rule_ids(audit) == ["GI000"]


# --------------------------------------------------------------------------
# Lock-order analysis.
# --------------------------------------------------------------------------


def test_tree_lock_graph_is_acyclic_and_clean():
    graph = build_lock_graph(default_lock_paths())
    assert graph.ok, "\n".join(f.format() for f in graph.findings)
    assert graph.cycles() == []
    keys = set(graph.nodes)
    # The known ingest/telemetry locks are all discovered.
    assert "sources/files.py::FileGenomicsSource._lock" in keys
    assert "obs/metrics.py::MetricsRegistry._lock" in keys
    assert "obs/metrics.py::_Family._lock" in keys
    assert "obs/metrics.py::_Child._lock" in keys
    assert "obs/spans.py::SpanRecorder._lock" in keys
    # The one real ordering edge: registry lock held while a new family's
    # constructor takes the family lock.
    assert (
        "obs/metrics.py::MetricsRegistry._lock",
        "obs/metrics.py::_Family._lock",
    ) in graph.edges


def test_lock_graph_dot_artifact():
    graph = build_lock_graph(default_lock_paths())
    dot = graph.to_dot()
    assert dot.startswith("digraph lock_order {")
    assert '"obs/metrics.py::MetricsRegistry._lock"' in dot
    assert "->" in dot


_BROKEN_LOCKS = textwrap.dedent(
    """
    import threading
    import queue
    import jax

    work_queue = queue.Queue()

    class Broken:
        def __init__(self):
            self._lock = threading.Lock()
            self._other_lock = threading.Lock()

        def forward(self):
            with self._lock:
                with self._other_lock:
                    pass

        def backward(self):
            with self._other_lock:
                with self._lock:
                    pass

        def sync_under_lock(self, x):
            with self._lock:
                jax.block_until_ready(x)

        def put_under_lock(self, item):
            with self._lock:
                work_queue.put(item)

        def reacquire(self):
            with self._lock:
                self.helper()

        def helper(self):
            with self._lock:
                pass
    """
)


def test_broken_lock_fixture_flags_every_gl_rule(tmp_path):
    mod = tmp_path / "broken_locks.py"
    mod.write_text(_BROKEN_LOCKS)
    graph = build_lock_graph([str(mod)])
    ids = {f.rule_id for f in graph.findings}
    assert ids == {"GL001", "GL002", "GL003", "GL004"}
    assert len(graph.cycles()) == 1
    by_rule = {f.rule_id: f for f in graph.findings}
    assert by_rule["GL002"].line == 25  # the block_until_ready line
    assert by_rule["GL003"].line == 29  # the work_queue.put line
    # Cycle names both member locks.
    assert "Broken._lock" in by_rule["GL001"].detail
    assert "Broken._other_lock" in by_rule["GL001"].detail


def test_lockgraph_escape_hatch(tmp_path):
    src = textwrap.dedent(
        """
        import threading
        import jax

        class Holder:
            def __init__(self):
                self._lock = threading.Lock()

            def sync(self, x):
                with self._lock:
                    jax.block_until_ready(x)  # graftcheck: disable=GL002 -- startup-only path, measured
        """
    )
    mod = tmp_path / "justified_locks.py"
    mod.write_text(src)
    graph = build_lock_graph([str(mod)])
    assert graph.ok, "\n".join(f.format() for f in graph.findings)


def test_annotated_and_class_level_locks_are_visible(tmp_path):
    """`x: Lock = threading.Lock()` (the strict-typing idiom) and
    class-body lock attributes must register exactly like the plain form —
    an invisible lock silently disables every GL rule for it."""
    src = textwrap.dedent(
        """
        import threading
        import jax

        class Annotated:
            _shared_lock = threading.Lock()

            def __init__(self):
                self._lock: threading.Lock = threading.Lock()

            def sync(self, x):
                with self._lock:
                    jax.block_until_ready(x)

            def shared_sync(self, x):
                with self._shared_lock:
                    jax.block_until_ready(x)
        """
    )
    mod = tmp_path / "annotated_locks.py"
    mod.write_text(src)
    graph = build_lock_graph([str(mod)])
    assert "annotated_locks.py::Annotated._lock" in graph.nodes
    assert "annotated_locks.py::Annotated._shared_lock" in graph.nodes
    assert [f.rule_id for f in graph.findings] == ["GL002", "GL002"]


def test_closure_calls_resolve_in_the_lock_graph(tmp_path):
    """Locks acquired inside a nested def must flow to a caller holding
    another lock — the closures-handed-to-pools case the scanner registers
    nested functions for."""
    src = textwrap.dedent(
        """
        import threading

        class Pool:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def drive(self):
                def flush():
                    with self._b_lock:
                        pass

                with self._a_lock:
                    flush()
        """
    )
    mod = tmp_path / "closure_locks.py"
    mod.write_text(src)
    graph = build_lock_graph([str(mod)])
    assert (
        "closure_locks.py::Pool._a_lock",
        "closure_locks.py::Pool._b_lock",
    ) in graph.edges


def test_module_level_lock_resolves_through_attr_reference(tmp_path):
    """`with holder.shared_lock:` against a module-level lock in another
    analyzed module must resolve (the '::' in the key must not defeat the
    attribute-name match)."""
    pkg = tmp_path / "lockpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "shared.py").write_text(
        "import threading\n\nshared_lock = threading.Lock()\n"
    )
    (pkg / "user.py").write_text(
        textwrap.dedent(
            """
            import threading
            from lockpkg import shared

            class User:
                def __init__(self):
                    self._lock = threading.Lock()

                def f(self):
                    with self._lock:
                        with shared.shared_lock:
                            pass
            """
        )
    )
    graph = build_lock_graph([str(pkg)])
    assert "shared.py::shared_lock" in graph.nodes
    assert (
        "user.py::User._lock",
        "shared.py::shared_lock",
    ) in graph.edges


def test_lockgraph_cli_rejects_unwritable_dot(tmp_path):
    from spark_examples_tpu.check.cli import main

    assert (
        main(["lockgraph", "--dot", str(tmp_path / "no_dir" / "g.dot")]) == 2
    )


def test_acquire_without_with_still_orders(tmp_path):
    src = textwrap.dedent(
        """
        import threading

        a_lock = threading.Lock()

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def f(self):
                a_lock.acquire()
                with self._lock:
                    pass
                a_lock.release()
        """
    )
    mod = tmp_path / "acquired.py"
    mod.write_text(src)
    graph = build_lock_graph([str(mod)])
    assert (
        "acquired.py::a_lock",
        "acquired.py::C._lock",
    ) in graph.edges


# --------------------------------------------------------------------------
# CLI exit codes.
# --------------------------------------------------------------------------


def test_cli_ir_and_lockgraph(tmp_path):
    from spark_examples_tpu.check.cli import main

    assert (
        main(["ir", "--mesh", "1,2", "--num-samples", "16",
              "--block-size", "4"])
        == 0
    )
    assert main(["ir", "--mesh", "bogus"]) == 2
    dot = tmp_path / "lockorder.dot"
    assert main(["lockgraph", "--dot", str(dot)]) == 0
    assert dot.read_text().startswith("digraph lock_order {")
    assert main(["lockgraph", str(tmp_path / "missing")]) == 2
    broken = tmp_path / "broken_locks.py"
    broken.write_text(_BROKEN_LOCKS)
    assert main(["lockgraph", str(broken)]) == 1

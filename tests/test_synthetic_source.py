"""Synthetic source: determinism, partition invariance, wire/packed agreement."""

import numpy as np
import pytest

from spark_examples_tpu.models.variant import VariantsBuilder
from spark_examples_tpu.sharding.contig import Contig, SexChromosomeFilter
from spark_examples_tpu.sources.base import ShardBoundary
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource


def test_callsets_are_stable_and_sized(small_source):
    callsets = small_source.search_callsets(["vs-a"])
    assert len(callsets) == 40
    assert callsets[0]["id"] == "vs-a-0"
    assert callsets == small_source.search_callsets(["vs-a"])


def test_contigs_exclude_xy(small_source):
    names = {c.reference_name for c in small_source.get_contigs("vs", SexChromosomeFilter.EXCLUDE_XY)}
    assert "X" not in names and "Y" not in names
    assert "1" in names and "22" in names


def _collect(source, vsid, start, end):
    client = source.client()
    request = {
        "variantSetIds": [vsid],
        "referenceName": "17",
        "start": start,
        "end": end,
    }
    return list(client.search_variants(request, ShardBoundary.STRICT))


def test_partition_invariance(small_source):
    """Splitting a range in two yields exactly the whole-range records —
    the synthetic analog of ShardBoundary.STRICT double-count protection."""
    whole = _collect(small_source, "vs-a", 10_000, 14_000)
    left = _collect(small_source, "vs-a", 10_000, 12_000)
    right = _collect(small_source, "vs-a", 12_000, 14_000)
    assert [v["id"] for v in left + right] == [v["id"] for v in whole]
    assert (left + right) == whole


def test_records_are_deterministic(small_source):
    again = SyntheticGenomicsSource(num_samples=40, seed=7, variant_spacing=100)
    assert _collect(small_source, "vs-a", 0, 3_000) == _collect(again, "vs-a", 0, 3_000)


def test_different_seeds_differ():
    a = SyntheticGenomicsSource(num_samples=40, seed=1)
    b = SyntheticGenomicsSource(num_samples=40, seed=2)
    assert _collect(a, "vs", 0, 3_000) != _collect(b, "vs", 0, 3_000)


def test_wire_records_build_cleanly(small_source):
    for wire in _collect(small_source, "vs-a", 0, 5_000):
        built = VariantsBuilder.build(wire)
        assert built is not None
        _, variant = built
        assert variant.contig == "17"
        assert len(variant.calls) == 40
        if variant.reference_bases == "N":
            assert variant.alternate_bases is None
            assert all(not c.has_variation() for c in variant.calls)
        else:
            assert variant.alternate_bases is not None
            assert "AF" in variant.info


def test_packed_path_matches_wire_path(small_source):
    """The packed fast path and the JSON wire path must agree exactly."""
    contig = Contig("17", 0, 10_000)
    blocks = list(small_source.genotype_blocks("vs-a", contig, block_size=37))
    packed_by_pos = {}
    for block in blocks:
        for i, pos in enumerate(block["positions"]):
            packed_by_pos[int(pos)] = block["has_variation"][i]

    wire_by_pos = {}
    for wire in _collect(small_source, "vs-a", 0, 10_000):
        built = VariantsBuilder.build(wire)
        _, variant = built
        row = np.array(
            [1 if c.has_variation() else 0 for c in variant.calls], dtype=np.uint8
        )
        if row.any():
            wire_by_pos[variant.start] = row

    assert set(packed_by_pos) == set(wire_by_pos)
    for pos, row in wire_by_pos.items():
        np.testing.assert_array_equal(packed_by_pos[pos], row)


def test_genotypes_differ_across_variant_sets_but_sites_match(small_source):
    a = _collect(small_source, "vs-a", 0, 4_000)
    b = _collect(small_source, "vs-b", 0, 4_000)
    assert [v["start"] for v in a] == [v["start"] for v in b]
    assert [v.get("referenceBases") for v in a] == [v.get("referenceBases") for v in b]
    keys_a = [VariantsBuilder.build(v)[1].variant_key() for v in a]
    keys_b = [VariantsBuilder.build(v)[1].variant_key() for v in b]
    assert keys_a == keys_b  # joinable across datasets
    genotypes = lambda recs: [c["genotype"] for v in recs for c in v["calls"]]
    assert genotypes(a) != genotypes(b)


def test_af_filter_threshold_semantics(small_source):
    contig = Contig("17", 0, 30_000)
    all_blocks = list(small_source.genotype_blocks("vs-a", contig))
    filtered = list(
        small_source.genotype_blocks("vs-a", contig, min_allele_frequency=0.2)
    )
    afs = np.concatenate([b["af"] for b in filtered]) if filtered else np.array([])
    assert (afs.astype(np.float32) > np.float32(0.2)).all()
    n_all = sum(len(b["positions"]) for b in all_blocks)
    n_filtered = sum(len(b["positions"]) for b in filtered)
    assert 0 < n_filtered < n_all


def test_page_accounting(small_source):
    client = small_source.client()
    request = {
        "variantSetIds": ["vs"],
        "referenceName": "17",
        "start": 0,
        "end": 5_000,
    }
    records = list(client.search_variants(request, page_size=10))
    expected_pages = -(-len(records) // 10)
    assert client.counters.initialized_requests == expected_pages


def test_population_structure_separates_afs():
    source = SyntheticGenomicsSource(num_samples=60, seed=3, n_pops=3)
    contig = Contig("1", 0, 200_000)
    rows = np.concatenate(
        [b["has_variation"] for b in source.genotype_blocks("vs", contig)], axis=0
    ).astype(np.float64)
    pops = source._pops
    # Mean within-population correlation should exceed cross-population.
    freq = rows.mean(axis=0)
    centered = rows - rows.mean(axis=0, keepdims=True)
    cov = centered.T @ centered
    same = [
        cov[i, j]
        for i in range(60)
        for j in range(i + 1, 60)
        if pops[i] == pops[j]
    ]
    diff = [
        cov[i, j]
        for i in range(60)
        for j in range(i + 1, 60)
        if pops[i] != pops[j]
    ]
    assert np.mean(same) > np.mean(diff)


def test_reads_depth_and_determinism(small_source):
    client = small_source.client()
    request = {
        "readGroupSetIds": ["rgs-1"],
        "referenceName": "11",
        "start": 1_000,
        "end": 2_000,
    }
    reads = list(client.search_reads(request))
    assert reads
    assert reads == list(small_source.client().search_reads(request))
    for r in reads:
        assert 1_000 <= r["alignment"]["position"]["position"] < 2_000
        assert len(r["alignedSequence"]) == small_source.read_length
        assert len(r["alignedQuality"]) == small_source.read_length


def test_tumor_normal_differ_only_at_somatic_sites():
    source = SyntheticGenomicsSource(num_samples=4, seed=9, somatic_rate=0.01)
    normal = source.read_json("Normal-set", "1", 100_000_000, 0)
    tumor = source.read_json("Tumor-set", "1", 100_000_000, 0)
    positions = np.arange(100_000_000, 100_000_000 + source.read_length)
    somatic = source._is_somatic_site("1", positions)
    for i, (a, b) in enumerate(
        zip(normal["alignedSequence"], tumor["alignedSequence"])
    ):
        if a != b:
            assert somatic[i]

"""Device compute vs. literal NumPy replications of the reference semantics."""

import numpy as np
import pytest

import jax

from spark_examples_tpu.ops.centering import gower_center, gower_center_sharded
from spark_examples_tpu.ops.gramian import (
    GramianAccumulator,
    ShardedGramianAccumulator,
    gramian_reference,
)
from spark_examples_tpu.ops.pca import mllib_reference_pca, principal_components
from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS, make_mesh


def _random_rows(rng, n_variants, n_samples, p=0.3):
    return (rng.random((n_variants, n_samples)) < p).astype(np.uint8)


def _pair_count_reference(rows):
    """The literal VariantsPca.scala:224-229 loop: for every variant, +1 for
    every ordered pair of varying samples."""
    n = rows.shape[1]
    matrix = np.zeros((n, n), dtype=np.int64)
    for row in rows:
        calls = np.nonzero(row)[0]
        for c1 in calls:
            for c2 in calls:
                matrix[c1, c2] += 1
    return matrix


def test_gramian_equals_pair_counting():
    rng = np.random.default_rng(0)
    rows = _random_rows(rng, 57, 12)
    np.testing.assert_array_equal(gramian_reference(rows), _pair_count_reference(rows))


def test_dense_accumulator_single_device():
    rng = np.random.default_rng(1)
    rows = _random_rows(rng, 301, 17)
    acc = GramianAccumulator(num_samples=17, block_size=64)
    # Feed in ragged chunks to exercise staging/padding.
    for chunk in np.array_split(rows, [13, 50, 51, 200]):
        acc.add_rows(chunk)
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(rows))


def test_dense_accumulator_exact_int():
    rng = np.random.default_rng(2)
    rows = _random_rows(rng, 100, 9)
    acc = GramianAccumulator(num_samples=9, block_size=32, exact_int=True)
    acc.add_rows(rows)
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(rows))


def test_dense_accumulator_data_parallel_mesh():
    mesh = make_mesh({DATA_AXIS: 4, SAMPLES_AXIS: 2})
    rng = np.random.default_rng(3)
    rows = _random_rows(rng, 500, 23)
    acc = GramianAccumulator(num_samples=23, mesh=mesh, block_size=16)
    for chunk in np.array_split(rows, 7):
        acc.add_rows(chunk)
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(rows))


def test_sharded_ring_accumulator():
    mesh = make_mesh({DATA_AXIS: 2, SAMPLES_AXIS: 4})
    rng = np.random.default_rng(4)
    rows = _random_rows(rng, 200, 24)  # divisible by samples axis
    acc = ShardedGramianAccumulator(num_samples=24, mesh=mesh, block_size=32)
    for chunk in np.array_split(rows, 5):
        acc.add_rows(chunk)
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(rows))


def test_sharded_ring_accumulator_with_padding():
    mesh = make_mesh({SAMPLES_AXIS: 8})
    rng = np.random.default_rng(5)
    rows = _random_rows(rng, 120, 21)  # 21 % 8 != 0 → padded cohort
    acc = ShardedGramianAccumulator(num_samples=21, mesh=mesh, block_size=16)
    acc.add_rows(rows)
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(rows))


def test_sharded_finalize_sharded_matches_host():
    mesh = make_mesh({SAMPLES_AXIS: 4})
    rng = np.random.default_rng(6)
    rows = _random_rows(rng, 64, 16)
    acc = ShardedGramianAccumulator(num_samples=16, mesh=mesh, block_size=16)
    acc.add_rows(rows)
    # finalize_sharded keeps the padded shape (the packed wire format pads
    # to 8x the samples axis); the pad block is all-zero and the true block
    # matches the trimming host finalize.
    sharded = np.asarray(jax.device_get(acc.finalize_sharded()))
    assert sharded.shape == (acc._padded, acc._padded)
    assert not sharded[16:, :].any() and not sharded[:, 16:].any()
    acc2 = ShardedGramianAccumulator(num_samples=16, mesh=mesh, block_size=16)
    acc2.add_rows(rows)
    np.testing.assert_array_equal(sharded[:16, :16], acc2.finalize())


def test_gower_center_semantics():
    rng = np.random.default_rng(7)
    S = rng.integers(0, 50, size=(10, 10)).astype(np.float64)
    S = S + S.T
    B = np.asarray(gower_center(S))
    n = S.shape[0]
    row_mean = S.sum(axis=1) / n
    col_mean = S.sum(axis=0) / n
    total = S.sum() / n / n
    expected = S - row_mean[:, None] - col_mean[None, :] + total
    np.testing.assert_allclose(B, expected, atol=1e-4)
    # Double-centered: row and column sums vanish.
    np.testing.assert_allclose(B.sum(axis=0), 0, atol=1e-3)
    np.testing.assert_allclose(B.sum(axis=1), 0, atol=1e-3)


def test_centering_is_exact_past_f32_range_under_x64():
    """The exactness guarantee holds PAST the accumulator: whole-genome
    int32 counts above f32's 2^24 exact-integer range center in f64 under
    x64 (the reference's Double centering, ``VariantsPca.scala:246-263``),
    and an int32 exact Gramian and an f32 Gramian carrying the same
    integers produce bit-identical f32 output."""
    rng = np.random.default_rng(3)
    n = 8
    # Past f32's exact range (counts ~2^25, the whole-genome regime): only
    # the int32 carrier exists in practice (the accumulator auto-switches
    # BEFORE 2^24, ``ops/gramian.py:EXACT_F32_LIMIT``); its f64-centered
    # result must equal the f64 oracle's rounding exactly.
    S_big = (1 << 25) + rng.integers(0, 64, size=(n, n)).astype(np.int64)
    S_big = (S_big + S_big.T) // 2
    with jax.enable_x64(True):
        got_big = np.asarray(
            jax.device_get(gower_center(S_big.astype(np.int32)))
        )
    assert got_big.dtype == np.float32
    np.testing.assert_array_equal(
        got_big, VariantsPcaHostCenter(S_big).astype(np.float32)
    )

    # Within f32's exact range, both carrier dtypes (int32 exact / f32
    # auto path holding the same integers) center bit-identically.
    S = rng.integers(0, 1 << 20, size=(n, n)).astype(np.int64)
    S = (S + S.T) // 2
    with jax.enable_x64(True):
        got_int = np.asarray(jax.device_get(gower_center(S.astype(np.int32))))
        got_f32 = np.asarray(
            jax.device_get(gower_center(S.astype(np.float32)))
        )
    np.testing.assert_array_equal(got_int, VariantsPcaHostCenter(S).astype(np.float32))
    np.testing.assert_array_equal(got_f32, got_int)


def VariantsPcaHostCenter(S: np.ndarray) -> np.ndarray:
    """The reference's Double centering as a NumPy oracle."""
    S = S.astype(np.float64)
    n = S.shape[0]
    row = S.sum(axis=1) / n
    total = S.sum() / n / n
    return S - row[:, None] - row[None, :] + total


def test_gower_center_sharded_matches_dense():
    mesh = make_mesh({SAMPLES_AXIS: 4})
    rng = np.random.default_rng(8)
    S = rng.integers(0, 30, size=(16, 16)).astype(np.float32)
    S = S + S.T
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    Sd = jax.device_put(jnp.asarray(S), NamedSharding(mesh, P(SAMPLES_AXIS, None)))
    out = np.asarray(jax.device_get(gower_center_sharded(Sd, mesh)))
    np.testing.assert_allclose(out, np.asarray(gower_center(S)), atol=1e-3)


def _align_signs(A, B):
    """Flip columns of B to match A's signs (eigenvector sign is arbitrary)."""
    signs = np.sign((A * B).sum(axis=0))
    signs[signs == 0] = 1.0
    return B * signs


def test_principal_components_match_mllib_semantics():
    rng = np.random.default_rng(9)
    rows = _random_rows(rng, 400, 15)
    S = gramian_reference(rows).astype(np.float64)
    B = np.asarray(gower_center(S), dtype=np.float64)
    ours, _ = principal_components(B, num_pc=3)
    ours = np.asarray(ours, dtype=np.float64)
    theirs, eigenvalues = mllib_reference_pca(B, num_pc=3)
    assert (np.diff(eigenvalues) <= 1e-9).all()  # descending
    theirs = _align_signs(ours, theirs)
    np.testing.assert_allclose(ours, theirs, atol=2e-4)


def test_principal_components_sign_is_deterministic():
    rng = np.random.default_rng(10)
    S = rng.random((12, 12))
    B = np.asarray(gower_center(S + S.T))
    pcs1, _ = principal_components(B, 2)
    pcs2, _ = principal_components(B.copy(), 2)
    np.testing.assert_array_equal(np.asarray(pcs1), np.asarray(pcs2))
    # Convention: the largest-|entry| of each component is positive.
    pcs = np.asarray(pcs1)
    for k in range(pcs.shape[1]):
        assert pcs[np.argmax(np.abs(pcs[:, k])), k] > 0


def test_mesh_construction_and_devices():
    mesh = make_mesh({DATA_AXIS: 8})
    assert mesh.shape[DATA_AXIS] == 8
    with pytest.raises(ValueError):
        make_mesh({DATA_AXIS: 9})


def test_subspace_pca_matches_eigh():
    from spark_examples_tpu.ops.pca import principal_components_subspace

    rng = np.random.default_rng(11)
    rows = _random_rows(rng, 500, 40)
    S = gramian_reference(rows).astype(np.float64)
    B = np.asarray(gower_center(S), dtype=np.float64)
    exact, exact_vals = principal_components(B, num_pc=2)
    approx, approx_vals = principal_components_subspace(B, num_pc=2)
    exact = np.asarray(exact)
    approx = np.asarray(approx)
    signs = np.sign((exact * approx).sum(axis=0))
    signs[signs == 0] = 1
    np.testing.assert_allclose(approx * signs, exact, atol=1e-3)
    np.testing.assert_allclose(
        np.asarray(approx_vals), np.asarray(exact_vals), rtol=1e-3
    )


def test_f32_accumulator_auto_switches_to_exact_int(monkeypatch):
    """Past the (patched) 2^24 projected-count limit the f32 accumulator
    converts losslessly to int32 and keeps exact counts."""
    import jax.numpy as jnp
    from spark_examples_tpu.ops import gramian as gr

    monkeypatch.setattr(gr, "EXACT_F32_LIMIT", 300)
    acc = GramianAccumulator(num_samples=6, block_size=64, exact_int=False)
    rows = np.ones((500, 6), dtype=np.uint8)
    acc.add_rows(rows)
    assert acc.accum_dtype == jnp.int32  # switched mid-stream
    np.testing.assert_array_equal(acc.finalize(), np.full((6, 6), 500))


def test_sharded_accumulator_auto_switches_to_exact_int(monkeypatch):
    import jax.numpy as jnp
    from spark_examples_tpu.ops import gramian as gr

    monkeypatch.setattr(gr, "EXACT_F32_LIMIT", 200)
    mesh = make_mesh({DATA_AXIS: 2, SAMPLES_AXIS: 2})
    acc = ShardedGramianAccumulator(
        num_samples=8, mesh=mesh, block_size=32, exact_int=False
    )
    rows = np.ones((400, 8), dtype=np.uint8)
    acc.add_rows(rows)
    assert acc.accum_dtype == jnp.int32
    np.testing.assert_array_equal(acc.finalize(), np.full((8, 8), 400))


def test_count_valued_rows_accumulate_multiplicity():
    """k duplicate occurrences contribute k² (the reference's pair loop over
    a call list with repeats, VariantsPca.scala:224-229)."""
    rows = np.array([[2, 1, 0], [0, 3, 1]], dtype=np.uint8)
    acc = GramianAccumulator(num_samples=3, block_size=4)
    acc.add_rows(rows)
    expected = rows.astype(np.int64).T @ rows.astype(np.int64)
    np.testing.assert_array_equal(acc.finalize(), expected)


def test_gower_center_sharded_padded_n():
    """Non-divisible cohort: padded rows/cols must come out zero and the
    true block must match the dense centering."""
    mesh = make_mesh({SAMPLES_AXIS: 8})
    rng = np.random.default_rng(10)
    n, n_pad = 21, 24
    S = rng.integers(0, 30, size=(n, n)).astype(np.float32)
    S = S + S.T
    S_pad = np.zeros((n_pad, n_pad), dtype=np.float32)
    S_pad[:n, :n] = S
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    Sd = jax.device_put(jnp.asarray(S_pad), NamedSharding(mesh, P(SAMPLES_AXIS, None)))
    out = np.asarray(jax.device_get(gower_center_sharded(Sd, mesh, n_true=n)))
    np.testing.assert_allclose(out[:n, :n], np.asarray(gower_center(S)), atol=1e-3)
    np.testing.assert_array_equal(out[n:], 0)
    np.testing.assert_array_equal(out[:, n:], 0)


def test_subspace_sharded_matches_dense_padded():
    from spark_examples_tpu.ops.pca import (
        principal_components_subspace,
        principal_components_subspace_sharded,
    )

    mesh = make_mesh({SAMPLES_AXIS: 8})
    rng = np.random.default_rng(11)
    n, n_pad = 21, 24
    rows = _random_rows(rng, 600, n)
    S = gramian_reference(rows).astype(np.float32)
    B = np.asarray(gower_center(S))
    B_pad = np.zeros((n_pad, n_pad), dtype=np.float32)
    B_pad[:n, :n] = B

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    Bd = jax.device_put(jnp.asarray(B_pad), NamedSharding(mesh, P(SAMPLES_AXIS, None)))
    c_sharded, e_sharded = principal_components_subspace_sharded(
        Bd, mesh, 2, n_true=n
    )
    c_sharded = np.asarray(jax.device_get(c_sharded))
    c_dense, e_dense = principal_components_subspace(jnp.asarray(B), 2)
    c_dense = np.asarray(jax.device_get(c_dense))
    np.testing.assert_array_equal(c_sharded[n:], 0)
    np.testing.assert_allclose(
        np.asarray(e_sharded), np.asarray(e_dense), rtol=1e-4
    )
    np.testing.assert_allclose(
        _align_signs(c_dense, c_sharded[:n]), c_dense, atol=1e-3
    )

"""File-backed source: VCF/JSONL variants and SAM reads through the same
partitioner/STRICT machinery as every other backend (the real-data ingest
path the reference lived on, ``rdd/VariantsRDD.scala:198-225``)."""

import gzip
import os
import textwrap

import numpy as np
import pytest

from spark_examples_tpu.models.read import ReadBuilder
from spark_examples_tpu.pipeline import pca_driver
from spark_examples_tpu.sources.base import ShardBoundary
from spark_examples_tpu.sources.files import (
    FileGenomicsSource,
    file_set_id,
    file_set_ids,
)

_VCF = textwrap.dedent(
    """\
    ##fileformat=VCFv4.2
    ##INFO=<ID=AF,Number=A,Type=Float,Description="Allele Frequency">
    #CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tNA00001\tNA00002\tNA00003
    17\t101\trs1\tA\tG\t50\tPASS\tAF=0.5\tGT\t0|1\t1|1\t0|0
    17\t205\t.\tT\tC\t50\tPASS\tAF=0.02\tGT\t0/0\t0/1\t./.
    17\t309\trs3\tG\tA,T\t50\tPASS\tAF=0.3,0.1\tGT\t1|2\t0|0\t0|1
    17\t401\trs4\tC\tT\t50\tPASS\tNS=3\tGT\t0|0\t1|0\t1|1
    GL000229.1\t42\trs6\tA\tT\t50\tPASS\tAF=0.5\tGT\t0|1\t0|0\t0|0
    """
)

_SAM = textwrap.dedent(
    """\
    @HD\tVN:1.6\tSO:coordinate
    @SQ\tSN:17\tLN:81195210
    r001\t99\t17\t101\t60\t8M2I4M\t=\t161\t75\tTTAGATAAAGGATA\tFFFFFFFFFFFFFF
    r002\t0\t17\t120\t30\t5M5D5M\t*\t0\t0\tAGCTAAGCTA\t*
    r003\t4\t*\t0\t0\t*\t*\t0\t0\tAAAA\tFFFF
    """
)


def _write(tmp_path, name, text, compress=False):
    path = tmp_path / name
    if compress:
        with gzip.open(path, "wt") as f:
            f.write(text)
    else:
        path.write_text(text)
    return str(path)


def test_set_ids_are_sanitized_and_unique(tmp_path):
    assert file_set_id("/data/chr17.vcf.gz") == "chr17"
    assert file_set_id("/data/my-cohort.2.jsonl") == "my_cohort.2"
    assert file_set_ids(["/a/x.vcf", "/b/x.vcf"]) == ["x", "x2"]


def test_vcf_wire_shape_and_callsets(tmp_path):
    source = FileGenomicsSource([_write(tmp_path, "mini.vcf", _VCF)])
    callsets = source.search_callsets(["mini"])
    assert [c["name"] for c in callsets] == ["NA00001", "NA00002", "NA00003"]
    assert [c["id"] for c in callsets] == ["mini-0", "mini-1", "mini-2"]

    client = source.client()
    got = list(
        client.search_variants(
            {"variantSetIds": ["mini"], "referenceName": "17", "start": 0, "end": 500}
        )
    )
    assert [v["start"] for v in got] == [100, 204, 308, 400]  # 1-based → 0-based
    first = got[0]
    assert first["end"] == 101 and first["referenceBases"] == "A"
    assert first["alternateBases"] == ["G"]
    assert first["info"]["AF"] == ["0.5"]
    assert first["names"] == ["rs1"]
    assert [c["genotype"] for c in first["calls"]] == [[0, 1], [1, 1], [0, 0]]
    # Missing alleles (./.) become -1: never counted as variation.
    assert got[1]["calls"][2]["genotype"] == [-1, -1]
    # Multi-allelic ALT splits; flag-style INFO keys parse to empty lists.
    assert got[2]["alternateBases"] == ["A", "T"]
    assert got[3]["info"]["NS"] == ["3"]


def test_strict_vs_overlaps_boundaries(tmp_path):
    source = FileGenomicsSource([_write(tmp_path, "mini.vcf", _VCF)])
    client = source.client()
    request = {"variantSetIds": ["mini"], "referenceName": "17", "start": 205, "end": 310}
    strict = list(client.search_variants(request, ShardBoundary.STRICT))
    assert [v["start"] for v in strict] == [308]
    overlaps = list(client.search_variants(request, ShardBoundary.OVERLAPS))
    # rs2 (start 204, end 205) does NOT overlap [205, 310); rs1 ends at 101.
    assert [v["start"] for v in overlaps] == [308]
    wide = list(
        client.search_variants(
            {**request, "start": 100}, ShardBoundary.OVERLAPS
        )
    )
    assert [v["start"] for v in wide] == [100, 204, 308]


def test_contig_discovery_and_af_filter(tmp_path):
    path = _write(tmp_path, "mini.vcf.gz", _VCF, compress=True)
    source = FileGenomicsSource([path])
    contigs = {c.reference_name: c for c in source.get_contigs("mini")}
    assert "17" in contigs and contigs["17"].end >= 401
    # End-to-end with the AF filter: variants without AF and with AF below
    # the threshold drop (strictly-greater, first AF value —
    # ``VariantsPca.scala:136-148``); rs2 (0.02) and rs4 (no AF) go.
    lines = pca_driver.run(
        [
            "--source", "file", "--input-files", path,
            "--references", "17:0:1000",
            "--pca-backend", "host",
            "--min-allele-frequency", "0.05",
        ]
    )
    assert len(lines) == 3  # one per sample, PCs from rs1+rs3 only


def test_vcf_run_tpu_matches_host_oracle(tmp_path):
    path = _write(tmp_path, "mini.vcf", _VCF)
    argv = [
        "--source", "file", "--input-files", path, "--references", "17:0:1000",
    ]
    tpu_lines = pca_driver.run(argv)
    host_lines = pca_driver.run(argv + ["--pca-backend", "host"])
    assert [l.split("\t")[:2] for l in tpu_lines] == [
        l.split("\t")[:2] for l in host_lines
    ]
    P_tpu = np.array([[float(p) for p in l.split("\t")[2:]] for l in tpu_lines])
    P_host = np.array([[float(p) for p in l.split("\t")[2:]] for l in host_lines])
    # Eigenvector sign is arbitrary per component; align before comparing.
    signs = np.sign((P_tpu * P_host).sum(axis=0))
    signs[signs == 0] = 1.0
    np.testing.assert_allclose(P_tpu, P_host * signs, atol=1e-5)


def test_two_vcf_join(tmp_path):
    """Two file-backed variant sets take the reference's 2-set inner-join
    path (``VariantsPca.scala:155-168``): matching variant keys concatenate
    both cohorts' calls."""
    a = _write(tmp_path, "cohort_a.vcf", _VCF)
    b = _write(tmp_path, "cohort_b.vcf", _VCF)
    lines = pca_driver.run(
        [
            "--source", "file", "--input-files", f"{a},{b}",
            "--references", "17:0:1000;17:0:1000",
            "--pca-backend", "host",
        ]
    )
    assert len(lines) == 6  # both cohorts' samples
    datasets = {line.split("\t")[1] for line in lines}
    assert datasets == {"cohort_a", "cohort_b"}


def test_checkpoint_directory_as_input(tmp_path):
    """A checkpoint written by the pipeline reads back through --input-files
    (the promotion of the reader into a first-class source)."""
    from spark_examples_tpu.models.variant import VariantsBuilder
    from spark_examples_tpu.pipeline.checkpoint import save_variants

    source = FileGenomicsSource([_write(tmp_path, "mini.vcf", _VCF)])
    client = source.client()
    records = [
        VariantsBuilder.build(wire)
        for wire in client.search_variants(
            {"variantSetIds": ["mini"], "referenceName": "17", "start": 0, "end": 1000}
        )
    ]
    ckpt = tmp_path / "ckpt"
    save_variants(str(ckpt), [[r for r in records if r is not None]])

    lines_vcf = pca_driver.run(
        [
            "--source", "file", "--input-files", str(tmp_path / "mini.vcf"),
            "--references", "17:0:1000", "--pca-backend", "host",
        ]
    )
    lines_ckpt = pca_driver.run(
        [
            "--source", "file", "--input-files", str(ckpt),
            "--references", "17:0:1000", "--pca-backend", "host",
        ]
    )
    # Same cohort, same variants, same PCs (names come from the callsets).
    assert [l.split("\t")[2:] for l in lines_ckpt] == [
        l.split("\t")[2:] for l in lines_vcf
    ]


def test_sam_reads_roundtrip(tmp_path):
    source = FileGenomicsSource([_write(tmp_path, "sample.sam", _SAM)])
    client = source.client()
    got = list(
        client.search_reads(
            {"readGroupSetIds": ["sample"], "referenceName": "17", "start": 0, "end": 1000}
        )
    )
    assert len(got) == 2  # the unmapped read (rname '*') is dropped
    key, read = ReadBuilder.build(got[0])
    assert read.position == 100 and read.cigar == "8M2I4M"
    assert read.fragment_name == "r001"
    assert read.mate_position == 160 and read.mate_reference_name == "17"
    assert read.aligned_quality[0] == 37  # 'F' → Q37
    _, read2 = ReadBuilder.build(got[1])
    assert read2.cigar == "5M5D5M" and read2.aligned_quality == ()
    # OVERLAPS spans the deletion: r002 covers [119, 134) on the reference.
    overlapping = list(
        client.search_reads(
            {"readGroupSetIds": ["sample"], "referenceName": "17", "start": 130, "end": 140},
            ShardBoundary.OVERLAPS,
        )
    )
    assert [r["fragmentName"] for r in overlapping] == ["r002"]


def test_native_vcf_parser_matches_python_fallback(tmp_path):
    """The C++ parser (native/vcfparse.cpp) and the pure-Python fallback
    produce identical packed views — positions, AF values (NaN for absent),
    and has-variation rows — for every contig."""
    from spark_examples_tpu.sharding.contig import Contig
    from spark_examples_tpu.sources.files import _PackedVcf
    from spark_examples_tpu.utils import native as native_mod

    path = _write(tmp_path, "mini.vcf.gz", _VCF, compress=True)
    if native_mod.vcf_library() is None:
        pytest.skip(f"no native build: {native_mod.native_unavailable_reason()}")
    native_view = _PackedVcf(path, "mini")
    assert native_view.native
    fallback = _PackedVcf.__new__(_PackedVcf)
    # Force the Python path: probe says no library → the fallback parser.
    original = native_mod.vcf_library
    try:
        native_mod.vcf_library = lambda: None
        fallback.__init__(path, "mini")
    finally:
        native_mod.vcf_library = original
    assert not fallback.native
    assert set(native_view.by_contig) == set(fallback.by_contig)
    for contig in native_view.by_contig:
        pos_n, af_n, hv_n = native_view.by_contig[contig]
        pos_p, af_p, hv_p = fallback.by_contig[contig]
        np.testing.assert_array_equal(pos_n, pos_p)
        np.testing.assert_array_equal(hv_n, hv_p)
        np.testing.assert_array_equal(np.isnan(af_n), np.isnan(af_p))
        np.testing.assert_array_equal(
            af_n[~np.isnan(af_n)], af_p[~np.isnan(af_p)]
        )
    # Window semantics: STRICT slice by start.
    window = native_view.window(Contig("17", 205, 310))
    assert window[0].tolist() == [308]


def test_short_sample_lines_zero_fill_in_both_parsers(tmp_path):
    """A data line with fewer sample columns than the header zero-fills the
    missing samples — identically in the native parser and the Python
    fallback (the header is the cohort authority)."""
    from spark_examples_tpu.sources.files import _PackedVcf, _python_vcf_arrays
    from spark_examples_tpu.utils import native as native_mod

    vcf = (
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS1\tS2\tS3\n"
        "17\t101\t.\tA\tG\t1\t.\tAF=0.5\tGT\t0|1\t1|1\n"
    )
    path = _write(tmp_path, "short.vcf", vcf)
    _, _, _, _, hv_py = _python_vcf_arrays(path, "short")
    np.testing.assert_array_equal(hv_py, [[1, 1, 0]])
    if native_mod.vcf_library() is not None:
        arrays = native_mod.parse_vcf_arrays(vcf.encode())
        np.testing.assert_array_equal(arrays[4], hv_py)
    view = _PackedVcf(path, "short")
    assert view.num_samples == 3


def test_file_packed_ingest_matches_wire(tmp_path, capsys):
    """--source file --ingest packed: same principal components AND the same
    partition/request accounting as the wire path (variants count follows
    the documented packed-vs-wire divergence: packed counts kept rows)."""
    path = _write(tmp_path, "mini.vcf", _VCF)
    argv = [
        "--source", "file", "--input-files", path, "--references", "17:0:1000",
        "--min-allele-frequency", "0.05",
    ]

    def run_and_stats(ingest):
        lines = pca_driver.run(argv + ["--ingest", ingest])
        out = capsys.readouterr().out
        fields = {
            line.split(": ")[0]: int(line.split(": ")[1])
            for line in out.splitlines()
            if line.startswith("# of")
        }
        return lines, fields

    packed_lines, packed_stats = run_and_stats("packed")
    wire_lines, wire_stats = run_and_stats("wire")
    assert packed_lines == wire_lines
    for key in ("# of partitions", "# of bases requested", "# of API requests"):
        assert packed_stats[key] == wire_stats[key]
    assert packed_stats["# of variants read"] <= wire_stats["# of variants read"]


def test_file_packed_rejects_multi_set(tmp_path):
    a = _write(tmp_path, "a.vcf", _VCF)
    b = _write(tmp_path, "b.vcf", _VCF)
    with pytest.raises(ValueError, match="single variant set"):
        pca_driver.run(
            [
                "--source", "file", "--input-files", f"{a},{b}",
                "--ingest", "packed", "--references", "17:0:1000",
            ]
        )


def test_native_parser_rejects_malformed_vcf(tmp_path):
    from spark_examples_tpu.utils import native as native_mod

    if native_mod.vcf_library() is None:
        pytest.skip("no native build")
    bad = "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n17\tnotanumber\t.\tA\tG\t1\t.\tAF=0.1\n"
    with pytest.raises(ValueError, match="data line #1"):
        native_mod.parse_vcf_arrays(bad.encode())


def test_native_parser_locale_independent():
    """AF parsing must not shift under a host process's setlocale(): the
    native parser uses a cached "C" locale (vcfparse.cpp:strtod_c), so a
    comma-decimal LC_NUMERIC must not make it reject '0.5' and drop every
    AF-filtered record. Skips when no comma-decimal locale is installed
    (the fix is then unobservable on this system)."""
    import locale

    from spark_examples_tpu.utils import native as native_mod

    if native_mod.vcf_library() is None:
        pytest.skip("no native build")
    comma_locale = None
    for candidate in ("de_DE.UTF-8", "fr_FR.UTF-8", "de_DE", "fr_FR"):
        try:
            locale.setlocale(locale.LC_NUMERIC, candidate)
        except locale.Error:
            continue
        if locale.localeconv()["decimal_point"] == ",":
            comma_locale = candidate
            break
        locale.setlocale(locale.LC_NUMERIC, "C")
    if comma_locale is None:
        locale.setlocale(locale.LC_NUMERIC, "C")
        pytest.skip("no comma-decimal locale installed")
    try:
        vcf = (
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS1\n"
            "17\t101\t.\tA\tG\t1\t.\tAF=0.5\tGT\t0|1\n"
        )
        arrays = native_mod.parse_vcf_arrays(vcf.encode())
        np.testing.assert_array_equal(arrays[3], [0.5])
    finally:
        locale.setlocale(locale.LC_NUMERIC, "C")


def test_missing_input_files_flag_raises():
    with pytest.raises(ValueError, match="input-files"):
        pca_driver.run(["--source", "file"])


def test_unknown_explicit_variant_set_id_raises(tmp_path):
    """A typo'd --variant-set-id must fail loudly, not silently widen the
    run to every input file."""
    path = _write(tmp_path, "mini.vcf", _VCF)
    with pytest.raises(ValueError, match="tyop"):
        pca_driver.run(
            [
                "--source", "file", "--input-files", path,
                "--variant-set-id", "tyop",
            ]
        )


def test_narrowed_variant_set_id_is_kept(tmp_path):
    a = _write(tmp_path, "cohort_a.vcf", _VCF)
    b = _write(tmp_path, "cohort_b.vcf", _VCF)
    lines = pca_driver.run(
        [
            "--source", "file", "--input-files", f"{a}, {b}",  # stray space OK
            "--variant-set-id", "cohort_b",
            "--references", "17:0:1000",
            "--pca-backend", "host",
        ]
    )
    assert {line.split("\t")[1] for line in lines} == {"cohort_b"}


def test_non_checkpoint_directory_raises(tmp_path):
    empty = tmp_path / "not_a_checkpoint"
    empty.mkdir()
    with pytest.raises(ValueError, match="part-"):
        pca_driver.run(
            [
                "--source", "file", "--input-files", str(empty),
                "--references", "17:0:1000", "--pca-backend", "host",
            ]
        )


def test_reads_example_cli_runs_on_sam(tmp_path, capsys):
    """The reads analyses are reachable from the CLI on a SAM file: the
    file-derived set id routes into the example's readset parameter."""
    from spark_examples_tpu.cli import main
    from spark_examples_tpu.constants import Examples

    snp = Examples.CILANTRO
    sam = "@HD\tVN:1.6\n@SQ\tSN:11\tLN:135006516\n" + "".join(
        f"r{i:03d}\t0\t11\t{snp - 20 + i}\t60\t40M\t*\t0\t0\t{'ACGT' * 10}\t{'F' * 40}\n"
        for i in range(10)
    )
    path = _write(tmp_path, "pileup.sam", sam)
    rc = main(["search-reads-example-1", "--source", "file", "--input-files", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "(37)" in out  # pileup rows print the SNP base quality inline


def test_reads_example4_needs_two_files(tmp_path):
    from spark_examples_tpu.cli import main

    path = _write(tmp_path, "only_one.sam", _SAM)
    with pytest.raises(ValueError, match="normal_readset, tumor_readset"):
        main(["search-reads-example-4", "--source", "file", "--input-files", path])


# Property-based native/Python parser parity moved to test_files_fuzz.py:
# hypothesis is only declared under the `test` extra, and a module-level
# dependency here would error this whole suite's collection on the bare
# seed image.

def test_wire_and_packed_agree_on_unparseable_af(tmp_path, capsys):
    """``--min-allele-frequency`` must drop junk/hex/absent AF identically in
    BOTH ingest modes of the same file — the wire filter shares the packed
    parsers' AF grammar (``af_float``) instead of the REST path's throwing
    float()."""
    from spark_examples_tpu.cli import main

    vcf = (
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\tS0\tS1\tS2\n"
        "17\t101\t.\tA\tG\t.\t.\tAF=0.5\tGT\t0|1\t0|0\t1|1\n"
        "17\t205\t.\tAT\tG\t.\t.\tNS=2;AF=1e-3;DB\tGT\t0/0\t0/1\t1|1\n"
        "17\t308\t.\tG\tC\t.\t.\tAF=junk\tGT\t1|1\t0|0\t0|1\n"
        "17\t410\t.\tC\tT\t.\t.\tAF=0x1A\tGT\t0|1\t0|1\t0|0\n"
        "17\t512\t.\tT\tA\t.\t.\tXAF=9\tGT\t0|0\t0|1\t1|1\n"
    )
    path = _write(tmp_path, "junk_af.vcf", vcf)
    outputs = []
    for ingest in ("wire", "packed"):
        rc = main(
            [
                "variants-pca",
                "--source", "file",
                "--input-files", path,
                "--ingest", ingest,
                "--min-allele-frequency", "0.0001",
                "--references", "17:0:1000",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        outputs.append(
            [l for l in out.splitlines() if l.startswith("S")]
        )
    assert outputs[0] == outputs[1]
    assert len(outputs[0]) == 3  # all three samples emitted


def test_jsonl_numeric_af_filters_without_crashing(tmp_path, capsys):
    """JSONL wire records carry AF as JSON numbers; the file-backed filter
    must treat them like their string forms instead of crashing."""
    import json as _json

    from spark_examples_tpu.cli import main

    records = [
        {
            "referenceName": "17",
            "start": 100 + 10 * i,
            "end": 101 + 10 * i,
            "referenceBases": "A",
            "alternateBases": ["G"],
            "info": {"AF": [af]},
            "calls": [
                {
                    "callSetId": f"j-{s}",
                    "callSetName": f"S{s}",
                    "genotype": [1, 0] if (i + s) % 2 else [0, 0],
                }
                for s in range(3)
            ],
        }
        for i, af in enumerate([0.5, 0.002, 1e-9, "junk"])
    ]
    path = tmp_path / "numeric_af.jsonl"
    path.write_text("".join(_json.dumps(r) + "\n" for r in records))
    rc = main(
        [
            "variants-pca",
            "--source", "file",
            "--input-files", str(path),
            "--min-allele-frequency", "0.001",
            "--references", "17:0:1000",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert len([l for l in out.splitlines() if l.startswith("S")]) == 3


def test_reads_coverage_and_depth_cli_on_sam(tmp_path, capsys):
    """Examples 2 (mean coverage) and 3 (per-base depth) run end to end on a
    SAM file input — completing the file-backed CLI matrix the pileup and
    tumor/normal tests already cover."""
    from spark_examples_tpu.cli import main

    sam = "@HD\tVN:1.6\n@SQ\tSN:21\tLN:48129895\n" + "".join(
        f"r{i:03d}\t0\t21\t{1000 + 5 * i}\t60\t40M\t*\t0\t0\t{'ACGT' * 10}\t{'F' * 40}\n"
        for i in range(20)
    )
    path = _write(tmp_path, "chr21.sam", sam)

    rc = main(["search-reads-example-2", "--source", "file", "--input-files", path])
    assert rc == 0
    out = capsys.readouterr().out
    # 20 reads x 40 bases over the chr21 length (Examples.HUMAN_CHROMOSOMES).
    assert "1.6621" in out.replace(",", "")  # 800 / 48129895 ~ 1.662e-05

    out_path = str(tmp_path / "depth_out")
    rc = main(
        ["search-reads-example-3", "--source", "file", "--input-files", path,
         "--output-path", out_path]
    )
    assert rc == 0
    capsys.readouterr()
    import glob

    parts = glob.glob(out_path + "/coverage_21/part-*")
    assert parts, out_path
    combined = "".join(open(p).read() for p in parts)
    # POS 1000 (1-based) -> 999 half-open 0-based; 40bp reads at 5bp stagger
    # rise to a depth-8 plateau.
    assert "(999,1)" in combined
    assert ",8)" in combined


# SAM parser roundtrip property moved to test_files_fuzz.py (hypothesis
# is only declared under the `test` extra; see note above).

"""The structured telemetry subsystem (``spark_examples_tpu/obs/``):
registry semantics and thread-safety, span nesting, heartbeat lifecycle,
manifest schema round-trip, and end-to-end parity between the printed
epilogue and the machine-readable manifest across ingest paths."""

import json
import re
import threading

import numpy as np
import pytest

from spark_examples_tpu.obs.heartbeat import Heartbeat
from spark_examples_tpu.obs.manifest import (
    build_run_manifest,
    manifest_metric_value,
    read_manifest,
    validate_manifest,
    write_manifest,
)
from spark_examples_tpu.obs.metrics import MetricError, MetricsRegistry
from spark_examples_tpu.obs.spans import SpanRecorder
from spark_examples_tpu.pipeline.stats import VariantsDatasetStats
from spark_examples_tpu.sources.base import ClientCounters
from spark_examples_tpu.utils.tracing import StageTimes

# ------------------------------------------------------------------ registry


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("requests_total", "Requests.")
    c.inc()
    c.inc(4)
    assert reg.value("requests_total") == 5
    with pytest.raises(MetricError):
        c.inc(-1)

    g = reg.gauge("depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert reg.value("depth") == 2
    g.set_function(lambda: 42)
    assert reg.value("depth") == 42

    h = reg.histogram("latency_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.value
    assert snap["count"] == 3
    assert snap["sum"] == pytest.approx(5.55)
    assert snap["buckets"] == {"0.1": 1, "1": 2, "+Inf": 3}


def test_labeled_series_and_registration_conflicts():
    reg = MetricsRegistry()
    fam = reg.counter("flushes_total", labelnames=("strategy",))
    fam.labels(strategy="dense").inc(2)
    fam.labels(strategy="sharded").inc(1)
    assert reg.value("flushes_total", {"strategy": "dense"}) == 2
    assert reg.value("flushes_total", {"strategy": "sharded"}) == 1
    # Labeled family refuses label-free use; wrong label names refuse.
    with pytest.raises(MetricError):
        fam.inc()
    with pytest.raises(MetricError):
        fam.labels(mode="dense")
    # Idempotent re-registration; kind/label mismatch raises.
    assert reg.counter("flushes_total", labelnames=("strategy",)) is fam
    with pytest.raises(MetricError):
        reg.gauge("flushes_total")
    with pytest.raises(MetricError):
        reg.counter("flushes_total", labelnames=("other",))


def test_registry_thread_safety_under_concurrent_workers():
    """The concurrent-ingest shape: many worker threads incrementing the
    same counters (directly and through VariantsDatasetStats) must lose no
    updates."""
    reg = MetricsRegistry()
    stats = VariantsDatasetStats(reg)
    counter = reg.counter("parallel_total")
    n_threads, n_iter = 8, 2000

    def work():
        client = ClientCounters()
        for _ in range(n_iter):
            counter.inc()
            stats.add_variants(2)
            stats.add_partition(10)
            client.add_request()
        stats.add_client(client)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.value("parallel_total") == n_threads * n_iter
    assert stats.variants == 2 * n_threads * n_iter
    assert stats.partitions == n_threads * n_iter
    assert stats.reference_bases == 10 * n_threads * n_iter
    assert stats.requests == n_threads * n_iter


def test_prometheus_text_export():
    reg = MetricsRegistry()
    reg.counter("io_requests_total", "Requests issued.").inc(3)
    reg.histogram(
        "flush_seconds", labelnames=("strategy",), buckets=(1.0,)
    ).labels(strategy="dense").observe(0.5)
    text = reg.prometheus_text()
    assert "# TYPE io_requests_total counter" in text
    assert "io_requests_total 3" in text
    assert 'flush_seconds_bucket{le="1",strategy="dense"} 1' in text
    assert 'flush_seconds_count{strategy="dense"} 1' in text


# ------------------------------------------------------------- stats shim


def test_stats_report_format_unchanged_and_writes_forbidden():
    stats = VariantsDatasetStats()
    stats.add_partition(1000)
    stats.add_variants(7)
    stats.add_requests(3)
    stats.add_client(
        ClientCounters(
            initialized_requests=2, unsuccessful_responses=1, io_exceptions=1
        )
    )
    assert str(stats) == (
        "Variants API stats:\n"
        "-------------------------------\n"
        "# of partitions: 1\n"
        "# of bases requested: 1000\n"
        "# of variants read: 7\n"
        "# of API requests: 5\n"
        "# of unsuccessful responses: 1\n"
        "# of IO exceptions: 1\n"
    )
    # The satellite contract: the old lock-bypassing mutation now fails.
    with pytest.raises(AttributeError, match="add_requests"):
        stats.requests += 1
    with pytest.raises(AttributeError):
        stats.variants = 0
    assert stats.as_dict() == {
        "partitions": 1,
        "reference_bases": 1000,
        "variants": 7,
        "requests": 5,
        "unsuccessful_responses": 1,
        "io_exceptions": 1,
        "io_retries": 0,
    }


# ---------------------------------------------------------------- spans


def test_span_nesting_and_ordering():
    rec = SpanRecorder()
    with rec.span("run"):
        with rec.span("ingest"):
            rec.add("chunk-parse", 0.25)
            with rec.span("dispatch"):
                pass
        with rec.span("pca", sync=lambda: None):
            pass
    (root,) = rec.as_list()
    assert root["name"] == "run"
    assert [c["name"] for c in root["children"]] == ["ingest", "pca"]
    ingest, pca = root["children"]
    assert [c["name"] for c in ingest["children"]] == ["chunk-parse", "dispatch"]
    assert ingest["children"][0]["seconds"] == 0.25
    assert pca["synced"] is True and ingest["synced"] is False
    paths = [row["path"] for row in rec.flat()]
    assert paths == [
        "run", "run/ingest", "run/ingest/chunk-parse",
        "run/ingest/dispatch", "run/pca",
    ]
    assert rec.find("run/ingest/dispatch") is not None
    assert rec.find("run/nope") is None
    # Durations nest sanely: the parent covers its children.
    assert root["seconds"] >= ingest["seconds"] + pca["seconds"] - 1e-6


def test_span_survives_raising_sync():
    """A sync fetch that raises (device error — the case sync exists for)
    must still close the span and pop the stack, or every later span on
    the thread would nest under a dead parent."""
    rec = SpanRecorder()
    with pytest.raises(RuntimeError):
        with rec.span("stage", sync=lambda: (_ for _ in ()).throw(
            RuntimeError("fetch failed")
        )):
            pass
    assert rec.find("stage").seconds is not None
    with rec.span("next"):
        pass
    # "next" rooted independently — not swallowed as a child of "stage".
    assert [s["name"] for s in rec.as_list()] == ["stage", "next"]


def test_span_records_on_exception_and_across_threads():
    rec = SpanRecorder()
    with pytest.raises(RuntimeError):
        with rec.span("outer"):
            with rec.span("inner"):
                raise RuntimeError("boom")
    outer = rec.find("outer")
    assert outer is not None and outer.seconds is not None
    assert rec.find("outer/inner").seconds is not None

    # A second thread's spans root independently (no cross-thread nesting).
    def other():
        with rec.span("worker"):
            pass

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert [s["name"] for s in rec.as_list()] == ["outer", "worker"]


def test_stage_times_format_and_recorder_shim():
    rec = SpanRecorder()
    times = StageTimes(recorder=rec)
    with times.stage("ingest+similarity"):
        pass
    with times.stage("center+pca", sync=lambda: None):
        pass
    text = str(times)
    lines = text.splitlines()
    assert lines[0] == "Stage timings:"
    assert lines[1] == "-------------------------------"
    assert re.fullmatch(r"ingest\+similarity: \d+\.\d{3} s", lines[2])
    assert re.fullmatch(r"center\+pca: \d+\.\d{3} s", lines[3])
    assert re.fullmatch(r"total: \d+\.\d{3} s", lines[4])
    # Every stage is also a span; the two views agree numerically.
    assert times.as_dict() == {
        s["name"]: s["seconds"] for s in rec.as_list()
    }


# ------------------------------------------------------------- heartbeat


def test_heartbeat_emits_and_stops_cleanly_on_error():
    reg = MetricsRegistry()
    reg.gauge("ingest_sites_scanned").set(1000)
    reg.counter("io_partitions_total").inc(2)
    reg.gauge("ingest_partitions_planned").set(8)
    emitted = []
    hb = Heartbeat(0.01, reg, emit=emitted.append)
    with pytest.raises(RuntimeError):
        with hb:
            deadline = threading.Event()
            for _ in range(500):
                if emitted:
                    break
                deadline.wait(0.01)
            raise RuntimeError("driver failed mid-run")
    assert not hb.running  # stopped by the context manager despite the error
    assert len(emitted) >= 1
    count_after_stop = len(emitted)
    threading.Event().wait(0.05)
    assert len(emitted) == count_after_stop  # silence after stop()
    line = emitted[0]
    assert line.startswith("heartbeat[")
    assert "1,000 sites scanned" in line
    assert "partitions 2/8" in line
    hb.stop()  # idempotent


def test_heartbeat_rate_and_eta_segments():
    reg = MetricsRegistry()
    sites = reg.gauge("ingest_sites_scanned")
    done = reg.counter("io_partitions_total")
    reg.gauge("ingest_partitions_planned").set(4)
    clock = [0.0]
    hb = Heartbeat(10.0, reg, emit=lambda line: None, clock=lambda: clock[0])
    hb._started_at = 0.0
    sites.set(0)
    hb.line()  # prime the rate baseline
    clock[0] = 10.0
    sites.set(50_000)
    done.inc(1)
    line = hb.line()
    assert "(5.0k sites/s)" in line
    assert "partitions 1/4 (ETA 30s)" in line
    assert "no progress metrics" not in line


def test_heartbeat_rejects_nonpositive_interval():
    with pytest.raises(ValueError):
        Heartbeat(0.0, MetricsRegistry())


def test_function_backed_gauge_rejects_inc():
    reg = MetricsRegistry()
    g = reg.gauge("occupancy")
    g.set_function(lambda: 3)
    with pytest.raises(MetricError, match="function-backed"):
        g.inc()
    g.set(1)  # set() detaches the sampler; deltas work again
    g.inc(2)
    assert reg.value("occupancy") == 3


def test_heartbeat_prefers_streaming_partitions_done_gauge():
    """The streamed ingest flushes io_partitions_total only after the full
    pass; its live ingest_partitions_done gauge must drive the heartbeat's
    progress segment instead of a run-long 0/N."""
    reg = MetricsRegistry()
    reg.gauge("ingest_partitions_planned").set(10)
    reg.counter("io_partitions_total")  # still 0 — flushed at stream end
    reg.gauge("ingest_partitions_done").set(4)
    clock = [100.0]
    hb = Heartbeat(10.0, reg, emit=lambda line: None, clock=lambda: clock[0])
    hb._started_at = 0.0
    assert "partitions 4/10 (ETA 150s)" in hb.line()


def test_stream_counters_publish_live_progress_gauges():
    from spark_examples_tpu.sources.files import StreamCounters

    reg = MetricsRegistry()
    counters = StreamCounters(5, registry=reg)
    counters.add_shard_rows(0, 30)
    counters.add_shard_rows(0, 10)
    counters.add_shard_rows(2, 20)
    assert reg.value("ingest_sites_scanned") == 60
    assert reg.value("ingest_partitions_done") == 2
    # Empty windows the cursor passed count as reached too — otherwise
    # done/planned would never converge and the ETA would grow forever.
    counters.mark_window_reached(1)
    assert reg.value("ingest_partitions_done") == 3
    assert 1 not in counters.shard_rows  # reached, but contributed no rows


# -------------------------------------------------------------- manifest


def test_manifest_round_trip_and_validation(tmp_path):
    reg = MetricsRegistry()
    reg.counter("io_requests_total").inc(3)
    reg.histogram("gramian_flush_seconds", labelnames=("strategy",)).labels(
        strategy="dense"
    ).observe(0.01)
    rec = SpanRecorder()
    with rec.span("ingest+similarity"):
        rec.add("dispatch", 0.5)
    stats = VariantsDatasetStats(reg)
    stats.add_partition(100)
    doc = build_run_manifest(
        conf={"num_pc": 2},
        spans=rec,
        registry=reg,
        io_stats=stats,
        overlap={"parse_busy_seconds": 0.1, "blocks": 4},
    )
    assert validate_manifest(doc) == []
    path = tmp_path / "out" / "manifest.json"
    write_manifest(str(path), doc)
    loaded = read_manifest(str(path))
    assert validate_manifest(loaded) == []
    assert loaded["io_stats"]["partitions"] == 1
    assert loaded["config"]["num_pc"] == 2
    assert manifest_metric_value(loaded, "io_requests_total") == 3
    # Histogram series read back as the bare snapshot (no labels key).
    snap = manifest_metric_value(
        loaded, "gramian_flush_seconds", {"strategy": "dense"}
    )
    assert snap["count"] == 1 and "labels" not in snap
    assert manifest_metric_value(loaded, "nope", default=-1) == -1
    assert loaded["spans"][0]["children"][0]["name"] == "dispatch"
    # JSON round-trip is loss-free for the metric payload.
    assert json.loads(json.dumps(doc["metrics"])) == loaded["metrics"]
    # Rewrites are atomic and leave no temp debris behind.
    write_manifest(str(path), doc)
    assert [p.name for p in path.parent.iterdir()] == [path.name]


def test_manifest_validation_catches_tampering():
    doc = build_run_manifest(conf={}, spans=SpanRecorder(), registry=MetricsRegistry())
    assert validate_manifest(doc) == []
    bad = json.loads(json.dumps(doc))
    bad["schema"]["version"] = 99
    assert any("version" in e for e in validate_manifest(bad))
    bad = json.loads(json.dumps(doc))
    del bad["metrics"]
    assert any("metrics" in e for e in validate_manifest(bad))
    bad = json.loads(json.dumps(doc))
    bad["spans"] = [{"name": 3, "seconds": -1, "children": []}]
    errors = validate_manifest(bad)
    assert any("name" in e for e in errors)
    assert any("seconds" in e for e in errors)
    bad = json.loads(json.dumps(doc))
    bad["io_stats"] = {"partitions": "many"}
    assert any("io_stats.partitions" in e for e in validate_manifest(bad))
    assert validate_manifest([]) == ["manifest is not a JSON object"]


def test_manifest_validation_treats_io_retries_as_additive():
    """``io_retries`` joined IO_STAT_FIELDS after schema v2 shipped:
    archived v2 manifests without it must still validate (the additive
    contract), while the other fields stay required."""
    from spark_examples_tpu.obs.manifest import IO_STAT_FIELDS

    doc = build_run_manifest(
        conf={}, spans=SpanRecorder(), registry=MetricsRegistry()
    )
    doc = json.loads(json.dumps(doc))
    doc["io_stats"] = {f: 0 for f in IO_STAT_FIELDS}
    assert validate_manifest(doc) == []
    del doc["io_stats"]["io_retries"]  # a pre-0.6 archived manifest
    assert validate_manifest(doc) == []
    del doc["io_stats"]["requests"]  # required fields stay enforced
    assert any("io_stats.requests" in e for e in validate_manifest(doc))


# ------------------------------------------------- end-to-end driver parity


def _parse_epilogue(out: str) -> dict:
    """The printed I/O stats block → dict (the operator-facing numbers)."""
    patterns = {
        "partitions": r"# of partitions: (\d+)",
        "reference_bases": r"# of bases requested: (\d+)",
        "variants": r"# of variants read: (\d+)",
        "requests": r"# of API requests: (\d+)",
        "unsuccessful_responses": r"# of unsuccessful responses: (\d+)",
        "io_exceptions": r"# of IO exceptions: (\d+)",
    }
    return {k: int(re.search(p, out).group(1)) for k, p in patterns.items()}


def test_manifest_matches_printed_epilogue_exactly(tmp_path, capsys):
    """The acceptance contract: a synthetic run with --metrics-json and a
    heartbeat produces a schema-valid manifest whose io stats and stage
    spans match the printed epilogue exactly."""
    from spark_examples_tpu.pipeline import pca_driver

    path = tmp_path / "manifest.json"
    pca_driver.run(
        [
            "--num-samples", "6",
            "--references", "1:0:40000",
            "--metrics-json", str(path),
            "--heartbeat-seconds", "1",
            "--profile-dir", str(tmp_path / "trace"),
        ]
    )
    out = capsys.readouterr().out
    doc = read_manifest(str(path))
    assert validate_manifest(doc) == []
    # io_retries rides the manifest only (the printed report keeps the
    # reference's six-line format, pipeline/stats.py).
    assert doc["io_stats"] == {**_parse_epilogue(out), "io_retries": 0}
    # Stage spans match the printed Stage timings block to the 3 printed
    # decimals (both are views of one measurement).
    printed = dict(
        re.findall(r"^([\w+]+): (\d+\.\d{3}) s$", out, flags=re.M)
    )
    spans = {s["name"]: s["seconds"] for s in doc["spans"]}
    for name in ("ingest+similarity", "center+pca"):
        assert f"{spans[name]:.3f}" == printed[name]
    assert doc["config"]["num_samples"] == 6
    assert manifest_metric_value(doc, "ingest_sites_scanned") > 0


def test_unwritable_manifest_path_does_not_destroy_the_run(tmp_path, capsys):
    """A typo'd --metrics-json path must not throw away hours of completed
    compute: the results return, the failure is reported on stderr."""
    from spark_examples_tpu.pipeline import pca_driver

    blocker = tmp_path / "not-a-dir"
    blocker.write_text("")
    lines = pca_driver.run(
        [
            "--num-samples", "5",
            "--references", "1:0:30000",
            "--metrics-json", str(blocker / "manifest.json"),
        ]
    )
    captured = capsys.readouterr()
    assert len(lines) == 5  # the PCA result survived
    assert "Run manifest NOT written" in captured.err
    assert "Run manifest written" not in captured.out


def test_sharded_accumulator_finalize_paths_record_telemetry():
    import jax

    from spark_examples_tpu.ops.gramian import ShardedGramianAccumulator
    from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS, make_mesh

    mesh = make_mesh({SAMPLES_AXIS: min(2, jax.device_count())})
    for finalize in ("finalize", "finalize_device_padded", "finalize_sharded"):
        reg, rec = MetricsRegistry(), SpanRecorder()
        acc = ShardedGramianAccumulator(
            8, mesh, block_size=4, registry=reg, spans=rec
        )
        acc.add_rows(np.ones((6, 8), dtype=np.uint8))
        with rec.span("ingest+similarity"):
            getattr(acc, finalize)()
        assert reg.value("gramian_rows_total", {"strategy": "sharded"}) == 6
        (ingest,) = rec.as_list()
        assert [c["name"] for c in ingest["children"]] == [
            "dispatch",
            "reduce-flush",
        ], finalize


def test_stdout_byte_identical_with_telemetry_off(capsys):
    """Telemetry defaults (no heartbeat, no manifest) leave stdout exactly
    as a telemetry-free run prints it."""
    from spark_examples_tpu.pipeline import pca_driver

    args = ["--num-samples", "5", "--references", "1:0:30000"]
    pca_driver.run(args)
    first = capsys.readouterr()
    pca_driver.run(args)
    second = capsys.readouterr()
    assert first.out == second.out
    assert "heartbeat" not in first.out + first.err
    assert "manifest" not in first.out.lower()


def _write_small_vcf(tmp_path) -> str:
    rng = np.random.default_rng(7)
    lines = [
        "##fileformat=VCFv4.2",
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\t"
        + "\t".join(f"S{i}" for i in range(5)),
    ]
    for k in range(90):
        gts = rng.choice(["0|0", "0|1", "1|1"], size=5)
        info = f"AF={rng.random():.4f}" if k % 4 else "NS=2"
        lines.append(
            f"17\t{100 + 29 * k}\t.\tA\tG\t.\t.\t{info}\tGT\t" + "\t".join(gts)
        )
    path = tmp_path / "cohort.vcf"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_stats_parity_packed_streaming_wire_in_manifest(tmp_path, capsys):
    """The I/O stats block of the manifest is identical across the packed,
    streaming, and wire ingest paths of the same file — the parity the
    printed reports have always had, now asserted on the structured form."""
    from spark_examples_tpu.pipeline import pca_driver

    vcf = _write_small_vcf(tmp_path)
    base = [
        "--source", "file", "--input-files", vcf,
        "--references", "17:0:2700",
        "--min-allele-frequency", "0.05",
        "--block-size", "32",
    ]
    docs = {}
    for mode, extra in {
        "packed": ["--ingest", "packed", "--stream-chunk-bytes", "0"],
        "streamed": ["--stream-chunk-bytes", "256"],
        "wire": ["--ingest", "wire", "--stream-chunk-bytes", "0"],
    }.items():
        path = tmp_path / f"{mode}.json"
        pca_driver.run(base + extra + ["--metrics-json", str(path)])
        capsys.readouterr()
        docs[mode] = read_manifest(str(path))
        assert validate_manifest(docs[mode]) == []
    # Packed and streamed agree on the full block. The wire path's
    # `variants` deliberately counts pre-filter records seen (the
    # reference's RDD accounting, ``rdd/VariantsRDD.scala:214-224``), so it
    # bounds the packed count from above; every other field agrees.
    assert docs["packed"]["io_stats"] == docs["streamed"]["io_stats"]
    wire = dict(docs["wire"]["io_stats"])
    packed = dict(docs["packed"]["io_stats"])
    assert wire.pop("variants") >= packed.pop("variants")
    assert wire == packed
    # The overlap block lands in the manifest on the prefetching paths.
    for mode in ("packed", "streamed"):
        overlap = docs[mode]["overlap"]
        assert overlap is not None
        assert overlap["blocks"] >= 1
        assert (
            manifest_metric_value(docs[mode], "prefetch_blocks_total")
            == overlap["blocks"]
        )


def test_prefetch_overlap_structured_and_report_formats_it():
    from spark_examples_tpu.pipeline.datasets import PrefetchIterator

    reg = MetricsRegistry()
    prefetch = PrefetchIterator(iter(range(5)), depth=2, registry=reg)
    assert list(prefetch) == [0, 1, 2, 3, 4]
    prefetch.close()
    stats = prefetch.overlap_stats()
    assert stats["blocks"] == 5 and stats["queue_depth"] == 2
    report = prefetch.overlap_report()
    assert report == (
        f"ingest overlap: parse {stats['parse_busy_seconds']:.3f}s busy, "
        f"{stats['parse_blocked_on_feed_seconds']:.3f}s blocked on device "
        f"feed (backpressure); feeder waited "
        f"{stats['feeder_waited_on_parse_seconds']:.3f}s on parse; 5 blocks "
        f"through a depth-2 queue"
    )
    assert reg.value("prefetch_blocks_total") == 5
    assert reg.value("prefetch_queue_depth") == 2
    assert reg.value("ingest_overlap_parse_busy_seconds") == pytest.approx(
        stats["parse_busy_seconds"]
    )
    # close() froze the live occupancy gauge (sampler detached): the value
    # is the final queue size, and deltas no longer raise as they would on
    # a function-backed gauge.
    occupancy = reg.gauge("prefetch_queue_occupancy")
    assert reg.value("prefetch_queue_occupancy") == 0
    occupancy.inc(0)  # would raise MetricError if still function-backed


def test_gramian_flush_telemetry():
    from spark_examples_tpu.ops.gramian import GramianAccumulator

    reg = MetricsRegistry()
    rec = SpanRecorder()
    acc = GramianAccumulator(8, block_size=4, registry=reg, spans=rec)
    rows = np.ones((10, 8), dtype=np.uint8)
    with rec.span("ingest+similarity"):
        acc.add_rows(rows)
        acc.finalize_device()
    # 10 rows through a 4-row staging block: flushes of 4 + 4 + 2 (the
    # finalize flush); padding rows are not counted.
    assert reg.value("gramian_rows_total", {"strategy": "dense"}) == 10
    assert reg.value("gramian_flushes_total", {"strategy": "dense"}) == 3
    hist = reg.value("gramian_flush_seconds", {"strategy": "dense"})
    assert hist["count"] == 3
    (ingest,) = [s for s in rec.as_list() if s["name"] == "ingest+similarity"]
    names = [c["name"] for c in ingest["children"]]
    assert names == ["dispatch", "reduce-flush"]


# ----------------------------------------------- exposition-format escaping


def test_prometheus_label_value_escaping():
    """Regression: label values carrying the three characters the text
    exposition format names — backslash, double-quote, newline — must
    escape per the spec, backslash first (so the later replacements
    cannot double-escape their own output)."""
    reg = MetricsRegistry()
    gauge = reg.gauge("escape_test", "", labelnames=("path",))
    gauge.labels(path='C:\\temp\\"quoted"\nnext').set(1)
    text = reg.prometheus_text()
    line = next(l for l in text.splitlines() if l.startswith("escape_test"))
    assert line == (
        'escape_test{path="C:\\\\temp\\\\\\"quoted\\"\\nnext"} 1'
    )
    # Exactly one physical line: the raw newline never leaks through.
    assert sum(1 for l in text.splitlines() if "escape_test" in l) == 2
    # A literal backslash-n sequence stays distinguishable from a real
    # newline after escaping (the round-trip-ability the spec is for).
    gauge2 = reg.gauge("escape_test_2", "", labelnames=("v",))
    gauge2.labels(v="a\\nb").set(1)
    assert 'escape_test_2{v="a\\\\nb"} 1' in reg.prometheus_text()


def test_prometheus_help_text_escaping():
    """HELP lines escape backslash and newline (a raw newline would
    terminate the comment mid-help and leave an unparseable line)."""
    reg = MetricsRegistry()
    reg.counter("help_test", "line one\nline two \\ backslash").inc()
    text = reg.prometheus_text()
    assert "# HELP help_test line one\\nline two \\\\ backslash" in text
    for line in text.splitlines():
        assert line.startswith(("#", "help_test"))


def test_escape_helpers_are_exact():
    from spark_examples_tpu.obs.metrics import (
        escape_help_text,
        escape_label_value,
    )

    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    assert escape_label_value("\\\n\"") == '\\\\\\n\\"'
    assert escape_help_text('a"b') == 'a"b'  # quotes legal in help
    assert escape_help_text("a\\\nb") == "a\\\\\\nb"


# ----------------------------------------- span recorder under concurrency


def test_span_recorder_thread_safety_under_concurrent_slices():
    """The serve daemon's slice workers nest spans concurrently in ONE
    recorder (each slice its own thread): per-thread stacks must keep
    every tree correctly nested with zero cross-thread adoption and zero
    lost spans under a start-barrier stampede."""
    rec = SpanRecorder()
    workers, jobs_per_worker = 8, 25
    barrier = threading.Barrier(workers)
    errors = []

    def slice_worker(idx):
        try:
            barrier.wait(timeout=10)
            for j in range(jobs_per_worker):
                with rec.span(f"job w{idx}-{j}") as outer:
                    with rec.span("admission"):
                        pass
                    with rec.span("device"):
                        with rec.span("flush"):
                            pass
                # Closed and attached as this thread's root: never
                # adopted by another thread's open span.
                assert outer.seconds is not None
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [
        threading.Thread(target=slice_worker, args=(i,))
        for i in range(workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    roots = rec.as_list()
    assert len(roots) == workers * jobs_per_worker
    for root in roots:
        assert re.fullmatch(r"job w\d+-\d+", root["name"])
        assert [c["name"] for c in root["children"]] == [
            "admission",
            "device",
        ]
        assert [c["name"] for c in root["children"][1]["children"]] == [
            "flush"
        ]
        assert root["seconds"] is not None
    # Per-worker ordering survives the interleaving (roots attach at
    # close time, but each worker's own jobs close in order).
    for idx in range(workers):
        mine = [
            r["name"] for r in roots if r["name"].startswith(f"job w{idx}-")
        ]
        assert mine == [f"job w{idx}-{j}" for j in range(jobs_per_worker)]
    # The per-thread stacks drained: nothing left open.
    assert rec._stacks == {}


def test_span_recorder_concurrent_add_and_span():
    """Pre-measured add() aggregates from worker threads land as roots
    (or under that thread's open span), never under another thread's."""
    rec = SpanRecorder()
    stop = threading.Event()

    def adder():
        while not stop.is_set():
            rec.add("flush-aggregate", 0.001)

    t = threading.Thread(target=adder)
    t.start()
    try:
        for _ in range(50):
            with rec.span("driver-stage"):
                pass
    finally:
        stop.set()
        t.join(timeout=10)
    for root in rec.as_list():
        if root["name"] == "driver-stage":
            assert root["children"] == []


# --------------------------------------------- heartbeat replica segments


def test_heartbeat_replica_lease_steal_segments():
    from spark_examples_tpu.obs.metrics import (
        SERVE_JOBS_STOLEN,
        SERVE_LEASE_RENEWALS,
        SERVE_REPLICAS_ALIVE,
        well_known_counter,
        well_known_gauge,
    )

    reg = MetricsRegistry()
    hb = Heartbeat(60.0, reg)
    well_known_gauge(reg, SERVE_REPLICAS_ALIVE).set(0)
    # Solo mode (0 replicas heartbeating): the segment stays silent.
    assert "replicas" not in hb.line()
    well_known_gauge(reg, SERVE_REPLICAS_ALIVE).set(2)
    assert "replicas 2 alive" in hb.line()
    well_known_counter(reg, SERVE_JOBS_STOLEN).inc(3)
    well_known_counter(reg, SERVE_LEASE_RENEWALS).inc(17)
    line = hb.line()
    assert "replicas 2 alive (stolen 3, lease renewals 17)" in line

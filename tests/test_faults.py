"""Deterministic fault injection + crash-consistent checkpointing.

Four surfaces, one recovery story:

- the fault-plan grammar and the in-process hook semantics
  (``utils/faults.py``: registered sites, one-shot deterministic firing,
  kill/raise/crash vs ioerror/truncate/slow);
- crash consistency of both checkpoint families
  (``pipeline/checkpoint.py``): corruption is DETECTED (typed errors, not
  parser tracebacks), resume fast-forward splits blocks exactly, and the
  Gramian artifact round-trips with fingerprint enforcement;
- the subprocess chaos matrix: a real CLI run SIGKILLed at EVERY
  registered driver/checkpoint kill-point, resumed with ``--resume-from``,
  and the eigenvector TSV byte-compared against an uninterrupted oracle —
  the acceptance contract of ISSUE 9;
- the serve self-healing loop: an injected worker crash mid-job yields a
  ``failed`` job with a structured error while the daemon keeps serving,
  and a crash before device work requeues exactly once.

Plus the retry satellites: ``Retry-After`` + full jitter in
``sources/rest.py`` (counted into ``io_retries``) and idempotent-GET
retries in ``serve/client.py`` (POST stays single-shot).
"""

import email.message
import gzip
import io
import json
import os
import random
import signal
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from spark_examples_tpu.pipeline import checkpoint as cp
from spark_examples_tpu.utils import faults
from spark_examples_tpu.utils.retry import (
    full_jitter_delay,
    retry_after_seconds,
)

from helpers import run_cli

#: The injected worker crash (a BaseException) escapes its thread BY
#: DESIGN — pytest's unhandled-thread-exception warning is the expected
#: crash signature here, not a defect.
pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)

TINY_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]


@pytest.fixture(autouse=True)
def _reset_fault_plan():
    """Every test starts and ends with no active plan (configure(None)
    also blocks lazy env-var pickup, so a leaked SPARK_EXAMPLES_TPU_FAULTS
    cannot contaminate in-process tests)."""
    faults.configure(None)
    yield
    faults.configure(None)


# ------------------------------------------------------------ plan grammar


def test_parse_plan_grammar():
    entries = faults.parse_plan(
        "kill@driver.post-flush, raise@driver.pre-finalize#3,"
        "truncate@files.read=4096,slow@rest.post=0.05"
    )
    assert [(e.action, e.site, e.nth, e.arg) for e in entries] == [
        ("kill", "driver.post-flush", 1, None),
        ("raise", "driver.pre-finalize", 3, None),
        ("truncate", "files.read", 1, "4096"),
        ("slow", "rest.post", 1, "0.05"),
    ]


@pytest.mark.parametrize(
    "spec",
    [
        "no-at-sign",
        "explode@driver.post-flush",  # unknown action
        "kill@not.a.site",  # unknown site
        "kill@driver.post-flush#0",  # occurrence must be >= 1
        "kill@driver.post-flush#x",  # non-integer occurrence
        "truncate@files.read",  # truncate needs =BYTES
        "slow@rest.post=soon",  # slow needs =SECONDS
        "truncate@driver.post-flush=4",  # IO action at a kill-point
        "truncate@rest.post=4",  # rest.post carries no payload to shorten
        "raise@files.read",  # control action at an IO point
    ],
)
def test_parse_plan_rejects_bad_specs(spec):
    with pytest.raises(faults.FaultSpecError):
        faults.parse_plan(spec)


def test_config_rejects_bad_fault_plan_at_parse_time():
    from spark_examples_tpu.config import PcaConf

    with pytest.raises(ValueError):
        PcaConf.parse(TINY_FLAGS + ["--fault-plan", "kill@not.a.site"])


# ------------------------------------------------------------ hook behavior


def test_hooks_are_noops_without_a_plan():
    faults.kill_point("driver.post-flush")
    assert faults.io_point("files.read", b"payload") == b"payload"
    assert faults.injected_count() == 0


def test_unregistered_sites_raise_key_error():
    with pytest.raises(KeyError):
        faults.kill_point("driver.not-registered")
    with pytest.raises(KeyError):
        faults.io_point("files.not-registered")


def test_io_point_fires_at_exact_occurrence():
    faults.configure("truncate@files.read#2=3")
    assert faults.io_point("files.read", b"abcdef") == b"abcdef"
    assert faults.io_point("files.read", b"abcdef") == b"abc"
    # One-shot: the third hit passes through untouched.
    assert faults.io_point("files.read", b"abcdef") == b"abcdef"
    count, hits = faults.snapshot()
    assert count == 1 and hits == {"files.read": 3}


def test_io_point_ioerror_and_kill_point_raise():
    faults.configure("ioerror@files.read,raise@driver.pre-finalize")
    with pytest.raises(OSError, match="injected IO error"):
        faults.io_point("files.read", b"x")
    with pytest.raises(faults.InjectedFault):
        faults.kill_point("driver.pre-finalize")
    assert faults.injected_count() == 2


def test_worker_crash_escapes_except_exception():
    faults.configure("crash@serve.worker.mid-job")
    with pytest.raises(faults.InjectedWorkerCrash):
        try:
            faults.kill_point("serve.worker.mid-job")
        except Exception:  # noqa: BLE001 — the point: crash is NOT caught
            pytest.fail("InjectedWorkerCrash must escape `except Exception`")
    assert not issubclass(faults.InjectedWorkerCrash, Exception)


def test_io_fault_reaches_the_streamed_read_boundary(tmp_path):
    """The hook is wired into the real windowed read loop: an injected
    ioerror on the second window surfaces from the chunk iterator."""
    from spark_examples_tpu.sources.files import _iter_vcf_chunks

    path = tmp_path / "data.txt"
    path.write_bytes(b"line-one\nline-two\nline-three\n")
    faults.configure("ioerror@files.read#2")
    with pytest.raises(OSError, match="injected IO error"):
        list(_iter_vcf_chunks(str(path), chunk_bytes=64))


# -------------------------------------------------- retry arithmetic (shared)


def test_full_jitter_delay_is_bounded():
    rng = random.Random(7)
    for attempt in range(6):
        d = full_jitter_delay(attempt, 0.5, 8.0, rng)
        assert 0.0 <= d <= min(8.0, 0.5 * 2**attempt)


def test_retry_after_parses_and_caps():
    headers = email.message.Message()
    headers["Retry-After"] = "7"
    assert retry_after_seconds(headers, 60.0) == 7.0
    headers.replace_header("Retry-After", "9999")
    assert retry_after_seconds(headers, 8.0) == 8.0
    headers.replace_header("Retry-After", "not-a-date")
    assert retry_after_seconds(headers, 8.0) is None
    assert retry_after_seconds(None, 8.0) is None


def _http_error(code, retry_after=None):
    headers = email.message.Message()
    if retry_after is not None:
        headers["Retry-After"] = str(retry_after)
    return urllib.error.HTTPError(
        "http://svc/x", code, "boom", headers, io.BytesIO(b"")
    )


def test_rest_client_honors_retry_after_and_counts_retries():
    from spark_examples_tpu.pipeline.stats import VariantsDatasetStats
    from spark_examples_tpu.sources.rest import RestClient

    attempts = []
    sleeps = []

    def transport(url, payload, headers):
        attempts.append(url)
        if len(attempts) == 1:
            raise _http_error(429, retry_after=7)
        return {"ok": True}

    client = RestClient(
        auth=None,
        transport=transport,
        max_retries=3,
        backoff_base=0.5,
        backoff_cap=60.0,
        sleep=sleeps.append,
        rng=random.Random(0),
    )
    assert client._post("variants/search", {}) == {"ok": True}
    assert sleeps == [7.0]  # the server's word, not jitter
    assert client.counters.retries == 1
    assert client.counters.unsuccessful_responses == 1

    stats = VariantsDatasetStats()
    stats.add_client(client.counters)
    assert stats.as_dict()["io_retries"] == 1
    assert stats.registry.value("io_retries_total") == 1


def test_rest_client_caps_hostile_retry_after():
    from spark_examples_tpu.sources.rest import RestClient

    sleeps = []
    calls = []

    def transport(url, payload, headers):
        calls.append(url)
        if len(calls) < 3:
            raise _http_error(503, retry_after=99999)
        return {"ok": True}

    client = RestClient(
        auth=None,
        transport=transport,
        max_retries=3,
        backoff_cap=8.0,
        sleep=sleeps.append,
        rng=random.Random(0),
    )
    assert client._post("variants/search", {}) == {"ok": True}
    assert sleeps == [8.0, 8.0]  # a broken header cannot park the pipeline
    assert client.counters.retries == 2


def test_rest_client_falls_back_to_jitter_without_header():
    from spark_examples_tpu.sources.rest import RestClient

    sleeps = []
    calls = []

    def transport(url, payload, headers):
        calls.append(url)
        if len(calls) == 1:
            raise _http_error(500)
        return {"ok": True}

    client = RestClient(
        auth=None,
        transport=transport,
        max_retries=3,
        backoff_base=0.5,
        backoff_cap=8.0,
        sleep=sleeps.append,
        rng=random.Random(0),
    )
    assert client._post("variants/search", {}) == {"ok": True}
    assert len(sleeps) == 1 and 0.0 <= sleeps[0] <= 0.5


class _FakeResponse:
    def __init__(self, body=b'{"status": "ok"}'):
        self.status = 200
        self._body = body
        self.headers = email.message.Message()
        self.headers["Content-Type"] = "application/json"

    def read(self, n=-1):
        return self._body

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def test_serve_client_retries_idempotent_gets(monkeypatch):
    from spark_examples_tpu.serve.client import ServeClient

    calls = []

    def flaky(req, timeout=None):
        calls.append((req.get_method(), req.full_url))
        if len(calls) == 1:
            raise urllib.error.URLError(ConnectionResetError("reset"))
        if len(calls) == 2:
            raise _http_error(503, retry_after=0)
        return _FakeResponse()

    monkeypatch.setattr(urllib.request, "urlopen", flaky)
    client = ServeClient(
        "http://svc", max_retries=3, sleep=lambda s: None,
        rng=random.Random(0),
    )
    assert client.healthz() == {"status": "ok"}
    assert [m for m, _ in calls] == ["GET", "GET", "GET"]


def test_serve_client_post_is_single_shot(monkeypatch):
    from spark_examples_tpu.serve.client import ServeClient

    calls = []

    def refused(req, timeout=None):
        calls.append(req.get_method())
        raise urllib.error.URLError(ConnectionResetError("reset"))

    monkeypatch.setattr(urllib.request, "urlopen", refused)
    client = ServeClient(
        "http://svc", max_retries=3, sleep=lambda s: None
    )
    with pytest.raises(urllib.error.URLError):
        client.submit(TINY_FLAGS)
    assert calls == ["POST"]  # a retried submit could enqueue twice


# ------------------------------------------- variant checkpoint corruption


def _variant_records(n=30):
    from spark_examples_tpu.models.variant import VariantKey, VariantsBuilder

    records = []
    for i in range(n):
        built = VariantsBuilder.build(
            {
                "referenceName": "1",
                "variantSetId": "s",
                "id": f"v{i}",
                "start": 100 + i,
                "end": 101 + i,
                "referenceBases": "A",
                "alternateBases": ["T"],
                "info": {"AF": ["0.5"]},
                "calls": [
                    {
                        "callSetId": "s-0",
                        "callSetName": "S0",
                        "genotype": [0, 1],
                    }
                ],
            }
        )
        assert built is not None
        records.append((VariantKey("1", 100 + i), built[1]))
    return records


def _write_checkpoint(path, records):
    cp.save_variants(str(path), [records[:20], records[20:]])


def test_rematerialize_into_smaller_checkpoint_stays_loadable(tmp_path):
    """Re-running --save-variants into the same dir with fewer shards must
    drop the stale part files: the reader's parts cross-check would
    otherwise reject every later load of a perfectly good checkpoint."""
    path = tmp_path / "ckpt"
    records = _variant_records()
    cp.save_variants(str(path), [records[:10], records[10:20], records[20:]])
    cp.save_variants(str(path), [records[:20], records[20:]])  # 3 → 2 parts
    parts = sorted(n for n in os.listdir(path) if n.startswith("part-"))
    assert parts == ["part-00000.jsonl.gz", "part-00001.jsonl.gz"]
    loaded = cp.load_variants(str(path))
    assert sum(1 for _ in loaded) == len(records)


def test_missing_manifest_is_a_typed_error(tmp_path):
    path = tmp_path / "ckpt"
    _write_checkpoint(path, _variant_records())
    os.remove(path / "_manifest.json")
    with pytest.raises(cp.CheckpointCorruptError, match="never completed"):
        cp.load_variants(str(path))


def test_truncated_manifest_is_a_typed_error(tmp_path):
    """A crash mid-manifest-write used to surface as a raw JSONDecodeError;
    with the atomic publish it can only happen to an externally-damaged
    file — and still gets the clean 'cannot be trusted' diagnosis."""
    path = tmp_path / "ckpt"
    _write_checkpoint(path, _variant_records())
    manifest = path / "_manifest.json"
    manifest.write_bytes(manifest.read_bytes()[:10])
    with pytest.raises(
        cp.CheckpointCorruptError, match="truncated or unparseable"
    ):
        cp.load_variants(str(path))


def test_manifest_write_is_atomic(tmp_path):
    """The tmp file never lingers and the manifest appears only whole."""
    path = tmp_path / "ckpt"
    _write_checkpoint(path, _variant_records())
    leftovers = [n for n in os.listdir(path) if n.endswith(".tmp")]
    assert leftovers == []
    with open(path / "_manifest.json") as f:
        manifest = json.load(f)
    assert manifest["parts"] == 2 and manifest["records"] == 30


def test_deleted_part_fails_on_open(tmp_path):
    path = tmp_path / "ckpt"
    _write_checkpoint(path, _variant_records())
    os.remove(path / "part-00001.jsonl.gz")
    with pytest.raises(cp.CheckpointCorruptError, match="on disk"):
        cp.load_variants(str(path))


def test_foreign_part_fails_on_open(tmp_path):
    path = tmp_path / "ckpt"
    _write_checkpoint(path, _variant_records())
    with gzip.open(path / "part-00002.jsonl.gz", "wt") as f:
        f.write("{}\n")
    with pytest.raises(cp.CheckpointCorruptError, match="on disk"):
        cp.load_variants(str(path))


def test_record_count_mismatch_fails_on_full_iteration(tmp_path):
    path = tmp_path / "ckpt"
    records = _variant_records()
    _write_checkpoint(path, records)
    # Re-write one part with a record quietly dropped (same part count, so
    # open() passes; only the full-iteration re-count can prove the loss).
    part = path / "part-00000.jsonl.gz"
    with gzip.open(part, "rt") as f:
        lines = f.readlines()
    with gzip.open(part, "wt") as f:
        f.writelines(lines[:-1])
    loaded = cp.load_variants(str(path))
    with pytest.raises(cp.CheckpointCorruptError, match="full iteration"):
        list(loaded)


def test_truncated_part_gzip_stream_is_a_typed_error(tmp_path):
    path = tmp_path / "ckpt"
    _write_checkpoint(path, _variant_records())
    part = path / "part-00000.jsonl.gz"
    part.write_bytes(part.read_bytes()[:-7])  # torn gzip stream
    loaded = cp.load_variants(str(path))
    with pytest.raises(cp.CheckpointCorruptError):
        list(loaded)


# ------------------------------------------------ Gramian checkpoint + feeder


def _gramian_state(sites_shape=(1, 4, 4)):
    return {
        "strategy": "dense",
        "G": np.arange(np.prod(sites_shape), dtype=np.int32).reshape(
            sites_shape
        ),
        "accum_dtype": "int32",
        "exact_int": True,
        "entry_bound": 7,
        "rows_seen": 12,
        "flushes": 3,
        "num_samples": 4,
        "data_parallel": 1,
        "padded": 4,
    }


def test_gramian_checkpoint_round_trip_and_fingerprint(tmp_path):
    directory = str(tmp_path / "ck")
    cp.save_gramian_checkpoint(directory, _gramian_state(), "fp-1", 12)
    loaded = cp.load_gramian_checkpoint(directory, "fp-1")
    assert loaded["meta"]["sites"] == 12
    assert loaded["meta"]["accum_dtype"] == "int32"
    np.testing.assert_array_equal(loaded["G"], _gramian_state()["G"])
    # Fingerprint drift = a DIFFERENT analysis; merging would be silent lies.
    with pytest.raises(cp.CheckpointMismatchError, match="fingerprint"):
        cp.load_gramian_checkpoint(directory, "fp-2")


def test_gramian_checkpoint_absent_and_corrupt(tmp_path):
    assert cp.load_gramian_checkpoint(str(tmp_path / "nope")) is None
    directory = str(tmp_path / "ck")
    cp.save_gramian_checkpoint(directory, _gramian_state(), "fp", 1)
    artifact = os.path.join(directory, cp.GRAMIAN_CKPT)
    with open(artifact, "wb") as f:
        f.write(b"not an npz")
    with pytest.raises(cp.CheckpointCorruptError, match="delete"):
        cp.load_gramian_checkpoint(directory)


def test_gramian_checkpoint_bad_zip_tail_is_a_typed_error(tmp_path):
    """A valid zip magic with a corrupt tail (disk corruption, partial
    copy) raises BadZipFile inside np.load, not ValueError — it must get
    the same typed diagnosis as any other unreadable artifact."""
    directory = str(tmp_path / "ck")
    cp.save_gramian_checkpoint(directory, _gramian_state(), "fp", 1)
    artifact = os.path.join(directory, cp.GRAMIAN_CKPT)
    with open(artifact, "wb") as f:
        f.write(b"PK\x03\x04" + b"\x00" * 64)  # zip magic, garbage body
    with pytest.raises(cp.CheckpointCorruptError, match="delete"):
        cp.load_gramian_checkpoint(directory)


def test_gramian_checkpoint_save_sweeps_orphaned_tmps(tmp_path):
    """Every mid-write kill leaves a full-size pid-named tmp and each
    resume runs under a fresh pid — saves must sweep the orphans or a
    repeatedly-preempted run fills the directory with dead O(N²) files."""
    directory = str(tmp_path / "ck")
    os.makedirs(directory)
    orphan = os.path.join(directory, f"{cp.GRAMIAN_CKPT}.99999.tmp")
    with open(orphan, "wb") as f:
        f.write(b"x" * 128)
    cp.save_gramian_checkpoint(directory, _gramian_state(), "fp", 1)
    leftovers = [n for n in os.listdir(directory) if n.endswith(".tmp")]
    assert leftovers == []
    assert cp.load_gramian_checkpoint(directory, "fp")["meta"]["sites"] == 1


def test_gramian_checkpoint_fingerprint_ignores_robustness_flags():
    """The saving run and the resuming run must digest identically even
    though they differ in exactly the checkpoint/resume/fault flags."""
    from spark_examples_tpu.config import PcaConf

    saver = PcaConf.parse(
        TINY_FLAGS + ["--gramian-checkpoint-dir", "/tmp/a"]
    )
    resumer = PcaConf.parse(
        TINY_FLAGS
        + [
            "--gramian-checkpoint-dir",
            "/tmp/b",
            "--resume-from",
            "/tmp/a",
            "--checkpoint-every-sites",
            "17",
            "--fault-plan",
            "slow@rest.post=0",
        ]
    )
    other = PcaConf.parse(["--num-samples", "9"] + TINY_FLAGS[2:])
    assert cp.gramian_checkpoint_fingerprint(
        saver
    ) == cp.gramian_checkpoint_fingerprint(resumer)
    assert cp.gramian_checkpoint_fingerprint(
        saver
    ) != cp.gramian_checkpoint_fingerprint(other)


class _StubAcc:
    def __init__(self):
        self.fed = []
        self.restored = None

    def add_rows(self, rows):
        self.fed.append(np.asarray(rows))

    def restore_state(self, ckpt):
        self.restored = ckpt

    def snapshot_state(self):
        return _gramian_state()


def test_feeder_resume_skips_the_cursor_and_splits_blocks():
    acc = _StubAcc()
    resume = {"meta": {**_gramian_state(), "sites": 5}, "G": None}
    feeder = cp.GramianFeeder(acc, resume=resume)
    assert acc.restored is resume
    blocks = [np.arange(12).reshape(3, 4) + 10 * i for i in range(3)]
    for block in blocks:
        feeder.add_rows(block)
    # 5 rows skipped: block 0 whole (3), block 1 split (2 of 3).
    assert feeder.sites_skipped == 5
    assert feeder.checkpoint_sites == 5
    assert feeder.sites_done == 9
    fed = np.concatenate(acc.fed)
    np.testing.assert_array_equal(
        fed, np.concatenate(blocks)[5:]
    )


def test_feeder_finish_rejects_truncated_input_stream():
    """The fingerprint covers conf flags and input paths, not file
    contents: an input that SHRANK since the checkpoint was written is
    only detectable at end of ingest — finish() must refuse to finalize
    a silently wrong analysis from the stale partial."""
    acc = _StubAcc()
    resume = {"meta": {**_gramian_state(), "sites": 5}, "G": None}
    feeder = cp.GramianFeeder(acc, resume=resume)
    feeder.add_rows(np.arange(12).reshape(3, 4))  # stream ends at 3 < 5
    with pytest.raises(cp.CheckpointMismatchError):
        feeder.finish()
    assert acc.fed == []  # nothing past the cursor was ever fed


def test_feeder_saves_on_cadence_and_finish(tmp_path):
    directory = str(tmp_path / "ck")
    acc = _StubAcc()
    feeder = cp.GramianFeeder(
        acc, directory=directory, every_sites=4, fingerprint="fp"
    )
    feeder.add_rows(np.zeros((3, 4), dtype=np.uint8))
    assert feeder.saves == 0
    feeder.add_rows(np.zeros((3, 4), dtype=np.uint8))
    assert feeder.saves == 1  # crossed the 4-site cadence at 6
    assert cp.load_gramian_checkpoint(directory, "fp")["meta"]["sites"] == 6
    feeder.add_rows(np.zeros((1, 4), dtype=np.uint8))
    feeder.finish()  # final snapshot covers the tail
    assert feeder.saves == 2
    assert cp.load_gramian_checkpoint(directory, "fp")["meta"]["sites"] == 7


# ------------------------------------------------------ plan validator hooks


def test_plan_validates_checkpoint_and_fault_flags():
    from spark_examples_tpu.check.plan import validate_plan
    from spark_examples_tpu.config import PcaConf

    conf = PcaConf(
        references="1:0:50000",
        num_samples=8,
        pca_backend="host",
        gramian_checkpoint_dir="/tmp/ck",
        fault_plan="kill@not.a.site",
    )
    codes = [i.code for i in validate_plan(conf, plan_devices=1).issues]
    assert "checkpoint-backend" in codes
    assert "fault-plan" in codes

    conf = PcaConf(
        references="1:0:50000",
        num_samples=8,
        ingest="device",
        resume_from="/tmp/ck",
    )
    codes = [i.code for i in validate_plan(conf, plan_devices=1).issues]
    assert "checkpoint-device-ingest" in codes


# ------------------------------------------------------ chaos matrix (CLI)


#: Occurrence per kill-point: post-flush/mid-write/post-save use the 2nd
#: hit so at least one COMPLETE artifact precedes the crash (mid-write's
#: tmp is torn on top of it); pre-finalize fires once, after the final
#: snapshot — resume must then skip the whole stream.
CHAOS_MATRIX = [
    ("driver.post-flush", 2, True),
    ("checkpoint.mid-write", 2, True),
    ("checkpoint.post-save", 2, True),
    ("driver.pre-finalize", 1, True),
]


def test_chaos_matrix_covers_every_driver_kill_point():
    """The matrix below must enumerate every registered driver/checkpoint
    kill-point — a new kill-point without chaos coverage fails HERE."""
    registered = {
        site
        for site in faults.KILL_POINTS
        if site.startswith(("driver.", "checkpoint."))
    }
    assert registered == {site for site, _, _ in CHAOS_MATRIX}


def test_chaos_matrix_kill_resume_parity(tmp_path):
    """SIGKILL a real CLI run at every registered driver/checkpoint
    kill-point; ``--resume-from`` must reproduce the uninterrupted
    oracle's eigenvector TSV byte for byte (the int32/f32 exactness
    contracts make this assertable, not approximate), and the resumed
    manifest must carry the resume accounting block."""
    flags = [
        "variants-pca",
        "--num-samples", "8",
        "--references", "1:0:150000",
        "--ingest", "packed",
        "--checkpoint-every-sites", "40",
    ]
    oracle_out = tmp_path / "oracle"
    run_cli(
        flags
        + [
            "--gramian-checkpoint-dir", tmp_path / "ck-oracle",
            "--output-path", oracle_out,
        ],
        check=True,
    )
    oracle_tsv = (
        tmp_path / "oracle-pca.tsv" / "part-00000"
    ).read_bytes()
    assert oracle_tsv

    for site, nth, expect_skip in CHAOS_MATRIX:
        ck = tmp_path / f"ck-{site}"
        killed = run_cli(
            flags
            + ["--gramian-checkpoint-dir", ck, "--output-path",
               tmp_path / f"killed-{site}"],
            env_extra={"SPARK_EXAMPLES_TPU_FAULTS": f"kill@{site}#{nth}"},
        )
        assert killed.returncode == -signal.SIGKILL, (
            f"{site}: expected SIGKILL, got rc={killed.returncode}\n"
            f"{killed.stderr[-2000:]}"
        )
        resumed_out = tmp_path / f"resumed-{site}"
        manifest = tmp_path / f"resumed-{site}.json"
        run_cli(
            flags
            + [
                "--gramian-checkpoint-dir", ck,
                "--resume-from", ck,
                "--output-path", resumed_out,
                "--metrics-json", manifest,
            ],
            check=True,
        )
        resumed_tsv = (
            tmp_path / f"resumed-{site}-pca.tsv" / "part-00000"
        ).read_bytes()
        assert resumed_tsv == oracle_tsv, f"{site}: resume parity broken"
        doc = json.loads(manifest.read_text())
        resume = doc["resume"]
        assert resume is not None, f"{site}: manifest missing resume block"
        assert resume["faults_injected"] == 0
        assert resume["sites_skipped"] == resume["checkpoint_sites"]
        if expect_skip:
            # A complete artifact preceded the crash: the fast-forward
            # must have skipped real ingest.
            assert resume["sites_skipped"] > 0, f"{site}: nothing resumed"
        from spark_examples_tpu.obs.manifest import validate_manifest

        assert validate_manifest(doc) == []


def test_resume_from_torn_first_write_starts_from_zero(tmp_path):
    """A run killed DURING its very first artifact write leaves only the
    tmp file; resume must ignore it and start from zero, cleanly."""
    flags = [
        "variants-pca",
        "--num-samples", "8",
        "--references", "1:0:150000",
        "--ingest", "packed",
        "--checkpoint-every-sites", "40",
    ]
    ck = tmp_path / "ck"
    killed = run_cli(
        flags + ["--gramian-checkpoint-dir", ck],
        env_extra={
            "SPARK_EXAMPLES_TPU_FAULTS": "kill@checkpoint.mid-write#1"
        },
    )
    assert killed.returncode == -signal.SIGKILL
    names = os.listdir(ck)
    assert cp.GRAMIAN_CKPT not in names  # only the torn tmp remains
    manifest = tmp_path / "resumed.json"
    resumed = run_cli(
        flags + ["--resume-from", ck, "--metrics-json", manifest],
        check=True,
    )
    assert "Non zero rows in matrix: 8 / 8." in resumed.stdout
    doc = json.loads(manifest.read_text())
    assert doc["resume"]["sites_skipped"] == 0


def test_resume_rejects_fingerprint_drift(tmp_path):
    """Resuming with flags that shape a DIFFERENT analysis must fail
    loudly before any ingest, not merge two different Gramians."""
    base = [
        "variants-pca",
        "--num-samples", "8",
        "--references", "1:0:50000",
        "--ingest", "packed",
    ]
    ck = tmp_path / "ck"
    run_cli(base + ["--gramian-checkpoint-dir", ck], check=True)
    drifted = run_cli(
        [
            "variants-pca",
            "--num-samples", "12",
            "--references", "1:0:50000",
            "--ingest", "packed",
            "--resume-from", ck,
        ]
    )
    assert drifted.returncode != 0
    assert "fingerprint" in drifted.stderr


# ------------------------------------------------------- serve self-healing


class _InstantExecutor:
    def __init__(self):
        self.calls = 0

    def __call__(self, job, run_dir):
        from spark_examples_tpu.serve.executor import ExecutionOutcome

        self.calls += 1
        return ExecutionOutcome(
            result={"ok": True}, manifest_path=None, compile_cache="cold"
        )


def _wait_terminal(svc, job_id, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        _status, doc = svc.job_status(job_id)
        if doc["job"]["status"] in ("done", "failed", "cancelled"):
            return doc["job"]
        time.sleep(0.02)
    raise AssertionError(f"job {job_id} never reached a terminal state")


def _submit(svc, flags=TINY_FLAGS):
    from spark_examples_tpu.serve.protocol import request_doc

    status, doc = svc.submit(request_doc(flags))
    assert status == 202, doc
    return doc["job"]["id"]


def test_daemon_start_rejects_malformed_env_fault_plan(tmp_path, monkeypatch):
    """A typo'd SPARK_EXAMPLES_TPU_FAULTS must fail the daemon AT STARTUP
    (the batch path's run_pipeline does the same): lazily parsed at the
    first hook, it would instead surface as a crash/restart loop where
    every job rides its one requeue and then fails 'worker-crashed:'."""
    from spark_examples_tpu.serve.daemon import PcaService

    monkeypatch.setenv(faults.ENV_VAR, "kill@serve.wrker.claim")
    with faults._lock:
        faults._plan_entries = faults._UNSET  # arm lazy env-var pickup
    svc = PcaService(run_dir=str(tmp_path), executor=_InstantExecutor())
    with pytest.raises(faults.FaultSpecError):
        svc.start()


def test_watchdog_fails_mid_job_crash_and_keeps_serving(tmp_path):
    """The acceptance contract: a worker crash mid-job (after device work
    began) yields a failed job with a structured error, the daemon stays
    healthy, and the next job completes on a fresh worker — no requeue of
    jobs that already touched the devices."""
    from spark_examples_tpu.serve.daemon import PcaService

    executor = _InstantExecutor()
    faults.configure("crash@serve.worker.mid-job")
    svc = PcaService(run_dir=str(tmp_path), executor=executor).start()
    try:
        job = _wait_terminal(svc, _submit(svc))
        assert job["status"] == "failed"
        assert job["error"].startswith("worker-crashed:")
        assert "not requeued" in job["error"]
        assert executor.calls == 0  # the crash preempted the executor

        health = svc.healthz()
        assert health["status"] == "ok"
        assert health["queue"]["worker_alive"]
        assert health["queue"]["worker_restarts"] == 1

        job2 = _wait_terminal(svc, _submit(svc))
        assert job2["status"] == "done"
        assert executor.calls == 1
    finally:
        assert svc.stop(timeout=10.0)


def test_watchdog_requeues_claim_crash_once(tmp_path):
    """A crash BEFORE device work began is side-effect-free: the watchdog
    requeues the job once and it completes invisibly to the client."""
    from spark_examples_tpu.serve.daemon import PcaService

    executor = _InstantExecutor()
    faults.configure("crash@serve.worker.claim")
    svc = PcaService(run_dir=str(tmp_path), executor=executor).start()
    try:
        job = _wait_terminal(svc, _submit(svc))
        assert job["status"] == "done"
        assert executor.calls == 1
        assert svc.healthz()["queue"]["worker_restarts"] == 1
    finally:
        assert svc.stop(timeout=10.0)


def test_watchdog_double_claim_crash_fails_the_job(tmp_path):
    """The one-requeue bound: a job whose claim crashes the worker twice
    is failed, not retried forever."""
    from spark_examples_tpu.serve.daemon import PcaService

    executor = _InstantExecutor()
    faults.configure(
        "crash@serve.worker.claim#1,crash@serve.worker.claim#2"
    )
    svc = PcaService(run_dir=str(tmp_path), executor=executor).start()
    try:
        job = _wait_terminal(svc, _submit(svc))
        assert job["status"] == "failed"
        assert job["error"].startswith("worker-crashed:")
        assert "requeue" in job["error"]
        assert executor.calls == 0
        assert svc.healthz()["queue"]["worker_restarts"] == 2
        # And the daemon still serves.
        assert _wait_terminal(svc, _submit(svc))["status"] == "done"
    finally:
        assert svc.stop(timeout=10.0)


def test_drain_completes_after_a_crash(tmp_path):
    """A crash does not break the drain contract: remaining admitted jobs
    finish on the replacement worker and stop() returns True."""
    from spark_examples_tpu.serve.daemon import PcaService

    executor = _InstantExecutor()
    faults.configure("crash@serve.worker.mid-job")
    svc = PcaService(run_dir=str(tmp_path), executor=executor).start()
    first = _submit(svc)
    second = _submit(svc)
    svc.begin_drain()
    assert svc.wait_drained(timeout=10.0)
    _status, doc1 = svc.job_status(first)
    _status, doc2 = svc.job_status(second)
    statuses = {doc1["job"]["status"], doc2["job"]["status"]}
    assert statuses == {"failed", "done"}

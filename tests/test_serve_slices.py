"""Executor slices, continuous batching, and the job journal (serve/).

The PR-12 serving-concurrency contract:

- ``parallel/mesh.py:plan_executor_slices`` — deterministic device-range
  math: shared topology at 0 small slices, large slice never starved,
  index ranges disjoint and covering.
- ``serve/queue.py`` — class-filtered pops (a small-slice worker never
  sees large jobs), fingerprint-keyed ``pop_batch`` coalescing with
  max-batch and linger bounds.
- ``serve/daemon.py`` — small jobs complete WHILE a large job holds the
  large slice; a crashing large job never takes a small-slice worker
  with it; N concurrent submitters lose no jobs and duplicate none.
- ``serve/journal.py`` — accepted jobs survive a daemon "death"
  (simulated: a second service over the same run dir, the exact replay
  path a SIGKILL'd daemon takes — the ci.sh smoke kills a real process);
  requeue-once preserved via the journaled ``device_began`` flag.
- batching parity — a coalesced dispatch group's results are
  byte-identical to serial execution of the same requests.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from spark_examples_tpu.parallel.mesh import (
    ExecutorSlice,
    plan_executor_slices,
    resolve_small_slices,
)
from spark_examples_tpu.serve.daemon import PcaService
from spark_examples_tpu.serve.executor import ExecutionOutcome
from spark_examples_tpu.serve.journal import (
    JobJournal,
    compact_journal,
    replay_journal,
)
from spark_examples_tpu.serve.protocol import request_doc
from spark_examples_tpu.serve.queue import (
    LARGE_CLASS,
    SMALL_CLASS,
    BoundedJobQueue,
    Job,
    classify_conf,
)
from spark_examples_tpu.utils import faults
from spark_examples_tpu.utils.cache import (
    batch_compile_fingerprint,
    compile_fingerprint,
)

@pytest.fixture(autouse=True)
def _reset_fault_plan():
    """Every test starts and ends with no active fault plan (the crash
    tests configure one; a leak would poison unrelated tests)."""
    faults.configure(None)
    yield
    faults.configure(None)


TINY_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]
TINY_FLAGS_B = ["--num-samples", "8", "--references", "2:0:50000"]
LARGE_FLAGS = ["--num-samples", "8", "--all-references"]


def _job(job_id, job_class=SMALL_CLASS, batch_key=None):
    return Job(
        id=job_id,
        request=None,
        conf=None,
        job_class=job_class,
        submitted_unix=time.time(),
        batch_key=batch_key,
    )


def _wait_status(service, job_id, statuses, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        _http, doc = service.job_status(job_id)
        if doc.get("job", {}).get("status") in statuses:
            return doc["job"]
        time.sleep(0.02)
    raise AssertionError(
        f"job {job_id} never reached {statuses}: {service.job_status(job_id)}"
    )


# ----------------------------------------------------------- slice math


def test_plan_executor_slices_shared_topology():
    (shared,) = plan_executor_slices(8, small_slices=0)
    assert shared.name == "shared"
    assert set(shared.job_classes) == {SMALL_CLASS, LARGE_CLASS}
    assert shared.device_indices() == tuple(range(8))


def test_plan_executor_slices_partitions_disjoint_and_covering():
    slices = plan_executor_slices(8, small_slices=2, small_slice_devices=2)
    assert [s.name for s in slices] == ["large", "small-0", "small-1"]
    assert slices[0].job_classes == (LARGE_CLASS,)
    assert all(s.job_classes == (SMALL_CLASS,) for s in slices[1:])
    covered = [i for s in slices for i in s.device_indices()]
    assert sorted(covered) == list(range(8))  # disjoint + covering
    assert slices[0].device_count == 4


def test_plan_executor_slices_rejects_starved_large_slice():
    with pytest.raises(ValueError, match="leaving none for the large"):
        plan_executor_slices(2, small_slices=2, small_slice_devices=1)
    with pytest.raises(ValueError, match="device_count"):
        plan_executor_slices(0)
    with pytest.raises(ValueError, match="small_slice_devices"):
        plan_executor_slices(4, small_slices=1, small_slice_devices=0)


def test_resolve_small_slices_auto_rule():
    assert resolve_small_slices("auto", 8) == 1
    assert resolve_small_slices(None, 1) == 0
    assert resolve_small_slices(3, 8) == 3
    with pytest.raises(ValueError):
        resolve_small_slices(-1, 8)


def test_executor_slice_validation():
    with pytest.raises(ValueError, match=">= 1 device"):
        ExecutorSlice("x", (SMALL_CLASS,), 0, 0)
    with pytest.raises(ValueError, match="no job class"):
        ExecutorSlice("x", (), 0, 1)


# -------------------------------------------------- classify w/ limit


def test_classify_conf_honors_small_site_limit():
    from spark_examples_tpu.config import PcaConf

    conf = PcaConf()
    conf.references = "1:0:50000"  # ~500 candidate sites
    assert classify_conf(conf) == SMALL_CLASS
    assert classify_conf(conf, small_site_limit=100) == LARGE_CLASS
    assert classify_conf(conf, small_site_limit=501) == SMALL_CLASS


# -------------------------------------------------- class-filtered pops


def test_pop_classes_filter_and_drained_for():
    q = BoundedJobQueue()
    q.put(_job("S1"))
    q.put(_job("L1", LARGE_CLASS))
    # A small-only worker never sees the large job.
    assert q.pop(timeout=1, classes=(SMALL_CLASS,)).id == "S1"
    assert q.pop(timeout=0.05, classes=(SMALL_CLASS,)) is None
    q.close()
    assert q.drained_for((SMALL_CLASS,))
    assert not q.drained_for((LARGE_CLASS,))
    assert not q.drained
    assert q.pop(timeout=1, classes=(LARGE_CLASS,)).id == "L1"
    assert q.drained_for((LARGE_CLASS,)) and q.drained


def test_pop_unknown_class_rejected():
    q = BoundedJobQueue()
    with pytest.raises(ValueError):
        q.pop(timeout=0.01, classes=("medium",))


# ---------------------------------------------------- continuous batching


def test_pop_batch_coalesces_same_key_small_jobs():
    q = BoundedJobQueue()
    for i in range(3):
        q.put(_job(f"A{i}", batch_key="geomA"))
    q.put(_job("B0", batch_key="geomB"))
    q.put(_job("A3", batch_key="geomA"))
    batch = q.pop_batch(timeout=1, max_batch=8)
    assert [j.id for j in batch] == ["A0", "A1", "A2", "A3"]
    # The non-matching job kept its queue position.
    assert q.pop(timeout=1).id == "B0"


def test_pop_batch_respects_max_batch():
    q = BoundedJobQueue()
    for i in range(5):
        q.put(_job(f"A{i}", batch_key="geom"))
    batch = q.pop_batch(timeout=1, max_batch=3)
    assert [j.id for j in batch] == ["A0", "A1", "A2"]
    assert [j.id for j in q.pop_batch(timeout=1, max_batch=3)] == [
        "A3",
        "A4",
    ]


def test_pop_batch_large_and_keyless_jobs_never_coalesce():
    q = BoundedJobQueue()
    q.put(_job("L1", LARGE_CLASS, batch_key="geom"))
    q.put(_job("L2", LARGE_CLASS, batch_key="geom"))
    assert [j.id for j in q.pop_batch(timeout=1)] == ["L1"]
    q2 = BoundedJobQueue()
    q2.put(_job("S1"))  # batch_key None
    q2.put(_job("S2"))
    assert [j.id for j in q2.pop_batch(timeout=1)] == ["S1"]


def test_pop_batch_linger_collects_late_arrival():
    q = BoundedJobQueue()
    q.put(_job("A0", batch_key="geom"))

    def late_put():
        time.sleep(0.1)
        q.put(_job("A1", batch_key="geom"))

    t = threading.Thread(target=late_put)
    t.start()
    batch = q.pop_batch(timeout=1, max_batch=4, linger_seconds=1.0)
    t.join()
    assert [j.id for j in batch] == ["A0", "A1"]


def test_pop_batch_no_linger_dispatches_immediately():
    q = BoundedJobQueue()
    q.put(_job("A0", batch_key="geom"))
    started = time.monotonic()
    batch = q.pop_batch(timeout=1, max_batch=4, linger_seconds=0.0)
    assert [j.id for j in batch] == ["A0"]
    assert time.monotonic() - started < 0.5


# ----------------------------------------------------- batch fingerprint


def test_batch_fingerprint_region_invariant_but_geometry_sensitive():
    from spark_examples_tpu.config import PcaConf

    a = PcaConf()
    a.references = "1:0:50000"
    b = PcaConf()
    b.references = "2:100000:900000,3:0:50000"
    # Different regions: different compile fingerprints, SAME batch key.
    assert compile_fingerprint(a) != compile_fingerprint(b)
    assert batch_compile_fingerprint(a) == batch_compile_fingerprint(b)
    # Cohort width changes the compiled shapes: different batch key.
    c = PcaConf()
    c.references = "1:0:50000"
    c.num_samples = a.num_samples + 1
    assert batch_compile_fingerprint(a) != batch_compile_fingerprint(c)
    # Kind is part of the key.
    assert batch_compile_fingerprint(a, kind="pca") != (
        batch_compile_fingerprint(a, kind="similarity")
    )


# ------------------------------------------------------------- journal


def test_journal_round_trip_and_replay(tmp_path):
    path = str(tmp_path / "j.jsonl")
    journal = JobJournal(path)
    doc1 = request_doc(TINY_FLAGS, tag="t1")
    doc2 = request_doc(LARGE_FLAGS)
    journal.accepted("job-000001", doc1, SMALL_CLASS, 1.0, None)
    journal.accepted("job-000002", doc2, LARGE_CLASS, 2.0, 32.0)
    journal.began("job-000002")
    journal.accepted("job-000003", doc1, SMALL_CLASS, 3.0, None)
    journal.terminal("job-000001", "done")
    journal.close()
    pending, max_seq = replay_journal(path)
    assert max_seq == 3
    assert [(p.job_id, p.device_began) for p in pending] == [
        ("job-000002", True),
        ("job-000003", False),
    ]
    assert pending[0].deadline_unix == 32.0
    assert pending[1].request_doc == doc1


def test_journal_torn_last_line_skipped(tmp_path):
    path = str(tmp_path / "j.jsonl")
    journal = JobJournal(path)
    journal.accepted(
        "job-000001", request_doc(TINY_FLAGS), SMALL_CLASS, 1.0, None
    )
    journal.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"event": "terminal", "id": "job-0000')  # torn mid-write
    pending, max_seq = replay_journal(path)
    assert [p.job_id for p in pending] == ["job-000001"]
    assert max_seq == 1


def test_journal_compaction_drops_settled_records(tmp_path):
    path = str(tmp_path / "j.jsonl")
    journal = JobJournal(path)
    for i in (1, 2, 3):
        journal.accepted(
            f"job-{i:06d}", request_doc(TINY_FLAGS), SMALL_CLASS, 1.0, None
        )
    journal.terminal("job-000001", "done")
    journal.began("job-000002")
    journal.close()
    pending, _seq = replay_journal(path)
    compact_journal(path, [p for p in pending if not p.device_began])
    lines = [
        json.loads(line)
        for line in open(path, encoding="utf-8")
        if line.strip()
    ]
    assert [r["id"] for r in lines] == ["job-000003"]
    # Replay over the compacted file sees only the surviving job.
    pending2, seq2 = replay_journal(path)
    assert [p.job_id for p in pending2] == ["job-000003"]
    assert seq2 == 3


def test_journal_missing_file_is_empty(tmp_path):
    pending, max_seq = replay_journal(str(tmp_path / "nope.jsonl"))
    assert pending == [] and max_seq == 0


def test_journal_replay_is_order_insensitive(tmp_path):
    """began/terminal records landing BEFORE their accepted record (the
    appenders are concurrent threads) still count: a settled job never
    resurrects and a began job keeps the no-silent-re-run pin."""
    path = str(tmp_path / "j.jsonl")
    journal = JobJournal(path)
    journal.began("job-000001")
    journal.terminal("job-000001", "done")
    journal.accepted(
        "job-000001", request_doc(TINY_FLAGS), SMALL_CLASS, 1.0, None
    )
    journal.began("job-000002")
    journal.accepted(
        "job-000002", request_doc(TINY_FLAGS), SMALL_CLASS, 2.0, None
    )
    journal.close()
    pending, _seq = replay_journal(path)
    assert [(p.job_id, p.device_began) for p in pending] == [
        ("job-000002", True)
    ]


def test_queue_put_capacity_exempt_for_readmissions():
    q = BoundedJobQueue(small_capacity=1, large_capacity=1)
    q.put(_job("S1"))
    with pytest.raises(Exception):
        q.put(_job("S2"))
    # A replayed/requeued job was already admitted once: no 429.
    q.put(_job("S2"), enforce_capacity=False)
    assert q.pop(timeout=1).id == "S1"
    assert q.pop(timeout=1).id == "S2"


def test_rejected_admission_leaves_journal_tombstone(tmp_path):
    """A 429'd submit must not replay on restart: the accepted record it
    journaled before the put carries a terminal tombstone."""
    from spark_examples_tpu.serve.journal import journal_path

    gate = GateExecutor(block_classes=("small", "large"))
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        executor=gate,
        small_capacity=1,
        small_slices=0,
    ).start()
    try:
        assert service.submit(request_doc(TINY_FLAGS))[0] == 202
        assert gate.started.wait(timeout=10)
        assert service.submit(request_doc(TINY_FLAGS))[0] == 202  # fills lane
        status, _body = service.submit(request_doc(TINY_FLAGS))
        assert status == 429
        pending, _seq = replay_journal(
            journal_path(str(tmp_path / "serve"))
        )
        # Only the two genuinely admitted jobs are replayable.
        assert len(pending) == 2
    finally:
        gate.release.set()
        service.stop(timeout=30)


# ------------------------------------------------ daemon: slice topology


class GateExecutor:
    """Stub executor recording (id, slice, batch_size); large jobs block
    on the gate so the concurrency window is deterministic."""

    def __init__(self, block_classes=("large",)):
        self.order = []
        self.release = threading.Event()
        self.started = threading.Event()
        self.block_classes = block_classes
        self._lock = threading.Lock()  # lock order: test-local leaf

    def __call__(self, job, run_dir):
        with self._lock:
            self.order.append((job.id, job.slice, job.batch_size))
        self.started.set()
        if job.job_class in self.block_classes:
            assert self.release.wait(timeout=30), "gate never released"
        return ExecutionOutcome(
            result={"stub": True}, manifest_path=None, compile_cache="cold"
        )


@pytest.fixture
def sliced_service(tmp_path):
    gate = GateExecutor()
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        executor=gate,
        small_slices=1,
    ).start()
    yield service, gate
    gate.release.set()
    service.stop(timeout=30)


def test_sliced_service_topology_and_admission_devices(sliced_service):
    service, _gate = sliced_service
    health = service.healthz()
    names = [s["name"] for s in health["slices"]]
    assert names == ["large", "small-0"]
    # conftest forces 8 virtual devices: large gets 7, small slice 1.
    assert service.admission_devices(SMALL_CLASS) == 1
    assert service.admission_devices(LARGE_CLASS) == 7
    assert health["queue"]["worker_alive"]


def test_small_job_completes_while_large_job_runs(sliced_service):
    service, gate = sliced_service
    status, large = service.submit(request_doc(LARGE_FLAGS))
    assert status == 202, large
    assert gate.started.wait(timeout=10)
    status, small = service.submit(request_doc(TINY_FLAGS))
    assert status == 202, small
    done = _wait_status(service, small["job"]["id"], {"done"})
    assert done["slice"] == "small-0"
    # The large job is still ON the devices: no head-of-line blocking.
    _status, ldoc = service.job_status(large["job"]["id"])
    assert ldoc["job"]["status"] == "running"
    assert ldoc["job"]["slice"] == "large"
    gate.release.set()
    _wait_status(service, large["job"]["id"], {"done"})


def test_small_admission_validates_against_small_slice_devices(
    sliced_service,
):
    """A small job demanding a mesh bigger than its slice is rejected —
    the SAME geometry as a large job passes against the large slice."""
    service, gate = sliced_service
    mesh_flags = ["--num-samples", "8", "--mesh-shape", "1,2"]
    status, body = service.submit(
        request_doc(mesh_flags + ["--references", "1:0:50000"])
    )
    assert status == 400, body
    codes = [i["code"] for i in body["plan"]["issues"]]
    assert "mesh-exceeds-devices" in codes
    assert body["plan"]["geometry"]["plan_devices"] == 1
    gate.release.set()
    status, body = service.submit(request_doc(mesh_flags + ["--all-references"]))
    assert status == 202, body


def test_crashing_large_job_never_kills_small_slice(sliced_service):
    """Per-slice isolation: an InjectedWorkerCrash escaping a LARGE job
    kills only the large slice's worker; small jobs keep completing, the
    watchdog replaces the large worker, and the crashed job fails with
    the structured error."""
    service, gate = sliced_service

    crash_once = threading.Event()
    original_call = gate.__call__

    def crashing_call(job, run_dir):
        if job.job_class == LARGE_CLASS and not crash_once.is_set():
            crash_once.set()
            gate.order.append((job.id, job.slice, job.batch_size))
            raise faults.InjectedWorkerCrash("large job crashed")
        return original_call(job, run_dir)

    service._executor = crashing_call
    status, large = service.submit(request_doc(LARGE_FLAGS))
    assert status == 202
    crashed = _wait_status(service, large["job"]["id"], {"failed"})
    assert crashed["error"].startswith("worker-crashed:")
    # Small slice untouched, still serving.
    status, small = service.submit(request_doc(TINY_FLAGS))
    assert status == 202
    assert _wait_status(service, small["job"]["id"], {"done"})
    health = service.healthz()
    assert health["queue"]["worker_restarts"] == 1
    assert all(s["worker_alive"] for s in health["slices"])
    # And the replaced large worker serves large jobs again.
    gate.release.set()
    status, large2 = service.submit(request_doc(LARGE_FLAGS))
    assert status == 202
    assert _wait_status(service, large2["job"]["id"], {"done"})


# --------------------------------------------------- stress: no lost jobs


def test_concurrent_submitters_lose_and_duplicate_nothing(tmp_path):
    """N submitter threads x mixed kinds: every 202'd job reaches exactly
    one terminal state and the executor ran each at most once (exactly
    once for done jobs) — no lost, no duplicated work under the per-slice
    worker concurrency."""
    executed = []
    lock = threading.Lock()  # lock order: test-local leaf

    def executor(job, run_dir):
        with lock:
            executed.append(job.id)
        return ExecutionOutcome(
            result={"ok": True}, manifest_path=None, compile_cache="cold"
        )

    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        executor=executor,
        small_slices=1,
        small_capacity=64,
        large_capacity=64,
        terminal_retention=512,
    ).start()
    try:
        accepted = []
        accepted_lock = threading.Lock()  # lock order: test-local leaf
        kinds = [
            (TINY_FLAGS, "pca"),
            (TINY_FLAGS_B, "pca"),
            (TINY_FLAGS, "similarity"),
            (LARGE_FLAGS, "pca"),
        ]

        def submitter(seed):
            for i in range(6):
                flags, kind = kinds[(seed + i) % len(kinds)]
                status, doc = service.submit(request_doc(flags, kind=kind))
                assert status == 202, doc
                with accepted_lock:
                    accepted.append(doc["job"]["id"])

        threads = [
            threading.Thread(target=submitter, args=(s,)) for s in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(accepted) == 24
        assert len(set(accepted)) == 24  # no id reuse
        for job_id in accepted:
            job = _wait_status(service, job_id, {"done"}, timeout=60)
            assert job["status"] == "done"
        assert sorted(executed) == sorted(accepted)  # exactly once each
    finally:
        assert service.stop(timeout=30)


# ------------------------------------------------- journal replay (daemon)


def test_daemon_restart_replays_queued_job_and_fails_began_job(tmp_path):
    """Process-death simulation over one run dir: the successor daemon
    replays the journal — the queued job completes, the mid-device job
    fails with `daemon-restarted`, ids stay stable, and the terminal
    records of the previous life do NOT resurrect."""
    run_dir = str(tmp_path / "serve")
    gate = GateExecutor()
    first = PcaService(run_dir=run_dir, executor=gate, small_slices=0).start()
    status, done_doc = first.submit(request_doc(TINY_FLAGS))
    assert status == 202
    _wait_status(first, done_doc["job"]["id"], {"done"})
    status, running_doc = first.submit(request_doc(LARGE_FLAGS))
    assert status == 202
    assert gate.started.wait(timeout=10)
    _wait_status(first, running_doc["job"]["id"], {"running"})
    status, queued_doc = first.submit(request_doc(LARGE_FLAGS))
    assert status == 202
    # "SIGKILL": abandon `first` without draining (its gate stays held;
    # the ci.sh smoke does this against a real process with kill -9).

    finisher = GateExecutor(block_classes=())
    second = PcaService(
        run_dir=run_dir, executor=finisher, small_slices=0
    ).start()
    try:
        health = second.healthz()
        assert health["warm_state"]["journal_replayed"] == 2
        # The mid-device job: failed, never re-run.
        crashed = _wait_status(
            second, running_doc["job"]["id"], {"failed"}
        )
        assert "daemon-restarted" in crashed["error"]
        # The queued job: replayed and finished by the successor.
        replayed = _wait_status(second, queued_doc["job"]["id"], {"done"})
        assert replayed["status"] == "done"
        # The terminal job of the previous life did not resurrect.
        _status, done_again = second.job_status(done_doc["job"]["id"])
        assert done_again["error"]["code"] == "unknown-job"
        # New admissions continue the id sequence past the replayed ids.
        status, fresh = second.submit(request_doc(TINY_FLAGS))
        assert status == 202
        assert fresh["job"]["id"] > queued_doc["job"]["id"]
        _wait_status(second, fresh["job"]["id"], {"done"})
    finally:
        gate.release.set()
        first.stop(timeout=30)
        second.stop(timeout=30)


def test_replayed_job_rides_no_second_requeue(tmp_path):
    """Requeue-once across lives: a replayed job whose worker then
    crashes at claim is failed (the restart consumed its one retry)."""
    run_dir = str(tmp_path / "serve")
    gate = GateExecutor()
    first = PcaService(run_dir=run_dir, executor=gate, small_slices=0).start()
    status, running_doc = first.submit(request_doc(LARGE_FLAGS))
    assert status == 202
    assert gate.started.wait(timeout=10)
    status, queued_doc = first.submit(request_doc(LARGE_FLAGS))
    assert status == 202

    faults.configure("crash@serve.worker.claim")
    try:
        second = PcaService(
            run_dir=run_dir, executor=GateExecutor(block_classes=())
        ).start()
        try:
            job = _wait_status(
                second, queued_doc["job"]["id"], {"failed"}, timeout=30
            )
            assert "requeue" in job["error"]
        finally:
            second.stop(timeout=30)
    finally:
        faults.configure(None)
        gate.release.set()
        first.stop(timeout=30)


# --------------------------------------------------- batching parity e2e


def test_batched_results_byte_identical_to_serial(tmp_path):
    """Real executor: three compatible small jobs coalesced into one
    dispatch group return byte-identical PC rows to the same requests run
    serially (and to each other where the request is identical)."""
    from spark_examples_tpu.pipeline.pca_driver import run

    serial = {
        tuple(TINY_FLAGS): run(TINY_FLAGS),
        tuple(TINY_FLAGS_B): run(TINY_FLAGS_B),
    }
    gate = GateExecutor(block_classes=("small", "large"))
    service = PcaService(
        run_dir=str(tmp_path / "serve"), small_slices=0
    ).start()
    try:
        # Occupy the shared worker so the next three jobs coalesce.
        service._executor = gate
        status, blocker = service.submit(request_doc(TINY_FLAGS))
        assert status == 202
        assert gate.started.wait(timeout=10)
        service._executor = __import__(
            "spark_examples_tpu.serve.executor", fromlist=["execute_job"]
        ).execute_job
        docs = []
        for flags in (TINY_FLAGS, TINY_FLAGS_B, TINY_FLAGS):
            status, doc = service.submit(request_doc(flags))
            assert status == 202, doc
            docs.append((flags, doc))
        gate.release.set()
        _wait_status(service, blocker["job"]["id"], {"done"})
        for flags, doc in docs:
            job = _wait_status(service, doc["job"]["id"], {"done"}, 120)
            assert job["batch_size"] == 3  # the group coalesced
            assert job["result"]["pc_lines"] == serial[tuple(flags)]
    finally:
        gate.release.set()
        service.stop(timeout=60)


# ------------------------------------------------------ client + serve_main


def test_client_wait_honors_retry_after(monkeypatch):
    """The wait loop sleeps exactly what the server's Retry-After says
    (capped), falling back to full-jitter when absent."""
    from spark_examples_tpu.serve.client import ServeClient

    sleeps = []
    client = ServeClient("http://example.invalid", sleep=sleeps.append)
    responses = [
        (200, {"job": {"status": "running"}}, "", {"Retry-After": "0.25"}),
        (200, {"job": {"status": "running"}}, "", {}),
        (200, {"job": {"status": "done"}}, "", {}),
    ]

    def fake_request(method, path, doc=None, extra_headers=None):
        assert method == "GET" and path == "/v1/jobs/j1"
        return responses.pop(0)

    monkeypatch.setattr(client, "_request", fake_request)
    doc = client.wait("j1", timeout=10, poll_cap_seconds=0.5)
    assert doc["job"]["status"] == "done"
    assert sleeps[0] == 0.25  # server-paced
    assert 0.0 <= sleeps[1] <= 0.5  # jittered fallback, capped


def test_http_job_status_sends_retry_after(tmp_path):
    """Non-terminal GET /v1/jobs/<id> carries the poll hint; terminal
    responses do not."""
    import urllib.request

    from spark_examples_tpu.serve.http import (
        POLL_RETRY_AFTER_SECONDS,
        start_server,
    )

    gate = GateExecutor(block_classes=("small", "large"))
    service = PcaService(
        run_dir=str(tmp_path / "serve"), executor=gate
    ).start()
    server = start_server(service)
    try:
        status, doc = service.submit(request_doc(TINY_FLAGS))
        assert status == 202
        assert gate.started.wait(timeout=10)
        job_id = doc["job"]["id"]
        with urllib.request.urlopen(
            f"{server.url}/v1/jobs/{job_id}", timeout=10
        ) as resp:
            assert resp.headers["Retry-After"] == (
                f"{POLL_RETRY_AFTER_SECONDS:g}"
            )
        gate.release.set()
        _wait_status(service, job_id, {"done"})
        with urllib.request.urlopen(
            f"{server.url}/v1/jobs/{job_id}", timeout=10
        ) as resp:
            assert resp.headers["Retry-After"] is None
    finally:
        gate.release.set()
        server.shutdown()
        service.stop(timeout=30)


@pytest.mark.parametrize(
    "flags",
    [
        ["--serve-small-site-limit", "0"],
        ["--serve-small-site-limit", "-5"],
        ["--small-slice-devices", "0"],
        ["--batch-max-jobs", "0"],
        ["--batch-linger-seconds", "-1"],
        ["--executor-slices", "-1"],
        ["--executor-slices", "many"],
    ],
)
def test_serve_main_rejects_nonsense_flags_exit_2(flags):
    from spark_examples_tpu.serve.http import serve_main

    with pytest.raises(SystemExit) as excinfo:
        serve_main(["--port", "0"] + flags)
    assert excinfo.value.code == 2


def test_service_ctor_validates_serving_parameters(tmp_path):
    for kwargs in (
        {"small_site_limit": 0},
        {"batch_max_jobs": 0},
        {"batch_linger_seconds": -0.1},
        {"small_slices": -1},
        {"small_slice_devices": 0},
    ):
        with pytest.raises(ValueError):
            PcaService(run_dir=str(tmp_path), **kwargs)


def test_stop_on_never_started_service_returns_immediately(tmp_path):
    """A submit-before-start service has no worker to drain: stop() must
    return at once (no spin-until-timeout on the queued job)."""
    service = PcaService(run_dir=str(tmp_path / "serve"))
    status, _doc = service.submit(request_doc(TINY_FLAGS))
    assert status == 202  # admission does not require start()
    started = time.monotonic()
    assert service.stop(timeout=30)
    assert time.monotonic() - started < 2.0


def test_service_small_site_limit_reclassifies(tmp_path):
    """A tiny limit pushes every bounded query into the large class —
    the knob is live, not cosmetic."""
    gate = GateExecutor(block_classes=())
    service = PcaService(
        run_dir=str(tmp_path / "serve"),
        executor=gate,
        small_site_limit=10,
    ).start()
    try:
        status, doc = service.submit(request_doc(TINY_FLAGS))
        assert status == 202
        assert doc["job"]["class"] == LARGE_CLASS
    finally:
        service.stop(timeout=30)

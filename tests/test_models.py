"""Variant/Read builders: normalization, field mapping, round-trip."""

from spark_examples_tpu.models.read import ReadBuilder
from spark_examples_tpu.models.variant import VariantKey, VariantsBuilder


def test_normalize_strips_chr_prefix():
    # rdd/VariantsRDD.scala:89-96 — ([a-z]*)?([0-9]*) full-match, keep digits.
    assert VariantsBuilder.normalize("chr17") == "17"
    assert VariantsBuilder.normalize("17") == "17"
    assert VariantsBuilder.normalize("chr1") == "1"


def test_normalize_drops_nonmatching_contigs():
    # Uppercase and dotted names do not full-match → dropped (None).
    assert VariantsBuilder.normalize("X") is None
    assert VariantsBuilder.normalize("chrX") is None
    assert VariantsBuilder.normalize("MT") is None
    assert VariantsBuilder.normalize("GL000229.1") is None


def _wire_variant(**kw):
    base = {
        "referenceName": "chr17",
        "id": "var-1",
        "start": 41196320,
        "end": 41196321,
        "referenceBases": "A",
        "alternateBases": ["G"],
        "variantSetId": "vs-1",
        "created": 123,
        "info": {"AF": ["0.25"]},
        "calls": [
            {
                "callSetId": "vs-1-0",
                "callSetName": "NA00001",
                "genotype": [0, 1],
                "phaseset": "*",
            },
            {
                "callSetId": "vs-1-1",
                "callSetName": "NA00002",
                "genotype": [0, 0],
                "genotypeLikelihood": [-0.1, -0.5, -2.0],
            },
        ],
    }
    base.update(kw)
    return base


def test_build_maps_fields_and_normalizes():
    key, variant = VariantsBuilder.build(_wire_variant())
    # Partition key keeps the RAW reference name (rdd/VariantsRDD.scala:99).
    assert key == VariantKey("chr17", 41196320)
    # The variant's contig is normalized (rdd/VariantsRDD.scala:118-124).
    assert variant.contig == "17"
    assert variant.reference_bases == "A"
    assert variant.alternate_bases == ("G",)
    assert variant.info["AF"] == ["0.25"]
    assert variant.calls[0].genotype == (0, 1)
    assert variant.calls[0].has_variation()
    assert not variant.calls[1].has_variation()
    assert variant.calls[1].genotype_likelihood == (-0.1, -0.5, -2.0)


def test_build_drops_bad_contig():
    assert VariantsBuilder.build(_wire_variant(referenceName="chrX")) is None


def test_build_missing_optionals():
    wire = _wire_variant()
    del wire["alternateBases"], wire["calls"], wire["info"], wire["created"]
    _, variant = VariantsBuilder.build(wire)
    assert variant.alternate_bases is None
    assert variant.calls is None
    assert variant.info == {}
    assert variant.created == 0


def test_variant_json_round_trip():
    # The analog of the toJavaVariant round-trip smoke check
    # (SearchVariantsExample.scala:77-79).
    _, variant = VariantsBuilder.build(_wire_variant())
    wire2 = variant.to_json()
    # Round-tripping the normalized record is stable.
    _, variant2 = VariantsBuilder.build(wire2)
    assert variant2 == variant


def test_read_builder_flattens_alignment_and_cigar():
    wire = {
        "id": "read-1",
        "fragmentName": "frag-1",
        "readGroupSetId": "rgs-1",
        "alignedSequence": "ACGT",
        "alignedQuality": [30, 31, 32, 33],
        "fragmentLength": 300,
        "nextMatePosition": {"referenceName": "11", "position": 999},
        "alignment": {
            "position": {"referenceName": "11", "position": 100},
            "mappingQuality": 60,
            "cigar": [
                {"operationLength": 3, "operation": "ALIGNMENT_MATCH"},
                {"operationLength": 1, "operation": "CLIP_SOFT"},
            ],
        },
    }
    key, read = ReadBuilder.build(wire)
    assert key.sequence == "11" and key.position == 100
    assert read.cigar == "3M1S"  # rdd/ReadsRDD.scala:46-63
    assert read.mapping_quality == 60
    assert read.mate_position == 999
    assert read.mate_reference_name == "11"
    assert read.aligned_quality == (30, 31, 32, 33)


def test_read_builder_no_mate():
    wire = {
        "id": "r",
        "fragmentName": "f",
        "readGroupSetId": "g",
        "alignedSequence": "A",
        "alignedQuality": [30],
        "alignment": {
            "position": {"referenceName": "1", "position": 5},
            "mappingQuality": 20,
            "cigar": [],
        },
    }
    _, read = ReadBuilder.build(wire)
    assert read.mate_position is None
    assert read.cigar == ""


def test_distributed_flags_parse_and_noop():
    from spark_examples_tpu.config import GenomicsConf

    conf = GenomicsConf.parse(
        ["--coordinator-address", "host:1234", "--num-processes", "2",
         "--process-id", "0"]
    )
    assert conf.coordinator_address == "host:1234"
    assert conf.num_processes == 2 and conf.process_id == 0
    # Default (no flags): init is a no-op.
    GenomicsConf.parse([]).init_distributed()

"""``graftcheck proto``: the protocol model checker's own tests.

Bounds here are deliberately SMALL — the full default matrix is
``ci.sh``'s stage. What the unit tests pin is the contract: a clean
protocol explores to exhaustion with zero findings and full
crash-window coverage; every planted single-decision bug is caught by
its matching GP rule; and the CLI/report surfaces around both stay
stable. The kill-point registry<->call-site consistency scan rides
along (it is GP006's other half: the registry the model checks against
must describe real code).
"""

import json
import re

from spark_examples_tpu.check.cli import main as graftcheck_main
from spark_examples_tpu.check.proto import (
    MUTATIONS,
    Mutations,
    check_protocol,
    run_mutation_harness,
)
from spark_examples_tpu.utils import faults


def test_clean_protocol_small_matrix_is_clean():
    report = check_protocol(replicas=2, jobs=1, crashes=1, stalls=1)
    assert report.exhausted
    assert report.ok
    assert report.findings == []
    assert report.states > 100
    assert report.transitions > report.states
    assert report.uncovered_windows == []
    # Every serve-phase crash window the model can reach must have been
    # reached even at this small bound — a shrinking window set would
    # mean the model lost transitions, not that the protocol improved.
    assert set(report.crash_windows) == {
        "serve.submit.post-accept",
        "serve.lease.post-claim",
        "serve.worker.claim",
        "serve.worker.mid-job",
    }


def test_clean_protocol_two_jobs_is_clean():
    report = check_protocol(replicas=2, jobs=2, crashes=1, stalls=0)
    assert report.exhausted and report.ok, [
        f.format() for f in report.findings
    ]


def test_report_json_shape():
    report = check_protocol(replicas=2, jobs=1, crashes=1, stalls=0)
    doc = json.loads(report.to_json())
    assert doc["tool"] == "graftcheck-proto"
    assert doc["ok"] is True and doc["exhausted"] is True
    assert doc["bounds"] == {
        "replicas": 2,
        "jobs": 1,
        "crashes": 1,
        "stalls": 0,
    }
    assert doc["states"] > 0 and doc["transitions"] > 0
    assert doc["findings"] == [] and doc["uncovered_windows"] == []
    # The formatted report must declare its bounds (ci.sh echoes them).
    text = report.format()
    assert "bounds [crashes=1, jobs=1, replicas=2, stalls=0]" in text
    assert "exhaustive" in text


def test_max_states_cap_fails_closed():
    report = check_protocol(replicas=2, jobs=1, crashes=2, stalls=2,
                            max_states=50)
    assert not report.exhausted
    assert not report.ok  # a capped run is NOT a proof


def test_mutation_harness_catches_every_planted_bug():
    # Per-mutation witness bounds (each run early-stops at its first
    # expected finding) keep this inside the tier-1 budget.
    outcomes = run_mutation_harness()
    assert len(outcomes) == len(MUTATIONS) >= 8
    missed = [o.name for o in outcomes if not o.caught]
    assert missed == [], missed
    for outcome in outcomes:
        assert outcome.expected in outcome.tripped
        assert outcome.states > 0
        assert set(outcome.bounds) == {
            "replicas", "jobs", "crashes", "stalls",
        }


def test_mutation_harness_bound_override_applies_everywhere():
    # stalls=0 removes lease expiry entirely: the graceless-steal bug
    # CANNOT trip (no steal ever happens), and the harness must report
    # that as a miss instead of silently restoring witness bounds.
    outcomes = run_mutation_harness(jobs=1, stalls=0)
    by_name = {o.name: o for o in outcomes}
    assert not by_name["graceless-steal"].caught
    assert by_name["graceless-steal"].bounds["stalls"] == 0


def test_mutation_findings_carry_witness_traces():
    mutation = next(m for m in MUTATIONS if m.name == "graceless-steal")
    report = check_protocol(
        replicas=2,
        jobs=1,
        crashes=1,
        stalls=1,
        mutations=mutation.mutations,
        stop_on_rule="GP005",
    )
    findings = [f for f in report.findings if f.rule_id == "GP005"]
    assert findings
    assert "[witness:" in findings[0].detail


def test_gp006_trips_on_unregistered_crash_window():
    report = check_protocol(
        replicas=2,
        jobs=1,
        crashes=1,
        stalls=0,
        mutations=Mutations(unregistered_crash_site=True),
        stop_on_rule="GP006",
    )
    assert any(f.rule_id == "GP006" for f in report.findings)
    assert report.uncovered_windows


def test_cli_proto_clean(capsys):
    rc = graftcheck_main(
        ["proto", "--replicas", "2", "--jobs", "1", "--crashes", "1",
         "--stalls", "1"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "bounds [crashes=1, jobs=1, replicas=2, stalls=1]" in out
    assert "clean: every reachable state satisfies GP001-GP006" in out


def test_cli_proto_json(capsys):
    rc = graftcheck_main(
        ["proto", "--replicas", "2", "--jobs", "1", "--crashes", "1",
         "--stalls", "0", "--json"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["ok"] and doc["states"] > 0


def test_cli_proto_rejects_nonsense_bounds(capsys):
    assert graftcheck_main(["proto", "--replicas", "0"]) == 2
    assert graftcheck_main(["proto", "--crashes", "-1"]) == 2


def test_cli_proto_mutations_json(capsys):
    rc = graftcheck_main(["proto", "--mutations", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    outcomes = json.loads(out)
    assert len(outcomes) >= 8
    assert all(o["caught"] for o in outcomes)
    assert {o["expected"] for o in outcomes} == {
        "GP001", "GP002", "GP003", "GP004", "GP005", "GP006",
    }


# --------------------------------------------------- kill-point registry


_KILL_POINT_CALL = re.compile(r'kill_point\(\s*"([^"]+)"\s*\)')


def _kill_point_call_sites():
    """Every string-literal ``kill_point("...")`` call in the package,
    ``{site: [relpath, ...]}``."""
    import os

    import spark_examples_tpu

    root = os.path.dirname(os.path.abspath(spark_examples_tpu.__file__))
    sites = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        for filename in filenames:
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            rel = os.path.relpath(path, root)
            if rel == os.path.join("utils", "faults.py"):
                continue  # the registry itself, not a call site
            with open(path, "r", encoding="utf-8") as handle:
                for match in _KILL_POINT_CALL.finditer(handle.read()):
                    sites.setdefault(match.group(1), []).append(rel)
    return sites


def test_kill_point_registry_matches_call_sites():
    registry = faults.registered_kill_points()
    sites = _kill_point_call_sites()
    # Every call site names a registered point: an unregistered literal
    # is a chaos window the matrix (and GP006) cannot see.
    unregistered = sorted(set(sites) - set(registry))
    assert unregistered == [], unregistered
    # Every registered point is called somewhere: a dangling registry
    # entry would let GP006 claim coverage no code provides.
    dangling = sorted(set(registry) - set(sites))
    assert dangling == [], dangling


def test_kill_point_registry_locations_name_real_modules():
    import os

    import spark_examples_tpu

    root = os.path.dirname(os.path.abspath(spark_examples_tpu.__file__))
    for site, where in faults.registered_kill_points().items():
        module = where.split(":", 1)[0].split(" ", 1)[0]
        assert os.path.exists(os.path.join(root, module)), (site, where)

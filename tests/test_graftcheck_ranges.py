"""``graftcheck ranges``: the abstract-interpretation overflow/exactness
prover. Golden audits across the mesh/dtype matrix (the shipped kernels
must PROVE clean, with the ring's disjoint-slice refinement engaged),
broken-kernel fixtures per GR rule, the interpreter's interval lattice,
the shared contract registry (``ops/contracts.py``), the ``graftcheck
plan`` exactness accept/reject matrix including the exact boundary
geometry, the GC011 narrowing-cast lint rule, the ``--check-ranges``
runtime sampling pair and its manifest block, and the zero-live-arrays
contract."""

import dataclasses
import json
import textwrap

import numpy as np
import pytest

from spark_examples_tpu.check.linter import lint_source
from spark_examples_tpu.check.plan import validate_plan
from spark_examples_tpu.check.ranges import (
    AbsVal,
    Interpreter,
    RangeKernelSpec,
    audit_range_kernel,
    counts_range_spec,
    default_specs,
    dense_range_spec,
    ring_range_spec,
    run_audit,
)
from spark_examples_tpu.check.rules import RANGES_RULES, RULES
from spark_examples_tpu.config import PcaConf
from spark_examples_tpu.ops.contracts import (
    COUNT_ROW,
    HAS_VARIATION,
    PACKED_BYTE,
    RangeContract,
    exact_int_window,
    exactness_headroom_sites,
    flush_entry_increment,
)

INT32_WINDOW = exact_int_window(np.int32)
F32_WINDOW = exact_int_window(np.float32)


def _rule_ids(audit):
    return sorted({f.rule_id for f in audit.findings})


# --------------------------------------------------------------------------
# The contract registry.
# --------------------------------------------------------------------------


def test_exact_int_windows():
    assert F32_WINDOW == 1 << 24
    assert exact_int_window("bfloat16") == 1 << 8
    assert exact_int_window(np.float64) == 1 << 53
    assert INT32_WINDOW == 2**31 - 1
    assert exact_int_window(np.uint8) == 255
    assert exact_int_window(np.int8) == 127
    assert exact_int_window("not-a-dtype") is None


def test_flush_entry_increment_and_headroom():
    assert flush_entry_increment(1024, 1) == 1024
    assert flush_entry_increment(1024, 3) == 9216
    assert exactness_headroom_sites(np.float32, 1) == F32_WINDOW
    assert exactness_headroom_sites(np.int32, 2) == INT32_WINDOW // 4
    assert exactness_headroom_sites("not-a-dtype", 1) == 0


def test_gramian_exact_limit_is_shared():
    # The accumulator conversion threshold and the contract registry are
    # ONE constant — the GR005 story depends on it.
    from spark_examples_tpu.ops.contracts import EXACT_F32_LIMIT
    from spark_examples_tpu.ops.gramian import (
        EXACT_F32_LIMIT as GRAMIAN_LIMIT,
    )

    assert GRAMIAN_LIMIT is EXACT_F32_LIMIT
    assert GRAMIAN_LIMIT == F32_WINDOW


def test_ranges_rules_registered():
    from spark_examples_tpu.check.rules import ALL_RULES

    for rule_id in ("GR000", "GR001", "GR002", "GR003", "GR004", "GR005"):
        assert rule_id in RANGES_RULES
        assert rule_id in ALL_RULES


# --------------------------------------------------------------------------
# The interval lattice on small traced programs.
# --------------------------------------------------------------------------


def _interp(fn, in_vals, *avals, axis_sizes=None):
    import jax

    closed = jax.make_jaxpr(fn)(*avals)
    return Interpreter(axis_sizes or {}).run(closed, list(in_vals))


def test_interpreter_arithmetic():
    import jax
    import jax.numpy as jnp

    x = jax.ShapeDtypeStruct((4,), jnp.int32)
    (out,) = _interp(
        lambda a, b: a * b + 3,
        [AbsVal(0, 2, True), AbsVal(0, 5, True)],
        x,
        x,
    )
    assert (out.lo, out.hi, out.integer) == (3.0, 13.0, True)


def test_interpreter_dot_contraction():
    import jax
    import jax.numpy as jnp

    a = jax.ShapeDtypeStruct((4, 16), jnp.float32)
    b = jax.ShapeDtypeStruct((16, 4), jnp.float32)
    interp = Interpreter({})
    closed = jax.make_jaxpr(lambda x, y: x @ y)(a, b)
    (out,) = interp.run(closed, [AbsVal(0, 1, True), AbsVal(0, 2, True)])
    # 16 products each in [0, 2].
    assert (out.lo, out.hi) == (0.0, 32.0)
    assert len(interp.dots) == 1
    assert interp.dots[0].contraction == 16


def test_interpreter_scan_widening():
    import jax
    import jax.numpy as jnp
    from jax import lax

    def f(x):
        return lax.fori_loop(0, 10, lambda i, c: c + x, jnp.float32(0))

    (out,) = _interp(
        f, [AbsVal(0, 3, True)], jax.ShapeDtypeStruct((), jnp.float32)
    )
    # Outward widening: 10 trips of growth <= 3.
    assert out.lo == 0.0
    assert out.hi == 30.0


def test_interpreter_unpack_tightens_packed_bytes():
    import jax
    import jax.numpy as jnp

    from spark_examples_tpu.ops.gramian import _unpack_bits

    x = jax.ShapeDtypeStruct((4, 2), jnp.uint8)
    (out,) = _interp(
        lambda p: _unpack_bits(p, 16), [AbsVal(0, 255, True)], x
    )
    # The shift-and-mask unpack provably yields membership bits.
    assert (out.lo, out.hi, out.integer) == (0.0, 1.0, True)


# --------------------------------------------------------------------------
# Golden audits: the shipped kernels PROVE clean across the matrix.
# --------------------------------------------------------------------------


def test_shipped_matrix_proves_clean():
    report = run_audit()
    assert report.ok, "\n".join(f.format() for f in report.findings)
    # 2x(dense+counts) + 2 stacked fused-group sizes + 3 meshes x
    # (2 pack x 2 dtype + 1 counts-ring + 1 devicegen-ring)
    assert len(report.audits) == 24
    for audit in report.audits:
        assert audit.facts["entry_increment"] is not None
        assert (
            audit.facts["flush_projection"]
            >= audit.facts["entry_increment"]
        )
        assert audit.facts["exactness_headroom_sites"]["int32"] > 0
    doc = json.loads(report.to_json())
    assert doc["tool"] == "graftcheck-ranges"
    assert doc["ok"] is True


def test_ring_disjoint_slice_refinement_engages():
    # The proof that matters: the ring's per-dispatch entry increment is
    # ONE dot partial (B x max_count²), not samples x that — the
    # dynamic_update_slice disjointness was PROVEN, not assumed.
    audit = audit_range_kernel(ring_range_spec(1, 4, 64, 8, True, False))
    assert audit.ok, [f.format() for f in audit.findings]
    assert audit.facts["entry_increment"] == 8.0
    assert audit.facts["entry_increment_conservative"] == 32.0
    assert audit.facts["dot_partial_bound"] == 8.0


def test_counts_ring_kernel_audited_under_join_contract():
    # Same-set-join flushes ride the UNPACKED ring kernel regardless of
    # --ring-pack-bits; the count contract must be proven on that path.
    audit = audit_range_kernel(
        ring_range_spec(1, 4, 64, 8, True, False, counts=True)
    )
    assert audit.ok, [f.format() for f in audit.findings]
    assert audit.facts["input_contracts"] == [None, COUNT_ROW.name]
    assert audit.facts["entry_increment"] == 8 * COUNT_ROW.hi**2


def test_ring_passes_multiply_refined_increment():
    # The disjointness proof bounds one update per entry per RING PASS;
    # an enclosing scan of length T runs T passes, so the refined
    # increment must scale by T (the unsound-direction regression the
    # review caught). Wrap the ring update in an outer fori_loop.
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import AbstractMesh

    from spark_examples_tpu.check.ranges import RangeKernelSpec
    from spark_examples_tpu.ops.gramian import build_sharded_update
    from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS

    T = 3

    def build():
        mesh = AbstractMesh(((DATA_AXIS, 1), (SAMPLES_AXIS, 4)))
        update = build_sharded_update(mesh, np.float32, True)

        def repeated(G, X):
            return lax.fori_loop(0, T, lambda _, g: update(g, X), G)

        G = jax.ShapeDtypeStruct((1, 64, 64), jnp.float32)
        X = jax.ShapeDtypeStruct((1, 8, 8), jnp.uint8)
        return repeated, (G, X)

    spec = RangeKernelSpec(
        name="fixture:ring-x3",
        build=build,
        input_contracts=(None, PACKED_BYTE),
        axis_sizes={DATA_AXIS: 1, SAMPLES_AXIS: 4},
        rows_per_flush=T * 8,
        max_count=1,
    )
    audit = audit_range_kernel(spec)
    assert audit.ok, [f.format() for f in audit.findings]
    # T passes x one dot partial (8) per entry per pass.
    assert audit.facts["entry_increment"] == T * 8


def test_counts_kernel_uses_join_ceiling():
    audit = audit_range_kernel(counts_range_spec(1, 64, 8))
    assert audit.ok
    # B x COUNT_ROW.hi² per dispatch.
    assert audit.facts["entry_increment"] == 8 * COUNT_ROW.hi**2
    assert (
        audit.facts["exactness_headroom_sites"]["float32"]
        == F32_WINDOW // COUNT_ROW.hi**2
    )


def test_zero_live_arrays_after_audit():
    import jax

    before = len(jax.live_arrays())
    run_audit(default_specs(num_samples=64, block_size=8, meshes=((1, 2),)))
    # Pure tracing: no device buffer outlives the audit.
    assert len(jax.live_arrays()) == before


# --------------------------------------------------------------------------
# Broken-kernel fixtures: one per GR rule.
# --------------------------------------------------------------------------


def test_gr000_trace_failure():
    def build():
        raise RuntimeError("deliberately broken builder")

    audit = audit_range_kernel(
        RangeKernelSpec(
            name="fixture:trace-failure",
            build=build,
            input_contracts=(),
            acc_invar=None,
        )
    )
    assert _rule_ids(audit) == ["GR000"]


def test_gr001_declared_geometry_overflow():
    spec = dataclasses.replace(
        ring_range_spec(1, 2, 64, 8, True, exact_int=True),
        declared_rows=3_000_000_000,
    )
    audit = audit_range_kernel(spec)
    assert "GR001" in _rule_ids(audit)
    assert audit.facts["gramian_entry_bound"] == 3_000_000_000


def test_gr001_per_dispatch_int32_partial():
    # A single dispatch whose int32 partial can pass 2^31: giant block.
    audit = audit_range_kernel(
        ring_range_spec(1, 2, 64, 3_000_000_000, True, exact_int=True)
    )
    assert "GR001" in _rule_ids(audit)


def test_gr002_f32_partial_past_window():
    # B x max_count² > 2^24 on the f32 path: exactness lost before the
    # conversion point could ever fire.
    audit = audit_range_kernel(dense_range_spec(1, 64, (1 << 24) + 8))
    assert "GR002" in _rule_ids(audit)


def test_gr003_lossy_cast():
    def build():
        import jax
        import jax.numpy as jnp

        x = jax.ShapeDtypeStruct((8,), jnp.int32)
        return (lambda v: v.astype(jnp.bfloat16), (x,))

    wide = RangeContract("fixture_wide", 0, 1 << 20, "fixture", True)
    audit = audit_range_kernel(
        RangeKernelSpec(
            name="fixture:lossy-cast",
            build=build,
            input_contracts=(wide,),
            acc_invar=None,
        )
    )
    assert _rule_ids(audit) == ["GR003"]
    assert "bfloat16" in audit.findings[0].detail


def test_gr004_uncontracted_dot_input():
    spec = dataclasses.replace(
        dense_range_spec(1, 64, 8), input_contracts=(None, None)
    )
    audit = audit_range_kernel(spec)
    assert "GR004" in _rule_ids(audit)


def test_gr005_broken_projection():
    # A projection that forgets max_count² under-projects the counts
    # kernel's proven per-dispatch increment: the conversion would fire
    # late.
    spec = dataclasses.replace(
        counts_range_spec(1, 64, 8),
        projection=lambda rows, max_count: rows,
    )
    audit = audit_range_kernel(spec)
    assert _rule_ids(audit) == ["GR005"]
    assert "fire late" in audit.findings[0].detail


def test_cli_exit_codes(capsys):
    from spark_examples_tpu.check import cli

    assert cli.main(["ranges", "--mesh", "1,2"]) == 0
    capsys.readouterr()
    assert cli.main(["ranges", "--json", "--mesh", "1,2"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["tool"] == "graftcheck-ranges"
    assert doc["ok"] is True
    assert cli.main(["ranges", "--mesh", "bogus"]) == 2


# --------------------------------------------------------------------------
# graftcheck plan: exactness facts + accept/reject matrix.
# --------------------------------------------------------------------------


def _plan(args, devices=1):
    conf = PcaConf.parse(args)
    return validate_plan(conf, plan_devices=devices)


def test_plan_reports_exactness_facts():
    report = _plan(["--num-samples", "64", "--references", "1:0:50000"])
    assert report.ok
    assert report.geometry["exactness_headroom_sites"] == {
        "float32": F32_WINDOW,
        "int32": INT32_WINDOW,
    }
    # 50000 bases / spacing 100 + 1 candidate sites.
    assert report.geometry["gramian_entry_bound"] == 501
    assert any("range audit" in line for line in report.shape_checks)


def test_plan_headroom_shrinks_with_duplicate_sets():
    report = _plan(
        [
            "--num-samples", "64", "--references", "1:0:50000;1:0:50000",
            "--variant-set-id", "a,a",
        ]
    )
    assert report.geometry["exactness_headroom_sites"]["float32"] == (
        F32_WINDOW // 4
    )


def test_plan_sharded_duplicate_ids_audits_counts_ring():
    # A sharded same-set-join config must prove the UNPACKED count-valued
    # ring path too (the kernel its flushes actually ride), not just the
    # packed-[0,1] ring.
    report = _plan(
        [
            "--num-samples", "64", "--references", "1:0:50000;1:0:50000",
            "--variant-set-id", "a,a", "--mesh-shape", "1,4",
            "--similarity-strategy", "sharded",
        ],
        devices=4,
    )
    assert report.ok, [i.format() for i in report.issues]
    assert any(
        "range audit (2 kernel(s))" in line for line in report.shape_checks
    )


def test_plan_exactness_boundary_geometry():
    # sites = span // 100 + 1; the int32 window is the exact boundary.
    at_window = (INT32_WINDOW - 1) * 100
    accept = _plan(
        [
            "--num-samples", "64",
            "--references", f"1:0:{at_window}",
            "--bases-per-partition", "1000000000000",
        ]
    )
    assert accept.ok, [i.format() for i in accept.issues]
    assert accept.geometry["gramian_entry_bound"] == INT32_WINDOW

    reject = _plan(
        [
            "--num-samples", "64",
            "--references", f"1:0:{at_window + 100}",
            "--bases-per-partition", "1000000000000",
        ]
    )
    assert not reject.ok
    assert any(i.code == "exactness-window" for i in reject.issues)


def test_plan_rejects_partial_past_f32_window():
    report = _plan(
        [
            "--num-samples", "8", "--references", "1:0:50000",
            "--block-size", str((1 << 24) + 8),
        ]
    )
    assert not report.ok
    assert any(i.code == "ranges-GR002" for i in report.issues)


def test_plan_file_source_has_no_static_entry_bound():
    report = _plan(
        [
            "--source", "file", "--input-files", "cohort.vcf",
            "--references", "1:0:50000",
        ]
    )
    assert report.ok
    assert report.geometry["gramian_entry_bound"] is None
    # Headroom facts exist regardless: they are dtype arithmetic.
    assert report.geometry["exactness_headroom_sites"]["int32"] > 0


def test_plan_exactness_cli_exit_2():
    from spark_examples_tpu.check import cli

    rc = cli.main(
        [
            "plan", "--num-samples", "64",
            "--references", f"1:0:{INT32_WINDOW * 100}",
            "--bases-per-partition", "1000000000000",
        ]
    )
    assert rc == 2


# --------------------------------------------------------------------------
# GC011: narrowing casts need a range justification.
# --------------------------------------------------------------------------


def _lint(src, relpath="ops/fixture.py"):
    return [
        (f.rule_id, f.line)
        for f in lint_source(textwrap.dedent(src), relpath)
        if f.rule_id == "GC011"
    ]


def test_gc011_registered():
    assert "GC011" in RULES
    assert RULES["GC011"].applies_to("ops/gramian.py")
    assert not RULES["GC011"].applies_to("sources/files.py")


def test_gc011_flags_unjustified_narrowing_cast():
    assert _lint(
        """
        import jax.numpy as jnp
        def f(x):
            return x.astype(jnp.int8)
        """
    ) == [("GC011", 4)]


def test_gc011_range_comment_and_contract_reference_justify():
    assert _lint(
        """
        import jax.numpy as jnp
        def f(x):
            # range: x is a {0,1} membership bit
            return x.astype(jnp.uint8)
        def g(x):
            # values declared in ops/contracts.py:HAS_VARIATION
            return x.astype(jnp.uint8)
        """
    ) == []


def test_gc011_convert_element_type_spelling():
    assert _lint(
        """
        import jax.numpy as jnp
        from jax import lax
        def f(x):
            return lax.convert_element_type(x, jnp.int16)
        """
    ) == [("GC011", 5)]


def test_gc011_skips_dynamic_and_wide_targets():
    assert _lint(
        """
        import jax.numpy as jnp
        def f(x, operand_dtype):
            a = x.astype(operand_dtype)
            b = x.astype(jnp.float64)
            return a, b
        """
    ) == []


def test_gc011_scope_and_escape_hatch():
    src = """
    import jax.numpy as jnp
    def f(x):
        return x.astype(jnp.int8)
    """
    assert _lint(src, relpath="sources/fixture.py") == []
    hatched = """
    import jax.numpy as jnp
    def f(x):
        return x.astype(jnp.int8)  # graftcheck: disable=GC011 -- fixture
    """
    assert _lint(hatched) == []


def test_shipped_tree_lints_clean():
    from spark_examples_tpu.check.cli import _default_lint_root
    from spark_examples_tpu.check.linter import lint_paths

    findings, checked = lint_paths([_default_lint_root()])
    assert checked > 40
    assert findings == [], "\n".join(f.format() for f in findings)


# --------------------------------------------------------------------------
# --check-ranges runtime sampling + manifest block.
# --------------------------------------------------------------------------


def test_check_ranges_sampling_measured_within_bound():
    from spark_examples_tpu.obs.metrics import (
        GRAMIAN_ENTRY_MAX,
        GRAMIAN_STATIC_ENTRY_BOUND,
        MetricsRegistry,
    )
    from spark_examples_tpu.ops.gramian import GramianAccumulator

    registry = MetricsRegistry()
    acc = GramianAccumulator(
        8, block_size=4, check_ranges=True, registry=registry
    )
    rng = np.random.RandomState(0)
    acc.add_rows((rng.rand(32, 8) > 0.5).astype(np.uint8))
    acc.finalize()
    measured = registry.value(GRAMIAN_ENTRY_MAX)
    bound = registry.value(GRAMIAN_STATIC_ENTRY_BOUND)
    assert measured is not None and measured > 0
    assert bound == acc._entry_bound
    assert measured <= bound
    assert acc.telemetry.entry_max_seen == measured


def test_check_ranges_off_registers_nothing():
    from spark_examples_tpu.obs.metrics import (
        GRAMIAN_ENTRY_MAX,
        MetricsRegistry,
    )
    from spark_examples_tpu.ops.gramian import GramianAccumulator

    registry = MetricsRegistry()
    acc = GramianAccumulator(8, block_size=4, registry=registry)
    acc.add_rows(np.ones((8, 8), dtype=np.uint8))
    acc.finalize()
    assert registry.value(GRAMIAN_ENTRY_MAX) is None


def test_manifest_gramian_exactness_block_and_validation():
    from spark_examples_tpu.obs.manifest import (
        build_manifest,
        build_run_manifest,
        validate_manifest,
    )
    from spark_examples_tpu.obs.metrics import (
        GRAMIAN_ENTRY_MAX,
        GRAMIAN_STATIC_ENTRY_BOUND,
        MetricsRegistry,
        well_known_gauge,
    )

    # Absent without sampling (v2-additive: existing manifests unchanged).
    doc = build_manifest()
    assert doc["gramian_exactness"] is None
    assert validate_manifest(doc) == []

    registry = MetricsRegistry()
    well_known_gauge(registry, GRAMIAN_ENTRY_MAX).set(142)
    well_known_gauge(registry, GRAMIAN_STATIC_ENTRY_BOUND).set(335)
    doc = build_run_manifest(registry=registry)
    assert doc["gramian_exactness"] == {
        "entry_max": 142,
        "static_entry_bound": 335,
    }
    assert validate_manifest(doc) == []

    bad = build_manifest(gramian_exactness={"entry_max": -1})
    errors = validate_manifest(bad)
    assert any("entry_max" in e for e in errors)
    assert any("static_entry_bound" in e for e in errors)


def test_check_ranges_e2e_driver_run():
    """The runtime half end to end: a packed-ingest driver run with
    --check-ranges records measured <= proven in its own registry — the
    pair the obs smoke asserts from the manifest."""
    from spark_examples_tpu.obs.manifest import (
        build_run_manifest,
        validate_manifest,
    )
    from spark_examples_tpu.pipeline import pca_driver

    conf = PcaConf(
        num_samples=8,
        block_size=8,
        references="1:0:30000",
        check_ranges=True,
        ingest="packed",
    )
    driver = pca_driver.VariantsPcaDriver(conf)
    similarity = pca_driver._similarity_stage(
        conf, driver, use_device=False, use_packed=True
    )
    driver.compute_pca(similarity)
    doc = build_run_manifest(conf=conf, registry=driver.registry)
    assert validate_manifest(doc) == []
    ge = doc["gramian_exactness"]
    assert ge is not None
    assert 0 < ge["entry_max"] <= ge["static_entry_bound"]


# --------------------------------------------------------------------------
# The bounded packed block stream (hostmem inventory shrink): identical
# stats and output, one fewer declared_unbounded site.
# --------------------------------------------------------------------------


def test_packed_stream_stats_and_inventory():
    from spark_examples_tpu.check.hostmem import (
        audit_paths,
        default_hostmem_paths,
    )
    from spark_examples_tpu.obs.metrics import INGEST_PARTITIONS_DONE
    from spark_examples_tpu.pipeline import pca_driver

    report = audit_paths(default_hostmem_paths())
    assert report.ok
    # The per-window list(genotype_blocks) site is GONE from the declared
    # inventory: the packed path now iterates blocks boundedly.
    assert "pipeline/pca_driver.py" not in {d.path for d in report.declared}

    conf = PcaConf(num_samples=8, block_size=8, references="1:0:30000")
    driver = pca_driver.VariantsPcaDriver(conf)
    pca_driver._similarity_stage(conf, driver, use_device=False, use_packed=True)
    stats = driver.io_stats.as_dict()
    assert stats["partitions"] > 0
    assert stats["variants"] > 0
    assert stats["requests"] > 0
    # The bounded stream now reports live window progress.
    done = driver.registry.value(INGEST_PARTITIONS_DONE)
    planned = driver.registry.value("ingest_partitions_planned")
    assert done == planned > 0

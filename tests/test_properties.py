"""Property-based invariants (hypothesis) for the determinism-critical core.

These are the synthetic analog of race-detection (SURVEY.md §5): partition
invariance and chunking invariance are what make results independent of
shard layout, worker count, and device count.
"""

import numpy as np
import pytest

# hypothesis is declared only under the `test` extra; the tier-1 gate must
# collect (and run everything else) on the bare seed image.
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st

from spark_examples_tpu.ops.gramian import GramianAccumulator, gramian_reference
from spark_examples_tpu.sharding.contig import Contig
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource
from spark_examples_tpu.utils.af import af_filter_micro, af_passes

_SOURCE = SyntheticGenomicsSource(num_samples=7, seed=13)


@given(
    start=st.integers(min_value=0, max_value=50_000),
    width=st.integers(min_value=1, max_value=12_000),
    split=st.floats(min_value=0.0, max_value=1.0),
)
@settings(max_examples=15, deadline=None)
def test_genotype_blocks_partition_invariant(start, width, split):
    """STRICT shard semantics: splitting a window anywhere yields exactly
    the concatenation — byte-identical rows, no duplicates, no gaps."""
    end = start + width
    mid = start + int(width * split)

    def rows(a, b):
        blocks = list(_SOURCE.genotype_blocks("vs", Contig("9", a, b), block_size=64))
        if not blocks:
            return np.zeros((0, 7), np.uint8), np.zeros(0, np.int64)
        return (
            np.concatenate([x["has_variation"] for x in blocks]),
            np.concatenate([x["positions"] for x in blocks]),
        )

    whole_rows, whole_pos = rows(start, end)
    left_rows, left_pos = rows(start, mid)
    right_rows, right_pos = rows(mid, end)
    np.testing.assert_array_equal(
        whole_pos, np.concatenate([left_pos, right_pos])
    )
    np.testing.assert_array_equal(
        whole_rows, np.concatenate([left_rows, right_rows])
    )


@given(
    start=st.integers(min_value=0, max_value=10**9),
    width=st.integers(min_value=0, max_value=10**7),
    shard=st.integers(min_value=1, max_value=10**6),
)
@settings(max_examples=50, deadline=None)
def test_contig_shards_cover_exactly(start, width, shard):
    """Windows tile [start, end) with no gaps or overlaps, in order."""
    contig = Contig("x", start, start + width)
    shards = contig.get_shards(shard)
    pos = start
    for s in shards:
        assert s.start == pos
        assert s.end > s.start
        assert s.end - s.start <= shard
        pos = s.end
    assert pos == start + width or (width == 0 and not shards)


@given(
    start=st.integers(min_value=0, max_value=10**8),
    width=st.integers(min_value=0, max_value=10**6),
)
@settings(max_examples=50, deadline=None)
def test_site_grid_range_matches_site_positions(start, width):
    k0, k1 = _SOURCE.site_grid_range(Contig("z", start, start + width))
    grid = np.arange(k0, k1, dtype=np.int64) * _SOURCE.variant_spacing
    np.testing.assert_array_equal(
        grid, _SOURCE._site_positions(start, start + width)
    )


@given(
    chunks=st.lists(
        st.integers(min_value=1, max_value=40), min_size=1, max_size=6
    ),
    seed=st.integers(min_value=0, max_value=2**31),
)
@settings(max_examples=8, deadline=None)
def test_gramian_chunking_invariant(chunks, seed):
    """Feeding rows in any chunking yields the identical matrix."""
    rng = np.random.default_rng(seed)
    rows = (rng.random((sum(chunks), 9)) < 0.4).astype(np.uint8)
    acc = GramianAccumulator(num_samples=9, block_size=16)
    offset = 0
    for c in chunks:
        acc.add_rows(rows[offset : offset + c])
        offset += c
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(rows))


@given(
    k=st.integers(min_value=0, max_value=2**32 - 1),
    thr_micro=st.integers(min_value=0, max_value=1_000_000),
)
@settings(max_examples=200, deadline=None)
def test_af_filter_wire_roundtrip_agrees(k, thr_micro):
    """The canonical micro-unit AF rule survives the 6-decimal wire format:
    filtering the parsed string equals filtering the Q32 dyadic value."""
    threshold = thr_micro / 1e6
    af = np.float64(k) * 2.0**-32
    direct = bool(af_passes(af, threshold))
    wire = float(f"{float(np.round(af * 1e6) / 1e6):.6f}")
    via_wire = bool(af_passes(wire, threshold))
    assert direct == via_wire
    # floor over the EXACT binary value of the threshold: off-grid floats
    # (e.g. float(1e-6) < 1/10⁶) may floor one below their decimal.
    assert af_filter_micro(threshold) in (thr_micro, thr_micro - 1)


@given(
    name=st.from_regex(r"(chr)?(X|Y|MT|[0-9]{1,2})", fullmatch=True),
)
@settings(max_examples=100, deadline=None)
def test_normalize_strips_chr_and_is_idempotent(name):
    from spark_examples_tpu.models.variant import VariantsBuilder

    normalized = VariantsBuilder.normalize(name)
    if normalized is not None:
        assert VariantsBuilder.normalize(normalized) == normalized
        assert not normalized.startswith("chr")

"""The ONE windowed stream abstraction (``sources/stream.py``) and the
totality proof built on it.

Covers: byte-window streaming with partial-line carry (plain + gzip, the
gz window-boundary/co-residency regression), text-line decoding, generic
windowing, the sortedness probe, the budgeted accumulators
(``ChunkedArrayBuilder`` / ``SpooledRecordTable``), the streaming k-way
``merge_join`` against a materialized-join oracle with its bounded-window
claim, the exhaustive conf-matrix totality of
``check/hostmem.py:conf_host_peak_bytes``, and golden fixtures for the
GH006 (declared-unbounded-forbidden) and GC012
(raw-file-iteration-outside-stream) rules.
"""

import dataclasses
import gzip
import itertools
import json
import textwrap
import tracemalloc

import numpy as np
import pytest

from spark_examples_tpu.check.hostmem import (
    audit_source,
    conf_host_peak_bytes,
)
from spark_examples_tpu.check.linter import lint_source
from spark_examples_tpu.config import AssocConf, GrmConf, LdConf, PcaConf
from spark_examples_tpu.parallel.mesh import HOST_RUNTIME_BASELINE_BYTES
from spark_examples_tpu.sources.stream import (
    ChunkedArrayBuilder,
    MergeJoinStats,
    SortednessProbe,
    SpooledRecordTable,
    StreamBudgetError,
    UnsortedStreamError,
    decompressed_size_bound,
    iter_byte_windows,
    iter_text_lines,
    merge_join,
    windowed,
    wire_rows_bound,
)

# --------------------------------------------------------------------------
# Byte windows: carry, boundaries, byte identity — plain and gzip.
# --------------------------------------------------------------------------


def _lines(n, width=40):
    return b"".join(
        b"line-%06d-" % i + b"x" * width + b"\n" for i in range(n)
    )


def test_byte_windows_concat_is_identity_plain(tmp_path):
    payload = _lines(500)
    path = tmp_path / "t.txt"
    path.write_bytes(payload)
    windows = list(iter_byte_windows(str(path), 256))
    assert b"".join(windows) == payload
    # Every window but the last ends at a line boundary (the carry moved
    # the partial line forward), and none is empty.
    for w in windows[:-1]:
        assert w.endswith(b"\n")
    assert all(windows)


def test_byte_windows_concat_is_identity_gzip(tmp_path):
    payload = _lines(500)
    path = tmp_path / "t.txt.gz"
    path.write_bytes(gzip.compress(payload))
    assert b"".join(iter_byte_windows(str(path), 256)) == payload


def test_byte_windows_window_smaller_than_line(tmp_path):
    # A window far below one line exercises the multi-read carry path.
    payload = b"a" * 5000 + b"\n" + b"b" * 3000 + b"\n"
    path = tmp_path / "long.txt"
    path.write_bytes(payload)
    windows = list(iter_byte_windows(str(path), 64))
    assert b"".join(windows) == payload
    assert windows == [b"a" * 5000 + b"\n", b"b" * 3000 + b"\n"]


def test_byte_windows_unterminated_tail(tmp_path):
    payload = b"one\ntwo\nunterminated-tail"
    path = tmp_path / "t.txt"
    path.write_bytes(payload)
    assert b"".join(iter_byte_windows(str(path), 64)) == payload


def test_gz_window_boundary_regression(tmp_path):
    # The gz co-residency contract (ISSUE 17 satellite): records that
    # straddle every window boundary round-trip exactly — the compressed
    # buffer is gzip's O(KB) read-ahead, never the file, and never sits
    # beside more than one decompressed window. Streaming a ~6 MB
    # decompressed payload through 64 KiB windows must stay O(window),
    # not O(file).
    window = 64 << 10
    # Line width chosen to never divide the window: every boundary cuts
    # a record and exercises the carry.
    payload = _lines(60_000, width=87)
    assert len(payload) > 90 * window
    path = tmp_path / "big.jsonl.gz"
    path.write_bytes(gzip.compress(payload))

    tracemalloc.start()
    total = 0
    baseline = tracemalloc.get_traced_memory()[0]
    for w in iter_byte_windows(str(path), window):
        total += len(w)
    peak = tracemalloc.get_traced_memory()[1]
    tracemalloc.stop()
    assert total == len(payload)
    # Peak traced allocation stays within a few windows of the baseline
    # (window + carry + gzip read-ahead + interpreter noise) — a whole-
    # file or whole-decompress regression would be >90 windows.
    assert peak - baseline < 8 * window


def test_text_lines_universal_newlines(tmp_path):
    path = tmp_path / "t.txt"
    path.write_bytes(b"a\r\nb\rc\nd")
    assert list(iter_text_lines(str(path), 64)) == ["a", "b", "c", "d"]


def test_windowed_shapes_and_validation():
    assert list(windowed(range(7), 3)) == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(windowed([], 3)) == []
    with pytest.raises(ValueError):
        list(windowed([1], 0))


def test_size_bounds(tmp_path):
    plain = tmp_path / "p.txt"
    plain.write_bytes(b"x" * 1000)
    assert decompressed_size_bound(str(plain)) == 1000
    assert wire_rows_bound(str(plain)) == 1000 // 16 + 1
    gz = tmp_path / "p.txt.gz"
    gz.write_bytes(gzip.compress(b"y" * 100_000))
    # The ISIZE trailer bounds the decompressed size of a well-formed
    # single-member gz.
    assert decompressed_size_bound(str(gz)) >= 100_000
    assert decompressed_size_bound(str(tmp_path / "missing")) == 0


# --------------------------------------------------------------------------
# Sortedness probe.
# --------------------------------------------------------------------------


def test_sortedness_probe_accepts_sorted_runs():
    probe = SortednessProbe("t")
    probe.check("1", np.array([5, 7, 7, 9]))
    probe.check("1", np.array([9, 12]))
    probe.check("2", np.array([1, 2]))


def test_sortedness_probe_rejects_regression_and_split_contig():
    probe = SortednessProbe("t")
    probe.check("1", np.array([5, 9]))
    with pytest.raises(UnsortedStreamError):
        probe.check("1", np.array([3]))
    probe = SortednessProbe("t", hint="sort the input")
    probe.check("1", np.array([1]))
    probe.check("2", np.array([1]))
    with pytest.raises(UnsortedStreamError, match="sort the input"):
        probe.check("1", np.array([2]))


# --------------------------------------------------------------------------
# Budgeted accumulators.
# --------------------------------------------------------------------------


def test_chunked_array_builder_matches_concat():
    parts = [np.arange(i * 10, i * 10 + i, dtype=np.int64) for i in range(8)]
    b = ChunkedArrayBuilder(np.int64)
    for p in parts:
        b.add(p)
    np.testing.assert_array_equal(b.finish(), np.concatenate(parts))


def test_chunked_array_builder_capacity_enforced():
    b = ChunkedArrayBuilder(np.int8, row_shape=(4,), capacity_rows=5)
    b.add(np.zeros((5, 4), np.int8))
    with pytest.raises(StreamBudgetError):
        b.add(np.zeros((1, 4), np.int8))


def test_spooled_table_round_trips_and_sorts_stably():
    t = SpooledRecordTable("t")
    records = [
        ("1", 30, {"id": "a", "payload": [1, 2]}),
        ("1", 10, {"id": "b"}),
        ("1", 30, {"id": "c", "nested": {"x": "y"}}),
        ("2", 5, {"id": "d"}),
    ]
    for contig, start, rec in records:
        t.add(contig, start, rec)
    t.finish()
    assert t.contig_names() == ["1", "2"]
    assert list(t.starts("1")) == [10, 30, 30]
    # Byte-identical round trip, stable at duplicate starts ("a" before
    # "c" — insertion order preserved, like the retired in-memory sort).
    assert [r["id"] for r in t.iter_records("1")] == ["b", "a", "c"]
    assert list(t.iter_records("1"))[2] == {"id": "c", "nested": {"x": "y"}}
    assert [r["id"] for r in t.tail_records("1", 2)] == ["a", "c"]
    assert t.rows("absent") == 0
    t.close()


def test_spooled_table_capacity_and_finish_contract():
    t = SpooledRecordTable("t", capacity_rows=1)
    t.add("1", 1, {"id": "a"})
    with pytest.raises(StreamBudgetError):
        t.add("1", 2, {"id": "b"})
    fresh = SpooledRecordTable("t")
    with pytest.raises(ValueError):
        fresh.contig_names()


# --------------------------------------------------------------------------
# merge_join vs. the materialized-join oracle (the retired shape), plus
# the bounded-window claim: peak tracked records <= k x window.
# --------------------------------------------------------------------------


def _materialized_join_oracle(streams):
    """The retired join shape: build every per-set keyed table whole,
    then group — the O(cohort) behavior merge_join replaces."""
    keyed = []
    for stream in streams:
        table = {}
        for key, record in stream:
            table.setdefault(key, []).append(record)
        keyed.append(table)
    all_keys = sorted(set(itertools.chain.from_iterable(keyed)))
    return [
        (key, [table.get(key, []) for table in keyed]) for key in all_keys
    ]


def _ragged_cohorts():
    """Ragged multi-set cohorts: uneven contigs, empty sets, duplicate
    sites — the property-test corpus (handwritten + seeded random so the
    bare image needs no hypothesis)."""
    cases = [
        # Uneven contigs and duplicate sites.
        [
            [(("1", 10), "a0"), (("1", 10), "a1"), (("2", 5), "a2")],
            [(("1", 10), "b0"), (("3", 1), "b1")],
            [(("2", 5), "c0"), (("2", 5), "c1"), (("2", 7), "c2")],
        ],
        # An empty set among populated ones.
        [[], [(("1", 1), "b")], []],
        # All empty.
        [[], []],
        # Single stream degenerates to grouping.
        [[(("1", 1), "a"), (("1", 1), "b"), (("1", 2), "c")]],
    ]
    rng = np.random.default_rng(17)
    for _ in range(20):
        k = int(rng.integers(1, 5))
        streams = []
        for i in range(k):
            n = int(rng.integers(0, 30))
            keys = sorted(
                (str(rng.integers(1, 4)), int(rng.integers(0, 15)))
                for _ in range(n)
            )
            streams.append(
                [(key, f"s{i}r{j}") for j, key in enumerate(keys)]
            )
        cases.append(streams)
    return cases


def test_merge_join_matches_materialized_oracle():
    for streams in _ragged_cohorts():
        stats = MergeJoinStats()
        got = list(merge_join([iter(s) for s in streams], stats=stats))
        expected = _materialized_join_oracle(streams)
        assert got == expected, streams
        # Bounded-window proof: the records tracked at once are one key
        # group — at most k x that key's widest per-stream duplicate run.
        window = max(
            (
                sum(1 for kk, _ in s if kk == key)
                for s in streams
                for key, _ in s
            ),
            default=0,
        )
        assert stats.peak_tracked <= len(streams) * window
        assert stats.groups == len(expected)


def test_merge_join_rejects_unsorted_stream():
    with pytest.raises(UnsortedStreamError):
        list(merge_join([iter([(2, "a"), (1, "b")])]))


# --------------------------------------------------------------------------
# Exhaustive conf-matrix totality: a finite, monotone bound for every
# parser-reachable (source x ingest x analysis x serve kind).
# --------------------------------------------------------------------------

_ANALYSIS_CONFS = {
    # Serve job kinds map onto these analyses (similarity == pca).
    "pca/similarity": PcaConf,
    "grm": GrmConf,
    "ld": lambda **kw: LdConf(ld_window_sites=64, **kw),
    "assoc": AssocConf,
}

_SOURCE_SHAPES = {
    "synthetic": {},
    "rest": {"source": "rest"},
    "file-vcf": {
        "source": "file",
        "input_files": ["c.vcf"],
        "variant_set_id": ["c"],
    },
    "file-vcf-streamed": {
        "source": "file",
        "input_files": ["c.vcf"],
        "variant_set_id": ["c"],
        "stream_chunk_bytes": 1 << 20,
    },
    "file-jsonl": {
        "source": "file",
        "input_files": ["c.jsonl"],
        "variant_set_id": ["c"],
    },
    "file-sam": {
        "source": "file",
        "input_files": ["c.sam"],
        "variant_set_id": ["c"],
    },
    "file-multiset": {
        "source": "file",
        "input_files": ["a.vcf", "b.vcf", "c.vcf"],
        "variant_set_id": ["a", "b", "c"],
    },
    "resume": {"input_path": "/tmp/nonexistent-ckpt"},
}

_INGEST_MODES = ("auto", "device", "packed", "wire")


def test_conf_matrix_totality_finite_and_monotone():
    checked = 0
    for (aname, make), (sname, shape), ingest in itertools.product(
        _ANALYSIS_CONFS.items(), _SOURCE_SHAPES.items(), _INGEST_MODES
    ):
        kwargs = dict(shape)
        if "input_path" not in kwargs:
            kwargs["ingest"] = ingest
        conf = make(num_samples=16, block_size=8, **kwargs)
        bound = conf_host_peak_bytes(conf, device_count=1)
        label = f"{aname} x {sname} x {ingest}"
        assert isinstance(bound, int), label
        assert not isinstance(bound, bool), label
        assert bound >= HOST_RUNTIME_BASELINE_BYTES, label
        # Monotone in the cohort width and stable (deterministic).
        wider = conf_host_peak_bytes(
            dataclasses.replace(conf, num_samples=32), device_count=1
        )
        assert wider >= bound, label
        assert conf_host_peak_bytes(conf, device_count=1) == bound, label
        checked += 1
    assert checked == len(_ANALYSIS_CONFS) * len(_SOURCE_SHAPES) * len(
        _INGEST_MODES
    )


# --------------------------------------------------------------------------
# Golden fixtures: GH006 (hostmem) and GC012 (linter).
# --------------------------------------------------------------------------


def _hostmem_ids(src, relpath="sources/fixture.py"):
    findings, declared = audit_source(textwrap.dedent(src), relpath)
    return (
        [(f.rule_id, f.line) for f in findings],
        [(d.rule_id, d.line) for d in declared],
    )


def _lint_ids(src, relpath):
    return [
        (f.rule_id, f.line)
        for f in lint_source(textwrap.dedent(src), relpath)
    ]


def test_gh006_escape_hatch_now_flagged():
    # The exact hatch idiom the retired sources/files.py sites used: the
    # underlying finding still lands in the declared inventory (context),
    # but the hatch line itself is a GH006 finding — the audit fails.
    findings, declared = _hostmem_ids(
        """
        def load_table(path):
            with open(path, "rb") as f:
                return f.read()  # graftcheck: hostmem(unbounded) -- wire-oracle table is whole-file by contract
        """
    )
    assert findings == [("GH006", 4)]
    assert declared == [("GH001", 4)]


def test_gh006_bare_hatch_without_finding_still_flagged():
    # Even a hatch hiding nothing (stale after a refactor) is a finding:
    # the syntax itself is forbidden.
    findings, declared = _hostmem_ids(
        """
        def f():
            return 1  # graftcheck: hostmem(unbounded) -- stale declaration
        """
    )
    assert findings == [("GH006", 3)]
    assert declared == []


def test_gc012_raw_iteration_flagged_in_sources_and_pipeline():
    src = """
    def f(path):
        with open(path) as handle:
            for line in handle:
                pass
    """
    assert ("GC012", 4) in _lint_ids(src, "sources/fixture.py")
    assert ("GC012", 4) in _lint_ids(src, "pipeline/fixture.py")
    # Out of scope: the rule owns the ingest layers only.
    assert all(r != "GC012" for r, _ in _lint_ids(src, "ops/fixture.py"))


def test_gc012_read_calls_and_wrappers_flagged():
    src = """
    def f(path):
        handle = gzip.open(path, "rt")
        data = handle.read()
        for i, line in enumerate(handle):
            pass
    """
    ids = _lint_ids(src, "sources/fixture.py")
    assert ("GC012", 4) in ids
    assert ("GC012", 5) in ids


def test_gc012_exemptions():
    # Write-mode handles, json.load, and the stream module itself are
    # all outside the rule.
    write_src = """
    def f(path, rows):
        with open(path, "w") as out:
            for row in rows:
                out.write(row)
    """
    assert all(
        r != "GC012" for r, _ in _lint_ids(write_src, "sources/fixture.py")
    )
    manifest_src = """
    def f(path):
        with open(path) as f:
            return json.load(f)
    """
    assert all(
        r != "GC012"
        for r, _ in _lint_ids(manifest_src, "pipeline/fixture.py")
    )
    reader_src = """
    def f(path):
        with open(path, "rb") as f:
            for chunk in f:
                yield chunk
    """
    assert all(
        r != "GC012" for r, _ in _lint_ids(reader_src, "sources/stream.py")
    )


def test_gc013_journal_record_dict_literal_flagged():
    # A hand-rolled protocol record anywhere outside serve/journal.py is
    # a finding — whatever it is assigned to or passed into: the record
    # shapes are exactly what `graftcheck proto` proves the coordination
    # protocol against.
    src = """
    def settle(journal, job_id):
        journal.append({"event": "terminal", "id": job_id,
                        "status": "done"})
    """
    assert ("GC013", 3) in _lint_ids(src, "serve/daemon.py")
    assert ("GC013", 3) in _lint_ids(src, "pipeline/fixture.py")


def test_gc013_every_protocol_event_name_covered():
    for event in ("accepted", "began", "terminal", "lease"):
        src = f"""
        def f():
            return {{"event": "{event}", "id": "j-1"}}
        """
        assert any(
            r == "GC013" for r, _ in _lint_ids(src, "serve/daemon.py")
        ), event


def test_gc013_private_append_seam_flagged():
    src = """
    def f(journal, record):
        journal._append(record)
    """
    assert ("GC013", 3) in _lint_ids(src, "serve/daemon.py")


def test_gc013_exemptions():
    # The journal module IS the protocol: its own constructors are the
    # one place the record shapes may be spelled out.
    src = """
    def terminal_record(job_id, status):
        return {"event": "terminal", "id": job_id, "status": status}
    """
    assert all(
        r != "GC013" for r, _ in _lint_ids(src, "serve/journal.py")
    )
    # Non-protocol event dicts (metrics, traces) are out of scope.
    trace_src = """
    def f(name):
        return {"event": "heartbeat", "name": name}
    """
    assert all(
        r != "GC013" for r, _ in _lint_ids(trace_src, "serve/daemon.py")
    )

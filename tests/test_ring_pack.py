"""Parity matrix + telemetry for the bit-packed, overlapped ring Gramian.

The packed ring wire format (``--ring-pack-bits``) must be BIT-EXACT
against both the unpacked oracle (``off``) and the host NumPy reference —
across mesh shapes, at cohort widths that are not multiples of 8 (ragged →
pack-width padding), for multi-set (merged-cohort) device generation, and
when count-valued blocks force the per-flush fallback to the unpacked
kernel. The ``gramian_ring_bytes`` counter is asserted against the one
audited traffic formula (``parallel/mesh.py:ring_traffic_bytes``) so the
8× claim in the manifests is arithmetic, not vibes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_examples_tpu.ops.gramian import (
    ShardedGramianAccumulator,
    _pack_bits_device,
    _unpack_bits,
    gramian_reference,
    resolve_ring_pack,
)
from spark_examples_tpu.parallel.mesh import (
    DATA_AXIS,
    RING_PACK_MULTIPLE,
    SAMPLES_AXIS,
    make_mesh,
    padded_cohort,
    ring_traffic_bytes,
)


def _random_rows(rng, n_variants, n_samples, p=0.3):
    return (rng.random((n_variants, n_samples)) < p).astype(np.uint8)


# ------------------------------------------------------------- pack/unpack


def test_pack_unpack_round_trip_ragged_widths():
    """Device unpack inverts np.packbits for every ragged width, and the
    device pack matches np.packbits bit-for-bit at byte-aligned widths."""
    rng = np.random.default_rng(0)
    for width in [1, 3, 7, 8, 9, 15, 16, 21, 24, 40, 64, 100]:
        bits = (rng.random((13, width)) < 0.4).astype(np.uint8)
        packed = np.packbits(bits, axis=-1)
        out = np.asarray(_unpack_bits(jnp.asarray(packed), width))
        np.testing.assert_array_equal(out, bits, err_msg=f"width={width}")
        if width % 8 == 0:
            dev = np.asarray(_pack_bits_device(jnp.asarray(bits)))
            np.testing.assert_array_equal(dev, packed, err_msg=f"width={width}")


def test_resolve_ring_pack_contract():
    assert resolve_ring_pack("auto") and resolve_ring_pack("on")
    assert not resolve_ring_pack("off")
    with pytest.raises(ValueError):
        resolve_ring_pack("sometimes")


def test_padded_cohort_rule():
    # Unpacked: multiple of the samples axis; packed: of 8x the samples
    # axis (every device tile a whole number of bytes).
    assert padded_cohort(21, 4, pack=False) == 24
    assert padded_cohort(21, 4, pack=True) == 32
    assert padded_cohort(64, 4, pack=True) == 64
    assert padded_cohort(64, 4, pack=True) // 4 % RING_PACK_MULTIPLE == 0


# ------------------------------------------------------ host-fed parity


MESHES = [
    {SAMPLES_AXIS: 4},
    {DATA_AXIS: 2, SAMPLES_AXIS: 2},
    {DATA_AXIS: 1, SAMPLES_AXIS: 8},
]


@pytest.mark.parametrize(
    "shape", MESHES, ids=["s4", "d2s2", "d1s8"]
)
@pytest.mark.parametrize("n_samples", [24, 21], ids=["aligned", "ragged"])
def test_packed_ring_parity_matrix(shape, n_samples):
    """packed == --ring-pack-bits off oracle == gramian_reference, across
    mesh shapes, including an N_local not divisible by 8 (n=21 over 4
    slices leaves ragged local widths the pack padding must absorb)."""
    mesh = make_mesh(dict(shape))
    rng = np.random.default_rng(11)
    rows = _random_rows(rng, 150, n_samples)
    results = {}
    for mode in ("on", "off"):
        acc = ShardedGramianAccumulator(
            n_samples, mesh, block_size=32, pack_bits=mode
        )
        for chunk in np.array_split(rows, 4):
            acc.add_rows(chunk)
        results[mode] = acc.finalize()
    reference = gramian_reference(rows)
    np.testing.assert_array_equal(results["off"], reference)
    np.testing.assert_array_equal(results["on"], results["off"])


def test_packed_ring_count_rows_fall_back_per_flush():
    """Count-valued blocks (same-set joins) cannot bit-pack; with packing
    on they must transparently ride the unpacked kernel — mixed with
    packed binary flushes in one accumulator — and stay exact."""
    mesh = make_mesh({SAMPLES_AXIS: 2})
    binary = _random_rows(np.random.default_rng(3), 4, 5)
    counts = np.array([[2, 1, 0, 3, 1], [0, 3, 1, 0, 2]], dtype=np.uint8)
    acc = ShardedGramianAccumulator(5, mesh, block_size=4, pack_bits="on")
    acc.add_rows(binary)  # fills one block exactly -> packed flush
    acc.add_rows(counts)  # partial block with counts -> unpacked flush
    all_rows = np.concatenate([binary, counts]).astype(np.int64)
    np.testing.assert_array_equal(acc.finalize(), all_rows.T @ all_rows)


def test_packed_ring_exact_int_parity():
    mesh = make_mesh({SAMPLES_AXIS: 4})
    rows = _random_rows(np.random.default_rng(8), 90, 21)
    for mode in ("on", "off"):
        acc = ShardedGramianAccumulator(
            21, mesh, block_size=16, exact_int=True, pack_bits=mode
        )
        acc.add_rows(rows)
        np.testing.assert_array_equal(acc.finalize(), gramian_reference(rows))


# ------------------------------------------------- device-generated parity


def _ring_device_acc(source, mesh, mode, vs_keys=None, set_sizes=None):
    from spark_examples_tpu.ops.devicegen import DeviceGenRingGramianAccumulator

    kwargs = dict(
        num_samples=source.num_samples,
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        mesh=mesh,
        block_size=16,
        blocks_per_dispatch=2,
        n_pops=source.n_pops,
        pack_bits=mode,
    )
    if vs_keys is None:
        kwargs["vs_key"] = source.genotype_stream_key("vs")
    else:
        kwargs["vs_key"] = vs_keys
        if set_sizes is not None:
            kwargs["set_sizes"] = set_sizes
            kwargs["pops_per_set"] = [source.populations] * len(set_sizes)
    return DeviceGenRingGramianAccumulator(**kwargs)


def test_devicegen_ring_packed_parity_single_set():
    from spark_examples_tpu.sharding.contig import Contig
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    mesh = make_mesh({DATA_AXIS: 2, SAMPLES_AXIS: 4})
    source = SyntheticGenomicsSource(num_samples=21, seed=9)  # ragged width
    contig = Contig("4", 5_000, 95_000)
    k0, k1 = source.site_grid_range(contig)
    finals = {}
    for mode in ("on", "off"):
        acc = _ring_device_acc(source, mesh, mode)
        acc.add_grid(k0, k1)
        finals[mode] = acc.finalize()
        if mode == "on":
            assert acc.n_local % RING_PACK_MULTIPLE == 0
    np.testing.assert_array_equal(finals["on"], finals["off"])


def test_devicegen_ring_packed_parity_multiset_merged_cohort():
    """The merged-cohort (multi-set) ring: concatenated per-set column
    blocks through the packed wire equal the unpacked oracle bit for bit,
    and the padded column space honors the pack-width invariant."""
    from spark_examples_tpu.sharding.contig import Contig
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    mesh = make_mesh({SAMPLES_AXIS: 4})
    source = SyntheticGenomicsSource(num_samples=9, seed=5)
    contig = Contig("7", 0, 60_000)
    k0, k1 = source.site_grid_range(contig)
    vs_keys = [
        source.genotype_stream_key("set-a"),
        source.genotype_stream_key("set-b"),
    ]
    finals = {}
    for mode in ("on", "off"):
        acc = _ring_device_acc(
            source, mesh, mode, vs_keys=vs_keys, set_sizes=[9, 9]
        )
        acc.add_grid(k0, k1)
        finals[mode] = acc.finalize()
        assert finals[mode].shape == (18, 18)
        if mode == "on":
            assert acc.padded % (4 * RING_PACK_MULTIPLE) == 0
    np.testing.assert_array_equal(finals["on"], finals["off"])


# ------------------------------------------------------------ telemetry


def test_ring_bytes_counter_matches_formula_and_shows_8x():
    from spark_examples_tpu.obs.metrics import (
        GRAMIAN_RING_BYTES,
        GRAMIAN_RING_FLUSH_SECONDS,
        MetricsRegistry,
    )

    mesh = make_mesh({SAMPLES_AXIS: 4})
    n = 64  # local width 16 in both wire formats -> identical work, 8x exact
    rows = _random_rows(np.random.default_rng(5), 64, n)
    recorded = {}
    for mode in ("on", "off"):
        registry = MetricsRegistry()
        acc = ShardedGramianAccumulator(
            n, mesh, block_size=32, pack_bits=mode, registry=registry
        )
        acc.add_rows(rows)
        acc.finalize()
        recorded[mode] = registry.value(GRAMIAN_RING_BYTES)
        # Two full 32-row flushes, each one ring circulation.
        expected = 2 * ring_traffic_bytes(32, 4, 16, packed=(mode == "on"))
        assert recorded[mode] == expected == acc.ring_bytes_total
        seconds = registry.value(GRAMIAN_RING_FLUSH_SECONDS)
        assert seconds["count"] == 2
    assert recorded["off"] == 8 * recorded["on"] > 0


def test_devicegen_ring_bytes_accounts_ragged_final_byte():
    """Device-generation ring traffic: padded vs valid capacity tracked,
    and the packed/unpacked byte ratio reflects the pack-width padding of
    a ragged cohort (21 -> widths 8 packed-padded vs 6 unpacked)."""
    from spark_examples_tpu.sharding.contig import Contig
    from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource

    mesh = make_mesh({SAMPLES_AXIS: 4})
    source = SyntheticGenomicsSource(num_samples=21, seed=9)
    contig = Contig("4", 5_000, 95_000)
    k0, k1 = source.site_grid_range(contig)
    byte_totals = {}
    for mode in ("on", "off"):
        acc = _ring_device_acc(source, mesh, mode)
        acc.add_grid(k0, k1)
        assert acc.sites_capacity >= acc.sites_valid == k1 - k0
        byte_totals[mode] = acc.ring_bytes_total
        expected = ring_traffic_bytes(
            acc.sites_capacity, 4, acc.n_local, packed=(mode == "on")
        )
        assert acc.ring_bytes_total == expected
    # Ragged cohort: unpacked n_local=6 (padded 24), packed n_local=8
    # (padded 32, 1 byte wide) -> the reduction is 6x here, 8x only at
    # byte-aligned widths ("ragged final byte accounted").
    assert byte_totals["off"] == 6 * byte_totals["on"] > 0


def test_driver_publishes_ring_bytes_for_device_ingest(tmp_path):
    """End to end through the CLI driver: a sharded synthetic run lands
    gramian_ring_bytes + devicegen_sites_capacity in its manifest, and
    packed results equal the oracle's result rows exactly."""
    from spark_examples_tpu.obs.manifest import (
        manifest_metric_value,
        read_manifest,
    )
    from spark_examples_tpu.obs.metrics import (
        DEVICEGEN_SITES_CAPACITY,
        GRAMIAN_RING_BYTES,
    )
    from spark_examples_tpu.pipeline import pca_driver

    lines = {}
    values = {}
    for mode in ("on", "off"):
        path = tmp_path / f"{mode}.json"
        lines[mode] = pca_driver.run(
            [
                "--num-samples", "64",
                "--references", "1:0:300000",
                "--mesh-shape", "1,4",
                "--similarity-strategy", "sharded",
                "--block-size", "64",
                "--ring-pack-bits", mode,
                "--metrics-json", str(path),
            ]
        )
        doc = read_manifest(str(path))
        values[mode] = manifest_metric_value(doc, GRAMIAN_RING_BYTES)
        assert manifest_metric_value(doc, DEVICEGEN_SITES_CAPACITY) > 0
    assert lines["on"] == lines["off"]
    assert values["off"] == 8 * values["on"] > 0


# ------------------------------------------------------------ plan checks


def _plan(argv, devices=None):
    from spark_examples_tpu.check.plan import validate_plan
    from spark_examples_tpu.config import PcaConf, build_pca_parser

    conf = PcaConf._from_namespace(build_pca_parser().parse_args(argv))
    return validate_plan(conf, plan_devices=devices)


def test_plan_packed_geometry_honors_pack_width_invariant():
    report = _plan(
        [
            "--mesh-shape", "1,4",
            "--similarity-strategy", "sharded",
            "--num-samples", "100",
        ],
        devices=4,
    )
    assert report.ok
    assert report.geometry["ring_pack_bits"] == "packed"
    assert report.geometry["ring_local_columns"] % RING_PACK_MULTIPLE == 0
    # 100 over 4x8 -> 128; auto-rounded, warned, never rejected.
    assert any(i.code == "cohort-padding" for i in report.issues)
    packed_flush = report.geometry["ring_bytes_per_flush"]
    oracle = _plan(
        [
            "--mesh-shape", "1,4",
            "--similarity-strategy", "sharded",
            "--num-samples", "100",
            "--ring-pack-bits", "off",
        ],
        devices=4,
    )
    assert oracle.ok
    assert oracle.geometry["ring_pack_bits"] == "unpacked"
    # 100 -> 104 unpacked (multiple of 4), width 26 vs packed width 4.
    assert oracle.geometry["ring_bytes_per_flush"] > 6 * packed_flush


def test_plan_rejects_sharded_geometry_past_hbm():
    report = _plan(
        [
            "--mesh-shape", "1,2",
            "--similarity-strategy", "sharded",
            "--num-samples", "300000",
        ],
        devices=2,
    )
    assert not report.ok
    assert any(i.code == "sharded-exceeds-hbm" for i in report.issues)


def test_plan_rejects_bogus_ring_pack_value():
    from spark_examples_tpu.check.plan import validate_plan
    from spark_examples_tpu.config import PcaConf

    conf = PcaConf()
    conf.ring_pack_bits = "sometimes"
    report = validate_plan(conf, plan_devices=1)
    assert not report.ok
    assert any(i.code == "ring-pack-bits" for i in report.issues)

"""Property-based native/Python parser parity (the C++ data plane is the one
component where a parsing divergence or memory error would corrupt ingest
silently — fuzz the whole VCF grammar surface, not just handwritten files).

Two fuzzing tiers share one grammar:

- hypothesis strategies (``_vcf_documents``) explore the grammar randomly —
  they need the optional ``test`` extra, so they skip (without erroring the
  module) on the bare seed image; ``test_streaming.py`` borrows
  ``_vcf_documents`` under the same guard;
- the DETERMINISTIC corpus (``spark_examples_tpu/check/corpus.py``) pins the
  same grammar plus handwritten edge documents as a fixed, reproducible
  set — replayed here through the parity properties on EVERY image, and
  replayed under ASAN/UBSAN/TSAN by ``graftcheck sanitize`` / ``ci.sh
  --sanitize`` (the sanitizer tier checks memory/race safety over exactly
  the documents whose semantics these tests pin).
"""

import os
import tempfile

import numpy as np
import pytest

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # the bare seed image: deterministic tiers still run
    HAVE_HYPOTHESIS = False


def _group_by_contig(contigs, positions, ends, af, hv):
    """{contig: (positions, ends, af, hv)} sorted by position (stable → file
    order on ties) — the _PackedVcf grouping, applied to raw arrays."""
    out = {}
    for name in dict.fromkeys(contigs.tolist()):
        mask = contigs == name
        order = np.argsort(positions[mask], kind="stable")
        out[str(name)] = (
            positions[mask][order],
            ends[mask][order],
            af[mask][order],
            hv[mask][order],
        )
    return out


def _assert_same_arrays(a, b):
    """Array-tuple equality with NaN-aware float comparison."""
    for x, y in zip(a, b):
        if np.issubdtype(np.asarray(x).dtype, np.floating):
            np.testing.assert_array_equal(np.isnan(x), np.isnan(y))
            np.testing.assert_array_equal(x[~np.isnan(x)], y[~np.isnan(y)])
        else:
            np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# Deterministic corpus tier: always collected, native build permitting.
# ---------------------------------------------------------------------------


def test_corpus_chunk_parallel_parse_matches_serial():
    """The chunk-parallel parity invariant over the WHOLE deterministic
    corpus: for every document — including the malformed and truncated edge
    cases — the span-parallel parse reproduces the serial outcome exactly
    (same arrays, or the same file-level malformed-line error)."""
    from spark_examples_tpu.check.corpus import corpus_documents
    from spark_examples_tpu.sources.files import _native_parallel_vcf_arrays
    from spark_examples_tpu.utils import native as native_mod

    if native_mod.vcf_library() is None:
        pytest.skip(f"no native build: {native_mod.native_unavailable_reason()}")

    parity_checked = 0
    for i, text in enumerate(corpus_documents()):
        try:
            serial = native_mod.parse_vcf_arrays(text)
            serial_error = None
        except ValueError as e:
            serial, serial_error = None, e
        for workers in (2, 5):
            if serial_error is not None:
                with pytest.raises(ValueError) as excinfo:
                    _native_parallel_vcf_arrays(text, workers)
                if isinstance(serial_error, native_mod.MalformedVcfLine):
                    assert isinstance(
                        excinfo.value, native_mod.MalformedVcfLine
                    ), f"corpus[{i}] workers={workers}"
                    assert excinfo.value.ordinal == serial_error.ordinal
                continue
            parallel = _native_parallel_vcf_arrays(text, workers)
            assert parallel is not None
            _assert_same_arrays(serial, parallel)
            parity_checked += 1
    assert parity_checked >= 20


# ---------------------------------------------------------------------------
# Hypothesis tier: random exploration of the same grammar (test extra).
# ---------------------------------------------------------------------------

if HAVE_HYPOTHESIS:
    _gt_alleles = st.one_of(
        st.just("."),
        st.integers(min_value=0, max_value=12).map(str),
    )
    _gt_field = st.builds(
        lambda alleles, sep: sep.join(alleles),
        st.lists(_gt_alleles, min_size=1, max_size=3),
        st.sampled_from(["/", "|"]),
    )
    _af_value = st.one_of(
        st.just("0.5"),
        st.floats(
            min_value=0.0, max_value=1.0, allow_nan=False, allow_infinity=False
        ).map(repr),
        st.sampled_from(
            [
                "1e-3", ".5", "5.", "+0.25", "-0", "0,0.5", "junk", "",
                "0.2_5", "0.5 ", " 0.5", "0x1A", "inf", "nan", "1e999",
                "0." + "1" * 70, "0.5" + " " * 61,
            ]
        ),
    )
    _info_field = st.one_of(
        st.just("."),
        st.just("DB"),
        st.just("NS=3;DP=14"),
        _af_value.map(lambda v: f"AF={v}"),
        _af_value.map(lambda v: f"NS=2;AF={v};DB"),
        st.just("XAF=9"),  # must NOT match as AF
    )
    _format_field = st.sampled_from(["GT", "GT:DP", "DP:GT", "DP"])

    @st.composite
    def _vcf_documents(draw):
        n_samples = draw(st.integers(min_value=0, max_value=5))
        n_records = draw(st.integers(min_value=0, max_value=12))
        crlf = draw(st.booleans())
        lines = ["##fileformat=VCFv4.2"]
        header = (
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT"
            + "".join(f"\tS{i}" for i in range(n_samples))
        )
        # A sample-free VCF has no FORMAT column either.
        if n_samples == 0:
            header = header[: header.rindex("\tFORMAT")]
        lines.append(header)
        for r in range(n_records):
            contig = draw(st.sampled_from(["1", "17", "chr2", "X"]))
            pos = draw(st.integers(min_value=1, max_value=10_000))
            ref = draw(st.sampled_from(["A", "AT", "GCC"]))
            fields = [
                contig,
                str(pos),
                draw(st.sampled_from([".", f"rs{r}"])),
                ref,
                draw(st.sampled_from([".", "G", "G,T"])),
                ".",
                ".",
                draw(_info_field),
            ]
            if n_samples:
                fmt = draw(_format_field)
                fields.append(fmt)
                # Sometimes fewer sample columns than the header declares.
                n_cols = draw(
                    st.sampled_from([n_samples, max(0, n_samples - 1)])
                )
                for _ in range(n_cols):
                    gt = draw(_gt_field)
                    subfields = {
                        "GT": gt,
                        "GT:DP": f"{gt}:7",
                        "DP:GT": f"7:{gt}",
                        "DP": "7",
                    }[fmt]
                    fields.append(subfields)
            lines.append("\t".join(fields))
        eol = "\r\n" if crlf else "\n"
        return eol.join(lines) + eol

    @settings(max_examples=40, deadline=None)
    @given(document=_vcf_documents())
    def test_fuzz_native_parser_matches_python(document):
        from spark_examples_tpu.sources.files import _python_vcf_arrays
        from spark_examples_tpu.utils import native as native_mod

        if native_mod.vcf_library() is None:
            pytest.skip(
                f"no native build: {native_mod.native_unavailable_reason()}"
            )

        native = native_mod.parse_vcf_arrays(document.encode())
        fd, path = tempfile.mkstemp(suffix=".vcf")
        try:
            with os.fdopen(fd, "w", newline="") as f:
                f.write(document)
            python = _python_vcf_arrays(path, "fuzz")
        finally:
            os.unlink(path)

        by_native = _group_by_contig(*native)
        by_python = _group_by_contig(*python)
        assert set(by_native) == set(by_python)
        for contig in by_native:
            _assert_same_arrays(by_native[contig], by_python[contig])

    @settings(max_examples=25, deadline=None)
    @given(
        document=_vcf_documents(),
        workers=st.sampled_from([2, 3, 5]),
    )
    def test_fuzz_chunk_parallel_parse_matches_serial(document, workers):
        """Property: for ANY fuzzed VCF document and ANY worker count, the
        chunk-parallel native parse reassembles the EXACT serial arrays —
        the parity invariant of the chunk-parallel ingest engine."""
        from spark_examples_tpu.sources.files import (
            _native_parallel_vcf_arrays,
        )
        from spark_examples_tpu.utils import native as native_mod

        if native_mod.vcf_library() is None:
            pytest.skip(
                f"no native build: {native_mod.native_unavailable_reason()}"
            )

        text = document.encode()
        serial = native_mod.parse_vcf_arrays(text)
        parallel = _native_parallel_vcf_arrays(text, workers)
        assert parallel is not None
        _assert_same_arrays(serial, parallel)


# SAM parser roundtrip property: generated SAM lines → _parse_sam wire dicts
# → ReadBuilder → the original fields. There is no second SAM implementation
# to diff against (unlike the VCF parsers), so the property pins the wire
# contract: every SAM column must survive into the Read model byte-exactly.

if HAVE_HYPOTHESIS:
    _cigar_ops = st.sampled_from(list("MIDNSHP=X"))
    _cigar_st = st.lists(
        st.tuples(st.integers(min_value=1, max_value=250), _cigar_ops),
        min_size=1,
        max_size=4,
    ).map(lambda units: "".join(f"{n}{op}" for n, op in units))

    @st.composite
    def _sam_records(draw):
        length = draw(st.integers(min_value=1, max_value=60))
        seq = draw(
            st.one_of(
                st.just("*"),
                st.text(alphabet="ACGTN", min_size=length, max_size=length),
            )
        )
        qual = (
            "*"
            if seq == "*" or draw(st.booleans())
            else "".join(
                chr(33 + q)
                for q in draw(
                    st.lists(
                        st.integers(min_value=0, max_value=60),
                        min_size=len(seq),
                        max_size=len(seq),
                    )
                )
            )
        )
        rnext = draw(st.sampled_from(["*", "=", "11"]))
        pnext = (
            0 if rnext == "*" else draw(st.integers(min_value=1, max_value=10**6))
        )
        return {
            "qname": draw(st.sampled_from(["r1", "frag.2", "x:y"])),
            "flag": draw(st.integers(min_value=0, max_value=4095)),
            "rname": draw(st.sampled_from(["17", "chr4"])),
            "pos": draw(st.integers(min_value=1, max_value=10**7)),
            "mapq": draw(st.integers(min_value=0, max_value=255)),
            "cigar": draw(_cigar_st),
            "rnext": rnext,
            "pnext": pnext,
            "tlen": draw(st.integers(min_value=-500, max_value=500)),
            "seq": seq,
            "qual": qual,
        }

    @settings(max_examples=60, deadline=None)
    @given(records=st.lists(_sam_records(), min_size=0, max_size=8))
    def test_fuzz_sam_roundtrips_through_read_builder(records):
        import tempfile

        from spark_examples_tpu.models.read import ReadBuilder
        from spark_examples_tpu.sources.files import _parse_sam
        from spark_examples_tpu.sources.stream import SpooledRecordTable

        text = "@HD\tVN:1.6\n" + "".join(
            "\t".join(
                str(r[k])
                for k in (
                    "qname", "flag", "rname", "pos", "mapq", "cigar",
                    "rnext", "pnext", "tlen", "seq", "qual",
                )
            )
            + "\n"
            for r in records
        )
        fd, path = tempfile.mkstemp(suffix=".sam")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text)
            sink = SpooledRecordTable(path)
            _parse_sam(path, "fuzz", sink)
            table = sink.finish()
            tables = {
                contig: list(table.iter_records(contig))
                for contig in table.contig_names()
            }
        finally:
            os.unlink(path)

        parsed = {}
        for contig, recs in tables.items():
            for wire in recs:
                key, read = ReadBuilder.build(wire)
                parsed[wire["id"]] = (key, read)
        assert len(parsed) == len(records)

        for i, r in enumerate(records):
            key, read = parsed[f"fuzz:{i + 1}"]  # line 0 is the header
            assert key.sequence == r["rname"]
            assert read.position == r["pos"] - 1  # 1-based POS → 0-based
            assert read.cigar == r["cigar"]  # letters survive the round trip
            assert read.mapping_quality == r["mapq"]
            assert read.fragment_name == r["qname"]
            assert read.fragment_length == r["tlen"]
            assert read.aligned_sequence == (
                "" if r["seq"] == "*" else r["seq"]
            )
            if r["qual"] == "*":
                assert read.aligned_quality == ()
            else:
                assert read.aligned_quality == tuple(
                    ord(c) - 33 for c in r["qual"]
                )
            if r["rnext"] == "*":
                assert read.mate_position is None
            else:
                assert read.mate_position == r["pnext"] - 1
                expected = r["rname"] if r["rnext"] == "=" else r["rnext"]
                assert read.mate_reference_name == expected

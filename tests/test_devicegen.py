"""Device-side genotype generation vs the host synthetic source.

The device data plane (``ops/devicegen.py``) must be bitwise-identical to the
host packed path (``sources/synthetic.py:genotype_blocks``) — same splitmix64
draws, same keep semantics — or the benchmark would be running a different
cohort than the wire path serves.
"""

import numpy as np
import pytest

import jax

from spark_examples_tpu.ops.devicegen import (
    DeviceGenGramianAccumulator,
    generate_has_variation,
    mix64,
    plan_blocks,
)
from spark_examples_tpu.ops.gramian import gramian_reference
from spark_examples_tpu.sharding.contig import Contig
from spark_examples_tpu.sources.synthetic import SyntheticGenomicsSource, _mix


def test_mix64_matches_host():
    xs = np.array(
        [0, 1, 2, 0xDEADBEEF, (1 << 64) - 1, 0x9E3779B97F4A7C15],
        dtype=np.uint64,
    )
    with jax.enable_x64(True):
        got = np.asarray(jax.device_get(mix64(jax.numpy.asarray(xs))))
    np.testing.assert_array_equal(got, _mix(xs))


def _host_blocks(source, vsid, contig, **kw):
    return list(source.genotype_blocks(vsid, contig, block_size=512, **kw))


@pytest.mark.parametrize("min_af", [None, 0.1])
def test_device_rows_bitwise_match_host_packed_path(min_af):
    source = SyntheticGenomicsSource(num_samples=40, seed=7)
    contig = Contig("17", 41_196_311, 41_277_499)  # BRCA1
    vsid = "10473108253681171589"
    host = _host_blocks(source, vsid, contig, min_allele_frequency=min_af)
    host_rows = np.concatenate([b["has_variation"] for b in host])
    host_pos = np.concatenate([b["positions"] for b in host])

    plan = list(source.site_threshold_plan(contig, min_allele_frequency=min_af))
    positions = np.concatenate([p for p, _ in plan])
    thresholds = np.concatenate([t for _, t in plan])
    with jax.enable_x64(True):
        rows = np.asarray(
            jax.device_get(
                generate_has_variation(
                    jax.numpy.asarray(positions),
                    jax.numpy.asarray(thresholds),
                    jax.numpy.asarray(
                        np.array(
                            [source.genotype_stream_key(vsid)], dtype=np.uint64
                        )
                    ),
                    jax.numpy.asarray(source.populations.astype(np.int32)),
                )
            )
        ).astype(np.uint8)
    # The host path additionally drops all-zero-variation rows; align on
    # positions and compare those rows bitwise, and check dropped rows are
    # exactly the all-zero ones.
    keep = np.isin(positions, host_pos)
    np.testing.assert_array_equal(rows[~keep], 0)
    np.testing.assert_array_equal(rows[keep], host_rows)


def test_device_multiset_concatenates_per_set_genotypes():
    source = SyntheticGenomicsSource(num_samples=12, seed=3)
    contig = Contig("20", 100_000, 140_000)
    set_a, set_b = "setA", "setB"
    plan = list(source.site_threshold_plan(contig))
    positions = np.concatenate([p for p, _ in plan])
    thresholds = np.concatenate([t for _, t in plan])
    with jax.enable_x64(True):
        rows = np.asarray(
            jax.device_get(
                generate_has_variation(
                    jax.numpy.asarray(positions),
                    jax.numpy.asarray(thresholds),
                    jax.numpy.asarray(
                        np.array(
                            [
                                source.genotype_stream_key(set_a),
                                source.genotype_stream_key(set_b),
                            ],
                            dtype=np.uint64,
                        )
                    ),
                    jax.numpy.asarray(source.populations.astype(np.int32)),
                )
            )
        ).astype(np.uint8)
    for col_off, vsid in ((0, set_a), (12, set_b)):
        host = _host_blocks(source, vsid, contig)
        host_rows = np.concatenate([b["has_variation"] for b in host])
        host_pos = np.concatenate([b["positions"] for b in host])
        keep = np.isin(positions, host_pos)
        np.testing.assert_array_equal(
            rows[keep, col_off : col_off + 12], host_rows
        )


@pytest.mark.parametrize("exact_int", [True, False])
def test_fused_accumulator_matches_reference_gramian(exact_int):
    source = SyntheticGenomicsSource(num_samples=24, seed=11)
    contig = Contig("1", 0, 60_000)
    vsid = "vs"
    host = _host_blocks(source, vsid, contig)
    host_rows = np.concatenate([b["has_variation"] for b in host])

    acc = DeviceGenGramianAccumulator(
        num_samples=24,
        vs_keys=[source.genotype_stream_key(vsid)],
        pops=source.populations,
        block_size=64,
        blocks_per_dispatch=4,
        exact_int=exact_int,
    )
    for pos, thr in plan_blocks(
        source.site_threshold_plan(contig), 64, 4, source.n_pops
    ):
        acc.add_plan(pos, thr)
    got = acc.finalize()
    np.testing.assert_array_equal(got, gramian_reference(host_rows))
    with jax.enable_x64(True):
        variant_rows = int(jax.device_get(acc.variant_rows))
    assert variant_rows == host_rows.shape[0]


def test_plan_blocks_pads_final_group():
    batches = [
        (np.arange(5, dtype=np.int64), np.ones((5, 2), dtype=np.uint64)),
        (np.arange(5, 8, dtype=np.int64), np.ones((3, 2), dtype=np.uint64)),
    ]
    groups = list(plan_blocks(iter(batches), block_size=3, blocks_per_dispatch=2, n_pops=2))
    assert len(groups) == 2
    pos0, thr0 = groups[0]
    assert pos0.shape == (2, 3) and thr0.shape == (2, 3, 2)
    np.testing.assert_array_equal(pos0.ravel(), np.arange(6))
    pos1, thr1 = groups[1]
    np.testing.assert_array_equal(pos1.ravel(), [6, 7, 0, 0, 0, 0])
    np.testing.assert_array_equal(thr1.reshape(-1, 2)[2:], 0)

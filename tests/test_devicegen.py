"""Device-side ingest vs the host synthetic source.

The device data plane (``ops/devicegen.py``) must be bitwise-identical to the
host packed path (``sources/synthetic.py:genotype_blocks``) — same splitmix64
draws, same fixed-point site metadata, same keep semantics — or the benchmark
would be running a different cohort than the wire path serves.
"""

import numpy as np
import pytest

import jax

from spark_examples_tpu.ops.devicegen import (
    DeviceGenGramianAccumulator,
    generate_has_variation,
    mix64,
    site_thresholds_on_device,
)
from spark_examples_tpu.ops.gramian import gramian_reference
from spark_examples_tpu.sharding.contig import Contig
from spark_examples_tpu.sources.synthetic import (
    SyntheticGenomicsSource,
    _mix,
    af_filter_micro,
)


def test_mix64_matches_host():
    xs = np.array(
        [0, 1, 2, 0xDEADBEEF, (1 << 64) - 1, 0x9E3779B97F4A7C15],
        dtype=np.uint64,
    )
    with jax.enable_x64(True):
        got = np.asarray(jax.device_get(mix64(jax.numpy.asarray(xs))))
    np.testing.assert_array_equal(got, _mix(xs))


def test_fmix32_matches_host_and_murmur3_vectors():
    """Device fmix32 == host _fmix32 == the published murmur3 finalizer
    (golden vectors pin the stream definition: any accidental drift in
    either implementation breaks loudly, not as a silent cohort change)."""
    from spark_examples_tpu.ops.devicegen import fmix32
    from spark_examples_tpu.sources.synthetic import _fmix32

    xs = np.array(
        [0, 1, 2, 0xDEADBEEF, 0xFFFFFFFF, 0x9E3779B9], dtype=np.uint32
    )
    host = _fmix32(xs)
    got = np.asarray(jax.device_get(fmix32(jax.numpy.asarray(xs))))
    np.testing.assert_array_equal(got, host)
    # murmur3 fmix32 reference values (h ^= h>>16; h*=0x85ebca6b;
    # h ^= h>>13; h*=0xc2b2ae35; h ^= h>>16), independently computed.
    def reference(h):
        h ^= h >> 16
        h = (h * 0x85EBCA6B) & 0xFFFFFFFF
        h ^= h >> 13
        h = (h * 0xC2B2AE35) & 0xFFFFFFFF
        h ^= h >> 16
        return h

    np.testing.assert_array_equal(host, [reference(int(x)) for x in xs])


def test_genotype_draw_pair_golden_vectors():
    """The v2 genotype stream definition, pinned: fold-after-sample-xor of
    the splitmix64 site state, one fmix32, multiplicative second allele.
    These values changing means the synthetic cohort itself changed —
    every recorded benchmark and parity artifact would silently shift."""
    from spark_examples_tpu.sources.synthetic import _genotype_draw_pair

    d1, d2 = _genotype_draw_pair(
        np.uint64(0x123456789ABCDEF0),
        np.array([100, 7300], dtype=np.int64),
        3,
    )
    assert d1.shape == (2, 3) and d1.dtype == np.uint32
    # Independently recomputed with the documented construction.
    def expected(vs_key, pos, sample):
        M = (1 << 64) - 1
        P1, P2, P3, P4 = (
            0x9E3779B97F4A7C15,
            0xC2B2AE3D27D4EB4F,
            0x165667B19E3779F9,
            0xD6E8FEB86659FD93,
        )

        def mix(x):
            x = (x + P1) & M
            x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & M
            x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & M
            return x ^ (x >> 31)

        h2 = mix(mix(vs_key ^ (pos * P2 & M)) ^ (100 * P3 & M))
        x64 = h2 ^ (sample * P4 & M)
        x = ((x64 >> 32) ^ x64) & 0xFFFFFFFF
        x ^= x >> 16
        x = (x * 0x85EBCA6B) & 0xFFFFFFFF
        x ^= x >> 13
        x = (x * 0xC2B2AE35) & 0xFFFFFFFF
        first = x ^ (x >> 16)
        second = ((first * 0x9E3779B9) & 0xFFFFFFFF) ^ 0x85EBCA6B
        return first, second

    for i, pos in enumerate((100, 7300)):
        for s in range(3):
            e1, e2 = expected(0x123456789ABCDEF0, pos, s)
            assert (int(d1[i, s]), int(d2[i, s])) == (e1, e2)


def _host_blocks(source, vsid, contig, **kw):
    return list(source.genotype_blocks(vsid, contig, block_size=512, **kw))


@pytest.mark.parametrize("min_af", [None, 0.1])
def test_device_thresholds_bitwise_match_host_plan(min_af):
    """On-device site metadata == the host's compacted threshold plan."""
    source = SyntheticGenomicsSource(num_samples=40, seed=7)
    contig = Contig("17", 41_196_311, 41_277_499)  # BRCA1
    plan = list(source.site_threshold_plan(contig, min_allele_frequency=min_af))
    host_pos = np.concatenate([p for p, _ in plan])
    host_thr = np.concatenate([t for _, t in plan])

    k0, k1 = source.site_grid_range(contig)
    grid_pos = np.arange(k0, k1, dtype=np.int64) * source.variant_spacing
    with jax.enable_x64(True):
        T = np.asarray(
            jax.device_get(
                site_thresholds_on_device(
                    jax.numpy.asarray(np.uint64(source.site_key)),
                    jax.numpy.asarray(grid_pos),
                    jax.numpy.asarray(np.ones(len(grid_pos), dtype=bool)),
                    source.n_pops,
                    source.ref_block_fraction,
                    af_filter_micro(min_af),
                )
            )
        )
    keep = np.isin(grid_pos, host_pos)
    np.testing.assert_array_equal(T[~keep], 0)
    np.testing.assert_array_equal(T[keep], host_thr)


def test_device_rows_bitwise_match_host_packed_path():
    source = SyntheticGenomicsSource(num_samples=40, seed=7)
    contig = Contig("17", 41_196_311, 41_277_499)
    vsid = "10473108253681171589"
    host = _host_blocks(source, vsid, contig)
    host_rows = np.concatenate([b["has_variation"] for b in host])
    host_pos = np.concatenate([b["positions"] for b in host])

    k0, k1 = source.site_grid_range(contig)
    grid_pos = np.arange(k0, k1, dtype=np.int64) * source.variant_spacing
    with jax.enable_x64(True):
        T = site_thresholds_on_device(
            jax.numpy.asarray(np.uint64(source.site_key)),
            jax.numpy.asarray(grid_pos),
            jax.numpy.asarray(np.ones(len(grid_pos), dtype=bool)),
            source.n_pops,
            source.ref_block_fraction,
            None,
        )
        rows = np.asarray(
            jax.device_get(
                generate_has_variation(
                    jax.numpy.asarray(grid_pos),
                    T,
                    jax.numpy.asarray(
                        np.array(
                            [source.genotype_stream_key(vsid)], dtype=np.uint64
                        )
                    ),
                    jax.numpy.asarray(source.populations.astype(np.int32)),
                )
            )
        ).astype(np.uint8)
    # The host path additionally drops all-zero-variation rows; align on
    # positions and compare those rows bitwise, and check dropped rows are
    # exactly the all-zero ones.
    keep = np.isin(grid_pos, host_pos)
    np.testing.assert_array_equal(rows[~keep], 0)
    np.testing.assert_array_equal(rows[keep], host_rows)


@pytest.mark.parametrize("exact_int", [True, False])
def test_fused_accumulator_matches_reference_gramian(exact_int):
    source = SyntheticGenomicsSource(num_samples=24, seed=11)
    contig = Contig("1", 0, 60_000)
    vsid = "vs"
    host = _host_blocks(source, vsid, contig)
    host_rows = np.concatenate([b["has_variation"] for b in host])

    acc = DeviceGenGramianAccumulator(
        num_samples=24,
        vs_keys=[source.genotype_stream_key(vsid)],
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        block_size=64,
        blocks_per_dispatch=4,
        exact_int=exact_int,
    )
    k0, k1 = source.site_grid_range(contig)
    acc.add_grid(k0, k1)
    got = acc.finalize()
    np.testing.assert_array_equal(got, gramian_reference(host_rows))
    with jax.enable_x64(True):
        variant_rows = np.asarray(jax.device_get(acc.variant_rows))
        kept = int(jax.device_get(acc.kept_sites))
    assert variant_rows.tolist() == [host_rows.shape[0]]
    # kept_sites counts AF/ref-kept sites BEFORE the all-zero-variation drop
    # — the compacted host threshold plan's site count.
    plan_sites = sum(
        len(p) for p, _ in source.site_threshold_plan(contig)
    )
    assert kept == plan_sites


def test_fused_accumulator_min_af_matches_host():
    source = SyntheticGenomicsSource(num_samples=16, seed=3)
    contig = Contig("2", 10_000, 90_000)
    vsid = "vs"
    host = _host_blocks(source, vsid, contig, min_allele_frequency=0.15)
    host_rows = np.concatenate([b["has_variation"] for b in host])

    acc = DeviceGenGramianAccumulator(
        num_samples=16,
        vs_keys=[source.genotype_stream_key(vsid)],
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        min_af_micro=af_filter_micro(0.15),
        block_size=32,
        blocks_per_dispatch=2,
    )
    k0, k1 = source.site_grid_range(contig)
    acc.add_grid(k0, k1)
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(host_rows))


def test_auto_blocks_per_dispatch_scales_with_cohort():
    """Constant device work per dispatch: the tuned large-N geometry stays
    put, small cohorts get longer scans (platinum whole-genome ~2× faster,
    1.03 → 0.53 s — DESIGN.md §7.3), clamped to the measured [32, 512]
    range and a multiple of 8 (the tail program is K/8 blocks)."""
    from spark_examples_tpu.ops.devicegen import auto_blocks_per_dispatch

    assert auto_blocks_per_dispatch(2504, 16384) == 32  # the tuned optimum
    assert auto_blocks_per_dispatch(2504, 1024) == 512  # same group sites
    assert auto_blocks_per_dispatch(17, 16384) == 512  # clamp high
    assert auto_blocks_per_dispatch(25_000, 16384) == 32  # clamp low
    k = auto_blocks_per_dispatch(500, 16384)
    assert 32 <= k <= 512 and k % 8 == 0


def test_poke_gating_spans_grid_walks():
    """The eager-mode poke fires exactly once, at the first dispatch with
    more work following — including work in a LATER add_grid call: a
    single-group first contig must not suppress the poke for the rest of a
    multi-contig run, and a single-group-only run must never poke (it would
    pay a pure round-trip for an overlap it cannot use)."""
    source = SyntheticGenomicsSource(num_samples=8, seed=5)

    def make():
        return DeviceGenGramianAccumulator(
            num_samples=8,
            vs_keys=[source.genotype_stream_key("vs")],
            pops=source.populations,
            site_key=source.site_key,
            spacing=source.variant_spacing,
            ref_block_fraction=source.ref_block_fraction,
            block_size=32,
            blocks_per_dispatch=2,
        )

    group = 32 * 2
    # Single-group run: no poke.
    acc = make()
    acc.add_grid(0, group)
    assert acc.dispatches == 1 and not acc._poked
    # Multi-group run: poked.
    acc = make()
    acc.add_grid(0, 3 * group)
    assert acc.dispatches == 3 and acc._poked
    # Single-group FIRST contig, then a multi-group contig: the poke fires
    # during the second walk.
    acc = make()
    acc.add_grid(0, group)
    assert not acc._poked
    acc.add_grid(10 * group, 13 * group)
    assert acc._poked
    # Many contigs each fitting ONE group (decoy-heavy --all-references):
    # the second dispatch — in a different add_grid — still pokes.
    acc = make()
    acc.add_grid(0, group)
    assert not acc._poked
    acc.add_grid(10 * group, 11 * group)
    assert acc.dispatches == 2 and acc._poked


def test_device_multiset_concatenates_per_set_genotypes():
    source = SyntheticGenomicsSource(num_samples=12, seed=3)
    contig = Contig("20", 100_000, 140_000)
    set_a, set_b = "setA", "setB"
    acc = DeviceGenGramianAccumulator(
        num_samples=12,
        vs_keys=[
            source.genotype_stream_key(set_a),
            source.genotype_stream_key(set_b),
        ],
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        block_size=32,
        blocks_per_dispatch=2,
    )
    k0, k1 = source.site_grid_range(contig)
    acc.add_grid(k0, k1)
    got = acc.finalize()

    rows_a = np.concatenate(
        [b["has_variation"] for b in _host_blocks(source, set_a, contig)]
    )
    pos_a = np.concatenate(
        [b["positions"] for b in _host_blocks(source, set_a, contig)]
    )
    rows_b = np.concatenate(
        [b["has_variation"] for b in _host_blocks(source, set_b, contig)]
    )
    pos_b = np.concatenate(
        [b["positions"] for b in _host_blocks(source, set_b, contig)]
    )
    # Build the joint matrix on the shared kept-site grid (drops differ only
    # by all-zero rows, which don't affect the Gramian).
    all_pos = np.union1d(pos_a, pos_b)
    joint = np.zeros((len(all_pos), 24), dtype=np.int64)
    joint[np.searchsorted(all_pos, pos_a), :12] = rows_a
    joint[np.searchsorted(all_pos, pos_b), 12:] = rows_b
    np.testing.assert_array_equal(got, joint.T @ joint)


@pytest.mark.parametrize(
    "mesh_shape", [{"samples": 4}, {"data": 2, "samples": 2}]
)
def test_ring_multiset_matches_dense_and_host(mesh_shape):
    """Multi-set ring ingest: concatenated per-set column blocks through the
    ring exchange equal the dense multi-set accumulator AND the host joint
    oracle — asymmetric cohorts (13 + 6 columns, padded 20) included, with
    per-set variant-row accounting identical to the dense path."""
    from spark_examples_tpu.ops.devicegen import DeviceGenRingGramianAccumulator
    from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS, make_mesh

    mesh = make_mesh(
        {
            **({DATA_AXIS: mesh_shape["data"]} if "data" in mesh_shape else {}),
            SAMPLES_AXIS: mesh_shape["samples"],
        }
    )
    source = SyntheticGenomicsSource(
        num_samples=13, seed=3, cohort_sizes={"setB": 6}
    )
    contig = Contig("20", 100_000, 140_000)
    sets = ["setA", "setB"]
    sizes = [source.num_samples_for(s) for s in sets]
    assert sizes == [13, 6]
    pops_per_set = [source.populations_for(s) for s in sets]
    keys = [source.genotype_stream_key(s) for s in sets]
    common = dict(
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        block_size=16,
        blocks_per_dispatch=2,
        n_pops=source.n_pops,
    )
    dense = DeviceGenGramianAccumulator(
        num_samples=13,
        vs_keys=keys,
        pops=source.populations,
        set_sizes=sizes,
        pops_per_set=pops_per_set,
        **common,
    )
    ring = DeviceGenRingGramianAccumulator(
        num_samples=13,
        vs_key=keys,
        pops=source.populations,
        mesh=mesh,
        set_sizes=sizes,
        pops_per_set=pops_per_set,
        **common,
    )
    k0, k1 = source.site_grid_range(contig)
    dense.add_grid(k0, k1)
    ring.add_grid(k0, k1)
    dense_G = dense.finalize()
    ring_G = ring.finalize()
    np.testing.assert_array_equal(ring_G, dense_G)

    # Host joint oracle on the shared kept-site grid.
    rows = {}
    pos = {}
    for s in sets:
        blocks = _host_blocks(source, s, contig)
        rows[s] = np.concatenate([b["has_variation"] for b in blocks])
        pos[s] = np.concatenate([b["positions"] for b in blocks])
    all_pos = np.union1d(pos[sets[0]], pos[sets[1]])
    joint = np.zeros((len(all_pos), sum(sizes)), dtype=np.int64)
    joint[np.searchsorted(all_pos, pos[sets[0]]), : sizes[0]] = rows[sets[0]]
    joint[np.searchsorted(all_pos, pos[sets[1]]), sizes[0] :] = rows[sets[1]]
    np.testing.assert_array_equal(ring_G, joint.T @ joint)

    dense_rows, dense_kept = dense.ingest_counters()
    ring_rows, ring_kept = ring.ingest_counters()
    np.testing.assert_array_equal(ring_rows, dense_rows)
    assert ring_kept == dense_kept
    assert ring_rows.tolist() == [rows["setA"].shape[0], rows["setB"].shape[0]]


def test_add_range_validates():
    source = SyntheticGenomicsSource(num_samples=8, seed=1)
    acc = DeviceGenGramianAccumulator(
        num_samples=8,
        vs_keys=[source.genotype_stream_key("v")],
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        block_size=8,
        blocks_per_dispatch=2,
    )
    with pytest.raises(ValueError):
        acc.add_range(0, 0)
    with pytest.raises(ValueError):
        acc.add_range(0, 17)


def test_fused_accumulator_data_parallel_mesh():
    """Data-parallel device ingest: slices generate disjoint grid spans,
    finalize psums — equals the host reference Gramian."""
    from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS, make_mesh

    mesh = make_mesh({DATA_AXIS: 4, SAMPLES_AXIS: 2})
    source = SyntheticGenomicsSource(num_samples=20, seed=13)
    contig = Contig("3", 0, 120_000)
    vsid = "vs"
    host = _host_blocks(source, vsid, contig)
    host_rows = np.concatenate([b["has_variation"] for b in host])

    acc = DeviceGenGramianAccumulator(
        num_samples=20,
        vs_keys=[source.genotype_stream_key(vsid)],
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        block_size=32,
        blocks_per_dispatch=2,
        mesh=mesh,
    )
    k0, k1 = source.site_grid_range(contig)
    acc.add_grid(k0, k1)
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(host_rows))
    with jax.enable_x64(True):
        rows = np.asarray(jax.device_get(acc.variant_rows))
    assert rows.shape == (4, 1)
    assert rows.sum() == host_rows.shape[0]


def test_device_ingest_bitwise_identical_across_device_counts():
    """Determinism across parallelism (the race-detection stand-in,
    SURVEY §5): int32 accumulation is associative, so 1-device and 4-slice
    data-parallel ingest produce BITWISE-identical Gramians and counters."""
    from spark_examples_tpu.parallel.mesh import DATA_AXIS, make_mesh

    source = SyntheticGenomicsSource(num_samples=16, seed=21)
    contig = Contig("5", 0, 150_000)
    kw = dict(
        num_samples=16,
        vs_keys=[source.genotype_stream_key("vs")],
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        block_size=32,
        blocks_per_dispatch=2,
    )
    k0, k1 = source.site_grid_range(contig)

    acc1 = DeviceGenGramianAccumulator(**kw)
    acc1.add_grid(k0, k1)
    acc4 = DeviceGenGramianAccumulator(**kw, mesh=make_mesh({DATA_AXIS: 4}))
    acc4.add_grid(k0, k1)
    np.testing.assert_array_equal(acc1.finalize(), acc4.finalize())
    with jax.enable_x64(True):
        r1 = np.asarray(jax.device_get(acc1.variant_rows)).sum()
        r4 = np.asarray(jax.device_get(acc4.variant_rows)).sum()
        k1_ = int(np.asarray(jax.device_get(acc1.kept_sites)).sum())
        k4_ = int(np.asarray(jax.device_get(acc4.kept_sites)).sum())
    assert r1 == r4 and k1_ == k4_


def test_device_ingest_bitwise_matches_host_fuzz():
    """Fuzz the device ingest kernel against the host packed path: any
    cohort/seed/region must produce the identical Gramian."""
    pytest.importorskip("hypothesis")  # declared only under the `test` extra
    from hypothesis import given, settings, strategies as st

    @given(
        seed=st.integers(min_value=0, max_value=2**31),
        n=st.integers(min_value=2, max_value=12),
        start=st.integers(min_value=0, max_value=200_000),
        width=st.integers(min_value=200, max_value=4_000),
    )
    @settings(max_examples=10, deadline=None)
    def check(seed, n, start, width):
        source = SyntheticGenomicsSource(num_samples=n, seed=seed)
        contig = Contig("7", start, start + width)
        blocks = _host_blocks(source, "vs", contig)
        rows = (
            np.concatenate([b["has_variation"] for b in blocks])
            if blocks
            else np.zeros((0, n), np.uint8)
        )
        acc = DeviceGenGramianAccumulator(
            num_samples=n,
            vs_keys=[source.genotype_stream_key("vs")],
            pops=source.populations,
            site_key=source.site_key,
            spacing=source.variant_spacing,
            ref_block_fraction=source.ref_block_fraction,
            block_size=16,
            blocks_per_dispatch=2,
        )
        k0, k1 = source.site_grid_range(contig)
        if k1 > k0:
            acc.add_grid(k0, k1)
        np.testing.assert_array_equal(acc.finalize(), gramian_reference(rows))

    check()


@pytest.mark.parametrize("mesh_shape", [{"samples": 4}, {"data": 2, "samples": 4}])
def test_ring_device_ingest_matches_host(mesh_shape):
    """Sharded large-N device ingest: per-slice column generation + ring
    exchange equals the host reference Gramian, at padded non-divisible N."""
    from spark_examples_tpu.ops.devicegen import DeviceGenRingGramianAccumulator
    from spark_examples_tpu.parallel.mesh import DATA_AXIS, SAMPLES_AXIS, make_mesh

    mesh = make_mesh(
        {
            **({DATA_AXIS: mesh_shape["data"]} if "data" in mesh_shape else {}),
            SAMPLES_AXIS: mesh_shape["samples"],
        }
    )
    source = SyntheticGenomicsSource(num_samples=18, seed=9)  # 18 % 4 != 0
    contig = Contig("4", 5_000, 95_000)
    host = _host_blocks(source, "vs", contig)
    host_rows = np.concatenate([b["has_variation"] for b in host])

    acc = DeviceGenRingGramianAccumulator(
        num_samples=18,
        vs_key=source.genotype_stream_key("vs"),
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        mesh=mesh,
        block_size=16,
        blocks_per_dispatch=2,
    )
    k0, k1 = source.site_grid_range(contig)
    acc.add_grid(k0, k1)
    np.testing.assert_array_equal(acc.finalize(), gramian_reference(host_rows))
    with jax.enable_x64(True):
        rows = int(np.asarray(jax.device_get(acc.variant_rows)).sum())
        kept = int(np.asarray(jax.device_get(acc.kept_sites)).sum())
    assert rows == host_rows.shape[0]
    plan_sites = sum(len(p) for p, _ in source.site_threshold_plan(contig))
    assert kept == plan_sites


def test_ring_device_ingest_end_to_end_sharded_pca():
    """Ring device ingest feeds the sharded centering + eigensolve without
    gathering N x N; result matches the dense single-device pipeline."""
    from spark_examples_tpu.ops.centering import gower_center, gower_center_sharded
    from spark_examples_tpu.ops.devicegen import (
        DeviceGenGramianAccumulator,
        DeviceGenRingGramianAccumulator,
    )
    from spark_examples_tpu.ops.pca import (
        principal_components_subspace,
        principal_components_subspace_sharded,
    )
    from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS, make_mesh

    mesh = make_mesh({SAMPLES_AXIS: 8})
    source = SyntheticGenomicsSource(num_samples=21, seed=17)
    contig = Contig("6", 0, 200_000)
    kw = dict(
        pops=source.populations,
        site_key=source.site_key,
        spacing=source.variant_spacing,
        ref_block_fraction=source.ref_block_fraction,
        block_size=32,
        blocks_per_dispatch=2,
    )
    k0, k1 = source.site_grid_range(contig)

    ring = DeviceGenRingGramianAccumulator(
        num_samples=21, vs_key=source.genotype_stream_key("vs"), mesh=mesh, **kw
    )
    ring.add_grid(k0, k1)
    B_sharded = gower_center_sharded(ring.finalize_sharded(), mesh, n_true=21)
    c_sharded, e_sharded = principal_components_subspace_sharded(
        B_sharded, mesh, 2, n_true=21
    )
    c_sharded = np.asarray(jax.device_get(c_sharded))[:21]

    dense = DeviceGenGramianAccumulator(
        num_samples=21, vs_keys=[source.genotype_stream_key("vs")], **kw
    )
    dense.add_grid(k0, k1)
    import jax.numpy as jnp

    B_dense = gower_center(jnp.asarray(dense.finalize_device(), jnp.float32))
    c_dense, e_dense = principal_components_subspace(B_dense, 2)
    c_dense = np.asarray(jax.device_get(c_dense))

    np.testing.assert_allclose(
        np.asarray(jax.device_get(e_sharded)),
        np.asarray(jax.device_get(e_dense)),
        rtol=1e-4,
    )
    signs = np.sign((c_dense * c_sharded).sum(axis=0))
    signs[signs == 0] = 1
    np.testing.assert_allclose(c_dense, c_sharded * signs, atol=1e-3)

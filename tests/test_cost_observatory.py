"""Fleet cost observatory (obs/costmodel.py, obs/calibration.py,
obs/report.py + the serve wiring): histogram quantile estimation edge
cases, the queued-deadline capacity-leak regression, the cost model's
floor/round-trip contract, calibration ledger fold/merge/crash
semantics, the heartbeat cost segment, manifest ``cost``-block
validation, journal round-trip of the prediction through compaction,
the deadline-infeasibility 413 (and its opt-out), fleet stats, and the
post-mortem ``obs report`` CLI verb."""

import json
import math
import os
import time

import pytest

from spark_examples_tpu.obs.calibration import (
    CalibrationFold,
    CalibrationLedger,
    MIN_CALIBRATION_SAMPLES,
    _Reservoir,
    calibration_path,
    fold_calibration,
)
from spark_examples_tpu.obs.costmodel import (
    COLD_COMPILE_SECONDS,
    DISPATCH_OVERHEAD_SECONDS,
    MIN_PREDICTED_SECONDS,
    CostPrediction,
    estimate_seconds,
)
from spark_examples_tpu.obs.heartbeat import Heartbeat
from spark_examples_tpu.obs.metrics import (
    COST_CALIBRATION_SAMPLES,
    COST_MEASURED_MEAN_SECONDS,
    COST_PREDICTED_MEAN_SECONDS,
    WIDE_SECONDS_BUCKETS,
    MetricsRegistry,
    histogram_quantile,
)
from spark_examples_tpu.serve.executor import ExecutionOutcome
from spark_examples_tpu.serve.protocol import parse_request, request_doc
from spark_examples_tpu.serve.queue import (
    LARGE_CLASS,
    SMALL_CLASS,
    BoundedJobQueue,
    Job,
    QueueFull,
)

TINY_FLAGS = ["--num-samples", "8", "--references", "1:0:50000"]


# ---------------------------------------------------- histogram_quantile


def _snapshot(values, buckets=(0.1, 1.0, 10.0)):
    """Build a cumulative-bucket snapshot the way Histogram.snapshot()
    does, from raw observations."""
    counts = {}
    for bound in buckets:
        counts[repr(float(bound))] = sum(1 for v in values if v <= bound)
    counts["+Inf"] = len(values)
    return {
        "buckets": counts,
        "sum": float(sum(values)),
        "count": len(values),
    }


def test_histogram_quantile_empty_is_none():
    assert histogram_quantile(_snapshot([]), 0.5) is None
    assert histogram_quantile({"buckets": {}, "sum": 0.0, "count": 0}, 0.5) \
        is None


def test_histogram_quantile_q0_and_q1_edges():
    snap = _snapshot([0.5, 0.7, 5.0])
    # q=0: the lower edge of the first populated bucket (0.1, 1.0].
    assert histogram_quantile(snap, 0.0) == pytest.approx(0.1)
    # q=1: the upper bound of the highest populated bucket.
    assert histogram_quantile(snap, 1.0) == pytest.approx(10.0)
    # Out-of-range q clamps, never raises.
    assert histogram_quantile(snap, -3.0) == pytest.approx(0.1)
    assert histogram_quantile(snap, 7.0) == pytest.approx(10.0)


def test_histogram_quantile_inf_mass_clamps_to_top_finite_bound():
    # All mass past every finite bound: the estimate is the top finite
    # bound (the honest "at least this much"), never inf/NaN.
    snap = _snapshot([50.0, 99.0], buckets=(0.1, 1.0, 10.0))
    for q in (0.5, 0.99, 1.0):
        estimate = histogram_quantile(snap, q)
        assert estimate == pytest.approx(10.0), q
        assert math.isfinite(estimate)


def test_histogram_quantile_interpolates_within_bucket():
    # 4 observations all inside (1.0, 10.0]: the median interpolates
    # linearly within that bucket, strictly between its edges.
    snap = _snapshot([2.0, 3.0, 4.0, 5.0])
    p50 = histogram_quantile(snap, 0.5)
    assert 1.0 < p50 < 10.0
    # Rank 2 of 4 in a bucket spanning [1, 10]: 1 + (2/4)*9 = 5.5.
    assert p50 == pytest.approx(5.5)


def test_wide_seconds_buckets_reach_hours():
    assert WIDE_SECONDS_BUCKETS == tuple(sorted(WIDE_SECONDS_BUCKETS))
    assert WIDE_SECONDS_BUCKETS[0] <= 0.01  # sub-dispatch-overhead floor
    assert WIDE_SECONDS_BUCKETS[-1] >= 3600.0  # whole-genome large jobs


# ------------------------------------------- queued-deadline capacity leak


def _queued_job(job_id, job_class=SMALL_CLASS, deadline_unix=None):
    return Job(
        id=job_id,
        request=parse_request(request_doc(TINY_FLAGS)),
        conf=None,
        job_class=job_class,
        submitted_unix=time.time(),
        deadline_unix=deadline_unix,
    )


def test_full_queue_of_expired_jobs_admits_new_job():
    """The capacity-leak regression: expired queued jobs must free their
    capacity at the next admission instead of 429ing live traffic."""
    q = BoundedJobQueue(small_capacity=2, large_capacity=1)
    settled = []
    q.set_expired_sink(settled.append)
    soon = time.time() + 0.05
    q.put(_queued_job("S1", deadline_unix=soon))
    q.put(_queued_job("S2", deadline_unix=soon))
    with pytest.raises(QueueFull):
        q.put(_queued_job("S3"))  # full, nothing expired yet
    time.sleep(0.08)
    q.put(_queued_job("S4"))  # sweeps S1+S2, admits without QueueFull
    assert [j.id for j in settled] == ["S1", "S2"]
    assert q.depth() == {SMALL_CLASS: 1, LARGE_CLASS: 0}
    assert q.pop(timeout=1).id == "S4"


def test_expired_sweep_delivers_even_when_put_still_raises():
    """Cross-lane sweep: a small-lane 429 must not re-strand the expired
    LARGE job the same put already removed."""
    q = BoundedJobQueue(small_capacity=1, large_capacity=1)
    settled = []
    q.set_expired_sink(settled.append)
    q.put(_queued_job("S-live"))
    q.put(_queued_job("L-exp", LARGE_CLASS, deadline_unix=time.time() + 0.05))
    time.sleep(0.08)
    with pytest.raises(QueueFull):
        q.put(_queued_job("S-new"))  # small lane still full of live work
    assert [j.id for j in settled] == ["L-exp"]
    assert q.depth() == {SMALL_CLASS: 1, LARGE_CLASS: 0}


def test_no_sink_means_no_sweep():
    """Without an owner to settle them, expired jobs must NOT be removed
    (they would be stranded in 'queued' forever)."""
    q = BoundedJobQueue(small_capacity=1, large_capacity=1)
    q.put(_queued_job("S1", deadline_unix=time.time() - 1))
    with pytest.raises(QueueFull):
        q.put(_queued_job("S2"))
    assert q.pop(timeout=1).id == "S1"


# -------------------------------------------------------------- cost model


def test_estimate_floor_overhead_and_cold_penalty():
    warm = estimate_seconds(
        sites=1_000_000, host_peak_bytes=None, sched_seconds=None, cold=False
    )
    cold = estimate_seconds(
        sites=1_000_000, host_peak_bytes=None, sched_seconds=None, cold=True
    )
    assert warm["predicted_seconds"] == pytest.approx(
        DISPATCH_OVERHEAD_SECONDS + warm["compute_seconds"]
    )
    assert cold["predicted_seconds"] - warm["predicted_seconds"] == (
        pytest.approx(COLD_COMPILE_SECONDS)
    )
    # No facts at all: still strictly positive (the 413 determinism
    # floor), never zero.
    empty = estimate_seconds(
        sites=None, host_peak_bytes=None, sched_seconds=None, cold=False
    )
    assert empty["predicted_seconds"] == pytest.approx(MIN_PREDICTED_SECONDS)
    # The link term dominates when the schedule simulator's critical
    # path is longer than the compute term (they overlap, not add).
    linked = estimate_seconds(
        sites=10, host_peak_bytes=None, sched_seconds=9.0, cold=False
    )
    assert linked["predicted_seconds"] == pytest.approx(
        DISPATCH_OVERHEAD_SECONDS + 9.0
    )


def test_cost_prediction_round_trip_and_junk():
    pred = CostPrediction(
        predicted_seconds=1.5,
        kind="pca",
        fingerprint="abc123",
        compile="warm",
        compute_seconds=0.2,
        sites=501,
        host_peak_bytes=1 << 30,
    )
    back = CostPrediction.from_dict(json.loads(json.dumps(pred.to_dict())))
    assert back == pred
    assert CostPrediction.from_dict({}) is None
    assert CostPrediction.from_dict({"predicted_seconds": "junk"}) is None
    assert CostPrediction.from_dict({"predicted_seconds": float("nan")}) \
        is None
    assert CostPrediction.from_dict({"predicted_seconds": -1.0}) is None


def test_best_estimate_prefers_calibrated():
    pred = CostPrediction(predicted_seconds=2.0)
    assert pred.best_estimate_seconds == 2.0
    pred.calibrated_seconds = 6.0
    assert pred.best_estimate_seconds == 6.0


def test_predict_job_cost_from_conf():
    """The shared estimator (check/plan.py): device-free, reuses the
    plan validator's geometry, positive, fingerprinted."""
    from spark_examples_tpu.check.plan import predict_job_cost
    from spark_examples_tpu.config import PcaConf

    conf = PcaConf.parse(TINY_FLAGS)
    pred = predict_job_cost(conf)
    assert pred.predicted_seconds >= MIN_PREDICTED_SECONDS
    assert pred.sites and pred.sites > 0
    assert pred.fingerprint
    assert pred.compile in ("warm", "cold")
    assert CostPrediction.from_dict(pred.to_dict()) == pred


# ------------------------------------------------------ calibration ledger


def _row(fingerprint="fp1", predicted=2.0, measured=1.0, **extra):
    doc = {
        "fingerprint": fingerprint,
        "kind": "pca",
        "job_class": "small",
        "predicted_seconds": predicted,
        "measured_seconds": measured,
        "queue_wait_seconds": 0.1,
        "compile": "warm",
    }
    doc.update(extra)
    return doc


def test_fold_learns_per_geometry_ratio_and_calibrates():
    fold = CalibrationFold()
    for _ in range(max(2, MIN_CALIBRATION_SAMPLES)):
        assert fold.add(_row("fp1", predicted=2.0, measured=1.0))
        assert fold.add(_row("fp2", predicted=1.0, measured=3.0))
    assert fold.ratio_for("fp1") == pytest.approx(0.5)
    assert fold.ratio_for("fp2") == pytest.approx(3.0)
    # Unknown geometry: the overall fleet ratio, not None.
    assert fold.ratio_for("fp-never-seen") == pytest.approx(
        fold.overall.ratio
    )
    pred = CostPrediction(predicted_seconds=4.0, fingerprint="fp1")
    fold.calibrated_estimate(pred)
    assert pred.calibrated_seconds == pytest.approx(2.0)
    assert pred.calibration_ratio == pytest.approx(0.5)
    assert pred.calibration_samples >= MIN_CALIBRATION_SAMPLES
    assert pred.best_estimate_seconds == pytest.approx(2.0)


def test_fold_skips_junk_and_failed_rows():
    fold = CalibrationFold()
    assert not fold.add("not a dict")
    assert not fold.add({"predicted_seconds": 1.0})  # no measured
    assert not fold.add(_row(predicted=float("nan")))
    assert not fold.add(_row(predicted=-1.0))
    # A failed row (stolen job the survivor fenced off) exists for the
    # post-mortem report, never for the ratio fold.
    assert not fold.add(_row(status="failed"))
    assert fold.overall.n == 0
    assert fold.add(_row())
    assert fold.overall.n == 1


def test_ledger_crash_durability_torn_tail_and_merge(tmp_path):
    run_dir = str(tmp_path)
    a = CalibrationLedger(run_dir)
    b = CalibrationLedger(run_dir)  # a peer replica, same shared file
    a.record(
        fingerprint="fp1", kind="pca", job_class="small",
        predicted_seconds=2.0, measured_seconds=1.0,
        queue_wait_seconds=0.1, compile="warm", job_id="job-a-1",
    )
    b.record(
        fingerprint="fp1", kind="pca", job_class="small",
        predicted_seconds=2.0, measured_seconds=1.0,
        queue_wait_seconds=None, compile="cold", job_id="job-b-1",
        status="failed",
    )
    # a's in-process fold has not seen b's append; refresh merges it —
    # but the failed row stays out of the ratio fold by contract.
    assert a.fold.overall.n == 1
    assert a.refresh().overall.n == 1
    # Simulate the kill -9 torn tail: a half-written trailing line.
    with open(calibration_path(run_dir), "a", encoding="utf-8") as f:
        f.write('{"fingerprint": "fp1", "predicted_sec')
    fold = fold_calibration(calibration_path(run_dir))
    assert fold.overall.n == 1
    assert fold.overall.ratio == pytest.approx(0.5)
    # The raw file still holds both rows (the report reads them all).
    rows = [
        json.loads(line)
        for line in open(calibration_path(run_dir), encoding="utf-8")
        if line.strip().endswith("}")
    ]
    assert {r["id"] for r in rows} == {"job-a-1", "job-b-1"}
    failed = next(r for r in rows if r["id"] == "job-b-1")
    assert failed["status"] == "failed"
    assert "queue_wait_seconds" not in failed  # None omits the key
    a.close()
    b.close()
    a.record(  # record() reopens after close — telemetry never dies
        fingerprint="fp1", kind="pca", job_class="small",
        predicted_seconds=2.0, measured_seconds=1.0,
        queue_wait_seconds=0.0, compile="warm",
    )
    a.close()


def test_reservoir_is_deterministic_and_bounded():
    r1 = _Reservoir(capacity=8)
    r2 = _Reservoir(capacity=8)
    for i in range(1000):
        r1.add(float(i))
        r2.add(float(i))
    assert r1.samples == r2.samples  # no randomness, ever
    assert len(r1.samples) <= 8
    assert r1.stride > 1  # it actually thinned
    assert r1.quantile(0.0) == min(r1.samples)
    assert r1.quantile(1.0) == max(r1.samples)
    assert _Reservoir().quantile(0.5) is None


# ------------------------------------------------------ heartbeat segment


def test_heartbeat_cost_segment():
    reg = MetricsRegistry()
    reg.gauge(COST_PREDICTED_MEAN_SECONDS).set(3.2)
    reg.gauge(COST_MEASURED_MEAN_SECONDS).set(2.9)
    reg.gauge(COST_CALIBRATION_SAMPLES).set(17)
    hb = Heartbeat(10.0, reg, emit=lambda line: None)
    assert "cost pred 3.2s / meas 2.9s (ratio 0.91, n=17)" in hb.line()


def test_heartbeat_cost_segment_silent_without_samples():
    reg = MetricsRegistry()
    reg.gauge(COST_PREDICTED_MEAN_SECONDS).set(3.2)
    reg.gauge(COST_MEASURED_MEAN_SECONDS).set(2.9)
    reg.gauge(COST_CALIBRATION_SAMPLES).set(0)
    hb = Heartbeat(10.0, reg, emit=lambda line: None)
    assert "cost pred" not in hb.line()
    # And a registry without the gauges at all stays silent too.
    assert "cost pred" not in Heartbeat(
        10.0, MetricsRegistry(), emit=lambda line: None
    ).line()


# --------------------------------------------------- manifest cost block


def _valid_cost_block():
    return {
        "predicted_seconds": 1.5,
        "measured_seconds": 1.2,
        "queue_wait_seconds": 0.01,
        "compile": "warm",
        "fingerprint": "abc",  # extras are allowed (additive envelope)
    }


def test_manifest_cost_block_valid_and_absent():
    from spark_examples_tpu.obs.manifest import (
        build_manifest,
        validate_manifest,
    )

    assert validate_manifest(build_manifest()) == []  # absent = fine (v2)
    doc = build_manifest(cost=_valid_cost_block())
    assert validate_manifest(doc) == []
    assert doc["cost"]["compile"] == "warm"


@pytest.mark.parametrize(
    "tamper",
    [
        lambda c: c.update(predicted_seconds=-1.0),
        lambda c: c.update(measured_seconds=float("nan")),
        lambda c: c.update(queue_wait_seconds=True),
        lambda c: c.update(queue_wait_seconds="0.1"),
        lambda c: c.pop("measured_seconds"),
        lambda c: c.update(compile="lukewarm"),
    ],
)
def test_manifest_cost_block_tampering_rejected(tamper):
    from spark_examples_tpu.obs.manifest import (
        build_manifest,
        validate_manifest,
    )

    cost = _valid_cost_block()
    tamper(cost)
    errors = validate_manifest(build_manifest(cost=cost))
    assert errors, cost
    assert any("cost" in e for e in errors), errors


# ----------------------------------------- journal round-trip + compaction


def test_journal_cost_survives_replay_and_compaction(tmp_path):
    from spark_examples_tpu.serve.journal import (
        JobJournal,
        compact_journal,
        journal_path,
        replay_journal,
    )

    path = journal_path(str(tmp_path))
    journal = JobJournal(path)
    cost = CostPrediction(
        predicted_seconds=2.5, fingerprint="fp9", compile="cold"
    ).to_dict()
    journal.accepted(
        "job-000001", request_doc(TINY_FLAGS), "small",
        submitted_unix=123.0, deadline_unix=None,
        trace_id="a" * 32, cost=cost,
    )
    journal.accepted(  # a pre-observatory record: no cost key at all
        "job-000002", request_doc(TINY_FLAGS), "small",
        submitted_unix=124.0, deadline_unix=None,
    )
    pending, _ = replay_journal(path)
    assert [p.job_id for p in pending] == ["job-000001", "job-000002"]
    assert pending[0].cost == cost
    assert pending[1].cost is None
    # Compaction rewrites accepted records verbatim: the prediction (and
    # trace id) survive the rewrite, exactly like before it.
    compact_journal(path, pending)
    pending2, _ = replay_journal(path)
    assert pending2[0].cost == cost
    assert pending2[0].trace_id == "a" * 32
    assert pending2[1].cost is None
    assert CostPrediction.from_dict(pending2[0].cost).predicted_seconds \
        == 2.5


# ------------------------------------- daemon: 413, fleet stats, report


class InstantExecutor:
    def __call__(self, job, run_dir):
        return ExecutionOutcome(
            result={"ok": True}, manifest_path=None, compile_cache="cold"
        )


def _wait_done(service, job_id, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        status, doc = service.job_status(job_id)
        assert status == 200, doc
        if doc["job"]["status"] in ("done", "failed", "cancelled"):
            return doc["job"]
        time.sleep(0.01)
    raise AssertionError(f"{job_id} never settled")


def test_deadline_infeasible_413_and_opt_out(tmp_path):
    from spark_examples_tpu.serve.daemon import PcaService

    service = PcaService(
        run_dir=str(tmp_path / "a"), executor=InstantExecutor()
    ).start()
    try:
        # Below MIN_PREDICTED_SECONDS: infeasible for ANY job, so the
        # 413 is deterministic with an empty calibration ledger.
        status, body = service.submit(
            request_doc(TINY_FLAGS, deadline_seconds=0.001)
        )
        assert status == 413
        assert body["error"]["code"] == "deadline-infeasible"
        assert body["cost"]["requested_deadline_seconds"] == 0.001
        assert body["cost"]["predicted_seconds"] >= MIN_PREDICTED_SECONDS
        message = body["error"]["message"]
        assert "0.001" in message and "--no-deadline-feasibility" in message
        # A feasible deadline on the same geometry is admitted.
        status, doc = service.submit(
            request_doc(TINY_FLAGS, deadline_seconds=3600.0)
        )
        assert status == 202, doc
        assert doc["job"]["cost"]["predicted_seconds"] > 0
    finally:
        service.stop(timeout=30)
    opt_out = PcaService(
        run_dir=str(tmp_path / "b"),
        executor=InstantExecutor(),
        deadline_feasibility=False,
    ).start()
    try:
        status, doc = opt_out.submit(
            request_doc(TINY_FLAGS, deadline_seconds=0.001)
        )
        assert status == 202  # the pre-observatory accept-then-expire
    finally:
        opt_out.stop(timeout=30)


def test_fleet_stats_metrics_and_postmortem_report(tmp_path, capsys):
    from spark_examples_tpu.obs.report import report_main
    from spark_examples_tpu.serve.daemon import PcaService

    run_dir = str(tmp_path / "serve")
    service = PcaService(run_dir=run_dir, executor=InstantExecutor()).start()
    try:
        status, doc = service.submit(request_doc(TINY_FLAGS))
        assert status == 202
        job = _wait_done(service, doc["job"]["id"])
        assert job["status"] == "done"
        # The terminal envelope carries the measured half of the pair.
        assert job["cost"]["measured_seconds"] is not None
        assert job["cost"]["queue_wait_seconds"] is not None
        stats = service.fleet_stats()
        wall = stats["classes"]["small"]["wall_seconds"]
        assert wall["count"] == 1 and wall["p50"] > 0
        assert stats["classes"]["small"]["queue_wait_seconds"]["count"] == 1
        assert stats["calibration"]["samples"] == 1
        assert stats["calibration"]["ratio"] > 0
        assert set(stats["counters"]) >= {
            "jobs_stolen", "worker_restarts", "journal_replayed",
        }
        text = service.metrics_text()
        for name in (
            "serve_queue_wait_seconds", "serve_job_wall_seconds",
            "cost_prediction_ratio", "cost_calibration_samples",
            "cost_predicted_mean_seconds", "cost_measured_mean_seconds",
        ):
            assert name in text, name
    finally:
        service.begin_drain()
        service.wait_drained(timeout=30)
        service.stop(timeout=30)
    # The fleet is dead; the report folds what it left on disk.
    assert report_main(["report", "--run-dir", run_dir, "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    (job_id,) = report["jobs"].keys()
    facts = report["jobs"][job_id]
    assert facts["status"] == "done"
    assert facts["trace"]
    assert facts["predicted_seconds"] > 0
    assert facts["measured_seconds"] is not None
    assert facts["queue_wait_seconds"] is not None
    assert report["calibration"]["samples"] == 1
    assert report["classes"]["small"]["wall_seconds"]["count"] == 1
    assert report["totals"]["journaled"] == 1
    # The protocol block is the SAME protocol_summary fold `graftcheck
    # proto` proves GP001-GP006 over, run on this fleet's real journal.
    protocol = report["protocol"]
    assert protocol["jobs"][job_id]["settled"] is True
    assert protocol["jobs"][job_id]["began"] is True
    terminals = protocol["jobs"][job_id]["terminals"]
    assert any(
        t["status"] == "done" and t["effective"] for t in terminals
    )
    assert protocol["totals"]["accepted"] == 1
    assert protocol["totals"]["effective_terminals"] >= 1
    assert protocol["totals"]["fenced_terminals"] == 0
    # Text mode renders the same facts.
    assert report_main(["report", "--run-dir", run_dir]) == 0
    text = capsys.readouterr().out
    assert "fleet report:" in text and job_id in text
    assert "predicted" in text and "queue wait" in text
    assert "protocol: accepted 1, settled 1" in text


def test_report_cli_exit_codes(tmp_path, capsys):
    from spark_examples_tpu.obs.report import report_main

    assert report_main([]) == 2  # usage
    assert report_main(["export"]) == 2  # wrong verb
    missing = str(tmp_path / "nope")
    assert report_main(["report", "--run-dir", missing]) == 2
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert report_main(["report", "--run-dir", empty]) == 1  # nothing
    capsys.readouterr()

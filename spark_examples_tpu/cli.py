"""Command-line entry points.

One subcommand per reference main class (SURVEY.md §2.1's L5 applications),
with the reference flag grammar (``GenomicsConf.scala:29-98``):

    python -m spark_examples_tpu variants-pca --references 17:41196311:41277499
    python -m spark_examples_tpu search-variants-klotho
    python -m spark_examples_tpu search-variants-brca1
    python -m spark_examples_tpu search-reads-example-1 .. -4
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

from spark_examples_tpu.analyses import reads_examples, variants_examples
from spark_examples_tpu.config import GenomicsConf
from spark_examples_tpu.pipeline import pca_driver


def _source(conf: GenomicsConf):
    return pca_driver.make_source(conf)  # type: ignore[arg-type]


COMMANDS = {
    "variants-pca": lambda argv: pca_driver.run(argv),
    "search-variants-klotho": lambda argv: variants_examples.run_klotho(
        *(lambda c: (c, _source(c)))(GenomicsConf.parse(argv))
    ),
    "search-variants-brca1": lambda argv: variants_examples.run_brca1(
        *(lambda c: (c, _source(c)))(GenomicsConf.parse(argv))
    ),
    "search-reads-example-1": lambda argv: reads_examples.run_example1(
        *(lambda c: (c, _source(c)))(GenomicsConf.parse(argv))
    ),
    "search-reads-example-2": lambda argv: reads_examples.run_example2(
        *(lambda c: (c, _source(c)))(GenomicsConf.parse(argv))
    ),
    "search-reads-example-3": lambda argv: reads_examples.run_example3(
        *(lambda c: (c, _source(c)))(GenomicsConf.parse(argv))
    ),
    "search-reads-example-4": lambda argv: reads_examples.run_example4(
        *(lambda c: (c, _source(c)))(GenomicsConf.parse(argv))
    ),
}


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m spark_examples_tpu <command> [flags]")
        print("commands:")
        for name in COMMANDS:
            print(f"  {name}")
        return 0
    command, rest = argv[0], argv[1:]
    if command not in COMMANDS:
        print(f"unknown command: {command}", file=sys.stderr)
        return 2
    # After the help/unknown early-outs: only real commands pay (and benefit
    # from) the process-global platform/cache configuration.
    from spark_examples_tpu.parallel.mesh import apply_platform_override
    from spark_examples_tpu.utils.cache import enable_persistent_compile_cache

    apply_platform_override()
    enable_persistent_compile_cache()
    COMMANDS[command](rest)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

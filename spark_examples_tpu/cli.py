"""Command-line entry points.

One subcommand per reference main class (SURVEY.md §2.1's L5 applications),
with the reference flag grammar (``GenomicsConf.scala:29-98``):

    python -m spark_examples_tpu variants-pca --references 17:41196311:41277499
    python -m spark_examples_tpu search-variants-klotho
    python -m spark_examples_tpu search-variants-brca1
    python -m spark_examples_tpu search-reads-example-1 .. -4

File-backed runs (``--source file``) parse VCF inputs through the
chunk-parallel native ingest engine; ``--ingest-workers N`` sizes its thread
pool (default min(8, cpu_count); ``0`` = the serial oracle path, identical
output):

    python -m spark_examples_tpu variants-pca --source file \\
        --input-files cohort.vcf.gz --ingest-workers 8

Population-genetics analyses (``analyses/``; README "Analyses"): three
per-site workloads on the same substrate — ``grm`` (allele-frequency-
standardized VanRaden kinship, a reweighting of the PCA Gramian), and two
M-sized-output analyses whose statistics spill window by window:

    python -m spark_examples_tpu grm --num-samples 64 \\
        --references 1:0:400000 --grm-out kinship.tsv
    python -m spark_examples_tpu ld-prune --ld-r2-threshold 0.2 \\
        --ld-window-sites 256 --ld-out kept.tsv
    python -m spark_examples_tpu assoc-scan --phenotypes pheno.tsv \\
        --assoc-out scan.tsv

Static analysis (``check/``; README "graftcheck"): ``graftcheck lint``
(AST JAX-pitfall linter), ``graftcheck ir`` (jaxpr-level audit of the real
Gramian kernels: ring overlap, donation contract, packed-wire dtype flow,
traffic/liveness facts), ``graftcheck ranges`` (abstract-interpretation
overflow & exactness prover over the same traced kernels: bf16/f32
partials < 2^24, int32 accumulation < 2^31, lossy casts, declared input
contracts from ``ops/contracts.py``, conversion-trigger conservativeness),
``graftcheck sched`` (device-free collective-schedule prover: the
communication schedule extracted from the traced kernel jaxprs, simulated
per link class over a declared ``--topology hosts,devices_per_host`` —
flat-ring vs hierarchical two-level ring traffic, overlap, liveness,
critical-path budgets, for a pod that need not exist),
``graftcheck lockgraph`` (static lock-acquisition-order graph of the
threaded ingest layer, DOT artifact), ``graftcheck hostmem`` (host-memory
bound audit of the staging layers: a closed totality proof — every byte
streams through ``sources/stream.py`` and the retired
``hostmem(unbounded)`` hatch syntax is itself a finding), ``graftcheck
plan`` (device-free
flag/geometry/kernel-shape validation; ``--host-mem-budget`` enforces the
static host-RAM bound, exactness-window facts/rejections come from the
ranges prover, and ``--topology``/``--sched-budget-seconds`` add the
schedule proof), ``graftcheck sanitize`` / ``graftcheck typecheck``:

    python -m spark_examples_tpu graftcheck ir --json
    python -m spark_examples_tpu graftcheck ranges --json
    python -m spark_examples_tpu graftcheck sched --topology 32,8
    python -m spark_examples_tpu graftcheck hostmem --json
    python -m spark_examples_tpu graftcheck lockgraph --dot lockorder.dot

Serving (``serve/``; README "Serving"): ``serve`` starts the resident
daemon — executor slices (small jobs concurrent beside a large one),
continuous batching, compile-once with restart-persistent warm state,
journaled job table, admission-controlled — and ``submit`` sends it
jobs expressed as the same PCA flag namespace (everything after ``--``
is forwarded verbatim; ``--wait`` polls with server-paced Retry-After +
full-jitter backoff); plan-invalid requests come back as structured 4xx
bodies carrying the ``graftcheck plan`` facts:

    python -m spark_examples_tpu serve --port 8765 --run-dir /tmp/serve
    python -m spark_examples_tpu submit --url http://127.0.0.1:8765 \\
        -- --num-samples 64 --references 17:41196311:41277499

Observability (``obs/``; README "Observability"): ``--heartbeat-seconds N``
emits a stderr progress line every N seconds (sites/sec, partition ETA,
prefetch queue, dispatch depth, device memory); ``--metrics-json PATH``
writes the schema-versioned run manifest (config echo, stage spans, all
metrics, I/O stats, overlap accounting, prover-conformance pairs) that
``bench.py`` and CI consume; ``--profile-dir`` adds the ``jax.profiler``
device trace:

    python -m spark_examples_tpu variants-pca --all-references \\
        --heartbeat-seconds 30 --metrics-json run.json

Distributed tracing (``obs/trace.py``/``obs/recorder.py``; README
"Tracing"): every served job carries a trace id from client submit
through journal records and replica steals, every replica daemon keeps a
crash-durable flight recorder under ``<run-dir>/trace/``, and ``trace
export`` merges journal + recorder segments into one Chrome-trace JSON
(replicas as processes, executor slices as threads, steals as flow
arrows — load it in chrome://tracing or https://ui.perfetto.dev):

    python -m spark_examples_tpu trace export --run-dir /tmp/serve \\
        --out fleet.trace.json

Cost observatory (``obs/report.py``; README "Fleet stats & cost
calibration"): every admitted job carries a predicted cost, every
finished job appends a measured one to the crash-durable calibration
ledger, and ``obs report`` folds journal + ledger + recorder segments
into a post-mortem fleet report (per-job predicted vs measured under
one trace id, per-class latency quantiles, calibration ratios) —
purely from run-dir artifacts, so it works on a dead fleet:

    python -m spark_examples_tpu obs report --run-dir /tmp/serve --json
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

from spark_examples_tpu.analyses import reads_examples, variants_examples
from spark_examples_tpu.config import GenomicsConf
from spark_examples_tpu.pipeline import pca_driver


def _source(conf: GenomicsConf):
    return pca_driver.make_source(conf)  # type: ignore[arg-type]


def _readset_kwargs(conf: GenomicsConf, names: Sequence[str]) -> dict:
    """For ``--source file``, route the file-derived set ids into the reads
    examples' readset parameters (``names``, in ``--input-files`` order) —
    the hardcoded Google public readset ids only exist on the sunset API."""
    if conf.source != "file":
        return {}
    from spark_examples_tpu.sources.files import file_set_ids

    ids = file_set_ids(conf.input_files or [])
    if len(ids) < len(names):
        raise ValueError(
            f"this analysis needs {len(names)} --input-files "
            f"({', '.join(names)} in order); got {len(ids)}"
        )
    return dict(zip(names, ids))


def _variants_cmd(run_fn):
    def invoke(argv):
        conf = GenomicsConf.parse(argv)
        return run_fn(conf, _source(conf))

    return invoke


def _reads_cmd(run_fn, readset_params: Sequence[str]):
    def invoke(argv):
        conf = GenomicsConf.parse(argv)
        return run_fn(conf, _source(conf), **_readset_kwargs(conf, readset_params))

    return invoke


def _graftcheck(argv):
    # Static analysis must not pay (or trigger) backend/platform/cache
    # configuration — dispatched before the real-command setup in main().
    from spark_examples_tpu.check.cli import main as graftcheck_main

    return graftcheck_main(argv)


def _serve(argv):
    # The resident daemon (serve/http.py): platform/cache setup happens in
    # main() like any real command, then the service owns the process.
    from spark_examples_tpu.serve.http import serve_main

    return serve_main(argv)


def _grm(argv):
    # Population-genetics analyses (analyses/; README "Analyses"):
    # imported lazily so `--help` and graftcheck stay import-light.
    from spark_examples_tpu.analyses import grm

    return grm.run(argv)


def _ld_prune(argv):
    from spark_examples_tpu.analyses import ld

    return ld.run(argv)


def _assoc_scan(argv):
    from spark_examples_tpu.analyses import assoc

    return assoc.run(argv)


def _submit(argv):
    # Pure HTTP client: submitting to a remote daemon must not initialize
    # a local jax backend — dispatched before the real-command setup.
    from spark_examples_tpu.serve.client import submit_main

    return submit_main(argv)


def _trace(argv):
    # Post-mortem tooling (obs/trace.py): merges a serve fleet's journal
    # + flight-recorder segments into one Chrome-trace JSON. Pure file
    # I/O — dispatched before the platform/cache setup like graftcheck.
    from spark_examples_tpu.obs.trace import export_main

    return export_main(argv)


def _obs(argv):
    # Post-mortem cost observatory (obs/report.py): folds a fleet's
    # journal + calibration ledger + recorder segments into one report.
    # Pure file I/O — dispatched before the platform/cache setup.
    from spark_examples_tpu.obs.report import report_main

    return report_main(argv)


COMMANDS = {
    "variants-pca": lambda argv: pca_driver.run(argv),
    "grm": _grm,
    "ld-prune": _ld_prune,
    "assoc-scan": _assoc_scan,
    "graftcheck": _graftcheck,
    "serve": _serve,
    "submit": _submit,
    "trace": _trace,
    "obs": _obs,
    "search-variants-klotho": _variants_cmd(variants_examples.run_klotho),
    "search-variants-brca1": _variants_cmd(variants_examples.run_brca1),
    "search-reads-example-1": _reads_cmd(reads_examples.run_example1, ["readset"]),
    "search-reads-example-2": _reads_cmd(reads_examples.run_example2, ["readset"]),
    "search-reads-example-3": _reads_cmd(reads_examples.run_example3, ["readset"]),
    "search-reads-example-4": _reads_cmd(
        reads_examples.run_example4, ["normal_readset", "tumor_readset"]
    ),
}


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m spark_examples_tpu <command> [flags]")
        print("commands:")
        for name in COMMANDS:
            print(f"  {name}")
        return 0
    command, rest = argv[0], argv[1:]
    if command not in COMMANDS:
        print(f"unknown command: {command}", file=sys.stderr)
        return 2
    if command in ("graftcheck", "submit", "trace", "obs"):
        # Analysis-only / client-only: no platform override, no compile
        # cache — graftcheck must run identically on devices-free CI
        # boxes, `submit` talks to a (possibly remote) daemon without
        # initializing a local backend, and `trace export` / `obs
        # report` are pure file I/O over a run dir. Exit codes
        # propagate.
        return int(COMMANDS[command](rest))
    # After the help/unknown early-outs: only real commands pay (and benefit
    # from) the process-global platform/cache configuration.
    from spark_examples_tpu.parallel.mesh import apply_platform_override
    from spark_examples_tpu.utils.cache import enable_persistent_compile_cache

    apply_platform_override()
    enable_persistent_compile_cache()
    if command == "serve":
        # The daemon's exit code IS the drain verdict (ci.sh gates on it).
        return int(COMMANDS[command](rest))
    COMMANDS[command](rest)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Gower double-centering of the similarity matrix.

The reference centers row-by-row against broadcast row sums
(``VariantsPca.scala:246-263``): entry (i, j) becomes
``v − rowMean(i) − colMean(j) + matrixMean`` with means taken over the full
row count N. On device this is three reductions and one fused elementwise
pass; the driver-side ``collect`` of row sums and the broadcast disappear.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_examples_tpu.utils.compat import shard_map

from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS


def _dtypes(in_dtype):
    """(compute, output) dtypes for centering.

    Every similarity matrix is integer-valued by construction (0/1 operand
    counts), and the reference centers in Double unconditionally
    (``VariantsPca.scala:246-263``) — so when x64 is live, centering
    arithmetic runs in float64 regardless of the carrier dtype (int32 exact
    Gramians and f32 Gramians holding the same exact integers center
    bit-identically; whole-genome counts exceed f32's 2^24 exact range).
    The upcast happens inside the fused reduction/elementwise kernels, so no
    f64 N×N is ever materialized; the OUTPUT stays in the eigensolve's
    dtype (f32, or f64 for callers that passed f64 in)."""
    wide = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    out = jnp.float64 if in_dtype == jnp.float64 else jnp.float32
    return wide, out


@jax.jit
def gower_center(S: jax.Array) -> jax.Array:
    """B = S − rowMean − colMean + matrixMean (``VariantsPca.scala:252-263``)."""
    wide, out = _dtypes(S.dtype)
    Sw = S.astype(wide)
    row_mean = jnp.mean(Sw, axis=1, keepdims=True)
    col_mean = jnp.mean(Sw, axis=0, keepdims=True)
    total_mean = jnp.mean(Sw)
    return (Sw - row_mean - col_mean + total_mean).astype(out)


def gower_center_sharded(
    S: jax.Array, mesh: Mesh, n_true: int | None = None
) -> jax.Array:
    """Centering for a row-sharded Gramian (``samples`` axis): row means are
    local, column/matrix means are one ``psum`` over the row tiles.

    ``n_true`` handles cohort padding (``ShardedGramianAccumulator`` pads N
    to a multiple of the samples axis with all-zero rows/columns): means are
    taken over the true cohort size and padded rows/columns are re-zeroed
    after centering, so the padded result is exactly the dense result
    embedded in a zero block — eigenvectors and eigenvalues are unchanged.

    Centering arithmetic runs in float64 when x64 is live (see
    :func:`_dtypes`); the row-tile output is f32 either way — the downstream
    sharded eigensolve's dtype.
    """
    n_padded = S.shape[0]
    n = n_padded if n_true is None else int(n_true)
    wide, _ = _dtypes(S.dtype)

    def per_tile(S_local):
        S_local = S_local.astype(wide)
        n_local = S_local.shape[0]
        row_start = jax.lax.axis_index(SAMPLES_AXIS) * n_local
        # Padded entries of S are zero by construction, so sums over the
        # padded extent equal sums over the true extent; only the divisor
        # and the output mask need the true size.
        row_mean = jnp.sum(S_local, axis=1, keepdims=True) / n
        col_sum = jax.lax.psum(jnp.sum(S_local, axis=0, keepdims=True), SAMPLES_AXIS)
        col_mean = col_sum / n
        total_mean = jnp.sum(col_sum) / (n * n)
        out = S_local - row_mean - col_mean + total_mean
        row_mask = (row_start + jnp.arange(n_local)) < n
        col_mask = jnp.arange(S_local.shape[1]) < n
        # range: centered values are real-valued (means subtracted) — the
        # downstream subspace eigensolve runs in f32 by design; integer
        # exactness intentionally ends at the centering boundary (the
        # accumulator ladder, ops/contracts.py, stops at the raw Gramian).
        return jnp.where(
            row_mask[:, None] & col_mask[None, :], out, 0.0
        ).astype(jnp.float32)

    fn = shard_map(
        per_tile,
        mesh=mesh,
        in_specs=P(SAMPLES_AXIS, None),
        out_specs=P(SAMPLES_AXIS, None),
    )
    return jax.jit(
        fn, out_shardings=NamedSharding(mesh, P(SAMPLES_AXIS, None))
    )(S)


__all__ = ["gower_center", "gower_center_sharded"]

"""Gower double-centering of the similarity matrix.

The reference centers row-by-row against broadcast row sums
(``VariantsPca.scala:246-263``): entry (i, j) becomes
``v − rowMean(i) − colMean(j) + matrixMean`` with means taken over the full
row count N. On device this is three reductions and one fused elementwise
pass; the driver-side ``collect`` of row sums and the broadcast disappear.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS


@jax.jit
def gower_center(S: jax.Array) -> jax.Array:
    """B = S − rowMean − colMean + matrixMean (``VariantsPca.scala:252-263``)."""
    S = S.astype(jnp.float64) if S.dtype == jnp.float64 else S.astype(jnp.float32)
    row_mean = jnp.mean(S, axis=1, keepdims=True)
    col_mean = jnp.mean(S, axis=0, keepdims=True)
    total_mean = jnp.mean(S)
    return S - row_mean - col_mean + total_mean


def gower_center_sharded(S: jax.Array, mesh: Mesh) -> jax.Array:
    """Centering for a row-sharded Gramian (``samples`` axis): row means are
    local, column/matrix means are one ``psum`` over the row tiles."""

    def per_tile(S_local):
        n_total = S_local.shape[1]
        row_mean = jnp.mean(S_local, axis=1, keepdims=True)
        col_sum = jax.lax.psum(jnp.sum(S_local, axis=0, keepdims=True), SAMPLES_AXIS)
        col_mean = col_sum / n_total
        total_mean = jnp.sum(col_sum) / (n_total * n_total)
        return S_local - row_mean - col_mean + total_mean

    fn = shard_map(
        per_tile,
        mesh=mesh,
        in_specs=P(SAMPLES_AXIS, None),
        out_specs=P(SAMPLES_AXIS, None),
    )
    return jax.jit(
        fn, out_shardings=NamedSharding(mesh, P(SAMPLES_AXIS, None))
    )(S.astype(jnp.float32))


__all__ = ["gower_center", "gower_center_sharded"]

"""Stacked-jobs fused dispatch: one device program per batch group.

Continuous batching (``serve/queue.py:pop_batch``) coalesces small jobs
that share a region-invariant compile fingerprint. Until now a group only
shared warm jit caches — K jobs still paid K dispatch + reduction + host
round-trips each, with the MXU mostly idle between them. This module
stacks the group: the dense Gramian update ``G[d] += X[d]ᵀ X[d]``
(``ops/gramian.py:_dense_update``) is ALREADY batched over a leading
axis, so a K-job group runs with the jobs axis in that slot — a
``(K, N, N)`` accumulator fed ``(K, B, ceil(N/8))`` bit-packed operands,
ONE einsum dispatch per step for the whole group, and per-job results
sliced out on host. No new kernel exists to audit separately by
construction: the stacked program is the same shared constructor
``check/ir.py`` traces, instantiated with ``jobs`` in the leading slot
(``check/ir.py:stacked_kernel_spec`` / ``check/ranges.py:
stacked_range_spec`` audit it as a first-class subject).

Byte-identity argument (CI-asserted, never assumed):

- each lane reproduces ``GramianAccumulator``'s ``data=1`` host staging
  EXACTLY — same zero-padded tail, same ``np.packbits`` big-endian pack,
  same operand/accumulator dtypes — so step t of lane k carries the
  identical operand bytes the serial job's flush t would ship;
- the einsum contracts over ``(b, n/m)`` only: lead-axis slice k of the
  stacked update equals the serial ``data=1`` update on lane k's
  operands, entry for entry;
- a lane that runs out of blocks (ragged groups) receives all-zero
  packed operands: ``XᵀX`` of a zero block is exactly zero, and Gramian
  entries are non-negative counts accumulated from +0.0, so ``x + 0.0``
  is bitwise ``x`` — padding steps are byte-identity, no masking needed;
- the serial finalize for ``data=1`` (``data_axis_sum``) is a
  dtype-preserving sum over a singleton axis — numerically the slice
  itself — so ``stacked.G[k]`` IS the serial job's finalized Gramian.

The one semantic the stack cannot carry: a mid-stream dtype-ladder climb
(``_maybe_switch_accumulator``) is per-accumulator, and lanes at
different ladder positions cannot share one stacked buffer. Groups whose
projected per-entry count could cross the f32 exact window are
:class:`FusedIneligible` and fall back to serial execution — small jobs
(the only fusable class, ≤ ``SMALL_JOB_MAX_SITES`` sites) sit orders of
magnitude below the 2^24 trigger, so the gate is a guard rail, not a
path.

HBM: the stacked accumulator charges K× the dense per-job liveness
(:func:`max_fused_jobs`, the same ``_DENSE_BUFFERS``/
``DENSE_HBM_FRACTION`` rule the dense strategy and ``check/plan.py``
share), so group size is capped before devices are touched and
``graftcheck plan --fused-jobs K`` proves a group device-free.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from spark_examples_tpu.ops.contracts import (
    EXACT_F32_LIMIT,
    flush_entry_increment,
)
from spark_examples_tpu.ops.gramian import (
    _DEFAULT_DEVICE_BYTES,
    _DENSE_BUFFERS,
    DENSE_HBM_FRACTION,
    _dense_update,
    _operand_dtypes,
)


class FusedIneligible(RuntimeError):
    """This group (or one member) cannot ride the stacked program.

    A scheduling signal, not an error surface: the caller falls back to
    serial back-to-back execution, which is always semantically valid.
    """


def max_fused_jobs(
    num_samples: int,
    accum_bytes: int = 4,
    device_bytes: Optional[int] = None,
) -> int:
    """Largest jobs axis whose stacked liveness fits the dense HBM rule.

    The stacked program holds K× the dense strategy's per-job working
    set (``_DENSE_BUFFERS`` simultaneous N×N accumulator-dtype buffers),
    so K is bounded by the same ``DENSE_HBM_FRACTION`` budget the
    dense/sharded auto-switch and the plan validator's
    ``dense-exceeds-hbm`` rule use — ONE rule, three consumers, no
    drifted constants. ``device_bytes=None`` uses the device-free default
    (the validator must not query real devices); the daemon may pass a
    measured budget. Always at least 1: a single job is just the dense
    strategy, gated by its own rule."""
    budget = _DEFAULT_DEVICE_BYTES if device_bytes is None else device_bytes
    per_job = _DENSE_BUFFERS * int(num_samples) ** 2 * int(accum_bytes)
    return max(1, int((DENSE_HBM_FRACTION * budget) // per_job))


class StackedJobsAccumulator:
    """K independent dense Gramian lanes, one device program per step.

    Feed lane ``k`` host ``(b, N)`` uint8 has-variation rows with
    :meth:`add_rows`; each lane stages into its own ``(block_size, N)``
    buffer with EXACTLY ``GramianAccumulator``'s ``data=1`` flush
    semantics (zero-padded tail, ``np.packbits`` along the samples axis).
    Full lane blocks queue as pending operands; a stacked step dispatches
    as soon as every lane can contribute one (a finished lane contributes
    zeros), so host memory stays O(K × block) when lanes are fed in
    lockstep. :meth:`finalize` drains every lane and returns the
    ``(K, N, N)`` device accumulator; :meth:`job_slice` is one job's
    finalized Gramian, byte-identical to its serial run.
    """

    def __init__(
        self,
        num_jobs: int,
        num_samples: int,
        block_size: int = 1024,
        exact_int: bool = False,
        pipeline_depth: int = 2,
    ):
        import jax.numpy as jnp

        if num_jobs < 1:
            raise ValueError(f"num_jobs must be >= 1, got {num_jobs}")
        self.num_jobs = int(num_jobs)
        self.num_samples = int(num_samples)
        self.block_size = int(block_size)
        self.exact_int = bool(exact_int)
        # Same dtype resolution as the serial dense accumulator with no
        # mesh: the process default backend — the fused group runs on the
        # same slice its serial members would.
        self.operand_dtype, self.accum_dtype = _operand_dtypes(
            exact_int, None
        )
        k, b, n = self.num_jobs, self.block_size, self.num_samples
        self._staging = [np.zeros((b, n), dtype=np.uint8) for _ in range(k)]
        self._fill = [0] * k
        self._pending: List[List[np.ndarray]] = [[] for _ in range(k)]
        self._finished = [False] * k
        self._entry_bound = [0] * k
        self.rows_seen = [0] * k
        self.steps = 0
        # XᵀX of a zero block is exactly zero (the ragged-lane pad).
        self._zero_op = np.packbits(
            np.zeros((1, b, n), dtype=np.uint8), axis=-1
        )
        self.G = jnp.zeros((k, n, n), self.accum_dtype)
        # Same bounded async feed as the serial accumulator's
        # double-buffered path: block on the update issued
        # ``pipeline_depth`` steps ago, keep the newest in flight.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._in_flight: List[object] = []

    # -------------------------------------------------------------- feeding

    def add_rows(self, lane: int, rows: np.ndarray) -> None:
        """Stage host rows into one lane; pack full blocks and dispatch
        any stacked step the group can now afford."""
        if self._finished[lane]:
            raise RuntimeError(f"lane {lane} already finished")
        rows = np.asarray(rows, dtype=np.uint8)
        if rows.ndim != 2 or rows.shape[1] != self.num_samples:
            raise ValueError(
                f"expected (b, {self.num_samples}) rows, got {rows.shape}"
            )
        self.rows_seen[lane] += rows.shape[0]
        staging, offset = self._staging[lane], 0
        capacity = staging.shape[0]
        while offset < rows.shape[0]:
            take = min(capacity - self._fill[lane], rows.shape[0] - offset)
            staging[self._fill[lane] : self._fill[lane] + take] = rows[
                offset : offset + take
            ]
            self._fill[lane] += take
            offset += take
            if self._fill[lane] == capacity:
                self._pack_lane(lane)
        self._drain()

    def finish_lane(self, lane: int) -> None:
        """One lane's stream is complete: pack its zero-padded partial
        tail (the serial accumulator's finalize flush) and let shorter
        lanes ride zero operands from here on."""
        if self._finished[lane]:
            return
        if self._fill[lane]:
            self._pack_lane(lane)
        self._finished[lane] = True
        self._drain()

    def _pack_lane(self, lane: int) -> None:
        """EXACTLY ``GramianAccumulator._flush`` for ``data=1``: pad the
        tail with zero rows (they contribute nothing to XᵀX), prove the
        dtype-ladder position is stable, bit-pack along samples."""
        fill = self._fill[lane]
        block = self._staging[lane]
        if fill < block.shape[0]:
            block = block.copy()
            block[fill:] = 0
        max_count = int(block.max(initial=0))
        if max_count > 1:
            # Count-valued rows (same-set joins) ride the unpacked counts
            # kernel serially; the stacked path declares only the packed
            # {0,1} contract — refuse, don't approximate.
            raise FusedIneligible(
                f"lane {lane} staged count-valued rows (max {max_count}); "
                "stacked dispatch covers has-variation {0,1} rows only"
            )
        increment = flush_entry_increment(fill, max_count)
        next_bound = self._entry_bound[lane] + increment
        if not self.exact_int and next_bound > EXACT_F32_LIMIT:
            # The serial accumulator would climb the dtype ladder HERE —
            # a per-lane event the shared stacked buffer cannot carry.
            # The executor's static gate keeps fusable (small) jobs far
            # below this; reaching it means fall back to serial.
            raise FusedIneligible(
                f"lane {lane} projects {next_bound} per-entry counts, past "
                f"the f32 exact window ({EXACT_F32_LIMIT}); the serial "
                "path would switch accumulator dtype mid-stream"
            )
        self._entry_bound[lane] = next_bound
        shaped = block.reshape(1, self.block_size, self.num_samples)
        self._pending[lane].append(np.packbits(shaped, axis=-1))
        self._fill[lane] = 0

    # ----------------------------------------------------------- dispatching

    def _step_ready(self) -> bool:
        """A stacked step can dispatch iff every lane can contribute an
        operand — a pending packed block, or zeros once finished — and at
        least one lane contributes real work (all-zero steps are dropped,
        they exist only between real blocks of a ragged drain)."""
        any_real = False
        for lane in range(self.num_jobs):
            if self._pending[lane]:
                any_real = True
            elif not self._finished[lane]:
                return False
        return any_real

    def _drain(self) -> None:
        import jax
        import jax.numpy as jnp

        while self._step_ready():
            ops = [
                self._pending[lane].pop(0)
                if self._pending[lane]
                else self._zero_op
                for lane in range(self.num_jobs)
            ]
            X = np.concatenate(ops, axis=0)
            self.G = _dense_update(
                self.G, jnp.asarray(X), self.operand_dtype, self.num_samples
            )
            self.steps += 1
            self._in_flight.append(self.G)
            if len(self._in_flight) > self.pipeline_depth:
                jax.block_until_ready(self._in_flight.pop(0))  # graftcheck: disable=GC007 -- this IS the bounded in-flight window the rule recommends: waits only for the stacked step issued pipeline_depth iterations ago (same double-buffered feed as GramianAccumulator._flush), never the step just dispatched

    # -------------------------------------------------------------- results

    def finalize(self):
        """Drain every lane (callers must have :meth:`finish_lane`'d them
        all) and return the stacked ``(K, N, N)`` device accumulator."""
        for lane in range(self.num_jobs):
            if not self._finished[lane]:
                raise RuntimeError(
                    f"finalize before finish_lane({lane}) — lane streams "
                    "must be complete"
                )
        self._drain()
        self._in_flight.clear()
        return self.G

    def job_slice(self, lane: int):
        """Lane ``k``'s finalized Gramian, on device. The serial
        ``data=1`` finalize (``data_axis_sum``) is a dtype-preserving sum
        over a singleton leading axis — the slice itself — so this is
        byte-identical to the serial job's ``finalize_device()``."""
        return self.G[lane]


__all__ = [
    "FusedIneligible",
    "StackedJobsAccumulator",
    "max_fused_jobs",
]

"""Numeric range & exactness contracts of the Gramian dtype ladder.

The measured-perf story rests on an exactness chain that no single module
could previously see whole: genotype operands in {0,1,2} make bf16×bf16→f32
partials exact below 2^24 per entry, int8×int8→int32 accumulation is exact
below 2^31, and the accumulators' lossless f32→int32 conversion
(``ops/gramian.py:_maybe_switch_accumulator``) must fire before any entry
could leave the f32 exact-integer window (DESIGN.md §5, §8.7). This module
is the ONE home of the numbers that chain is built from:

- **input contracts** — the declared value ranges of every operand class
  the kernels consume (genotypes, has-variation bits, count-valued join
  rows, allele frequencies, packed wire bytes). The static prover
  (``check/ranges.py``) seeds its abstract interpretation from these, and
  ``graftcheck plan`` derives its geometry-level exactness facts from the
  same objects — declared once, consumed by both;
- **exact-integer windows per dtype** — the largest magnitude below which
  EVERY integer is exactly representable (2^24 for f32, 2^8 for bf16,
  2^53 for f64; an integer dtype's window is its own max). ``EXACT_F32_LIMIT``
  (the accumulator conversion threshold) is defined here and re-exported by
  ``ops/gramian.py``;
- **the flush-projection formula** — ``flush_entry_increment(rows,
  max_count)``, the conservative per-flush per-entry increment the runtime
  accumulators project before every dispatch. The SAME callable is what
  GR005 (``check/ranges.py``) holds the jaxpr-proven increment against, so
  the trigger the runtime uses and the bound the prover verifies can never
  drift.

Pure Python arithmetic over numpy dtypes — importable by the device-free
checkers without touching jax.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np


@dataclass(frozen=True)
class RangeContract:
    """One declared operand range: every value of this operand class lies
    in ``[lo, hi]`` (inclusive), and — for ``integral`` contracts — is an
    integer. The prover treats a contracted input as this interval and an
    uncontracted one as unbounded (which any dot consuming it turns into a
    GR004 finding)."""

    name: str
    lo: int
    hi: int
    description: str
    integral: bool = True


#: VCF/synthetic genotype allele-dosage values (0 = ref, 1 = het, 2 = hom
#: alt) — the widest per-site value the parse/devicegen layers stage.
GENOTYPE = RangeContract(
    "genotype", 0, 2, "diploid allele dosage (0/1/2) from parse/devicegen"
)

#: The Gramian's row operand on every default path: the per-(variant,
#: sample) has-variation membership bit (``VariantsPca.scala:65-69``).
HAS_VARIATION = RangeContract(
    "has_variation", 0, 1, "per-sample has-variation membership bit"
)

#: Count-valued rows (same-set joins): a callset column appearing k times
#: per variant contributes k — the reference pair-loop's multiplicity
#: (``VariantsPca.scala:224-229``). The declared production ceiling is a
#: set joined with itself at most this many times; the runtime projection
#: additionally measures the true per-flush max, so this constant only
#: bounds the STATIC geometry proofs, never correctness.
SAME_SET_JOIN_MAX_COUNT = 4
COUNT_ROW = RangeContract(
    "count_row",
    0,
    SAME_SET_JOIN_MAX_COUNT,
    "count-valued join row (duplicate-id multiplicity, declared ceiling)",
)

#: Allele frequencies, the one real-valued (non-integral) contract.
ALLELE_FREQUENCY = RangeContract(
    "allele_frequency", 0, 1, "per-site allele frequency", integral=False
)

#: A bit-packed ring/staging wire byte (8 genotype bits, np.packbits).
PACKED_BYTE = RangeContract(
    "packed_byte", 0, 255, "bit-packed wire byte (8 has-variation bits)"
)

CONTRACTS: Dict[str, RangeContract] = {
    c.name: c
    for c in (GENOTYPE, HAS_VARIATION, COUNT_ROW, ALLELE_FREQUENCY, PACKED_BYTE)
}


#: Mantissa-driven exact-integer windows of the float dtypes the ladder
#: uses: every integer of magnitude <= the window is exactly representable.
_FLOAT_WINDOWS = {
    "float64": 1 << 53,
    "float32": 1 << 24,
    "bfloat16": 1 << 8,
    "float16": 1 << 11,
}


def exact_int_window(dtype) -> Optional[int]:
    """Largest magnitude M such that every integer ``|n| <= M`` is exactly
    representable in ``dtype`` (an int dtype's own max; 2^mantissa for
    floats; ``None`` for dtypes with no integer-exactness story).

    Accepts any dtype spelling: a name string (``"bfloat16"``), a numpy
    dtype instance, a numpy scalar type (``np.int32``), or a jax dtype.
    """
    if isinstance(dtype, str):
        name = dtype
    else:
        try:
            name = np.dtype(dtype).name
        except TypeError:
            name = getattr(dtype, "name", None) or str(dtype)
    if name in _FLOAT_WINDOWS:
        return _FLOAT_WINDOWS[name]
    try:
        np_dtype = np.dtype(name)
    except TypeError:
        return None
    if np_dtype.kind in ("i", "u"):
        return int(np.iinfo(np_dtype).max)
    if np_dtype.kind == "b":
        return 1
    return None


#: Declared maximum production geometry: total candidate variant rows of
#: one run. The whole-genome synthetic grid carries ~39.5M candidate sites
#: (DESIGN.md §7); 40M is the declared ceiling the GR001 overflow proof
#: and the plan validator's ``gramian_entry_bound`` facts cover.
DECLARED_MAX_SITES = 40_000_000

#: Site-grid scalars: dispatch offsets, valid-site counts, and per-set row
#: counters are all bounded by the declared production geometry. This is
#: the contract of the fused device-generation kernel's scalar operands
#: (``ops/devicegen.py:_ring_update``) — without it the range prover would
#: treat a grid offset as unbounded and taint the whole generation chain
#: (every generated genotype is a function of the site position).
SITE_INDEX = RangeContract(
    "site_index",
    0,
    DECLARED_MAX_SITES,
    "site-grid offset / site count (declared geometry ceiling)",
)
CONTRACTS[SITE_INDEX.name] = SITE_INDEX

#: f32 accumulation is exact for integers up to 2^24; past a projected
#: per-entry count of this limit the accumulators losslessly convert to the
#: int8->int32 MXU path. Defined here (the dtype-window registry) and
#: re-exported by ``ops/gramian.py``, whose conversion trigger consumes it.
EXACT_F32_LIMIT = exact_int_window(np.float32) or (1 << 24)


def flush_entry_increment(rows: int, max_count: int) -> int:
    """Conservative per-entry Gramian increment of one flush of ``rows``
    variant rows whose entries are bounded by ``max_count``: every entry of
    ``XᵀX`` gains at most ``rows x max_count²``.

    THE runtime projection formula: both accumulators feed it to
    ``_maybe_switch_accumulator`` before every dispatch, and GR005
    (``check/ranges.py``) proves it conservative w.r.t. the per-dispatch
    increment read off the traced kernel jaxpr — one callable, two
    consumers, no drift.
    """
    return int(rows) * int(max_count) * int(max_count)


def exactness_headroom_sites(dtype, max_count: int = 1) -> int:
    """The largest variant-row count whose Gramian accumulation is provably
    exact on ``dtype``'s ladder rung: ``window(dtype) // max_count²``
    (0 when the dtype has no exact-integer window)."""
    window = exact_int_window(dtype)
    if window is None or max_count < 1:
        return 0
    return int(window) // (int(max_count) * int(max_count))


__all__ = [
    "ALLELE_FREQUENCY",
    "CONTRACTS",
    "COUNT_ROW",
    "DECLARED_MAX_SITES",
    "EXACT_F32_LIMIT",
    "GENOTYPE",
    "HAS_VARIATION",
    "PACKED_BYTE",
    "RangeContract",
    "SAME_SET_JOIN_MAX_COUNT",
    "SITE_INDEX",
    "exact_int_window",
    "exactness_headroom_sites",
    "flush_entry_increment",
]

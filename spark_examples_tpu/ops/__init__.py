from spark_examples_tpu.ops.centering import gower_center
from spark_examples_tpu.ops.gramian import GramianAccumulator, ShardedGramianAccumulator
from spark_examples_tpu.ops.pca import principal_components

__all__ = [
    "gower_center",
    "GramianAccumulator",
    "ShardedGramianAccumulator",
    "principal_components",
]

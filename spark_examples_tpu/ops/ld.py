"""Per-site (M-sized) device kernels: windowed LD statistics + association
carrier counts.

The PCA/GRM reduction layer only ever emits per-SAMPLE outputs (the N×N
Gramian). The population-genetics analyses (``analyses/``) add the other
output shape — per-SITE statistics — and this module is their device half.
Both kernels are stateless per dispatch (window in, small statistics out):
there is no device accumulator to donate, no dtype ladder to climb, and
the M-sized result never materializes on device — only the O(W²)/O(B)
window statistics do, which the host consumes immediately (the greedy
prune and the chi-square are inherently host-sequential/scalar work).

**Windowed LD** (:func:`build_ld_window_stats`): for a contig-ordered
window ``X ∈ {0,1}^(W×N)`` of has-variation rows, the pairwise r² between
sites i, j over binary genotypes needs only the co-carrier counts
``C = X Xᵀ`` and the per-site carrier counts ``k`` (for binary x,
``Σx² = Σx``):

    r²_ij = (n·C_ij − k_i·k_j)² / ((n·k_i − k_i²) · (n·k_j − k_j²))

``C`` is one W×W MXU matmul; under a mesh with a ``samples`` axis the
kernel runs blockwise under ``shard_map`` — each device computes the
partial ``C`` over its own sample columns and one ``psum`` over the
``samples`` axis completes it (the per-site analog of the Gramian's
finalize reduce; no ring is needed because the OUTPUT is per-site W×W,
not per-sample N×N). Everything is exact int32 integer arithmetic
(``W·max_count² ≤ N < 2^31``); the r² quotient itself is host float64
(:func:`r2_from_counts`), shared with the NumPy oracle so parity is
exact, not approximate.

**Association counts** (:func:`build_case_counts`): per site, the carrier
count among cases ``a = X @ case`` and the total carrier count
``t = X @ 1`` — the two device-side numbers the allelic 2×2 chi-square
needs; the scalar chi-square arithmetic stays on host in float64
(``analyses/assoc.py:chi2_from_counts``, also oracle-shared).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from spark_examples_tpu.ops.contracts import HAS_VARIATION  # noqa: F401  (the input contract both kernels assume)
from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS


def _window_counts_body(X_local, samples_axis: Optional[str]):
    """Per-device body: partial co-carrier counts over the local sample
    columns, completed by one psum when a samples axis exists."""
    import jax.numpy as jnp
    from jax import lax

    # range: HAS_VARIATION {0,1} membership bits; int8 holds them exactly
    # and the int8×int8→int32 dot is exact for W·N < 2^31 (ops/contracts.py).
    Xc = X_local.astype(jnp.int8)
    C = jnp.matmul(Xc, Xc.T, preferred_element_type=jnp.int32)
    # range: HAS_VARIATION bits sum to at most N < 2^31 per site.
    k = jnp.sum(X_local.astype(jnp.int32), axis=1)
    if samples_axis is not None:
        C = lax.psum(C, samples_axis)
        k = lax.psum(k, samples_axis)
    return C, k


def build_ld_window_stats(mesh=None):
    """The jitted window-statistics kernel for ``mesh`` (or single-device
    when ``None``/no samples axis): ``(W, N) uint8 → (C (W,W) int32,
    k (W,) int32)``. ONE construction site shared by the runtime
    (``analyses/ld.py``) and the device-free plan validator
    (``check/plan.py`` traces it over an ``AbstractMesh``), so the kernel
    the run executes and the kernel the validator proves are the same
    object. Build once per run — the returned callable is jit-cached."""
    import jax
    from jax.sharding import PartitionSpec as P

    from spark_examples_tpu.utils.compat import shard_map

    if mesh is None or mesh.shape.get(SAMPLES_AXIS, 1) < 2:

        @jax.jit
        def window_stats(X):
            return _window_counts_body(X, None)

        return window_stats

    # The data axis (when present) carries no per-site work here — one
    # window at a time — so the window replicates over it and only the
    # sample columns shard; the same mesh serves PCA and LD unchanged.
    x_spec = P(None, SAMPLES_AXIS)

    @jax.jit
    def window_stats(X):
        return shard_map(
            lambda x: _window_counts_body(x, SAMPLES_AXIS),
            mesh=mesh,
            in_specs=(x_spec,),
            out_specs=(P(None, None), P(None)),
        )(X)

    return window_stats


def ld_window_stats_reference(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host NumPy oracle of the window-statistics kernel."""
    X = np.asarray(rows, dtype=np.int64)
    return (X @ X.T).astype(np.int64), X.sum(axis=1).astype(np.int64)


def r2_from_counts(
    C: np.ndarray, k: np.ndarray, num_samples: int
) -> np.ndarray:
    """Pairwise r² from integer window statistics, float64, with the
    zero-variance guard: pairs involving a monomorphic site (variance
    numerator ``k·(n−k) == 0``) get r² = 0 — no correlation evidence,
    never NaN. The numerator/denominator are exact int64 products of the
    device-counted integers, so the oracle and the device path compute
    the IDENTICAL float64 quotient."""
    from spark_examples_tpu.utils.af import variance_counts

    n = int(num_samples)
    C = np.asarray(C, dtype=np.int64)
    k = np.asarray(k, dtype=np.int64)
    cov = n * C - k[:, None] * k[None, :]
    var = variance_counts(k, n)  # k·(n−k), exactly 0 for monomorphic
    denom = (var[:, None] * var[None, :]).astype(np.float64)
    num = cov.astype(np.float64) ** 2
    out = np.zeros_like(num)
    np.divide(num, denom, out=out, where=denom > 0)
    return out


def greedy_prune(
    C: np.ndarray,
    k: np.ndarray,
    num_samples: int,
    r2_threshold: float,
    valid: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Greedy windowed LD prune: walk sites in window (position) order,
    keep site i iff its r² against EVERY previously-kept site in the
    window is <= ``r2_threshold`` (prune strictly above, mirroring the
    ``--min-allele-frequency`` strictly-greater convention). Deterministic
    by construction — the walk order is the contig order. ``valid`` masks
    out tail-padding rows (never kept, never pruned against). Returns the
    kept bool mask over the window."""
    r2 = r2_from_counts(C, k, num_samples)
    W = r2.shape[0]
    kept = np.zeros(W, dtype=bool)
    kept_idx: list = []  # bounded by W, the window size — not O(M)
    for i in range(W):
        if valid is not None and not valid[i]:
            continue
        if kept_idx and float(r2[i, kept_idx].max()) > r2_threshold:
            continue
        kept[i] = True
        kept_idx.append(i)
    return kept


def build_case_counts():
    """The jitted per-site association-counts kernel: ``((B, N) uint8,
    (N,) uint8 case mask) → (a (B,) int32 carriers among cases,
    t (B,) int32 carriers total)``. Single construction site shared by
    the runtime and the plan validator's eval_shape check."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def case_counts(X, case):
        # range: HAS_VARIATION {0,1} bits and a {0,1} case mask — every
        # product and per-site sum is bounded by N < 2^31 (ops/contracts.py).
        Xi = X.astype(jnp.int32)
        a = Xi @ case.astype(jnp.int32)
        t = jnp.sum(Xi, axis=1)
        return a, t

    return case_counts


def case_counts_reference(
    rows: np.ndarray, case: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Host NumPy oracle of the association-counts kernel."""
    X = np.asarray(rows, dtype=np.int64)
    c = np.asarray(case, dtype=np.int64)
    return X @ c, X.sum(axis=1)


__all__ = [
    "build_case_counts",
    "build_ld_window_stats",
    "case_counts_reference",
    "greedy_prune",
    "ld_window_stats_reference",
    "r2_from_counts",
]

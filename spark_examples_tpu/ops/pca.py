"""Principal components of the centered similarity matrix.

The reference feeds centered rows into MLlib's
``RowMatrix.computePrincipalComponents`` (``VariantsPca.scala:264-266``),
which builds the column covariance and eigendecomposes it. For a Gower
double-centered matrix B (symmetric, zero row/column means) the covariance is
``BᵀB/(n−1) = B²/(n−1)``, whose eigenvectors are B's eigenvectors ordered by
eigenvalue *magnitude*. So the TPU-native equivalent is a single
``jnp.linalg.eigh`` on the HBM-resident B with |λ|-descending ordering —
no covariance materialization, no driver round-trip. A unit test pins this
equivalence against a literal NumPy replication of the MLlib semantics.

Eigenvector sign is arbitrary in both implementations; we fix a deterministic
convention (largest-magnitude component positive) so runs are reproducible.
"""

from __future__ import annotations

from typing import Tuple

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("num_pc",))
def principal_components(
    centered: jax.Array, num_pc: int = 2
) -> Tuple[jax.Array, jax.Array]:
    """Top-k principal components of a centered symmetric matrix.

    Returns ``(components, eigenvalues)`` where ``components`` is (N, k) —
    row i is sample i's coordinates, matching the reference's consumption of
    the MLlib result (``VariantsPca.scala:267-270``) — and ``eigenvalues``
    holds the corresponding eigenvalues of B (descending |λ|).
    """
    # range: centered input is real-valued; the eigensolve is defined in
    # f32 — integer exactness ends at the centering boundary by design.
    B = centered.astype(jnp.float32)
    # Symmetrize against accumulated roundoff; B is symmetric by construction.
    B = (B + B.T) * 0.5
    eigenvalues, eigenvectors = jnp.linalg.eigh(B)
    order = jnp.argsort(-jnp.abs(eigenvalues))[:num_pc]
    top = eigenvectors[:, order]
    # Deterministic sign: largest-|component| entry of each PC is positive.
    idx = jnp.argmax(jnp.abs(top), axis=0)
    signs = jnp.sign(top[idx, jnp.arange(num_pc)])
    signs = jnp.where(signs == 0, 1.0, signs)
    return top * signs, eigenvalues[order]


@functools.partial(
    jax.jit, static_argnames=("num_pc", "iterations", "oversample")
)
def principal_components_subspace(
    centered: jax.Array,
    num_pc: int = 2,
    iterations: int = 80,
    oversample: int = 8,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k principal components by subspace iteration + Rayleigh–Ritz.

    The TPU-first eigensolver for the driver path: ``num_pc`` is tiny (the
    reference defaults to 2, ``GenomicsConf.scala:76``), so the full O(N³)
    ``eigh`` is the wrong tool — XLA's TPU eigh at N=2,504 compiles for
    minutes, runs in tens of seconds, and degrades subsequent dispatch
    throughput ~20× on remote-attached backends (measured), while subspace
    iteration is a few hundred skinny (N×N)@(N×k) MXU matmuls: ~20 ms warm.
    Subspace iteration converges to the largest-|λ| eigenpairs — exactly the
    MLlib covariance ordering (see :func:`principal_components`). It also
    extends to a row-sharded B unchanged, where sharded eigh would not.

    Deterministic: fixed PRNG key, fixed iteration count, and the same sign
    convention as :func:`principal_components`.
    """
    # range: centered input is real-valued; the subspace iteration runs in
    # f32 by design — integer exactness ends at the centering boundary.
    B = centered.astype(jnp.float32)
    B = (B + B.T) * 0.5
    n = B.shape[0]
    k = min(num_pc + oversample, n)
    V = jax.random.normal(jax.random.PRNGKey(0), (n, k), dtype=B.dtype)
    V, _ = jnp.linalg.qr(V)

    def body(_, V):
        Q, _ = jnp.linalg.qr(B @ V)
        return Q

    V = jax.lax.fori_loop(0, iterations, body, V)
    return _rayleigh_ritz(V, B @ V, num_pc)


def _rayleigh_ritz(V, W, num_pc: int):
    """Rayleigh–Ritz extraction shared by the dense and sharded solvers:
    project (T = VᵀW where W = BV), eigh the small k×k, order by |λ|, and fix
    the deterministic sign convention (largest-|component| entry positive)."""
    T = V.T @ W
    evals, Wk = jnp.linalg.eigh((T + T.T) * 0.5)
    order = jnp.argsort(-jnp.abs(evals))[:num_pc]
    top = V @ Wk[:, order]
    idx = jnp.argmax(jnp.abs(top), axis=0)
    signs = jnp.sign(top[idx, jnp.arange(num_pc)])
    signs = jnp.where(signs == 0, 1.0, signs)
    return top * signs, evals[order]


def principal_components_subspace_sharded(
    centered: jax.Array,
    mesh,
    num_pc: int = 2,
    iterations: int = 80,
    oversample: int = 8,
    n_true: int | None = None,
) -> Tuple[jax.Array, jax.Array]:
    """Subspace iteration on a ROW-SHARDED centered matrix — the large-N
    completion of the sharded pipeline (``VariantsPca.scala:216-217``'s
    ~50K-samples regime): no device ever materializes the full N×N matrix.

    Per iteration the only sharded compute is ``B_local @ V`` (one skinny
    MXU matmul per row tile) followed by an ``all_gather`` of the (N, k)
    iterate — k is ``num_pc + oversample``, so the collective traffic is a
    few hundred KB regardless of N. QR/Rayleigh–Ritz run replicated on the
    gathered skinny matrix (identical on every device). Padded rows/columns
    (all-zero after :func:`gower_center_sharded` with ``n_true``) contribute
    nothing and the returned components simply carry zero rows for padding.
    """
    from spark_examples_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS

    n_padded = centered.shape[0]
    n = n_padded if n_true is None else int(n_true)
    k = min(num_pc + oversample, n)

    def per_tile(B_local):
        V = jax.random.normal(jax.random.PRNGKey(0), (n_padded, k), jnp.float32)

        def gathered_bv(V):
            # range: centered row tile is real-valued; the sharded
            # eigensolve runs in f32 by design (see the dense variants).
            W_local = B_local.astype(jnp.float32) @ V  # (n_local, k)
            return jax.lax.all_gather(
                W_local, SAMPLES_AXIS, axis=0, tiled=True
            )  # (n_padded, k), replicated

        def body(_, V):
            Q, _ = jnp.linalg.qr(gathered_bv(V))
            return Q

        V, _ = jnp.linalg.qr(V)
        V = jax.lax.fori_loop(0, iterations, body, V)
        return _rayleigh_ritz(V, gathered_bv(V), num_pc)

    # check_vma=False: the iterate alternates device-varying (B_local @ V)
    # and replicated (all_gather → identical QR on every device) forms, which
    # the static replication checker can't follow; the replicated out_specs
    # are correct because every device computes the same gathered iterate.
    fn = shard_map(
        per_tile,
        mesh=mesh,
        in_specs=P(SAMPLES_AXIS, None),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)(centered)


def mllib_reference_pca(centered, num_pc: int = 2):
    """NumPy oracle replicating MLlib ``computePrincipalComponents``
    literally: column covariance of the rows, then eigh, descending
    eigenvalues (used by tests to pin the equivalence argument above)."""
    import numpy as np

    M = np.asarray(centered, dtype=np.float64)
    n = M.shape[0]
    mean = M.mean(axis=0, keepdims=True)
    cov = (M - mean).T @ (M - mean) / (n - 1)
    eigenvalues, eigenvectors = np.linalg.eigh(cov)
    order = np.argsort(-eigenvalues)[:num_pc]
    return eigenvectors[:, order], eigenvalues[order]


__all__ = [
    "principal_components",
    "principal_components_subspace",
    "principal_components_subspace_sharded",
    "mllib_reference_pca",
]

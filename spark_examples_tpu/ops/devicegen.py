"""On-device synthetic genotype generation fused with Gramian accumulation.

The reference's runtime is dominated by ingest: executors stream variant
pages from the Genomics API and the similarity pass consumes them
(``VariantsRDD.scala:198-225`` feeding ``VariantsPca.scala:222-231``). The
synthetic source stands in for that ingest, and its data plane is a
counter-based hash (splitmix64 finalizer, ``sources/synthetic.py``) — which
is trivially jittable. This module moves the genotype data plane onto the
TPU:

- the host computes only per-*site* metadata (allele frequencies, ref-block
  flags, per-population comparison thresholds) — a few hundred bytes per
  variant, the moral equivalent of the reference's variant metadata;
- the device generates the (block, samples) genotype matrix with the exact
  same splitmix64 draws as the host source (bitwise-identical, tested) and
  feeds it straight into the MXU Gramian update, fused in one XLA program;
- many blocks are processed per dispatch via ``lax.scan``, so the
  host→device round-trip count stays in the hundreds for a whole-genome run.
  (On remote-attached backends, per-dispatch overhead is ~7 ms and the final
  result fetch pays O(prior dispatches) — measured; fusing is what makes the
  end-to-end number honest rather than a projection.)

Exactness of the comparison: the host draws ``u = (h >> 11) * 2**-53`` and
keeps an allele when ``u < p`` (``sources/synthetic.py:_u01``). Because
``m = h >> 11`` is a 53-bit integer, ``m * 2**-53 < p  ⟺  m < ceil(p * 2**53)``
(for real ``p``; when ``p * 2**53`` is an integer, strictness matches because
``m`` is an integer). ``p < 1`` has a 53-bit mantissa so ``p * 2**53`` is an
exact float64 and its ``ceil`` converts to uint64 exactly — the device never
touches float64, it compares 64-bit integers.
"""

from __future__ import annotations

import functools
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# splitmix64 constants — must match sources/synthetic.py exactly.
_P1 = 0x9E3779B97F4A7C15
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0xD6E8FEB86659FD93
_M1 = 0xBF58476D1CE4E5B9
_M2 = 0x94D049BB133111EB
_MASK64 = (1 << 64) - 1
_S_GENOTYPE = 100  # sources/synthetic.py draw-stream tag


def _c64(value: int) -> jax.Array:
    """uint64 constant, wrapped mod 2^64 (Python ints over 2^63 would
    overflow the default int path)."""
    return jnp.asarray(np.uint64(value & _MASK64))


def mix64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer on uint64 arrays — bitwise-identical to
    ``sources/synthetic.py:_mix`` (tested)."""
    x = (x + _c64(_P1)).astype(jnp.uint64)
    x = ((x ^ (x >> jnp.uint64(30))) * _c64(_M1)).astype(jnp.uint64)
    x = ((x ^ (x >> jnp.uint64(27))) * _c64(_M2)).astype(jnp.uint64)
    return (x ^ (x >> jnp.uint64(31))).astype(jnp.uint64)


def generate_has_variation(
    positions: jax.Array,  # (B,) int64
    thresholds: jax.Array,  # (B, P) uint64: ceil(af_pop * 2^53), 0 = dropped
    vs_keys: jax.Array,  # (S,) uint64: per-variant-set genotype stream keys
    pops: jax.Array,  # (N,) int32: sample → population
) -> jax.Array:
    """(B, S*N) {0,1} has-variation rows, bitwise-equal to the host packed
    path (``sources/synthetic.py:genotype_blocks``) for kept sites; rows whose
    thresholds are zeroed come out all-zero (contribute nothing to XᵀX).

    Multi-dataset: synthetic variant sets share the site grid (site identity
    is keyed by position only — ``sources/synthetic.py:_site_fields``), so the
    reference's 2-set join and ≥3-set merge-intersect (``VariantsPca.scala:
    155-188``) both reduce to column concatenation of per-set genotype
    matrices; ``vs_keys`` carries one genotype stream per set.
    """
    n = pops.shape[0]
    samples = (jnp.arange(n, dtype=jnp.uint64) * _c64(_P4))[None, :]
    pos_term = positions.astype(jnp.uint64) * _c64(_P2)
    t_full = jnp.take(thresholds, pops, axis=1)  # (B, N)
    parts = []
    for s in range(vs_keys.shape[0]):
        h1 = mix64(vs_keys[s] ^ pos_term)  # (B,)
        h2 = mix64(h1 ^ _c64(_S_GENOTYPE * _P3))
        h3 = mix64(h2[:, None] ^ samples)  # (B, N)
        m1 = mix64(h3 ^ _c64(1 * _P1)) >> jnp.uint64(11)
        m2 = mix64(h3 ^ _c64(2 * _P1)) >> jnp.uint64(11)
        parts.append((m1 < t_full) | (m2 < t_full))
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


class DeviceGenGramianAccumulator:
    """Fused generate→accumulate pipeline for the synthetic data plane.

    Carries the Gramian and a variant-row counter through chained scanned
    dispatches; nothing is fetched from the device until
    :meth:`finalize_device`'s result is consumed downstream. ``exact_int``
    accumulates int8×int8→int32 on the MXU (always exact; whole-genome
    diagonal counts ~12M would sit uncomfortably close to f32's 2^24 integer
    limit — SURVEY §7 hard-part 3).
    """

    def __init__(
        self,
        num_samples: int,
        vs_keys: Sequence[int],
        pops: np.ndarray,
        block_size: int = 2048,
        blocks_per_dispatch: int = 32,
        exact_int: bool = True,
    ):
        self.num_samples = int(num_samples)
        self.n_sets = len(vs_keys)
        self.total_columns = self.num_samples * self.n_sets
        self.block_size = int(block_size)
        self.blocks_per_dispatch = int(blocks_per_dispatch)
        from spark_examples_tpu.ops.gramian import _operand_dtypes

        # Shared dtype policy: int8→int32 when exact, bf16 on TPU / f32 on
        # CPU otherwise (the CPU thunk runtime lacks some bf16 dot shapes).
        operand_dtype, accum_dtype = _operand_dtypes(exact_int)
        self.accum_dtype = accum_dtype
        self.dispatches = 0

        with jax.enable_x64(True):
            self._vs_keys = jnp.asarray(
                np.array([k & _MASK64 for k in vs_keys], dtype=np.uint64)
            )
            self._pops = jnp.asarray(np.asarray(pops, dtype=np.int32))
            self.G = jnp.zeros(
                (self.total_columns, self.total_columns), accum_dtype
            )
            # Per-set counts of rows with variation in that set's columns —
            # matches the wire path's per-dataset record accounting.
            self.variant_rows = jnp.zeros((self.n_sets,), jnp.int64)

            vs_keys_arr, pops_arr = self._vs_keys, self._pops

            @jax.jit
            def update(G, count, positions, thresholds):
                def body(carry, xs):
                    G, count = carry
                    pos, thr = xs
                    hv = generate_has_variation(
                        pos, thr, vs_keys_arr, pops_arr
                    )
                    per_set = hv.reshape(hv.shape[0], count.shape[0], -1)
                    count += jnp.sum(jnp.any(per_set, axis=2), axis=0).astype(
                        count.dtype
                    )
                    X = hv.astype(operand_dtype)
                    G = G + jnp.einsum(
                        "bn,bm->nm", X, X, preferred_element_type=accum_dtype
                    )
                    return (G, count), None

                (G, count), _ = lax.scan(body, (G, count), (positions, thresholds))
                return G, count

            self._update = update

    def add_plan(self, positions: np.ndarray, thresholds: np.ndarray) -> None:
        """Dispatch one scanned group: ``positions`` (K, B) int64,
        ``thresholds`` (K, B, P) uint64 (zero rows = dropped/padding)."""
        if positions.shape != (self.blocks_per_dispatch, self.block_size):
            raise ValueError(
                f"expected ({self.blocks_per_dispatch}, {self.block_size}) "
                f"positions, got {positions.shape}"
            )
        with jax.enable_x64(True):
            self.G, self.variant_rows = self._update(
                self.G,
                self.variant_rows,
                jnp.asarray(positions),
                jnp.asarray(thresholds),
            )
        self.dispatches += 1

    def finalize_device(self) -> jax.Array:
        """The accumulated Gramian, still on device (single data slice, so no
        cross-device reduce is needed here; multi-slice accumulation reduces
        via the mesh paths in ``ops/gramian.py``)."""
        return self.G

    def finalize(self) -> np.ndarray:
        with jax.enable_x64(True):
            return np.asarray(jax.device_get(self.G)).astype(np.float64)


def plan_blocks(
    plan_iter: Iterator[Tuple[np.ndarray, np.ndarray]],
    block_size: int,
    blocks_per_dispatch: int,
    n_pops: int,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Re-chunk a stream of (positions, thresholds) site batches into fixed
    (K, B) dispatch groups, zero-padding the final group (zero thresholds
    generate all-zero rows, which contribute nothing to XᵀX)."""
    cap = block_size * blocks_per_dispatch
    pos_buf = np.zeros(cap, dtype=np.int64)
    thr_buf = np.zeros((cap, n_pops), dtype=np.uint64)
    fill = 0
    for positions, thresholds in plan_iter:
        offset = 0
        while offset < len(positions):
            take = min(cap - fill, len(positions) - offset)
            pos_buf[fill : fill + take] = positions[offset : offset + take]
            thr_buf[fill : fill + take] = thresholds[offset : offset + take]
            fill += take
            offset += take
            if fill == cap:
                yield (
                    pos_buf.reshape(blocks_per_dispatch, block_size).copy(),
                    thr_buf.reshape(
                        blocks_per_dispatch, block_size, n_pops
                    ).copy(),
                )
                fill = 0
    if fill:
        pos_buf[fill:] = 0
        thr_buf[fill:] = 0
        yield (
            pos_buf.reshape(blocks_per_dispatch, block_size).copy(),
            thr_buf.reshape(blocks_per_dispatch, block_size, n_pops).copy(),
        )


__all__ = [
    "DeviceGenGramianAccumulator",
    "generate_has_variation",
    "mix64",
    "plan_blocks",
]

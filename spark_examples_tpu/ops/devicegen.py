"""On-device synthetic ingest: site metadata + genotype generation fused
with Gramian accumulation.

The reference's runtime is dominated by ingest: executors stream variant
pages from the Genomics API and the similarity pass consumes them
(``VariantsRDD.scala:198-225`` feeding ``VariantsPca.scala:222-231``). The
synthetic source stands in for that ingest, and its entire data plane is
counter-based u64 hashing (splitmix64) plus fixed-point arithmetic
(``sources/synthetic.py``) — all trivially jittable. This module moves the
whole ingest onto the TPU:

- per dispatch the host sends TWO SCALARS (a site-grid offset and a valid
  count); the device reconstructs positions, recomputes the per-site
  metadata (ref-block drops, Q32 allele frequencies, per-population
  genotype thresholds, the ``--min-allele-frequency`` filter) bit-identically
  to the host source, generates the (block, samples) genotype matrix with
  the exact same splitmix64 draws, and accumulates ``G += XᵀX`` on the MXU —
  one scanned XLA program per dispatch group;
- there is no per-site host→device traffic at all, so throughput is pure
  device compute, independent of interconnect bandwidth (on remote-attached
  backends the per-site threshold transfer of an earlier design was the
  bottleneck, and the final fetch pays O(prior dispatches) — fused scanning
  keeps dispatches in the hundreds for a whole-genome run).

Exactness of the host↔device correspondence is trivial by construction:
both sides draw the same uint32 allele pair (``_allele_pair`` here,
``_genotype_draw_pair`` on host) and compare against the same Q32 integer
thresholds — pure integer arithmetic, no floating point anywhere in the
data plane. The AF filter compares micro-units (``round(af·1e6)``,
half-even) against ``floor(threshold·1e6)`` (exact via Fraction) — the same
rule every host path uses (``sources/synthetic.py:af_passes``).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_examples_tpu.parallel.mesh import device_put_global

# splitmix64 constants — must match sources/synthetic.py exactly.
_P1 = 0x9E3779B97F4A7C15
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0xD6E8FEB86659FD93
_M1 = 0xBF58476D1CE4E5B9
_M2 = 0x94D049BB133111EB
_MASK64 = (1 << 64) - 1
# Draw-stream tags (sources/synthetic.py).
_S_REF_BLOCK = 1
_S_AF = 2
_S_POP_BASE = 3
_S_GENOTYPE = 100


def _c64(value: int) -> jax.Array:
    """uint64 constant, wrapped mod 2^64 (Python ints over 2^63 would
    overflow the default int path)."""
    return jnp.asarray(np.uint64(value & _MASK64))


def mix64(x: jax.Array) -> jax.Array:
    """splitmix64 finalizer on uint64 arrays — bitwise-identical to
    ``sources/synthetic.py:_mix`` (tested)."""
    x = (x + _c64(_P1)).astype(jnp.uint64)
    x = ((x ^ (x >> jnp.uint64(30))) * _c64(_M1)).astype(jnp.uint64)
    x = ((x ^ (x >> jnp.uint64(27))) * _c64(_M2)).astype(jnp.uint64)
    return (x ^ (x >> jnp.uint64(31))).astype(jnp.uint64)


def _u64_stream(key: jax.Array, pos_term: jax.Array, stream: int) -> jax.Array:
    """``sources/synthetic.py:_u64(key, pos, stream)`` with default
    sample/allele — four chained mixes (the zero sample/allele terms still
    mix)."""
    h = mix64(key ^ pos_term)
    h = mix64(h ^ _c64(stream * _P3))
    h = mix64(h)  # sample = 0
    return mix64(h)  # allele = 0


def site_thresholds_on_device(
    site_key: jax.Array,  # scalar uint64
    positions: jax.Array,  # (B,) int64
    valid: jax.Array,  # (B,) bool
    n_pops: int,
    ref_block_fraction: float,
    min_af_micro: Optional[int],
) -> jax.Array:
    """(B, P) uint64 Q32 genotype thresholds (``af_pop_q32``), zeroed for
    ref-block sites, AF-filtered sites, and invalid (padding) rows —
    bit-identical to the host's ``_site_fields_q`` metadata / the
    ``site_threshold_plan`` values (``sources/synthetic.py``)."""
    from spark_examples_tpu.sources.synthetic import (
        _AF_BASE_Q32,
        _AF_SPAN_Q16,
        _POP_BASE_Q16,
        _POP_HI_Q32,
        _POP_LO_Q32,
        _POP_SPAN_Q17,
    )
    import math

    pos_term = positions.astype(jnp.uint64) * _c64(_P2)
    ref_thresh = math.ceil(ref_block_fraction * 2.0**53)
    is_ref = (
        _u64_stream(site_key, pos_term, _S_REF_BLOCK) >> jnp.uint64(11)
    ) < _c64(ref_thresh)
    u_af = _u64_stream(site_key, pos_term, _S_AF) >> jnp.uint64(48)  # Q16
    af_q32 = _c64(_AF_BASE_Q32) + ((u_af * u_af * _c64(_AF_SPAN_Q16)) >> jnp.uint64(16))
    keep = valid & ~is_ref
    if min_af_micro is not None:
        # round-half-even(af_q32 · 1e6 / 2^32) > floor(threshold · 1e6):
        # the canonical micro-unit AF rule (sources/synthetic.py:af_passes).
        x = af_q32 * _c64(1_000_000)
        q = x >> jnp.uint64(32)
        frac = x & _c64((1 << 32) - 1)
        half = _c64(1 << 31)
        r = q + ((frac > half) | ((frac == half) & ((q & jnp.uint64(1)) == 1))).astype(jnp.uint64)
        keep = keep & (r > _c64(min_af_micro))
    pops = []
    for p in range(n_pops):
        u_p = _u64_stream(site_key, pos_term, _S_POP_BASE + p) >> jnp.uint64(48)
        factor = _c64(_POP_BASE_Q16) + ((u_p * _c64(_POP_SPAN_Q17)) >> jnp.uint64(16))
        af_pop = jnp.clip(
            (af_q32 * factor) >> jnp.uint64(16),
            _c64(_POP_LO_Q32),
            _c64(_POP_HI_Q32),
        )
        pops.append(af_pop)  # Q32 threshold
    T = jnp.stack(pops, axis=1)  # (B, P)
    return jnp.where(keep[:, None], T, jnp.uint64(0))


def fmix32(x: jax.Array) -> jax.Array:
    """murmur3 fmix32 finalizer on uint32 arrays — bitwise-identical to
    ``sources/synthetic.py:_fmix32`` (tested)."""
    x = (x ^ (x >> jnp.uint32(16))) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> jnp.uint32(13))) * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> jnp.uint32(16))


def _pop_segments(pops_np: np.ndarray) -> Optional[list]:
    """``[(pop, start, stop)]`` run-length segments of a non-decreasing
    population vector, or ``None`` when the vector is not contiguous (or too
    fragmented to be worth unrolling). The synthetic source assigns
    contiguous population blocks by construction, which lets the kernel
    broadcast one scalar threshold per segment instead of a (B, N) gather."""
    if pops_np.ndim != 1 or len(pops_np) == 0:
        return None
    diffs = np.diff(pops_np)
    if np.any(diffs < 0):
        return None
    boundaries = np.flatnonzero(diffs) + 1
    if len(boundaries) > 15:
        return None
    if len(pops_np) < 128 * (len(boundaries) + 1):
        # Narrow segments waste VPU lanes (each pads to the 128-lane
        # register width): a 17-sample deep-call cohort is ~2.5× FASTER
        # through the single gathered compare (measured, BENCH_r04 platinum).
        return None
    starts = np.concatenate([[0], boundaries])
    stops = np.concatenate([boundaries, [len(pops_np)]])
    return [
        (int(pops_np[s]), int(s), int(e)) for s, e in zip(starts, stops)
    ]


def _allele_pair(h2_col: jax.Array, samples_u64: jax.Array):
    """The two (B, n) uint32 allele draws from the per-site genotype state —
    the device half of ``sources/synthetic.py:_genotype_draw_pair``: xor the
    sample term into the 64-bit state, fold to 32 bits, one fmix32, and a
    multiplicative re-mix for the second allele. One u64 xor + three u32
    multiplies per (site, sample) — the ingest hot loop (DESIGN.md
    "single-chip ingest roofline")."""
    x64 = h2_col ^ samples_u64
    # range: deliberate 64→32 bit FOLD (high xor low) — the draw is defined
    # on u32; truncation is the hash, not a lost value (DESIGN.md §7 step 1).
    x32 = ((x64 >> jnp.uint64(32)) ^ x64).astype(jnp.uint32)
    d1 = fmix32(x32)
    d2 = (d1 * jnp.uint32(0x9E3779B9)) ^ jnp.uint32(0x85EBCA6B)
    return d1, d2


def generate_has_variation(
    positions: jax.Array,  # (B,) int64
    thresholds: jax.Array,  # (B, P) uint64 Q32 thresholds, 0 = dropped
    vs_keys: jax.Array,  # (S,) uint64: per-variant-set genotype stream keys
    pops: jax.Array,  # (N_total,) int32: per-set cohorts' sample → population
    set_sizes: Optional[Tuple[int, ...]] = None,  # per-set cohort sizes
) -> jax.Array:
    """(B, ΣNₛ) {0,1} has-variation rows, bitwise-equal to the host packed
    path (``sources/synthetic.py:genotype_blocks``) for kept sites; rows
    whose thresholds are zeroed come out all-zero (contribute nothing to
    XᵀX).

    Multi-dataset: synthetic variant sets share the site grid (site identity
    is keyed by position only — ``sources/synthetic.py:_site_fields``), so
    the reference's 2-set join and ≥3-set merge-intersect
    (``VariantsPca.scala:155-188``) both reduce to column concatenation of
    per-set genotype matrices; ``vs_keys`` carries one stream per set.
    Cohorts may differ per set (the 1KG × Platinum scenario): ``pops`` is
    the concatenation of each set's population vector and ``set_sizes``
    splits it. With ``set_sizes`` omitted, every set shares the one cohort
    ``pops`` describes.

    When ``pops`` is a concrete array (always the case from the memoized
    update builders, which close over it), contiguous population blocks are
    unrolled into per-segment scalar-threshold compares — no (B, N) gather;
    a traced or non-contiguous ``pops`` falls back to the gather.
    """
    n_sets = vs_keys.shape[0]
    try:
        pops_np: Optional[np.ndarray] = np.asarray(pops)
    except Exception:  # a tracer: no static view available
        pops_np = None
    if set_sizes is None:
        sizes = (pops.shape[0],) * n_sets
        offsets = [0] * n_sets
        pops_dyn = [pops] * n_sets
    else:
        sizes = tuple(int(s) for s in set_sizes)
        cum = np.concatenate([[0], np.cumsum(sizes)])
        offsets = [int(c) for c in cum[:-1]]
        pops_dyn = [
            lax.slice_in_dim(pops, offsets[s], offsets[s] + sizes[s])
            for s in range(n_sets)
        ]
    # range: Q32 thresholds are < 2^32 by construction (clipped at
    # _POP_HI_Q32, sources/synthetic.py) — uint32 holds them exactly.
    Tq32 = thresholds.astype(jnp.uint32)
    pos_term = positions.astype(jnp.uint64) * _c64(_P2)
    parts = []
    for s in range(n_sets):
        h1 = mix64(vs_keys[s] ^ pos_term)  # (B,)
        h2 = mix64(h1 ^ _c64(_S_GENOTYPE * _P3))[:, None]
        segments = (
            _pop_segments(pops_np[offsets[s] : offsets[s] + sizes[s]])
            if pops_np is not None
            else None
        )
        if segments is not None:
            columns = []
            for pop, start, stop in segments:
                samples = (
                    jnp.arange(start, stop, dtype=jnp.uint64) * _c64(_P4)
                )[None, :]
                d1, d2 = _allele_pair(h2, samples)
                tf = Tq32[:, pop : pop + 1]  # (B, 1) broadcast
                columns.append((d1 < tf) | (d2 < tf))
            parts.append(jnp.concatenate(columns, axis=1))
        else:
            samples = (jnp.arange(sizes[s], dtype=jnp.uint64) * _c64(_P4))[
                None, :
            ]
            d1, d2 = _allele_pair(h2, samples)
            tf = jnp.take(Tq32, pops_dyn[s], axis=1)  # (B, N_s)
            parts.append((d1 < tf) | (d2 < tf))
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def generate_column_block(
    positions: jax.Array,  # (B,) int64
    thresholds: jax.Array,  # (B, P) uint64 Q32 thresholds, 0 = dropped
    vs_key: jax.Array,  # (scalar | (S,)) uint64 genotype stream key(s)
    pops_local: jax.Array,  # (N_local,) int32: this slice's column pops
    col_start: jax.Array,  # scalar int: first GLOBAL column index
    num_samples: int,  # total columns (Σ per-set sizes for multi-set)
    set_sizes: Optional[Tuple[int, ...]] = None,
) -> jax.Array:
    """(B, N_local) {0,1} has-variation for one COLUMN slice: the genotype
    draw is keyed by the set-local sample index, so a slice can generate
    exactly its own columns of the cohort matrix (bitwise-equal to the
    corresponding columns of :func:`generate_has_variation`); padded
    columns past ``num_samples`` come out all-zero. ``pops_local`` is traced
    (sliced by axis index inside shard_map), so this path keeps the
    threshold gather.

    Multi-set joint cohorts (``set_sizes`` + an (S,) ``vs_key`` array, the
    reference's join/merge scenario ``VariantsPca.scala:155-188``): the
    global column space is the concatenation of per-set cohorts, and a
    slice's columns may span set boundaries — each set's draw plane is
    computed for the whole slice and masked to its own columns (S× the
    per-column u32 work; S is 2–3 in practice, and the alternative is the
    orders-of-magnitude-slower host wire ingest). ``pops_local`` is then a
    slice of the CONCATENATED per-set population vector."""
    n_local = pops_local.shape[0]
    cols = col_start + jnp.arange(n_local, dtype=jnp.int64)
    pos_term = positions.astype(jnp.uint64) * _c64(_P2)
    # range: Q32 thresholds < 2^32 by construction (clipped at _POP_HI_Q32).
    t_full = jnp.take(thresholds, pops_local, axis=1).astype(jnp.uint32)
    t_full = jnp.where((cols < num_samples)[None, :], t_full, jnp.uint32(0))
    if set_sizes is None:
        samples = (cols.astype(jnp.uint64) * _c64(_P4))[None, :]
        h1 = mix64(vs_key ^ pos_term)  # (B,)
        h2 = mix64(h1 ^ _c64(_S_GENOTYPE * _P3))[:, None]
        d1, d2 = _allele_pair(h2, samples)
        return (d1 < t_full) | (d2 < t_full)
    offsets = np.concatenate([[0], np.cumsum(set_sizes)])
    hv = jnp.zeros((positions.shape[0], n_local), dtype=bool)
    for s, size in enumerate(set_sizes):
        lo, hi = int(offsets[s]), int(offsets[s + 1])
        mask = (cols >= lo) & (cols < hi)
        # Set-local sample index; clamped outside the mask so the uint64
        # cast never sees a negative value.
        local_idx = jnp.clip(cols - lo, 0, max(size - 1, 0))
        samples = (local_idx.astype(jnp.uint64) * _c64(_P4))[None, :]
        h1 = mix64(vs_key[s] ^ pos_term)
        h2 = mix64(h1 ^ _c64(_S_GENOTYPE * _P3))[:, None]
        d1, d2 = _allele_pair(h2, samples)
        hv = hv | (mask[None, :] & ((d1 < t_full) | (d2 < t_full)))
    return hv


# Measured v5e sweet spot: 524,288-site dispatch groups at 2,504 columns
# (~40 ms of device work per dispatch). Per-dispatch overhead (host loop +
# tunnel) is fixed, so the per-dispatch SITE budget scales inversely with
# the cohort's column count: the 17-column deep-call cohort runs ~2× faster
# at K=512 than at the large-N optimum K=32 (platinum whole-genome
# 1.03 → 0.53 s, matched tunnel conditions — DESIGN.md §7.3); past ~512
# the gain plateaus, and at ≥2,504 columns larger K measurably regresses
# (tail padding × 22 contigs).
_TARGET_COLUMN_SITES = 524_288 * 2504


def auto_blocks_per_dispatch(total_columns: int, block_size: int) -> int:
    """Dispatch-group length (``lax.scan`` steps) for a cohort: constant
    device work per dispatch across cohort sizes, clamped to the measured
    [32, 512] sweet range and rounded to a multiple of 8 (the tail program
    is K/8 blocks)."""
    k = _TARGET_COLUMN_SITES // max(int(total_columns), 1)
    k //= max(int(block_size), 1)
    return int(min(512, max(32, (k // 8) * 8)))


@functools.lru_cache(maxsize=32)
def _fused_update(
    vs_keys: Tuple[int, ...],
    pops_bytes: bytes,
    site_key: int,
    spacing: int,
    ref_block_fraction: float,
    min_af_micro: Optional[int],
    block_size: int,
    blocks_per_dispatch: int,
    operand_name: str,
    accum_name: str,
    n_pops: int,
    set_sizes: Optional[Tuple[int, ...]] = None,
):
    """Build (and memoize) the scanned generate→accumulate program for one
    static configuration. Memoizing at module level means every accumulator
    with the same configuration — e.g. a warmup instance and a measured
    instance — shares one traced/compiled program instead of re-tracing per
    instance.

    ``n_pops`` is the SOURCE's population count, passed explicitly rather
    than inferred as ``pops.max()+1``: for a cohort smaller than the
    population count the device must still compute every population's
    threshold stream to stay bit-identical with the host path by
    construction, not by accident.

    ``set_sizes`` carries per-variant-set cohort sizes for asymmetric
    joint-cohort configurations (``pops_bytes`` is then the concatenation of
    each set's population vector); ``None`` means every set shares the one
    cohort ``pops_bytes`` describes."""
    operand_dtype = np.dtype(operand_name)
    accum_dtype = np.dtype(accum_name)
    K, B = blocks_per_dispatch, block_size
    column_splits = (
        [int(x) for x in np.cumsum(set_sizes)[:-1]]
        if set_sizes is not None
        else None
    )

    with jax.enable_x64(True):
        vs_keys_arr = jnp.asarray(
            np.array([k & _MASK64 for k in vs_keys], dtype=np.uint64)
        )
        pops_arr = jnp.asarray(np.frombuffer(pops_bytes, dtype=np.int32))
        site_key_arr = _c64(site_key)

        @jax.jit
        def update(G, rows_count, kept_count, grid_offset, n_valid):  # graftcheck: disable=GC005 -- non-donation matches ops/gramian.py's measured policy (donated-buffer serialization costs ~10x sustained throughput on remote-attached backends); G here is the scan carry, double-buffered by the driver
            block_idx = jnp.arange(K * B, dtype=jnp.int64).reshape(K, B)

            def body(carry, idx):
                G, rows_count, kept_count = carry
                index = grid_offset + idx  # (B,) grid indices
                positions = index * spacing
                valid = idx < n_valid
                T = site_thresholds_on_device(
                    site_key_arr,
                    positions,
                    valid,
                    n_pops,
                    ref_block_fraction,
                    min_af_micro,
                )
                kept_count += jnp.sum(jnp.any(T > 0, axis=1)).astype(
                    kept_count.dtype
                )
                hv = generate_has_variation(
                    positions, T, vs_keys_arr, pops_arr, set_sizes
                )
                if column_splits is None:
                    per_set_any = jnp.any(
                        hv.reshape(hv.shape[0], rows_count.shape[0], -1), axis=2
                    )
                else:
                    per_set_any = jnp.stack(
                        [
                            jnp.any(part, axis=1)
                            for part in jnp.split(hv, column_splits, axis=1)
                        ],
                        axis=1,
                    )
                rows_count += jnp.sum(per_set_any, axis=0).astype(
                    rows_count.dtype
                )
                # The barrier forces X to MATERIALIZE once: without it XLA
                # fuses the whole u32 generation chain into the dot's operand
                # producers and recomputes it per output tile — measured
                # 4.43 s → 3.14 s whole-genome, 8.85 s → 5.46 s large-cohort
                # on v5e (it must sit on the int8 cast; a barrier on the
                # bool lets the cast re-fuse and drag generation with it).
                X = lax.optimization_barrier(hv.astype(operand_dtype))
                G = G + jnp.einsum(
                    "bn,bm->nm", X, X, preferred_element_type=accum_dtype
                )
                return (G, rows_count, kept_count), None

            (G, rows_count, kept_count), _ = lax.scan(
                body, (G, rows_count, kept_count), block_idx
            )
            return G, rows_count, kept_count

        return update


@functools.lru_cache(maxsize=32)
def _fused_update_mesh(
    vs_keys: Tuple[int, ...],
    pops_bytes: bytes,
    site_key: int,
    spacing: int,
    ref_block_fraction: float,
    min_af_micro: Optional[int],
    block_size: int,
    blocks_per_dispatch: int,
    operand_name: str,
    accum_name: str,
    n_pops: int,
    set_sizes: Optional[Tuple[int, ...]],
    mesh,
):
    """The data-parallel (shard_map) wrapper of :func:`_fused_update`,
    memoized on (config, mesh) so warmup and measured accumulators share one
    traced/compiled program, like the single-slice path."""
    from spark_examples_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_examples_tpu.parallel.mesh import DATA_AXIS

    update = _fused_update(
        vs_keys,
        pops_bytes,
        site_key,
        spacing,
        ref_block_fraction,
        min_af_micro,
        block_size,
        blocks_per_dispatch,
        operand_name,
        accum_name,
        n_pops,
        set_sizes,
    )
    g_spec = P(DATA_AXIS, None, None)
    r_spec = P(DATA_AXIS, None)
    s_spec = P(DATA_AXIS)

    def per_slice(g, r, k, o, v):
        g1, r1, k1 = update(g[0], r[0], k[0], o[0], v[0])
        return g1[None], r1[None], k1[None]

    return jax.jit(
        shard_map(
            per_slice,
            mesh=mesh,
            in_specs=(g_spec, r_spec, s_spec, s_spec, s_spec),
            out_specs=(g_spec, r_spec, s_spec),
        )
    )


class _GridDispatchAccumulator:
    """Shared dispatch machinery for the device-generation accumulators:
    validated (grid_offset, n_valid) group dispatch, data-axis round-robin,
    and the eager-mode poke. Subclasses provide ``_update`` with signature
    ``(G, variant_rows, kept_sites, offsets, valids)`` plus the
    ``data_parallel`` / ``sites_per_dispatch`` / ``_scalar_sharding``
    attributes."""

    #: whether the eager-mode poke has fired for this accumulator (at most
    #: once; see :meth:`poke` and the dispatch-loop gating).
    _poked = False

    #: dispatched site-grid CAPACITY (summed over data slices — every slice
    #: executes the full scan, padding included) vs the VALID sites inside
    #: it. Their gap is the dispatch padding waste (``bench.py`` reports the
    #: fraction per config; at small regions the fixed tail-group padding
    #: dominates wall-clock), and capacity × per-site ring traffic gives
    #: ``ring_bytes_total`` for the ring accumulator.
    sites_capacity = 0
    sites_valid = 0

    def add_ranges(self, grid_offsets: np.ndarray, n_valids: np.ndarray) -> None:
        """Data-parallel dispatch: slice d processes grid indices
        ``[grid_offsets[d], grid_offsets[d] + n_valids[d])`` (``n_valids[d]
        == 0`` means an idle slice this round)."""
        self._dispatch_ranges(
            self._update, self.sites_per_dispatch, grid_offsets, n_valids
        )

    def _maybe_poke(self) -> None:
        """Poke once, at the moment a SECOND dispatch is about to be issued:
        the poke exists to overlap the host dispatch loop with device
        execution, so the first follow-up dispatch — in this grid walk or a
        later one — is the earliest point where the overlap can pay. A
        single-dispatch run never pokes (it would spend a pure round-trip on
        an overlap it cannot use; the terminal fetch executes the lone
        dispatch either way)."""
        if self.dispatches == 1 and not self._poked:
            self.poke()

    def _dispatch_ranges(self, update, cap, grid_offsets, n_valids) -> None:
        D = self.data_parallel
        grid_offsets = np.asarray(grid_offsets, dtype=np.int64)
        n_valids = np.asarray(n_valids, dtype=np.int64)
        if grid_offsets.shape != (D,) or n_valids.shape != (D,):
            raise ValueError(f"expected ({D},) offsets/valids")
        if n_valids.min(initial=0) < 0 or n_valids.max(initial=0) > cap:
            raise ValueError(f"n_valids must be in [0, {cap}]")
        if (grid_offsets < 0).any():
            # Negative grid indices would wrap to garbage uint64 positions on
            # device and silently corrupt the Gramian.
            raise ValueError("grid_offsets must be non-negative")
        self._maybe_poke()
        with jax.enable_x64(True):
            self.G, self.variant_rows, self.kept_sites = update(
                self.G,
                self.variant_rows,
                self.kept_sites,
                device_put_global(grid_offsets, self._scalar_sharding),
                device_put_global(n_valids, self._scalar_sharding),
            )
        self.dispatches += 1
        self.sites_capacity += int(cap) * D
        self.sites_valid += int(n_valids.sum())

    #: position of ``blocks_per_dispatch`` in both subclasses' update-key
    #: tuples (``_fused_update`` and ``_ring_update`` share the prefix
    #: ``(..., block_size, blocks_per_dispatch, ...)``).
    _TAIL_KEY_INDEX = 7

    def _compile_update(self, key):
        """Build the update program for a (possibly tail-modified) key;
        subclasses with a tail program override this."""
        return None

    def _tail_spec(self):
        """(tail_update, tail_sites) — a ~K/8-length program for grid
        remainders, or ``(None, 0)`` for accumulators without one (the
        remainder then pads a full group, the pre-tail behavior)."""
        if getattr(self, "_update_key", None) is None:
            return None, 0
        if self._update_tail is None:
            i = self._TAIL_KEY_INDEX
            key = (
                self._update_key[:i]
                + (self._tail_blocks,)
                + self._update_key[i + 1 :]
            )
            self._update_tail = self._compile_update(key)
        return self._update_tail, self.block_size * self._tail_blocks

    def _round_robin(self, update, cap, starts, last_index: int) -> None:
        D = self.data_parallel
        for i in range(0, len(starts), D):
            offsets = np.zeros(D, dtype=np.int64)
            valids = np.zeros(D, dtype=np.int64)
            for d, off in enumerate(starts[i : i + D]):
                offsets[d] = off
                valids[d] = min(cap, last_index - off)
            self._dispatch_ranges(update, cap, offsets, valids)

    def add_grid(self, first_index: int, last_index: int) -> None:
        """Dispatch all groups for a contiguous grid index range
        ``[first_index, last_index)``, round-robining groups over the data
        axis; the remainder after the full groups runs through the tail
        program when the subclass provides one (padding waste bounded by one
        tail group instead of one full group per contig)."""
        step = self.sites_per_dispatch
        total = max(0, last_index - first_index)
        n_main = total // step
        rem_start = first_index + n_main * step
        self._round_robin(
            self._update,
            step,
            [first_index + i * step for i in range(n_main)],
            last_index,
        )
        if rem_start >= last_index:
            return
        tail_update, tail_sites = self._tail_spec()
        if tail_update is None:
            self._round_robin(self._update, step, [rem_start], last_index)
            return
        self._round_robin(
            tail_update,
            tail_sites,
            list(range(rem_start, last_index, tail_sites)),
            last_index,
        )

    def poke(self) -> None:
        """Force the backend into eager execution with one tiny sync fetch.

        The remote-attached (tunneled) PJRT backend defers execution of
        queued dispatches until the first synchronous transfer — host work
        and device work would otherwise run strictly serially (measured:
        total = host + execute). One scalar fetch after the first dispatch
        flips it to eager for the rest of the stream. Fetches a process-local
        shard, not the global value: in a multi-controller run the counter
        spans non-addressable devices and ``device_get`` would raise.
        """
        from spark_examples_tpu.parallel.mesh import local_shard

        with jax.enable_x64(True):
            local_shard(self.kept_sites)
        self._poked = True

    def sync(self) -> None:
        """Block until the whole ingest chain has executed: one synchronous
        fetch of a value that depends on every dispatch (``kept_sites``
        threads through the scan carry). The cheap alternative to
        :meth:`ingest_counters` when the counter VALUES aren't needed —
        stage timing stays honest at half the fetch round-trips."""
        from spark_examples_tpu.parallel.mesh import host_value

        with jax.enable_x64(True):
            host_value(self.kept_sites)

    def ingest_counters(self) -> Tuple[np.ndarray, int]:
        """``(per-set variant-row totals, kept-site total)``, synchronously
        fetched — valid in every process of a multi-controller run
        (``host_value`` replicates before fetching). Blocks until the whole
        ingest chain has executed, so calling this at the end of the ingest
        stage also makes the stage's wall-clock honest on asynchronous
        backends (``utils/tracing.py``).

        Both counters ride ONE transfer (``parallel/mesh.py:
        packed_host_fetch`` — each synchronous fetch on a remote-attached
        backend pays a full tunnel round-trip, and the two separate fetches
        here were a measurable share of small-region wall-clock, VERDICT r4
        weakness 1)."""
        from spark_examples_tpu.parallel.mesh import packed_host_fetch

        rows_shape = tuple(self.variant_rows.shape)
        rows_size = int(np.prod(rows_shape)) if rows_shape else 1
        flat = packed_host_fetch(
            [self.variant_rows, self.kept_sites],
            self.mesh if self._scalar_sharding is not None else None,
        )
        rows = flat[:rows_size].reshape(rows_shape)
        kept = flat[rows_size:]
        return self._reduce_row_counts(rows), int(np.sum(kept))


class DeviceGenGramianAccumulator(_GridDispatchAccumulator):
    """Fully fused on-device ingest+similarity for the synthetic source.

    The host walks the site grid in fixed-size dispatch groups and sends only
    ``(grid_offset, valid_count)`` scalars; the device reconstructs
    positions (``index · spacing``), recomputes site metadata, generates
    genotypes, and accumulates. Carries the Gramian, a kept-site counter,
    and per-set variant-row counters through chained scanned dispatches;
    nothing is fetched until finalize. ``exact_int`` accumulates
    int8×int8→int32 on the MXU (always exact; whole-genome diagonal counts
    ~12M would sit uncomfortably close to f32's 2^24 integer limit — SURVEY
    §7 hard-part 3).
    """

    def __init__(
        self,
        num_samples: int,
        vs_keys: Sequence[int],
        pops: np.ndarray,
        site_key: int,
        spacing: int,
        ref_block_fraction: float,
        min_af_micro: Optional[int] = None,
        block_size: int = 2048,
        blocks_per_dispatch: int = 32,
        exact_int: bool = True,
        mesh=None,
        n_pops: Optional[int] = None,
        set_sizes: Optional[Sequence[int]] = None,
        pops_per_set: Optional[Sequence[np.ndarray]] = None,
    ):
        from spark_examples_tpu.ops.gramian import _operand_dtypes
        from spark_examples_tpu.parallel.mesh import DATA_AXIS

        self.num_samples = int(num_samples)
        self.n_sets = len(vs_keys)
        # Asymmetric joint cohorts (the 1KG × Platinum scenario): per-set
        # sizes with per-set population vectors; symmetric configurations
        # share the one (num_samples,) cohort.
        if set_sizes is not None:
            self.set_sizes: Optional[Tuple[int, ...]] = tuple(
                int(s) for s in set_sizes
            )
            if len(self.set_sizes) != self.n_sets:
                raise ValueError(
                    f"set_sizes has {len(self.set_sizes)} entries for "
                    f"{self.n_sets} variant sets"
                )
            if pops_per_set is None or len(pops_per_set) != self.n_sets:
                raise ValueError("set_sizes needs matching pops_per_set")
            if any(
                len(p) != s for p, s in zip(pops_per_set, self.set_sizes)
            ):
                raise ValueError("pops_per_set lengths must match set_sizes")
            pops = np.concatenate(
                [np.asarray(p, dtype=np.int32) for p in pops_per_set]
            )
            self.total_columns = sum(self.set_sizes)
        else:
            self.set_sizes = None
            self.total_columns = self.num_samples * self.n_sets
        self.block_size = int(block_size)
        self.blocks_per_dispatch = int(blocks_per_dispatch)
        self.sites_per_dispatch = self.block_size * self.blocks_per_dispatch
        self.spacing = int(spacing)
        self.mesh = mesh
        self.data_parallel = (
            mesh.shape.get(DATA_AXIS, 1) if mesh is not None else 1
        )
        # Shared dtype policy: int8→int32 when exact, bf16 on TPU / f32 on
        # CPU otherwise (the CPU thunk runtime lacks some bf16 dot shapes).
        operand_dtype, accum_dtype = _operand_dtypes(exact_int, mesh)
        self.accum_dtype = accum_dtype
        self.dispatches = 0

        pops32 = np.asarray(pops, dtype=np.int32)
        update_key = (
            tuple(int(k) for k in vs_keys),
            pops32.tobytes(),
            int(site_key),
            self.spacing,
            float(ref_block_fraction),
            min_af_micro,
            self.block_size,
            self.blocks_per_dispatch,
            np.dtype(operand_dtype).name,
            np.dtype(accum_dtype).name,
            # Source-authoritative population count (falls back to inference
            # for callers that predate the parameter).
            int(n_pops) if n_pops is not None else int(pops32.max()) + 1,
            self.set_sizes,
        )

        D = self.data_parallel
        with jax.enable_x64(True):
            if D == 1:
                self.G = jnp.zeros(
                    (self.total_columns, self.total_columns), accum_dtype
                )
                # Per-set counts of rows with variation in that set's columns
                # — matches the wire path's per-dataset record accounting.
                self.variant_rows = jnp.zeros((self.n_sets,), jnp.int64)
                self.kept_sites = jnp.zeros((), jnp.int64)
                self._update = _fused_update(*update_key)
                self._scalar_sharding = None
            else:
                # Data-parallel ingest: each data slice generates and
                # accumulates a DIFFERENT span of the site grid (its own
                # (grid_offset, n_valid) pair) into its own replica of G —
                # the Spark-executor analog; finalize is the one psum.
                from jax.sharding import NamedSharding, PartitionSpec as P

                g_spec = P(DATA_AXIS, None, None)
                r_spec = P(DATA_AXIS, None)
                s_spec = P(DATA_AXIS)
                self._scalar_sharding = NamedSharding(mesh, s_spec)
                self.G = device_put_global(
                    np.zeros(
                        (D, self.total_columns, self.total_columns),
                        np.dtype(accum_dtype),
                    ),
                    NamedSharding(mesh, g_spec),
                )
                self.variant_rows = device_put_global(
                    np.zeros((D, self.n_sets), np.int64),
                    NamedSharding(mesh, r_spec),
                )
                self.kept_sites = device_put_global(
                    np.zeros((D,), np.int64), NamedSharding(mesh, s_spec)
                )
                self._update = _fused_update_mesh(*update_key, mesh)
        # Tail program: a ~K/8-length variant of the same scanned update for
        # contig remainders. Large dispatch groups amortize per-dispatch
        # overhead, but a whole-genome run has 22 contig tails — padding
        # each to the full group would waste up to (group-1) sites of
        # compute per contig (>50% at the tuned 16K×32 group size). Built
        # lazily: only runs that produce remainders pay its compile.
        self._update_key = update_key
        self._tail_blocks = max(1, self.blocks_per_dispatch // 8)
        self._update_tail = None

    def _compile_update(self, key):
        return (
            _fused_update_mesh(*key, self.mesh)
            if self.data_parallel > 1
            else _fused_update(*key)
        )

    def _reduce_row_counts(self, rows: np.ndarray) -> np.ndarray:
        """(n_sets,) per-set totals: data-parallel slices each hold partial
        per-set counts (disjoint grid spans) that sum elementwise."""
        return rows.sum(axis=0) if rows.ndim > 1 else rows

    def add_range(self, grid_offset: int, n_valid: int) -> None:
        """Dispatch one group covering grid indices
        ``[grid_offset, grid_offset + n_valid)`` (positions ``index ·
        spacing``); indices past ``n_valid`` are padding. Single-slice form;
        data-parallel accumulators use :meth:`add_ranges`."""
        if not 0 < n_valid <= self.sites_per_dispatch:
            raise ValueError(
                f"n_valid must be in (0, {self.sites_per_dispatch}], got {n_valid}"
            )
        if grid_offset < 0:
            raise ValueError("grid_offset must be non-negative")
        if self.data_parallel > 1:
            offsets = np.zeros(self.data_parallel, dtype=np.int64)
            valids = np.zeros(self.data_parallel, dtype=np.int64)
            offsets[0], valids[0] = grid_offset, n_valid
            self.add_ranges(offsets, valids)
            return
        self._dispatch_single(self._update, grid_offset, n_valid)

    def _dispatch_single(
        self, update, grid_offset: int, n_valid: int, cap: Optional[int] = None
    ) -> None:
        self._maybe_poke()
        with jax.enable_x64(True):
            self.G, self.variant_rows, self.kept_sites = update(
                self.G,
                self.variant_rows,
                self.kept_sites,
                jnp.asarray(np.int64(grid_offset)),
                jnp.asarray(np.int64(n_valid)),
            )
        self.dispatches += 1
        self.sites_capacity += int(
            self.sites_per_dispatch if cap is None else cap
        )
        self.sites_valid += int(n_valid)

    def add_grid(self, first_index: int, last_index: int) -> None:
        """Single-slice fast path keeps scalar dispatches; data-parallel
        instances use the shared round-robin (both with the tail program
        for remainders, bounding padding waste per contig to under one tail
        group)."""
        if self.data_parallel > 1:
            super().add_grid(first_index, last_index)
            return
        main = self.sites_per_dispatch
        off = first_index
        while last_index - off >= main:
            self.add_range(off, main)
            off += main
        if off < last_index:
            tail_update, tail = self._tail_spec()
            while off < last_index:
                self._dispatch_single(
                    tail_update, off, min(tail, last_index - off), cap=tail
                )
                off += tail

    def finalize_device(self) -> jax.Array:
        """The accumulated Gramian, still on device; for data-parallel
        accumulators this is the one cross-slice reduce (the Spark
        ``reduceByKey`` shuffle become a single ``psum`` over ICI,
        ``VariantsPca.scala:230``)."""
        from spark_examples_tpu.ops.gramian import data_axis_sum

        if self.data_parallel > 1:
            if not self.G.is_fully_addressable:
                # Multi-controller: replicate so every process can fetch.
                # The result spans other processes' devices (so it is fully
                # *replicated*, not fully *addressable*); host_value
                # short-circuits on is_fully_replicated, so downstream
                # fetches read the local replica without a second gather.
                from jax.sharding import NamedSharding, PartitionSpec

                return data_axis_sum(
                    self.G,
                    out_shardings=NamedSharding(self.mesh, PartitionSpec()),
                )
            return data_axis_sum(self.G)
        return self.G

    def finalize(self) -> np.ndarray:
        from spark_examples_tpu.parallel.mesh import host_value

        with jax.enable_x64(True):
            return host_value(self.finalize_device()).astype(np.float64)


@functools.lru_cache(maxsize=32)
def _ring_update(
    vs_keys: Tuple[int, ...],
    pops_bytes: bytes,
    site_key: int,
    spacing: int,
    ref_block_fraction: float,
    min_af_micro: Optional[int],
    block_size: int,
    blocks_per_dispatch: int,
    operand_name: str,
    num_samples: int,
    padded: int,
    n_pops: int,
    mesh,
    set_sizes: Optional[Tuple[int, ...]] = None,
    pack: bool = False,
):
    """Memoized scanned generate→ring-accumulate program for one static
    configuration (warmup and measured accumulators share one compiled
    program, like :func:`_fused_update`). Signature of the returned jit:
    ``(G, variant_rows, kept_sites, offsets, valids)``. ``n_pops`` is the
    source's population count (see :func:`_fused_update`). ``set_sizes``
    makes the column space a multi-set concatenation
    (:func:`generate_column_block`); ``variant_rows`` is then per set —
    a row counts for set s when ANY of set s's columns vary. ``pack``
    selects the bit-packed ring wire format: generated columns are packed
    on device (8 genotypes/byte) before the first ``ppermute``, so the ring
    moves ⅛ the ICI bytes; requires ``padded`` to satisfy the pack-width
    invariant (local width a multiple of 8 —
    ``parallel/mesh.py:padded_cohort``). Passing a hierarchical
    ``data x hosts x samples`` mesh selects the two-level reduction
    schedule (``ops/gramian.py:_hier_ring_tiles``): generation is
    schedule-independent (each device still generates its flat column
    slot) and only the tile circulation changes, so flat and hier runs are
    byte-identical (CI-asserted)."""
    from spark_examples_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from spark_examples_tpu.ops.gramian import (
        _hier_ring_tiles,
        _pack_bits_device,
        _ring_tiles,
    )
    from spark_examples_tpu.parallel.mesh import (
        DATA_AXIS,
        HOST_AXIS,
        SAMPLES_AXIS,
    )

    operand_dtype = np.dtype(operand_name)
    pops_padded = np.frombuffer(pops_bytes, dtype=np.int32)
    # A hierarchical (data x hosts x samples) mesh selects the two-level
    # schedule: the host-major factorization IS the schedule choice
    # (parallel/mesh.py:hierarchical_mesh), exactly as in
    # ops/gramian.py:build_hierarchical_update — no extra flag, and the
    # memo key stays this same positional tuple.
    hier = HOST_AXIS in mesh.shape
    hier_hosts = mesh.shape[HOST_AXIS] if hier else 1
    inner_devices = mesh.shape[SAMPLES_AXIS]
    n_local = padded // (hier_hosts * inner_devices)
    K, B = blocks_per_dispatch, block_size
    data_axis = DATA_AXIS if DATA_AXIS in mesh.shape else None
    sample_axes = (HOST_AXIS, SAMPLES_AXIS) if hier else SAMPLES_AXIS
    g_spec = P(data_axis, sample_axes, None)
    s_spec = P(data_axis)
    r_spec = P(data_axis, None)
    n_sets = len(vs_keys)
    set_bounds = (
        np.concatenate([[0], np.cumsum(set_sizes)])
        if set_sizes is not None
        else np.array([0, num_samples])
    )

    with jax.enable_x64(True):
        vs_keys_arr = jnp.asarray(
            np.array([k & _MASK64 for k in vs_keys], dtype=np.uint64)
        )
        site_key_arr = _c64(site_key)
        pops_all = jnp.asarray(pops_padded)

        def per_device(g, rows, kept, offset, n_valid):
            # g: (1, n_local, padded); offset/n_valid/kept: (1,);
            # rows: (1, n_sets)
            s_idx = jax.lax.axis_index(SAMPLES_AXIS)
            if hier:
                # Flat slot of this device in the host-major factorization:
                # it owns the same column tile the flat ring would give it
                # (hierarchical_mesh reshapes without reordering devices),
                # so generation is schedule-independent by construction.
                s_idx = jax.lax.axis_index(HOST_AXIS) * inner_devices + s_idx
            col_start = (s_idx * n_local).astype(jnp.int64)
            cols = col_start + jnp.arange(n_local, dtype=jnp.int64)
            pops_local = jax.lax.dynamic_slice(
                pops_all, (s_idx * n_local,), (n_local,)
            )
            block_idx = jnp.arange(K * B, dtype=jnp.int64).reshape(K, B)

            def body(carry, idx):
                g_l, rows_l, kept_l = carry
                positions = (offset[0] + idx) * spacing
                valid = idx < n_valid[0]
                T = site_thresholds_on_device(
                    site_key_arr,
                    positions,
                    valid,
                    n_pops,
                    ref_block_fraction,
                    min_af_micro,
                )
                kept_l += jnp.sum(jnp.any(T > 0, axis=1)).astype(kept_l.dtype)
                hv = generate_column_block(
                    positions,
                    T,
                    vs_keys_arr if set_sizes is not None else vs_keys_arr[0],
                    pops_local,
                    col_start,
                    num_samples,
                    set_sizes,
                )
                # A row "has variation" for set s if ANY of set s's columns
                # do, across every slice (matches the dense accumulator's
                # per-set accounting).
                # range: bool any() → {0,1} per row, exact in int32.
                per_set_local = jnp.stack(
                    [
                        jnp.any(
                            hv
                            & (
                                (cols >= int(set_bounds[s]))
                                & (cols < int(set_bounds[s + 1]))
                            )[None, :],
                            axis=1,
                        ).astype(jnp.int32)
                        for s in range(n_sets)
                    ],
                    axis=1,
                )  # (B, n_sets)
                total_any = jax.lax.psum(per_set_local, sample_axes)
                rows_l += jnp.sum(total_any > 0, axis=0).astype(rows_l.dtype)
                # Same materialization barrier as the dense update: the ring
                # exchange dots the local column block against every rotated
                # tile, so a fused generation chain would recompute per tile
                # AND per ring step. Under the packed wire format the
                # barrier sits on the PACKED tile — the ⅛-size buffer is
                # what the ring circulates, and packing right after
                # generation keeps the u32 chain materialized exactly once.
                if pack:
                    # range: hv is {0,1} (ops/contracts.py:HAS_VARIATION)
                    # — exact in uint8 for the bit pack.
                    x_cols = jax.lax.optimization_barrier(
                        _pack_bits_device(hv.astype(jnp.uint8))
                    )
                else:
                    x_cols = jax.lax.optimization_barrier(
                        hv.astype(operand_dtype)
                    )
                if hier:
                    g_l = _hier_ring_tiles(
                        g_l, x_cols, HOST_AXIS, SAMPLES_AXIS,
                        operand_dtype, packed=pack,
                    )
                else:
                    g_l = _ring_tiles(
                        g_l, x_cols, SAMPLES_AXIS, operand_dtype, packed=pack
                    )
                return (g_l, rows_l, kept_l), None

            (g_l, rows_l, kept_l), _ = jax.lax.scan(
                body, (g[0], rows[0], kept[0]), block_idx
            )
            return g_l[None], rows_l[None], kept_l[None]

        return jax.jit(  # graftcheck: disable=GC005 -- non-donation matches ops/gramian.py's measured policy (donated-buffer serialization costs ~10x sustained throughput on remote-attached backends); graftcheck ir cross-checks this disable against the traced donated_invars (GI002)
            shard_map(
                per_device,
                mesh=mesh,
                in_specs=(g_spec, r_spec, s_spec, s_spec, s_spec),
                out_specs=(g_spec, r_spec, s_spec),
                # kept/rows are samples-replicated by construction
                # (identical metadata / psum'd flags on every slice).
                check_vma=False,
            )
        )


class DeviceGenRingGramianAccumulator(_GridDispatchAccumulator):
    """Sharded large-N device ingest: the composition of on-device
    generation with the ring-exchange Gramian.

    Each ``samples``-axis slice generates ONLY its own sample-column block
    of the cohort matrix (``generate_column_block``) and the ring exchange
    (``ops/gramian.py:_ring_tiles``) accumulates row tiles — so for a 50K+
    cohort (the reference's ~20 GB in-memory warning,
    ``VariantsPca.scala:216-217``) no device ever materializes the full
    N×N, no host→device data traffic exists at all, and the optional
    ``data`` axis adds Spark-executor-style grid parallelism on top.

    Multi-set joint cohorts (``set_sizes`` + ``pops_per_set`` + a list
    ``vs_key``) concatenate per-set column blocks exactly like the dense
    accumulator — the join/merge scenario past the dense HBM rule
    (``VariantsPca.scala:155-188``) stays on device instead of falling
    back to host wire ingest.
    """

    def __init__(
        self,
        num_samples: int,
        vs_key,
        pops: np.ndarray,
        site_key: int,
        spacing: int,
        ref_block_fraction: float,
        mesh,
        min_af_micro: Optional[int] = None,
        block_size: int = 1024,
        blocks_per_dispatch: int = 8,
        exact_int: bool = True,
        n_pops: Optional[int] = None,
        set_sizes: Optional[Sequence[int]] = None,
        pops_per_set: Optional[Sequence[np.ndarray]] = None,
        pack_bits: str = "auto",
        reduce_schedule: str = "auto",
        hier_hosts: Optional[int] = None,
    ):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from spark_examples_tpu.ops.gramian import (
            _operand_dtypes,
            resolve_ring_pack,
        )
        from spark_examples_tpu.parallel.mesh import (
            DATA_AXIS,
            SAMPLES_AXIS,
            hierarchical_mesh,
            padded_cohort,
            resolve_hier_hosts,
            resolve_reduce_schedule,
        )

        if SAMPLES_AXIS not in mesh.shape or mesh.shape[SAMPLES_AXIS] < 2:
            raise ValueError("ring device ingest needs a samples axis >= 2")
        self.mesh = mesh
        self.pack = resolve_ring_pack(pack_bits)
        self.num_samples = int(num_samples)
        vs_keys = (
            tuple(int(k) for k in vs_key)
            if isinstance(vs_key, (list, tuple))
            else (int(vs_key),)
        )
        self.n_sets = len(vs_keys)
        if set_sizes is not None:
            self.set_sizes: Optional[Tuple[int, ...]] = tuple(
                int(s) for s in set_sizes
            )
            if len(self.set_sizes) != self.n_sets:
                raise ValueError(
                    f"set_sizes has {len(self.set_sizes)} entries for "
                    f"{self.n_sets} variant sets"
                )
            if pops_per_set is None or len(pops_per_set) != self.n_sets:
                raise ValueError("set_sizes needs matching pops_per_set")
            if any(
                len(p) != s for p, s in zip(pops_per_set, self.set_sizes)
            ):
                raise ValueError("pops_per_set lengths must match set_sizes")
            pops = np.concatenate(
                [np.asarray(p, dtype=np.int32) for p in pops_per_set]
            )
            self.total_columns = sum(self.set_sizes)
        elif self.n_sets > 1:
            # Symmetric multi-set: every set shares the one cohort.
            self.set_sizes = (self.num_samples,) * self.n_sets
            pops = np.concatenate(
                [np.asarray(pops, dtype=np.int32)] * self.n_sets
            )
            self.total_columns = self.num_samples * self.n_sets
        else:
            self.set_sizes = None
            self.total_columns = self.num_samples
        self.samples_parallel = mesh.shape[SAMPLES_AXIS]
        self.data_parallel = mesh.shape.get(DATA_AXIS, 1)
        # --reduce-schedule on the fused generation ring: the SAME
        # resolution rule as the host-fed accumulator
        # (ops/gramian.py:ShardedGramianAccumulator) — auto = hier iff the
        # samples axis spans more than one host, explicit hier with a
        # non-dividing host factor fails loudly. Everything outside the
        # tile circulation — G, generation, finalize — is
        # schedule-independent, so flat and hier are byte-identical.
        resolve_reduce_schedule(reduce_schedule, 1)  # validate the spelling
        try:
            self.hier_hosts = resolve_hier_hosts(
                self.samples_parallel, hier_hosts
            )
        except ValueError:
            if reduce_schedule == "hier":
                raise  # an explicit hier request must not silently degrade
            self.hier_hosts = 1
        self.reduce_schedule = resolve_reduce_schedule(
            reduce_schedule, self.hier_hosts
        )
        self._hier_mesh = (
            hierarchical_mesh(mesh, self.hier_hosts)
            if self.reduce_schedule == "hier"
            else None
        )
        # Packed wire format pads the column space to 8× the samples axis
        # (pack-width invariant); pad columns generate all-zero and finalize
        # trims them, exactly like the plain samples-axis padding.
        self.padded = padded_cohort(
            self.total_columns, self.samples_parallel, pack=self.pack
        )
        self.n_local = self.padded // self.samples_parallel
        self.block_size = int(block_size)
        self.blocks_per_dispatch = int(blocks_per_dispatch)
        self.sites_per_dispatch = self.block_size * self.blocks_per_dispatch
        self.spacing = int(spacing)
        self.dispatches = 0
        operand_dtype, accum_dtype = _operand_dtypes(exact_int, mesh)
        self.accum_dtype = accum_dtype

        D = self.data_parallel
        pops_padded = np.zeros(self.padded, dtype=np.int32)
        pops_padded[: self.total_columns] = np.asarray(pops, dtype=np.int32)
        data_axis = DATA_AXIS if DATA_AXIS in mesh.shape else None
        g_spec = P(data_axis, SAMPLES_AXIS, None)
        self._scalar_sharding = NamedSharding(mesh, P(data_axis))

        with jax.enable_x64(True):
            self.G = device_put_global(
                np.zeros((D, self.padded, self.padded), np.dtype(accum_dtype)),
                NamedSharding(mesh, g_spec),
            )
            self.kept_sites = device_put_global(
                np.zeros((D,), np.int64), self._scalar_sharding
            )
            self.variant_rows = device_put_global(
                np.zeros((D, self.n_sets), np.int64),
                NamedSharding(mesh, P(data_axis, None)),
            )
        self._update_key = (
            vs_keys,
            pops_padded.tobytes(),
            int(site_key),
            self.spacing,
            float(ref_block_fraction),
            min_af_micro,
            self.block_size,
            self.blocks_per_dispatch,
            np.dtype(operand_dtype).name,
            self.total_columns,
            self.padded,
            int(n_pops)
            if n_pops is not None
            else int(np.asarray(pops, dtype=np.int32).max()) + 1,
            # The mesh in the memo key selects the schedule: the
            # hierarchical factorization shards the same rows over the same
            # devices in the same order (identical HloSharding), so G and
            # the scalar operands need no reshard at the jit boundary.
            self._hier_mesh if self._hier_mesh is not None else mesh,
            self.set_sizes,
            self.pack,
        )
        self._update = _ring_update(*self._update_key)
        self._tail_blocks = max(1, self.blocks_per_dispatch // 8)
        self._update_tail = None

    def _compile_update(self, key):
        return _ring_update(*key)

    @property
    def ring_bytes_total(self) -> int:
        """Total ICI bytes the ring exchanges have moved so far: every
        dispatched site (padding included — padded rows ride the ring too)
        costs one (samples-1)-step circulation of its row's column tiles
        (``parallel/mesh.py:ring_traffic_bytes``). Deterministic host-side
        arithmetic, published as ``gramian_ring_bytes`` by the driver."""
        from spark_examples_tpu.parallel.mesh import ring_traffic_bytes

        return ring_traffic_bytes(
            self.sites_capacity, self.samples_parallel, self.n_local, self.pack
        )

    def schedule_block(self) -> dict:
        """The manifest ``schedule`` block for the fused device-generation
        ring: which reduction schedule ran (flat, or the two-level
        hierarchical schedule over the host-major factorization) and its
        provable per-link-class byte split. Unlike the host-fed
        accumulator, this path has no independent per-flush accounting:
        ``ring_bytes_total`` IS the closed-form projection over dispatched
        capacity, so predicted == measured here by construction and the
        pair's drift signal lives on the host-fed side
        (``ShardedGramianAccumulator.schedule_block``)."""
        from spark_examples_tpu.parallel.mesh import (
            hierarchical_traffic_bytes,
        )

        predicted = int(self.ring_bytes_total)
        if self.reduce_schedule == "hier":
            level = hierarchical_traffic_bytes(
                self.sites_capacity,
                self.hier_hosts,
                self.samples_parallel // self.hier_hosts,
                self.n_local,
                self.pack,
            )
            ici, dcn = int(level.ici_bytes), int(level.dcn_bytes)
        elif self.hier_hosts == 1:
            ici, dcn = predicted, 0
        else:
            # Flat ring spanning hosts: no byte is provably intra-host
            # (parallel/mesh.py:flat_traffic_split) — the GS001 premise.
            ici, dcn = 0, predicted
        return {
            "kind": self.reduce_schedule,
            "hosts": int(self.hier_hosts),
            "devices_per_host": int(
                self.samples_parallel // self.hier_hosts
            ),
            "predicted_ring_bytes": predicted,
            "measured_ring_bytes": predicted,
            "predicted_ici_bytes": ici,
            "predicted_dcn_bytes": dcn,
        }

    def finalize_sharded(self) -> jax.Array:
        """(padded, padded) Gramian, row-sharded over ``samples`` — feeds
        the sharded centering/eigensolve without ever gathering N×N.

        The cross-data-slice sum promotes integer accumulators to int64
        (``ops/gramian.py:data_axis_sum`` — the per-slice int32 accumulators
        are each bounded by their own kept sites, but the total across
        slices is not)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from spark_examples_tpu.ops.gramian import data_axis_sum
        from spark_examples_tpu.parallel.mesh import SAMPLES_AXIS

        return data_axis_sum(
            self.G,
            out_shardings=NamedSharding(self.mesh, P(SAMPLES_AXIS, None)),
        )

    def _reduce_row_counts(self, rows: np.ndarray) -> np.ndarray:
        """(n_sets,) per-set totals: data-parallel slices hold partial
        per-set counts (disjoint grid spans, already samples-replicated
        inside the shard_map) that sum elementwise."""
        return rows.sum(axis=0) if rows.ndim > 1 else np.asarray([rows.sum()])

    def finalize(self) -> np.ndarray:
        from spark_examples_tpu.parallel.mesh import host_value

        with jax.enable_x64(True):
            full = host_value(self.finalize_sharded())
        return full[: self.total_columns, : self.total_columns].astype(
            np.float64
        )


__all__ = [
    "DeviceGenGramianAccumulator",
    "DeviceGenRingGramianAccumulator",
    "auto_blocks_per_dispatch",
    "generate_column_block",
    "generate_has_variation",
    "mix64",
    "site_thresholds_on_device",
]

"""Similarity-matrix (Gramian) accumulation on the MXU.

The reference computes sample-similarity counts with a per-variant pair loop
into a per-partition Breeze matrix, merged by a ``reduceByKey`` shuffle
(``VariantsPca.scala:222-231``), or a pair-emission streaming variant
(``VariantsPca.scala:302-319``). Both are equivalent to

    G = Xᵀ X,   X ∈ {0,1}^(V×N),  X[v, s] = sample s has variation at v

so the TPU formulation is blockwise matmul: pack variants into fixed-shape
``(B, N)`` {0,1} blocks, compute ``G += XᵀX`` on the MXU with bfloat16
operands and float32 accumulation (0/1 operands and integer partial sums are
exact in bf16×bf16→f32 up to 2^24 per entry; an int8→int32 path is available
for absolute exactness), and reduce across devices once at the end — the
shuffle becomes a single ``psum`` over ICI.

Variable-length host batches are staged into the fixed block and the final
partial block is padded with zero rows, which contribute nothing to XᵀX —
static shapes for jit with no masking.

Two strategies, mirroring the reference's in-memory/streaming duality:

- :class:`GramianAccumulator` ("dense", ``VariantsPca.scala:210-231``): one
  replicated N×N accumulator per data-parallel device. Right whenever N×N
  fits HBM comfortably (N=2,504 → 25 MB f32).
- :class:`ShardedGramianAccumulator` ("sharded", the analog of
  ``VariantsPca.scala:288-319``'s memory-bounded strategy): the Gramian lives
  row-tile-sharded over the ``samples`` mesh axis and each update runs a
  ring exchange (``ppermute``) of sample-column blocks, so no device ever
  materializes the full N×N — the ~50K-samples/~20GB regime
  (``VariantsPca.scala:216-217``) at TPU HBM sizes.

The ring wire format is BIT-PACKED by default (``--ring-pack-bits``): tiles
circulate as ``(B, n_local/8)`` uint8 (8 genotypes/byte — ⅛ the ICI traffic
of unpacked uint8) and are unpacked on device per step, and the ring loop is
double-buffered — the ``ppermute`` for step k+1 is issued before the dot of
step k consumes its tile, so XLA overlaps the ICI transfer with the MXU
matmul instead of alternating them (the decomposed collective-matmul
pattern; see DESIGN.md §7.4). ``--ring-pack-bits off`` keeps the unpacked
wire as the bit-exact parity oracle. Host staging packs the same way before
``device_put``, so host→device transfer shrinks 8× too (the dense path's
``np.packbits`` trick, applied to the sharded staging buffer).

At pod scale the ring grows a second SCHEDULE (``--reduce-schedule``): the
hierarchical two-level ring (:func:`build_hierarchical_update`) factors
the samples axis host-major into ``hosts x devices`` and runs a packed
intra-host ring over ICI inside an inter-host ring over DCN, so one slow
DCN hop hides behind a whole inner ring of ICI + MXU work and each host's
columns cross DCN exactly once per pass — same bytes, same results
(byte-identical, CI-asserted), provably-placed links. The schedule-level
contracts (per-link traffic, overlap, liveness, critical path) are
machine-proven device-free by ``graftcheck sched`` (``check/sched.py``,
DESIGN.md §8.8) on declared topologies up to 32x8 — no pod required.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_examples_tpu.utils.compat import axis_size, shard_map

from spark_examples_tpu.ops.contracts import (
    EXACT_F32_LIMIT,
    flush_entry_increment,
)
from spark_examples_tpu.parallel.mesh import (
    DATA_AXIS,
    HOST_AXIS,
    SAMPLES_AXIS,
    device_put_global,
    hierarchical_mesh,
    hierarchical_traffic_bytes,
    padded_cohort,
    resolve_hier_hosts,
    resolve_reduce_schedule,
    ring_traffic_bytes,
)


def resolve_ring_pack(pack_bits: str) -> bool:
    """``--ring-pack-bits`` → whether the ring circulates packed tiles.

    ``off`` is the unpacked bit-exact oracle; ``on`` packs; ``auto`` (the
    default) currently equals ``on`` — the pack/unpack is a cheap VPU
    shift-and-mask on every backend while the 8× traffic cut always helps,
    so there is nothing for auto to decide yet (the spelling reserves room
    for a future platform-conditional rule without a flag migration).
    """
    if pack_bits not in ("auto", "on", "off"):
        raise ValueError(
            f"--ring-pack-bits must be one of auto/on/off, got {pack_bits!r}"
        )
    return pack_bits != "off"


def _operand_dtypes(exact_int: bool, mesh: Optional[Mesh] = None):
    if exact_int:
        return np.int8, jnp.int32
    # bf16 operands feed the MXU on TPU (and tensor cores on GPU); the CPU
    # thunk runtime cannot execute bf16×bf16→f32 dots for some shapes
    # (UNIMPLEMENTED DotThunk), and on CPU f32 is the fast path anyway.
    # Exactness is identical: 0/1 operands, integer partial sums exact to
    # 2^24 per entry either way. Decide from the devices that will actually
    # run the dot, not the process default.
    platform = (
        mesh.devices.flat[0].platform if mesh is not None else jax.default_backend()
    )
    if platform == "cpu":
        return np.float32, jnp.float32
    return ml_dtypes.bfloat16, jnp.float32


# f32 accumulation is exact for integers up to 2^24 (EXACT_F32_LIMIT, now
# defined with the rest of the dtype-window registry in ops/contracts.py and
# re-exported here); past a projected per-entry count of this limit the
# accumulators losslessly convert to the int8->int32 MXU path (all entries
# are still exact integers at the moment of conversion). SURVEY §7 hard-part
# 3: whole-genome diagonal counts (~12M) approach this, and merged-cohort
# configs exceed it. The projection itself is contracts.flush_entry_increment
# — the same callable `graftcheck ranges` GR005 proves conservative against
# the per-dispatch increment read off the traced kernel jaxprs.

# Dense vs sharded similarity strategy, decided from memory — the TPU
# restatement of the reference's guidance, which states its bound in GB ("a
# matrix which may be up to 20GB for ~50K samples",
# ``VariantsPca.scala:216-217,296-297``). The dense strategy holds about
# _DENSE_BUFFERS simultaneous N×N accumulator-dtype buffers per device at
# peak (G, its non-donated update, the centered copy, and eigensolve
# temporaries); it fits when that stays under DENSE_HBM_FRACTION of
# per-device memory. One rule, used by BOTH the driver's strategy resolution
# and the ingest-path eligibility check — no duplicated magic constants.
DENSE_HBM_FRACTION = 0.8
_DENSE_BUFFERS = 4
_DEFAULT_DEVICE_BYTES = 16 << 30  # v5e HBM, used when memory_stats is absent


def per_device_memory_bytes(default: int = _DEFAULT_DEVICE_BYTES) -> int:
    """This process's per-device memory budget: ``memory_stats()`` when the
    backend reports it (TPU does), else a v5e-sized default (CPU's virtual
    test devices report nothing useful)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        limit = int(stats.get("bytes_limit", 0)) if stats else 0
        if limit > 0:
            return limit
    except Exception:
        return default
    return default


def dense_strategy_fits(n_columns: int, accum_bytes: int = 4) -> bool:
    """Whether a replicated ``n_columns``² accumulator (plus working copies)
    fits per-device memory — the dense/sharded auto-switch predicate."""
    need = _DENSE_BUFFERS * int(n_columns) ** 2 * accum_bytes
    return need <= DENSE_HBM_FRACTION * per_device_memory_bytes()


def _maybe_switch_accumulator(acc, next_bound: int, out_shardings=None) -> bool:
    """Losslessly convert an f32 accumulator to int32 before any entry could
    cross the 2^24 exact-integer limit (entries are bounded by
    Σ rows × max-count², all still exact integers at conversion time).
    Returns True when a switch happened (callers may need to rebuild a
    dtype-closed update function)."""
    if acc.exact_int or acc.accum_dtype == jnp.int32:
        return False
    if next_bound <= EXACT_F32_LIMIT:
        return False
    # range: every entry is an exact integer <= EXACT_F32_LIMIT (2^24) at
    # conversion time — far inside int32's 2^31 window, so the cast is
    # lossless by the GR005-proven trigger (check/ranges.py).
    acc.G = jax.jit(
        lambda g: g.astype(jnp.int32), out_shardings=out_shardings
    )(acc.G)
    acc.operand_dtype, acc.accum_dtype = np.int8, jnp.int32
    return True


@functools.partial(jax.jit, static_argnames=("operand_dtype",))
def _dense_update_counts(G, X, operand_dtype):  # graftcheck: disable=GC005 -- non-donation is the measured win: donating G forces a serializing buffer-reuse pattern, ~10x sustained-throughput loss on remote-attached backends (module docstring; same rationale as _dense_update)
    """G[d] += X[d]ᵀ X[d] for unpacked count-valued uint8 rows (the rare
    same-set-join case where a callset column appears more than once per
    variant — the reference's pair loop adds k² for k duplicates, which is
    exactly the outer product of count vectors)."""
    Xc = X.astype(operand_dtype)
    return G + jnp.einsum(
        "dbn,dbm->dnm", Xc, Xc, preferred_element_type=G.dtype
    )


@functools.partial(jax.jit, static_argnames=("operand_dtype", "num_samples"))
def _dense_update(G, X_packed, operand_dtype, num_samples):  # graftcheck: disable=GC005 -- deliberate: donation serializes buffer reuse, ~10x sustained-throughput loss measured on the v5e tunnel (see docstring below); one extra NxN buffer is the cheaper trade
    """G[d] += X[d]ᵀ X[d] — local per data-slice, no communication.

    X arrives BIT-PACKED (8 genotypes/byte over PCIe/DCN — ⅛ the traffic of
    uint8, 1/16 of bf16) and is unpacked + cast to the MXU operand dtype on
    device; the unpack is a cheap VPU shift-and-mask fused ahead of the
    matmul. Deliberately NOT donating G: donation forces a serializing
    buffer-reuse pattern that degrades sustained throughput ~10× on
    remote-attached backends (measured on the v5e tunnel); one extra N×N
    buffer is cheap.
    """
    # Materialize the unpacked operand once: fused into the dot, the
    # unpack+cast recomputes per output tile (same effect as the generation
    # chain in ops/devicegen.py, scaled to the unpack's ~2 ops — measured
    # ~5% on v5e).
    Xc = jax.lax.optimization_barrier(
        _unpack_bits(X_packed, num_samples).astype(operand_dtype)
    )
    return G + jnp.einsum(
        "dbn,dbm->dnm", Xc, Xc, preferred_element_type=G.dtype
    )


def data_axis_sum(G: jax.Array, out_shardings=None) -> jax.Array:
    """Cross-data-slice reduce of a ``(D, ...)`` stacked accumulator.

    With more than one slice, integer accumulators are promoted to int64 in
    the reduce: each slice's int32 accumulator is bounded by its own
    accumulated sites, but the TOTAL across D slices is not — it can pass
    2^31 while every slice stays under it. Traced under x64 so the requested
    dtype is honored regardless of the caller's config (outside x64 JAX
    silently canonicalizes int64 back to int32). Single-slice reduces keep
    the accumulator dtype — no promotion is needed where no cross-slice sum
    happens. Shared by every accumulator's finalize (here and
    ``ops/devicegen.py``) so the overflow policy lives in one place.
    """
    out_dtype = (
        jnp.int64
        if G.shape[0] > 1 and jnp.issubdtype(G.dtype, jnp.integer)
        else G.dtype
    )
    with jax.enable_x64(True):
        if out_shardings is not None:
            return jax.jit(
                lambda g: jnp.sum(g, axis=0, dtype=out_dtype),
                out_shardings=out_shardings,
            )(G)
        return jnp.sum(G, axis=0, dtype=out_dtype)


class _AccumulatorTelemetry:
    """Optional flush instrumentation shared by both accumulators.

    When a run registry is attached (the driver always attaches its own),
    every flush feeds ``gramian_flushes_total`` / ``gramian_rows_total``
    counters and the ``gramian_flush_seconds`` histogram (all labeled by
    strategy), and ``gramian_inflight_dispatches`` tracks the pipelined
    feed depth for the heartbeat. The sharded strategy additionally feeds
    the ``gramian_ring_bytes`` counter (total ICI bytes its ring exchanges
    moved — ``parallel/mesh.py:ring_traffic_bytes``, the number the packed
    wire format cuts 8×) and the per-flush ``gramian_ring_flush_seconds``
    histogram, both surfaced in the run manifest and the heartbeat. At
    finalize the accumulated host-side flush time attaches to the open span
    tree as a ``dispatch`` aggregate (one span, not one per flush — a
    whole-genome run has thousands) and the finalize reduce itself runs
    under a ``reduce-flush`` span.
    """

    def __init__(self, registry, spans, strategy: str):
        self.spans = spans
        self.flush_seconds_total = 0.0
        self._flushes = self._rows = self._seconds = self._inflight = None
        self._ring_bytes = self._ring_seconds = None
        self._entry_max = self._entry_bound_gauge = None
        self.entry_max_seen = 0.0
        self._registry = registry
        if registry is not None and strategy == "sharded":
            from spark_examples_tpu.obs.metrics import (
                GRAMIAN_RING_BYTES,
                GRAMIAN_RING_FLUSH_SECONDS,
                well_known_counter,
            )

            self._ring_bytes = well_known_counter(registry, GRAMIAN_RING_BYTES)
            self._ring_seconds = registry.histogram(
                GRAMIAN_RING_FLUSH_SECONDS,
                "Host-side seconds per ring-exchange flush "
                "(pack + device_put + ring dispatch).",
            )
        if registry is not None:
            labels = {"strategy": strategy}
            self._flushes = registry.counter(
                "gramian_flushes_total",
                "Device flushes (one dispatched G += XᵀX update each).",
                labelnames=("strategy",),
            ).labels(**labels)
            self._rows = registry.counter(
                "gramian_rows_total",
                "Variant rows accumulated into the Gramian.",
                labelnames=("strategy",),
            ).labels(**labels)
            self._seconds = registry.histogram(
                "gramian_flush_seconds",
                "Host-side time per flush (pack + device_put + dispatch).",
                labelnames=("strategy",),
            ).labels(**labels)
            from spark_examples_tpu.obs.metrics import (
                GRAMIAN_INFLIGHT_DISPATCHES,
                well_known_gauge,
            )

            self._inflight = well_known_gauge(
                registry, GRAMIAN_INFLIGHT_DISPATCHES
            )

    def record_flush(self, rows: int, seconds: float, in_flight: int) -> None:
        self.flush_seconds_total += seconds
        if self._flushes is not None:
            self._flushes.inc(1)
            self._rows.inc(rows)
            self._seconds.observe(seconds)
            self._inflight.set(in_flight)

    def record_ring(self, nbytes: int, seconds: float) -> None:
        if self._ring_bytes is not None:
            self._ring_bytes.inc(nbytes)
            self._ring_seconds.observe(seconds)

    def record_entry_sample(self, G, entry_bound: int) -> None:
        """``--check-ranges`` debug sampling: the measured max |entry| of
        the live accumulator next to the statically-projected bound
        (``contracts.flush_entry_increment`` accumulated over flushes) —
        the runtime half of the ``graftcheck ranges`` exactness contract,
        mirroring the hostmem measured-RSS/static-bound pair. The sampled
        pair lands in the ``gramian_entry_max`` / ``gramian_static_entry_bound``
        gauges and, from there, in the run manifest; the obs smoke asserts
        measured <= proven on every build."""
        sample = float(np.asarray(jax.device_get(jnp.max(jnp.abs(G)))))  # graftcheck: disable=GC001 -- deliberate per-flush device fetch: --check-ranges is an opt-in DEBUG mode whose whole point is sampling the live accumulator (off by default, documented in the flag help)
        self.entry_max_seen = max(self.entry_max_seen, sample)
        if self._registry is not None:
            if self._entry_max is None:
                from spark_examples_tpu.obs.metrics import (
                    GRAMIAN_ENTRY_MAX,
                    GRAMIAN_STATIC_ENTRY_BOUND,
                    well_known_gauge,
                )

                self._entry_max = well_known_gauge(
                    self._registry, GRAMIAN_ENTRY_MAX
                )
                self._entry_bound_gauge = well_known_gauge(
                    self._registry, GRAMIAN_STATIC_ENTRY_BOUND
                )
            self._entry_max.set(self.entry_max_seen)
            self._entry_bound_gauge.set(float(entry_bound))

    def finalize_span(self):
        """Context for the finalize reduce; also attaches the flush-time
        aggregate so the span tree reads ingest → dispatch → reduce-flush."""
        import contextlib

        if self.spans is None:
            return contextlib.nullcontext()
        self.spans.add("dispatch", self.flush_seconds_total)
        return self.spans.span("reduce-flush")


def _unpack_bits(packed: jax.Array, num_columns: int) -> jax.Array:
    """(..., ceil(N/8)) uint8 → (..., N) {0,1} uint8 (np.packbits big-endian
    bit order)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(*packed.shape[:-1], packed.shape[-1] * 8)[
        ..., :num_columns
    ]


def _pack_bits_device(bits: jax.Array) -> jax.Array:
    """(..., N) {0,1} uint8 → (..., N/8) uint8, ``N % 8 == 0`` — the exact
    on-device inverse of :func:`_unpack_bits` (np.packbits big-endian bit
    order, verified against NumPy in tests). A cheap VPU shift-and-sum; the
    device-generation ring packs its generated columns with this before the
    first ``ppermute`` so the wire format matches the host-packed path."""
    *lead, n = bits.shape
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    # range: inputs are {0,1} membership bits (ops/contracts.py:HAS_VARIATION)
    # — uint8 holds them exactly, and the shifted disjoint-bit terms below
    # sum to at most 255.
    grouped = bits.reshape(*lead, n // 8, 8).astype(jnp.uint8) << shifts
    # Exact in uint8: 8 disjoint-bit terms sum to at most 255.
    return jnp.sum(grouped, axis=-1, dtype=jnp.uint8)


class GramianAccumulator:
    """Dense strategy: replicated N×N per data-parallel device.

    Feed host ``(b, N)`` uint8 has-variation rows with :meth:`add_rows`;
    :meth:`finalize` pads, flushes, and cross-device-reduces to a single
    float32 (or int32) N×N similarity matrix on host.
    """

    def __init__(
        self,
        num_samples: int,
        mesh: Optional[Mesh] = None,
        block_size: int = 1024,
        exact_int: bool = False,
        sync_every: int = 1,
        pipeline_depth: Optional[int] = None,
        registry=None,
        spans=None,
        check_ranges: bool = False,
    ):
        self.telemetry = _AccumulatorTelemetry(registry, spans, "dense")
        self.check_ranges = bool(check_ranges)
        self.num_samples = int(num_samples)
        self.mesh = mesh
        self.block_size = int(block_size)
        self.exact_int = bool(exact_int)
        self.operand_dtype, self.accum_dtype = _operand_dtypes(exact_int, mesh)
        self._entry_bound = 0  # conservative max over per-entry counts
        self.data_parallel = mesh.shape[DATA_AXIS] if mesh is not None else 1
        # Bound the async dispatch queue: an unboundedly deep chain of
        # in-flight updates degrades sustained throughput ~30× on
        # remote-attached backends (measured). Two policies:
        # - sync_every (legacy): block on the CURRENT G every few flushes —
        #   zero host/device overlap at the default of 1;
        # - pipeline_depth d: block on the G from d flushes AGO, so up to d
        #   updates stay in flight and flush k+1's pack + device_put overlap
        #   flush k's matmul — the double-buffered device feed of the
        #   chunk-parallel ingest engine (d=2 is classic double buffering).
        #   Updates do NOT donate G (see _dense_update), so holding the
        #   older references is safe.
        self.sync_every = max(1, int(sync_every))
        self.pipeline_depth = (
            None if pipeline_depth is None else max(1, int(pipeline_depth))
        )
        self._in_flight: list = []
        self._flushes = 0

        rows = self.data_parallel * self.block_size
        self._staging = np.zeros((rows, self.num_samples), dtype=np.uint8)
        self._fill = 0
        self.rows_seen = 0

        g_shape = (self.data_parallel, self.num_samples, self.num_samples)
        if mesh is not None:
            self._g_sharding = NamedSharding(mesh, P(DATA_AXIS, None, None))
            self._x_sharding = NamedSharding(mesh, P(DATA_AXIS, None, None))
            self.G = device_put_global(
                np.zeros(g_shape, dtype=np.dtype(self.accum_dtype)), self._g_sharding
            )
        else:
            self._g_sharding = None
            self._x_sharding = None
            self.G = jnp.zeros(g_shape, self.accum_dtype)

    def add_rows(self, rows: np.ndarray) -> None:
        """Stage host rows; flush full blocks to the device."""
        rows = np.asarray(rows, dtype=np.uint8)
        if rows.ndim != 2 or rows.shape[1] != self.num_samples:
            raise ValueError(
                f"expected (b, {self.num_samples}) rows, got {rows.shape}"
            )
        self.rows_seen += rows.shape[0]
        offset = 0
        capacity = self._staging.shape[0]
        while offset < rows.shape[0]:
            take = min(capacity - self._fill, rows.shape[0] - offset)
            self._staging[self._fill : self._fill + take] = rows[offset : offset + take]
            self._fill += take
            offset += take
            if self._fill == capacity:
                self._flush()

    def _flush(self) -> None:
        if self._fill == 0:
            return
        flush_rows, flush_start = self._fill, time.perf_counter()
        block = self._staging
        if self._fill < block.shape[0]:
            # Zero rows contribute nothing to XᵀX — pad instead of masking.
            block = block.copy()
            block[self._fill :] = 0
        max_count = int(block.max(initial=0))
        # The ONE projection formula (ops/contracts.py) — GR005 proves it
        # conservative w.r.t. the jaxpr-derived per-dispatch increment.
        increment = flush_entry_increment(self._fill, max_count)
        _maybe_switch_accumulator(
            self, self._entry_bound + increment, out_shardings=self._g_sharding
        )
        self._entry_bound += increment
        shaped = block.reshape(
            self.data_parallel, self.block_size, self.num_samples
        )
        if max_count > 1:
            # Count-valued rows (same-set joins) can't be bit-packed; ship
            # them unpacked through the counts kernel. Under pipeline_depth
            # the flush returns with the dispatch still in flight, and a
            # full-block `shaped` is a VIEW of the reused _staging buffer —
            # which jnp.asarray/device_put may alias zero-copy on the CPU
            # backend — so the next add_rows would overwrite an in-flight
            # operand; copy before shipping. (The bit-packed branch is safe:
            # np.packbits allocates fresh. The legacy sync-per-flush path is
            # safe: nothing is in flight when add_rows resumes.)
            if self.pipeline_depth is not None and block is self._staging:
                shaped = shaped.copy()
            Xd = (
                device_put_global(shaped, self._x_sharding)
                if self._x_sharding is not None
                else jnp.asarray(shaped)
            )
            self.G = _dense_update_counts(self.G, Xd, self.operand_dtype)
        else:
            X = np.packbits(shaped, axis=-1)
            Xd = (
                device_put_global(X, self._x_sharding)
                if self._x_sharding is not None
                else jnp.asarray(X)
            )
            self.G = _dense_update(
                self.G, Xd, self.operand_dtype, self.num_samples
            )
        self._fill = 0
        self._flushes += 1
        if self.pipeline_depth is not None:
            # Double-buffered feed: wait only for the update issued
            # `pipeline_depth` flushes ago, leaving the most recent
            # transfers/dispatches in flight behind this block's compute.
            self._in_flight.append(self.G)
            if len(self._in_flight) > self.pipeline_depth:
                jax.block_until_ready(self._in_flight.pop(0))
        elif self._flushes % self.sync_every == 0:
            jax.block_until_ready(self.G)
        if self.check_ranges:
            self.telemetry.record_entry_sample(self.G, self._entry_bound)
        self.telemetry.record_flush(
            flush_rows, time.perf_counter() - flush_start, len(self._in_flight)
        )

    def snapshot_state(self) -> dict:
        """Crash-consistent checkpoint state: flush staged rows, drain the
        dispatch pipeline, and fetch the partial Gramian with its
        dtype-ladder position — everything :meth:`restore_state` needs to
        rebuild this accumulator mid-stream on a fresh process. The fetch
        is deliberate and periodic (``--gramian-checkpoint-dir``), not a
        hot-path sync."""
        self._flush()
        jax.block_until_ready(self.G)  # graftcheck: disable=GC001 -- deliberate checkpoint barrier: the snapshot must capture a quiesced accumulator (no in-flight updates), at --checkpoint-every-sites cadence, not per flush
        self._in_flight.clear()
        G_host = np.asarray(jax.device_get(self.G))  # graftcheck: disable=GC001 -- deliberate periodic checkpoint fetch of the partial Gramian (the artifact payload); cadence is --checkpoint-every-sites, not the dispatch loop
        return {
            "strategy": "dense",
            "G": G_host,
            "accum_dtype": np.dtype(self.accum_dtype).name,
            "exact_int": self.exact_int,
            "entry_bound": self._entry_bound,
            "rows_seen": self.rows_seen,
            "flushes": self._flushes,
            "num_samples": self.num_samples,
            "data_parallel": self.data_parallel,
            "padded": self.num_samples,
        }

    def restore_state(self, checkpoint: dict) -> None:
        """Merge a persisted partial into this (fresh, empty) accumulator:
        adopt the saved dtype-ladder position, load the saved G, and
        restore the cursor bookkeeping. Geometry mismatches fail loudly —
        the conf fingerprint should have caught them already; this is the
        defense-in-depth shape check."""
        meta, G = checkpoint["meta"], checkpoint["G"]
        if meta["strategy"] != "dense":
            raise ValueError(
                f"checkpoint was written by the {meta['strategy']!r} "
                "strategy; this run resolved dense — the similarity "
                "strategy is part of the checkpoint geometry"
            )
        expect = (self.data_parallel, self.num_samples, self.num_samples)
        if tuple(G.shape) != expect:
            raise ValueError(
                f"checkpoint Gramian shape {tuple(G.shape)} != this run's "
                f"{expect} (cohort width or data-axis size changed)"
            )
        if meta["accum_dtype"] == "int32" and self.accum_dtype != jnp.int32:
            # The saved run had already climbed the dtype ladder; adopt
            # int32 before loading so the merge is exact by construction.
            self.operand_dtype, self.accum_dtype = np.int8, jnp.int32
        # range: checkpoint entries are exact integers within the saved
        # accumulator dtype (the GR005-proven invariant); casting to this
        # accumulator's (equal-or-wider) dtype is lossless.
        G = G.astype(np.dtype(self.accum_dtype))
        self.G = (
            device_put_global(G, self._g_sharding)
            if self._g_sharding is not None
            else jnp.asarray(G)
        )
        self._entry_bound = int(meta["entry_bound"])
        self.rows_seen = int(meta["rows_seen"])
        self._flushes = int(meta["flushes"])

    def finalize_device(self) -> jax.Array:
        """Reduce across the data axis (the one ``psum``); result stays on
        device. Downstream stages (centering, PCA) should consume this —
        a device→host round-trip of the N×N matrix is both pointless and,
        on remote-attached backends, poisons subsequent dispatch throughput
        (any device_get degrades later host→device traffic ~50×, measured)."""
        self._flush()
        self._in_flight.clear()  # release held buffers from the pipeline
        with self.telemetry.finalize_span():
            return data_axis_sum(self.G)

    def finalize(self) -> np.ndarray:
        """Host copy of :meth:`finalize_device` (tests / host backend)."""
        return np.asarray(jax.device_get(self.finalize_device())).astype(np.float64)


def _ring_tiles(G_local, X_cols, samples_axis: str, operand_dtype, packed=False):
    """One block's ring update, executed per device inside shard_map.

    ``G_local``: (N_local, N) — this device's row tile of the Gramian.
    ``X_cols``: this block's columns for this device's samples — ``(B,
    N_local)`` {0,1}/count uint8, or ``(B, N_local/8)`` bit-packed uint8
    when ``packed`` (np.packbits big-endian; ``N_local % 8 == 0``, the
    pack-width invariant ``parallel/mesh.py:padded_cohort`` guarantees).
    Packed tiles move ⅛ the bytes per ``ppermute`` and are unpacked on
    device per step (a cheap VPU shift-and-mask fused ahead of the dot).

    Double-buffered ring: the loop issues the ``ppermute`` for step k+1
    BEFORE the dot of step k consumes its tile, so the transfer and the
    matmul have no mutual dependency and XLA's async collectives overlap
    ICI with the MXU instead of alternating them; the last step's tile
    arrives while step D-2 computes and is consumed outside the loop — D-1
    permutes total (the old serialized loop paid D, one of them wasted on
    returning the tile to its owner).
    """
    D = axis_size(samples_axis)
    i = lax.axis_index(samples_axis)
    n_local = X_cols.shape[1] * 8 if packed else X_cols.shape[1]

    def unpack(tile):
        return _unpack_bits(tile, n_local) if packed else tile

    x_mine_t = unpack(X_cols).astype(operand_dtype).T  # (N_local, B)
    if packed:
        # Materialize the unpacked own-operand once: it feeds all D dots,
        # and without the barrier XLA re-fuses the unpack+cast into each
        # dot's operand producers (same rationale as _dense_update).
        x_mine_t = lax.optimization_barrier(x_mine_t)

    def dot_into(G, tile, k):
        j = (i + k) % D  # owner of `tile`'s sample columns
        t = jnp.matmul(
            x_mine_t, unpack(tile).astype(operand_dtype),
            preferred_element_type=G.dtype,
        )  # (N_local, N_local)
        # Explicit int32 indices: under enable_x64 the literal 0 would
        # otherwise promote to int64 and mismatch the axis-index dtype.
        # range: j < D and j * n_local < padded cohort width << 2^31.
        col = (j * n_local).astype(jnp.int32)
        zero = jnp.int32(0)
        return lax.dynamic_update_slice(
            G,
            lax.dynamic_slice(G, (zero, col), (n_local, n_local)) + t,
            (zero, col),
        )

    if D == 1:
        return dot_into(G_local, X_cols, 0)
    perm = [((p + 1) % D, p) for p in range(D)]

    def body(k, carry):
        G, cur = carry
        # Issue step k+1's transfer first; the dot below shares no data
        # dependency with it, so the ICI permute runs behind the matmul.
        nxt = lax.ppermute(cur, samples_axis, perm)
        return dot_into(G, cur, k), nxt

    G_local, last = lax.fori_loop(0, D - 1, body, (G_local, X_cols))
    return dot_into(G_local, last, D - 1)


def _hier_ring_tiles(
    G_local, X_cols, host_axis: str, device_axis: str, operand_dtype,
    packed=False,
):
    """One block's TWO-LEVEL ring update, executed per device inside
    shard_map — the pod-scale sibling of :func:`_ring_tiles`.

    The samples axis is factored host-major into ``hosts x devices``
    (``parallel/mesh.py:hierarchical_mesh``), so the inner ring's
    ``ppermute`` neighbors are intra-host (ICI) BY CONSTRUCTION and only
    the outer ring crosses hosts (DCN):

    - **inner ring** (per outer step): circulate the currently-held tile
      around the host's ``D`` devices over ICI, double-buffered exactly
      like the flat ring (permute for step j+1 issued before step j's dot);
    - **outer ring**: circulate each device's OWN tile around the ``H``
      hosts over DCN — issued BEFORE the inner ring consumes the current
      host block, so the slow DCN transfer overlaps a whole inner ring's
      ICI + MXU work, not one dot. Each host's columns cross DCN to every
      other host exactly once (``H - 1`` outer permutes), against the flat
      ring's ``S - 1`` lockstep steps each gated on its slowest edge.

    Total permutes stay ``S - 1`` (``(H-1) + H x (D-1)``) and total bytes
    stay ``ring_traffic_bytes``'s — the schedule moves the same data, it
    just proves which link every byte rides (``check/sched.py``). At the
    step (k, j) of the double loop this device holds the tile of device
    ``((h + k) mod H, (d + j) mod D)``; the flat owner index drives the
    same disjoint-slice accumulation the flat ring uses (one update per
    Gramian entry per pass — the two-radix form ``graftcheck ranges``
    proves disjoint).
    """
    H = axis_size(host_axis)
    D = axis_size(device_axis)
    h = lax.axis_index(host_axis)
    d = lax.axis_index(device_axis)
    n_local = X_cols.shape[1] * 8 if packed else X_cols.shape[1]

    def unpack(tile):
        return _unpack_bits(tile, n_local) if packed else tile

    x_mine_t = unpack(X_cols).astype(operand_dtype).T  # (N_local, B)
    if packed:
        # One materialization feeding all S dots (see _ring_tiles).
        x_mine_t = lax.optimization_barrier(x_mine_t)

    def dot_into(G, tile, k, j):
        # Owner of `tile`'s sample columns after k outer + j inner steps.
        owner = ((h + k) % H) * D + ((d + j) % D)
        t = jnp.matmul(
            x_mine_t, unpack(tile).astype(operand_dtype),
            preferred_element_type=G.dtype,
        )  # (N_local, N_local)
        # range: owner < H*D and owner * n_local < padded cohort << 2^31;
        # explicit int32 so x64 tracing cannot promote the slice indices.
        col = (owner * n_local).astype(jnp.int32)
        zero = jnp.int32(0)
        return lax.dynamic_update_slice(
            G,
            lax.dynamic_slice(G, (zero, col), (n_local, n_local)) + t,
            (zero, col),
        )

    perm_d = [((p + 1) % D, p) for p in range(D)]

    def inner_ring(G, block, k):
        if D == 1:
            return dot_into(G, block, k, 0)

        def body(j, carry):
            G, cur = carry
            # Step j+1's ICI transfer first; the dot shares no dependency.
            nxt = lax.ppermute(cur, device_axis, perm_d)
            return dot_into(G, cur, k, j), nxt

        G, last = lax.fori_loop(0, D - 1, body, (G, block))
        return dot_into(G, last, k, D - 1)

    if H == 1:
        # Degenerate topology: the two-level schedule IS the flat ring.
        return inner_ring(G_local, X_cols, 0)
    perm_h = [((p + 1) % H, p) for p in range(H)]

    def outer_body(k, carry):
        G, cur = carry
        # Host block k+1's DCN transfer is issued before the inner ring
        # consumes block k — the whole inner ring hides one DCN hop.
        nxt = lax.ppermute(cur, host_axis, perm_h)
        return inner_ring(G, cur, k), nxt

    G_local, last = lax.fori_loop(0, H - 1, outer_body, (G_local, X_cols))
    return inner_ring(G_local, last, H - 1)


def build_hierarchical_update(mesh, operand_dtype, packed: bool = False,
                              g_spec=None, x_spec=None):
    """The jitted two-level (ICI ring + DCN ring) Gramian update for a
    hierarchical ``data x hosts x samples`` mesh
    (``parallel/mesh.py:hierarchical_mesh``) — the runtime constructor the
    schedule prover (``check/sched.py``), the IR auditor, and the range
    prover all trace, exactly like :func:`build_sharded_update` for the
    flat ring. Works with a concrete ``Mesh`` or an ``AbstractMesh``.

    The default specs shard G rows (and X columns) over ``(hosts,
    samples)`` jointly — the SAME per-device layout as the flat ring's
    ``samples`` sharding over ``H x D`` devices, so a flat-ring
    accumulator can swap schedules without touching its staging,
    checkpoint, or finalize paths (byte-identical results, CI-asserted).
    """
    data_axis = DATA_AXIS if DATA_AXIS in mesh.shape else None
    if g_spec is None:
        g_spec = P(data_axis, (HOST_AXIS, SAMPLES_AXIS), None)
    if x_spec is None:
        x_spec = P(data_axis, None, (HOST_AXIS, SAMPLES_AXIS))

    @jax.jit
    def update(G, X):  # graftcheck: disable=GC005 -- same non-donation policy as build_sharded_update's update (measured ~10x throughput loss from donated-buffer serialization); graftcheck ir cross-checks this disable against the traced donated_invars (GI002).
        def per_slice(G_local, X_local):
            return _hier_ring_tiles(
                G_local[0], X_local[0], HOST_AXIS, SAMPLES_AXIS,
                operand_dtype, packed=packed,
            )[None]

        return shard_map(
            per_slice,
            mesh=mesh,
            in_specs=(g_spec, x_spec),
            out_specs=g_spec,
        )(G, X)

    return update


def build_sharded_update(mesh, operand_dtype, packed: bool = False,
                         g_spec=None, x_spec=None):
    """The jitted ring-exchange Gramian update for ``mesh``.

    ONE construction site shared by three callers so they can never drift:
    :class:`ShardedGramianAccumulator` (the runtime), the device-free plan
    validator (``check/plan.py``, over an ``AbstractMesh``), and the IR
    auditor (``check/ir.py``, which walks the traced jaxpr of exactly this
    function to prove the overlap/donation/dtype/traffic contracts). Works
    with a concrete ``Mesh`` or an ``AbstractMesh`` — nothing here touches
    a device.

    ``g_spec``/``x_spec`` default to the accumulator's shardings (data axis
    only when the mesh has one); pass them explicitly to match a
    pre-computed accumulator layout.
    """
    data_axis = DATA_AXIS if DATA_AXIS in mesh.shape else None
    if g_spec is None:
        g_spec = P(data_axis, SAMPLES_AXIS, None)
    if x_spec is None:
        x_spec = P(data_axis, None, SAMPLES_AXIS)

    @jax.jit
    def update(G, X):  # graftcheck: disable=GC005 -- same non-donation policy as _dense_update (measured ~10x throughput loss from donated-buffer serialization); the pipeline holds prior G references, which donation would invalidate. graftcheck ir cross-checks this disable against the traced donated_invars (GI002).
        def per_slice(G_local, X_local):
            # Leading data-axis dim is size 1 locally; drop it.
            return _ring_tiles(
                G_local[0], X_local[0], SAMPLES_AXIS, operand_dtype,
                packed=packed,
            )[None]

        return shard_map(
            per_slice,
            mesh=mesh,
            in_specs=(g_spec, x_spec),
            out_specs=g_spec,
        )(G, X)

    return update


class ShardedGramianAccumulator:
    """Sharded strategy: Gramian row-tiles over the ``samples`` axis, ring
    exchange per block, optional data-parallel axis on top.

    ``pack_bits`` selects the ring wire format (``--ring-pack-bits``):
    packed tiles move 8× fewer bytes per ``ppermute`` AND the host staging
    ships bit-packed (8× less host→device traffic); ``off`` keeps the
    unpacked uint8 wire as the bit-exact oracle. Count-valued blocks
    (same-set joins, entries > 1) cannot pack and transparently ride the
    unpacked kernel per flush — exactness never depends on the wire format.
    """

    def __init__(
        self,
        num_samples: int,
        mesh: Mesh,
        block_size: int = 1024,
        exact_int: bool = False,
        sync_every: int = 1,
        registry=None,
        spans=None,
        pack_bits: str = "auto",
        check_ranges: bool = False,
        reduce_schedule: str = "auto",
        hier_hosts: Optional[int] = None,
    ):
        self.telemetry = _AccumulatorTelemetry(registry, spans, "sharded")
        self.check_ranges = bool(check_ranges)
        self.sync_every = max(1, int(sync_every))
        self._flushes = 0
        if SAMPLES_AXIS not in mesh.shape:
            raise ValueError(f"mesh must have a {SAMPLES_AXIS!r} axis")
        self.mesh = mesh
        self.pack = resolve_ring_pack(pack_bits)
        self.samples_parallel = mesh.shape[SAMPLES_AXIS]
        self.data_parallel = mesh.shape.get(DATA_AXIS, 1)
        # --reduce-schedule: the flat ring, or the two-level hierarchical
        # schedule over the host-major factorization (auto = hier iff the
        # samples axis spans more than one host). Everything OUTSIDE the
        # update kernel — G, staging, checkpointing, finalize — is
        # schedule-independent: the hierarchical mesh shards the same rows
        # over the same devices in the same order, so swapping schedules
        # changes which links the tiles ride and nothing else
        # (byte-identical results, CI-asserted).
        resolve_reduce_schedule(reduce_schedule, 1)  # validate the spelling
        try:
            self.hier_hosts = resolve_hier_hosts(
                self.samples_parallel, hier_hosts
            )
        except ValueError:
            if reduce_schedule == "hier":
                raise  # an explicit hier request must not silently degrade
            # auto/flat: a non-dividing host factor just means no
            # hierarchical factorization exists — the flat ring runs.
            self.hier_hosts = 1
        self.reduce_schedule = resolve_reduce_schedule(
            reduce_schedule, self.hier_hosts
        )
        self._hier_mesh = (
            hierarchical_mesh(mesh, self.hier_hosts)
            if self.reduce_schedule == "hier"
            else None
        )
        # Cohort padding: a multiple of the samples axis (equal column tiles
        # per device) and, under the packed wire format, of 8× that (every
        # device's tile a whole number of bytes — the pack-width invariant).
        # Padded columns are all-zero and are trimmed in finalize().
        self._padded = padded_cohort(
            num_samples, self.samples_parallel, pack=self.pack
        )
        self.num_samples = int(num_samples)
        self.n_local = self._padded // self.samples_parallel
        self.block_size = int(block_size)
        self.exact_int = bool(exact_int)
        self.operand_dtype, self.accum_dtype = _operand_dtypes(exact_int, mesh)
        self._entry_bound = 0
        self.ring_bytes_total = 0

        rows = self.data_parallel * self.block_size
        self._staging = np.zeros((rows, self._padded), dtype=np.uint8)
        self._fill = 0
        self.rows_seen = 0

        data_axis = DATA_AXIS if DATA_AXIS in mesh.shape else None
        g_spec = P(data_axis, SAMPLES_AXIS, None)
        # One spec serves both wire formats: the packed block shards its
        # (padded/8)-wide byte dim over ``samples`` and the byte boundary
        # coincides with every shard boundary (pack-width invariant), so
        # each device's shard is exactly its own columns, packed.
        x_spec = P(data_axis, None, SAMPLES_AXIS)
        self._g_sharding = NamedSharding(mesh, g_spec)
        self._x_sharding = NamedSharding(mesh, x_spec)
        self.G = device_put_global(
            jnp.zeros(
                (self.data_parallel, self._padded, self._padded), self.accum_dtype
            ),
            self._g_sharding,
        )

        self._g_spec, self._x_spec = g_spec, x_spec
        self._update = self._build_update(self.operand_dtype)
        self._update_packed = (
            self._build_update(self.operand_dtype, packed=True)
            if self.pack
            else None
        )

    def _build_update(self, operand_dtype, packed: bool = False):
        if self._hier_mesh is not None:
            # The hierarchical specs name the factored axes; G/X keep their
            # flat-mesh shardings (identical device layout — the jit sees
            # the same HloSharding, so no reshard happens at the boundary).
            data_axis = (
                DATA_AXIS if DATA_AXIS in self._hier_mesh.shape else None
            )
            return build_hierarchical_update(
                self._hier_mesh,
                operand_dtype,
                packed,
                P(data_axis, (HOST_AXIS, SAMPLES_AXIS), None),
                P(data_axis, None, (HOST_AXIS, SAMPLES_AXIS)),
            )
        return build_sharded_update(
            self.mesh, operand_dtype, packed, self._g_spec, self._x_spec
        )

    def schedule_block(self) -> dict:
        """The run manifest's ``schedule`` block: which reduction schedule
        ran, its topology factorization, the STATIC per-flush projection of
        ring bytes next to the per-flush-accounted total — the
        predicted-vs-measured pair ``bench.py`` reports so BENCH rounds
        catch formula drift (a counts-fallback flush or a wire-format
        change moves ``measured`` away from ``predicted``)."""
        capacity_rows = self.data_parallel * self.block_size
        per_flush = ring_traffic_bytes(
            capacity_rows, self.samples_parallel, self.n_local, self.pack
        )
        predicted = per_flush * self._flushes
        if self.reduce_schedule == "hier":
            level = hierarchical_traffic_bytes(
                capacity_rows,
                self.hier_hosts,
                self.samples_parallel // self.hier_hosts,
                self.n_local,
                self.pack,
            )
            ici, dcn = (
                level.ici_bytes * self._flushes,
                level.dcn_bytes * self._flushes,
            )
        elif self.hier_hosts == 1:
            ici, dcn = predicted, 0
        else:
            # Flat ring spanning hosts: no byte is provably intra-host
            # (parallel/mesh.py:flat_traffic_split) — the GS001 premise.
            ici, dcn = 0, predicted
        return {
            "kind": self.reduce_schedule,
            "hosts": int(self.hier_hosts),
            "devices_per_host": int(
                self.samples_parallel // self.hier_hosts
            ),
            "predicted_ring_bytes": int(predicted),
            "measured_ring_bytes": int(self.ring_bytes_total),
            "predicted_ici_bytes": int(ici),
            "predicted_dcn_bytes": int(dcn),
        }

    def add_rows(self, rows: np.ndarray) -> None:
        rows = np.asarray(rows, dtype=np.uint8)
        if rows.ndim != 2 or rows.shape[1] != self.num_samples:
            raise ValueError(
                f"expected (b, {self.num_samples}) rows, got {rows.shape}"
            )
        self.rows_seen += rows.shape[0]
        offset = 0
        capacity = self._staging.shape[0]
        while offset < rows.shape[0]:
            take = min(capacity - self._fill, rows.shape[0] - offset)
            self._staging[
                self._fill : self._fill + take, : self.num_samples
            ] = rows[offset : offset + take]
            self._fill += take
            offset += take
            if self._fill == capacity:
                self._flush()

    def _flush(self) -> None:
        if self._fill == 0:
            return
        flush_rows, flush_start = self._fill, time.perf_counter()
        block = self._staging
        if self._fill < block.shape[0]:
            block = block.copy()
            block[self._fill :] = 0
        max_count = int(block.max(initial=0))
        # Same shared projection formula as the dense path (GR005).
        next_bound = self._entry_bound + flush_entry_increment(
            self._fill, max_count
        )
        if _maybe_switch_accumulator(
            self, next_bound, out_shardings=self._g_sharding
        ):
            # The scanned update closes over the operand dtype — rebuild it.
            self._update = self._build_update(self.operand_dtype)
            if self.pack:
                self._update_packed = self._build_update(
                    self.operand_dtype, packed=True
                )
        self._entry_bound = next_bound
        X = block.reshape(self.data_parallel, self.block_size, self._padded)
        # Count-valued rows (same-set joins) cannot bit-pack; they ride the
        # unpacked kernel for this flush — same geometry, same result.
        use_packed = self.pack and max_count <= 1
        if use_packed:
            # Host staging ships packed: ⅛ the host→device bytes, and the
            # ring circulates the packed tiles as-is (np.packbits allocates
            # fresh, so the reused staging buffer is never in flight).
            Xd = device_put_global(np.packbits(X, axis=-1), self._x_sharding)
            self.G = self._update_packed(self.G, Xd)
        else:
            self.G = self._update(self.G, device_put_global(X, self._x_sharding))
        self._fill = 0
        self._flushes += 1
        if self._flushes % self.sync_every == 0:
            jax.block_until_ready(self.G)
        if self.check_ranges:
            self.telemetry.record_entry_sample(self.G, self._entry_bound)
        flush_seconds = time.perf_counter() - flush_start
        flush_ring_bytes = ring_traffic_bytes(
            self.data_parallel * self.block_size,
            self.samples_parallel,
            self.n_local,
            use_packed,
        )
        self.ring_bytes_total += flush_ring_bytes
        self.telemetry.record_ring(flush_ring_bytes, flush_seconds)
        self.telemetry.record_flush(flush_rows, flush_seconds, 0)

    def snapshot_state(self) -> dict:
        """Checkpoint state of the sharded strategy: the row-tile-sharded
        (padded) partial Gramian fetched whole, plus the dtype-ladder
        position and cursor — see ``GramianAccumulator.snapshot_state``."""
        self._flush()
        jax.block_until_ready(self.G)  # graftcheck: disable=GC001 -- deliberate checkpoint barrier at --checkpoint-every-sites cadence (see the dense accumulator's snapshot_state)
        G_host = np.asarray(jax.device_get(self.G))  # graftcheck: disable=GC001 -- deliberate periodic checkpoint fetch (the artifact payload), not a hot-path sync
        return {
            "strategy": "sharded",
            "G": G_host,
            "accum_dtype": np.dtype(self.accum_dtype).name,
            "exact_int": self.exact_int,
            "entry_bound": self._entry_bound,
            "rows_seen": self.rows_seen,
            "flushes": self._flushes,
            "num_samples": self.num_samples,
            "data_parallel": self.data_parallel,
            "padded": self._padded,
            # Ring accounting rides along so a resumed run's manifest
            # schedule block keeps predicted == measured (both count the
            # pre-crash flushes); absent in old artifacts -> 0.
            "ring_bytes_total": self.ring_bytes_total,
        }

    def restore_state(self, checkpoint: dict) -> None:
        """Sharded counterpart of ``GramianAccumulator.restore_state``:
        shape/strategy checks, dtype-ladder adoption (including the
        dtype-closed ring update rebuild), then the sharded device load."""
        meta, G = checkpoint["meta"], checkpoint["G"]
        if meta["strategy"] != "sharded":
            raise ValueError(
                f"checkpoint was written by the {meta['strategy']!r} "
                "strategy; this run resolved sharded — the similarity "
                "strategy is part of the checkpoint geometry"
            )
        expect = (self.data_parallel, self._padded, self._padded)
        if tuple(G.shape) != expect:
            raise ValueError(
                f"checkpoint Gramian shape {tuple(G.shape)} != this run's "
                f"{expect} (cohort width, padding, mesh data axis, or the "
                "samples-axis tile count changed)"
            )
        if meta["accum_dtype"] == "int32" and self.accum_dtype != jnp.int32:
            self.operand_dtype, self.accum_dtype = np.int8, jnp.int32
            # The scanned updates close over the operand dtype — rebuild.
            self._update = self._build_update(self.operand_dtype)
            if self.pack:
                self._update_packed = self._build_update(
                    self.operand_dtype, packed=True
                )
        # range: checkpoint entries are exact integers within the saved
        # dtype (GR005 invariant); the equal-or-wider target is lossless.
        G = G.astype(np.dtype(self.accum_dtype))
        self.G = device_put_global(G, self._g_sharding)
        self._entry_bound = int(meta["entry_bound"])
        self.rows_seen = int(meta["rows_seen"])
        self._flushes = int(meta["flushes"])
        self.ring_bytes_total = int(meta.get("ring_bytes_total", 0))

    def finalize(self) -> np.ndarray:
        self._flush()
        with self.telemetry.finalize_span():
            total = data_axis_sum(self.G)
        full = np.asarray(jax.device_get(total)).astype(np.float64)
        return full[: self.num_samples, : self.num_samples]

    def finalize_device_padded(self) -> jax.Array:
        """Device-resident reduce over the data axis; includes cohort padding
        columns/rows (all zero). See :meth:`finalize_sharded` for the
        samples-sharded variant."""
        self._flush()
        with self.telemetry.finalize_span():
            return data_axis_sum(self.G)

    def finalize_sharded(self) -> jax.Array:
        """Device-resident finalize: (padded N, padded N) row-sharded over
        ``samples`` — for cohorts where the host copy is undesirable."""
        self._flush()
        with self.telemetry.finalize_span():
            return data_axis_sum(
                self.G,
                out_shardings=NamedSharding(self.mesh, P(SAMPLES_AXIS, None)),
            )


def accumulate_index_rows(
    acc,
    call_rows,
    num_columns: int,
    block_size: int,
    accumulate_duplicates: bool = False,
) -> None:
    """Stage per-variant column-index rows into dense uint8 blocks and feed
    an accumulator — the one shared row-staging loop (driver and public API).

    ``accumulate_duplicates`` switches to unbuffered accumulation so a column
    appearing k times contributes k² per entry (the reference's pair-loop
    multiplicity, ``VariantsPca.scala:224-229`` — needed when a variant set
    is joined with itself); the default fast path sets membership bits.
    """
    staging: list = []

    def flush():
        if not staging:
            return
        rows = np.zeros((len(staging), num_columns), dtype=np.uint8)
        for i, row in enumerate(staging):
            if accumulate_duplicates:
                np.add.at(rows[i], np.asarray(list(row), dtype=np.int64), 1)
            else:
                rows[i, list(row)] = 1
        acc.add_rows(rows)
        staging.clear()

    for row in call_rows:
        staging.append(row)
        if len(staging) >= block_size:
            flush()
    flush()


def gramian_reference(rows: np.ndarray) -> np.ndarray:
    """Host NumPy oracle: the pair-counting semantics of
    ``VariantsPca.scala:224-229`` (for each variant, +1 for every ordered
    pair of varying samples), vectorized."""
    X = np.asarray(rows, dtype=np.int64)
    return X.T @ X


__all__ = [
    "GramianAccumulator",
    "ShardedGramianAccumulator",
    "build_hierarchical_update",
    "build_sharded_update",
    "data_axis_sum",
    "gramian_reference",
    "resolve_ring_pack",
]

"""Device ops for the read analyses.

The reference computes per-base depth and base frequencies with flatMap +
``reduceByKey``/``groupByKey`` shuffles over (position, x) pairs
(``SearchReadsExample.scala:140-167, 219-244``). On TPU these are
scatter-adds into a dense coordinate window: each read contributes its
``read_length`` positions via one ``.at[].add`` (XLA scatter), vectorized
over all reads of a shard — no shuffle, no per-position records.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

#: Fixed base vocabulary for frequency analyses.
BASES = "ACGT"
_BASE_CODE = {c: i for i, c in enumerate(BASES)}


def encode_bases(sequence: str) -> list:
    """Base chars → codes (unknown bases → -1, excluded from counts)."""
    return [_BASE_CODE.get(c, -1) for c in sequence]


@functools.partial(jax.jit, static_argnames=("window_size", "max_read_length"))
def depth_counts(
    positions: jax.Array,  # (R,) int32 — read start positions
    lengths: jax.Array,  # (R,) int32 — aligned-sequence lengths
    window_start: jax.Array,  # scalar int32
    window_size: int,
    max_read_length: int = 256,
) -> jax.Array:
    """Per-base read depth over a window (``SearchReadsExample.scala:153-162``).

    Each read covers positions ``[position, position + length)``; counts land
    in a dense (window_size,) int32 vector.
    """
    rel = positions - window_start
    offsets = jnp.arange(max_read_length, dtype=jnp.int32)
    idx = rel[:, None] + offsets[None, :]  # (R, L)
    valid = (
        (offsets[None, :] < lengths[:, None])
        & (idx >= 0)
        & (idx < window_size)
    )
    idx = jnp.clip(idx, 0, window_size - 1)
    # range: valid is a bool mask — {0,1} increments, exact in int32 up to
    # 2^31-1 overlapping reads per position.
    return (
        jnp.zeros((window_size,), jnp.int32)
        .at[idx.ravel()]
        .add(valid.ravel().astype(jnp.int32))
    )


@functools.partial(jax.jit, static_argnames=("window_size",))
def base_counts(
    positions: jax.Array,  # (R,) int32 — read start positions
    base_codes: jax.Array,  # (R, L) int8 — encoded bases, -1 = unknown
    quality_ok: jax.Array,  # (R, L) bool — base-quality >= threshold
    window_start: jax.Array,
    window_size: int,
) -> jax.Array:
    """Per-position per-base counts (``SearchReadsExample.scala:223-243``).

    Returns (window_size, 4) int32; callers derive frequencies by dividing by
    the per-position total, matching the reference's groupBy/length ratio.
    """
    R, L = base_codes.shape
    rel = positions - window_start
    offsets = jnp.arange(L, dtype=jnp.int32)
    idx = rel[:, None] + offsets[None, :]
    valid = (
        quality_ok
        & (base_codes >= 0)
        & (idx >= 0)
        & (idx < window_size)
    )
    idx = jnp.clip(idx, 0, window_size - 1)
    codes = jnp.clip(base_codes, 0, 3)
    # range: codes are clipped to [0,3] and valid is a {0,1} bool mask —
    # both exact in int32 (counts bounded by reads per position < 2^31).
    return (
        jnp.zeros((window_size, len(BASES)), jnp.int32)
        .at[idx.ravel(), codes.ravel().astype(jnp.int32)]
        .add(valid.ravel().astype(jnp.int32))
    )


def frequent_bases(counts: jax.Array, min_freq: float) -> Tuple[jax.Array, jax.Array]:
    """Per-position base sets with frequency ≥ min_freq
    (``SearchReadsExample.scala:282-291``).

    Returns ``(mask (W, 4) bool, covered (W,) bool)``; the caller renders the
    sorted base strings host-side.
    """
    totals = counts.sum(axis=1, keepdims=True)
    freq = counts / jnp.maximum(totals, 1)
    return (freq >= min_freq) & (totals > 0), (totals[:, 0] > 0)


__all__ = ["BASES", "encode_bases", "depth_counts", "base_counts", "frequent_bases"]

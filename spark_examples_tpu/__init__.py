"""spark_examples_tpu — a TPU-native genomics analytics framework.

A brand-new framework with the capabilities of ``googlegenomics/spark-examples``
(reference at /root/reference), redesigned TPU-first on JAX/XLA:

- Distributed datasets of genomic variants and reads streamed from a paginated
  genomics source with contig-range sharding (reference: ``rdd/VariantsRDD.scala``,
  ``rdd/ReadsRDD.scala``).
- The seven example analyses: Klotho / BRCA1 variant counting, pileup, mean
  coverage, per-base depth, tumor/normal base-frequency comparison (reference:
  ``SearchVariantsExample.scala``, ``SearchReadsExample.scala``).
- The flagship 1000 Genomes PCoA pipeline (reference: ``VariantsPca.scala``):
  genotype → similarity (Gramian) → Gower double-centering → eigendecomposition,
  rebuilt as blockwise ``G += XᵀX`` on the MXU with ``psum`` over ICI replacing
  Spark's shuffle and ``jnp.linalg.eigh`` replacing Breeze/MLlib.

Package layout:

- ``models``    — serializable Variant/Call/Read data models + builders
- ``sharding``  — contig windows, split policies, partitioners
- ``sources``   — genomics backends (synthetic, REST, local VCF/JSONL/SAM
  files with bounded-memory streaming) + client counters
- ``parallel``  — device mesh construction and the Spark-shuffle → XLA-collective mapping
- ``ops``       — device compute: gramian, centering, pca, read depth
- ``pipeline``  — datasets, stats, PCA driver, checkpointing
- ``analyses``  — the seven reference example analyses
- ``utils``     — murmur3 hashing, AF-filter arithmetic, tracing
- ``api``       — the composable public pipeline (prepare → similarity →
  center → pca), mirroring ``src/main/python/variants_pca.py:19-152``
"""

__version__ = "0.12.0"

# jax-version shims (shard_map location, jax.enable_x64) — imported first so
# every submodule and test sees one resolved API surface. jax itself is
# already resident in this image (the sitecustomize PJRT hook imports it at
# interpreter start), so this adds no import weight.
from spark_examples_tpu.utils import compat as _compat  # noqa: F401

from spark_examples_tpu.models.variant import Call, Variant, VariantKey, VariantsBuilder
from spark_examples_tpu.models.read import Read, ReadKey, ReadBuilder
from spark_examples_tpu.sharding.contig import Contig, SexChromosomeFilter
from spark_examples_tpu.sharding.partitioners import (
    FixedSplits,
    ReadsPartitioner,
    TargetSizeSplits,
    VariantsPartitioner,
)

__all__ = [
    "Call",
    "Variant",
    "VariantKey",
    "VariantsBuilder",
    "Read",
    "ReadKey",
    "ReadBuilder",
    "Contig",
    "SexChromosomeFilter",
    "VariantsPartitioner",
    "ReadsPartitioner",
    "FixedSplits",
    "TargetSizeSplits",
]

"""Fused batch execution: one device program for a whole batch group.

``serve/executor.py`` runs a continuous-batching group (jobs sharing a
``batch_compile_fingerprint``) through this runner instead of
back-to-back ``run_pipeline`` calls: every job becomes one lane of a
:class:`~spark_examples_tpu.ops.batched.StackedJobsAccumulator`, the
whole group accumulates through ONE ``(K, N, N)`` device program (one
dispatch and one reduction per step for K jobs), and each job's Gramian
is sliced out of the stacked accumulator — byte-identical to its serial
run, see ``ops/batched.py`` for the identity argument. Everything after
the slice IS the serial epilogue, reused verbatim: the same
``compute_pca``/``_summarize_similarity``, the same printed result rows,
the same warm-ledger recording, the same schema-v2 manifest built from
the same per-driver registry — a fused job's artifacts are
indistinguishable from a serial job's except for the additive
``fused_size`` stamp the daemon adds to its envelope.

Two-phase contract the executor relies on:

- :func:`preflight_fused` is SIDE-EFFECT FREE (no prints, no device
  work, no files). It raises :class:`FusedIneligible` for any group the
  stacked program cannot carry — mixed kinds, non-synthetic sources,
  sharded strategies, mismatched cohort geometry, a dtype-ladder risk,
  or a jobs axis past the HBM cap — and the caller falls back to serial
  execution with nothing to undo.
- :func:`run_fused_pipeline` then runs an eligible group to completion.
  Per-job output (driver banner, result rows, epilogue, manifest
  notice) is routed through the caller's ``stdout_factory`` so each
  job's prints land in its own log exactly as the serial executor
  routes them; the interleaved accumulation phase prints nothing
  per-job by construction.
"""

from __future__ import annotations

import contextlib
import io
from typing import Callable, ContextManager, List, Optional, Sequence

import numpy as np

from spark_examples_tpu.config import PcaConf
from spark_examples_tpu.ops.batched import (
    FusedIneligible,
    StackedJobsAccumulator,
    max_fused_jobs,
)
from spark_examples_tpu.ops.contracts import EXACT_F32_LIMIT
from spark_examples_tpu.pipeline.pca_driver import (
    PipelineResult,
    VariantsPcaDriver,
    _export_compile_cache_gauges,
    _register_prover_conformance,
    _summarize_similarity,
    _sync_scalar,
    jax_default_device,
    make_source,
)
from spark_examples_tpu.sharding.partitioners import VariantsPartitioner
from spark_examples_tpu.sources import partition_page_requests

#: The only request kinds with a stacked device program. ``grm`` finalizes
#: through a different kernel family and stays serial.
FUSABLE_KINDS = ("pca", "similarity")


def _check(condition: bool, reason: str) -> None:
    if not condition:
        raise FusedIneligible(reason)


def preflight_fused(
    confs: Sequence[PcaConf],
    kinds: Sequence[str],
    device_bytes: Optional[int] = None,
) -> int:
    """Prove a group can ride ONE stacked device program, or raise
    :class:`FusedIneligible` — before any side effect, so serial fallback
    has nothing to undo. Returns the group size K.

    The checks mirror the stacked accumulator's contract: one kind, the
    packed synthetic ingest for every lane (the only stream whose blocks
    are pure functions of the conf — file/REST lanes would interleave
    I/O nondeterministically), identical cohort geometry (the stacked
    buffer has ONE (K, N, N) shape), the dense strategy (a sharded lane
    has no N×N slice to stack), no per-lane stateful machinery
    (checkpoints, fault plans, range telemetry), a dtype ladder that
    provably never climbs mid-stream, and K inside the HBM cap."""
    k = len(confs)
    _check(k >= 1, "empty group")
    _check(
        len(kinds) == k, f"{k} confs but {len(kinds)} kinds"
    )
    distinct = sorted(set(kinds))
    _check(
        len(distinct) == 1,
        f"mixed-kind group {distinct}: one stacked program serves one "
        "kind",
    )
    _check(
        distinct[0] in FUSABLE_KINDS,
        f"kind {distinct[0]!r} has no stacked device program",
    )
    base = confs[0]
    for conf in confs:
        _check(
            conf.source == "synthetic",
            f"source {conf.source!r}: only the synthetic packed stream "
            "is a pure function of the conf",
        )
        _check(not conf.input_path, "--input-path resumes are serial")
        _check(
            conf.pca_backend == "tpu",
            f"--pca-backend {conf.pca_backend!r} has no device program",
        )
        _check(
            len(conf.variant_set_id) == 1,
            "packed lanes need a single variant set",
        )
        _check(
            getattr(conf, "num_samples_per_set", None) is None,
            "per-set cohort sizes change the lane width",
        )
        _check(
            conf.ingest in ("auto", "packed"),
            f"--ingest {conf.ingest!r} is not the packed lane stream",
        )
        _check(
            getattr(conf, "similarity_strategy", "auto") != "sharded",
            "sharded lanes have no dense N×N slice to stack",
        )
        _check(
            not getattr(conf, "save_variants", False),
            "--save-variants needs the wire ingest",
        )
        _check(
            not getattr(conf, "check_ranges", False),
            "--check-ranges telemetry is per-accumulator",
        )
        _check(
            not getattr(conf, "gramian_checkpoint_dir", None)
            and not getattr(conf, "resume_from", None),
            "Gramian checkpointing cursors are per-accumulator",
        )
        _check(
            getattr(conf, "fault_plan", None) is None,
            "a fault plan must fire inside its own job only",
        )
        _check(
            conf.num_samples == base.num_samples,
            f"cohort width {conf.num_samples} != {base.num_samples}: "
            "the stacked buffer has one sample axis",
        )
        _check(
            conf.block_size == base.block_size,
            "lane staging needs one block size",
        )
        _check(
            bool(getattr(conf, "exact_similarity", False))
            == bool(getattr(base, "exact_similarity", False)),
            "mixed dtype ladders cannot share the stacked buffer",
        )
    from spark_examples_tpu.ops.gramian import dense_strategy_fits

    _check(
        dense_strategy_fits(base.num_samples),
        f"cohort {base.num_samples} is past the dense HBM rule "
        "(sharded lanes cannot stack)",
    )
    if not getattr(base, "exact_similarity", False):
        # The serial accumulator climbs to int32 when a lane's projected
        # per-entry count could leave f32's exact window — a per-lane
        # event one stacked buffer cannot carry. Bound each lane's total
        # rows from the declared synthetic site grid (exact for the
        # synthetic source; flush increments are rows × 1² for {0,1}
        # operands), silently: preflight must not print.
        for conf in confs:
            source = make_source(conf)
            with contextlib.redirect_stdout(io.StringIO()):
                contigs = conf.get_contigs(source, conf.variant_set_id)
            total_sites = sum(source.declared_sites(c) for c in contigs)
            _check(
                total_sites <= EXACT_F32_LIMIT,
                f"{total_sites} projected sites could climb the dtype "
                f"ladder mid-stream (f32 exact window {EXACT_F32_LIMIT})",
            )
    cap = max_fused_jobs(base.num_samples, device_bytes=device_bytes)
    _check(
        k <= cap,
        f"group of {k} exceeds max_fused_jobs={cap} for "
        f"N={base.num_samples} (stacked HBM charge is K× per-job)",
    )
    return k


def _lane_stream(conf: PcaConf, driver: VariantsPcaDriver):
    """One job's packed block stream, verbatim the serial packed branch of
    ``pca_driver._similarity_stage`` (same partition order, same io_stats
    accounting, same progress gauges) — the lane feeds the stacked
    accumulator the identical blocks its serial run would stage."""
    from spark_examples_tpu.obs.metrics import (
        INGEST_PARTITIONS_DONE,
        INGEST_PARTITIONS_PLANNED,
        well_known_gauge,
    )

    source = driver.source
    contigs = driver._host_contigs(
        conf.get_contigs(source, conf.variant_set_id)
    )
    partitioner = VariantsPartitioner(contigs, conf.bases_per_partition)
    partitions = partitioner.get_partitions(conf.variant_set_id[0])
    well_known_gauge(driver.registry, INGEST_PARTITIONS_PLANNED).set(
        len(partitions)
    )
    done_gauge = well_known_gauge(driver.registry, INGEST_PARTITIONS_DONE)

    def blocks():
        for index, part in enumerate(partitions):
            if driver.io_stats is not None:
                driver.io_stats.add_partition(part.range)
                driver.io_stats.add_requests(
                    partition_page_requests(
                        source,
                        part.variant_set_id,
                        part.contig,
                        conf.bases_per_partition,
                    )
                )
            window_variants = 0
            for block in source.genotype_blocks(
                part.variant_set_id,
                part.contig,
                block_size=conf.block_size,
                min_allele_frequency=conf.min_allele_frequency,
            ):
                window_variants += len(block["positions"])
                yield block["has_variation"]
            if driver.io_stats is not None:
                driver.io_stats.add_variants(window_variants)
            done_gauge.set(index + 1)

    return blocks()


def run_fused_pipeline(
    confs: Sequence[PcaConf],
    kinds: Sequence[str],
    devices=None,
    stdout_factory: Optional[Callable[[int], ContextManager]] = None,
) -> List[PipelineResult]:
    """Run an eligible group as ONE stacked device program; one
    :class:`PipelineResult` per job, in group order, each byte-identical
    to the serial ``run_pipeline`` result for the same conf.

    ``stdout_factory(j)`` returns a context manager routing prints to job
    j's log; per-job phases (driver construction, result emission,
    manifest notice) run inside it. The interleaved accumulation phase
    runs outside any job context and prints nothing."""
    from spark_examples_tpu.obs.manifest import (
        build_run_manifest,
        write_manifest,
    )
    from spark_examples_tpu.utils.cache import (
        batch_compile_fingerprint,
        compile_fingerprint,
        fused_group_fingerprint,
        record_geometry,
    )
    from spark_examples_tpu.utils.tracing import StageTimes

    k = preflight_fused(confs, kinds)
    job_stdout = stdout_factory or (lambda j: contextlib.nullcontext())
    kind = kinds[0]
    similarity_only = kind == "similarity"
    placement = (
        jax_default_device(devices[0]) if devices else contextlib.nullcontext()
    )
    results: List[PipelineResult] = []
    with placement:
        drivers: List[VariantsPcaDriver] = []
        times: List[StageTimes] = []
        for j, conf in enumerate(confs):
            with job_stdout(j):
                # The serial preamble, per lane: contig banner + driver
                # construction ("Matrix size: N.") print into job j's log.
                driver = VariantsPcaDriver(conf, devices=devices)
                _export_compile_cache_gauges(driver.registry)
                drivers.append(driver)
                times.append(StageTimes(recorder=driver.spans))
        n = len(drivers[0].indexes)
        for driver in drivers:
            if len(driver.indexes) != n:
                raise FusedIneligible(
                    f"lane cohort width {len(driver.indexes)} != {n}"
                )
        acc = StackedJobsAccumulator(
            k,
            n,
            block_size=confs[0].block_size,
            exact_int=bool(getattr(confs[0], "exact_similarity", False)),
            pipeline_depth=2,
        )
        with contextlib.ExitStack() as stack:
            # Every job's ingest+similarity stage spans the shared
            # accumulation — the honest wall-clock of a fused lane IS the
            # group's wall (that is the throughput win: K lanes, one
            # wall). The spans land in each driver's own recorder, so
            # each manifest still carries its own stage tree.
            for j in range(k):
                stack.enter_context(times[j].stage("ingest+similarity"))
            streams = [
                _lane_stream(confs[j], drivers[j]) for j in range(k)
            ]
            # Lockstep round-robin: one block per live lane per round
            # keeps every lane's pending depth O(1), so host memory stays
            # O(K × block) — the bounded-ingest contract, fused.
            live = list(range(k))
            while live:
                for j in list(live):
                    block = next(streams[j], None)
                    if block is None:
                        acc.finish_lane(j)
                        live.remove(j)
                    else:
                        acc.add_rows(j, np.asarray(block, dtype=np.uint8))
            G_stack = acc.finalize()
            import jax

            jax.block_until_ready(G_stack)
        # Warm the fused-group geometry once per group: the K-lane
        # stacked program is its own compile geometry, keyed off the
        # group's shared batch fingerprint.
        record_geometry(
            fused_group_fingerprint(
                batch_compile_fingerprint(confs[0], kind=kind), k
            )
        )
        for j, (conf, driver) in enumerate(zip(confs, drivers)):
            with job_stdout(j):
                similarity = acc.job_slice(j)
                _sync_scalar(similarity)
                similarity_summary = None
                result = None
                if similarity_only:
                    similarity_summary = _summarize_similarity(similarity, n)
                else:
                    with times[j].stage("center+pca"):
                        result = driver.compute_pca(similarity)
                # The serial epilogue, verbatim (run_pipeline's tail):
                # warm ledger, conformance snapshot, printed rows, stats,
                # manifest — same order, same prints, same artifacts.
                record_geometry(compile_fingerprint(conf, kind=kind))
                _register_prover_conformance(driver)
                lines = (
                    driver.emit_result(result) if result is not None else []
                )
                driver.report_io_stats()
                manifest_doc = None
                manifest_path = None
                if getattr(conf, "metrics_json", None):
                    manifest_doc = build_run_manifest(
                        conf=conf,
                        spans=driver.spans,
                        registry=driver.registry,
                        io_stats=driver.io_stats,
                        overlap=driver._overlap,
                    )
                    try:
                        write_manifest(conf.metrics_json, manifest_doc)
                    except OSError as e:
                        import sys

                        print(
                            f"Run manifest NOT written to "
                            f"{conf.metrics_json}: {e}",
                            file=sys.stderr,
                        )
                    else:
                        manifest_path = conf.metrics_json
                        print(
                            f"Run manifest written to {conf.metrics_json}."
                        )
                driver.stop()
                results.append(
                    PipelineResult(
                        lines=lines,
                        similarity_summary=similarity_summary,
                        manifest=manifest_doc,
                        manifest_path=manifest_path,
                    )
                )
    return results


__all__ = [
    "FUSABLE_KINDS",
    "FusedIneligible",
    "preflight_fused",
    "run_fused_pipeline",
]

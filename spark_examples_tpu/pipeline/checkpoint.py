"""Checkpoint / resume of materialized variants.

The reference can resume from pre-materialized variants:
``--input-path`` makes ``getData`` read ``sc.objectFile[(VariantKey, Variant)]``
instead of hitting the API (``VariantsPca.scala:112-113``), with stats
disabled (``:332-335``) — but no writer for that format exists in the repo.
Here both sides exist: :func:`save_variants` writes sharded gzip JSON-lines
part files with a manifest, :func:`load_variants` streams them back as a
dataset with the same iteration surface as ``VariantsDataset``.

Both sides move data through FIXED-SIZE buffers (``graftcheck hostmem``
audits this file): the writer coalesces encoded lines into a bounded text
buffer between ``write()`` calls (artifact bytes are identical to the
per-record writes — gzip's compressor state only flushes at close), and
the reader (:meth:`CheckpointDataset.iter_part` / ``__iter__``) walks each
part in ``_READ_CHUNK_BYTES`` decompressed windows with a partial-line
carry, so resuming never stages a whole part — let alone the whole
checkpoint — as one buffer. Only :meth:`CheckpointDataset.compute` still
materializes (one shard's record list, the ``VariantsDataset`` API
surface), and that site is a declared ``hostmem(unbounded)``.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Iterable, Iterator, List, Tuple

from spark_examples_tpu.models.variant import Variant, VariantKey, VariantsBuilder

_MANIFEST = "_manifest.json"

#: Writer-side coalescing buffer: encoded lines accumulate to ~this many
#: characters between ``write()`` calls (bounded by one record past it).
_WRITE_BUFFER_BYTES = 1 << 20

#: Reader-side window: decompressed bytes per chunk of a part-file walk.
_READ_CHUNK_BYTES = 4 << 20


def _iter_jsonl_lines(path: str, chunk_bytes: int = _READ_CHUNK_BYTES):
    """Decoded JSON objects of one gzip JSON-lines file, streamed through a
    fixed-size read window with a partial-line carry (the checkpoint-side
    sibling of ``sources/files.py:_iter_vcf_chunks``): peak memory is
    O(window), never O(part)."""
    carry = b""
    with gzip.open(path, "rb") as f:
        while True:
            data = f.read(max(64, int(chunk_bytes)))
            if not data:
                break
            data = carry + data
            cut = data.rfind(b"\n")
            if cut < 0:
                carry = data
                continue
            carry = data[cut + 1 :]
            for line in data[: cut + 1].splitlines():
                if line.strip():
                    yield json.loads(line)
    if carry.strip():
        yield json.loads(carry)


class CheckpointWriter:
    """Incremental checkpoint writer: one gzip JSON-lines part file per
    shard as it streams, the manifest only on :meth:`close` — an abandoned
    (partially written) checkpoint has no manifest and fails loudly on
    load instead of silently resuming a truncated cohort.

    Records are the wire-format JSON of ``Variant.to_json`` plus the raw
    partition key, so the round trip preserves both members of the
    ``(VariantKey, Variant)`` pair the reference's objectFile held.
    """

    def __init__(self, path: str):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.total = 0
        self.parts = 0

    def write_shard(self, records: List[Tuple[VariantKey, Variant]]) -> None:
        part_path = os.path.join(self.path, f"part-{self.parts:05d}.jsonl.gz")
        with gzip.open(part_path, "wt") as f:
            # Fixed-size coalescing buffer: one write() per ~_WRITE_BUFFER_
            # BYTES of encoded text instead of one per record. The artifact
            # is byte-identical to per-record writes (gzip's compressor
            # only emits at its own block boundaries and at close; the
            # round-trip regression test asserts this), but the host never
            # holds more than one buffer of encoded lines beyond the
            # records the caller already owns.
            buffer: List[str] = []
            buffered = 0
            for key, variant in records:
                entry = {
                    "key": {"contig": key.contig, "position": key.position},
                    "variant": variant.to_json(),
                }
                line = json.dumps(entry) + "\n"
                buffer.append(line)
                buffered += len(line)
                self.total += 1
                if buffered >= _WRITE_BUFFER_BYTES:
                    f.write("".join(buffer))
                    buffer.clear()
                    buffered = 0
            if buffer:
                f.write("".join(buffer))
        self.parts += 1

    def close(self) -> None:
        with open(os.path.join(self.path, _MANIFEST), "w") as f:
            json.dump(
                {
                    "parts": self.parts,
                    "records": self.total,
                    "format": "jsonl.gz/v1",
                },
                f,
            )


def save_variants(
    path: str,
    shards: Iterable[List[Tuple[VariantKey, Variant]]],
) -> int:
    """Write one part file per shard (consumed lazily); returns the record
    count. The driver's streaming save (``--save-variants``) uses
    :class:`CheckpointWriter` directly to interleave writing with the
    analysis pass."""
    writer = CheckpointWriter(path)
    for records in shards:
        writer.write_shard(records)
    writer.close()
    return writer.total


class CheckpointDataset:
    """Reader with the ``VariantsDataset`` iteration surface."""

    def __init__(self, path: str):
        self.path = path
        manifest_path = os.path.join(path, _MANIFEST)
        with open(manifest_path) as f:
            self.manifest = json.load(f)

    def partitions(self) -> List[str]:
        return [
            os.path.join(self.path, name)
            for name in sorted(os.listdir(self.path))
            if name.startswith("part-")
        ]

    def iter_part(self, part_path: str) -> Iterator[Tuple[VariantKey, Variant]]:
        """Stream one part's ``(key, variant)`` pairs through the bounded
        read window — the resume path that never stages a whole part."""
        for entry in _iter_jsonl_lines(part_path):
            built = VariantsBuilder.build(entry["variant"])
            if built is None:
                continue
            key = VariantKey(
                entry["key"]["contig"], int(entry["key"]["position"])
            )
            yield key, built[1]

    def compute(self, part_path: str) -> List[Tuple[VariantKey, Variant]]:
        records: List[Tuple[VariantKey, Variant]] = []
        for pair in self.iter_part(part_path):
            # graftcheck: hostmem(unbounded) -- the VariantsDataset API surface returns ONE shard's record list (O(part), bounded by the writer's shard size); whole-checkpoint iteration streams via iter_part
            records.append(pair)
        return records

    def __iter__(self) -> Iterator[Tuple[VariantKey, Variant]]:
        for part in self.partitions():
            yield from self.iter_part(part)

    def variants(self) -> Iterator[Variant]:
        for _, variant in self:
            yield variant


def load_variants(path: str) -> CheckpointDataset:
    return CheckpointDataset(path)


__all__ = [
    "CheckpointWriter",
    "save_variants",
    "load_variants",
    "CheckpointDataset",
]

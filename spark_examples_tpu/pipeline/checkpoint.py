"""Checkpoint / resume of materialized variants.

The reference can resume from pre-materialized variants:
``--input-path`` makes ``getData`` read ``sc.objectFile[(VariantKey, Variant)]``
instead of hitting the API (``VariantsPca.scala:112-113``), with stats
disabled (``:332-335``) — but no writer for that format exists in the repo.
Here both sides exist: :func:`save_variants` writes sharded gzip JSON-lines
part files with a manifest, :func:`load_variants` streams them back as a
dataset with the same iteration surface as ``VariantsDataset``.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Iterable, Iterator, List, Tuple

from spark_examples_tpu.models.variant import Variant, VariantKey, VariantsBuilder

_MANIFEST = "_manifest.json"


class CheckpointWriter:
    """Incremental checkpoint writer: one gzip JSON-lines part file per
    shard as it streams, the manifest only on :meth:`close` — an abandoned
    (partially written) checkpoint has no manifest and fails loudly on
    load instead of silently resuming a truncated cohort.

    Records are the wire-format JSON of ``Variant.to_json`` plus the raw
    partition key, so the round trip preserves both members of the
    ``(VariantKey, Variant)`` pair the reference's objectFile held.
    """

    def __init__(self, path: str):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.total = 0
        self.parts = 0

    def write_shard(self, records: List[Tuple[VariantKey, Variant]]) -> None:
        part_path = os.path.join(self.path, f"part-{self.parts:05d}.jsonl.gz")
        with gzip.open(part_path, "wt") as f:
            for key, variant in records:
                entry = {
                    "key": {"contig": key.contig, "position": key.position},
                    "variant": variant.to_json(),
                }
                f.write(json.dumps(entry) + "\n")
                self.total += 1
        self.parts += 1

    def close(self) -> None:
        with open(os.path.join(self.path, _MANIFEST), "w") as f:
            json.dump(
                {
                    "parts": self.parts,
                    "records": self.total,
                    "format": "jsonl.gz/v1",
                },
                f,
            )


def save_variants(
    path: str,
    shards: Iterable[List[Tuple[VariantKey, Variant]]],
) -> int:
    """Write one part file per shard (consumed lazily); returns the record
    count. The driver's streaming save (``--save-variants``) uses
    :class:`CheckpointWriter` directly to interleave writing with the
    analysis pass."""
    writer = CheckpointWriter(path)
    for records in shards:
        writer.write_shard(records)
    writer.close()
    return writer.total


class CheckpointDataset:
    """Reader with the ``VariantsDataset`` iteration surface."""

    def __init__(self, path: str):
        self.path = path
        manifest_path = os.path.join(path, _MANIFEST)
        with open(manifest_path) as f:
            self.manifest = json.load(f)

    def partitions(self) -> List[str]:
        return [
            os.path.join(self.path, name)
            for name in sorted(os.listdir(self.path))
            if name.startswith("part-")
        ]

    def compute(self, part_path: str) -> List[Tuple[VariantKey, Variant]]:
        records = []
        with gzip.open(part_path, "rt") as f:
            for line in f:
                entry = json.loads(line)
                built = VariantsBuilder.build(entry["variant"])
                if built is None:
                    continue
                key = VariantKey(
                    entry["key"]["contig"], int(entry["key"]["position"])
                )
                records.append((key, built[1]))
        return records

    def __iter__(self) -> Iterator[Tuple[VariantKey, Variant]]:
        for part in self.partitions():
            yield from self.compute(part)

    def variants(self) -> Iterator[Variant]:
        for _, variant in self:
            yield variant


def load_variants(path: str) -> CheckpointDataset:
    return CheckpointDataset(path)


__all__ = [
    "CheckpointWriter",
    "save_variants",
    "load_variants",
    "CheckpointDataset",
]

"""Checkpoint / resume: materialized variants AND the live Gramian state.

Two checkpoint families live here, both crash-consistent (every artifact
is published by an atomic rename, so a crash at ANY instant leaves either
the previous complete artifact or none — never a half-written one):

**Variant checkpoints** (the reference's resume surface): the reference
can resume from pre-materialized variants (``--input-path`` makes
``getData`` read ``sc.objectFile[(VariantKey, Variant)]`` instead of
hitting the API, ``VariantsPca.scala:112-113``, stats disabled
``:332-335``) — but no writer for that format exists in the repo. Here
both sides exist: :func:`save_variants` writes sharded gzip JSON-lines
part files with a manifest, :func:`load_variants` streams them back.
The manifest is written atomically (tmp + ``os.replace``) and the reader
cross-checks it against the part files actually on disk — a deleted,
extra, or truncated part fails loudly as :class:`CheckpointCorruptError`
instead of silently resuming a polluted cohort.

**Gramian checkpoints** (the analysis-pass resume surface, new): the
Gramian is additive over variants, so an interrupted ingest+similarity
pass need not restart from zero. :class:`GramianFeeder` wraps a live
accumulator: it periodically persists the full device accumulator state —
the per-partition partial Gramian with its dtype-ladder position, the
site cursor, and a conf fingerprint — as ONE atomically-published
``.npz`` artifact (:func:`save_gramian_checkpoint`). A restarted run
(``--resume-from``) validates the fingerprint against its conf
(:class:`CheckpointMismatchError` on drift), merges the persisted partial
into a fresh accumulator, fast-forwards the deterministic contig-ordered
ingest stream to the cursor, and finishes at O(remaining) device cost.
Because every accumulator entry is an exact integer at every point (the
``graftcheck ranges`` contracts, DESIGN.md §5/§8.7), the resumed Gramian
— and therefore the eigenvectors — is **byte-identical** to an
uninterrupted run, which the chaos matrix (``tests/test_faults.py``)
asserts at every registered kill-point.

Both families move data through FIXED-SIZE buffers (``graftcheck
hostmem`` audits this file): the variant writer coalesces encoded lines
into a bounded text buffer between ``write()`` calls, the variant reader
walks each part in ``_READ_CHUNK_BYTES`` decompressed windows with a
partial-line carry — :meth:`CheckpointDataset.compute` streams one
shard's pairs through the same window (its former O(part) record list
was the resume path's last ``hostmem(unbounded)`` site, now retired) —
and the Gramian artifact is O(N²) by definition (the accumulator state
itself, not the data that produced it): the one remaining declared site
is the artifact's ``np.load`` read oracle.
"""

from __future__ import annotations

import gzip
import json
import os
import zipfile
import zlib
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from spark_examples_tpu.models.variant import Variant, VariantKey, VariantsBuilder
from spark_examples_tpu.utils import faults

_MANIFEST = "_manifest.json"

#: Writer-side coalescing buffer: encoded lines accumulate to ~this many
#: characters between ``write()`` calls (bounded by one record past it).
_WRITE_BUFFER_BYTES = 1 << 20

#: Reader-side window: decompressed bytes per chunk of a part-file walk.
_READ_CHUNK_BYTES = 4 << 20


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory that cannot be trusted: missing/truncated/
    unparseable manifest, or part files that disagree with it. Raised
    instead of a raw ``JSONDecodeError``/``KeyError`` so callers (and
    operators) see "this checkpoint is corrupt — re-materialize it", not
    a parser traceback."""


class CheckpointMismatchError(RuntimeError):
    """A Gramian checkpoint whose conf fingerprint does not match the
    resuming run: merging it would silently produce a Gramian of a
    DIFFERENT analysis (other cohort, block size, references, dtype
    ladder...). The artifact is fine; the flags are not."""


def _iter_jsonl_lines(path: str, chunk_bytes: int = _READ_CHUNK_BYTES):
    """Decoded JSON objects of one gzip JSON-lines file, streamed through
    the ONE windowed reader (``sources/stream.py:iter_byte_windows`` —
    fixed-size window, partial-line carry): peak memory is O(window),
    never O(part)."""
    from spark_examples_tpu.sources.stream import iter_byte_windows

    for window in iter_byte_windows(path, chunk_bytes):
        for line in window.splitlines():
            if line.strip():
                yield json.loads(line)


class CheckpointWriter:
    """Incremental checkpoint writer: one gzip JSON-lines part file per
    shard as it streams, the manifest only on :meth:`close` — an abandoned
    (partially written) checkpoint has no manifest and fails loudly on
    load instead of silently resuming a truncated cohort.

    Records are the wire-format JSON of ``Variant.to_json`` plus the raw
    partition key, so the round trip preserves both members of the
    ``(VariantKey, Variant)`` pair the reference's objectFile held.
    """

    def __init__(self, path: str):
        os.makedirs(path, exist_ok=True)
        # Re-materializing into an existing checkpoint dir: retract the
        # old manifest FIRST, so a crash mid-write leaves unreferenced
        # part files (loud CheckpointCorruptError) rather than the prior
        # manifest pointing at a mix of old and half-overwritten parts.
        try:
            os.remove(os.path.join(path, _MANIFEST))
        except FileNotFoundError:
            pass
        self.path = path
        self.total = 0
        self.parts = 0

    def write_shard(self, records: List[Tuple[VariantKey, Variant]]) -> None:
        part_path = os.path.join(self.path, f"part-{self.parts:05d}.jsonl.gz")
        with gzip.open(part_path, "wt") as f:
            # Fixed-size coalescing buffer: one write() per ~_WRITE_BUFFER_
            # BYTES of encoded text instead of one per record. The artifact
            # is byte-identical to per-record writes (gzip's compressor
            # only emits at its own block boundaries and at close; the
            # round-trip regression test asserts this), but the host never
            # holds more than one buffer of encoded lines beyond the
            # records the caller already owns.
            buffer: List[str] = []
            buffered = 0
            for key, variant in records:
                entry = {
                    "key": {"contig": key.contig, "position": key.position},
                    "variant": variant.to_json(),
                }
                line = json.dumps(entry) + "\n"
                buffer.append(line)
                buffered += len(line)
                self.total += 1
                if buffered >= _WRITE_BUFFER_BYTES:
                    f.write("".join(buffer))
                    buffer.clear()
                    buffered = 0
            if buffer:
                f.write("".join(buffer))
        self.parts += 1

    def close(self) -> None:
        # Drop stale parts from a previous, larger materialization before
        # publishing: the reader's parts-count cross-check would otherwise
        # reject this completed write forever ("3 declared but 5 on
        # disk"). A crash in here leaves extra-or-missing parts against
        # whichever manifest exists — still a loud load failure.
        written = {f"part-{i:05d}.jsonl.gz" for i in range(self.parts)}
        for name in os.listdir(self.path):
            if name.startswith("part-") and name not in written:
                os.remove(os.path.join(self.path, name))
        # Atomic publish (the obs/manifest.py pattern): a crash mid-write
        # leaves only the per-pid tmp, never a truncated _manifest.json a
        # later load would half-parse.
        manifest_path = os.path.join(self.path, _MANIFEST)
        tmp = f"{manifest_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "parts": self.parts,
                    "records": self.total,
                    "format": "jsonl.gz/v1",
                },
                f,
            )
        os.replace(tmp, manifest_path)


def save_variants(
    path: str,
    shards: Iterable[List[Tuple[VariantKey, Variant]]],
) -> int:
    """Write one part file per shard (consumed lazily); returns the record
    count. The driver's streaming save (``--save-variants``) uses
    :class:`CheckpointWriter` directly to interleave writing with the
    analysis pass."""
    writer = CheckpointWriter(path)
    for records in shards:
        writer.write_shard(records)
    writer.close()
    return writer.total


class CheckpointDataset:
    """Reader with the ``VariantsDataset`` iteration surface.

    Trust-but-verify on open AND on iteration: the manifest must parse and
    carry its required fields, the part files on disk must match the
    manifest's ``parts`` count, and a full iteration (:meth:`__iter__`)
    re-counts raw records against ``records`` — a part truncated after the
    manifest was written fails the resumed run loudly at the point the
    truncation is provable, instead of silently analyzing fewer variants.
    """

    def __init__(self, path: str):
        self.path = path
        manifest_path = os.path.join(path, _MANIFEST)
        try:
            with open(manifest_path) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            raise CheckpointCorruptError(
                f"{path}: no {_MANIFEST} — the checkpoint write never "
                "completed (the manifest is written last, atomically); "
                "re-materialize with --save-variants"
            ) from None
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise CheckpointCorruptError(
                f"{path}/{_MANIFEST} is truncated or unparseable ({e}); "
                "the checkpoint cannot be trusted — re-materialize it"
            ) from e
        if (
            not isinstance(manifest, dict)
            or not isinstance(manifest.get("parts"), int)
            or not isinstance(manifest.get("records"), int)
        ):
            raise CheckpointCorruptError(
                f"{path}/{_MANIFEST} is missing required integer fields "
                "parts/records; the checkpoint cannot be trusted"
            )
        self.manifest = manifest
        on_disk = len(self.partitions())
        if on_disk != manifest["parts"]:
            raise CheckpointCorruptError(
                f"{path}: manifest declares {manifest['parts']} part "
                f"file(s) but {on_disk} are on disk — a deleted or foreign "
                "part would silently resume a truncated/polluted cohort"
            )

    def partitions(self) -> List[str]:
        return [
            os.path.join(self.path, name)
            for name in sorted(os.listdir(self.path))
            if name.startswith("part-") and not name.endswith(".tmp")
        ]

    def _iter_part_entries(self, part_path: str) -> Iterator[Dict]:
        """Raw manifest-counted entries of one part (pre-build): the unit
        the writer's ``records`` total counts, so the full-iteration
        cross-check compares like with like."""
        try:
            yield from _iter_jsonl_lines(part_path)
        except (EOFError, OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                f"{part_path} is truncated or unparseable ({e}); the "
                "checkpoint cannot be trusted — re-materialize it"
            ) from e

    @staticmethod
    def _build_pairs(entries: Iterator[Dict]) -> Iterator[Tuple[VariantKey, Variant]]:
        """The ONE spelling of entry → ``(key, variant)`` (build, skip
        unbuildable, reconstruct the partition key) — shared by the
        per-part reader and the counted whole-checkpoint iteration."""
        for entry in entries:
            built = VariantsBuilder.build(entry["variant"])
            if built is None:
                continue
            yield (
                VariantKey(
                    entry["key"]["contig"], int(entry["key"]["position"])
                ),
                built[1],
            )

    def iter_part(self, part_path: str) -> Iterator[Tuple[VariantKey, Variant]]:
        """Stream one part's ``(key, variant)`` pairs through the bounded
        read window — the resume path that never stages a whole part."""
        yield from self._build_pairs(self._iter_part_entries(part_path))

    def compute(self, part_path: str) -> Iterator[Tuple[VariantKey, Variant]]:
        """One part's ``(key, variant)`` pairs — the ``VariantsDataset``
        consumption surface, STREAMED through :meth:`iter_part`'s bounded
        read window. Callers iterate (the multi-set window join consumes
        lazily); none needed the list, so the former O(part) staging —
        the last ``hostmem(unbounded)`` site of the resume path — is
        retired rather than declared (byte-identical output, asserted by
        the round-trip regression test)."""
        return self.iter_part(part_path)

    def __iter__(self) -> Iterator[Tuple[VariantKey, Variant]]:
        seen = 0

        def counted(part: str) -> Iterator[Dict]:
            nonlocal seen
            for entry in self._iter_part_entries(part):
                seen += 1
                yield entry

        for part in self.partitions():
            yield from self._build_pairs(counted(part))
        if seen != self.manifest["records"]:
            raise CheckpointCorruptError(
                f"{self.path}: manifest declares {self.manifest['records']} "
                f"record(s) but a full iteration found {seen} — a part was "
                "truncated or padded after the manifest was written"
            )

    def variants(self) -> Iterator[Variant]:
        for _, variant in self:
            yield variant


def load_variants(path: str) -> CheckpointDataset:
    return CheckpointDataset(path)


# ---------------------------------------------------------------------------
# Gramian checkpoints: the analysis-pass resume artifact.
# ---------------------------------------------------------------------------

GRAMIAN_CKPT = "gramian.ckpt.npz"
GRAMIAN_CKPT_VERSION = 1

#: Default ``--checkpoint-every-sites`` when a checkpoint directory is
#: given without an interval: ~18 snapshots across a whole genome
#: (~28.9 M candidate sites), each costing one accumulator sync + one
#: O(N²) host fetch + write — noise against the ingest it protects.
DEFAULT_CHECKPOINT_EVERY_SITES = 1_600_000

#: Meta fields every complete artifact carries (version-1 contract).
_META_REQUIRED = (
    "version",
    "fingerprint",
    "sites",
    "strategy",
    "accum_dtype",
    "entry_bound",
    "rows_seen",
    "flushes",
    "num_samples",
)


def gramian_checkpoint_fingerprint(conf) -> str:
    """The conf digest a Gramian checkpoint is keyed by: the
    ``utils/cache.py:compile_fingerprint`` fields (everything that shapes
    the analysis — cohort, references, block size, mesh, strategy, dtype
    ladder, ingest path — with output/telemetry placement excluded, and
    the checkpoint/fault flags themselves excluded so the saving run and
    the resuming run fingerprint identically)."""
    from spark_examples_tpu.utils.cache import compile_fingerprint

    return compile_fingerprint(conf, kind="gramian-checkpoint")


def save_gramian_checkpoint(
    directory: str, state: Dict, fingerprint: str, sites: int
) -> str:
    """Atomically publish one accumulator snapshot as
    ``<directory>/gramian.ckpt.npz`` (single file: tmp write + rename, so
    a crash at any instant leaves the PREVIOUS complete snapshot — or
    none — never a torn one). ``state`` is
    ``GramianAccumulator.snapshot_state()``'s dict; ``sites`` is the
    ingest cursor (rows of the deterministic stream consumed so far)."""
    os.makedirs(directory, exist_ok=True)
    meta = {
        "version": GRAMIAN_CKPT_VERSION,
        "fingerprint": str(fingerprint),
        "sites": int(sites),
        "strategy": state["strategy"],
        "accum_dtype": state["accum_dtype"],
        "exact_int": bool(state["exact_int"]),
        "entry_bound": int(state["entry_bound"]),
        "rows_seen": int(state["rows_seen"]),
        "flushes": int(state["flushes"]),
        "num_samples": int(state["num_samples"]),
        "data_parallel": int(state.get("data_parallel", 1)),
        "padded": int(state.get("padded", state["num_samples"])),
        # Sharded-only ring accounting (0 for the dense strategy): lets a
        # resumed run's manifest schedule block keep predicted == measured.
        "ring_bytes_total": int(state.get("ring_bytes_total", 0)),
    }
    final = os.path.join(directory, GRAMIAN_CKPT)
    # Sweep orphaned tmps from prior killed writes: each tmp is a full
    # O(N²) snapshot and every preemption/resume cycle runs under a fresh
    # pid, so without this a repeatedly-preempted run steadily fills the
    # directory with dead full-size files. One writer per directory by
    # design (the driver), so nothing live can match the pattern here.
    for name in os.listdir(directory):
        if name.startswith(f"{GRAMIAN_CKPT}.") and name.endswith(".tmp"):
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass
    tmp = f"{final}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, G=state["G"], meta=np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8
        ))
        f.flush()
        os.fsync(f.fileno())
    faults.kill_point("checkpoint.mid-write")
    os.replace(tmp, final)
    faults.kill_point("checkpoint.post-save")
    return final


def load_gramian_checkpoint(
    directory: str, fingerprint: Optional[str] = None
) -> Optional[Dict]:
    """Load the last COMPLETE snapshot from a checkpoint directory, or
    ``None`` when no complete artifact exists yet (a run killed before —
    or during — its first save resumes from zero; leftover ``.tmp`` files
    are ignored by construction). Raises :class:`CheckpointCorruptError`
    on an unreadable artifact and :class:`CheckpointMismatchError` when
    ``fingerprint`` is given and disagrees.

    Returns ``{"meta": dict, "G": ndarray}``.
    """
    path = os.path.join(directory, GRAMIAN_CKPT)
    if not os.path.exists(path):
        return None
    try:
        # One O(N²) accumulator snapshot staged whole by np.load: its
        # size is the accumulator itself (already charged by the
        # host-matrix term of the bound), not the ingested data.
        with np.load(path) as archive:
            meta = json.loads(bytes(archive["meta"]).decode("utf-8"))
            G = np.array(archive["G"])
    except (
        OSError,
        ValueError,
        KeyError,
        json.JSONDecodeError,
        # A valid zip magic with a corrupt/truncated tail surfaces as
        # BadZipFile or zlib.error, not ValueError — same diagnosis.
        zipfile.BadZipFile,
        zlib.error,
    ) as e:
        raise CheckpointCorruptError(
            f"{path} is not a readable Gramian checkpoint ({e}); delete "
            "the directory to restart from zero"
        ) from e
    missing = [k for k in _META_REQUIRED if k not in meta]
    if missing or meta.get("version") != GRAMIAN_CKPT_VERSION:
        raise CheckpointCorruptError(
            f"{path}: incomplete or wrong-version checkpoint meta "
            f"(version={meta.get('version')!r}, missing={missing})"
        )
    if fingerprint is not None and meta["fingerprint"] != fingerprint:
        raise CheckpointMismatchError(
            f"{path} was written by a run with conf fingerprint "
            f"{meta['fingerprint']} but this run fingerprints as "
            f"{fingerprint}: the flags that shape the analysis (cohort, "
            "references, block size, mesh, strategy, dtype ladder, ingest "
            "path) differ — resuming would merge two different analyses. "
            "Re-run with the original flags, or point --resume-from at a "
            "matching checkpoint"
        )
    return {"meta": meta, "G": G}


class GramianFeeder:
    """Row-block conduit between an ingest stream and a live accumulator,
    adding crash-consistent periodic snapshots and resume fast-forward.

    Exposes ``add_rows`` (the accumulator surface the driver and
    ``ops/gramian.py:accumulate_index_rows`` feed), so it drops into both
    the packed/streamed block path and the wire calls path unchanged:

    - **resume**: constructed with a loaded checkpoint, it restores the
      accumulator state once and then SKIPS the first ``meta["sites"]``
      rows of the (deterministic, contig-ordered) stream — splitting a
      block when the cursor lands inside one — before feeding resumes;
    - **checkpointing**: every ``every_sites`` accumulated rows it syncs
      the accumulator (:meth:`snapshot_state` flushes and drains the
      dispatch pipeline), fetches the partial Gramian, and publishes the
      atomic artifact; :meth:`finish` writes a final snapshot so a crash
      between ingest end and finalize also resumes at O(1) re-ingest.

    Different flush boundaries between the original and resumed runs are
    harmless by the exactness contracts: every accumulator entry is an
    exact integer at every point, so the merged Gramian is byte-identical
    regardless of how rows were grouped into flushes.
    """

    def __init__(
        self,
        acc,
        directory: Optional[str] = None,
        every_sites: Optional[int] = None,
        fingerprint: str = "",
        resume: Optional[Dict] = None,
        registry=None,
    ):
        self.acc = acc
        self.directory = directory
        self.every_sites = (
            int(every_sites)
            if every_sites is not None
            else DEFAULT_CHECKPOINT_EVERY_SITES
        )
        if self.every_sites < 1:
            raise ValueError(
                f"checkpoint cadence must be >= 1 site, got "
                f"{self.every_sites}"
            )
        self.fingerprint = fingerprint
        self.checkpoint_sites = 0
        self.sites_skipped = 0
        self.saves = 0
        self._skip_remaining = 0
        self._saves_counter = self._sites_gauge = None
        if resume is not None:
            acc.restore_state(resume)
            self.checkpoint_sites = int(resume["meta"]["sites"])
            self._skip_remaining = self.checkpoint_sites
        self.sites_done = self.checkpoint_sites
        self._last_saved = self.checkpoint_sites
        if registry is not None and directory is not None:
            from spark_examples_tpu.obs.metrics import (
                GRAMIAN_CHECKPOINT_SAVES,
                GRAMIAN_CHECKPOINT_SITES,
                well_known_counter,
                well_known_gauge,
            )

            self._saves_counter = well_known_counter(
                registry, GRAMIAN_CHECKPOINT_SAVES
            )
            self._sites_gauge = well_known_gauge(
                registry, GRAMIAN_CHECKPOINT_SITES
            )
            self._sites_gauge.set(float(self._last_saved))

    def add_rows(self, rows) -> None:
        n = len(rows)
        if self._skip_remaining > 0:
            if n <= self._skip_remaining:
                self._skip_remaining -= n
                self.sites_skipped += n
                return
            rows = rows[self._skip_remaining :]
            self.sites_skipped += self._skip_remaining
            self._skip_remaining = 0
            n = len(rows)
        self.acc.add_rows(rows)
        self.sites_done += n
        if (
            self.directory is not None
            and self.sites_done - self._last_saved >= self.every_sites
        ):
            self.save()

    def save(self) -> None:
        """Snapshot + atomic publish at the current cursor."""
        state = self.acc.snapshot_state()
        faults.kill_point("driver.post-flush")
        save_gramian_checkpoint(
            self.directory, state, self.fingerprint, self.sites_done
        )
        self._last_saved = self.sites_done
        self.saves += 1
        if self._saves_counter is not None:
            self._saves_counter.inc(1)
            self._sites_gauge.set(float(self._last_saved))

    def finish(self) -> None:
        """End of ingest: write the final snapshot (when checkpointing and
        anything accumulated since the last save), so a crash before or
        during finalize resumes without re-ingesting anything.

        Fails loudly if the fast-forward never completed: the fingerprint
        covers conf flags and input paths, not file contents, so an input
        that SHRANK since the checkpoint (truncated/replaced file) is only
        detectable here — finalizing anyway would emit a structurally
        valid but silently wrong analysis built from the stale partial."""
        if self._skip_remaining > 0:
            raise CheckpointMismatchError(
                f"resume cursor lies past the end of the input stream: the "
                f"checkpoint was written at {self.checkpoint_sites} sites "
                f"but the stream ended after {self.sites_skipped} — the "
                "input shrank since the checkpoint was saved. Re-run "
                "without --resume-from (or against the original input)"
            )
        if self.directory is not None and self.sites_done > self._last_saved:
            self.save()


__all__ = [
    "CheckpointCorruptError",
    "CheckpointMismatchError",
    "CheckpointWriter",
    "save_variants",
    "load_variants",
    "CheckpointDataset",
    "GRAMIAN_CKPT",
    "DEFAULT_CHECKPOINT_EVERY_SITES",
    "gramian_checkpoint_fingerprint",
    "save_gramian_checkpoint",
    "load_gramian_checkpoint",
    "GramianFeeder",
]

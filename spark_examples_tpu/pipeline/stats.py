"""I/O statistics accumulators — a thin view over the metrics registry.

The reference tracks ingest health with six Spark accumulators flushed from
executors (``rdd/VariantsRDD.scala:152-172``) and pretty-prints them at the
end of a run (``VariantsPca.scala:321-326``). The counters now live in a
:class:`~spark_examples_tpu.obs.metrics.MetricsRegistry` (``io_*_total``,
thread-safe, exported into the run manifest and the Prometheus text dump);
this class keeps the reference's accessor surface and the line-for-line
report format, so runs stay comparable and the printed epilogue is
numerically identical to the manifest's ``io_stats`` block — both read the
same registry series.

Mutation goes through the ``add_*`` methods ONLY. The stat names are
read-only properties: a direct ``stats.requests += n`` — which used to
silently bypass the lock — now raises, and ``graftcheck`` rule GC009 flags
the pattern statically in ``ops/``, ``pipeline/``, and ``sources/``.
"""

from __future__ import annotations

from typing import Dict, Optional

from spark_examples_tpu.obs.metrics import (
    IO_PARTITIONS_TOTAL,
    IO_RETRIES_TOTAL,
    MetricsRegistry,
)
from spark_examples_tpu.sources.base import ClientCounters

#: stat name → (metric name, help) — the registry series backing each field.
_STAT_METRICS = {
    "partitions": (IO_PARTITIONS_TOTAL, "Shards (partitions) processed."),
    "reference_bases": (
        "io_reference_bases_total",
        "Reference bases covered by processed partitions.",
    ),
    "requests": ("io_requests_total", "API/page requests issued."),
    "unsuccessful_responses": (
        "io_unsuccessful_responses_total",
        "Unsuccessful (non-2xx) responses.",
    ),
    "io_exceptions": ("io_io_exceptions_total", "I/O exceptions raised."),
    "variants": ("io_variants_total", "Variant records read (pre-drop)."),
    # Not part of the reference's six-line report (__str__ keeps its
    # line-for-line format); rides the manifest's io_stats block and the
    # io_retries_total registry series as the transient-pressure signal.
    "retries": (
        IO_RETRIES_TOTAL,
        "Transient-failure retries (bounded-backoff) issued by clients.",
    ),
}


def _forbidden(name: str):
    def getter(self) -> int:
        return int(self._counters[name].value)

    def setter(self, value) -> None:
        raise AttributeError(
            f"direct writes to VariantsDatasetStats.{name} bypass the "
            f"registry accounting; use add_{name}()/add_client() instead"
        )

    return property(getter, setter)


class VariantsDatasetStats:
    """Mirror of ``VariantsRddStats`` (``rdd/VariantsRDD.scala:152-172``),
    registry-backed. Pass the run's registry to share one namespace with
    the rest of the pipeline's telemetry; a private registry is created
    otherwise (standalone/tests)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        self._counters = {
            stat: self.registry.counter(metric, help_text)
            for stat, (metric, help_text) in _STAT_METRICS.items()
        }

    partitions = _forbidden("partitions")
    reference_bases = _forbidden("reference_bases")
    requests = _forbidden("requests")
    unsuccessful_responses = _forbidden("unsuccessful_responses")
    io_exceptions = _forbidden("io_exceptions")
    variants = _forbidden("variants")
    retries = _forbidden("retries")

    def add_partition(self, reference_bases: int) -> None:
        self._counters["partitions"].inc(1)
        self._counters["reference_bases"].inc(int(reference_bases))

    def add_variants(self, n: int) -> None:
        self._counters["variants"].inc(int(n))

    def add_requests(self, n: int) -> None:
        """Page/API requests accounted outside a client session (the
        device-gen and streaming ingest paths compute them arithmetically)."""
        self._counters["requests"].inc(int(n))

    def add_client(self, counters: ClientCounters) -> None:
        """Flush a per-partition client's counters
        (``rdd/VariantsRDD.scala:192-196``)."""
        self._counters["requests"].inc(counters.initialized_requests)
        self._counters["unsuccessful_responses"].inc(
            counters.unsuccessful_responses
        )
        self._counters["io_exceptions"].inc(counters.io_exceptions)
        self._counters["retries"].inc(counters.retries)

    def as_dict(self) -> Dict[str, int]:
        """The manifest's ``io_stats`` block (``obs/manifest.py``) — the
        same numbers ``__str__`` prints."""
        return {
            "partitions": self.partitions,
            "reference_bases": self.reference_bases,
            "variants": self.variants,
            "requests": self.requests,
            "unsuccessful_responses": self.unsuccessful_responses,
            "io_exceptions": self.io_exceptions,
            "io_retries": self.retries,
        }

    def __str__(self) -> str:
        return (
            "Variants API stats:\n"
            "-------------------------------\n"
            f"# of partitions: {self.partitions}\n"
            f"# of bases requested: {self.reference_bases}\n"
            f"# of variants read: {self.variants}\n"
            f"# of API requests: {self.requests}\n"
            f"# of unsuccessful responses: {self.unsuccessful_responses}\n"
            f"# of IO exceptions: {self.io_exceptions}\n"
        )


__all__ = ["VariantsDatasetStats"]

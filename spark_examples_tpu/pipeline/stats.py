"""I/O statistics accumulators.

The reference tracks ingest health with six Spark accumulators flushed from
executors (``rdd/VariantsRDD.scala:152-172``) and pretty-prints them at the
end of a run (``VariantsPca.scala:321-326``). Without Spark, the host
streaming loop is in-process (or one process per host under
``jax.distributed``), so the accumulators are plain counters aggregated by
the dataset layer; the report format is kept identical so runs are
comparable line-for-line.
"""

from __future__ import annotations

import threading

from spark_examples_tpu.sources.base import ClientCounters


class VariantsDatasetStats:
    """Mirror of ``VariantsRddStats`` (``rdd/VariantsRDD.scala:152-172``)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.partitions = 0
        self.reference_bases = 0
        self.requests = 0
        self.unsuccessful_responses = 0
        self.io_exceptions = 0
        self.variants = 0

    def add_partition(self, reference_bases: int) -> None:
        with self._lock:
            self.partitions += 1
            self.reference_bases += int(reference_bases)

    def add_variants(self, n: int) -> None:
        with self._lock:
            self.variants += int(n)

    def add_client(self, counters: ClientCounters) -> None:
        """Flush a per-partition client's counters
        (``rdd/VariantsRDD.scala:192-196``)."""
        with self._lock:
            self.requests += counters.initialized_requests
            self.unsuccessful_responses += counters.unsuccessful_responses
            self.io_exceptions += counters.io_exceptions

    def __str__(self) -> str:
        return (
            "Variants API stats:\n"
            "-------------------------------\n"
            f"# of partitions: {self.partitions}\n"
            f"# of bases requested: {self.reference_bases}\n"
            f"# of variants read: {self.variants}\n"
            f"# of API requests: {self.requests}\n"
            f"# of unsuccessful responses: {self.unsuccessful_responses}\n"
            f"# of IO exceptions: {self.io_exceptions}\n"
        )


__all__ = ["VariantsDatasetStats"]

"""Bounded per-site output writer — the M-sized spill path of ``analyses/``.

The PCA pipeline's outputs are O(N) (PC rows) and were emitted from memory;
the population-genetics analyses emit one row PER SITE — O(M), up to ~40M
rows for a whole genome (``ops/contracts.py:DECLARED_MAX_SITES``) — so an
in-memory list of result rows would be exactly the O(file) staging shape
``graftcheck hostmem`` exists to forbid. This writer is the shared bounded
alternative:

- rows are appended WINDOW BY WINDOW as the analysis streams (one
  ``write_rows`` call per genotype block / LD window), formatted and
  written straight into a buffered file handle — peak host memory is
  O(window), never O(M);
- the output is published ATOMICALLY: rows land in ``<path>.<pid>.tmp``
  and one ``os.replace`` at :meth:`close` makes the finished file appear —
  a killed run leaves a ``.tmp`` orphan, never a truncated file that looks
  complete (the same contract as ``obs/manifest.py:write_manifest``);
- accounting rides the owning run's metrics registry (``sites_written``
  count exposed for the manifest's ``analysis`` block), never ad-hoc
  attribute mutation.

Column layout is the caller's: the writer takes a header tuple once and
pre-formatted row tuples after, so GRM/LD/assoc share one spill mechanism
without sharing a schema. ``header=None`` writes no header line at all —
the reference's ``saveAsTextFile`` part files (``analyses/reads_examples``)
are headerless by format, and their bytes must not change when the
in-memory result list is replaced by this streaming path.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional, Sequence, Tuple


class SiteOutputWriter:
    """One streaming TSV output file with atomic publish.

    Usage::

        writer = SiteOutputWriter(path, header=("contig", "pos", "kept"))
        for window in ...:
            writer.write_rows((c, p, int(k)) for c, p, k in window_rows)
        writer.close()   # atomic rename; the file now exists
    """

    def __init__(self, path: str, header: Optional[Sequence[str]] = None):
        self.path = str(path)
        self.rows_written = 0
        self._closed = False
        directory = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(directory, exist_ok=True)
        self._tmp = f"{self.path}.{os.getpid()}.tmp"
        self._f = open(self._tmp, "w", encoding="utf-8")
        if header is not None:
            self._f.write("\t".join(str(h) for h in header) + "\n")

    def write_rows(self, rows: Iterable[Tuple]) -> int:
        """Append one window's rows (any iterable of field tuples); returns
        the row count written. Rows stream straight through the buffered
        handle — nothing is retained."""
        if self._closed:
            raise ValueError(f"writer for {self.path} is closed")
        n = 0
        for row in rows:
            self._f.write("\t".join(str(field) for field in row) + "\n")
            n += 1
        self.rows_written += n
        return n

    def close(self) -> None:
        """Flush and atomically publish the finished file (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._f.close()
        os.replace(self._tmp, self.path)

    def abort(self) -> None:
        """Discard the temp file without publishing (error paths): the
        output either exists complete or not at all."""
        if self._closed:
            return
        self._closed = True
        self._f.close()
        try:
            os.remove(self._tmp)
        except OSError:
            pass

    def __enter__(self) -> "SiteOutputWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()
        else:
            self.abort()


__all__ = ["SiteOutputWriter"]

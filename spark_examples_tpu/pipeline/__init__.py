from spark_examples_tpu.pipeline.stats import VariantsDatasetStats
from spark_examples_tpu.pipeline.datasets import ReadsDataset, VariantsDataset

__all__ = ["VariantsDatasetStats", "VariantsDataset", "ReadsDataset"]

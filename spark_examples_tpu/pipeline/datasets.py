"""Sharded host-streaming datasets: the RDD layer, redesigned for TPU.

The reference's ``VariantsRDD`` / ``ReadsRDD`` are lazy record streams whose
partitions are genomic ranges, computed executor-side against the paginated
API (``rdd/VariantsRDD.scala:179-226``, ``rdd/ReadsRDD.scala:93-118``). On
TPU the equivalent is a *host-side sharded stream*: partitions (contig
windows) are traversed by host worker threads that build records, pack device
blocks, and keep the chip fed while it computes — the ingest/compute overlap
that the 2h→5min win depends on (SURVEY.md §7 "hard parts").

Unlike Spark, transformations here are ordinary Python: analyses iterate
records per shard or consume packed blocks. What this layer owns is shard
enumeration, STRICT boundary streaming, record building (with the
normalization drop), stats accounting, and a prefetching parallel iterator.
"""

from __future__ import annotations

import concurrent.futures
from typing import Callable, Iterator, List, Optional, Sequence, Tuple, TypeVar

from spark_examples_tpu.models.read import Read, ReadBuilder, ReadKey
from spark_examples_tpu.models.variant import Variant, VariantKey, VariantsBuilder
from spark_examples_tpu.pipeline.stats import VariantsDatasetStats
from spark_examples_tpu.sharding.partitioners import (
    ReadsPartition,
    ReadsPartitioner,
    VariantsPartition,
    VariantsPartitioner,
)
from spark_examples_tpu.sources.base import GenomicsSource, ShardBoundary

T = TypeVar("T")


def _parallel_shards(
    partitions: Sequence[T],
    compute: Callable[[T], List],
    num_workers: int,
) -> Iterator[Tuple[T, List]]:
    """Compute shards in a thread pool, yielding in partition order.

    The streaming analog of Spark executors pulling shards concurrently:
    workers run the (I/O-bound) record building while the consumer feeds the
    device. Results are yielded in order for determinism.
    """
    if num_workers <= 1 or len(partitions) <= 1:
        for part in partitions:
            yield part, compute(part)
        return
    with concurrent.futures.ThreadPoolExecutor(max_workers=num_workers) as pool:
        # Bounded in-flight window: submit at most num_workers + margin ahead
        # of the consumer so unconsumed shard results stay O(workers), not
        # O(partitions) — a whole-genome run would otherwise materialize
        # arbitrarily many shards ahead of a slow consumer and exhaust host
        # memory.
        window = num_workers + 2
        futures = {}
        next_submit = 0
        for i, part in enumerate(partitions):
            while next_submit < min(len(partitions), i + window):
                futures[next_submit] = pool.submit(compute, partitions[next_submit])
                next_submit += 1
            yield part, futures.pop(i).result()


class VariantsDataset:
    """A sharded stream of ``(VariantKey, Variant)`` records
    (``rdd/VariantsRDD.scala:179-226``)."""

    def __init__(
        self,
        source: GenomicsSource,
        variant_set_id: str,
        partitioner: VariantsPartitioner,
        stats: Optional[VariantsDatasetStats] = None,
        num_workers: int = 8,
    ):
        self.source = source
        self.variant_set_id = variant_set_id
        self.partitioner = partitioner
        self.stats = stats
        self.num_workers = num_workers

    def partitions(self) -> List[VariantsPartition]:
        return self.partitioner.get_partitions(self.variant_set_id)

    def compute(self, partition: VariantsPartition) -> List[Tuple[VariantKey, Variant]]:
        """Stream one shard (``rdd/VariantsRDD.scala:198-225``): open a fresh
        client, page with STRICT boundaries, build records (dropping
        non-normalizable contigs), then flush counters into stats."""
        client = self.source.client()
        records: List[Tuple[VariantKey, Variant]] = []
        n_seen = 0
        for wire in client.search_variants(
            partition.get_variants_request(), ShardBoundary.STRICT
        ):
            n_seen += 1
            built = VariantsBuilder.build(wire)
            if built is not None:
                records.append(built)
        if self.stats is not None:
            self.stats.add_variants(n_seen)
            self.stats.add_partition(partition.range)
            self.stats.add_client(client.counters)
        return records

    def iter_shards(self) -> Iterator[Tuple[VariantsPartition, List[Tuple[VariantKey, Variant]]]]:
        yield from _parallel_shards(self.partitions(), self.compute, self.num_workers)

    def __iter__(self) -> Iterator[Tuple[VariantKey, Variant]]:
        for _, records in self.iter_shards():
            yield from records

    def variants(self) -> Iterator[Variant]:
        """Values only — the ``.map(_._2)`` at ``VariantsPca.scala:122``."""
        for _, variant in self:
            yield variant


class ReadsDataset:
    """A sharded stream of ``(ReadKey, Read)`` records
    (``rdd/ReadsRDD.scala:93-118``)."""

    def __init__(
        self,
        source: GenomicsSource,
        read_group_set_ids: Sequence[str],
        partitioner: ReadsPartitioner,
        num_workers: int = 8,
    ):
        self.source = source
        self.read_group_set_ids = list(read_group_set_ids)
        self.partitioner = partitioner
        self.num_workers = num_workers

    def partitions(self) -> List[ReadsPartition]:
        return self.partitioner.get_partitions(self.read_group_set_ids)

    def compute(self, partition: ReadsPartition) -> List[Tuple[ReadKey, Read]]:
        client = self.source.client()
        return [
            ReadBuilder.build(wire)
            for wire in client.search_reads(
                partition.get_reads_request(), ShardBoundary.STRICT
            )
        ]

    def iter_shards(self) -> Iterator[Tuple[ReadsPartition, List[Tuple[ReadKey, Read]]]]:
        yield from _parallel_shards(self.partitions(), self.compute, self.num_workers)

    def __iter__(self) -> Iterator[Tuple[ReadKey, Read]]:
        for _, records in self.iter_shards():
            yield from records

    def reads(self) -> Iterator[Read]:
        for _, read in self:
            yield read


__all__ = ["VariantsDataset", "ReadsDataset"]

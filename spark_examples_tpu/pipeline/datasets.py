"""Sharded host-streaming datasets: the RDD layer, redesigned for TPU.

The reference's ``VariantsRDD`` / ``ReadsRDD`` are lazy record streams whose
partitions are genomic ranges, computed executor-side against the paginated
API (``rdd/VariantsRDD.scala:179-226``, ``rdd/ReadsRDD.scala:93-118``). On
TPU the equivalent is a *host-side sharded stream*: partitions (contig
windows) are traversed by host worker threads that build records, pack device
blocks, and keep the chip fed while it computes — the ingest/compute overlap
that the 2h→5min win depends on (SURVEY.md §7 "hard parts").

Unlike Spark, transformations here are ordinary Python: analyses iterate
records per shard or consume packed blocks. What this layer owns is shard
enumeration, STRICT boundary streaming, record building (with the
normalization drop), stats accounting, and a prefetching parallel iterator.
"""

from __future__ import annotations

import concurrent.futures
import queue
import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence, Tuple, TypeVar

from spark_examples_tpu.models.read import Read, ReadBuilder, ReadKey
from spark_examples_tpu.models.variant import Variant, VariantKey, VariantsBuilder
from spark_examples_tpu.pipeline.stats import VariantsDatasetStats
from spark_examples_tpu.sharding.partitioners import (
    ReadsPartition,
    ReadsPartitioner,
    VariantsPartition,
    VariantsPartitioner,
)
from spark_examples_tpu.sources.base import GenomicsSource, ShardBoundary

T = TypeVar("T")


def _parallel_shards(
    partitions: Sequence[T],
    compute: Callable[[T], List],
    num_workers: int,
) -> Iterator[Tuple[T, List]]:
    """Compute shards in a thread pool, yielding in partition order.

    The streaming analog of Spark executors pulling shards concurrently:
    workers run the (I/O-bound) record building while the consumer feeds the
    device. Results are yielded in order for determinism.
    """
    if num_workers <= 1 or len(partitions) <= 1:
        for part in partitions:
            yield part, compute(part)
        return
    with concurrent.futures.ThreadPoolExecutor(max_workers=num_workers) as pool:
        # Bounded in-flight window: submit at most num_workers + margin ahead
        # of the consumer so unconsumed shard results stay O(workers), not
        # O(partitions) — a whole-genome run would otherwise materialize
        # arbitrarily many shards ahead of a slow consumer and exhaust host
        # memory.
        window = num_workers + 2
        futures = {}
        next_submit = 0
        for i, part in enumerate(partitions):
            while next_submit < min(len(partitions), i + window):
                futures[next_submit] = pool.submit(compute, partitions[next_submit])
                next_submit += 1
            yield part, futures.pop(i).result()


class PrefetchIterator:
    """Bounded background-thread prefetch of an iterator — the hand-off
    between the chunk-parallel parse engine (producer) and the device feeder
    (consumer), so the host keeps parsing block *k+1* while block *k*'s
    ``device_put`` + Gramian dispatch are in flight.

    Backpressure is a hard bound: the queue holds at most ``depth`` items
    (plus the one the producer is computing), so a slow device feeder stalls
    the parse instead of letting parsed blocks pile up in host memory.
    Exceptions from the source iterator re-raise at the consuming position.
    Overlap accounting (:meth:`overlap_stats` — producer-busy,
    producer-blocked, consumer-wait seconds) feeds the run manifest, the
    ingest-overlap report in ``bench.py``, and ``--profile-dir`` stage
    timings: producer-blocked time means the device is the bottleneck,
    consumer-wait time means parse is. ``registry`` (the run's
    :class:`~spark_examples_tpu.obs.metrics.MetricsRegistry`, optional)
    gets a live ``prefetch_queue_occupancy`` gauge for the heartbeat and
    the final overlap gauges on :meth:`close`; ``spans`` (the run's
    recorder, optional) gets a ``chunk-parse`` aggregate span.
    """

    _DONE = object()

    def __init__(self, iterable, depth: int = 2, registry=None, spans=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = int(depth)
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._registry = registry
        self._spans = spans
        self._published = False
        self.producer_seconds = 0.0
        self.producer_blocked_seconds = 0.0
        self.consumer_wait_seconds = 0.0
        self.items = 0
        self._occupancy_gauge = None
        if registry is not None:
            from spark_examples_tpu.obs.metrics import (
                PREFETCH_QUEUE_DEPTH,
                PREFETCH_QUEUE_OCCUPANCY,
                well_known_gauge,
            )

            well_known_gauge(registry, PREFETCH_QUEUE_DEPTH).set(self.depth)
            self._occupancy_gauge = well_known_gauge(
                registry, PREFETCH_QUEUE_OCCUPANCY
            )
            self._occupancy_gauge.set_function(self._queue.qsize)
        self._thread = threading.Thread(
            target=self._run, args=(iter(iterable),), daemon=True
        )
        self._thread.start()

    def _run(self, it) -> None:
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    break
                t1 = time.perf_counter()
                self.producer_seconds += t1 - t0
                self._put(item)
                self.producer_blocked_seconds += time.perf_counter() - t1
        except BaseException as e:  # surfaced from __next__
            self._error = e
        finally:
            # close() may have filled the queue already; drop the sentinel
            # rather than deadlock on a full queue nobody will drain.
            try:
                self._queue.put_nowait(self._DONE)
            except queue.Full:
                pass

    def _put(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self):
        t0 = time.perf_counter()
        while True:
            try:
                item = self._queue.get(timeout=0.1)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # The producer exited — possibly AFTER our get() timed
                    # out but BEFORE this liveness check, with its last
                    # item (or the sentinel) now sitting in the queue.
                    # Thread termination happens-after its final put, so
                    # one non-blocking drain here sees everything; only a
                    # truly empty queue means the stream really ended
                    # (otherwise the final genotype block would be
                    # silently dropped — a truncated Gramian).
                    try:
                        item = self._queue.get_nowait()
                    except queue.Empty:
                        item = self._DONE
                    break
        self.consumer_wait_seconds += time.perf_counter() - t0
        if item is self._DONE:
            if self._error is not None:
                error, self._error = self._error, None
                raise error
            raise StopIteration
        self.items += 1
        return item

    def close(self) -> None:
        """Stop the producer and release its thread (idempotent); publish
        the final overlap numbers to the registry/span recorder (once)."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._occupancy_gauge is not None:
            # Freeze the live gauge: drop the sampler so the run-long
            # registry stops referencing the dead queue (and its buffered
            # blocks), keeping the final occupancy for post-mortems.
            self._occupancy_gauge.set(self._queue.qsize())
        if not self._published:
            self._published = True
            stats = self.overlap_stats()
            if self._registry is not None:
                for field, help_text in (
                    ("parse_busy_seconds", "Producer time spent parsing."),
                    (
                        "parse_blocked_on_feed_seconds",
                        "Producer time blocked on the full queue "
                        "(device feed is the bottleneck).",
                    ),
                    (
                        "feeder_waited_on_parse_seconds",
                        "Consumer time waiting on the empty queue "
                        "(parse is the bottleneck).",
                    ),
                ):
                    self._registry.gauge(
                        f"ingest_overlap_{field}", help_text
                    ).set(stats[field])
                self._registry.counter(
                    "prefetch_blocks_total",
                    "Blocks that passed through the prefetch queue.",
                ).inc(stats["blocks"])
            if self._spans is not None:
                self._spans.add("chunk-parse", stats["parse_busy_seconds"])

    def overlap_stats(self) -> dict:
        """Structured ingest/compute overlap accounting — the manifest's
        ``overlap`` block; :meth:`overlap_report` is its formatter."""
        return {
            "parse_busy_seconds": self.producer_seconds,
            "parse_blocked_on_feed_seconds": self.producer_blocked_seconds,
            "feeder_waited_on_parse_seconds": self.consumer_wait_seconds,
            "blocks": self.items,
            "queue_depth": self.depth,
        }

    def overlap_report(self) -> str:
        """One line of ingest/compute overlap accounting (the stdout form
        of :meth:`overlap_stats`, format unchanged)."""
        stats = self.overlap_stats()
        return (
            f"ingest overlap: parse {stats['parse_busy_seconds']:.3f}s busy, "
            f"{stats['parse_blocked_on_feed_seconds']:.3f}s blocked on device feed "
            f"(backpressure); feeder waited {stats['feeder_waited_on_parse_seconds']:.3f}s "
            f"on parse; {stats['blocks']} blocks through a depth-{stats['queue_depth']} queue"
        )


class VariantsDataset:
    """A sharded stream of ``(VariantKey, Variant)`` records
    (``rdd/VariantsRDD.scala:179-226``)."""

    def __init__(
        self,
        source: GenomicsSource,
        variant_set_id: str,
        partitioner: VariantsPartitioner,
        stats: Optional[VariantsDatasetStats] = None,
        num_workers: int = 8,
    ):
        self.source = source
        self.variant_set_id = variant_set_id
        self.partitioner = partitioner
        self.stats = stats
        self.num_workers = num_workers

    def partitions(self) -> List[VariantsPartition]:
        return self.partitioner.get_partitions(self.variant_set_id)

    def compute(self, partition: VariantsPartition) -> List[Tuple[VariantKey, Variant]]:
        """Stream one shard (``rdd/VariantsRDD.scala:198-225``): open a fresh
        client, page with STRICT boundaries, build records (dropping
        non-normalizable contigs), then flush counters into stats."""
        client = self.source.client()
        records: List[Tuple[VariantKey, Variant]] = []
        n_seen = 0
        for wire in client.search_variants(
            partition.get_variants_request(), ShardBoundary.STRICT
        ):
            n_seen += 1
            built = VariantsBuilder.build(wire)
            if built is not None:
                records.append(built)
        if self.stats is not None:
            self.stats.add_variants(n_seen)
            self.stats.add_partition(partition.range)
            self.stats.add_client(client.counters)
        return records

    def iter_shards(self) -> Iterator[Tuple[VariantsPartition, List[Tuple[VariantKey, Variant]]]]:
        yield from _parallel_shards(self.partitions(), self.compute, self.num_workers)

    def __iter__(self) -> Iterator[Tuple[VariantKey, Variant]]:
        for _, records in self.iter_shards():
            yield from records

    def variants(self) -> Iterator[Variant]:
        """Values only — the ``.map(_._2)`` at ``VariantsPca.scala:122``."""
        for _, variant in self:
            yield variant


class ReadsDataset:
    """A sharded stream of ``(ReadKey, Read)`` records
    (``rdd/ReadsRDD.scala:93-118``)."""

    def __init__(
        self,
        source: GenomicsSource,
        read_group_set_ids: Sequence[str],
        partitioner: ReadsPartitioner,
        num_workers: int = 8,
    ):
        self.source = source
        self.read_group_set_ids = list(read_group_set_ids)
        self.partitioner = partitioner
        self.num_workers = num_workers

    def partitions(self) -> List[ReadsPartition]:
        return self.partitioner.get_partitions(self.read_group_set_ids)

    def compute(self, partition: ReadsPartition) -> List[Tuple[ReadKey, Read]]:
        client = self.source.client()
        return [
            ReadBuilder.build(wire)
            for wire in client.search_reads(
                partition.get_reads_request(), ShardBoundary.STRICT
            )
        ]

    def iter_shards(self) -> Iterator[Tuple[ReadsPartition, List[Tuple[ReadKey, Read]]]]:
        yield from _parallel_shards(self.partitions(), self.compute, self.num_workers)

    def __iter__(self) -> Iterator[Tuple[ReadKey, Read]]:
        for _, records in self.iter_shards():
            yield from records

    def reads(self) -> Iterator[Read]:
        for _, read in self:
            yield read


__all__ = ["PrefetchIterator", "VariantsDataset", "ReadsDataset"]
